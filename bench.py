"""Benchmark: d2q9 MRT Kármán channel, the reference's headline case
(reference example/karman.xml: 1024x100 lattice) measured exactly the way the
reference measures itself: MLUPS = nx*ny*iters/elapsed/1e6 (reference
src/main.cpp.Rt:100-126).

Prints ONE JSON line: metric/value/unit/vs_baseline.  ``vs_baseline`` is the
achieved fraction of this chip's HBM streaming roofline for the same traffic
model the reference prints as GB/s (2 x n_storage x sizeof(real) + flag read
per node update, src/main.cpp.Rt:126) — the reference publishes no absolute
numbers (BASELINE.md), so roofline fraction is the honest comparison axis.
"""

import json
import os
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    # karman.xml is 1024x100; square it for steady bandwidth measurement.
    # Env knobs exist for CPU smoke runs only; the driver runs defaults.
    ny = nx = int(os.environ.get("TCLB_BENCH_N", 1024))
    iters = int(os.environ.get("TCLB_BENCH_ITERS", 2000))
    m = get_model("d2q9")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.02, "Velocity": 0.01})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    flags[ny//3:2*ny//3, nx//10:nx//5] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()

    def timed(iterate_fn, state, params, niter):
        """Time one `niter`-step chunk; returns (mlups, final_state).
        Materializes a device->host scalar INSIDE the timed region: a Python
        float cannot exist until the step chain actually executed, so
        asynchronous-dispatch backends can't fake this (round-1 bench
        reported 818x the HBM roofline because block_until_ready returned
        before execution on the axon transport).  One big chunk with one end
        checksum: the transport costs ~100 ms per checksum round-trip, so
        per-chunk checksums would bill fixed dispatch latency to the kernel
        (the number below still conservatively includes ONE such round
        trip).  Warmup runs the same niter — niter is a static jit arg, a
        different value would recompile inside the timed region."""
        state = iterate_fn(state, params, niter)   # warmup / compile
        float(jnp.sum(state.fields))
        t0 = time.perf_counter()
        state = iterate_fn(state, params, niter)
        checksum = float(jnp.sum(state.fields))
        dt = time.perf_counter() - t0
        assert np.isfinite(checksum), \
            f"simulation blew up inside the timed region ({checksum})"
        return ny * nx * niter / dt / 1e6, state

    mlups_xla, _ = timed(lambda s, p, n: lat._iterate(s, p, n),
                         jax.tree.map(jnp.copy, lat.state), lat.params,
                         iters)

    # Pallas fused collide-stream path (ops/pallas_d2q9.py) — the tuned
    # 1R+1W-per-density kernel, the analogue of the reference's RunKernel
    # (src/LatticeContainer.inc.cpp.Rt:247-266).  ~5x more iterations: the
    # kernel is ~20x faster than the XLA path, so it needs a longer run to
    # amortize the same fixed dispatch overhead.
    mlups_pallas = None
    mlups_fused = None
    from tclb_tpu.ops import pallas_d2q9
    if pallas_d2q9.supports(m, (ny, nx), jnp.float32):
        it_p = pallas_d2q9.make_pallas_iterate(m, (ny, nx))
        mlups_pallas, _ = timed(it_p, jax.tree.map(jnp.copy, lat.state),
                                lat.params, iters * 5)
        # temporally-fused variant: two steps per band pass
        it_f = pallas_d2q9.make_pallas_iterate(m, (ny, nx), fuse=2)
        mlups_fused, _ = timed(it_f, jax.tree.map(jnp.copy, lat.state),
                               lat.params, iters * 5)

    mlups = max(mlups_xla, mlups_pallas or 0.0, mlups_fused or 0.0)
    # HBM roofline: bytes per node update (reference traffic model,
    # src/main.cpp.Rt:126: 1 read + 1 write per density + flag read)
    bytes_per_update = 2 * m.n_storage * 4 + 2
    dev = jax.devices()[0]
    hbm_gbs = {"TPU v5 lite": 819.0, "TPU v5e": 819.0,
               "TPU v5p": 2765.0, "TPU v4": 1228.0,
               "TPU v6 lite": 1640.0, "TPU v6e": 1640.0}.get(
                   dev.device_kind, 819.0)
    roofline_mlups = hbm_gbs * 1e9 / bytes_per_update / 1e6
    # LBM is bandwidth-bound under the classical 1R+1W-per-step traffic
    # model; the temporally-fused kernel legitimately halves traffic per
    # step, so its physical ceiling is 2x that roofline.  EVERY reported
    # component must sit under its own ceiling — beyond it the timing
    # itself is broken and must not be reported.
    for label, v, cap in (("xla", mlups_xla, 1.0),
                          ("pallas", mlups_pallas, 1.0),
                          ("pallas_fused2", mlups_fused, 2.0)):
        if v is None:
            continue
        r = v / roofline_mlups
        assert 0.0 < r <= cap, \
            f"{label}: {v:.0f} MLUPS = {r:.2f}x the HBM roofline on " \
            f"{dev.device_kind} (cap {cap}x): timing is not credible, " \
            "refusing to report"
    ratio = mlups / roofline_mlups
    print(json.dumps({
        "metric": f"MLUPS d2q9 Karman {ny}x{nx} f32",
        "value": round(mlups, 1),
        "unit": "MLUPS",
        "vs_baseline": round(ratio, 4),
        "xla_mlups": round(mlups_xla, 1),
        "pallas_mlups": round(mlups_pallas, 1) if mlups_pallas else None,
        "pallas_fused2_mlups": round(mlups_fused, 1) if mlups_fused
        else None,
    }))


if __name__ == "__main__":
    main()
