"""Benchmark: the ENGINE entry point (Lattice.iterate — what `tclb run`
executes), measured exactly the way the reference measures itself:
MLUPS = nx*ny*nz*iters/elapsed/1e6 (reference src/main.cpp.Rt:100-126).

Headline: d2q9 MRT channel with walls/inlet/outlet/obstacle (the reference's
karman.xml boundary family on a 1024x1024 lattice — square for steady
bandwidth measurement; karman.xml itself is 1024x100).  The solver path
auto-selects the fused Pallas kernel with the hybrid globals refresh, so this
measures the product, not a bench-only artifact.  Components (pure XLA, pure
Pallas fuse=1/2) and the 3D d3q27 cases are reported as extra keys.

Prints ONE JSON line: metric/value/unit/vs_baseline.  ``vs_baseline`` is the
achieved fraction of this chip's HBM streaming roofline for the same traffic
model the reference prints as GB/s (2 x n_storage x sizeof(real) + flag read
per node update, src/main.cpp.Rt:126) — the reference publishes no absolute
numbers (BASELINE.md), so roofline fraction is the honest comparison axis.
"""

import json
import os
import sys
import time

import numpy as np

from tclb_tpu import telemetry

# known per-chip HBM bandwidths (GB/s) — shared with the telemetry spans
# layer so a trace's vs_roofline and this file's credibility asserts can
# never drift; unknown kinds fall back to an ESTIMATE and skip the
# asserts (round-2 VERDICT Weak #5: a wrong fallback must not make the
# assert fire or silently pass on new hardware)
from tclb_tpu.telemetry.spans import HBM_GBS  # noqa: F401 (re-export)

# pinned per-case roofline-fraction floors (re-pinned BENCH_r07, the
# first run with the deep-K generic fusion, the fused kuper Run+CalcPhi
# band kernel and the engaged d3q27 z-slab planner).  The bench exits
# nonzero when a case lands more than 5% below its floor — same
# contract as the adjoint_regressed guard: the JSON still prints (a
# regression hunt needs the numbers), the exit code fails the run.
# Only enforced where the chip's roofline is known (TPU).
BENCH_FLOORS = {
    "solver_vs_roofline": 0.90,
    "karman_vs_roofline": 0.90,
    # 0.43 -> 0.60: the fused Run+CalcPhi kernel retires the second
    # HBM round trip the gradient stencil used to cost every step
    "kuper_drop_vs_roofline": 0.60,
    "heat_adj_vs_roofline": 0.88,
    # 0.75 -> 0.78: fused_cfg now engages (K>=2) at the bench shape
    # instead of silently demoting the cumulant to single-step slabs
    "d3q27_vs_roofline": 0.78,
    "d3q19_vs_roofline": 0.80,
    "d3q19_heat_vs_roofline": 0.66,
    # 3D adjoint tentpole: fused z-slab backward (Run_b band kernel)
    # vs the Pallas-forward/XLA-backward hybrid on the same gradient.
    # The XLA reverse chain round-trips the 19-plane working set
    # through HBM per step; the fused kernel keeps the band resident —
    # under 2x means the backward kernel degraded (or silently fell
    # back to the hybrid, which the engine-tag assert catches first).
    "adjoint3d_speedup": 2.0,
    # serving: batched-32 aggregate throughput vs cached batch-1 serial
    # dispatches of the same cases (a speedup ratio, not a roofline
    # fraction) — the ensemble engine's reason to exist is amortizing
    # the per-dispatch host round trip across the batch, so a batch of
    # 32 tiny cases under 2x the serial rate means the lax.map engine
    # or the compiled-executable cache regressed.  TPU-gated like every
    # floor; the CPU smoke run prints the number informationally.
    "ensemble_speedup_b32": 2.0,
    # gradient serving: a width-8 line-search fan batched into ONE
    # dispatch of the lax.map'd VJP executable vs the same 8 evals as
    # cached batch-1 dispatches.  The grad bin exists to amortize the
    # per-dispatch round trip across the fan — under 2x the serial rate
    # means GradSpec binning or the AOT VJP cache regressed.  TPU-gated
    # like every floor; the CPU smoke prints the ratio informationally.
    "grad_batch_speedup": 2.0,
    # precision ladder: MLUPS(bf16 storage) / MLUPS(f32 storage) on the
    # same engine+geometry, measured over the default *shifted*
    # representation (DDF shifting: the per-plane w_i shift is a
    # compile-time constant folded into the existing widen/narrow
    # seams, so it moves no extra bytes).  Halving the field bytes cuts
    # the per-node traffic from 2*Q*4+2 to 2*Q*2+2, so a bandwidth-
    # bound engine must deliver close to that ratio (1.9x for d2q9) —
    # under 1.6x means the narrow path is spilling casts (or shift
    # adds) to HBM instead of folding them into the DMA pipeline.
    "bf16_effective_bw": 1.6,
    # fleet: the 16-small-cavity-job workload through the per-device
    # FleetDispatcher (one serving lane per local device, double-buffered
    # host staging) vs the single-worker Scheduler, same max_batch, both
    # warmed (serve/fleet_bench.py — the exact workload CI smokes).  N
    # real devices must buy close to N lanes' worth of throughput; 4.0
    # on 8 devices leaves headroom for binning/staging overheads.
    # TPU-gated like every floor: forced-host CPU "devices" timeshare
    # the same cores, so the CPU run prints the ratio informationally.
    "fleet_speedup_d8": 4.0,
    # staging overlap (percent of host-staging time hidden under device
    # execution, first-fill batches excluded): under 90% means batch k+1
    # device_put is no longer overlapping batch k's execute
    "fleet_staging_overlap_pct": 90.0,
}


def engine_cap(engine) -> float:
    """Physical MLUPS ceiling of an engine, as a multiple of the 1R+1W
    streaming roofline: a fuse=K engine pays one HBM round trip per K
    steps, so its credible ceiling is Kx (the VMEM-resident engines tag
    fuse=8 — one round trip per 8-step call)."""
    return float(max(telemetry.fuse_of(engine), 1))


def timed(nodes, iterate_fn, state, params, niter):
    """Time one `niter`-step chunk; returns (mlups, final_state).
    Materializes a device->host scalar INSIDE the timed region: a Python
    float cannot exist until the step chain actually executed, so
    asynchronous-dispatch backends can't fake this (round-1 bench reported
    818x the HBM roofline because block_until_ready returned before
    execution on the axon transport).  One big chunk with one end checksum:
    the transport costs ~100 ms per checksum round-trip, so per-chunk
    checksums would bill fixed dispatch latency to the kernel (the number
    below still conservatively includes ONE such round trip).  Warmup runs
    the same niter — niter is a static jit arg, a different value would
    recompile inside the timed region."""
    import jax.numpy as jnp
    state = iterate_fn(state, params, niter)   # warmup / compile
    float(jnp.sum(state.fields))
    t0 = time.perf_counter()
    state = iterate_fn(state, params, niter)
    checksum = float(jnp.sum(state.fields))
    dt = time.perf_counter() - t0
    assert np.isfinite(checksum), \
        f"simulation blew up inside the timed region ({checksum})"
    return nodes * niter / dt / 1e6, state


def timed_solver(lat, niter):
    """Time the engine entry point itself (Lattice.iterate: auto-selected
    fast path + hybrid globals refresh — what a user's <Solve> runs).
    Same measurement protocol as timed(), via an adapter."""
    def run(state, params, n):
        lat.state = state
        lat.iterate(n)
        return lat.state
    mlups, _ = timed(float(np.prod(lat.shape)), run,
                     lat.state, lat.params, niter)
    return mlups


def bench_d2q9(results):
    import jax
    import jax.numpy as jnp
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model
    from tclb_tpu.ops import pallas_d2q9

    ny = nx = int(os.environ.get("TCLB_BENCH_N", 1024))
    iters = int(os.environ.get("TCLB_BENCH_ITERS", 2000))
    m = get_model("d2q9")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.02, "Velocity": 0.01})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    flags[ny//3:2*ny//3, nx//10:nx//5] = m.flag_for("Wall")
    flags[1:-1, 2] = m.flag_for("MRT", "Inlet")       # globals accumulate
    flags[1:-1, -3] = m.flag_for("MRT", "Outlet")
    lat.set_flags(flags)
    lat.init()
    nodes = float(ny * nx)

    # the product path: hybrid fast engine (on TPU), ~5x iterations to
    # amortize dispatch overhead of the much faster kernel
    solver_iters = iters * (5 if jax.default_backend() == "tpu" else 1)
    mlups_solver = timed_solver(lat, solver_iters)
    results["solver_mlups"] = round(mlups_solver, 1)
    results["solver_engine"] = lat._fast_name or "xla"

    mlups_xla, _ = timed(nodes, lambda s, p, n: lat._iterate(s, p, n),
                         jax.tree.map(jnp.copy, lat.state), lat.params,
                         iters)
    results["xla_mlups"] = round(mlups_xla, 1)

    mlups_pallas = mlups_fused = None
    if pallas_d2q9.supports(m, (ny, nx), jnp.float32):
        it_p = pallas_d2q9.make_pallas_iterate(m, (ny, nx))
        mlups_pallas, _ = timed(nodes, it_p, jax.tree.map(jnp.copy, lat.state),
                                lat.params, iters * 5)
        it_f = pallas_d2q9.make_pallas_iterate(m, (ny, nx), fuse=2)
        mlups_fused, _ = timed(nodes, it_f, jax.tree.map(jnp.copy, lat.state),
                               lat.params, iters * 5)
        results["pallas_mlups"] = round(mlups_pallas, 1)
        results["pallas_fused2_mlups"] = round(mlups_fused, 1)

    # the 2D cumulant family kernel (best roofline fraction in the repo)
    mc = get_model("d2q9_cumulant")
    latc = Lattice(mc, (ny, nx), dtype=jnp.float32,
                   settings={"nu": 0.02, "Velocity": 0.01,
                             "omega_bulk": 1.0})
    fc = np.full((ny, nx), mc.flag_for("BGK"), dtype=np.uint16)
    fc[:, 0] = mc.flag_for("WVelocity", "BGK")
    fc[:, -1] = mc.flag_for("EPressure", "BGK")
    fc[0, :] = fc[-1, :] = mc.flag_for("Wall")
    latc.set_flags(fc)
    latc.init()
    mlups_cum = timed_solver(latc, solver_iters)
    results["d2q9_cumulant_mlups"] = round(mlups_cum, 1)
    results["d2q9_cumulant_engine"] = latc._fast_name or "xla"

    # sharded fast path on a 1-device mesh: measures the per-step
    # ppermute + shard_map machinery overhead vs the single-device
    # kernels (multi-chip hardware is not available here; the identity
    # exchange is the overhead floor a real mesh adds per step)
    mlups_sharded = None
    try:
        from tclb_tpu.parallel.mesh import make_mesh
        mesh1 = make_mesh((ny, nx), devices=jax.devices()[:1],
                          decomposition={"y": 1, "x": 1})
        lat_s = Lattice(m, (ny, nx), dtype=jnp.float32,
                        settings={"nu": 0.02, "Velocity": 0.01},
                        mesh=mesh1)
        lat_s.set_flags(flags)
        lat_s.init()
        mlups_sharded = timed_solver(lat_s, iters * 2)
        results["sharded_1dev_mlups"] = round(mlups_sharded, 1)
        results["sharded_1dev_engine"] = lat_s._fast_name or "xla"
    except Exception as e:      # never let the overhead probe kill bench
        results["sharded_1dev_error"] = str(e)[:200]

    bytes_per_update = 2 * m.n_storage * 4 + 2
    return (ny, nx), bytes_per_update, [
        ("solver", mlups_solver,
         engine_cap(results["solver_engine"])),
        ("xla", mlups_xla, 1.0),
        ("pallas", mlups_pallas, 1.0),
        ("pallas_fused2", mlups_fused, 2.0),
        ("d2q9_cumulant", mlups_cum,
         engine_cap(results["d2q9_cumulant_engine"])),
        ("sharded_1dev", mlups_sharded,
         engine_cap(results.get("sharded_1dev_engine", "xla")))]


def bench_baseline_cases(results):
    """The driver-designated BASELINE geometries (BASELINE.md), on the
    ENGINE path at their real shapes — not friendlier stand-ins:

    * karman: the reference's headline karman.xml at 1024x100 (d2q9 MRT,
      Zou/He inlet/outlet, wedge obstacle) — the small-ny case that
      stresses the band-DMA halo amplification;
    * kuper drop: drop.xml's physics at the reference's original 512^2
      (two Density zones, 225x density ratio) on the generic engine;
    * heat_adj: the d2q9_heat_adj primal (Brinkman-penalized flow +
      temperature) at channel scale on the generic engine.
    """
    import jax
    import jax.numpy as jnp
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    on_tpu = jax.default_backend() == "tpu"
    checks = []

    # ---- karman.xml geometry: 1024 x 100 ------------------------------ #
    nx, ny = (1024, 100) if on_tpu else (128, 20)
    iters = int(os.environ.get("TCLB_BENCH_ITERS_KARMAN",
                               30000 if on_tpu else 4))
    m = get_model("d2q9")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.02, "Velocity": 0.01})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    if on_tpu:   # the karman.xml wedge obstacle (octagon bounding box)
        flags[30:70, 120:160] = m.flag_for("Wall")
        flags[1:-1, 5] = m.flag_for("MRT", "Inlet")
        flags[1:-1, -6] = m.flag_for("MRT", "Outlet")
    lat.set_flags(flags)
    lat.init()
    v = timed_solver(lat, iters)
    results["karman_mlups"] = round(v, 1)
    results["karman_engine"] = lat._fast_name or "xla"
    results["karman_shape"] = f"{nx}x{ny}"
    # ceiling from the selected engine's fuse tag (resident tags fuse=8:
    # one HBM round trip per 8-step call; band engines tag their planner
    # depth; XLA has no tag -> 1x)
    checks.append(("karman_solver", v,
                   engine_cap(results["karman_engine"]),
                   2 * m.n_storage * 4 + 2))

    # ---- drop.xml physics at the reference's original 512^2 ----------- #
    n = 512 if on_tpu else 32
    iters = int(os.environ.get("TCLB_BENCH_ITERS_DROP",
                               10000 if on_tpu else 4))
    mk = get_model("d2q9_kuper")
    latk = Lattice(mk, (n, n), dtype=jnp.float32,
                   settings={"omega": 1.0, "Temperature": 0.56,
                             "FAcc": 1.0, "Magic": 0.01,
                             "MagicA": -0.152, "MagicF": -2.0 / 3.0,
                             "Density": 3.2600529440452366})
    latk.set_setting("Density", 0.014500641645077492, zone=1)
    fk = np.full((n, n), mk.flag_for("MRT"), dtype=np.uint16)
    yy, xx = np.mgrid[0:n, 0:n]
    drop = (yy - n / 2) ** 2 + (xx - n / 2) ** 2 < (n / 5) ** 2
    fk[drop] = mk.flag_for("MRT", zone=1)
    latk.set_flags(fk)
    latk.init()
    v = timed_solver(latk, iters)
    results["kuper_drop_mlups"] = round(v, 1)
    results["kuper_drop_engine"] = latk._fast_name or "xla"
    checks.append(("kuper_drop_solver", v,
                   engine_cap(results["kuper_drop_engine"]),
                   2 * mk.n_storage * 4 + 2))

    # ---- heat_adj primal at channel scale ----------------------------- #
    ny2, nx2 = (512, 1024) if on_tpu else (16, 128)
    iters = int(os.environ.get("TCLB_BENCH_ITERS_HEATADJ",
                               6000 if on_tpu else 4))
    mh = get_model("d2q9_heat_adj")
    lath = Lattice(mh, (ny2, nx2), dtype=jnp.float32,
                   settings={"nu": 0.05, "InletVelocity": 0.02,
                             "FluidAlfa": 0.05})
    fh = np.full((ny2, nx2), mh.flag_for("MRT"), dtype=np.uint16)
    fh[0, :] = fh[-1, :] = mh.flag_for("Wall")
    lath.set_flags(fh)
    lath.init()
    v = timed_solver(lath, iters)
    results["heat_adj_mlups"] = round(v, 1)
    results["heat_adj_engine"] = lath._fast_name or "xla"
    checks.append(("heat_adj_solver", v,
                   engine_cap(results["heat_adj_engine"]),
                   2 * mh.n_storage * 4 + 2))
    return checks


def bench_adjoint(results):
    """Unsteady adjoint wall-clock: the Pallas primal+adjoint kernels
    (ops/pallas_adjoint custom_vjp step — the reference's tuned ``Run_b``
    analogue) vs the XLA reverse-mode, 1000-step horizon on d2q9_adj at
    512x1024.  Reported as MLUPS-primal-equivalents (nodes*niter/time —
    a gradient costs ~3 primal sweeps, so ~1/3 of the primal rate is the
    engine-quality bar)."""
    import jax
    import jax.numpy as jnp
    from tclb_tpu.adjoint import InternalTopology, make_unsteady_gradient
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        return []
    m = get_model("d2q9_adj")
    ny, nx = 512, 1024
    niter = int(os.environ.get("TCLB_BENCH_ITERS_ADJ", 1000))
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[128:384, 300:700] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)

    def timed_grad(engine):
        # production defaults: levels auto (no-recompute when the chunk
        # inputs fit HBM), chunked fused kernels on the pallas engine
        gf = make_unsteady_gradient(m, design, niter, levels=None,
                                    engine=engine, shape=(ny, nx))
        obj, g, _ = gf(theta0, lat.state, lat.params)
        float(obj)
        best = 0.0
        for _ in range(2):   # first post-compile call pays one-time costs
            t0 = time.perf_counter()
            obj, g, _ = gf(theta0, lat.state, lat.params)
            s = float(obj) + float(jnp.sum(g))
            dt = time.perf_counter() - t0
            assert np.isfinite(s)
            best = max(best, ny * nx * niter / dt / 1e6)
        return best

    try:
        results["adjoint_pallas_mlups"] = round(timed_grad("pallas"), 1)
        results["adjoint_xla_mlups"] = round(timed_grad("xla"), 1)
        results["adjoint_speedup"] = round(
            results["adjoint_pallas_mlups"]
            / results["adjoint_xla_mlups"], 2)
    except Exception as e:      # never let the adjoint probe kill bench
        results["adjoint_error"] = str(e)[:200]
        return []
    # wall-clock regression guard (round-4 weak #8): flag instead of
    # asserting mid-run — the full results JSON (the diagnostics a
    # regression hunt needs) still prints, and main() exits nonzero
    if results["adjoint_speedup"] <= 1.5:
        results["adjoint_regressed"] = True
    return []


def bench_adjoint3d(results):
    """3D fused-backward adjoint: the z-slab banded ``Run_b`` kernel
    (ops/pallas_adjoint ``bwd="pallas"``) vs the PR 9 hybrid (Pallas
    forward, XLA reverse chain) on the same d3q19_adj gradient.  The
    XLA chain round-trips the 19-plane working set through HBM on
    every reverse step; the fused kernel keeps the band resident in
    VMEM, so ``adjoint3d_speedup`` is floor-gated at 2.0 on TPU.  The
    engine tag is asserted first — a silent fallback to the hybrid
    would otherwise report a flattering 1.0x."""
    import jax
    import jax.numpy as jnp
    from tclb_tpu.adjoint import InternalTopology, make_unsteady_gradient
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model
    from tclb_tpu.ops import pallas_adjoint

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        return []   # interpret-mode 3D backward: minutes of compile
    m = get_model("d3q19_adj")
    nz, ny, nx = 32, 64, 256
    niter = int(os.environ.get("TCLB_BENCH_ITERS_ADJ3D", 200))
    lat = Lattice(m, (nz, ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.05, "Velocity": 0.02, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full((nz, ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0, :] = flags[:, -1, :] = m.flag_for("Wall")
    flags[nz // 4:3 * nz // 4, ny // 4:3 * ny // 4,
          nx // 3:2 * nx // 3] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)

    def timed_grad():
        gf = make_unsteady_gradient(m, design, niter, levels=None,
                                    engine="pallas", shape=(nz, ny, nx))
        obj, g, _ = gf(theta0, lat.state, lat.params)
        float(obj)
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            obj, g, _ = gf(theta0, lat.state, lat.params)
            s = float(obj) + float(jnp.sum(g))
            dt = time.perf_counter() - t0
            assert np.isfinite(s)
            best = max(best, nz * ny * nx * niter / dt / 1e6)
        return best, gf.engine_name

    try:
        v_fused, tag = timed_grad()
        assert tag.startswith("pallas_adjoint[d3q19_adj") \
            and ",3d]" in tag, f"fused 3D backward not engaged: {tag}"
        results["adjoint3d_fused_mlups"] = round(v_fused, 1)
        results["adjoint3d_engine"] = tag
        # hybrid baseline: deny the slab planner so the auto path
        # builds the Pallas-forward / XLA-backward step (the PR 9 path)
        orig = pallas_adjoint.adjoint_slab_plan
        pallas_adjoint.adjoint_slab_plan = lambda *a, **k: None
        try:
            v_hyb, tag_h = timed_grad()
        finally:
            pallas_adjoint.adjoint_slab_plan = orig
        assert "bwd=xla" in tag_h, f"hybrid baseline not engaged: {tag_h}"
        results["adjoint3d_hybrid_mlups"] = round(v_hyb, 1)
        results["adjoint3d_speedup"] = round(v_fused / v_hyb, 2)
    except Exception as e:   # never let the 3D adjoint probe kill bench
        results["adjoint3d_error"] = str(e)[:200]
    return []


def bench_unsteady_adjoint(results):
    """Production unsteady adjoint: the revolve-checkpointed gradient
    (adjoint/revolve — binomial schedule, host-mem snapshot tier) at a
    fixed snapshot budget S, reported as gradient MLUPS-primal-
    equivalents plus the sweep's measured recompute factor (which must
    track the planner's binomial bound — a drift means the executor is
    re-advancing segments it already paid for).  CPU runs a small smoke
    geometry informationally; TPU runs the production shape."""
    import jax
    import jax.numpy as jnp
    from tclb_tpu.adjoint import InternalTopology, make_revolve_gradient
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    on_tpu = jax.default_backend() == "tpu"
    ny, nx = (512, 1024) if on_tpu else (64, 128)
    niter = int(os.environ.get("TCLB_BENCH_ITERS_REVOLVE",
                               1000 if on_tpu else 48))
    snaps = int(os.environ.get("TCLB_BENCH_REVOLVE_SNAPSHOTS", 8))
    m = get_model("d2q9_adj")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[ny // 4:3 * ny // 4, nx // 3:2 * nx // 3] |= \
        m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    try:
        rev = make_revolve_gradient(m, design, niter, snapshots=snaps,
                                    engine="auto", shape=(ny, nx),
                                    dtype=jnp.float32)
        obj, g, _ = rev(theta0, lat.state, lat.params)
        float(obj)                                    # warmup / compile
        t0 = time.perf_counter()
        obj, g, _ = rev(theta0, lat.state, lat.params)
        s = float(obj) + float(jnp.sum(g))
        dt = time.perf_counter() - t0
        assert np.isfinite(s)
        results["unsteady_adjoint_mlups"] = round(
            ny * nx * niter / dt / 1e6, 3)
        results["unsteady_adjoint_snapshots"] = snaps
        results["unsteady_adjoint_recompute"] = round(
            rev.last["recompute_factor"], 3)
        results["unsteady_adjoint_peak_snapshots"] = \
            rev.last["peak_snapshots"]
        results["unsteady_adjoint_engine"] = rev.engine_name

        # D2D spill overhead: the identical sweep with all but one
        # snapshot forced through the peer-HBM tier (device_put onto a
        # leased fleet lane) vs the all-mem run above.  The CI gate
        # (telemetry report --compare) holds this under 5%; here it is
        # reported so the JSON row carries the measured cost.  Needs a
        # second device to park on — single-chip runs skip.
        if len(jax.devices()) >= 2:
            from tclb_tpu.serve import FleetDispatcher
            with FleetDispatcher(devices=jax.devices()[:2]) as disp:
                rev_p = make_revolve_gradient(
                    m, design, niter, snapshots=snaps, engine="auto",
                    shape=(ny, nx), dtype=jnp.float32,
                    mem_slots=1, peer_slots=snaps - 1, dispatcher=disp)
                obj_p, g_p, _ = rev_p(theta0, lat.state, lat.params)
                float(obj_p)                          # warmup / compile
                t0 = time.perf_counter()
                obj_p, g_p, _ = rev_p(theta0, lat.state, lat.params)
                sp = float(obj_p) + float(jnp.sum(g_p))
                dtp = time.perf_counter() - t0
                assert np.isfinite(sp)
                # the tier split must not change the arithmetic: the
                # bit-invariance contract is what makes the overhead
                # number a pure transport cost
                assert sp == s, "peer-tier gradient diverged from all-mem"
                results["d2d_spill_bytes"] = rev_p.last["spill_peer"]
                results["d2d_spill_overhead_pct"] = round(
                    100.0 * (dtp - dt) / dt, 2)
        else:
            results["d2d_spill_overhead_pct"] = None
    except Exception as e:   # never let the revolve probe kill bench
        results["unsteady_adjoint_error"] = str(e)[:200]
    return []


def bench_grad_batch(results):
    """Batched gradient serving: W same-class gradient evals (one
    line-search fan) through serve's grad mode — ONE dispatch of the
    lax.map'd VJP executable — vs the same W evals as cached batch-1
    dispatches.  Tiny grids are the serving regime: per-dispatch host
    round trips dominate, and batching pays one for the whole fan.
    ``grad_batch_speedup`` is floor-gated on TPU."""
    import jax.numpy as jnp
    from tclb_tpu.adjoint import InternalTopology
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model
    from tclb_tpu.serve import (Case, GradSpec, JobSpec, Scheduler,
                                make_grad_evaluator)

    ny, nx = 32, 64
    iters = int(os.environ.get("TCLB_BENCH_ITERS_GRADBATCH", 16))
    width = int(os.environ.get("TCLB_BENCH_GRADBATCH_W", 8))
    m = get_model("d2q9_adj")
    settings = {"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                "DragInObj": 1.0}
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[8:24, 20:44] |= m.flag_for("DesignSpace")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32, settings=settings)
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    thetas = [jnp.clip(theta0 + 0.01 * i, 0.0, 1.0) for i in range(width)]
    sched = Scheduler(autostart=False)
    try:
        spec = JobSpec(model=m, shape=(ny, nx), case=Case(), niter=iters,
                       flags=flags, dtype=jnp.float32,
                       base_settings=settings,
                       grad=GradSpec(design=design), name="bench")
        ev = make_grad_evaluator(sched, spec)
        ev([thetas[0]])                     # compile the batch-1 VJP
        t0 = time.perf_counter()
        for th in thetas:
            out = ev([th])
            assert np.isfinite(out[0][0])
        dt_seq = time.perf_counter() - t0
        ev(thetas)                          # compile the batch-W VJP
        t0 = time.perf_counter()
        out = ev(thetas)
        assert all(np.isfinite(o) for o, _ in out)
        dt_batch = time.perf_counter() - t0
        results["grad_batch_width"] = width
        results["grad_batch_seq_evals_per_s"] = round(width / dt_seq, 2)
        results["grad_batch_evals_per_s"] = round(width / dt_batch, 2)
        results["grad_batch_speedup"] = round(dt_seq / dt_batch, 2)
        results["grad_batch_cache"] = sched.cache.stats()
    except Exception as e:   # never let the serving probe kill bench
        results["grad_batch_error"] = str(e)[:200]
    finally:
        sched.close()
    return []


def bench_d3q27(results):
    """d3q27_cumulant forced channel (the BASELINE north-star case,
    reference example/3d_channel_test_periodic_force_driven.xml geometry
    family) + a d3q19 XLA number."""
    import jax
    import jax.numpy as jnp
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    on_tpu = jax.default_backend() == "tpu"
    nz, ny, nx = (48, 48, 256) if on_tpu else (8, 16, 128)
    # long runs: the axon transport's ~100 ms sync round-trip would
    # otherwise dominate (the 3D case is only ~0.6M nodes)
    iters = int(os.environ.get("TCLB_BENCH_ITERS3D", 4000 if on_tpu else 4))
    m = get_model("d3q27_cumulant")
    lat = Lattice(m, (nz, ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.01, "ForceX": 1e-5})
    flags = np.full((nz, ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0, :] = m.flag_for("Wall")
    flags[:, -1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    mlups = timed_solver(lat, iters)
    results["d3q27_mlups"] = round(mlups, 1)
    results["d3q27_engine"] = lat._fast_name or "xla"
    results["d3q27_shape"] = f"{nz}x{ny}x{nx}"
    # the z-slab kernels fuse K steps per HBM round trip (planner-chosen,
    # tagged fuse=K in the engine name): the credible ceiling scales with
    # the tag, same as the 2D band engines
    checks = [("d3q27_solver", mlups,
               engine_cap(results["d3q27_engine"]),
               2 * m.n_storage * 4 + 2)]

    m19 = get_model("d3q19")
    lat19 = Lattice(m19, (nz, ny, nx), dtype=jnp.float32,
                    settings={"nu": 0.01, "GravitationX": 1e-5})
    f19 = np.full((nz, ny, nx), m19.flag_for("MRT"), dtype=np.uint16)
    f19[:, 0, :] = m19.flag_for("Wall")
    f19[:, -1, :] = m19.flag_for("Wall")
    lat19.set_flags(f19)
    lat19.init()
    mlups19 = timed_solver(lat19, iters)
    results["d3q19_mlups"] = round(mlups19, 1)
    results["d3q19_engine"] = lat19._fast_name or "xla"
    checks.append(("d3q19_solver", mlups19,
                   engine_cap(results["d3q19_engine"]),
                   2 * m19.n_storage * 4 + 2))

    # a model with NO hand-tuned kernel: the registry-driven generic 3D
    # engine (multi-lattice d3q19_heat, 26 planes) — was XLA-only
    mh = get_model("d3q19_heat")
    lath = Lattice(mh, (nz, ny, nx), dtype=jnp.float32,
                   settings={"nu": 0.05, "Velocity": 0.02,
                             "FluidAlfa": 0.05})
    fh = np.full((nz, ny, nx), mh.flag_for("MRT"), dtype=np.uint16)
    fh[:, 0, :] = fh[:, -1, :] = mh.flag_for("Wall")
    lath.set_flags(fh)
    lath.init()
    mlupsh = timed_solver(lath, iters)
    results["d3q19_heat_mlups"] = round(mlupsh, 1)
    results["d3q19_heat_engine"] = lath._fast_name or "xla"
    checks.append(("d3q19_heat_solver", mlupsh,
                   engine_cap(results["d3q19_heat_engine"]),
                   2 * mh.n_storage * 4 + 2))
    return checks


def bench_ensemble(results):
    """Serving throughput: N independent tiny-d2q9 cases per dispatch
    through serve.EnsemblePlan (the bit-parity ``mode="map"`` engine,
    AOT-compiled via CompiledCache) vs the same cases served as cached
    batch-1 dispatches.  Tiny grids are the serving regime — dispatch
    latency dominates the per-case kernel time, and batching pays one
    round trip for the whole batch.  Reports aggregate and per-case
    MLUPS for batch sizes 1/8/32 plus the throughput-oriented
    ``mode="vmap"`` engine at batch 8 as an informational extra."""
    import jax.numpy as jnp
    from tclb_tpu.models import get_model
    from tclb_tpu.serve import Case, CompiledCache, EnsemblePlan

    ny = nx = int(os.environ.get("TCLB_BENCH_ENSEMBLE_N", 64))
    iters = int(os.environ.get("TCLB_BENCH_ITERS_ENSEMBLE", 50))
    m = get_model("d2q9")
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    base_settings = {"nu": 0.02, "Velocity": 0.01}
    cases = [Case(settings={"nu": 0.02 + 0.0005 * i}, name=f"c{i}")
             for i in range(32)]
    nodes = float(ny * nx)
    cache = CompiledCache(capacity=8)
    plan = EnsemblePlan(m, (ny, nx), flags=flags,
                        base_settings=base_settings)

    def timed_run(p, batch_cases):
        # same protocol as timed(): warmup compiles (and fills the
        # cache); plan.run pulls per-case globals to host, so the timed
        # region cannot return before the batch actually executed
        p.run(batch_cases, iters, cache=cache)
        t0 = time.perf_counter()
        res = p.run(batch_cases, iters, cache=cache)
        dt = time.perf_counter() - t0
        assert all(np.isfinite(v) for r in res for v in r.globals.values())
        return nodes * len(batch_cases) * iters / dt / 1e6

    # serial baseline: the 8-case workload as batch-1 dispatches of the
    # SAME cached executable (what serving looks like without binning)
    plan.run(cases[:1], iters, cache=cache)      # compile batch-1 once
    t0 = time.perf_counter()
    for c in cases[:8]:
        plan.run([c], iters, cache=cache)
    dt = time.perf_counter() - t0
    seq = nodes * 8 * iters / dt / 1e6
    results["ensemble_seq_mlups"] = round(seq, 2)

    for b in (1, 8, 32):
        v = timed_run(plan, cases[:b])
        results[f"ensemble_b{b}_mlups"] = round(v, 2)
        results[f"ensemble_b{b}_per_case_mlups"] = round(v / b, 2)
        if b > 1:
            results[f"ensemble_speedup_b{b}"] = round(v / seq, 2)

    vplan = EnsemblePlan(m, (ny, nx), flags=flags,
                         base_settings=base_settings, mode="vmap")
    results["ensemble_vmap_b8_mlups"] = round(timed_run(vplan, cases[:8]), 2)

    # precision-ladder batch caps: narrowing storage to bf16 shrinks the
    # per-case working set, so the SAME serve budget admits a deeper bin
    # (the scheduler keys bins by storage dtype+repr and recomputes this
    # cap; the shifted representation is free here — the shift is a
    # compile-time constant, not stored state, so the doubled cap holds
    # on the default shifted rung)
    from tclb_tpu.ops.fusion import ensemble_batch_cap
    sweep_n = 2048
    results["ensemble_cap_2048_f32"] = ensemble_batch_cap(
        m.n_storage, (sweep_n, sweep_n), 4)
    results["ensemble_cap_2048_bf16"] = ensemble_batch_cap(
        m.n_storage, (sweep_n, sweep_n), 2)
    results["ensemble_cap_2048_bf16_gain"] = round(
        results["ensemble_cap_2048_bf16"]
        / max(results["ensemble_cap_2048_f32"], 1), 2)
    bplan = EnsemblePlan(m, (ny, nx), flags=flags,
                         base_settings=base_settings,
                         storage_dtype=jnp.bfloat16)
    results["ensemble_bf16_b8_mlups"] = round(timed_run(bplan, cases[:8]), 2)
    results["ensemble_cache"] = cache.stats()
    return []


def bench_fleet(results):
    """Pod-scale serving: the fleet workload from serve/fleet_bench.py —
    single-worker Scheduler vs per-device FleetDispatcher throughput,
    staging overlap / occupancy from a dedicated telemetry trace, one
    large job routed to the sharded engine, and bit-parity of every
    sampled lane result against the sequential path.  With fewer than 2
    local devices the workload re-launches itself in a subprocess with 8
    forced host devices so the dispatcher logic is exercised everywhere;
    the speedup/overlap floors stay TPU-gated (virtual CPU devices
    timeshare the same cores)."""
    import subprocess

    import jax

    jobs = int(os.environ.get("TCLB_BENCH_FLEET_JOBS", 16))
    iters = int(os.environ.get("TCLB_BENCH_ITERS_FLEET", 60))
    multi = len(jax.devices()) >= 2
    if multi:
        from tclb_tpu.serve.fleet_bench import run_fleet
        doc = run_fleet(jobs=jobs, niter=iters)
    else:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        env.pop("TCLB_TELEMETRY", None)  # keeps its own internal trace
        out = subprocess.run(
            [sys.executable, "-m", "tclb_tpu.serve.fleet_bench",
             "--jobs", str(jobs), "--niter", str(iters)],
            capture_output=True, text=True, env=env, check=True)
        doc = json.loads(out.stdout)
    assert doc.get("parity_ok"), \
        "fleet lanes lost bit-parity vs the sequential path"
    assert doc.get("devices_evicted", 0) == 0, \
        f"fleet bench evicted {doc['devices_evicted']} healthy device(s)"
    results["fleet_devices"] = doc["devices"]
    results["fleet_lanes_active"] = doc.get("lanes_active")
    results["fleet_occupancy_pct"] = doc.get("mean_occupancy_pct")
    results["fleet_route_sharded"] = doc.get("route_sharded_events")
    # floor keys only from a real multi-device run — the forced-host
    # fallback's numbers describe core timesharing, not the dispatcher
    spd = "fleet_speedup_d8" if multi else "fleet_speedup_forced_host"
    ovl = ("fleet_staging_overlap_pct" if multi
           else "fleet_staging_overlap_forced_host")
    results[spd] = doc.get("fleet_speedup_d8")
    results[ovl] = doc.get("staging_overlap_pct")
    return []


def bench_precision_ladder(results):
    """The bf16 storage ladder on its flagship case: the d2q9 channel at
    the headline bench shape, same auto-selected engine, f32 vs bf16
    storage.  ``bf16_effective_bw`` is MLUPS(bf16)/MLUPS(f32) — on a
    bandwidth-bound engine the credible ceiling is the bytes-per-node
    ratio (2*Q*4+2)/(2*Q*2+2) = 1.9x for d2q9, and the pinned floor is
    1.6x (below that the narrow path is round-tripping casts through
    HBM).  The bf16 rung runs in its default *shifted* representation
    (DDF shifting, ``core/shift.py``): the per-plane shift folds into
    the existing widen/narrow seams as compile-time constants, so the
    floor is pinned over the shifted rung — same bytes, same cap.  The
    bf16 row also gets its own roofline attribution at its own (halved)
    bytes-per-node.

    A low-Mach accuracy sidebar (the Ma~0.02 cavity from
    ``tclb_tpu.precision``, short run) records velocity-Linf for the
    raw and shifted rungs side by side — the number that justifies
    shifted-by-default."""
    import jax.numpy as jnp
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    import jax
    on_tpu = jax.default_backend() == "tpu"
    ny = nx = int(os.environ.get("TCLB_BENCH_N", 1024)) if on_tpu else 64
    iters = int(os.environ.get("TCLB_BENCH_ITERS",
                               10000 if on_tpu else 8))
    m = get_model("d2q9")

    def run(storage_dtype, storage_repr=None):
        lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                      settings={"nu": 0.02, "Velocity": 0.01},
                      storage_dtype=storage_dtype,
                      storage_repr=storage_repr)
        flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
        flags[0, :] = flags[-1, :] = m.flag_for("Wall")
        lat.set_flags(flags)
        lat.init()
        return timed_solver(lat, iters), lat._fast_name or "xla"

    v32, _ = run(None)
    v16, engine16 = run(jnp.bfloat16)          # default repr: shifted
    v16raw, _ = run(jnp.bfloat16, "raw")
    results["bf16_d2q9_mlups"] = round(v16, 1)
    results["bf16_d2q9_engine"] = engine16
    results["bf16_d2q9_repr"] = "shifted"
    results["bf16_effective_bw"] = round(v16 / v32, 3)
    results["bf16_raw_effective_bw"] = round(v16raw / v32, 3)

    from tclb_tpu.precision import compare_reprs
    err_iters = int(os.environ.get("TCLB_BENCH_ERR_ITERS", 100))
    raw_rep, shifted_rep = compare_reprs(
        "cavity", niter=err_iters, n=64, checkpoints=(err_iters,))
    results["bf16_cavity_raw_u_linf"] = float(
        f"{raw_rep['checkpoints'][-1]['u_linf']:.3g}")
    results["bf16_cavity_shifted_u_linf"] = float(
        f"{shifted_rep['checkpoints'][-1]['u_linf']:.3g}")
    return [("bf16_d2q9_solver", v16, engine_cap(engine16),
             2 * m.n_storage * 2 + 2)]


def bench_gateway(results):
    """Serving front door overhead: a parameter sweep submitted through
    the HTTP gateway (validation + journal + admission + scheduler
    rails) vs the same cases run directly on an EnsemblePlan.  The
    interesting number is the per-job overhead the network path adds —
    it should be dominated by the solve itself, with ONE compiled
    executable shared by every case either way."""
    import tempfile
    import urllib.request

    from tclb_tpu.control.sweep import expand_grid
    from tclb_tpu.gateway.http import GatewayServer
    from tclb_tpu.gateway.service import GatewayService
    from tclb_tpu.models import get_model
    from tclb_tpu.serve import EnsemblePlan

    ny = nx = int(os.environ.get("TCLB_BENCH_GATEWAY_N", 64))
    iters = int(os.environ.get("TCLB_BENCH_ITERS_GATEWAY", 50))
    n_cases = int(os.environ.get("TCLB_BENCH_GATEWAY_CASES", 8))
    grid = {"nu": f"0.02:0.08:{n_cases}"}
    nodes = float(ny * nx)

    # in-process baseline, warm AOT cache
    from tclb_tpu.serve import CompiledCache
    cache = CompiledCache(capacity=4)
    plan = EnsemblePlan(get_model("d2q9"), (ny, nx),
                        base_settings={"Velocity": 0.01})
    cases = expand_grid(grid)
    plan.run(cases, iters, cache=cache)
    t0 = time.perf_counter()
    plan.run(cases, iters, cache=cache)
    direct_s = time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as root:
        srv = GatewayServer(GatewayService(root)).start()
        try:
            body = json.dumps({
                "model": "d2q9", "shape": [ny, nx], "niter": iters,
                "params": {"Velocity": 0.01}, "sweep": grid}).encode()

            def submit_and_wait():
                req = urllib.request.Request(
                    srv.url + "/v1/jobs", data=body, method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=600) as r:
                    jid = json.loads(r.read())["job"]["id"]
                with urllib.request.urlopen(
                        srv.url + f"/v1/jobs/{jid}/result?wait=600",
                        timeout=600) as r:
                    doc = json.loads(r.read())
                assert doc["job"]["status"] == "done", doc
                return doc

            submit_and_wait()        # warmup: compile via the gateway
            t0 = time.perf_counter()
            submit_and_wait()
            gw_s = time.perf_counter() - t0
            stats = srv.service.cache.stats()
        finally:
            srv.stop()

    assert stats["misses"] == 1, \
        f"gateway sweep should compile once, saw {stats['misses']} misses"
    results["gateway_direct_mlups"] = round(
        nodes * n_cases * iters / direct_s / 1e6, 2)
    results["gateway_http_mlups"] = round(
        nodes * n_cases * iters / gw_s / 1e6, 2)
    results["gateway_overhead_ms_per_job"] = round(
        1e3 * (gw_s - direct_s), 2)
    return []


def main():
    import jax

    # each bench section runs under a telemetry span (active only when
    # TCLB_TELEMETRY is set), so every BENCH row carries a trace whose
    # iterate spans attribute the row to an engine and roofline fraction
    results = {}
    with telemetry.span("bench.d2q9"):
        shape2d, bytes_d2q9, checks2d = bench_d2q9(results)
    with telemetry.span("bench.d3q27"):
        checks3d = bench_d3q27(results)
    with telemetry.span("bench.baseline_cases"):
        checks3d += bench_baseline_cases(results)
    with telemetry.span("bench.adjoint"):
        checks3d += bench_adjoint(results)
    with telemetry.span("bench.adjoint3d"):
        checks3d += bench_adjoint3d(results)
    with telemetry.span("bench.unsteady_adjoint"):
        checks3d += bench_unsteady_adjoint(results)
    with telemetry.span("bench.grad_batch"):
        checks3d += bench_grad_batch(results)
    with telemetry.span("bench.precision_ladder"):
        checks3d += bench_precision_ladder(results)
    with telemetry.span("bench.ensemble"):
        checks3d += bench_ensemble(results)
    with telemetry.span("bench.fleet"):
        checks3d += bench_fleet(results)
    with telemetry.span("bench.gateway"):
        checks3d += bench_gateway(results)

    dev = jax.devices()[0]
    hbm = HBM_GBS.get(dev.device_kind)
    results["device_kind"] = dev.device_kind
    results["roofline_known"] = hbm is not None
    hbm_est = hbm if hbm is not None else 819.0

    def roofline(bpu):
        return hbm_est * 1e9 / bpu / 1e6

    # LBM is bandwidth-bound under the classical 1R+1W-per-step traffic
    # model; the temporally-fused kernel legitimately halves traffic per
    # step, so its physical ceiling is 2x that roofline.  EVERY reported
    # component must sit under its own ceiling — beyond it the timing
    # itself is broken and must not be reported.  Only assert when this
    # chip's bandwidth is actually known.
    for label, v, cap in checks2d:
        if v is None:
            continue
        r = v / roofline(bytes_d2q9)
        if label == "solver":
            results["solver_vs_roofline"] = round(r, 4)
        if hbm is not None:
            assert 0.0 < r <= cap, \
                f"{label}: {v:.0f} MLUPS = {r:.2f}x the HBM roofline on " \
                f"{dev.device_kind} (cap {cap}x): timing is not credible, " \
                "refusing to report"
    for label, v, cap, bpu in checks3d:
        if v is None:
            continue
        r = v / roofline(bpu)
        results[label.replace("solver", "vs_roofline")] = round(r, 4)
        if hbm is not None:
            assert 0.0 < r <= cap, \
                f"{label}: {v:.0f} MLUPS = {r:.2f}x roofline " \
                f"(cap {cap}x): timing not credible"

    mlups = results["solver_mlups"]
    ratio = mlups / roofline(bytes_d2q9)
    ny, nx = shape2d
    print(json.dumps({
        "metric": f"MLUPS d2q9 channel {ny}x{nx} f32 (engine path)",
        "value": mlups,
        "unit": "MLUPS",
        "vs_baseline": round(ratio, 4),
        **results,
    }))
    failed = False
    if results.get("adjoint_regressed"):
        print("FAIL: pallas adjoint regressed to XLA-class "
              f"(speedup {results.get('adjoint_speedup')}x <= 1.5x)",
              file=sys.stderr)
        failed = True
    # roofline-fraction floors: only judged where the roofline itself is
    # real (known chip) — the CPU smoke run reports fractions near zero
    if hbm is not None:
        for key, floor in BENCH_FLOORS.items():
            got = results.get(key)
            if got is not None and got < floor * 0.95:
                print(f"FAIL: {key} = {got:.3f} dropped >5% below its "
                      f"pinned floor {floor:.2f}", file=sys.stderr)
                failed = True
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
