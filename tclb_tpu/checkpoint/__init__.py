"""Fault-tolerant checkpoint/restart subsystem.

The reference treats full-state save/restart as a first-class capability
(``Lattice::save``, src/Lattice.cu.Rt:592-626, plus the SaveBinary /
LoadBinary handlers); this package is its production-grade counterpart,
built with the discipline of a training stack:

* **atomic** — every checkpoint is written into a temp step directory
  and fsync+renamed into place, so a SIGKILL mid-write can never corrupt
  the only copy (:mod:`tclb_tpu.checkpoint.writer`);
* **verified** — per-array CRC32 + dtype/shape land in a JSON manifest
  stamped with ``Model.fingerprint``, the mesh layout and a schema
  version (:mod:`tclb_tpu.checkpoint.manifest`); restore refuses a
  manifest that does not match the live model;
* **async** — device→host copies are fenced with ``block_until_ready``,
  then serialization runs on a background thread with at most one save
  in flight, so iterate loops keep running
  (:class:`tclb_tpu.checkpoint.manager.CheckpointManager`);
* **sharded** — on a device mesh, ``fields``/``flags`` are written one
  file per shard keyed by mesh coordinates, and restore stitches the
  global array back together onto the same or a compatible layout
  (:mod:`tclb_tpu.checkpoint.restore`);
* **resumable** — ``CheckpointManager.latest()`` skips checkpoints that
  fail verification and falls back to the previous valid one; the
  control layer's ``<SaveCheckpoint every=.../>`` handler and the
  ``--resume`` CLI flag build kill-resume on top.

``python -m tclb_tpu.checkpoint {inspect,verify,prune}`` operates on
checkpoint directories from the command line.
"""

from tclb_tpu.checkpoint.manifest import (CheckpointError, MANIFEST_NAME,
                                          SCHEMA_VERSION, is_checkpoint_dir,
                                          read_manifest, verify_checkpoint)
from tclb_tpu.checkpoint.writer import (atomic_path, atomic_write_bytes,
                                        resolve_npz, strip_suffix,
                                        with_suffix)
from tclb_tpu.checkpoint.manager import (CheckpointManager,
                                         CheckpointSaveError)
from tclb_tpu.checkpoint.restore import (apply_restored_solver_state,
                                         collect_solver_state, load_any,
                                         restore_lattice, save_checkpoint)

__all__ = [
    "CheckpointError", "CheckpointManager", "CheckpointSaveError",
    "MANIFEST_NAME",
    "SCHEMA_VERSION", "apply_restored_solver_state", "atomic_path",
    "atomic_write_bytes", "collect_solver_state", "is_checkpoint_dir",
    "load_any", "read_manifest", "resolve_npz", "restore_lattice",
    "save_checkpoint", "strip_suffix", "verify_checkpoint", "with_suffix",
]
