"""Capture, serialize and restore full solver state.

The split matters for async saves: :func:`capture_lattice` runs on the
calling thread (fences the device arrays with ``block_until_ready`` and
pulls host copies — the only part that must see a quiescent device),
while :func:`write_checkpoint_files` runs on the manager's background
thread and only touches numpy + the filesystem.

Sharded lattices write one file per shard keyed by mesh coordinates
(``fields@y0x1.npy``), each with the global index block it covers, so
restore can stitch the global array back together and re-place it onto
*any* compatible mesh — the same-or-different-layout restore the
reference's MPI restart files cannot do.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from tclb_tpu import telemetry
from tclb_tpu.checkpoint import manifest as mf
from tclb_tpu.checkpoint import writer
from tclb_tpu.utils import log


class ShardedCapture:
    """Host-side copies of one sharded array: global dtype/shape plus a
    list of ``{"coords": {...}, "index": ((lo, hi), ...), "data": np}``."""

    __slots__ = ("dtype", "shape", "shards")

    def __init__(self, dtype: str, shape: tuple, shards: list):
        self.dtype = dtype
        self.shape = shape
        self.shards = shards


def _shard_host_copies(arr, mesh) -> ShardedCapture:
    dims = tuple(int(s) for s in arr.shape)
    shards, seen = [], set()
    for sh in arr.addressable_shards:
        index = tuple(
            (0 if sl.start is None else int(sl.start),
             dims[d] if sl.stop is None else int(sl.stop))
            for d, sl in enumerate(sh.index))
        if index in seen:     # replicated axis: one copy is enough
            continue
        seen.add(index)
        pos = np.argwhere(mesh.devices == sh.device)
        coords = ({a: int(pos[0][i]) for i, a in enumerate(mesh.axis_names)}
                  if len(pos) else {})
        shards.append({"coords": coords, "index": index,
                       "data": np.asarray(sh.data)})
    return ShardedCapture(str(arr.dtype), dims, shards)


def npy_safe(arr: np.ndarray) -> np.ndarray:
    """bfloat16 has no ``.npy`` representation (numpy writes an opaque
    ``V2`` descr that loses the type) — store the raw bits as uint16;
    the manifest's storage stamp carries the real dtype for
    :func:`npy_restore` to reinterpret.  Everything else passes
    through untouched."""
    if arr.dtype.name == "bfloat16":
        return arr.view(np.uint16)
    return arr


def npy_restore(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    """Undo :func:`npy_safe` given the stamped at-rest dtype name."""
    if dtype_name == "bfloat16" and arr.dtype.name != "bfloat16":
        import ml_dtypes
        return arr.view(ml_dtypes.bfloat16)
    return arr


def capture_lattice(lattice, extra: Optional[dict] = None) -> dict:
    """Fence + host-copy everything a checkpoint needs (runs on the
    calling thread; the result is plain numpy, safe to serialize on a
    background thread)."""
    import jax
    state, params = lattice.state, lattice.params
    jax.block_until_ready((state.fields, state.flags, state.globals_))
    mesh = lattice.mesh
    arrays: dict[str, Any] = {}
    if mesh is not None:
        arrays["fields"] = _shard_host_copies(state.fields, mesh)
        arrays["flags"] = _shard_host_copies(state.flags, mesh)
    else:
        arrays["fields"] = npy_safe(np.asarray(state.fields))
        arrays["flags"] = np.asarray(state.flags)
    arrays["globals"] = np.asarray(state.globals_)
    arrays["settings"] = np.asarray(params.settings)
    arrays["zone_table"] = np.asarray(params.zone_table)
    if params.time_series is not None:
        arrays["time_series"] = np.asarray(params.time_series)
        arrays["series_map"] = np.asarray(params.series_map, dtype=np.int64)
    full_extra = {"avg_start": int(lattice.avg_start)}
    full_extra.update(extra or {})
    mesh_layout = None
    if mesh is not None:
        mesh_layout = {"axes": {a: int(s) for a, s in
                                zip(mesh.axis_names, mesh.devices.shape)}}
    return {
        "arrays": arrays,
        "fingerprint": lattice.model.fingerprint,
        "model_name": lattice.model.name,
        "iteration": int(np.asarray(state.iteration)),
        "shape": lattice.shape,
        "dtype": str(np.dtype(lattice.dtype)),
        # the fields array is captured AT REST — stamp its layout so a
        # restore can convert across storage representations instead of
        # misreading a shifted deviation stack as raw distributions
        "storage": {"dtype": str(np.dtype(lattice.storage_dtype)),
                    "repr": lattice.storage_repr},
        "mesh": mesh_layout,
        "extra": full_extra,
    }


def _shard_tag(coords: dict) -> str:
    return "".join(f"{a}{coords[a]}" for a in sorted(coords)) or "p0"


def _write_shards(dirpath: str, name: str, val: ShardedCapture,
                  codec: str = "none") -> tuple[list, int]:
    shards, total = [], 0
    for sh in val.shards:
        fname = f"{name}@{_shard_tag(sh['coords'])}.npy"
        rec = writer.write_npy(os.path.join(dirpath, fname), sh["data"],
                               codec=codec)
        rec["coords"] = sh["coords"]
        rec["index"] = [[int(a), int(b)] for a, b in sh["index"]]
        shards.append(rec)
        total += rec["nbytes"]
    return shards, total


def write_shard_fragment(dirpath: str, captured: dict, proc: int,
                         codec: str = "none") -> int:
    """Multi-host: write this process's addressable shards plus a JSON
    fragment of their manifest records (merged by the main process)."""
    import json
    frag: dict[str, list] = {}
    total = 0
    for name, val in captured["arrays"].items():
        if isinstance(val, ShardedCapture):
            frag[name], nb = _write_shards(dirpath, name, val, codec=codec)
            total += nb
    with open(os.path.join(dirpath, f"fragment.{proc}.json"), "w") as f:
        json.dump(frag, f)
    return total


def write_checkpoint_files(dirpath: str, captured: dict,
                           merge_fragments: bool = False,
                           codec: str = "none") -> int:
    """Serialize a capture into ``dirpath`` (already existing, typically a
    temp step dir) + its manifest; returns total array bytes written.

    ``codec`` compresses the shard files (``"zlib"``/``"zstd"``; resolve
    it with :func:`writer.resolve_codec` first — this layer assumes the
    codec is usable).  With ``merge_fragments`` (multi-host main
    process), sharded arrays are assumed already written — this
    process's via :func:`write_shard_fragment`, peers' via theirs — and
    their records are merged from the fragment files instead of
    re-written."""
    import json
    records: dict[str, dict] = {}
    total = 0
    fragments: dict[str, list] = {}
    if merge_fragments:
        for fname in sorted(os.listdir(dirpath)):
            if fname.startswith("fragment.") and fname.endswith(".json"):
                with open(os.path.join(dirpath, fname)) as f:
                    for name, shards in json.load(f).items():
                        fragments.setdefault(name, []).extend(shards)
                os.unlink(os.path.join(dirpath, fname))
    for name, val in captured["arrays"].items():
        if isinstance(val, ShardedCapture):
            if merge_fragments:
                seen: set = set()
                shards = []
                for rec in fragments.get(name, []):
                    key = tuple(tuple(p) for p in rec["index"])
                    if key not in seen:
                        seen.add(key)
                        shards.append(rec)
                total += sum(int(r["nbytes"]) for r in shards)
            else:
                shards, nb = _write_shards(dirpath, name, val, codec=codec)
                total += nb
            records[name] = {"dtype": val.dtype,
                             "shape": [int(s) for s in val.shape],
                             "shards": shards}
        else:
            rec = writer.write_npy(os.path.join(dirpath, f"{name}.npy"),
                                   val, codec=codec)
            records[name] = rec
            total += rec["nbytes"]
    man = mf.build_manifest(
        fingerprint=captured["fingerprint"],
        model_name=captured["model_name"],
        iteration=captured["iteration"],
        shape=captured["shape"],
        dtype=captured["dtype"],
        mesh_layout=captured["mesh"],
        arrays=records,
        extra=captured["extra"],
        storage=captured.get("storage"))
    mf.write_manifest(dirpath, man)
    return total


def save_checkpoint(dirpath: str, lattice, extra: Optional[dict] = None,
                    compress: Optional[str] = None) -> str:
    """One-shot synchronous checkpoint of ``lattice`` into directory
    ``dirpath`` (atomic: written to a temp dir, then committed).
    ``compress`` optionally codecs the shard files ("zlib"/"zstd";
    zstd degrades to uncompressed with a warning when unavailable)."""
    import shutil
    codec = writer.resolve_codec(compress)
    with telemetry.span("checkpoint.save", mode="sync",
                        path=dirpath) as sp:
        captured = capture_lattice(lattice, extra)
        tmp = dirpath.rstrip("/") + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        nbytes = write_checkpoint_files(tmp, captured, codec=codec)
        writer.commit_dir(tmp, dirpath)
        sp.add(bytes=nbytes, step=captured["iteration"])
        telemetry.counter("checkpoint.bytes_written", nbytes)
        telemetry.counter("checkpoint.saves")
    return dirpath


def storage_layout(man: dict) -> tuple[str, str]:
    """``(dtype, repr)`` of a manifest's at-rest fields array.

    Manifests older than the ``storage`` stamp hold raw distributions
    at the compute dtype (what every pre-stamp save wrote).  An unknown
    representation raises a structured
    :class:`~tclb_tpu.checkpoint.manifest.CheckpointError` with
    ``kind="storage_repr"`` — refusing is mandatory, a shifted stack
    read as raw (or the reverse) is silent wrong physics."""
    from tclb_tpu.core import shift as ddf
    st = man.get("storage") or {}
    repr_ = str(st.get("repr", "raw"))
    if repr_ not in ddf.STORAGE_REPRS:
        raise mf.CheckpointError(
            f"checkpoint stores fields in unknown storage_repr "
            f"{repr_!r} (known: {ddf.STORAGE_REPRS}) — refusing to "
            "load a representation this build cannot convert",
            kind="storage_repr")
    return str(st.get("dtype", man.get("dtype", "float32"))), repr_


def _load_array(dirpath: str, rec: dict) -> np.ndarray:
    shards = rec.get("shards")
    if shards is None:
        return writer.read_npy(os.path.join(dirpath, rec["file"]),
                               rec.get("codec", "none"))
    out = np.empty(tuple(rec["shape"]), dtype=np.dtype(rec["dtype"]))
    for srec in shards:
        block = tuple(slice(int(a), int(b)) for a, b in srec["index"])
        out[block] = writer.read_npy(os.path.join(dirpath, srec["file"]),
                                     srec.get("codec", "none"))
    return out


def restore_lattice(lattice, dirpath: str, verify: bool = True) -> dict:
    """Restore a lattice from a committed checkpoint directory; returns
    the manifest (its ``extra`` carries handler/solver state).

    The stitched global arrays are re-placed through the lattice's own
    mesh, so a checkpoint saved on one layout restores onto any
    compatible one (including unsharded).
    """
    import jax.numpy as jnp

    from tclb_tpu.core.lattice import FLAG_DTYPE, LatticeState, SimParams
    with telemetry.span("checkpoint.restore", path=dirpath) as sp:
        if verify:
            problems = mf.verify_checkpoint(dirpath)
            if problems:
                raise mf.CheckpointError(
                    f"checkpoint {dirpath} failed verification: "
                    + "; ".join(problems))
        man = mf.read_manifest(dirpath)
        fp = man["model"]["fingerprint"]
        if fp != lattice.model.fingerprint:
            raise mf.CheckpointError(
                f"checkpoint {dirpath} was saved by model "
                f"{man['model']['name']} (fingerprint {fp}); live model is "
                f"{lattice.model.name} ({lattice.model.fingerprint})")
        if tuple(man["shape"]) != tuple(lattice.shape):
            raise mf.CheckpointError(
                f"checkpoint shape {tuple(man['shape'])} != lattice shape "
                f"{tuple(lattice.shape)}")
        from tclb_tpu.core import shift as ddf
        src_dtype, src_repr = storage_layout(man)
        recs = man["arrays"]
        fields = npy_restore(_load_array(dirpath, recs["fields"]),
                             src_dtype)
        flags = _load_array(dirpath, recs["flags"])
        nbytes = fields.nbytes + flags.nbytes
        # restore into the LIVE lattice's at-rest layout: same
        # representation is a plain (possibly narrowing/widening) cast;
        # across representations the shift moves in f64 on the host, so
        # a shifted-bf16 <-> raw-f32 round trip is bit-faithful
        if src_repr == lattice.storage_repr:
            fields = jnp.asarray(fields, dtype=lattice.storage_dtype)
        else:
            fields = jnp.asarray(ddf.convert_fields_host(
                fields, src_repr, lattice.storage_repr,
                ddf.storage_shift(lattice.model), lattice.storage_dtype))
        lattice._fast_tried = False   # restored flags may paint new types
        lattice._iterate_cached = None
        lattice._host_flags = np.asarray(flags, dtype=np.uint16)
        lattice.state = LatticeState(
            fields=fields,
            flags=jnp.asarray(flags, dtype=FLAG_DTYPE),
            globals_=jnp.asarray(_load_array(dirpath, recs["globals"]),
                                 dtype=lattice.dtype),
            iteration=jnp.asarray(int(man["iteration"]), dtype=jnp.int32),
        )
        lattice._series = {}
        ts, smap = None, ()
        if "time_series" in recs:
            ts_np = _load_array(dirpath, recs["time_series"])
            smap_np = _load_array(dirpath, recs["series_map"])
            ts = jnp.asarray(ts_np, dtype=lattice.dtype)
            smap = tuple(tuple(int(v) for v in row) for row in smap_np)
            for si, z, r in smap:
                lattice._series[(si, z)] = np.asarray(ts_np[r])
        lattice.params = SimParams(
            settings=jnp.asarray(_load_array(dirpath, recs["settings"]),
                                 dtype=lattice.dtype),
            zone_table=jnp.asarray(_load_array(dirpath, recs["zone_table"]),
                                   dtype=lattice.dtype),
            time_series=ts, series_map=smap)
        if lattice._place is not None:
            lattice.state, lattice.params = lattice._place()
        lattice.avg_start = int(man.get("extra", {}).get("avg_start", 0))
        sp.add(step=int(man["iteration"]), bytes=nbytes)
        telemetry.counter("checkpoint.bytes_read", nbytes)
        telemetry.counter("checkpoint.restores")
    return man


def load_any(lattice, path: str) -> Optional[dict]:
    """Restore from either a checkpoint directory (returns its manifest)
    or a legacy ``.npz`` save (returns None) — the LoadBinary handler's
    single entry point."""
    if mf.is_checkpoint_dir(path):
        return restore_lattice(lattice, path)
    legacy = mf.is_checkpoint_dir(writer.strip_suffix(path, ".npz"))
    if legacy:
        return restore_lattice(lattice, writer.strip_suffix(path, ".npz"))
    lattice.load(writer.strip_suffix(path, ".npz"))
    return None


# -- solver-side glue (duck-typed: no import of the control layer) ----------- #


def collect_solver_state(solver) -> dict:
    """The ``extra`` dict a full-run checkpoint records: averaging
    accumulator origin, optimizer iteration, and every stacked handler's
    ``restorable_state()`` plus its schedule anchor, keyed by the
    handler's deterministic config-order key."""
    handlers: dict[str, dict] = {}
    stack = list(getattr(solver, "solve_stack", []))
    for h in list(solver.hands) + stack:
        key = getattr(h, "ck_key", None)
        if key is None or key in handlers:
            continue
        if getattr(h, "kind", "action") == "action" and h not in stack:
            # a COMPLETED periodic action (a <Solve> that already returned
            # but still sits in the callback stack for chunk alignment):
            # its schedule anchor is spent — recording it would re-anchor
            # a later run's same-keyed action to the old origin
            continue
        st = dict(h.restorable_state() or {})
        st["__start_iter"] = int(h.start_iter)
        handlers[key] = st
    return {"avg_start": int(solver.lattice.avg_start),
            "opt_iter": int(solver.opt_iter),
            "iter": int(solver.iter),
            "handlers": handlers}


def apply_restored_solver_state(solver, manifest: Optional[dict]) -> None:
    """Reconcile the Solver clock and handler schedules with a freshly
    restored lattice iteration.

    Handlers recorded in the checkpoint get their exact saved
    ``start_iter`` + ``restorable_state`` back (so a resumed ``<Solve
    Iterations="N">`` completes to the same absolute iteration as the
    uninterrupted run).  Handlers the checkpoint doesn't know — including
    every handler after a plain ``LoadBinary`` of a legacy ``.npz`` —
    are shifted by the clock jump instead, so ``every=`` firing stays
    aligned relative to their own start.  States for handlers that
    initialize later in the config replay are parked on
    ``solver._pending_restore`` and applied as they come up.
    """
    restored = int(np.asarray(solver.lattice.state.iteration))
    delta = restored - solver.iter
    solver.iter = restored
    extra = (manifest or {}).get("extra", {})
    solver.opt_iter = int(extra.get("opt_iter", solver.opt_iter))
    states = dict(extra.get("handlers") or {})
    for h in list(solver.hands) + list(getattr(solver, "solve_stack", [])):
        key = getattr(h, "ck_key", None)
        st = states.pop(key, None) if key is not None else None
        if st is not None:
            if "__start_iter" in st:
                h.start_iter = int(st["__start_iter"])
            h.restore_state({k: v for k, v in st.items()
                             if not k.startswith("__")})
        elif delta:
            h.start_iter += delta
    if states:
        solver._pending_restore.update(states)
    if delta:
        log.notice(f"restored state at iteration {restored} "
                   f"(clock jumped by {delta:+d})")
