"""``python -m tclb_tpu.checkpoint {inspect,verify,prune}``.

Operates purely on the on-disk format (manifest + npy files) — no model
or jax state is needed, so these commands are safe on a machine that
can't even run the solver.

Exit codes: 0 ok, 1 verification failed / no valid checkpoint,
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tclb_tpu.checkpoint import manifest as mf
from tclb_tpu.checkpoint.manager import CheckpointManager


def _checkpoint_dirs(path: str) -> list[str]:
    """``path`` is either one checkpoint dir or a manager root holding
    ``step_*`` dirs."""
    if mf.is_checkpoint_dir(path):
        return [path]
    mgr = CheckpointManager(path, keep_last=0)
    return [p for _s, p in mgr.steps()]


def _summary(dirpath: str) -> dict:
    try:
        man = mf.read_manifest(dirpath)
    except mf.CheckpointError as e:
        return {"path": dirpath, "error": str(e)}
    arrays = {}
    nbytes = 0
    for name, rec in man.get("arrays", {}).items():
        shards = rec.get("shards")
        nb = (sum(int(s["nbytes"]) for s in shards) if shards is not None
              else int(rec.get("nbytes", 0)))
        nbytes += nb
        arrays[name] = {"dtype": rec["dtype"], "shape": rec["shape"],
                        "nbytes": nb,
                        **({"shards": len(shards)} if shards is not None
                           else {})}
    return {"path": dirpath, "schema": man["schema"],
            "model": man["model"], "iteration": man["iteration"],
            "shape": man["shape"], "dtype": man["dtype"],
            "mesh": man["mesh"], "bytes": nbytes, "arrays": arrays,
            "extra_keys": sorted(man.get("extra", {}))}


def _cmd_inspect(args) -> int:
    dirs = _checkpoint_dirs(args.path)
    if not dirs:
        print(f"no checkpoints under {args.path}", file=sys.stderr)
        return 1
    summaries = [_summary(d) for d in dirs]
    if args.format == "json":
        print(json.dumps(summaries if len(summaries) > 1 else summaries[0],
                         indent=2))
        return 0
    for s in summaries:
        if "error" in s:
            print(f"{s['path']}: INVALID — {s['error']}")
            continue
        mesh = s["mesh"]["axes"] if s["mesh"] else "unsharded"
        print(f"{s['path']}: {s['model']['name']} "
              f"(fp {s['model']['fingerprint']}) iter={s['iteration']} "
              f"shape={tuple(s['shape'])} {s['dtype']} mesh={mesh} "
              f"{s['bytes'] / 1e6:.2f} MB")
        for name, rec in sorted(s["arrays"].items()):
            extra = f" x{rec['shards']} shards" if "shards" in rec else ""
            print(f"    {name:12s} {rec['dtype']:10s} "
                  f"{tuple(rec['shape'])}{extra}")
    return 0


def _cmd_verify(args) -> int:
    dirs = _checkpoint_dirs(args.path)
    if not dirs:
        print(f"no checkpoints under {args.path}", file=sys.stderr)
        return 1
    bad = 0
    for d in dirs:
        problems = mf.verify_checkpoint(d, deep=not args.shallow)
        if problems:
            bad += 1
            print(f"{d}: FAIL")
            for p in problems:
                print(f"    {p}")
        else:
            print(f"{d}: ok")
    return 1 if bad else 0


def _cmd_prune(args) -> int:
    if mf.is_checkpoint_dir(args.path):
        print(f"{args.path} is a single checkpoint, not a root of "
              "step_* directories", file=sys.stderr)
        return 2
    mgr = CheckpointManager(args.path, keep_last=args.keep)
    if not mgr.steps():
        print(f"no checkpoints under {args.path}", file=sys.stderr)
        return 1
    for p in mgr.prune():
        print(f"removed {p}")
    kept = mgr.steps()
    print(f"kept {len(kept)} checkpoint(s)"
          + (f", newest step {kept[-1][0]}" if kept else ""))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tclb_tpu.checkpoint",
        description="Inspect, verify and prune tclb checkpoints")
    sub = p.add_subparsers(dest="cmd", required=True)

    i = sub.add_parser("inspect", help="print manifest summaries")
    i.add_argument("path", help="checkpoint dir or manager root")
    i.add_argument("--format", choices=("text", "json"), default="text")
    i.set_defaults(fn=_cmd_inspect)

    v = sub.add_parser("verify", help="recompute CRCs against manifests")
    v.add_argument("path", help="checkpoint dir or manager root")
    v.add_argument("--shallow", action="store_true",
                   help="skip CRC recomputation (existence+header only)")
    v.set_defaults(fn=_cmd_verify)

    pr = sub.add_parser("prune", help="apply keep-last-N retention")
    pr.add_argument("path", help="manager root of step_* directories")
    pr.add_argument("--keep", type=int, default=3, metavar="N")
    pr.set_defaults(fn=_cmd_prune)

    args = p.parse_args(argv)
    if not os.path.exists(args.path):
        print(f"no such path: {args.path}", file=sys.stderr)
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
