"""Atomic file plumbing + the async background writer.

Everything that touches the filesystem on the save path goes through
here: the temp+fsync+rename discipline (no output file can ever be
observed half-written — also adopted by ``Solver.write_txt``/
``write_bin``), the centralized filename-suffix normalization that the
SaveBinary/LoadBinary handlers previously juggled inline (``fn[:-4]``
broke for stems containing a dot), and the one-save-in-flight background
thread that :class:`~tclb_tpu.checkpoint.manager.CheckpointManager`
serializes on.
"""

from __future__ import annotations

import contextlib
import os
import threading
import zlib
from typing import Callable, Iterator, Optional

import numpy as np

from tclb_tpu import faults


# -- path normalization ------------------------------------------------------- #
# One place for the ".npz"/".npy" suffix rules: a suffix is only ever the
# exact trailing extension, never "the last 4 characters", so stems with
# dots ("state.v2", "run.best") survive a save/load round trip.


def with_suffix(path: str, ext: str) -> str:
    """``path`` guaranteed to end with ``ext`` (appended when absent)."""
    return path if path.endswith(ext) else path + ext


def strip_suffix(path: str, ext: str) -> str:
    """``path`` with one trailing ``ext`` removed (only if present)."""
    return path[:-len(ext)] if path.endswith(ext) else path


def resolve_npz(path: str) -> str:
    """The on-disk file a legacy ``.npz`` reference points at: the path
    itself when it exists (or already carries the suffix), else the
    suffixed variant ``np.savez`` would have produced."""
    if path.endswith(".npz") or os.path.exists(path):
        return path
    return path + ".npz"


# -- atomic writes ------------------------------------------------------------ #


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass   # some filesystems refuse fsync on directories
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_path(path: str) -> Iterator[str]:
    """Yield a temp path; on clean exit fsync it and rename onto ``path``.

    The rename is atomic on POSIX, so readers see either the old file or
    the complete new one — never a torn write.  On error the temp file is
    removed and nothing replaces ``path``.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        yield tmp
        _fsync_file(tmp)
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write_bytes(path: str, data: bytes) -> None:
    with atomic_path(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(data)


# -- shard codecs ------------------------------------------------------------- #
# Optional compression of shard files.  The manifest records the codec
# per array record and the CRC is ALWAYS over the uncompressed .npy
# bytes — so verification proves the payload decodes to exactly what was
# saved, not merely that the compressed envelope is intact, and a
# checkpoint re-written with a different codec keeps the same CRC.

CODEC_SUFFIX = {"zstd": ".zst", "zlib": ".zlib"}


def resolve_codec(codec: Optional[str]) -> str:
    """Normalize + availability-check a codec request.  Unknown names
    raise; a ``zstd`` request without the ``zstandard`` package degrades
    to uncompressed with a warning (a save must never fail because an
    optional dependency is absent)."""
    codec = (codec or "none").lower()
    if codec not in ("none", "zlib", "zstd"):
        raise ValueError(f"unknown checkpoint codec {codec!r} "
                         "(known: none, zlib, zstd)")
    if codec == "zstd":
        try:
            import zstandard  # noqa: F401
        except ImportError:
            from tclb_tpu.utils import log
            log.warning("checkpoint: compress='zstd' requested but the "
                        "zstandard package is not installed — saving "
                        "uncompressed")
            return "none"
    return codec


def compress_bytes(data: bytes, codec: str) -> bytes:
    if codec == "none":
        return data
    if codec == "zlib":
        return zlib.compress(data, level=1)
    if codec == "zstd":
        import zstandard
        return zstandard.ZstdCompressor(level=3).compress(data)
    raise ValueError(f"unknown checkpoint codec {codec!r}")


def decompress_bytes(data: bytes, codec: str) -> bytes:
    if codec == "none":
        return data
    if codec == "zlib":
        return zlib.decompress(data)
    if codec == "zstd":
        try:
            import zstandard
        except ImportError as e:
            raise RuntimeError(
                "this checkpoint's shards are zstd-compressed but the "
                "zstandard package is not installed") from e
        return zstandard.ZstdDecompressor().decompress(data)
    raise ValueError(f"unknown checkpoint codec {codec!r}")


def npy_bytes(arr: np.ndarray) -> bytes:
    """The exact ``.npy`` serialization of ``arr`` (what the CRC covers)."""
    import io
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr))
    return buf.getvalue()


def write_npy(path: str, arr: np.ndarray, codec: str = "none") -> dict:
    """Write one shard file and return its manifest record.

    ``codec="none"`` writes a plain ``.npy``; compressed codecs append
    their suffix (``fields.npy.zst``) and store the compressed stream.
    The record's ``crc32`` covers the uncompressed npy bytes either way
    (see CODEC_SUFFIX block comment)."""
    arr = np.ascontiguousarray(arr)
    raw = npy_bytes(arr)
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    if codec != "none":
        path = path + CODEC_SUFFIX[codec]
    payload = compress_bytes(raw, codec)
    # the chaos seam for checkpoint IO: `enospc` raises before the open
    # (disk full), `slow` stalls the fsync path, `torn` truncates the
    # payload so CRC verification downstream must catch it
    mode = faults.fire("checkpoint.write", file=os.path.basename(path))
    if mode == "torn":
        payload = payload[:max(1, len(payload) // 2)]
    with open(path, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    rec = {"file": os.path.basename(path),
           "crc32": crc,
           "dtype": str(arr.dtype),
           "shape": [int(s) for s in arr.shape],
           "nbytes": int(arr.nbytes)}
    if codec != "none":
        rec["codec"] = codec
    return rec


def read_npy(path: str, codec: str = "none") -> np.ndarray:
    """Load one shard file written by :func:`write_npy`."""
    if codec == "none":
        return np.load(path)
    import io
    with open(path, "rb") as f:
        raw = decompress_bytes(f.read(), codec)
    return np.load(io.BytesIO(raw))


def crc32_file(path: str, chunk: int = 1 << 22) -> int:
    """Streaming CRC32 of a file's bytes (what the manifest records and
    verification recomputes)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def commit_dir(tmp_dir: str, final_dir: str) -> None:
    """Atomically promote a fully-written temp step directory: fsync its
    contents, rename into place, fsync the parent.

    An existing ``final_dir`` (a re-save of a step the run already
    passed — e.g. after resuming below a corrupted checkpoint) is
    removed first; ``os.replace`` cannot rename onto a non-empty
    directory, so this one case trades the atomic swap for a brief
    window in which the step is absent rather than torn."""
    import shutil
    for name in os.listdir(tmp_dir):
        _fsync_file(os.path.join(tmp_dir, name))
    _fsync_dir(tmp_dir)
    if os.path.isdir(final_dir):
        shutil.rmtree(final_dir)
    os.replace(tmp_dir, final_dir)
    _fsync_dir(os.path.dirname(os.path.abspath(final_dir)))


# -- async serialization ------------------------------------------------------ #


class AsyncWriter:
    """At most one background save in flight.

    ``submit`` first drains any previous job (so two saves can never
    interleave in one checkpoint root), then runs ``fn`` on a daemon
    thread.  Errors are captured and re-raised on the *next* ``wait()``
    — a failed background save must not kill the solve loop, but it must
    not stay silent either.
    """

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def submit(self, fn: Callable[[], None]) -> None:
        self.wait()

        def run() -> None:
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                # concurrency-ok[unguarded]: single-writer latch — only
                # this worker writes it, and wait() joins the thread
                # before reading (join is the happens-before edge)
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="tclb-checkpoint-writer")
        self._thread.start()

    def busy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            # concurrency-ok[unguarded]: read/cleared only after join()
            # above — the writing thread is gone by this line
            err, self._error = self._error, None
            raise err
