import sys

from tclb_tpu.checkpoint.cli import main

sys.exit(main())
