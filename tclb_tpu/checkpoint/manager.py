"""CheckpointManager: retention, auto-resume and async orchestration.

One manager owns one checkpoint root::

    <root>/step_00000500/   step_00001000/   step_00001500.tmp-...

``save`` fences + host-copies on the calling thread, then serializes on
a background thread (at most one save in flight — a second save first
drains the previous one).  ``latest()`` walks committed step directories
newest-first, *verifying* each manifest, and falls back past corrupted
or truncated checkpoints — the property the kill-resume CI job exercises
with a real SIGKILL.
"""

from __future__ import annotations

import errno
import os
import re
import shutil
import time
from typing import Optional

from tclb_tpu import telemetry
from tclb_tpu.checkpoint import manifest as mf
from tclb_tpu.checkpoint import restore as rst
from tclb_tpu.checkpoint import writer
from tclb_tpu.utils import log

_STEP_RE = re.compile(r"^step_(\d{8,})$")


class CheckpointSaveError(RuntimeError):
    """One checkpoint *save* failed in a survivable way (e.g. disk full).

    Callers that can continue without this particular checkpoint — the
    gateway's resumable runner, a solve loop with periodic saves —
    should catch this, mark the unit of work failed-but-resumable, and
    keep the process alive.  ``step`` is the step whose save failed;
    ``kind`` names the failure class (currently ``"enospc"``)."""

    def __init__(self, message: str, step: Optional[int] = None,
                 kind: str = "io"):
        super().__init__(message)
        self.step = step
        self.kind = kind


class CheckpointManager:
    """Keep-last-N checkpoints of one run under ``root``."""

    def __init__(self, root: str, keep_last: int = 3,
                 async_saves: bool = True,
                 compress: Optional[str] = None):
        self.root = root
        self.keep_last = int(keep_last)
        self.async_saves = bool(async_saves)
        # resolved once: a zstd request without the package degrades to
        # uncompressed here (with a warning), not on every save
        self.codec = writer.resolve_codec(compress)
        self._writer = writer.AsyncWriter()

    # -- naming / discovery -------------------------------------------------- #

    def step_path(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step):08d}")

    def steps(self) -> list[tuple[int, str]]:
        """Committed checkpoints, oldest first, as ``(step, path)``."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.root, name)))
        return sorted(out)

    def latest(self) -> Optional[str]:
        """Path of the newest checkpoint that passes verification; skips
        (with a warning + telemetry event) any that don't.

        An unknown ``storage_repr`` stamp is NOT a skip: the checkpoint
        is intact, this build just cannot decode its at-rest layout —
        falling back to an older one would silently resume from stale
        state, so the structured ``kind="storage_repr"`` error
        propagates to the caller."""
        for step, path in reversed(self.steps()):
            problems = mf.verify_checkpoint(path)
            if not problems:
                rst.storage_layout(mf.read_manifest(path))
                return path
            log.warning(f"checkpoint {path} failed verification "
                        f"({problems[0]}) — falling back")
            telemetry.event("checkpoint_invalid", path=path, step=step,
                            problems=problems[:4])
        return None

    # -- save ---------------------------------------------------------------- #

    def save(self, lattice, step: Optional[int] = None,
             extra: Optional[dict] = None, block: bool = False) -> str:
        """Checkpoint ``lattice`` as step ``step`` (default: its current
        iteration).  Async mode returns right after the fenced host copy;
        the CRC/manifest/commit work runs on the background thread."""
        import jax
        import numpy as np
        if step is None:
            step = int(np.asarray(lattice.state.iteration))
        step = int(step)
        multihost = jax.process_count() > 1
        mode = "sync" if (block or multihost or not self.async_saves) \
            else "async"
        with telemetry.span("checkpoint.save", step=step, mode=mode) as sp:
            captured = rst.capture_lattice(lattice, extra)
            if mode == "async":
                self._writer.submit(lambda: self._write(step, captured))
            else:
                self._writer.wait()
                self._write(step, captured, multihost=multihost)
            sp.add(root=self.root)
        return self.step_path(step)

    def _write(self, step: int, captured: dict,
               multihost: bool = False) -> None:
        t0 = time.perf_counter()
        final = self.step_path(step)
        # fixed temp name (no pid): under multi-host every process writes
        # its shards into the same directory on the shared filesystem
        tmp = final + ".tmp"
        try:
            self._write_inner(step, captured, tmp, final, t0, multihost)
        except OSError as e:
            if e.errno != errno.ENOSPC:
                raise
            self._enospc(step, tmp, e)

    def _enospc(self, step: int, tmp: str, err: OSError) -> None:
        """Disk-full degradation: drop the torn temp dir, emergency-prune
        to the single newest committed checkpoint, and fail the *save*
        with a structured error — never the process.  Resumability is
        preserved: the newest committed step stays restorable."""
        shutil.rmtree(tmp, ignore_errors=True)
        pruned = []
        steps = self.steps()
        for _s, path in steps[:-1]:
            shutil.rmtree(path, ignore_errors=True)
            pruned.append(path)
        telemetry.event("checkpoint.enospc", step=step, root=self.root,
                        pruned=pruned, error=repr(err))
        telemetry.counter("checkpoint.enospc")
        log.warning(f"checkpoint: save at step {step} hit ENOSPC; "
                    f"emergency-pruned {len(pruned)} old checkpoint(s), "
                    "failing the save (newest committed step kept)")
        raise CheckpointSaveError(
            f"checkpoint save at step {step} failed: no space left on "
            f"device (emergency-pruned {len(pruned)} old checkpoint(s))",
            step=step, kind="enospc") from err

    def _write_inner(self, step: int, captured: dict, tmp: str,
                     final: str, t0: float,
                     multihost: bool = False) -> None:
        if multihost:
            import jax
            main = jax.process_index() == 0
            if main and os.path.isdir(tmp):
                shutil.rmtree(tmp)
            self._barrier(f"checkpoint_clean_{step}")
            os.makedirs(tmp, exist_ok=True)
            rst.write_shard_fragment(tmp, captured, jax.process_index(),
                                     codec=self.codec)
            self._barrier(f"checkpoint_write_{step}")
            if not main:
                return
            nbytes = rst.write_checkpoint_files(tmp, captured,
                                                merge_fragments=True,
                                                codec=self.codec)
        else:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            nbytes = rst.write_checkpoint_files(tmp, captured,
                                                codec=self.codec)
        writer.commit_dir(tmp, final)
        telemetry.event("checkpoint_committed", step=step, path=final,
                        bytes=nbytes,
                        dur_s=round(time.perf_counter() - t0, 6))
        telemetry.counter("checkpoint.bytes_written", nbytes)
        telemetry.counter("checkpoint.saves")
        self.prune()

    @staticmethod
    def _barrier(tag: str) -> None:
        try:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)
        except Exception as e:  # noqa: BLE001 — older jax / no DCN
            log.warning(f"multi-host checkpoint barrier unavailable: {e!r}")

    # -- restore / retention ------------------------------------------------- #

    def restore(self, lattice, path: Optional[str] = None) -> dict:
        """Restore from ``path`` (default: ``latest()``); returns the
        manifest."""
        if path is None:
            path = self.latest()
            if path is None:
                raise mf.CheckpointError(
                    f"no valid checkpoint under {self.root}")
        return rst.restore_lattice(lattice, path)

    def prune(self) -> list[str]:
        """Apply keep-last-N retention; returns removed paths."""
        removed = []
        steps = self.steps()
        if self.keep_last > 0:
            for _step, path in steps[:-self.keep_last]:
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
        return removed

    def wait(self) -> None:
        """Drain the in-flight background save (re-raises its error)."""
        self._writer.wait()
