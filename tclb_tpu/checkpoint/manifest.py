"""Checkpoint manifests: the integrity contract of a step directory.

A committed checkpoint is a directory::

    step_00001200/
      manifest.json
      fields.npy            (unsharded)   or   fields@y0x1.npy ... (sharded)
      flags.npy
      settings.npy  zone_table.npy  globals.npy  [time_series.npy ...]

``manifest.json`` records, per array, the file name, CRC32 of the
uncompressed ``.npy`` bytes, dtype, shape and (when compressed) the
shard codec — plus the saving model's ``Model.fingerprint``, the
mesh/shard layout and a schema version.  Verification recomputes the
CRCs; restore refuses a fingerprint that does not match the live model
(a checkpoint is only meaningful against the exact structural model that
produced it, the same contract ``supports_diff`` keys on).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np

from tclb_tpu.checkpoint import writer

SCHEMA_VERSION = 1
MANIFEST_NAME = "manifest.json"


class CheckpointError(RuntimeError):
    """A checkpoint is missing, malformed, or fails verification.

    ``kind`` is the machine-readable failure class (``"storage_repr"``
    for an unknown at-rest representation, ``"verify"``, ... ``None``
    for unclassified legacy raises) — the structured half callers
    branch on without parsing the message."""

    def __init__(self, message: str, kind: Optional[str] = None):
        super().__init__(message)
        self.kind = kind


def _json_sanitize(obj: Any):
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:  # noqa: BLE001
                continue
    return str(obj)


def build_manifest(*, fingerprint: str, model_name: str, iteration: int,
                   shape: tuple, dtype: str, mesh_layout: Optional[dict],
                   arrays: dict, extra: Optional[dict] = None,
                   storage: Optional[dict] = None) -> dict:
    return {
        "schema": SCHEMA_VERSION,
        "kind": "tclb_checkpoint",
        "model": {"name": model_name, "fingerprint": fingerprint},
        "iteration": int(iteration),
        "shape": [int(s) for s in shape],
        "dtype": str(dtype),
        # at-rest layout of the fields array: {"dtype": ..., "repr":
        # "raw"|"shifted"}.  Manifests older than the storage_repr stamp
        # omit the key — readers treat that as raw at the compute dtype
        # (exactly what those checkpoints hold)
        "storage": storage,
        "mesh": mesh_layout,          # {"axes": {"y": 2, "x": 1}} or None
        "arrays": arrays,
        "extra": extra or {},
    }


def write_manifest(dirpath: str, manifest: dict) -> None:
    # the manifest lives inside a temp step dir whose commit is the atomic
    # boundary; a plain write here is enough (commit_dir fsyncs it)
    with open(os.path.join(dirpath, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1, default=_json_sanitize)
        f.write("\n")


def read_manifest(dirpath: str) -> dict:
    path = os.path.join(dirpath, MANIFEST_NAME)
    try:
        with open(path) as f:
            man = json.load(f)
    except OSError as e:
        raise CheckpointError(f"no readable manifest in {dirpath}: {e}") \
            from e
    except json.JSONDecodeError as e:
        raise CheckpointError(f"manifest {path} is not valid JSON: {e}") \
            from e
    if not isinstance(man, dict) or man.get("kind") != "tclb_checkpoint":
        raise CheckpointError(f"{path} is not a tclb checkpoint manifest")
    if int(man.get("schema", -1)) > SCHEMA_VERSION:
        raise CheckpointError(
            f"{path} has schema {man.get('schema')} — newer than this "
            f"build understands ({SCHEMA_VERSION})")
    return man


def is_checkpoint_dir(path: str) -> bool:
    return os.path.isdir(path) \
        and os.path.isfile(os.path.join(path, MANIFEST_NAME))


def _npy_header(path: str, codec: str = "none") -> tuple[str, tuple]:
    """(dtype, shape) from an ``.npy`` header — mmap'd for plain files,
    via decompression for codec'd shards (no cheaper way to reach the
    header inside a compressed stream)."""
    if codec == "none":
        arr = np.load(path, mmap_mode="r")
    else:
        arr = writer.read_npy(path, codec)
    return str(arr.dtype), tuple(int(s) for s in arr.shape)


def _check_record(dirpath: str, name: str, rec: dict, deep: bool,
                  problems: list) -> None:
    path = os.path.join(dirpath, rec["file"])
    codec = rec.get("codec", "none")
    if not os.path.isfile(path):
        problems.append(f"{name}: missing file {rec['file']}")
        return
    if deep:
        # the manifest CRC covers the UNCOMPRESSED npy bytes, so codec'd
        # shards are decompressed before hashing (writer.write_npy)
        try:
            if codec == "none":
                crc = writer.crc32_file(path)
            else:
                import zlib
                with open(path, "rb") as f:
                    raw = writer.decompress_bytes(f.read(), codec)
                crc = zlib.crc32(raw) & 0xFFFFFFFF
        except Exception as e:  # noqa: BLE001 — torn/garbled stream
            problems.append(f"{name}: undecodable {codec} shard "
                            f"{rec['file']}: {e!r}")
            return
        if crc != int(rec["crc32"]):
            problems.append(
                f"{name}: CRC mismatch in {rec['file']} "
                f"(manifest {int(rec['crc32']):#010x}, file {crc:#010x})")
            return
    try:
        dtype, shape = _npy_header(path, codec)
    except Exception as e:  # noqa: BLE001 — truncated/garbled header
        problems.append(f"{name}: unreadable npy {rec['file']}: {e!r}")
        return
    if dtype != rec["dtype"]:
        problems.append(f"{name}: dtype {dtype} != manifest {rec['dtype']}")
    if list(shape) != list(rec["shape"]):
        problems.append(f"{name}: shape {list(shape)} != manifest "
                        f"{list(rec['shape'])}")


def verify_checkpoint(dirpath: str, deep: bool = True) -> list[str]:
    """Every problem found in one committed checkpoint directory (empty
    list == valid).  ``deep`` recomputes per-file CRC32s; shallow checks
    only existence and npy headers."""
    try:
        man = read_manifest(dirpath)
    except CheckpointError as e:
        return [str(e)]
    problems: list[str] = []
    for name, rec in man.get("arrays", {}).items():
        shards = rec.get("shards")
        if shards is None:
            _check_record(dirpath, name, rec, deep, problems)
            continue
        covered = 0
        for srec in shards:
            _check_record(dirpath, name, srec, deep, problems)
            covered += int(np.prod(srec["shape"]))
        total = int(np.prod(rec["shape"]))
        if covered != total:
            problems.append(
                f"{name}: shard files cover {covered} elements of {total} "
                "— incomplete shard set for this layout")
    return problems
