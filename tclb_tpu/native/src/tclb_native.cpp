// Native host-side kernels for tclb_tpu.
//
// The reference implements its whole host layer in C++ (geometry/STL
// voxelizer: src/Geometry.cpp.Rt:462-577, VTI output: src/vtkOutput.cpp).
// The TPU compute path here is JAX/XLA/Pallas, but these two host-side
// loops are genuinely hot on large cases — an STL voxelization is
// O(nz*ny*ntri) ray tests and the VTI encoder moves the whole field
// through zlib — so they are native, bound to Python via ctypes
// (tclb_tpu/native/__init__.py) with the pure-Python implementations kept
// as a fallback and as the oracle in tests/test_native.py.
//
// Build: g++ -O3 -std=c++17 -fPIC -shared tclb_native.cpp -o ... -lz

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// STL ray-parity voxelizer.
//
// Mirrors tclb_tpu/utils/stl.py::voxelize exactly (same barycentric solve in
// the (y, z) projection, same parity fill, same half-voxel "surface" rule)
// so the two paths are interchangeable; the reference's per-triangle
// scanline rasterizer is src/Geometry.cpp.Rt:462-577.
//
// tri:  (ntri, 3 vertices, 3 coords xyz) C-contiguous doubles
// out:  (nz, ny, nx) bytes, 0/1
// side: 0 = in, 1 = out, 2 = surface
// returns 0 on success
int tclb_voxelize(const double *tri, int64_t ntri,
                  int64_t nx, int64_t ny, int64_t nz,
                  int side, uint8_t *out) {
    if (ntri < 0 || nx <= 0 || ny <= 0 || nz <= 0) return 1;
    std::memset(out, side == 1 ? 1 : 0, (size_t)(nx * ny * nz));

    std::vector<double> zmin(ntri), zmax(ntri), ymin(ntri), ymax(ntri);
    for (int64_t t = 0; t < ntri; t++) {
        const double *p = tri + t * 9;
        zmin[t] = std::min({p[2], p[5], p[8]});
        zmax[t] = std::max({p[2], p[5], p[8]});
        ymin[t] = std::min({p[1], p[4], p[7]});
        ymax[t] = std::max({p[1], p[4], p[7]});
    }

    std::vector<int64_t> sel;
    std::vector<double> xs;
    for (int64_t iz = 0; iz < nz; iz++) {
        const double z = (double)iz;
        sel.clear();
        for (int64_t t = 0; t < ntri; t++)
            if (zmin[t] <= z && zmax[t] >= z) sel.push_back(t);
        if (sel.empty()) continue;
        for (int64_t iy = 0; iy < ny; iy++) {
            const double y = (double)iy;
            xs.clear();
            for (int64_t t : sel) {
                if (ymin[t] > y || ymax[t] < y) continue;
                const double *p = tri + t * 9;
                const double a0 = p[0], a1 = p[1], a2 = p[2];
                const double b0 = p[3], b1 = p[4], b2 = p[5];
                const double c0 = p[6], c1 = p[7], c2 = p[8];
                const double d = (b1 - a1) * (c2 - a2)
                               - (c1 - a1) * (b2 - a2);
                if (std::fabs(d) <= 1e-30) continue;
                const double w1 = ((y - a1) * (c2 - a2)
                                   - (c1 - a1) * (z - a2)) / d;
                const double w2 = ((b1 - a1) * (z - a2)
                                   - (y - a1) * (b2 - a2)) / d;
                if (w1 >= 0.0 && w2 >= 0.0 && w1 + w2 <= 1.0) {
                    const double w0 = 1.0 - w1 - w2;
                    xs.push_back(w0 * a0 + w1 * b0 + w2 * c0);
                }
            }
            if (xs.empty()) continue;
            std::sort(xs.begin(), xs.end());
            uint8_t *row = out + (iz * ny + iy) * nx;
            if (side == 2) {
                // voxel centers within half a cell of a surface crossing;
                // nearbyint rounds half-to-even exactly like Python round()
                for (double xh : xs) {
                    const int64_t i = (int64_t)std::nearbyint(xh);
                    if (i >= 0 && i < nx && std::fabs((double)i - xh) <= 0.5)
                        row[i] = 1;
                }
                continue;
            }
            const uint8_t fill = side == 1 ? 0 : 1;
            for (size_t k = 0; k + 1 < xs.size(); k += 2) {
                int64_t lo = (int64_t)std::ceil(xs[k]);
                int64_t hi = (int64_t)std::floor(xs[k + 1]);
                lo = std::max<int64_t>(lo, 0);
                hi = std::min<int64_t>(hi, nx - 1);
                for (int64_t i = lo; i <= hi; i++) row[i] = fill;
            }
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// VTI appended-data zlib block encoder (vtkZLibDataCompressor layout).
//
// VTK's compressed appended block is: a header of UInt32s
// [nblocks, blocksize, last_partial_blocksize, compressed_size_0, ...]
// followed by the concatenated zlib streams of each block.  The reference
// writes raw appended data (src/vtkOutput.cpp); compression is an added
// capability — every VTK reader understands it and large fields shrink ~3x.
//
// out must have room for 4*(3+nblocks) + nblocks*compressBound(block).
// Returns total bytes written, or -1 on error.
int64_t tclb_zlib_blocks(const uint8_t *data, int64_t n,
                         int64_t block, int level,
                         uint8_t *out, int64_t outcap) {
    if (n < 0 || block <= 0) return -1;
    const int64_t nblocks = n == 0 ? 1 : (n + block - 1) / block;
    const int64_t last = n == 0 ? 0 : (n - (nblocks - 1) * block);
    const int64_t header = 4 * (3 + nblocks);
    if (outcap < header) return -1;
    uint32_t *h = (uint32_t *)out;
    h[0] = (uint32_t)nblocks;
    h[1] = (uint32_t)block;
    h[2] = (uint32_t)(last == block ? 0 : last);
    int64_t off = header;
    for (int64_t b = 0; b < nblocks; b++) {
        const int64_t sz = b == nblocks - 1 ? last : block;
        uLongf dest = (uLongf)(outcap - off);
        if (compress2(out + off, &dest, data + b * block, (uLong)sz,
                      level) != Z_OK)
            return -1;
        h[3 + b] = (uint32_t)dest;
        off += (int64_t)dest;
    }
    return off;
}

}  // extern "C"
