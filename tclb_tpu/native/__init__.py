"""Native host-side kernels: on-demand g++ build + ctypes bindings.

The reference's host layer is C++ (SURVEY.md §2.1 native-code census);
here the two genuinely hot host loops — STL voxelization
(reference src/Geometry.cpp.Rt:462-577) and VTI appended-data encoding
(reference src/vtkOutput.cpp) — are native C++ (src/tclb_native.cpp),
compiled once per checkout into ``_build/`` and loaded via ctypes.

Everything degrades gracefully: no compiler, a failed build, or
``TCLB_NATIVE=0`` fall back to the pure-Python implementations
(tclb_tpu/utils/stl.py, zlib stdlib), which remain the test oracle.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import zlib

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "tclb_native.cpp")
_lib: ctypes.CDLL | None = None
_tried = False


def _build_lib() -> str | None:
    """Compile (or reuse) the shared lib; returns its path or None.

    Any OSError — missing .cpp in a stripped install, read-only
    site-packages, no compiler — means "no native lib", never a crash."""
    try:
        with open(_SRC, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
        out = os.path.join(_DIR, "_build", f"libtclb_native-{tag}.so")
        if os.path.exists(out):
            return out
        os.makedirs(os.path.dirname(out), exist_ok=True)
        tmp = f"{out}.tmp.{os.getpid()}"  # per-pid: parallel builders
        cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", _SRC,
               "-o", tmp, "-lz"]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic publish
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, building it on first call (or None)."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("TCLB_NATIVE", "1") == "0":
        return None
    path = _build_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.tclb_voxelize.restype = ctypes.c_int
    lib.tclb_voxelize.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int, ctypes.POINTER(ctypes.c_uint8)]
    lib.tclb_zlib_blocks.restype = ctypes.c_int64
    lib.tclb_zlib_blocks.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


_SIDES = {"in": 0, "out": 1, "surface": 2}


def voxelize(tri: np.ndarray, shape_xyz: tuple[int, int, int],
             side: str = "in") -> np.ndarray | None:
    """Native ray-parity voxelization; None if the native lib is absent.

    Same contract as tclb_tpu.utils.stl.voxelize: bool array [z, y, x].
    """
    lib = get_lib()
    if lib is None:
        return None
    tri = np.ascontiguousarray(tri, dtype=np.float64)
    nx, ny, nz = shape_xyz
    out = np.zeros((nz, ny, nx), dtype=np.uint8)
    rc = lib.tclb_voxelize(
        tri.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), tri.shape[0],
        nx, ny, nz, _SIDES[side],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        return None
    return out.astype(bool)


def zlib_blocks(data: bytes, block: int = 1 << 15,
                level: int = 6) -> bytes:
    """vtkZLibDataCompressor appended block: UInt32 header + zlib streams.

    Uses the native encoder when available, else a byte-identical Python
    fallback (zlib.compress produces the same stream — both are zlib at the
    same level).
    """
    lib = get_lib()
    n = len(data)
    if n == 0:
        # header [0, block, 0]: zero blocks.  [1, block, 0] would declare
        # one FULL uncompressed block per VTK convention while the stream
        # decompresses to nothing — a strict reader would mis-size.
        return np.array([0, block, 0], dtype=np.uint32).tobytes()
    nblocks = (n + block - 1) // block
    if lib is not None:
        cap = 4 * (3 + nblocks) + nblocks * (block + block // 1000 + 64)
        out = np.empty(cap, dtype=np.uint8)
        src = np.frombuffer(data, dtype=np.uint8)
        total = lib.tclb_zlib_blocks(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
            block, level,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
        if total > 0:
            return out[:total].tobytes()
    # Python fallback, same layout
    last = n - (nblocks - 1) * block
    chunks = [zlib.compress(data[b * block:(b + 1) * block], level)
              for b in range(nblocks)]
    head = np.array([nblocks, block, 0 if last == block else last]
                    + [len(c) for c in chunks], dtype=np.uint32)
    return head.tobytes() + b"".join(chunks)
