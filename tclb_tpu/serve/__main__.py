"""Sweep runner: expand an XML base case over --param grids and serve
the ensemble through the scheduler.

``python -m tclb_tpu sweep case.xml --param "nu=0.01:0.05:8"`` (also
reachable as ``python -m tclb_tpu.serve``).  The config's Units,
Geometry painting and <Model><Params> become the shared base; the
cartesian product of the --param axes becomes the case list; cases run
batched (bit-identical to sequential runs) through the compiled-
executable cache, and the result is one JSON document on stdout with
per-case globals and the cache/scheduler statistics CI asserts on.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


def run_sweep(args) -> int:
    import jax.numpy as jnp

    from tclb_tpu import telemetry
    from tclb_tpu.control.sweep import expand_cases, load_setup
    from tclb_tpu.serve.cache import default_cache
    from tclb_tpu.serve.ensemble import EnsemblePlan
    from tclb_tpu.serve.scheduler import JobSpec, Scheduler

    model = None
    if args.model:
        from tclb_tpu.models import get_model
        model = get_model(args.model)
    dtype = {"f32": jnp.float32, "f64": jnp.float64}[args.precision]
    if dtype is jnp.float64:
        import jax
        jax.config.update("jax_enable_x64", True)

    setup = load_setup(args.case, model=model, dtype=dtype)
    cases = expand_cases(setup, args.param or [])
    niter = args.iters if args.iters is not None else setup.niter
    if niter <= 0:
        print("error: no <Solve Iterations> in the config and no --iters",
              file=sys.stderr)
        return 2

    # one plan for the whole sweep: the painted (un-inited) base lattice
    # carries the XML's zonal base params, which a settings dict cannot
    plan = EnsemblePlan(setup.model, setup.shape, base=setup.solver.lattice)
    cache = default_cache()
    sched = Scheduler(max_batch=args.batch, retries=args.retries,
                      cache=cache, autostart=False)
    specs = [JobSpec(model=setup.model, shape=setup.shape, case=c,
                     niter=niter, dtype=plan.dtype, plan=plan,
                     timeout_s=args.timeout, name=c.name or f"case{i}")
             for i, c in enumerate(cases)]
    jobs = sched.run(specs)
    sched.close()

    out = {
        "config": args.case,
        "model": setup.model.name,
        "shape": list(setup.shape),
        "iterations": int(niter),
        "cases": [],
        "cache": cache.stats(),
        "counters": {k: v for k, v in telemetry.counters().items()
                     if k.startswith("serve.")},
    }
    failed = 0
    for job in jobs:
        rec: dict = {"name": job.spec.name, "status": job.status,
                     "attempts": job.attempts, "degraded": job.degraded}
        if job.status == "done":
            r = job._result
            rec["settings"] = dict(r.case.settings)
            if r.case.zonal:
                rec["zonal"] = {f"{n}@{z}": v
                                for (n, z), v in r.case.zonal.items()}
            rec["globals"] = r.globals
        else:
            failed += 1
            rec["error"] = repr(job.error)
        out["cases"].append(rec)
    print(json.dumps(out, indent=2))
    if failed:
        print(f"sweep: {failed}/{len(jobs)} case(s) failed",
              file=sys.stderr)
        return 1
    return 0


def add_sweep_arguments(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("case", help="base case.xml config")
    sp.add_argument("--param", action="append", default=[],
                    metavar="NAME=SPEC",
                    help="sweep axis: 'nu=0.01:0.05:8' (linspace) or "
                    "'nu=0.01,0.02' (list); 'Name-zone=...' for zonal "
                    "settings; repeatable (axes combine cartesian)")
    sp.add_argument("--model", "-m", default=None,
                    help="model name (or model= attr in the config)")
    sp.add_argument("--iters", type=int, default=None,
                    help="iterations per case (default: <Solve "
                    "Iterations> from the config)")
    sp.add_argument("--batch", type=int, default=None,
                    help="max cases per batched dispatch (default: the "
                    "memory-predicated cap)")
    sp.add_argument("--retries", type=int, default=1,
                    help="batched-run retries before degrading to the "
                    "sequential path (default 1)")
    sp.add_argument("--timeout", type=float, default=None,
                    help="per-job timeout in seconds")
    sp.add_argument("--precision", choices=("f32", "f64"), default="f32")


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tclb-sweep",
        description="batched parameter sweep over an XML base case")
    add_sweep_arguments(p)
    return run_sweep(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
