"""AOT compiled-executable cache for the ensemble engine.

Every distinct (model, shape, engine tag, batch size, dtype) class costs
one trace + XLA compile; a sweep that re-uses the class must not pay it
again.  The cache AOT-compiles via ``jax.jit(...).lower().compile()``
and keys on ``Model.fingerprint`` — never ``id()`` (the
``hygiene.id_keyed_cache`` scan errors on any id()-keyed cache: ids
recycle and would alias unrelated models) — plus the trace-shaping
extras the spec'd key implies: the present-node-type set (the trace
specializes on painted types), the static ``niter`` and whether Init is
fused in.

Process-persistent compiles: ``TCLB_COMPILE_CACHE=<dir>`` wires JAX's
persistent compilation cache so a *new* process warm-starts from disk
(the serving analogue of a model-server's compiled-artifact store).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Callable, Optional

import jax

from tclb_tpu import faults, telemetry
from tclb_tpu.utils import log

_persistent_wired = False


def wire_persistent_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at ``TCLB_COMPILE_CACHE``
    (idempotent; no-op when the env is unset).  Returns the directory
    when wired."""
    global _persistent_wired
    cache_dir = os.environ.get("TCLB_COMPILE_CACHE")
    if not cache_dir:
        return None
    if not _persistent_wired:
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # serving compiles are worth persisting regardless of their
            # compile time; the default threshold would skip tiny cases
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0)
        except Exception as e:  # noqa: BLE001 - knob names drift across jax
            log.warning(f"TCLB_COMPILE_CACHE: could not wire the "
                        f"persistent compilation cache ({e!r})")
            return None
        _persistent_wired = True
        log.info(f"serve: persistent compilation cache at {cache_dir}")
    return cache_dir


class CompiledCache:
    """LRU cache of AOT-compiled ensemble executables.

    ``capacity`` bounds live executables (each pins device memory for
    its program); default from ``TCLB_SERVE_CACHE_CAP`` or 16.  Hits and
    misses are counted on the instance and mirrored to telemetry
    (``serve.cache.hit``/``serve.cache.miss`` counters + a
    ``serve.compile`` span per lookup carrying ``cache="hit"|"miss"`` —
    the report CLI derives the serving hit rate from those spans)."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.environ.get("TCLB_SERVE_CACHE_CAP", "16"))
        self.capacity = max(1, int(capacity))
        self._entries: OrderedDict[tuple, Callable] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        wire_persistent_cache()

    def key_for(self, plan, batch: int, niter: int, init: bool,
                device: Any = None) -> tuple:
        grad = getattr(plan, "grad", None)
        return (plan.model.fingerprint,
                plan.shape,
                plan.engine_tag(batch),
                int(batch),
                str(jax.numpy.dtype(plan.dtype)),
                int(niter),
                bool(init),
                frozenset(plan.present or ()),
                str(device),
                None if grad is None else grad.key())

    def get(self, plan, batch: int, niter: int, fn: Callable,
            init: bool = True, device: Any = None) -> Callable:
        """Compiled ``(states, params) -> states`` executable for this
        plan/batch/niter class, compiling on miss.  ``device`` pins the
        executable to one device via input shardings (a fleet lane's
        cache compiles against its own device so executables never
        migrate)."""
        key = self.key_for(plan, batch, niter, init, device=device)
        hit = key in self._entries
        fields = dict(cache="hit" if hit else "miss",
                      engine=plan.engine_tag(batch),
                      model=plan.model.name, batch=int(batch),
                      niter=int(niter))
        if device is not None:
            fields["device"] = str(device)
        with telemetry.span("serve.compile", **fields):
            if hit:
                self._entries.move_to_end(key)
                self.hits += 1
                telemetry.counter("serve.cache.hit")
                return self._entries[key]
            self.misses += 1
            telemetry.counter("serve.cache.miss")
            faults.fire("serve.compile", model=plan.model.name,
                        batch=int(batch))
            # forward plans lower on (states, params); gradient plans on
            # (thetas, states, params) — the plan owns the input tuple
            abstract = plan.abstract_inputs(batch, device=device)
            lowered = jax.jit(fn, static_argnames=("niter",)).lower(
                *abstract, niter=niter)
            compiled = lowered.compile()
        self._entries[key] = compiled
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self.evictions += 1
            telemetry.counter("serve.cache.evict")
        return compiled

    def stats(self) -> dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "size": len(self._entries),
                "capacity": self.capacity}


_default_cache: Optional[CompiledCache] = None


def default_cache() -> CompiledCache:
    """Process-wide cache shared by the sweep CLI and the scheduler."""
    global _default_cache
    if _default_cache is None:
        _default_cache = CompiledCache()
    return _default_cache
