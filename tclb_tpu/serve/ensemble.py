"""Batched ensemble engine: N independent cases in one device dispatch.

Parameter sweeps, UQ ensembles and optimization line-searches are all
"N cases of the same (model, shape, engine) class, different settings"
workloads (the reference TCLB amortizes these one-case-per-MPI-job
through its NLopt loop).  Here the whole ensemble is ONE executable:
stacked ``LatticeState``s and per-case ``SimParams`` go through
:func:`tclb_tpu.core.lattice.make_ensemble_iterate`, keeping the
contract that matters:

    **bit-parity** — the batched run's per-case output is bit-identical
    to running each case alone through ``Lattice.iterate``'s XLA engine.

The default ``mode="map"`` engine guarantees parity by compiling each
case's whole loop as an isolated ``lax.map`` body (the exact clustering
of the sequential program); ``mode="vmap"`` vectorizes the batch per
step for throughput but lets XLA re-cluster some models' FMA chains by
1 ulp (see make_ensemble_iterate's docstring).  tests/test_serve.py
enforces parity for a plain and a zonal-settings model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tclb_tpu.core.lattice import (Lattice, LatticeState, SimParams,
                                   make_ensemble_iterate,
                                   make_ensemble_step)
from tclb_tpu.core.registry import Model


@dataclasses.dataclass(frozen=True)
class Case:
    """One ensemble member: setting overrides on top of the shared base.

    ``settings`` are plain ``name -> value`` assignments (derived
    settings update exactly like ``Lattice.set_setting``); ``zonal``
    maps ``(name, zone_id) -> value`` into the case's zone table.
    ``theta`` is the design vector for gradient-mode plans (ignored —
    and normally None — on forward plans)."""

    settings: dict[str, float] = dataclasses.field(default_factory=dict)
    zonal: dict[tuple[str, int], float] = dataclasses.field(
        default_factory=dict)
    name: str = ""
    theta: Any = None


@dataclasses.dataclass(frozen=True)
class GradSpec:
    """What a gradient-mode plan differentiates: the design space plus
    the adjoint configuration of :func:`make_unsteady_gradient`.

    ``key()`` is the CONTENT identity used for batch binning and the
    compiled-executable cache (never ``id()``): two GradSpecs built from
    the same design class over the same parameter names with the same
    remat depth produce the same executable and must share it."""

    design: Any
    levels: Optional[int] = None
    engine: str = "xla"
    action: str = "Iteration"

    def key(self) -> tuple:
        d = self.design
        return (type(d).__name__,
                tuple(getattr(d, "names", ()) or ()),
                self.levels, self.engine, self.action)


@dataclasses.dataclass
class EnsembleResult:
    case: Case
    state: LatticeState            # this case's final (unstacked) state
    globals: dict[str, float]
    # gradient-mode extras (None on forward plans)
    objective: Optional[float] = None
    grad: Any = None


def case_params(model: Model, base: SimParams, case: Case,
                dtype: Any) -> SimParams:
    """Per-case SimParams, derived with the same float64 host arithmetic
    as ``Lattice.set_setting`` (same order: scalar settings with their
    derived updates first, then zonal table entries) — any drift here
    would silently break the bit-parity contract."""
    vec = np.array(base.settings, dtype=np.float64)
    table = np.array(base.zone_table, dtype=np.float64)
    for name, value in case.settings.items():
        model._set_with_derived(vec, name, float(value))
        table[model.setting_index[name], :] = vec[model.setting_index[name]]
    for (name, zone), value in case.zonal.items():
        table[model.setting_index[name], int(zone)] = float(value)
    return base.replace(
        settings=jnp.asarray(vec, dtype=dtype),
        zone_table=jnp.asarray(table, dtype=dtype))


def stack_trees(trees: Sequence[Any]) -> Any:
    """Stack a list of identical pytrees along a new leading case axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def unstack_tree(tree: Any, n: int) -> list:
    """Split a case-stacked pytree back into n per-case pytrees."""
    return [jax.tree.map(lambda x: x[k], tree) for k in range(n)]


class EnsemblePlan:
    """The compiled shape of one ensemble class: a model + lattice shape
    + painted flags + dtype, ready to run any batch of setting cases.

    Built once per (fingerprint, shape, flags) class — the scheduler
    keeps one per batch bin — and stateless across runs: ``run`` is a
    pure dispatch.  ``engine_tag`` names the batched engine the way the
    Lattice names its fast paths (telemetry + cache key component)."""

    def __init__(self, model: Model, shape: Sequence[int],
                 flags: Optional[np.ndarray] = None,
                 dtype: Any = jnp.float32,
                 base_settings: Optional[dict[str, float]] = None,
                 base: Optional[Lattice] = None,
                 mode: str = "map",
                 storage_dtype: Any = None,
                 grad: Optional[GradSpec] = None,
                 init_on_run: bool = True,
                 storage_repr: Optional[str] = None):
        from tclb_tpu.ops.lbm import present_types
        if grad is not None and storage_dtype is not None and \
                jnp.dtype(storage_dtype) != jnp.dtype(dtype):
            raise ValueError("gradient-mode plans do not support narrowed "
                             "storage (the adjoint tape must round-trip "
                             "bit-exactly)")
        if base is None:
            base = Lattice(model, tuple(int(s) for s in shape), dtype=dtype,
                           settings=base_settings,
                           storage_dtype=storage_dtype,
                           storage_repr=storage_repr)
            if flags is not None:
                base.set_flags(np.asarray(flags, dtype=np.uint16))
        self.model = base.model
        self.shape = base.shape
        self.dtype = base.dtype
        self.storage_dtype = base.storage_dtype
        self.storage_repr = base.storage_repr
        self._shift_block = base._shift_block
        self.mode = mode
        self.flags = base._flags_host()
        self.base_state = base.state
        self.base_params = base.params
        self._host_state = None  # numpy mirror, built lazily for staging
        self.present = present_types(self.model, self.flags)
        narrowed = jnp.dtype(self.storage_dtype) != jnp.dtype(self.dtype)
        self._init = make_ensemble_step(self.model, "Init", present=None)
        if narrowed:
            # Init evaluates in the compute dtype, the carry lives narrow
            # (same round trip as Lattice._init's precision-ladder wrap;
            # the shift block applies the at-rest representation)
            raw_init, sdt = self._init, jnp.dtype(self.storage_dtype)
            sb = self._shift_block

            def _init_narrow(states, params):
                from tclb_tpu.core import shift as ddf
                cdt = params.settings.dtype
                out = raw_init(
                    states.replace(
                        fields=ddf.widen_stack(states.fields, cdt, sb)),
                    params)
                return out.replace(
                    fields=ddf.narrow_stack(out.fields, sdt, sb))
            self._init = _init_narrow
        self._iterate = make_ensemble_iterate(
            self.model, present=self.present, mode=mode,
            storage_dtype=(self.storage_dtype if narrowed else None),
            storage_shift=self._shift_block)
        self.grad = grad
        # init_on_run=False plans continue from base_state as-is (resume
        # segments): run() skips the Init stage unless told otherwise
        self.init_on_run = bool(init_on_run)

    def engine_tag(self, batch: int) -> str:
        if self.grad is not None:
            g = self.grad
            tag = (f"ensemble_grad[{self.model.name},b={batch},"
                   f"design={g.key()[0]},lv={g.levels},eng={g.engine}")
            return tag + "]"
        tag = f"ensemble_xla[{self.model.name},{self.mode},b={batch}"
        if jnp.dtype(self.storage_dtype) != jnp.dtype(self.dtype):
            # dtype AND representation: a raw-bf16 and a shifted-bf16
            # plan compile DIFFERENT programs (the seam adds), so the
            # CompiledCache key must split on both
            tag += (f",{np.dtype(self.storage_dtype).name}"
                    f"/{self.storage_repr}")
        return tag + "]"

    # -- pieces the cache compiles ----------------------------------------- #

    def build_fn(self, init: bool = True) -> Callable:
        """The whole ensemble program as one jittable callable over this
        plan's input tuple (see :meth:`abstract_inputs`).

        Forward plans: ``fn(states, params, niter) -> states`` (init +
        bulk + final).  Gradient plans: ``fn(thetas, states, params,
        niter) -> (objs, grads, states)`` — N unsteady-adjoint
        evaluations in ONE dispatch, each case's whole (forward +
        reverse) sweep compiled as an isolated ``lax.map`` body so the
        per-case gradient is bit-identical to running
        :func:`make_unsteady_gradient` on that case alone (the mode="map"
        parity contract, extended to reverse mode)."""
        if self.grad is not None:
            from tclb_tpu.adjoint.run import make_unsteady_gradient
            g = self.grad

            def gfn_fn(thetas, states: LatticeState, params: SimParams,
                       niter: int):
                gfn = make_unsteady_gradient(
                    self.model, g.design, niter, action=g.action,
                    levels=g.levels, engine=g.engine, shape=self.shape,
                    dtype=self.dtype)

                def one(args):
                    th, st, pp = args
                    if init:
                        st = self._init_one(st, pp)
                    return gfn(th, st, pp)

                return jax.lax.map(one, (thetas, states, params))
            return gfn_fn

        def fn(states: LatticeState, params: SimParams, niter: int
               ) -> LatticeState:
            if init:
                states = self._init(states, params)
            return self._iterate(states, params, niter)
        return fn

    def _init_one(self, state: LatticeState, params: SimParams
                  ) -> LatticeState:
        """Init for ONE (unstacked) case — the grad map body runs it
        inside its own lax.map iteration so the whole per-case program
        (init + forward + reverse) stays an isolated sequential trace."""
        stacked = self._init(jax.tree.map(lambda x: x[None], state),
                             jax.tree.map(lambda x: x[None], params))
        return jax.tree.map(lambda x: x[0], stacked)

    def abstract_inputs(self, batch: int, device: Any = None) -> tuple:
        """``jax.ShapeDtypeStruct`` pytrees matching a batch-of-``batch``
        call — what AOT lowering sees instead of real arrays.  Forward
        plans get ``(states, params)``; gradient plans prepend the
        stacked design vectors: ``(thetas, states, params)``.  With
        ``device`` the structs carry a ``SingleDeviceSharding`` so the
        compiled executable is pinned to that device (a fleet lane's
        executables never migrate)."""
        sharding = None
        if device is not None:
            from jax.sharding import SingleDeviceSharding
            sharding = SingleDeviceSharding(device)

        def sds(x):
            return jax.ShapeDtypeStruct((batch,) + tuple(x.shape), x.dtype,
                                        sharding=sharding)
        states = jax.tree.map(sds, self.base_state)
        params = jax.tree.map(sds, self.base_params)
        if self.grad is not None:
            theta0 = self._theta_template()
            return (jax.tree.map(sds, theta0), states, params)
        return states, params

    def _theta_template(self):
        """An abstract per-case design vector (shape/dtype only)."""
        return jax.eval_shape(
            lambda s, p: self.grad.design.get(s, p),
            self.base_state, self.base_params)

    def _case_theta(self, case: Case):
        if case.theta is None:
            raise ValueError(
                f"gradient-mode plan needs Case.theta (case "
                f"{case.name!r} has none)")
        tmpl = self._theta_template()
        return jax.tree.map(lambda t, th: jnp.asarray(th, t.dtype),
                            tmpl, case.theta)

    def stack_cases(self, cases: Sequence[Case]) -> tuple:
        states = stack_trees([self.base_state] * len(cases))
        params = stack_trees([case_params(self.model, self.base_params, c,
                                          self.dtype) for c in cases])
        if self.grad is not None:
            thetas = stack_trees([self._case_theta(c) for c in cases])
            return thetas, states, params
        return states, params

    def host_stacked_cases(self, cases: Sequence[Case]) -> tuple:
        """Host-side (numpy) stacked inputs for a batch — what a staging
        thread builds while the device executes the *previous* batch, so
        the only device work left is one explicit ``device_put``.  Values
        are identical to :meth:`stack_cases` (same float64 host derivation
        in :func:`case_params`), preserving the bit-parity contract."""
        if self._host_state is None:
            self._host_state = jax.tree.map(np.asarray, self.base_state)
        states = jax.tree.map(
            lambda x: np.broadcast_to(x[None], (len(cases),) + x.shape),
            self._host_state)
        per_case = [case_params(self.model, self.base_params, c, self.dtype)
                    for c in cases]
        params = jax.tree.map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *per_case)
        if self.grad is not None:
            thetas = jax.tree.map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[self._case_theta(c) for c in cases])
            return thetas, states, params
        return states, params

    def results_from(self, cases: Sequence[Case], out
                     ) -> list[EnsembleResult]:
        """Per-case results (input order) from a batched output.  Forward
        plans pass the stacked final state; gradient plans the
        ``(objs, grads, states)`` triple from the batched adjoint."""
        m = self.model
        objs = grads = None
        if self.grad is not None:
            objs, gstack, out = out
            objs = np.asarray(objs)
            grads = unstack_tree(gstack, len(cases))
        finals = unstack_tree(out, len(cases))
        results = []
        for k, (case, st) in enumerate(zip(cases, finals)):
            vals = np.asarray(st.globals_)
            results.append(EnsembleResult(
                case=case, state=st,
                globals={g.name: float(vals[i])
                         for i, g in enumerate(m.globals_)},
                objective=(None if objs is None else float(objs[k])),
                grad=(None if grads is None else grads[k])))
        return results

    def rebase(self, state: LatticeState) -> None:
        """Replace the shared base state in place — a resume segment
        starts every case from the previous segment's final state.  The
        lazy host mirror is invalidated; params, flags and the compiled
        engine are untouched (the AOT cache key never hashes base_state,
        so every segment reuses one compiled executable)."""
        self.base_state = state
        self._host_state = None

    def run(self, cases: Sequence[Case], niter: int,
            cache=None, init: Optional[bool] = None
            ) -> list[EnsembleResult]:
        """Run the batch; returns per-case results in input order.
        ``init=None`` follows the plan's ``init_on_run`` default."""
        init = self.init_on_run if init is None else bool(init)
        cases = [c if isinstance(c, Case) else Case(settings=dict(c))
                 for c in cases]
        inputs = self.stack_cases(cases)
        fn = self.build_fn(init=init)
        if cache is not None:
            compiled = cache.get(self, batch=len(cases), niter=niter,
                                 fn=fn, init=init)
            out = compiled(*inputs)
        else:
            out = jax.jit(fn, static_argnames=("niter",))(
                *inputs, niter=niter)
        return self.results_from(cases, out)

    # -- sequential reference path ----------------------------------------- #

    def run_sequential(self, case: Case, niter: int,
                       device: Any = None) -> EnsembleResult:
        """One case through the plain ``Lattice`` path (auto-selected
        engine) — the scheduler's degradation target when a batched
        compile fails, and the parity reference in tests.  ``device``
        pins the run to one device (a fleet lane degrading a poisoned
        batch stays on its own lane)."""
        case = case if isinstance(case, Case) else Case(settings=dict(case))
        lat = Lattice(self.model, self.shape, dtype=self.dtype,
                      storage_dtype=self.storage_dtype,
                      storage_repr=self.storage_repr, device=device)
        lat.set_flags(self.flags.copy())
        lat.params = case_params(self.model, self.base_params, case,
                                 self.dtype)
        lat.init()
        if self.grad is not None:
            from tclb_tpu.adjoint.run import make_unsteady_gradient
            g = self.grad
            gfn = make_unsteady_gradient(
                self.model, g.design, niter, action=g.action,
                levels=g.levels, engine=g.engine, shape=self.shape,
                dtype=self.dtype)
            obj, gr, final = gfn(self._case_theta(case), lat.state,
                                 lat.params)
            vals = np.asarray(final.globals_)
            return EnsembleResult(
                case=case, state=final,
                globals={gg.name: float(vals[i])
                         for i, gg in enumerate(self.model.globals_)},
                objective=float(obj), grad=gr)
        if niter > 0:
            lat.iterate(niter)
        return EnsembleResult(case=case, state=lat.state,
                              globals=lat.get_globals())


def run_ensemble(model: Model, cases: Sequence[Case | dict], niter: int,
                 *, shape: Optional[Sequence[int]] = None,
                 flags: Optional[np.ndarray] = None,
                 dtype: Any = jnp.float32,
                 storage_dtype: Any = None,
                 storage_repr: Optional[str] = None,
                 base_settings: Optional[dict[str, float]] = None,
                 base: Optional[Lattice] = None,
                 cache=None, init: bool = True) -> list[EnsembleResult]:
    """Run N independent cases of one model/shape class in one dispatch.

    ``base`` reuses an existing (painted, un-inited) Lattice as the
    shared starting point; otherwise ``shape``/``flags``/
    ``base_settings`` build one.  Per-case output is bit-identical to
    running each case alone on the XLA engine (see module docstring).
    """
    if base is None and shape is None:
        raise ValueError("run_ensemble needs `shape` (or `base`)")
    plan = EnsemblePlan(model, shape or (), flags=flags, dtype=dtype,
                        base_settings=base_settings, base=base,
                        storage_dtype=storage_dtype,
                        storage_repr=storage_repr)
    return plan.run(cases, niter, cache=cache, init=init)
