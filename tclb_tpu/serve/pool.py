"""Process-isolated worker pool: supervision, hang detection, backoff.

One solver worker **subprocess** per lane (see
:mod:`tclb_tpu.serve.worker`) — the process-isolation analogue of the
reference TCLB's MPI rank.  The failure unit becomes one worker: a hung
XLA compile, a wedged device, or a native crash kills (at most) one
child process, and the supervisor restarts it while sibling lanes keep
serving and the gateway front door stays responsive.

Supervision contract, per worker:

* **heartbeats** — workers beat *on progress* (once per solve chunk);
  a beat older than ``heartbeat_timeout_s`` mid-job is a hang
  (``serve.worker_hung``), and the worker is killed;
* **escalation** — SIGTERM first (the worker's flight recorder dumps on
  it), SIGKILL after ``term_grace_s`` (``serve.worker_killed``);
* **crash-loop backoff** — respawns run through
  :class:`~tclb_tpu.serve.retry.RetryPolicy` (the
  ``hygiene.unpoliced_retry`` contract); a worker that stays up
  ``stable_after_s`` or completes a job resets the failure streak, and
  a lane that exhausts the policy is marked dead;
* **no lost jobs** — a job in flight on a dead/hung worker is re-queued
  (up to ``job_attempts``); resumable jobs re-enter via
  ``CheckpointManager.latest()`` bit-identically.

Job specs and results cross the pipe as plain JSON + ``.npy`` payloads
(never pickled device arrays).  Fault points fired on the supervisor
side: ``pool.spawn`` (spawn attempt) and ``pool.ipc`` (frame send /
result receive); ``pool.heartbeat`` / ``pool.worker_exit`` /
``pool.telemetry_relay`` fire inside the worker — the installed plan
crosses the process boundary because :func:`_spawn` re-serializes it
into the child's ``TCLB_FAULTS``.

Cross-process telemetry relay (on by default, ``relay=False`` to opt
out): workers batch their telemetry events into ``{"t": "telemetry"}``
frames between solve chunks, and the supervisor re-emits each event
into the parent fan-out stamped with ``worker_pid`` / ``lane`` /
``incarnation`` — so worker iterate spans, engine fallbacks, and
failchecks reach the gateway's ``/metrics``, ``/status``, flight ring,
and JSONL trace, and ``telemetry report --job <id>`` renders one
timeline spanning both processes.  ``{"t": "progress"}`` frames land on
the in-flight :class:`PoolJob` (``job.progress`` + ``on_progress``
callback) for the gateway's ``/stream`` long-poll.  Unknown frame kinds
are counted (``pool.unknown_frame``) and warned once per kind, so
supervisor/worker protocol drift is visible.

Monitor contract: the pool registers a ``pool`` ``/status`` provider
(per-worker pid / state / restarts / last-heartbeat age + recent worker
post-mortems with their ``flight-<pid>.jsonl`` paths) and attaches the
flight recorder; every worker attaches its own recorder in-process, so
a worker crash leaves its own dump.
"""

from __future__ import annotations

import os
import queue
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Optional

from tclb_tpu import faults, telemetry
from tclb_tpu.serve.retry import RetryPolicy
# the !II frame protocol lives in cluster/wire.py (shared between the
# worker pipe here and the pod control channel); worker re-exports it
from tclb_tpu.cluster.wire import (IpcError, npy_load, read_frame,
                                   write_frame)
from tclb_tpu.telemetry import live as tlive
from tclb_tpu.telemetry import locks
from tclb_tpu.utils import log


class PoolJobError(RuntimeError):
    """A pool job failed terminally (worker error or attempts exhausted)."""


class PoolJob:
    """Handle for one submitted job: wait on :meth:`result`."""

    def __init__(self, jid: str, doc: dict,
                 on_done: Optional[Callable[["PoolJob"], None]] = None,
                 on_progress: Optional[Callable[["PoolJob"], None]] = None):
        self.id = jid
        self.doc = doc
        self.attempts = 0
        self.status = "queued"
        self.error: Optional[BaseException] = None
        #: latest worker progress sample (iter / mlups / wall_s
        #: [/ reductions]) — updated in place as frames arrive
        self.progress: Optional[dict] = None
        self._result: Optional[dict] = None
        self._on_done = on_done
        self._on_progress = on_progress
        self._evt = threading.Event()

    @property
    def done(self) -> bool:
        return self._evt.is_set()

    def _finish(self, result: Optional[dict],
                error: Optional[BaseException]) -> None:
        self._result = result
        self.error = error
        self.status = "done" if error is None else "failed"
        self._evt.set()
        if self._on_done is not None:
            try:
                self._on_done(self)
            except Exception as e:  # noqa: BLE001 — callback is advisory
                log.warning(f"pool: on_done callback failed: {e!r}")

    def result(self, timeout: Optional[float] = None) -> dict:
        """The result doc (globals / digest / iteration / resumed_from
        [/ fields]); raises on job failure or timeout."""
        if not self._evt.wait(timeout):
            raise TimeoutError(f"pool job {self.id} still in flight")
        if self.error is not None:
            raise self.error
        return self._result


class PoolResult:
    """Host-side outcome of a process-isolated job: plain-python globals
    and an optional ``state_sha256`` digest / fields array — NOT a live
    device :class:`EnsembleResult` (device arrays never cross the worker
    pipe)."""

    def __init__(self, case, doc: dict):
        self.case = case
        self.globals = doc.get("globals") or {}
        self.state_sha256 = doc.get("state_sha256")
        self.iteration = doc.get("iteration")
        self.resumed_from = doc.get("resumed_from")
        self.lane = doc.get("lane")
        self.pid = doc.get("pid")
        #: pod host id when the job came back through a cluster control
        #: channel (None for local lanes) — lane/pid alone are ambiguous
        #: across hosts
        self.host = doc.get("host")
        self.fields = doc.get("fields")


class _Worker:
    """Mutable per-lane supervisor state (owned by one manager thread)."""

    def __init__(self, lane: int):
        self.lane = lane
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.state = "starting"   # starting/idle/busy/backoff/dead/stopped
        self.restarts = 0
        self.jobs_done = 0
        self.life_jobs = 0
        self.spawned_at = 0.0
        self.last_beat = time.monotonic()
        self.job: Optional[PoolJob] = None
        self.frames: "queue.Queue[tuple[dict, bytes]]" = queue.Queue()


class WorkerPool:
    """Supervised fleet of solver worker subprocesses (one per lane)."""

    def __init__(self, workers: int = 1,
                 heartbeat_timeout_s: float = 60.0,
                 spawn_timeout_s: float = 180.0,
                 term_grace_s: float = 5.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 job_attempts: int = 2,
                 stable_after_s: float = 30.0,
                 worker_cmd: Optional[list] = None,
                 env: Optional[dict] = None,
                 autostart: bool = True,
                 relay: bool = True) -> None:
        self.n = max(1, int(workers))
        #: ask workers to relay their telemetry events over the pipe
        #: (TCLB_POOL_RELAY=1 at spawn); off = strict no-op worker-side
        self.relay = bool(relay)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.term_grace_s = float(term_grace_s)
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy(max_attempts=8, base_delay_s=0.1,
                             max_delay_s=5.0)
        self.job_attempts = max(1, int(job_attempts))
        self.stable_after_s = float(stable_after_s)
        self.worker_cmd = list(worker_cmd) if worker_cmd else None
        self.env = dict(env) if env else {}
        self._queue: "queue.Queue[PoolJob]" = queue.Queue()
        self._workers = [_Worker(i) for i in range(self.n)]
        self._threads: list[threading.Thread] = []
        self._lock = locks.make_lock("serve.pool.WorkerPool._lock")
        self._closing = False
        self._started = False
        self._jobs = 0
        self._done = 0
        self._failed = 0
        self._requeued = 0
        self._unknown_kinds: set = set()    # warned-once frame kinds
        self._worker_dumps: list[dict] = []  # recent flight post-mortems
        self._status_fn = self._status
        if autostart:
            self.start()

    # -- lifecycle ---------------------------------------------------------- #

    def start(self) -> "WorkerPool":
        with self._lock:
            if self._started or self._closing:
                return self
            self._started = True
        tlive.enable_live()
        tlive.flight_recorder().attach()
        tlive.register_status("pool", self._status_fn)
        for w in self._workers:
            t = threading.Thread(target=self._manage, args=(w,),
                                 name=f"tclb-pool-sup-{w.lane}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def close(self, wait: bool = True, timeout: float = 30.0) -> None:
        with self._lock:
            if self._closing:
                return
            self._closing = True
            started = self._started
        if wait and started:
            deadline = time.monotonic() + timeout
            for t in self._threads:
                t.join(timeout=max(0.1, deadline - time.monotonic()))
        # belt and braces: no child outlives the pool
        for w in self._workers:
            proc = w.proc
            if proc is not None and proc.poll() is None:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        self._fail_queued("pool is closed")
        if started:
            tlive.unregister_status("pool", self._status_fn)
            tlive.flight_recorder().detach()
            tlive.disable_live()

    def __enter__(self) -> "WorkerPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission --------------------------------------------------------- #

    def submit(self, doc: dict,
               on_done: Optional[Callable[[PoolJob], None]] = None,
               on_progress: Optional[Callable[[PoolJob], None]] = None
               ) -> PoolJob:
        """Enqueue one plain-JSON job spec; returns a :class:`PoolJob`.
        ``on_progress`` fires on each worker progress frame with the
        handle (latest sample on ``job.progress``)."""
        if self._closing:
            raise RuntimeError("pool is closed")
        with self._lock:
            self._jobs += 1
            jid = f"pj-{self._jobs}"
        job = PoolJob(jid, dict(doc), on_done, on_progress)
        if self._started and all(w.state in ("dead", "stopped")
                                 for w in self._workers):
            # nobody will ever drain the queue: fail fast instead of
            # stranding the caller on result()
            job._finish(None, PoolJobError(
                f"job {jid}: all pool lanes dead"))
            with self._lock:
                self._failed += 1
            return job
        self._queue.put(job)
        if not self._started:
            self.start()
        return job

    def run(self, docs, timeout: Optional[float] = None) -> list:
        """Submit all, wait for all; failures stay on the handles."""
        jobs = [self.submit(d) for d in docs]
        for j in jobs:
            try:
                j.result(timeout=timeout)
            except Exception:  # noqa: BLE001 — surfaced on the handle
                pass
        return jobs

    def live_workers(self) -> int:
        """Workers currently able to serve (spawned and not dead)."""
        return sum(1 for w in self._workers
                   if w.state in ("idle", "busy"))

    def stats(self) -> dict:
        with self._lock:
            return {"submitted": self._jobs, "done": self._done,
                    "failed": self._failed, "requeued": self._requeued,
                    "live": self.live_workers(),
                    "restarts": sum(w.restarts for w in self._workers)}

    # -- supervisor --------------------------------------------------------- #

    def _spawn(self, w: _Worker) -> None:
        faults.fire("pool.spawn", lane=w.lane)
        cmd = self.worker_cmd or [sys.executable, "-m",
                                  "tclb_tpu.serve.worker"]
        cmd = cmd + ["--lane", str(w.lane)]
        env = dict(os.environ)
        env.update(self.env)
        env["TCLB_POOL_LANE"] = str(w.lane)
        if self.relay:
            env["TCLB_POOL_RELAY"] = "1"
        else:
            env.pop("TCLB_POOL_RELAY", None)
        # the installed fault plan crosses the process boundary, so
        # worker-side points (pool.heartbeat / pool.worker_exit) fire
        # under the same seeded schedule
        spec = faults.current_spec()
        if spec:
            env["TCLB_FAULTS"] = spec
        else:
            env.pop("TCLB_FAULTS", None)
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE, env=env)
        w.proc = proc
        w.pid = proc.pid
        w.frames = queue.Queue()
        w.life_jobs = 0
        w.last_beat = time.monotonic()
        threading.Thread(target=self._read_loop, args=(w, proc),
                         name=f"tclb-pool-read-{w.lane}",
                         daemon=True).start()
        deadline = time.monotonic() + self.spawn_timeout_s
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                self._kill_proc(w, "spawn_timeout")
                raise PoolJobError(
                    f"worker lane {w.lane} never sent ready "
                    f"(pid {proc.pid})")
            try:
                doc, _ = w.frames.get(timeout=min(budget, 0.5))
            except queue.Empty:
                continue
            if doc.get("t") == "_eof":
                raise PoolJobError(
                    f"worker lane {w.lane} died during startup "
                    f"(rc {proc.poll()})")
            if doc.get("t") == "ready":
                break
        w.spawned_at = time.monotonic()
        w.state = "idle"
        telemetry.event("serve.worker_spawned", lane=w.lane, pid=w.pid,
                        restarts=w.restarts)
        telemetry.counter("pool.workers.spawned")

    def _read_loop(self, w: _Worker, proc: subprocess.Popen) -> None:
        """Per-incarnation reader: frames -> queue, beats -> timestamp.
        Bound to its own queue object, so a stale reader from a dead
        incarnation can never feed the replacement's queue."""
        frames = w.frames
        fh = proc.stdout
        while True:
            try:
                doc, payload = read_frame(fh)
            except (EOFError, IpcError, OSError, ValueError):
                frames.put(({"t": "_eof"}, b""))
                return
            w.last_beat = time.monotonic()
            frames.put((doc, payload))

    def _manage(self, w: _Worker) -> None:
        """One lane's supervisor loop: spawn, serve, reap, backoff."""
        fails = 0
        respawn = False
        while not self._closing:
            try:
                self._spawn(w)
            except Exception as e:  # noqa: BLE001 — spawn is a retried seam
                w.state = "backoff"
                fails += 1
                d = self.retry_policy.next_delay(
                    fails - 1, key=f"pool-spawn-{w.lane}")
                if d is None:
                    self._mark_dead(w, f"spawn crash-loop: {e!r}")
                    return
                log.warning(f"pool: lane {w.lane} spawn failed "
                            f"({e!r}); retry in {d:.2f}s")
                time.sleep(d)
                continue
            if respawn:
                telemetry.event("serve.worker_restarted", lane=w.lane,
                                pid=w.pid, restarts=w.restarts)
                telemetry.counter("pool.workers.restarted")
            reason = self._serve(w)
            if reason is None:      # pool closing: clean shutdown
                return
            respawn = True
            w.restarts += 1
            stable = (w.life_jobs > 0
                      or (time.monotonic() - w.spawned_at)
                      >= self.stable_after_s)
            fails = 0 if stable else fails + 1
            if fails:
                d = self.retry_policy.next_delay(
                    fails - 1, key=f"pool-respawn-{w.lane}")
                if d is None:
                    self._mark_dead(w, f"crash-loop ({reason})")
                    return
                w.state = "backoff"
                time.sleep(d)
        self._shutdown_worker(w)

    def _serve(self, w: _Worker) -> Optional[str]:
        """Feed jobs to one live worker until it fails (returns the
        failure reason) or the pool closes (returns None)."""
        while not self._closing:
            if w.proc.poll() is not None:
                return self._reap(w, "exit")
            try:
                job = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if self._closing:
                self._queue.put(job)
                break
            w.job = job
            w.state = "busy"
            w.last_beat = time.monotonic()
            job.status = "running"
            job.attempts += 1
            try:
                faults.fire("pool.ipc", lane=w.lane, job=job.id,
                            op="send")
                write_frame(w.proc.stdin,
                            {"t": "job", "id": job.id, "spec": job.doc})
            except Exception as e:  # noqa: BLE001 — IPC failure = lane
                self._requeue(w, job, f"ipc send: {e!r}")   # failure
                return self._reap(w, "ipc")
            telemetry.event("serve.pool_job_started", job=job.id,
                            job_id=job.doc.get("job_id"), lane=w.lane,
                            pid=w.pid, incarnation=w.restarts,
                            attempt=job.attempts)
            verdict = self._await_result(w, job)
            if verdict == "done":
                w.jobs_done += 1
                w.life_jobs += 1
                w.job = None
                w.state = "idle"
                continue
            self._requeue(w, job, verdict)
            return self._reap(w, verdict)
        self._shutdown_worker(w)
        return None

    def _await_result(self, w: _Worker, job: PoolJob) -> str:
        """Pump frames for one in-flight job; verdicts: ``done`` /
        ``hung`` / ``exit`` / ``ipc``."""
        while True:
            now = time.monotonic()
            budget = self.heartbeat_timeout_s - (now - w.last_beat)
            if budget <= 0:
                telemetry.event("serve.worker_hung", lane=w.lane,
                                pid=w.pid, job=job.id,
                                beat_age_s=round(now - w.last_beat, 3))
                telemetry.counter("pool.workers.hung")
                return "hung"
            try:
                doc, payload = w.frames.get(timeout=min(budget, 0.2))
            except queue.Empty:
                continue
            t = doc.get("t")
            if t == "_eof":
                return "exit"
            if t == "hb":
                continue
            if t == "result" and doc.get("id") == job.id:
                try:
                    faults.fire("pool.ipc", lane=w.lane, job=job.id,
                                op="recv")
                except Exception:  # noqa: BLE001 — injected IPC fault
                    return "ipc"
                if doc.get("ok"):
                    res = {k: v for k, v in doc.items()
                           if k not in ("t", "id", "ok")}
                    if payload:
                        res["fields"] = npy_load(payload)
                    job._finish(res, None)
                    with self._lock:
                        self._done += 1
                else:
                    job._finish(None, PoolJobError(
                        f"job {job.id} failed in worker lane "
                        f"{w.lane}: {doc.get('error')}"))
                    with self._lock:
                        self._failed += 1
                telemetry.event("serve.pool_job_done", job=job.id,
                                job_id=job.doc.get("job_id"),
                                lane=w.lane, ok=bool(doc.get("ok")),
                                attempts=job.attempts)
                return "done"
            if t == "telemetry":
                self._reemit(w, doc)
                continue
            if t == "progress" and doc.get("id") == job.id:
                job.progress = {k: v for k, v in doc.items()
                                if k not in ("t", "id")}
                if job._on_progress is not None:
                    try:
                        job._on_progress(job)
                    except Exception as e:  # noqa: BLE001 — advisory
                        log.warning(
                            f"pool: on_progress callback failed: {e!r}")
                continue
            # unknown frame kinds are protocol drift between supervisor
            # and worker versions: count them, warn once per kind
            telemetry.counter("pool.unknown_frame")
            if t not in self._unknown_kinds:
                self._unknown_kinds.add(t)
                log.warning(f"pool: ignoring unknown IPC frame kind "
                            f"{t!r} from lane {w.lane} (pid {w.pid})")

    def _reemit(self, w: _Worker, doc: dict) -> None:
        """Re-emit one relayed telemetry batch into the parent fan-out,
        stamped with the worker's identity — this is what carries iterate
        spans, fallbacks, and failchecks across the process boundary into
        ``/metrics``, ``/status``, the flight ring, and the trace."""
        evs = doc.get("events") or ()
        dropped = doc.get("dropped") or 0
        if dropped:
            telemetry.counter("pool.relay_dropped", int(dropped))
        if evs:
            telemetry.counter("pool.relay_events", len(evs))
        for ev in evs:
            if not isinstance(ev, dict):
                continue
            fields = dict(ev)
            kind = fields.pop("kind", None)
            if not kind:
                continue
            # event() preserves a passed `ts`, so the worker's original
            # timestamps survive re-emission and the merged timeline
            # keeps true ordering
            fields.setdefault("worker_pid", w.pid)
            fields.setdefault("lane", w.lane)
            fields.setdefault("incarnation", w.restarts)
            telemetry.event(str(kind), **fields)

    def _requeue(self, w: _Worker, job: PoolJob, reason: str) -> None:
        """A job lost to a worker failure goes back in the queue (up to
        ``job_attempts``) — never silently dropped."""
        w.job = None
        if job.attempts >= self.job_attempts:
            job._finish(None, PoolJobError(
                f"job {job.id} failed after {job.attempts} attempts "
                f"(last worker failure: {reason})"))
            with self._lock:
                self._failed += 1
        else:
            job.status = "queued"
            with self._lock:
                self._requeued += 1
            telemetry.event("serve.pool_job_requeued", job=job.id,
                            lane=w.lane, reason=reason,
                            attempts=job.attempts)
            self._queue.put(job)
            if self._closing:
                # close() may already have drained the backlog — a job
                # requeued after that must still fail fast, not strand
                # its waiter on a queue nobody serves
                self._fail_queued("pool is closed")

    def _flight_path(self, pid: Optional[int]) -> Optional[str]:
        """Where a dead worker's flight-recorder dump lands (same rule
        as ``FlightRecorder.dump``: TCLB_FLIGHT_DIR, else cwd)."""
        if pid is None:
            return None
        d = (self.env.get("TCLB_FLIGHT_DIR")
             or os.environ.get("TCLB_FLIGHT_DIR") or os.getcwd())
        return os.path.join(d, f"flight-{pid}.jsonl")

    def _note_dump(self, w: _Worker, reason: str,
                   flight: Optional[str]) -> None:
        """Remember a dead worker's post-mortem for the ``/status``
        provider, so triage doesn't hunt the flight dir by pid."""
        rec = {"lane": w.lane, "pid": w.pid, "reason": reason,
               "flight": (flight if flight and os.path.exists(flight)
                          else None),
               "ts": round(time.time(), 3)}
        with self._lock:
            self._worker_dumps.append(rec)
            del self._worker_dumps[:-8]

    def _kill_proc(self, w: _Worker, reason: str) -> None:
        """SIGTERM-then-SIGKILL escalation (SIGTERM lets the worker's
        flight recorder dump its ring first)."""
        proc = w.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self.term_grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        flight = self._flight_path(w.pid)
        telemetry.event("serve.worker_killed", lane=w.lane, pid=w.pid,
                        reason=reason, flight=flight)
        telemetry.counter("pool.workers.killed")
        self._note_dump(w, reason, flight)

    def _reap(self, w: _Worker, reason: str) -> str:
        w.state = "respawning"
        proc = w.proc
        if proc is not None and proc.poll() is None:
            self._kill_proc(w, reason)
        else:
            flight = self._flight_path(w.pid)
            telemetry.event("serve.worker_exit", lane=w.lane, pid=w.pid,
                            returncode=(None if proc is None
                                        else proc.returncode),
                            reason=reason, flight=flight)
            telemetry.counter("pool.workers.exited")
            self._note_dump(w, reason, flight)
        for fh in (getattr(proc, "stdin", None),
                   getattr(proc, "stdout", None)):
            try:
                if fh is not None:
                    fh.close()
            except OSError:  # pragma: no cover — already torn down
                pass
        return reason

    def _shutdown_worker(self, w: _Worker) -> None:
        proc = w.proc
        w.state = "stopped"
        if proc is None or proc.poll() is not None:
            return
        try:
            write_frame(proc.stdin, {"t": "shutdown"})
            proc.stdin.close()
        except (OSError, ValueError):  # pragma: no cover — pipe gone
            pass
        try:
            proc.wait(timeout=self.term_grace_s)
        except subprocess.TimeoutExpired:
            self._kill_proc(w, "shutdown_timeout")

    def _mark_dead(self, w: _Worker, why: str) -> None:
        w.state = "dead"
        log.warning(f"pool: lane {w.lane} marked dead — {why}")
        telemetry.event("serve.worker_dead", lane=w.lane, reason=why)
        if self.live_workers() == 0 and all(
                x.state in ("dead", "stopped") for x in self._workers):
            # nobody left to serve: fail the backlog instead of letting
            # callers wait forever
            self._fail_queued(f"all pool lanes dead (last: {why})")

    def _fail_queued(self, why: str) -> None:
        while True:
            try:
                job = self._queue.get_nowait()
            except queue.Empty:
                return
            job._finish(None, PoolJobError(f"job {job.id}: {why}"))
            with self._lock:
                self._failed += 1

    # -- observability ------------------------------------------------------ #

    def _status(self) -> dict:
        """Plain-python ``/status`` fragment — monitor-thread safe."""
        now = time.monotonic()
        with self._lock:
            jobs = {"submitted": self._jobs, "done": self._done,
                    "failed": self._failed, "requeued": self._requeued}
            dumps = list(self._worker_dumps)
        return {
            "workers": [{
                "lane": w.lane, "pid": w.pid, "state": w.state,
                "restarts": w.restarts, "jobs_done": w.jobs_done,
                "job": None if w.job is None else w.job.id,
                "last_heartbeat_age_s": round(now - w.last_beat, 3),
            } for w in self._workers],
            "live": self.live_workers(),
            "queue_depth": self._queue.qsize(),
            "jobs": jobs,
            "worker_dumps": dumps,
            "heartbeat_timeout_s": self.heartbeat_timeout_s,
            "closing": self._closing,
        }


def pool_doc_from_spec(spec) -> dict:
    """A :class:`~tclb_tpu.serve.scheduler.JobSpec` as a plain-JSON pool
    job doc.  Only self-contained solve specs cross the process
    boundary — a custom plan or gradient spec holds live Python/device
    objects and must use the in-process lanes."""
    if getattr(spec, "plan", None) is not None \
            or getattr(spec, "grad", None) is not None:
        raise ValueError(
            "process-isolated lanes serve plain solve specs only: a "
            "custom EnsemblePlan or GradSpec cannot cross the worker "
            "pipe (JSON + npy payloads, never pickled objects)")
    import jax.numpy as jnp
    dtype = "f64" if spec.dtype == jnp.float64 else "f32"
    sdt = {jnp.bfloat16: "bf16", jnp.float32: "f32",
           jnp.float64: "f64"}.get(spec.storage_dtype)
    case = spec.case
    return {"model": spec.model.name,
            "shape": [int(s) for s in spec.shape],
            "niter": int(spec.niter),
            "dtype": dtype, "storage_dtype": sdt,
            "storage_repr": getattr(spec, "storage_repr", None),
            "params": dict(spec.base_settings or {}),
            "case": {"name": case.name,
                     "settings": dict(case.settings)},
            "timeout_s": spec.timeout_s,
            "digest": True}
