"""Fleet dispatcher: one concurrent serving lane per local device.

The single-worker :class:`~tclb_tpu.serve.scheduler.Scheduler` drives
one device; on an 8-device host 7/8 of the fleet idles while jobs
queue.  This layer turns ``jax.devices()`` into N concurrent lanes:

* **lanes** — one worker lane per device.  Jobs bin by the scheduler's
  ``_bin_key`` and the memory-predicated ``ensemble_batch_cap``, but a
  burst spreads one-batch-per-device (fair-share cap) instead of one
  lane swallowing the queue.  Every lane owns a device-pinned
  :class:`CompiledCache` (AOT inputs carry a ``SingleDeviceSharding``),
  so executables never migrate between devices;
* **double-buffered host staging** — each lane pairs a staging thread
  with its execute thread: while the device runs batch k, batch k+1's
  stacked case params/fields are already built host-side and
  ``device_put`` onto the lane's device; results start their D2H copy
  asynchronously right after dispatch.  ``serve.lane_batch`` spans
  carry ``stage_s``/``stall_s`` so ``telemetry report`` can prove the
  staging is hidden under execution (the bench gate wants >90%);
* **size-aware routing** — a cost model compares lane time (~cells x
  niter) against the sharded engine's (~work x (1+overhead)/n, with
  ``decomposition_overhead`` from the mesh divisor search): swarms of
  small cases go to per-device ensemble lanes, a single large case is
  routed to the multi-device ``parallel/halo.py`` engine.  The fleet
  temporarily *coalesces* for a sharded job — lanes pause between
  batches, the job runs over all devices, lane mode resumes;
* **device eviction** — the degradation ladder's last rung: a lane
  whose batches repeatedly fail (batched retries exhausted AND every
  sequential degrade failed) is drained, its queued work redistributed
  to the surviving lanes, and a ``serve.device_evicted`` event emitted.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tclb_tpu import faults, telemetry
from tclb_tpu.telemetry import live as tlive
from tclb_tpu.telemetry import locks
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.ops import fusion
from tclb_tpu.parallel.mesh import (choose_decomposition,
                                    decomposition_overhead, make_mesh)
from tclb_tpu.serve.cache import CompiledCache
from tclb_tpu.serve.ensemble import Case, EnsemblePlan, EnsembleResult
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.serve.scheduler import (DONE, Job, JobSpec, JobTimeout,
                                      PENDING, RUNNING, _bin_key)
from tclb_tpu.utils import log

# below this many node-updates (cells x niter) a job is not worth
# coalescing the whole fleet for — it stays on a single lane
DEFAULT_SHARD_MIN_WORK = int(
    os.environ.get("TCLB_FLEET_SHARD_MIN_WORK", str(1 << 26)))


def route_job(spec: JobSpec, n_devices: int,
              shard_min_work: Optional[int] = None) -> tuple[str, dict]:
    """Size-aware routing verdict for one job: ``("lane", info)`` or
    ``("sharded", info)``.

    The cost model: a lane serves the job in ~``work = cells x niter``
    node-update units; the sharded engine in ~``work x (1+overhead) /
    n_devices`` plus a fleet-coalescing pause, where ``overhead`` is the
    halo-to-volume ratio of the best decomposition.  Sharding wins only
    when the job is big enough to amortize the pause (``shard_min_work``)
    and the halo tax doesn't eat the device fan-out."""
    if shard_min_work is None:
        shard_min_work = DEFAULT_SHARD_MIN_WORK
    cells = int(np.prod(spec.shape))
    work = cells * max(1, int(spec.niter))
    info: dict[str, Any] = {"cells": cells, "work": work}
    if n_devices < 2:
        return "lane", dict(info, reason="single_device")
    if spec.plan is not None:
        # a prebuilt ensemble plan (zonal XML base) only exists on the
        # batched path; the sharded Lattice can't replay it
        return "lane", dict(info, reason="plan_base")
    if spec.grad is not None:
        # the batched adjoint is a lane program (the sharded Lattice has
        # no reverse sweep); N gradient cases amortize on one lane
        return "lane", dict(info, reason="grad")
    if spec.storage_dtype is not None and \
            jnp.dtype(spec.storage_dtype) != jnp.dtype(spec.dtype):
        # halo building block is f32-only (core/lattice.py rejects it)
        return "lane", dict(info, reason="narrowed_storage")
    if work < shard_min_work:
        return "lane", dict(info, reason="below_work_floor")
    try:
        decomp = choose_decomposition(spec.shape, n_devices)
    except ValueError:
        return "lane", dict(info, reason="indivisible")
    overhead = decomposition_overhead(spec.shape, decomp)
    info["overhead"] = round(overhead, 6)
    if (1.0 + overhead) >= n_devices:
        return "lane", dict(info, reason="overhead_dominates")
    info["reason"] = "above_work_floor"
    return "sharded", info


class _Staged:
    """One lane batch, staged: host work done, inputs on the device."""

    __slots__ = ("batch", "plan", "inputs", "stage_s", "cap", "waits")

    def __init__(self, batch, plan, inputs, stage_s, cap, waits):
        self.batch = batch
        self.plan = plan
        self.inputs = inputs
        self.stage_s = stage_s
        self.cap = cap
        self.waits = waits


class LaneLease:
    """A reservation of one fleet lane's DEVICE by a non-serving tenant
    (the revolve peer-HBM spill tier).  While held, the lane's stager
    takes no batches — serving jobs and spill tenants never fight for
    the device's memory.  The dispatcher may *revoke* the lease when
    serving demand needs the lane back; the tenant's ``on_revoke``
    callback must then migrate its data off the device (the revolve
    store re-spills peer snapshots to disk) before the lane resumes."""

    def __init__(self, disp: "FleetDispatcher", lane: "Lane", tenant: str,
                 on_revoke: Optional[Callable[["LaneLease", str], None]]
                 = None):
        self.disp = disp
        self.lane = lane
        self.tenant = tenant
        self.on_revoke = on_revoke
        self.revoked = False
        self.released = False

    @property
    def device(self):
        return self.lane.device

    def release(self) -> None:
        self.disp.release_lane(self)


class Lane:
    """One device's serving lane: a staging thread feeding an execute
    thread through a one-slot buffer (the double buffer)."""

    def __init__(self, dispatcher: "FleetDispatcher", index: int, device):
        self.disp = dispatcher
        self.index = index
        self.device = device
        # precomputed so the monitor thread never repr()s a live device
        self.device_str = str(device)
        self.cache = CompiledCache()
        self.evicted = False
        # tenant name while a LaneLease holds this lane, else None
        # (written under the dispatcher lock; the stager polls it)
        self.reserved: Optional[str] = None
        self.batches = 0
        self.jobs_served = 0
        self.busy_s = 0.0
        self.failstreak = 0
        self._current_job_ids: list[int] = []
        # one slot: batch k+1 stages while batch k executes
        self._staged: queue.Queue[Optional[_Staged]] = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._stager: Optional[threading.Thread] = None
        self._exec: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stager = threading.Thread(
            target=self._stage_loop, name=f"tclb-fleet-stage-{self.index}",
            daemon=True)
        self._exec = threading.Thread(
            target=self._exec_loop, name=f"tclb-fleet-exec-{self.index}",
            daemon=True)
        self._stager.start()
        self._exec.start()

    # -- staging thread ----------------------------------------------------- #

    def _stage_loop(self) -> None:
        d = self.disp
        try:
            self._stage_loop_inner()
        except BaseException as e:  # noqa: BLE001 - post-mortem first
            tlive.flight_recorder().dump("stage_loop_exception",
                                         lane=self.index, error=repr(e))
            raise
        finally:
            self._staged.put(None)  # release the execute thread

    def _stage_loop_inner(self) -> None:
        d = self.disp
        while not self.evicted:
            batch = d._take_batch(self)
            if batch is None:
                if d._closing:
                    return
                continue
            if not batch:
                continue
            spec = batch[0].spec
            key = _bin_key(spec)
            cap = d.batch_cap(spec)
            now = time.monotonic()
            waits = [round(now - j.submitted, 6) for j in batch]
            t0 = time.perf_counter()
            try:
                plan = d._plan_for(spec, key)
                with telemetry.span("serve.stage",
                                    device=str(self.device),
                                    lane=self.index, batch=len(batch),
                                    job_ids=[j.id for j in batch]):
                    faults.fire("serve.stage", lane=self.index,
                                batch=len(batch))
                    inputs = jax.device_put(
                        plan.host_stacked_cases(
                            [j.spec.case for j in batch]),
                        self.device)
                    jax.block_until_ready(inputs)
            except Exception as e:  # noqa: BLE001 - per-batch verdict
                for j in batch:
                    j._finish(None, e)
                    d._stream(j)
                continue
            stage_s = time.perf_counter() - t0
            self._staged.put(_Staged(batch, plan, inputs, stage_s,
                                     cap, waits))

    # -- execute thread ----------------------------------------------------- #

    def _exec_loop(self) -> None:
        try:
            self._exec_loop_inner()
        except BaseException as e:  # noqa: BLE001 - post-mortem first
            tlive.flight_recorder().dump("exec_loop_exception",
                                         lane=self.index, error=repr(e))
            raise

    def _exec_loop_inner(self) -> None:
        d = self.disp
        while True:
            t0 = time.perf_counter()
            item = self._staged.get()
            wait_s = time.perf_counter() - t0
            if item is None:
                return
            d._gate.wait()  # a sharded job may hold the whole fleet
            if self.evicted:
                d._redistribute(item.batch)
                continue
            self._idle.clear()
            try:
                self._serve(item, wait_s)
            finally:
                self._idle.set()

    def _serve(self, item: _Staged, wait_s: float) -> None:
        d = self.disp
        batch, plan = item.batch, item.plan
        spec = batch[0].spec
        # stall = the part of the staging latency the execute thread
        # actually waited out; a lane's first fill has nothing to hide
        # under, so the report excludes first=True rows from the overlap
        stall_s = min(wait_s, item.stage_s)
        first = self.batches == 0
        job_ids = [j.id for j in batch]
        for j in batch:
            j.status = RUNNING
        results: Optional[list[EnsembleResult]] = None
        err: Optional[BaseException] = None
        busy_t0 = time.perf_counter()
        telemetry.set_job(job_ids[0] if len(job_ids) == 1 else None)
        with telemetry.span("serve.lane_batch", device=str(self.device),
                            lane=self.index, batch=len(batch),
                            capacity=item.cap, model=spec.model.name,
                            niter=int(spec.niter),
                            engine=plan.engine_tag(len(batch)),
                            stage_s=round(item.stage_s, 6),
                            stall_s=round(stall_s, 6), first=first,
                            wait_s=item.waits, job_ids=job_ids) as sp:
            self._current_job_ids = job_ids
            # the batch deadline is the earliest member's: a retry may
            # never start past the moment any co-batched caller times out
            bd = None
            for j in batch:
                if j.spec.timeout_s is not None:
                    t = j.submitted + j.spec.timeout_s
                    bd = t if bd is None else min(bd, t)
            policy = d.retry_policy
            for attempt in range(policy.max_attempts):
                for j in batch:
                    j.attempts += 1
                try:
                    results = d._batch_runner(
                        self, plan, [j.spec.case for j in batch],
                        spec.niter, item.inputs)
                    break
                except Exception as e:  # noqa: BLE001 - degrade below
                    err = e
                    delay = policy.next_delay(
                        attempt, deadline=bd,
                        key=f"lane{self.index}:{job_ids[0]}")
                    if delay is None:
                        break
                    telemetry.counter("serve.batch.retry")
                    telemetry.event(
                        "serve.batch.retry", lane=self.index,
                        attempt=attempt + 1, delay_s=round(delay, 6),
                        job_ids=job_ids,
                        deadline_in_s=(None if bd is None else
                                       round(bd - time.monotonic(), 6)))
                    log.warning(f"fleet lane {self.index}: batched run "
                                f"failed (attempt {attempt + 1}): {e!r};"
                                f" retrying in {delay:.3f}s")
                    time.sleep(delay)
            self.batches += 1
            if results is not None:
                sp.add(outcome="ok", retries=attempt)
                telemetry.set_job(None)
                self.busy_s += time.perf_counter() - busy_t0
                self.jobs_served += len(batch)
                self.failstreak = 0
                for j, r in zip(batch, results):
                    j._finish(r, None)
                    d._stream(j)
                return
            sp.add(outcome="degraded", error=repr(err))
            telemetry.counter("serve.batch.degraded")
            log.warning(f"fleet lane {self.index}: batched run failed after "
                        f"{attempt + 1} attempt(s) ({err!r}); degrading "
                        f"{len(batch)} job(s) to sequential")
        telemetry.set_job(None)
        any_ok = False
        for j in batch:
            j.degraded = True
            telemetry.event("serve.job_degraded", job_id=j.id,
                            lane=self.index, error=repr(err))
            with telemetry.job_context(j.id):
                try:
                    r = d._seq_runner(self, plan, j.spec.case, spec.niter)
                    j._finish(r, None)
                    any_ok = True
                except Exception as e:  # noqa: BLE001 - per-job verdict
                    j._finish(None, e)
            d._stream(j)
        self.busy_s += time.perf_counter() - busy_t0
        self.jobs_served += len(batch)
        if any_ok:
            self.failstreak = 0
        else:
            self.failstreak += 1
            if self.failstreak >= d.evict_after:
                self._evict(err)

    def _evict(self, cause: Optional[BaseException]) -> None:
        self.evicted = True
        telemetry.event("serve.device_evicted", device=str(self.device),
                        lane=self.index, failstreak=self.failstreak,
                        cause=repr(cause))
        telemetry.counter("serve.device_evicted")
        log.warning(f"fleet: evicting lane {self.index} ({self.device}) "
                    f"after {self.failstreak} consecutive failed batches: "
                    f"{cause!r}")
        self.disp._lane_evicted(self)


class FleetDispatcher:
    """Device-aware dispatcher: N lanes over N devices + a sharded rail.

    Drop-in surface of :class:`Scheduler` (``submit``/``run``/``close``,
    same :class:`Job` handles, same retry/degrade ladder) plus routing:
    jobs above the work floor with a worthwhile decomposition run on the
    all-device sharded engine, everything else bins onto per-device
    ensemble lanes.  ``batch_runner`` / ``sequential_runner`` are
    injectable for fault testing with lane-aware signatures
    ``(lane, plan, cases, niter, staged_inputs) -> [EnsembleResult]``
    and ``(lane, plan, case, niter) -> EnsembleResult``."""

    def __init__(self, devices: Optional[Sequence] = None,
                 max_batch: Optional[int] = None, retries: int = 1,
                 evict_after: int = 2,
                 shard_min_work: Optional[int] = None,
                 batch_runner: Optional[Callable] = None,
                 sequential_runner: Optional[Callable] = None,
                 on_result: Optional[Callable[[Job], None]] = None,
                 autostart: bool = True,
                 monitor: Optional[str] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 probe_interval_s: Optional[float] = None,
                 probe_runner: Optional[Callable] = None,
                 process_isolation: bool = False,
                 pool: Optional[Any] = None):
        self.devices = list(devices) if devices is not None \
            else list(jax.devices())
        # process isolation: one supervised worker SUBPROCESS per lane
        # instead of in-process device lanes — a wedged device or a
        # native crash kills one child, not the dispatcher.  Jobs cross
        # as plain JSON (pool_doc_from_spec); results come back as
        # host-side dicts (globals + sha256 digest), not live device
        # arrays, so plan/grad specs must use the in-process lanes.
        self._pool = None
        if process_isolation or pool is not None:
            from tclb_tpu.serve.pool import WorkerPool
            self._pool = pool if pool is not None else WorkerPool(
                workers=max(1, len(self.devices)),
                retry_policy=retry_policy, autostart=False)
        self.max_batch = max_batch
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy.from_retries(retries)
        self.retries = self.retry_policy.retries
        self.evict_after = max(1, int(evict_after))
        # lane probation: when set, an evicted lane is re-probed every
        # `probe_interval_s` seconds with a canary and reinstated on
        # success.  Opt-in (constructor or TCLB_FLEET_PROBE_S) — the
        # default fleet keeps permanent eviction and its all-evicted
        # fast-fail contract.
        if probe_interval_s is None:
            env = os.environ.get("TCLB_FLEET_PROBE_S")
            probe_interval_s = float(env) if env else None
        self.probe_interval_s = probe_interval_s
        self._probe_runner = probe_runner or self._default_probe
        # how long a reinstatement waits for the evicted lane's old
        # threads to finish dying before deferring to the next probe
        self.reinstate_join_s = 10.0
        self._probe_threads: list[threading.Thread] = []
        self._stop_probes = threading.Event()
        self.shard_min_work = shard_min_work
        self.autostart = autostart
        self._batch_runner = batch_runner or self._run_batched
        self._seq_runner = sequential_runner or (
            lambda lane, plan, case, niter:
            plan.run_sequential(case, niter, device=lane.device))
        self._on_result = on_result
        self.lanes = [Lane(self, i, dev)
                      for i, dev in enumerate(self.devices)]
        self._queue: queue.Queue[Job] = queue.Queue()
        self._sharded: queue.Queue[Job] = queue.Queue()
        self._gate = threading.Event()
        self._gate.set()
        self._plans: dict[tuple, EnsemblePlan] = {}
        self._plan_lock = locks.make_lock("serve.dispatcher.FleetDispatcher._plan_lock")
        self._jobs = 0
        self._leases: list[LaneLease] = []
        self._lock = locks.make_lock("serve.dispatcher.FleetDispatcher._lock")
        self._inflight: dict[int, Job] = {}
        self._closing = False
        self._started = False
        self._shard_worker: Optional[threading.Thread] = None
        self._t0 = time.monotonic()
        self._monitor_spec = monitor
        self._monitor = None
        # flight recorder on by default inside serve/: a crashed fleet
        # yields a post-mortem ring dump even without a trace
        self._flight_attached = True
        tlive.flight_recorder().attach()
        tlive.register_status("fleet", self._status)

    # -- admission ---------------------------------------------------------- #

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
        if self._monitor_spec is not None and self._monitor is None:
            from tclb_tpu.telemetry.http import MonitorServer
            self._monitor = MonitorServer.from_spec(
                self._monitor_spec).start()
            log.notice(f"fleet: monitor at {self._monitor.url}/status")
        if self._pool is not None:
            # process isolation: worker subprocesses ARE the lanes; the
            # parent never starts in-process device threads
            self._pool.start()
            return
        for lane in self.lanes:
            lane.start()
        self._shard_worker = threading.Thread(
            target=self._sharded_loop, name="tclb-fleet-sharded", daemon=True)
        self._shard_worker.start()

    @property
    def monitor_url(self) -> Optional[str]:
        """Base URL of the live monitor, or None when not enabled."""
        return self._monitor.url if self._monitor is not None else None

    def _status(self) -> dict:
        """Plain-python /status fragment: per-lane occupancy, queue
        depths, inflight job ages, evicted devices.  Reads only
        thread-safe python state — monitor-thread safe by construction
        (and enforced by hygiene.device_work_in_monitor)."""
        now = time.monotonic()
        wall = max(now - self._t0, 1e-9)
        with self._lock:
            inflight = [{"job_id": j.id, "name": j.spec.name,
                         "status": j.status,
                         "age_s": round(now - j.submitted, 3)}
                        for j in list(self._inflight.values())[:64]]
        return {
            "queue_depth": self._queue.qsize(),
            "sharded_queue_depth": self._sharded.qsize(),
            "jobs_submitted": self._jobs,
            "inflight": inflight,
            "lanes": [{"lane": l.index, "device": l.device_str,
                       "batches": l.batches, "jobs": l.jobs_served,
                       "busy_s": round(l.busy_s, 6),
                       "occupancy_pct": round(100.0 * l.busy_s / wall, 2),
                       "failstreak": l.failstreak,
                       "evicted": l.evicted,
                       "reserved": l.reserved} for l in self.lanes],
            "reserved_lanes": sum(1 for l in self.lanes
                                  if l.reserved is not None),
            "evicted_devices": [l.device_str for l in self.lanes
                                if l.evicted],
            "uptime_s": round(wall, 3),
            "closing": self._closing,
        }

    def submit(self, spec: JobSpec, lane: Optional[int] = None) -> Job:
        """Route + enqueue one job; ``lane`` pins it to a specific lane
        (parity tests / targeted draining)."""
        if self._closing:
            raise RuntimeError("dispatcher is closed")
        if self._pool is not None:
            return self._submit_pooled(spec)
        with self._lock:
            self._jobs += 1
            job = Job(spec, self._jobs)
            self._inflight[job.id] = job
        telemetry.counter("serve.jobs.submitted")
        if lane is not None:
            job.pin = int(lane)
            route, info = "lane", {"reason": "pinned"}
        else:
            route, info = route_job(spec, len(self.devices),
                                    self.shard_min_work)
        telemetry.event("serve.job_queued", job_id=job.id,
                        name=spec.name, model=spec.model.name,
                        shape=list(spec.shape), niter=int(spec.niter),
                        route=route, reason=info.get("reason"))
        if route == "sharded":
            telemetry.event("serve.route_sharded", job=job.id,
                            job_id=job.id, model=spec.model.name,
                            shape=list(spec.shape), niter=int(spec.niter),
                            **info)
            telemetry.counter("serve.route_sharded")
            self._sharded.put(job)
        else:
            telemetry.counter("serve.route_lane")
            if all(l.evicted for l in self.lanes) \
                    and self.probe_interval_s is None:
                # no probation: the fleet is permanently dead, fail fast
                job._finish(None, RuntimeError(
                    "fleet: all lanes evicted; no device can serve the job"))
                self._stream(job)
            else:
                self._queue.put(job)
        if self.autostart:
            self.start()
        return job

    def _submit_pooled(self, spec: JobSpec) -> Job:
        """Route one job through the process-isolated pool: the spec
        crosses as plain JSON, the result comes back as a host-side
        :class:`~tclb_tpu.serve.pool.PoolResult`.  Anything speaking
        the pool protocol slots in via the ``pool=`` constructor arg —
        a local :class:`WorkerPool` or a whole pod behind a
        :class:`~tclb_tpu.cluster.server.ClusterServer` (the result
        then carries its serving ``host``)."""
        from tclb_tpu.serve.pool import PoolResult, pool_doc_from_spec
        doc = pool_doc_from_spec(spec)   # rejects plan/grad specs early
        with self._lock:
            self._jobs += 1
            job = Job(spec, self._jobs)
            self._inflight[job.id] = job
        telemetry.counter("serve.jobs.submitted")
        telemetry.event("serve.job_queued", job_id=job.id,
                        name=spec.name, model=spec.model.name,
                        shape=list(spec.shape), niter=int(spec.niter),
                        route="pool", reason="process_isolation")

        def _done(pj) -> None:
            job.attempts = pj.attempts
            if pj.error is None:
                job._finish(PoolResult(spec.case, pj._result), None)
            else:
                job._finish(None, pj.error)
            self._stream(job)

        self._pool.submit(doc, on_done=_done)
        if self.autostart:
            self.start()
        return job

    def run(self, specs: Sequence[JobSpec]) -> list[Job]:
        """Submit all, wait for all; failed jobs keep their error on the
        handle instead of raising."""
        jobs = [self.submit(s) for s in specs]
        self.start()
        for j in jobs:
            try:
                j.result()
            except Exception:  # noqa: BLE001 - surfaced on the handle
                pass
        return jobs

    def close(self, wait: bool = True, join_timeout: float = 60.0) -> None:
        self._closing = True
        self._stop_probes.set()
        if self._pool is not None:
            # finishes or fails every pool job first, so the pending
            # sweep below only sees what the pool could not deliver
            self._pool.close(wait=wait)
        if wait and self._started:
            deadline = time.monotonic() + join_timeout
            for t in self._probe_threads:
                t.join(timeout=1.0)
            if self._shard_worker is not None:
                # first: it may degrade a failed sharded job back onto
                # the lane queue, which the stagers must still drain
                self._shard_worker.join(
                    timeout=max(0.1, deadline - time.monotonic()))
            for lane in self.lanes:
                if lane._stager is not None:
                    lane._stager.join(
                        timeout=max(0.1, deadline - time.monotonic()))
                if lane._exec is not None:
                    lane._exec.join(
                        timeout=max(0.1, deadline - time.monotonic()))
        # same close/timeout contract as Scheduler.close: anything still
        # unfinished surfaces as failed-not-hung
        now = time.monotonic()
        with self._lock:
            pending = [j for j in self._inflight.values()
                       if not j._done.is_set()]
            self._inflight.clear()
        for job in pending:
            t = job.spec.timeout_s
            if t is not None and now >= job.submitted + t:
                job._finish(None, JobTimeout(
                    f"job {job.id} timed out during close "
                    f"(waited {now - job.submitted:.2f}s)"))
                telemetry.counter("serve.jobs.timeout")
            else:
                job._finish(None, RuntimeError(
                    f"job {job.id}: dispatcher closed before it finished"))
        telemetry.event("span", name="serve.fleet",
                        dur_s=round(now - self._t0, 6),
                        lanes=len(self.lanes), jobs=self._jobs,
                        evicted=sum(1 for l in self.lanes if l.evicted))
        tlive.unregister_status("fleet", self._status)
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None
        if self._flight_attached:
            self._flight_attached = False
            tlive.flight_recorder().detach()

    def __enter__(self) -> "FleetDispatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- binning ------------------------------------------------------------ #

    def batch_cap(self, spec: JobSpec) -> int:
        sdt = spec.storage_dtype if spec.storage_dtype is not None \
            else spec.dtype
        cap = fusion.ensemble_batch_cap(
            spec.model.n_storage, tuple(spec.shape),
            jnp.dtype(sdt).itemsize)
        if self.max_batch is not None:
            cap = min(cap, int(self.max_batch))
        return max(1, cap)

    def _plan_for(self, spec: JobSpec, key: tuple) -> EnsemblePlan:
        with self._plan_lock:
            plan = self._plans.get(key)
            if plan is None:
                plan = spec.plan if spec.plan is not None else EnsemblePlan(
                    spec.model, spec.shape, flags=spec.flags,
                    dtype=spec.dtype, base_settings=spec.base_settings,
                    storage_dtype=spec.storage_dtype, grad=spec.grad)
                self._plans[key] = plan
            return plan

    def _take_batch(self, lane: Lane) -> Optional[list[Job]]:
        """One compatible batch for ``lane`` off the shared queue.  The
        cap is the memory predicate AND a fair share of the visible
        burst, so 16 queued jobs land one-batch-per-device instead of
        one lane swallowing them all."""
        if lane.reserved is not None:
            # a spill tenant holds the device; don't pull work the lane
            # cannot run — the queue stays for the unreserved lanes
            time.sleep(0.05)
            return None
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return None
        if getattr(first, "pin", None) not in (None, lane.index):
            self._queue.put(first)
            return []
        now = time.monotonic()
        t = first.spec.timeout_s
        if t is not None and now > first.submitted + t:
            first._finish(None, JobTimeout(
                f"job {first.id} expired in queue "
                f"(waited {now - first.submitted:.2f}s)"))
            telemetry.counter("serve.jobs.timeout")
            self._stream(first)
            return []
        key = _bin_key(first.spec)
        active = max(1, sum(1 for l in self.lanes
                            if not l.evicted and l.reserved is None))
        fair = -(-(self._queue.qsize() + 1) // active)  # ceil
        cap = max(1, min(self.batch_cap(first.spec), fair))
        batch, requeue = [first], []
        while len(batch) < cap:
            try:
                j = self._queue.get_nowait()
            except queue.Empty:
                break
            if getattr(j, "pin", None) not in (None, lane.index) \
                    or _bin_key(j.spec) != key:
                requeue.append(j)
            else:
                batch.append(j)
        for j in requeue:
            self._queue.put(j)
        return batch

    # -- lane runners ------------------------------------------------------- #

    def _run_batched(self, lane: Lane, plan: EnsemblePlan,
                     cases: Sequence[Case], niter: int,
                     inputs: tuple) -> list[EnsembleResult]:
        faults.fire("serve.lane_dispatch", rail="lane", lane=lane.index,
                    batch=len(cases))
        compiled = lane.cache.get(plan, batch=len(cases), niter=int(niter),
                                  fn=plan.build_fn(init=True), init=True,
                                  device=lane.device)
        out = compiled(*inputs)
        # kick off the D2H copies while the lane stages its next batch;
        # results_from's np.asarray then finds the bytes already landing
        try:
            jax.tree.map(lambda x: x.copy_to_host_async(), out)
        except Exception:  # noqa: BLE001 - an optimization, never a verdict
            pass
        with telemetry.span("serve.d2h", lane=lane.index,
                            batch=len(cases),
                            job_ids=list(lane._current_job_ids)):
            return plan.results_from(cases, out)

    # -- sharded rail ------------------------------------------------------- #

    def _sharded_loop(self) -> None:
        try:
            self._sharded_loop_inner()
        except BaseException as e:  # noqa: BLE001 - post-mortem first
            tlive.flight_recorder().dump("sharded_loop_exception",
                                         error=repr(e))
            raise

    def _sharded_loop_inner(self) -> None:
        while True:
            try:
                job = self._sharded.get(timeout=0.1)
            except queue.Empty:
                if self._closing:
                    return
                continue
            now = time.monotonic()
            t = job.spec.timeout_s
            if t is not None and now > job.submitted + t:
                job._finish(None, JobTimeout(
                    f"job {job.id} expired in queue "
                    f"(waited {now - job.submitted:.2f}s)"))
                telemetry.counter("serve.jobs.timeout")
                self._stream(job)
                continue
            # coalesce: hold the lanes between batches, wait for in-
            # flight batches to finish, then take the whole fleet
            self._gate.clear()
            try:
                for lane in self.lanes:
                    lane._idle.wait(timeout=120.0)
                job.status = RUNNING
                job.attempts += 1
                spec = job.spec
                with telemetry.job_context(job.id), \
                        telemetry.span("serve.sharded_job",
                                       model=spec.model.name,
                                       shape=list(spec.shape),
                                       niter=int(spec.niter),
                                       devices=len(self.devices),
                                       job_id=job.id) as sp:
                    result = self._run_sharded(spec)
                    sp.add(outcome="ok")
                job._finish(result, None)
                self._stream(job)
            except Exception as e:  # noqa: BLE001 - ladder below
                if not job.degraded:
                    # next rung of the ladder: one lane instead of the
                    # whole fleet
                    job.degraded = True
                    telemetry.event("serve.job_degraded", job_id=job.id,
                                    rail="sharded", error=repr(e))
                    telemetry.counter("serve.sharded.degraded")
                    log.warning(f"fleet: sharded job {job.id} failed "
                                f"({e!r}); degrading to a single lane")
                    self._queue.put(job)
                else:
                    job._finish(None, e)
                    self._stream(job)
            finally:
                self._gate.set()

    def _run_sharded(self, spec: JobSpec) -> EnsembleResult:
        mesh = make_mesh(spec.shape, devices=self.devices)
        lat = Lattice(spec.model, spec.shape, dtype=spec.dtype,
                      settings=spec.base_settings, mesh=mesh)
        if spec.flags is not None:
            lat.set_flags(np.asarray(spec.flags, dtype=np.uint16))
        for name, value in spec.case.settings.items():
            lat.set_setting(name, float(value))
        for (name, zone), value in spec.case.zonal.items():
            lat.set_setting(name, float(value), zone=int(zone))
        lat.init()
        if spec.niter > 0:
            lat.iterate(spec.niter)
        return EnsembleResult(case=spec.case, state=lat.state,
                              globals=lat.get_globals())

    # -- lane reservation (spill tenants) ------------------------------------ #

    def reserve_lane(self, tenant: str = "adjoint",
                     on_revoke: Optional[Callable] = None
                     ) -> Optional[LaneLease]:
        """Lease one idle lane's device to a non-serving tenant (the
        revolve peer-HBM spill tier), or None when no lane can be
        spared.  At least one healthy lane always stays unreserved so
        serving never starves; evicted lanes are never leased (their
        device already failed).  The lease is revocable: serving demand
        may reclaim the lane via :meth:`revoke_lease`, after the
        tenant's ``on_revoke`` migrated its data off the device."""
        with self._lock:
            free = [l for l in self.lanes
                    if not l.evicted and l.reserved is None]
            if len(free) < 2:
                return None   # keep the last healthy lane serving
            # prefer an idle lane: leasing mid-batch would co-host the
            # tenant's buffers with a running batch's working set
            lane = next((l for l in free if l._idle.is_set()), free[0])
            lane.reserved = tenant
            lease = LaneLease(self, lane, tenant, on_revoke)
            self._leases.append(lease)
        telemetry.counter("serve.lane_reserved")
        telemetry.event("serve.lane_reserved", lane=lane.index,
                        device=lane.device_str, tenant=tenant)
        return lease

    def release_lane(self, lease: LaneLease) -> None:
        """Return a leased lane to serving (idempotent)."""
        with self._lock:
            if lease.released:
                return
            lease.released = True
            if lease in self._leases:
                self._leases.remove(lease)
            lease.lane.reserved = None
        telemetry.counter("serve.lane_released")
        telemetry.event("serve.lane_released", lane=lease.lane.index,
                        device=lease.lane.device_str, tenant=lease.tenant)

    def revoke_lease(self, lease: LaneLease, reason: str = "demand") -> None:
        """Reclaim a leased lane for serving: notify the tenant (which
        must migrate its device-resident data — the revolve store
        re-spills peer snapshots to disk), then release the lane.  The
        callback runs OUTSIDE the dispatcher lock: it does device work
        (D2H fetches + disk writes)."""
        with self._lock:
            if lease.released or lease.revoked:
                return
            lease.revoked = True
        telemetry.counter("serve.lane_revoked")
        telemetry.event("serve.lane_revoked", lane=lease.lane.index,
                        device=lease.lane.device_str, tenant=lease.tenant,
                        reason=reason)
        if lease.on_revoke is not None:
            try:
                lease.on_revoke(lease, reason)
            except Exception as e:  # noqa: BLE001 - reclaim regardless
                log.warning(f"fleet: lease revoke callback failed "
                            f"({lease.tenant}): {e!r}")
        self.release_lane(lease)

    # -- eviction / bookkeeping --------------------------------------------- #

    def _redistribute(self, batch: Sequence[Job]) -> None:
        """Hand an evicted lane's staged-but-unexecuted jobs back to the
        shared queue for the surviving lanes.  With no survivor left the
        jobs fail here — re-queueing after the all-evicted drain would
        strand them (nobody polls a dead fleet's queue) — unless lane
        probation is on, in which case they wait for a reinstatement."""
        if all(l.evicted for l in self.lanes) \
                and self.probe_interval_s is None:
            for j in batch:
                if not j._done.is_set():
                    j._finish(None, RuntimeError(
                        "fleet: all lanes evicted; no device can serve "
                        "the job"))
                    self._stream(j)
            return
        for j in batch:
            j.status = PENDING
            if getattr(j, "pin", None) is not None:
                j.pin = None  # its lane is gone; any survivor may serve
            self._queue.put(j)
        telemetry.counter("serve.jobs.redistributed", inc=len(batch))

    def _lane_evicted(self, lane: Lane) -> None:
        if self.probe_interval_s is not None and not self._closing:
            t = threading.Thread(target=self._probe_loop, args=(lane,),
                                 name=f"tclb-fleet-probe-{lane.index}",
                                 daemon=True)
            self._probe_threads.append(t)
            t.start()
            return  # probation: queued jobs wait for a reinstatement
        if all(l.evicted for l in self.lanes):
            log.warning("fleet: ALL lanes evicted; failing queued jobs")
            while True:
                try:
                    j = self._queue.get_nowait()
                except queue.Empty:
                    return
                if not j._done.is_set():
                    j._finish(None, RuntimeError(
                        "fleet: all lanes evicted; no device can serve "
                        "the job"))
                    self._stream(j)

    # -- lane probation ------------------------------------------------------ #

    def _default_probe(self, lane: Lane) -> None:
        """Canary: land a tiny buffer on the lane device and fence it.
        Raises when the device is still unhealthy."""
        jax.block_until_ready(
            jax.device_put(np.zeros(8, np.float32), lane.device))

    def _probe_loop(self, lane: Lane) -> None:
        interval = self.probe_interval_s
        while not self._closing and lane.evicted:
            if self._stop_probes.wait(interval):
                return
            if self._closing or not lane.evicted:
                return
            try:
                self._probe_runner(lane)
            except Exception as e:  # noqa: BLE001 - still unhealthy
                telemetry.event("serve.device_probe_failed",
                                lane=lane.index, device=lane.device_str,
                                error=repr(e))
                continue
            if self._reinstate(lane):
                return
            # old threads still alive: keep the lane on probation and
            # retry the whole probe/reinstate cycle next interval

    def _reinstate(self, lane: Lane) -> bool:
        """Rejoin a probed-healthy lane: restart its stage/exec threads
        (both exited on eviction) and let it pull from the shared queue
        again — redistribution back happens by construction.  Returns
        False (lane stays evicted) when an old thread outlives the join
        timeout: starting duplicates would let the fresh exec thread
        consume the old stager's trailing None sentinel and exit
        immediately, leaving staged batches nobody executes."""
        # the old threads exited on eviction (stage loop breaks, its
        # final None sentinel makes exec return); join them and drain
        # the sentinel so the fresh exec thread doesn't eat it
        me = threading.current_thread()
        for t in (lane._stager, lane._exec):
            if t is not None and t is not me:
                t.join(timeout=self.reinstate_join_s)
                if t.is_alive():
                    telemetry.event("serve.device_reinstate_deferred",
                                    lane=lane.index,
                                    device=lane.device_str,
                                    thread=t.name)
                    log.warning(f"fleet: lane {lane.index} thread "
                                f"{t.name} still alive after "
                                f"{self.reinstate_join_s}s; deferring "
                                "reinstatement to the next probe cycle")
                    return False
        while True:
            try:
                item = lane._staged.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._redistribute(item.batch)
        lane.failstreak = 0
        lane.evicted = False
        # emit BEFORE start(): once the lane threads run, a parked job can
        # complete and its observer must already see the reinstatement
        telemetry.event("serve.device_reinstated", device=lane.device_str,
                        lane=lane.index)
        telemetry.counter("serve.device_reinstated")
        lane.start()
        log.warning(f"fleet: lane {lane.index} ({lane.device_str}) "
                    "probed healthy; reinstated")
        return True

    def _stream(self, job: Job) -> None:
        self._inflight.pop(job.id, None)
        telemetry.counter("serve.jobs.done" if job.status == DONE
                          else "serve.jobs.failed")
        telemetry.event(
            "serve.job_done", job_id=job.id, status=job.status,
            attempts=job.attempts, degraded=job.degraded,
            wall_s=(None if job.finished_at is None else
                    round(job.finished_at - job.submitted, 6)))
        if self._on_result is not None:
            try:
                self._on_result(job)
            except Exception as e:  # noqa: BLE001 - callback is advisory
                log.warning(f"fleet: on_result callback failed: {e!r}")

    def stats(self) -> dict[str, Any]:
        """Per-lane counters for smoke checks and the sweep CLI."""
        return {
            "devices": [str(d) for d in self.devices],
            "lanes": [{"lane": l.index, "device": str(l.device),
                       "batches": l.batches, "evicted": l.evicted,
                       "reserved": l.reserved,
                       "cache": l.cache.stats()} for l in self.lanes],
            "jobs": self._jobs,
        }
