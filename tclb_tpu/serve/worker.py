"""Solver worker subprocess: one process per pool lane.

``python -m tclb_tpu.serve.worker --lane N`` is the child half of
:class:`~tclb_tpu.serve.pool.WorkerPool` — the process-isolation unit
that mirrors the reference TCLB's MPI rank: a wedged device, a hung XLA
compile, or a native crash kills *this* process, and the supervisor in
the parent restarts it without taking down sibling lanes or the serving
front door.

IPC protocol (length-prefixed pipes, stdin/stdout):

* every frame is an 8-byte ``!II`` header (JSON length, payload length)
  followed by a UTF-8 JSON document and an optional raw binary payload
  (``.npy`` bytes for array data) — **never** pickled device arrays, so
  a malicious or corrupt peer can at worst feed bad numbers, not code;
* parent -> worker: ``{"t": "job", "id": ..., "spec": {...}}`` and
  ``{"t": "shutdown"}``;
* worker -> parent: ``{"t": "ready"}`` once importable, ``{"t": "hb"}``
  heartbeats *during* execution (progress-based: one per solve chunk, so
  a wedged device stops the beat), and ``{"t": "result"}`` with globals,
  an optional ``state_sha256`` digest, and an optional ``.npy`` payload
  of the final fields.

Resumable jobs (``spec["ckpt_root"]``) save through
:class:`~tclb_tpu.checkpoint.manager.CheckpointManager` at deterministic
absolute segment boundaries and re-enter via ``latest()`` on restart, so
a SIGKILLed worker's job finishes bit-identical to an uninterrupted run.

Fault points fired *inside* the worker (the plan crosses the process
boundary via ``TCLB_FAULTS``, re-serialized by the pool at spawn):
``pool.heartbeat`` (``error`` wedges the worker mid-solve — the missed
heartbeat the supervisor must catch; ``slow`` delays the beat) and
``pool.worker_exit`` (``error`` hard-exits the process at a job start or
segment boundary — the crash the supervisor must absorb).

The worker claims the real stdout fd for frames at startup and rebinds
``sys.stdout``/fd 1 to stderr, so a stray ``print`` (or a chatty
library) can never corrupt the frame stream.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import struct
import sys
import time
from typing import Any, BinaryIO, Optional

_HEADER = struct.Struct("!II")

#: refuse absurd frames instead of allocating unbounded buffers
MAX_FRAME = 1 << 30


class IpcError(RuntimeError):
    """A torn or malformed frame on the worker pipe."""


def write_frame(fh: BinaryIO, doc: dict, payload: bytes = b"") -> None:
    """Write one length-prefixed frame: JSON doc + raw payload bytes."""
    from tclb_tpu.telemetry import events
    body = json.dumps(doc, default=events._json_default).encode()
    fh.write(_HEADER.pack(len(body), len(payload)))
    fh.write(body)
    if payload:
        fh.write(payload)
    fh.flush()


def _read_exact(fh: BinaryIO, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = fh.read(n)
        if not chunk:
            raise IpcError(f"pipe closed mid-frame ({n} bytes short)")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def read_frame(fh: BinaryIO) -> tuple[dict, bytes]:
    """Read one frame; EOFError on a clean close at a frame boundary,
    :class:`IpcError` on a torn or malformed one."""
    header = fh.read(_HEADER.size)
    if not header:
        raise EOFError("pipe closed")
    if len(header) < _HEADER.size:
        header += _read_exact(fh, _HEADER.size - len(header))
    body_len, payload_len = _HEADER.unpack(header)
    if body_len > MAX_FRAME or payload_len > MAX_FRAME:
        raise IpcError(f"oversized frame ({body_len}+{payload_len} bytes)")
    try:
        doc = json.loads(_read_exact(fh, body_len).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise IpcError(f"malformed frame body: {e}") from e
    payload = _read_exact(fh, payload_len) if payload_len else b""
    if not isinstance(doc, dict):
        raise IpcError("frame body must be a JSON object")
    return doc, payload


def npy_bytes(arr) -> bytes:
    """Serialize a host array as ``.npy`` bytes (the only array wire
    format — plain data, never pickles)."""
    import numpy as np
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(np.asarray(arr)),
            allow_pickle=False)
    return buf.getvalue()


def npy_load(payload: bytes):
    import numpy as np
    return np.load(io.BytesIO(payload), allow_pickle=False)


# --------------------------------------------------------------------------- #
# Solve execution (the only jax-touching half; imports stay lazy so the
# protocol helpers above are importable from the device-free supervisor)
# --------------------------------------------------------------------------- #


def _solve(spec: dict, jid: str, lane: int, beat) -> tuple[dict, bytes]:
    """Run one solve job from a plain-JSON spec; returns the result doc
    + optional ``.npy`` payload of the final fields."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tclb_tpu import faults
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    model = get_model(spec["model"])
    shape = tuple(int(s) for s in spec["shape"])
    precision = spec.get("dtype", "f32")
    if precision == "f64":
        jax.config.update("jax_enable_x64", True)
    dtype = jnp.float64 if precision == "f64" else jnp.float32
    sdt = {"bf16": jnp.bfloat16, "f32": jnp.float32,
           "f64": jnp.float64}.get(spec.get("storage_dtype"))
    settings = dict(spec.get("params") or {})
    settings.update((spec.get("case") or {}).get("settings") or {})
    niter = int(spec["niter"])

    lat = Lattice(model, shape, dtype=dtype, storage_dtype=sdt,
                  storage_repr=spec.get("storage_repr"),
                  settings=settings or None)
    mgr = None
    resumed_from: Optional[int] = None
    start = 0
    ckpt_root = spec.get("ckpt_root")
    if ckpt_root:
        from tclb_tpu.checkpoint.manager import CheckpointManager
        mgr = CheckpointManager(ckpt_root,
                                keep_last=int(spec.get("checkpoint_keep")
                                              or 2))
        newest = mgr.latest()
        if newest is not None:
            mgr.restore(lat, newest)
            start = int(np.asarray(lat.state.iteration))
            resumed_from = start
        else:
            lat.init()
    else:
        lat.init()
    beat(phase="built", iter=start)

    every = int(spec.get("checkpoint_every") or 0) if mgr else 0
    hb_every = int(spec.get("hb_iters") or 0) or every \
        or max(1, niter // 8)
    done = start
    while done < niter:
        # chunk boundaries are ABSOLUTE multiples of the cadence, so a
        # resumed run (which starts at a checkpoint step) replays the
        # exact boundary sequence of an uninterrupted one — the
        # bit-identity contract
        nxt = min(niter, (done // hb_every + 1) * hb_every)
        if every:
            nxt = min(nxt, (done // every + 1) * every)
        lat.iterate(nxt - done)
        done = nxt
        if mgr and every and (done % every == 0 or done == niter):
            mgr.save(lat, step=done)
            try:
                faults.fire("pool.worker_exit", lane=lane, job=jid,
                            at="segment", step=done)
            except faults.InjectedFault:
                mgr.wait()
                os._exit(17)
        beat(iter=done)
    if mgr:
        mgr.wait()

    doc: dict[str, Any] = {"globals": lat.get_globals(),
                           "iteration": done,
                           "resumed_from": resumed_from,
                           "lane": lane, "pid": os.getpid()}
    if spec.get("digest"):
        import hashlib
        arr = np.ascontiguousarray(np.asarray(lat.state.fields))
        doc["state_sha256"] = hashlib.sha256(arr.tobytes()).hexdigest()
    payload = b""
    if spec.get("return_state"):
        payload = npy_bytes(lat.state.fields)
    return doc, payload


def _run_job(out: BinaryIO, lane: int, doc: dict) -> None:
    from tclb_tpu import faults
    jid = str(doc.get("id"))
    spec = doc.get("spec") or {}

    def beat(**kw) -> None:
        try:
            faults.fire("pool.heartbeat", lane=lane, job=jid)
        except faults.InjectedFault:
            # a wedged worker: stop beating without exiting — the
            # supervisor's missed-heartbeat watchdog must catch this
            time.sleep(3600.0)
        write_frame(out, {"t": "hb", "id": jid, **kw})

    try:
        try:
            faults.fire("pool.worker_exit", lane=lane, job=jid,
                        at="start")
        except faults.InjectedFault:
            out.flush()
            os._exit(17)
        beat(phase="accepted")
        result, payload = _solve(spec, jid, lane, beat)
        write_frame(out, dict({"t": "result", "id": jid, "ok": True},
                              **result), payload)
    except BaseException as e:  # noqa: BLE001 — per-job verdict: a bad
        # spec fails the job, not the worker
        write_frame(out, {"t": "result", "id": jid, "ok": False,
                          "error": repr(e),
                          "error_kind": type(e).__name__})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tclb-worker",
        description="pool solver worker (speaks the WorkerPool frame "
                    "protocol on stdin/stdout; not for interactive use)")
    ap.add_argument("--lane", type=int, default=0,
                    help="pool lane index this worker serves")
    args = ap.parse_args(argv)

    # claim the frame channel, then point fd 1 (and sys.stdout) at
    # stderr so no library print can corrupt the protocol stream
    out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    inp = os.fdopen(os.dup(0), "rb")

    from tclb_tpu.telemetry import live as tlive

    # a crashing worker leaves its own flight-<pid>.jsonl post-mortem
    tlive.flight_recorder().attach()
    write_frame(out, {"t": "ready", "pid": os.getpid(),
                      "lane": args.lane})
    while True:
        try:
            doc, _payload = read_frame(inp)
        except (EOFError, IpcError):
            return 0
        t = doc.get("t")
        if t == "shutdown":
            return 0
        if t == "job":
            _run_job(out, args.lane, doc)


if __name__ == "__main__":
    sys.exit(main())
