"""Solver worker subprocess: one process per pool lane.

``python -m tclb_tpu.serve.worker --lane N`` is the child half of
:class:`~tclb_tpu.serve.pool.WorkerPool` — the process-isolation unit
that mirrors the reference TCLB's MPI rank: a wedged device, a hung XLA
compile, or a native crash kills *this* process, and the supervisor in
the parent restarts it without taking down sibling lanes or the serving
front door.

IPC protocol (length-prefixed pipes, stdin/stdout):

* every frame is an 8-byte ``!II`` header (JSON length, payload length)
  followed by a UTF-8 JSON document and an optional raw binary payload
  (``.npy`` bytes for array data) — **never** pickled device arrays, so
  a malicious or corrupt peer can at worst feed bad numbers, not code;
* parent -> worker: ``{"t": "job", "id": ..., "spec": {...}}`` and
  ``{"t": "shutdown"}``;
* worker -> parent: ``{"t": "ready"}`` once importable, ``{"t": "hb"}``
  heartbeats *during* execution (progress-based: one per solve chunk, so
  a wedged device stops the beat), optional ``{"t": "telemetry"}``
  frames (batched event docs relayed to the parent fan-out — only when
  the supervisor requested relay via ``TCLB_POOL_RELAY=1``), optional
  ``{"t": "progress"}`` frames (iteration / MLUPS / wall and opt-in
  downsampled quantity reductions, when the spec asks for them), and
  ``{"t": "result"}`` with globals, per-phase wall times, an optional
  ``state_sha256`` digest, and an optional ``.npy`` payload of the
  final fields.

Telemetry relay discipline: the relay sink is a bounded queue
(:data:`RELAY_QUEUE_CAP`; overflow is dropped and counted), flushed only
*between* solve chunks right after the heartbeat — never mid-kernel, and
never before the beat, so a wedged relay (its own chaos point,
``pool.telemetry_relay``) can delay telemetry but not liveness.  When
the supervisor does not request relay, no queue, subscriber, or clock
read exists at all.

Resumable jobs (``spec["ckpt_root"]``) save through
:class:`~tclb_tpu.checkpoint.manager.CheckpointManager` at deterministic
absolute segment boundaries and re-enter via ``latest()`` on restart, so
a SIGKILLed worker's job finishes bit-identical to an uninterrupted run.

Fault points fired *inside* the worker (the plan crosses the process
boundary via ``TCLB_FAULTS``, re-serialized by the pool at spawn):
``pool.heartbeat`` (``error`` wedges the worker mid-solve — the missed
heartbeat the supervisor must catch; ``slow`` delays the beat) and
``pool.worker_exit`` (``error`` hard-exits the process at a job start or
segment boundary — the crash the supervisor must absorb).

The worker claims the real stdout fd for frames at startup and rebinds
``sys.stdout``/fd 1 to stderr, so a stray ``print`` (or a chatty
library) can never corrupt the frame stream.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Any, BinaryIO, Optional

# the frame protocol grew up here and moved to cluster/wire.py when the
# control channel adopted it; re-exported so existing imports
# (`from tclb_tpu.serve.worker import read_frame`, the pool, tests)
# keep working
from tclb_tpu.cluster.wire import (MAX_FRAME, IpcError,  # noqa: F401
                                   npy_bytes, npy_load, read_frame,
                                   write_frame)


# --------------------------------------------------------------------------- #
# Telemetry relay: worker events -> supervisor pipe (between chunks only)
# --------------------------------------------------------------------------- #

#: bounded relay queue: events accumulated between two solve-chunk
#: flushes beyond this cap are dropped (and counted) rather than growing
#: worker memory while the supervisor-side reader is slow or blocked
RELAY_QUEUE_CAP = 512


class _TelemetryRelay:
    """Worker-side bridge from the in-process telemetry fan-out to the
    supervisor pipe.

    :meth:`sink` is an ``events.subscribe`` subscriber (subscribing it is
    what turns the worker's telemetry on): it appends event docs to a
    bounded deque and counts overflow — O(1), no I/O, safe under the
    events lock.  :meth:`flush` drains the queue into one
    ``{"t": "telemetry"}`` frame and runs only between solve chunks,
    right *after* a heartbeat.  A failed or faulted flush (the
    ``pool.telemetry_relay`` chaos point) drops the batch and counts it
    — relay loss is observable via ``pool.relay_dropped``, but the relay
    can never block a heartbeat or fail the job.
    """

    def __init__(self, lane: int, cap: int = RELAY_QUEUE_CAP) -> None:
        from collections import deque

        from tclb_tpu.telemetry import locks
        self.lane = lane
        self.cap = max(1, int(cap))
        self._q: "Any" = deque()
        # deque append/popleft are atomic; the lock guards only the
        # dropped counters (checkpoint async-save threads emit too)
        self._lock = locks.make_lock("serve.worker._TelemetryRelay._lock")
        self.dropped_total = 0
        self._dropped_pending = 0

    def __len__(self) -> int:
        return len(self._q)

    def sink(self, doc: dict) -> None:
        # counters snapshots stay worker-local: the parent folds its own
        # counter sessions, and relaying a child's cumulative snapshot
        # would double-count in `telemetry report`
        if doc.get("kind") == "counters":
            return
        if len(self._q) >= self.cap:
            with self._lock:
                self.dropped_total += 1
                self._dropped_pending += 1
            return
        self._q.append(doc)

    def flush(self, out: BinaryIO, jid: str, trace_id: str,
              parent_span: Optional[str] = None) -> None:
        """Drain queued events into one relay frame (between chunks
        only).  Injected faults and write failures are contained here:
        the batch is dropped and counted, nothing propagates."""
        from tclb_tpu import faults
        q = self._q
        batch: list = []
        while q:
            try:
                batch.append(q.popleft())
            except IndexError:  # pragma: no cover — flush is the lone consumer
                break
        with self._lock:
            dropped = self._dropped_pending
            self._dropped_pending = 0
        if not batch and not dropped:
            return
        docs = []
        for ev in batch:
            d = dict(ev)  # subscribers share the doc: stamp a copy
            d.setdefault("job_id", trace_id)
            if parent_span is not None:
                d.setdefault("parent_span", parent_span)
            docs.append(d)
        try:
            verdict = faults.fire("pool.telemetry_relay", lane=self.lane,
                                  job=jid, batch=len(docs))
            if verdict == "torn":
                # a half-written relay frame would desync the whole
                # pipe; the contained truncation writes nothing at all
                raise IpcError("torn relay frame")
            write_frame(out, {"t": "telemetry", "id": jid,
                              "events": docs, "dropped": dropped})
        except Exception:  # noqa: BLE001 — relay loss is counted, never fatal
            with self._lock:
                self.dropped_total += len(docs)
                self._dropped_pending += len(docs) + dropped


# --------------------------------------------------------------------------- #
# Solve execution (the only jax-touching half; imports stay lazy so the
# protocol helpers above are importable from the device-free supervisor)
# --------------------------------------------------------------------------- #


def _stream_sample(lat, stream_spec) -> Optional[dict]:
    """Downsampled quantity reduction for one progress frame — computed
    at a segment boundary (the iterate fence has already synced), so the
    extract never races device execution.  Kilobytes, never a full
    field dump."""
    import numpy as np

    from tclb_tpu.utils.render import downsample
    cfg = stream_spec if isinstance(stream_spec, dict) else {}
    qty = cfg.get("quantity")
    used = qty
    try:
        arr = None
        if qty:
            try:
                arr = np.asarray(lat.get_quantity(qty))
            except Exception:  # noqa: BLE001 — tolerate case drift
                names = {q.name.lower(): q.name
                         for q in getattr(lat.model, "quantities", ())}
                used = names.get(str(qty).lower())
                if used:
                    arr = np.asarray(lat.get_quantity(used))
        if arr is None:
            used = "field0"
            arr = np.asarray(lat.state.fields)[0]
        arr = np.asarray(arr, dtype=np.float64)
        while arr.ndim > 2:
            arr = arr[arr.shape[0] // 2]
        if arr.ndim < 2:
            arr = np.atleast_2d(arr)
        coarse = downsample(arr, int(cfg.get("max_dim") or 32))
        return {"quantity": used or "field0",
                "mean": round(float(np.nanmean(arr)), 6),
                "min": round(float(np.nanmin(arr)), 6),
                "max": round(float(np.nanmax(arr)), 6),
                "shape": [int(s) for s in coarse.shape],
                "data": [[round(float(v), 6) for v in row]
                         for row in coarse]}
    except Exception:  # noqa: BLE001 — a reduction must never fail a job
        return None


def _solve(spec: dict, jid: str, lane: int, beat,
           progress=None) -> tuple[dict, bytes]:
    """Run one solve job from a plain-JSON spec; returns the result doc
    + optional ``.npy`` payload of the final fields.  ``progress``
    (optional) is called at each chunk boundary with
    ``(lat, done, start, solve_wall_s)`` to emit progress frames."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tclb_tpu import faults, telemetry
    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model

    t_stage = time.perf_counter()
    with telemetry.span("serve.stage", job=jid, lane=lane):
        model = get_model(spec["model"])
        shape = tuple(int(s) for s in spec["shape"])
        precision = spec.get("dtype", "f32")
        if precision == "f64":
            jax.config.update("jax_enable_x64", True)
        dtype = jnp.float64 if precision == "f64" else jnp.float32
        sdt = {"bf16": jnp.bfloat16, "f32": jnp.float32,
               "f64": jnp.float64}.get(spec.get("storage_dtype"))
        settings = dict(spec.get("params") or {})
        settings.update((spec.get("case") or {}).get("settings") or {})
        niter = int(spec["niter"])

        lat = Lattice(model, shape, dtype=dtype, storage_dtype=sdt,
                      storage_repr=spec.get("storage_repr"),
                      settings=settings or None)
        mgr = None
        resumed_from: Optional[int] = None
        start = 0
        ckpt_root = spec.get("ckpt_root")
        if ckpt_root:
            from tclb_tpu.checkpoint.manager import CheckpointManager
            mgr = CheckpointManager(ckpt_root,
                                    keep_last=int(spec.get("checkpoint_keep")
                                                  or 2))
            newest = mgr.latest()
            if newest is not None:
                mgr.restore(lat, newest)
                start = int(np.asarray(lat.state.iteration))
                resumed_from = start
            else:
                lat.init()
        else:
            lat.init()
    stage_s = time.perf_counter() - t_stage
    beat(phase="built", iter=start)

    every = int(spec.get("checkpoint_every") or 0) if mgr else 0
    hb_every = int(spec.get("hb_iters") or 0) or every \
        or max(1, niter // 8)
    done = start
    t_solve = time.perf_counter()
    while done < niter:
        # chunk boundaries are ABSOLUTE multiples of the cadence, so a
        # resumed run (which starts at a checkpoint step) replays the
        # exact boundary sequence of an uninterrupted one — the
        # bit-identity contract
        nxt = min(niter, (done // hb_every + 1) * hb_every)
        if every:
            nxt = min(nxt, (done // every + 1) * every)
        lat.iterate(nxt - done)
        done = nxt
        if mgr and every and (done % every == 0 or done == niter):
            mgr.save(lat, step=done)
            try:
                faults.fire("pool.worker_exit", lane=lane, job=jid,
                            at="segment", step=done)
            except faults.InjectedFault:
                mgr.wait()
                os._exit(17)
        beat(iter=done)
        if progress is not None:
            progress(lat, done, start, time.perf_counter() - t_solve)
    solve_s = time.perf_counter() - t_solve
    if mgr:
        mgr.wait()

    t_d2h = time.perf_counter()
    with telemetry.span("serve.d2h", job=jid, lane=lane):
        doc: dict[str, Any] = {"globals": lat.get_globals(),
                               "iteration": done,
                               "resumed_from": resumed_from,
                               "lane": lane, "pid": os.getpid()}
        if spec.get("digest"):
            import hashlib
            arr = np.ascontiguousarray(np.asarray(lat.state.fields))
            doc["state_sha256"] = hashlib.sha256(arr.tobytes()).hexdigest()
        payload = b""
        if spec.get("return_state"):
            payload = npy_bytes(lat.state.fields)
    doc["phases"] = {"stage_s": round(stage_s, 6),
                     "solve_s": round(solve_s, 6),
                     "d2h_s": round(time.perf_counter() - t_d2h, 6)}
    return doc, payload


def _run_job(out: BinaryIO, lane: int, doc: dict,
             relay: Optional[_TelemetryRelay] = None) -> None:
    from tclb_tpu import faults
    jid = str(doc.get("id"))
    spec = doc.get("spec") or {}
    # the gateway threads its record id + parent span through the job
    # doc; relayed events are stamped with them so `telemetry report
    # --job <id>` stitches one cross-process timeline
    trace_id = str(spec.get("job_id") or jid)
    parent_span = spec.get("parent_span")

    def beat(**kw) -> None:
        try:
            faults.fire("pool.heartbeat", lane=lane, job=jid)
        except faults.InjectedFault:
            # a wedged worker: stop beating without exiting — the
            # supervisor's missed-heartbeat watchdog must catch this
            time.sleep(3600.0)
        write_frame(out, {"t": "hb", "id": jid, **kw})
        # relay flushes AFTER the beat, never before: a wedged relay
        # can delay telemetry, not liveness
        if relay is not None:
            relay.flush(out, jid, trace_id, parent_span)

    progress = None
    if spec.get("progress") or spec.get("stream"):
        stream_spec = spec.get("stream")
        niter = int(spec.get("niter") or 0)
        nodes = 1
        for s in (spec.get("shape") or ()):
            nodes *= int(s)

        def progress(lat, done, start, wall):  # noqa: F811
            frame = {"t": "progress", "id": jid, "iter": done,
                     "niter": niter, "wall_s": round(wall, 6)}
            if wall > 0 and done > start:
                frame["mlups"] = round(
                    nodes * (done - start) / wall / 1e6, 3)
            if stream_spec:
                sample = _stream_sample(lat, stream_spec)
                if sample is not None:
                    frame["reductions"] = sample
            write_frame(out, frame)

    try:
        if relay is not None:
            from tclb_tpu.telemetry import events
            events.set_job(trace_id)
        try:
            faults.fire("pool.worker_exit", lane=lane, job=jid,
                        at="start")
        except faults.InjectedFault:
            out.flush()
            os._exit(17)
        beat(phase="accepted")
        result, payload = _solve(spec, jid, lane, beat, progress)
        if relay is not None:
            # FIFO pipe: trailing telemetry lands before the parent's
            # own `serve.pool_job_done`, keeping the timeline ordered
            relay.flush(out, jid, trace_id, parent_span)
        write_frame(out, dict({"t": "result", "id": jid, "ok": True},
                              **result), payload)
    except BaseException as e:  # noqa: BLE001 — per-job verdict: a bad
        # spec fails the job, not the worker
        if relay is not None:
            relay.flush(out, jid, trace_id, parent_span)
        write_frame(out, {"t": "result", "id": jid, "ok": False,
                          "error": repr(e),
                          "error_kind": type(e).__name__})
    finally:
        if relay is not None:
            from tclb_tpu.telemetry import events
            events.set_job(None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tclb-worker",
        description="pool solver worker (speaks the WorkerPool frame "
                    "protocol on stdin/stdout; not for interactive use)")
    ap.add_argument("--lane", type=int, default=0,
                    help="pool lane index this worker serves")
    args = ap.parse_args(argv)

    # claim the frame channel, then point fd 1 (and sys.stdout) at
    # stderr so no library print can corrupt the protocol stream
    out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    inp = os.fdopen(os.dup(0), "rb")

    from tclb_tpu.telemetry import live as tlive

    # a crashing worker leaves its own flight-<pid>.jsonl post-mortem
    tlive.flight_recorder().attach()

    # relay is opt-in by the supervisor: when unset, no queue, no
    # subscriber, no clock reads — the strict no-op discipline
    relay: Optional[_TelemetryRelay] = None
    if os.environ.get("TCLB_POOL_RELAY") == "1":
        from tclb_tpu.telemetry import events
        relay = _TelemetryRelay(args.lane)
        events.subscribe(relay.sink)

    write_frame(out, {"t": "ready", "pid": os.getpid(),
                      "lane": args.lane})
    while True:
        try:
            doc, _payload = read_frame(inp)
        except (EOFError, IpcError):
            return 0
        t = doc.get("t")
        if t == "shutdown":
            return 0
        if t == "job":
            _run_job(out, args.lane, doc, relay)


if __name__ == "__main__":
    sys.exit(main())
