"""The fleet-throughput workload: bench case + CI smoke in one place.

``bench.py:fleet_throughput`` and the CI fast job's dispatcher smoke
both drive this module so the measured thing is identical everywhere:

* **throughput** — the 16-small-cavity-job workload through the
  single-worker :class:`Scheduler` vs the :class:`FleetDispatcher`
  (same ``max_batch``, both warmed), reported as ``fleet_speedup_d8``;
* **staging overlap / occupancy** — a deeper run (several batches per
  lane) under a dedicated telemetry trace, summarized by the report
  CLI's Fleet table (``staging_overlap_pct`` must exceed 90% on the
  bench workload: host staging hides under device execution);
* **routing** — one large job whose ``cells x niter`` clears the work
  floor, which must route to the all-device sharded engine
  (``serve.route_sharded``) while the swarm stays on the lanes;
* **bit-parity** — per-lane results are compared bit-exactly against
  the sequential ``Lattice`` path (the serving contract).

Run standalone (CI smoke)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m tclb_tpu.serve.fleet_bench --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Optional

import numpy as np

from tclb_tpu import telemetry
from tclb_tpu.models import get_model
from tclb_tpu.serve import (Case, EnsemblePlan, FleetDispatcher, JobSpec,
                            Scheduler)
from tclb_tpu.telemetry import report

DONE = "done"


def _cavity_flags(model, shape):
    flags = np.full(shape, model.flag_for("MRT"), np.uint16)
    flags[0] = model.flag_for("Wall")
    flags[-1] = model.flag_for("Wall")
    return flags


def make_specs(model, n: int, shape, niter: int) -> list[JobSpec]:
    """n cavity-class jobs in one bin (same flags/shape/niter, a nu
    ladder of cases)."""
    flags = _cavity_flags(model, shape)
    return [JobSpec(model=model, shape=shape,
                    case=Case(settings={"nu": 0.04 + 0.005 * (i % 12)},
                              name=f"cavity{i}"),
                    niter=niter, flags=flags,
                    base_settings={"nu": 0.05})
            for i in range(n)]


def _scrape(url: str) -> tuple[int, str, str]:
    """(status, content-type, body) for one monitor endpoint."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=10) as r:
        return (r.status, r.headers.get("Content-Type", ""),
                r.read().decode("utf-8"))


def run_fleet(jobs: int = 16, shape=(24, 32), niter: int = 60,
              max_batch: int = 2, repeats: int = 2,
              overlap_batches: int = 4, smoke: bool = False,
              trace_out: Optional[str] = None,
              monitor: Optional[str] = None) -> dict:
    """Run the fleet workload; returns the JSON-ready result doc.

    With ``monitor`` set (a ``[host]:port`` spec; port 0 picks a free
    one) the telemetry-phase dispatcher serves the live HTTP plane and
    the workload scrapes ``/metrics`` + ``/status`` mid-run, embedding
    the scrape verdicts in the result doc — the CI smoke asserts them."""
    import jax
    devices = jax.devices()
    n_dev = len(devices)
    model = get_model("d2q9")
    if smoke:
        niter, repeats = min(niter, 10), 0
    specs = make_specs(model, jobs, shape, niter)
    plan = EnsemblePlan(model, shape, flags=_cavity_flags(model, shape),
                        base_settings={"nu": 0.05})
    doc: dict = {"devices": n_dev, "jobs": jobs, "niter": niter,
                 "max_batch": max_batch, "shape": list(shape)}

    # -- aggregate throughput: single worker vs fleet ----------------------- #
    if repeats > 0:
        sched = Scheduler(max_batch=max_batch)
        sched.run(specs)  # warm the compile cache
        t_sched = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            js = sched.run(specs)
            dt = time.perf_counter() - t0
            assert all(j.status == DONE for j in js), \
                [(j.status, repr(j.error)) for j in js if j.status != DONE]
            t_sched = dt if t_sched is None else min(t_sched, dt)
        sched.close()
        fleet = FleetDispatcher(max_batch=max_batch)
        fleet.run(specs)  # warm every lane's device-pinned cache
        t_fleet = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            js = fleet.run(specs)
            dt = time.perf_counter() - t0
            assert all(j.status == DONE for j in js), \
                [(j.status, repr(j.error)) for j in js if j.status != DONE]
            t_fleet = dt if t_fleet is None else min(t_fleet, dt)
        fleet.close()
        doc["t_scheduler_s"] = round(t_sched, 6)
        doc["t_fleet_s"] = round(t_fleet, 6)
        doc["fleet_speedup_d8"] = round(t_sched / t_fleet, 4)

    # -- telemetry phase: staging overlap, occupancy, routing --------------- #
    if trace_out is None:
        fd, trace = tempfile.mkstemp(prefix="fleet-trace-", suffix=".jsonl")
        os.close(fd)
    else:
        trace = trace_out
    prev_trace = telemetry.path()
    telemetry.enable(trace)
    try:
        n_tel = jobs if smoke else overlap_batches * n_dev * max_batch
        tel_specs = make_specs(model, n_tel, shape, niter)
        big_shape = (64, 64)  # y divisible by any n_devices <= 8
        # the routing work floor sits at 2x the swarm jobs' work, and the
        # big job is sized to clear it by another 2x — swarm on lanes,
        # big on the sharded rail, whatever jobs/niter the caller picked
        swarm_work = int(np.prod(shape)) * niter
        floor = 2 * swarm_work
        big_niter = max(50, -(-2 * floor // int(np.prod(big_shape))))
        big = JobSpec(model=model, shape=big_shape,
                      case=Case(settings={"nu": 0.05}, name="big"),
                      niter=big_niter, base_settings={"nu": 0.05})
        fleet2 = FleetDispatcher(max_batch=max_batch, shard_min_work=floor,
                                 monitor=monitor)
        if monitor is not None:
            # async submit so the scrape sees jobs genuinely in flight
            fjobs = [fleet2.submit(s) for s in tel_specs]
            fleet2.start()
            from tclb_tpu.telemetry import live as tlive
            st, ctype, body = _scrape(fleet2.monitor_url + "/metrics")
            doc["monitor_metrics_ok"] = bool(
                st == 200 and ctype == tlive.CONTENT_TYPE
                and "tclb_" in body)
            st, _ctype, body = _scrape(fleet2.monitor_url + "/status")
            status = json.loads(body) if st == 200 else {}
            fstat = status.get("fleet") or {}
            doc["monitor_status_ok"] = bool(
                st == 200 and len(fstat.get("lanes", [])) == n_dev)
            doc["monitor_status_jobs_submitted"] = \
                fstat.get("jobs_submitted")
            for j in fjobs:
                try:
                    j.result()
                except Exception:  # noqa: BLE001 - surfaced on handle
                    pass
        else:
            fjobs = fleet2.run(tel_specs)
        bjob = fleet2.submit(big)
        try:
            bjob.result(timeout=600)
        except Exception:  # noqa: BLE001 - surfaced via status below
            pass
        fleet2.close()
    finally:
        telemetry.disable()
        if prev_trace is not None:
            telemetry.enable(prev_trace)

    summary = report.summarize(report.load(trace))
    fl = summary.get("fleet") or {}
    doc["lanes_active"] = fl.get("lanes_active", 0)
    doc["staging_overlap_pct"] = fl.get("staging_overlap_pct")
    doc["mean_occupancy_pct"] = fl.get("mean_occupancy_pct")
    doc["route_sharded_events"] = fl.get("routed_sharded", 0)
    doc["devices_evicted"] = fl.get("devices_evicted", 0)
    doc["sharded_job_status"] = bjob.status
    doc["trace"] = trace if trace_out is not None else None
    if trace_out is None:
        os.unlink(trace)

    # -- bit-parity: lanes and the sharded rail vs sequential --------------- #
    parity_ok = all(j.status == DONE for j in fjobs) \
        and bjob.status == DONE
    # one job per active lane-batch sample + the sharded job; the full
    # sweep would re-run every case sequentially
    for j in fjobs[:: max(1, len(fjobs) // 4)]:
        seq = plan.run_sequential(j.spec.case, niter)
        got = j.result()
        parity_ok = parity_ok and np.array_equal(
            np.asarray(got.state.fields), np.asarray(seq.state.fields)) \
            and got.globals == seq.globals
    if bjob.status == DONE:
        big_plan = EnsemblePlan(model, big_shape,
                                base_settings={"nu": 0.05})
        seq = big_plan.run_sequential(big.case, big_niter)
        got = bjob.result()
        parity_ok = parity_ok and np.array_equal(
            np.asarray(got.state.fields), np.asarray(seq.state.fields))
    doc["parity_ok"] = bool(parity_ok)
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tclb_tpu.serve.fleet_bench",
        description="Fleet dispatcher throughput workload / CI smoke.")
    p.add_argument("--smoke", action="store_true",
                   help="CI mode: skip the timing laps, tiny niter")
    p.add_argument("--jobs", type=int, default=16)
    p.add_argument("--niter", type=int, default=60)
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--repeats", type=int, default=2)
    p.add_argument("--trace-out", default=None,
                   help="keep the telemetry trace at this path")
    p.add_argument("--monitor", default=None, metavar="[HOST]:PORT",
                   help="serve the live HTTP monitor during the "
                   "telemetry phase and scrape it mid-run (port 0 "
                   "picks a free one)")
    args = p.parse_args(argv)
    doc = run_fleet(jobs=args.jobs, niter=args.niter,
                    max_batch=args.max_batch, repeats=args.repeats,
                    smoke=args.smoke, trace_out=args.trace_out,
                    monitor=args.monitor)
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
