"""High-throughput case serving: batched ensembles, compiled-executable
caching, and a fault-tolerant job scheduler.

The serving stack turns the one-case ``Lattice`` runtime into a
many-case engine:

* :mod:`tclb_tpu.serve.ensemble` — run N independent cases of one
  ``(model, shape, engine)`` class in a single device dispatch, with
  per-case output bit-identical to N sequential runs; gradient-mode
  plans (:class:`GradSpec`) batch N whole unsteady-adjoint sweeps the
  same way;
* :mod:`tclb_tpu.serve.cache` — LRU cache of AOT-compiled ensemble
  executables keyed on ``Model.fingerprint`` (+ JAX's persistent
  compilation cache via ``TCLB_COMPILE_CACHE``);
* :mod:`tclb_tpu.serve.scheduler` — in-process queue that bins
  compatible jobs into batches, retries failed batched runs and
  degrades to the sequential path rather than failing a whole batch;
* :mod:`tclb_tpu.serve.dispatcher` — the fleet layer: one concurrent
  serving lane per local device (device-pinned compiled caches,
  double-buffered host staging) plus size-aware routing of large jobs
  onto the multi-device sharded engine.

CLI: ``python -m tclb_tpu sweep case.xml --param "nu=0.01:0.05:8"``.
"""

from tclb_tpu.serve.cache import (CompiledCache, default_cache,
                                  wire_persistent_cache)
from tclb_tpu.serve.dispatcher import FleetDispatcher, route_job
from tclb_tpu.serve.ensemble import (Case, EnsemblePlan, EnsembleResult,
                                     GradSpec, run_ensemble)
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.serve.scheduler import (Job, JobSpec, JobTimeout, Scheduler,
                                      make_grad_evaluator)

__all__ = [
    "Case",
    "CompiledCache",
    "EnsemblePlan",
    "EnsembleResult",
    "FleetDispatcher",
    "GradSpec",
    "Job",
    "JobSpec",
    "JobTimeout",
    "RetryPolicy",
    "Scheduler",
    "default_cache",
    "make_grad_evaluator",
    "route_job",
    "run_ensemble",
    "wire_persistent_cache",
]
