"""Unified retry policy: exponential backoff + deterministic jitter,
bounded by a per-job attempt budget AND the caller's deadline.

Every retry loop in ``serve/`` and ``gateway/`` goes through a
:class:`RetryPolicy` (enforced by ``hygiene.unpoliced_retry``): scattered
``for attempt in range(1 + retries)`` loops with fixed sleeps can't
honor a submitted deadline, and a fleet of lanes retrying in lockstep
hammers a recovering device — backoff plus jitter spreads them out,
and the deadline cap guarantees a retry never starts after the moment
the caller would already have timed out.

The jitter is *deterministic*: seeded from ``(key, attempt)`` so chaos
schedules replay bit-identically run to run.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule for one class of retried operation.

    ``max_attempts`` counts the first try: ``max_attempts=3`` means at
    most 2 retries.  The delay before retry ``k`` (0-based index of the
    failed attempt) is ``base_delay_s * multiplier**k``, capped at
    ``max_delay_s``, then jittered by ``±jitter`` (fractional)."""

    max_attempts: int = 2
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError("need 0 <= base_delay_s <= max_delay_s")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")

    @classmethod
    def from_retries(cls, retries: int, **kw) -> "RetryPolicy":
        """Back-compat shim for the old ``retries=N`` constructor args."""
        return cls(max_attempts=1 + max(0, int(retries)), **kw)

    @property
    def retries(self) -> int:
        return self.max_attempts - 1

    def backoff(self, attempt: int, key: Optional[str] = None) -> float:
        """Deterministic jittered delay after failed attempt ``attempt``."""
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** max(0, attempt))
        if self.jitter and d > 0:
            r = random.Random(f"{key}:{attempt}").random()
            d *= 1.0 + self.jitter * (2.0 * r - 1.0)
        return d

    def next_delay(self, attempt: int, deadline: Optional[float] = None,
                   key: Optional[str] = None) -> Optional[float]:
        """Delay to sleep before retrying after failed attempt
        ``attempt`` (0-based), or None when the attempt budget or the
        deadline (``time.monotonic()`` scale) is exhausted — the caller
        must stop retrying.  The cap is *start-of-retry*: if sleeping
        the delay would land past the deadline, there is no retry."""
        if attempt + 1 >= self.max_attempts:
            return None
        d = self.backoff(attempt, key=key)
        if deadline is not None and time.monotonic() + d >= deadline:
            return None
        return d
