"""In-process job scheduler: admit, bin, batch, retry, degrade.

The serving loop a parameter-sweep or many-tenant deployment needs on
top of the ensemble engine:

* **admission** — ``submit()`` returns a :class:`Job` handle
  immediately; a single worker thread drains the queue;
* **binning** — jobs of the same ``(Model.fingerprint, shape, dtype,
  flags, niter)`` class batch into one ensemble dispatch, up to the
  memory-predicated cap (``ops/fusion.py:ensemble_batch_cap``, the same
  working-set arithmetic the slab engines' VMEM predicates use);
* **fault tolerance** — a failed batched run is retried a bounded
  number of times, then *degrades* to the per-case sequential path so a
  single poisoned compile never takes the whole batch down; per-job
  timeouts surface as failed jobs, never hung callers;
* **observability** — every batch runs under a ``serve.batch`` span
  (batch size, capacity, per-job queue waits) and the compile cache
  stamps ``serve.compile`` spans; ``telemetry report`` renders both as
  the Serving table.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from tclb_tpu import faults, telemetry
from tclb_tpu.telemetry import live as tlive
from tclb_tpu.telemetry import locks
from tclb_tpu.core.registry import Model
from tclb_tpu.ops import fusion
from tclb_tpu.serve.cache import CompiledCache
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.serve.ensemble import (Case, EnsemblePlan, EnsembleResult,
                                     GradSpec)
from tclb_tpu.utils import log

PENDING, RUNNING, DONE, FAILED = "pending", "running", "done", "failed"


class JobTimeout(TimeoutError):
    """A job missed its deadline (queued too long, or the caller's wait
    expired while the worker was stuck)."""


@dataclasses.dataclass
class JobSpec:
    """One case to serve: the ensemble class it belongs to + its case."""

    model: Model
    shape: tuple[int, ...]
    case: Case
    niter: int
    flags: Optional[np.ndarray] = None
    dtype: Any = jnp.float32
    # opt-in narrowed storage (e.g. bf16): halves the per-case working
    # set, so the memory-predicated batch cap roughly doubles
    storage_dtype: Any = None
    # at-rest representation of narrowed storage ("raw"/"shifted");
    # None resolves to the Lattice default (shifted on a narrowed rung
    # with a recognized velocity set — the Mach-independent choice)
    storage_repr: Optional[str] = None
    base_settings: Optional[dict[str, float]] = None
    # a prebuilt plan (e.g. the sweep CLI's XML-derived base, whose zonal
    # base params a plain settings dict cannot express); must describe
    # the same (model, shape, flags, dtype) class as the fields above
    plan: Optional[EnsemblePlan] = None
    # gradient mode: the job evaluates the unsteady adjoint of its case
    # at Case.theta instead of a forward run — same-(class, grad) jobs
    # batch into ONE dispatch of N whole (forward + reverse) sweeps, and
    # the AOT cache keys the compiled VJP executable on GradSpec.key()
    # content (never id())
    grad: Optional[GradSpec] = None
    timeout_s: Optional[float] = None
    name: str = ""
    # multi-tenancy: jobs queue per tenant and the bin loop drains them
    # round-robin (fair share); the empty string is the default tenant
    tenant: str = ""
    # opaque binning discriminator: jobs with different tags never share
    # a batched dispatch even when everything else matches — the gateway
    # stamps one per resumable job, whose plan carries job-private
    # restored state that _bin_key's base-params digest cannot see.  The
    # compiled-executable cache never keys on it, so tagged jobs still
    # share AOT executables
    bin_tag: str = ""


class Job:
    """Handle returned by ``Scheduler.submit``: poll ``status`` or block
    on ``result()``."""

    def __init__(self, spec: JobSpec, jid: int):
        self.spec = spec
        self.id = jid
        self.status = PENDING
        self.error: Optional[BaseException] = None
        self.attempts = 0
        self.degraded = False
        self.submitted = time.monotonic()
        self.finished_at: Optional[float] = None
        self._result: Optional[EnsembleResult] = None
        self._done = threading.Event()

    def _finish(self, result: Optional[EnsembleResult],
                error: Optional[BaseException]) -> None:
        self._result = result
        self.error = error
        self.status = DONE if error is None else FAILED
        self.finished_at = time.monotonic()
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> EnsembleResult:
        """Block for the outcome.  ``timeout`` defaults to the job's own
        ``timeout_s``; expiring raises :class:`JobTimeout` and marks the
        job failed — a stuck worker surfaces as a failed job, never a
        hung caller (the worker may still finish it in the background,
        but this handle's verdict stands)."""
        if timeout is None:
            timeout = self.spec.timeout_s
        if not self._done.wait(timeout):
            err = JobTimeout(
                f"job {self.id} ({self.spec.name or self.spec.model.name}) "
                f"timed out after {timeout}s")
            if not self._done.is_set():
                self.status = FAILED
                self.error = err
            raise err
        if self.error is not None:
            raise self.error
        return self._result


def _bin_key(spec: JobSpec) -> tuple:
    """Jobs sharing this key run in one batched dispatch.  Keys on the
    model *fingerprint* (never id()) + everything that shapes the
    compiled program: lattice shape, dtype, painted flags, niter."""
    flags_digest = ("none" if spec.flags is None else
                    hashlib.sha1(
                        np.ascontiguousarray(spec.flags).tobytes()
                    ).hexdigest()[:16])
    if spec.plan is not None:
        # content digest of the plan's base params, NOT id(): two plans
        # built from the same config bin together
        h = hashlib.sha1()
        h.update(np.asarray(spec.plan.base_params.settings).tobytes())
        h.update(np.asarray(spec.plan.base_params.zone_table).tobytes())
        base: tuple = ("plan", h.hexdigest()[:16],
                       bool(spec.plan.init_on_run))
    else:
        base = tuple(sorted((spec.base_settings or {}).items()))
    return (spec.model.fingerprint, tuple(spec.shape),
            str(jnp.dtype(spec.dtype)),
            str(jnp.dtype(spec.storage_dtype if spec.storage_dtype
                          is not None else spec.dtype)),
            # at-rest representation: raw and shifted jobs compile to
            # different programs, so they must never share a dispatch
            _repr_key(spec),
            flags_digest, int(spec.niter), base, spec.bin_tag,
            None if spec.grad is None else spec.grad.key())


def _repr_key(spec: JobSpec) -> str:
    """Resolved storage representation of this job, for binning.  Uses
    the same default rule as the Lattice so an explicit ``"shifted"``
    and a None that resolves to shifted bin together."""
    from tclb_tpu.core import shift as ddf
    narrowed = (spec.storage_dtype is not None
                and jnp.dtype(spec.storage_dtype) != jnp.dtype(spec.dtype))
    return ddf.resolve_repr(spec.model, narrowed, spec.storage_repr)


class Scheduler:
    """Local in-process queue + worker loop over the ensemble engine.

    ``retries`` bounds re-attempts of a failed *batched* run before it
    degrades to the sequential per-case path; ``max_batch`` caps the bin
    size on top of the memory predicate.  ``batch_runner`` /
    ``sequential_runner`` are injectable for fault testing: signatures
    ``(plan, cases, niter) -> [EnsembleResult]`` and
    ``(plan, case, niter) -> EnsembleResult``."""

    def __init__(self, max_batch: Optional[int] = None, retries: int = 1,
                 cache: Optional[CompiledCache] = None,
                 batch_runner: Optional[Callable] = None,
                 sequential_runner: Optional[Callable] = None,
                 on_result: Optional[Callable[[Job], None]] = None,
                 autostart: bool = True,
                 retry_policy: Optional[RetryPolicy] = None):
        self.max_batch = max_batch
        self.autostart = autostart
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy.from_retries(retries)
        self.retries = self.retry_policy.retries
        self.cache = cache if cache is not None else CompiledCache()
        self._batch_runner = batch_runner or self._run_batched
        self._seq_runner = sequential_runner or (
            lambda plan, case, niter: plan.run_sequential(case, niter))
        self._on_result = on_result
        # fair-share pending queues: one FIFO deque per tenant, drained
        # round-robin by the bin loop (single-tenant deployments see the
        # exact FIFO order a plain queue gave)
        self._pending: OrderedDict[str, deque[Job]] = OrderedDict()
        self._rr_last: Optional[str] = None
        self._plans: dict[tuple, EnsemblePlan] = {}
        self._jobs = 0
        self._lock = locks.make_lock("serve.scheduler.Scheduler._lock")
        # held across a submit_many burst AND the worker's bin drain, so
        # the worker's next batch sees a whole burst or none of it
        # (reentrant: submit() runs under it inside submit_many)
        self._admit = locks.make_rlock("serve.scheduler.Scheduler._admit")
        self._avail = threading.Condition(self._admit)
        self._closing = False
        self._worker: Optional[threading.Thread] = None
        # every live handle, so close() can sweep jobs whose timeout
        # fires while the worker is stuck or the queue never drains
        self._inflight: dict[int, Job] = {}
        # flight recorder on by default inside serve/: a crashed serving
        # process yields a post-mortem ring dump even without a trace
        self._flight_attached = True
        tlive.flight_recorder().attach()
        tlive.register_status("scheduler", self._status)

    # -- admission ---------------------------------------------------------- #

    def start(self) -> None:
        """Start the worker thread (idempotent).  With
        ``autostart=False``, call after queueing a burst so the binning
        sees the whole burst instead of racing the submitter —
        deterministic batch sizes, deterministic cache keys."""
        with self._lock:
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._loop, name="tclb-serve-worker", daemon=True)
                self._worker.start()

    def submit(self, spec: JobSpec) -> Job:
        if self._closing:
            raise RuntimeError("scheduler is closed")
        with self._lock:
            self._jobs += 1
            job = Job(spec, self._jobs)
            self._inflight[job.id] = job
        with self._avail:
            self._pending.setdefault(spec.tenant, deque()).append(job)
            self._avail.notify()
        telemetry.counter("serve.jobs.submitted")
        telemetry.event("serve.job_queued", job_id=job.id,
                        name=spec.name, model=spec.model.name,
                        shape=list(spec.shape), niter=int(spec.niter))
        if self.autostart:
            self.start()
        return job

    def submit_many(self, specs: Sequence[JobSpec]) -> list[Job]:
        """Admit a burst atomically: the worker's next bin drain sees the
        whole burst, never a prefix — deterministic batch sizes (and
        therefore deterministic compiled-executable cache keys) even when
        the worker is already running between bursts."""
        with self._admit:
            jobs = [self.submit(s) for s in specs]
        return jobs

    def run(self, specs: Sequence[JobSpec]) -> list[Job]:
        """Submit all, wait for all; returns the job handles (failed
        jobs keep their error on the handle instead of raising)."""
        jobs = self.submit_many(specs)
        self.start()
        for j in jobs:
            try:
                j.result()
            except Exception:  # noqa: BLE001 - surfaced on the handle
                pass
        return jobs

    def _status(self) -> dict:
        """Plain-python /status fragment (monitor-thread safe)."""
        now = time.monotonic()
        with self._lock:
            inflight = [{"job_id": j.id, "name": j.spec.name,
                         "status": j.status,
                         "age_s": round(now - j.submitted, 3)}
                        for j in list(self._inflight.values())[:64]]
        # never nested inside _lock: submit_many holds _admit and takes
        # _lock, so _lock -> _avail here would deadlock against it
        with self._avail:
            depth = sum(len(d) for d in self._pending.values())
            per_tenant = {t: len(d) for t, d in self._pending.items()
                          if d}
        return {"queue_depth": depth,
                "queue_depth_by_tenant": per_tenant,
                "jobs_submitted": self._jobs,
                "inflight": inflight,
                "closing": self._closing}

    def close(self, wait: bool = True, join_timeout: float = 60.0) -> None:
        self._closing = True
        tlive.unregister_status("scheduler", self._status)
        if self._flight_attached:
            self._flight_attached = False
            tlive.flight_recorder().detach()
        if wait and self._worker is not None:
            self._worker.join(timeout=join_timeout)
        # close/timeout race: a job whose deadline passes while close is
        # draining (worker stuck mid-batch, or a queue that never ran)
        # must surface as failed-not-hung — the caller may never wait on
        # result() with its own timeout again after close returns.
        now = time.monotonic()
        with self._lock:
            pending = [j for j in self._inflight.values()
                       if not j._done.is_set()]
            self._inflight = {j.id: j for j in pending}
        for job in pending:
            t = job.spec.timeout_s
            if t is not None and now >= job.submitted + t:
                job._finish(None, JobTimeout(
                    f"job {job.id} timed out during close "
                    f"(waited {now - job.submitted:.2f}s)"))
                telemetry.counter("serve.jobs.timeout")

    def __enter__(self) -> "Scheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker loop -------------------------------------------------------- #

    def _plan_for(self, spec: JobSpec, key: tuple) -> EnsemblePlan:
        plan = self._plans.get(key)
        if plan is None:
            plan = spec.plan if spec.plan is not None else EnsemblePlan(
                spec.model, spec.shape, flags=spec.flags, dtype=spec.dtype,
                base_settings=spec.base_settings,
                storage_dtype=spec.storage_dtype,
                storage_repr=spec.storage_repr, grad=spec.grad)
            self._plans[key] = plan
        return plan

    def batch_cap(self, spec: JobSpec) -> int:
        # the carry lives in the STORAGE dtype, so bf16 storage halves
        # the per-case working set and roughly doubles the cap
        sdt = spec.storage_dtype if spec.storage_dtype is not None \
            else spec.dtype
        cap = fusion.ensemble_batch_cap(
            spec.model.n_storage, tuple(spec.shape),
            jnp.dtype(sdt).itemsize)
        if self.max_batch is not None:
            cap = min(cap, int(self.max_batch))
        return max(1, cap)

    def _pop_next_locked(self) -> Optional[Job]:
        """The next batch head: round-robin across tenants with pending
        work, FIFO within a tenant.  Caller holds ``_avail``."""
        tenants = [t for t, d in self._pending.items() if d]
        if not tenants:
            return None
        start = 0
        if self._rr_last is not None and self._rr_last in tenants:
            start = tenants.index(self._rr_last) + 1
        t = tenants[start % len(tenants)]
        self._rr_last = t
        return self._pending[t].popleft()

    def _fill_batch_locked(self, batch: list[Job], key: tuple,
                           cap: int) -> None:
        """Fill ``batch`` with bin-compatible jobs up to ``cap``: one job
        per tenant per pass (fair interleave), FIFO scan within each
        tenant.  Incompatible jobs keep their queue position — no
        requeue-to-tail reordering.  Caller holds ``_avail``."""
        tenants = list(self._pending.keys())
        if not tenants:
            return
        head = batch[0].spec.tenant
        start = (tenants.index(head) + 1) if head in tenants else 0
        order = tenants[start:] + tenants[:start]
        cursor = {t: 0 for t in order}
        progress = True
        while len(batch) < cap and progress:
            progress = False
            for t in order:
                if len(batch) >= cap:
                    break
                dq = self._pending.get(t)
                i = cursor[t]
                while dq is not None and i < len(dq):
                    if _bin_key(dq[i].spec) == key:
                        batch.append(dq[i])
                        del dq[i]
                        progress = True
                        break
                    i += 1
                cursor[t] = i

    def _take_batch(self) -> Optional[list[Job]]:
        """One compatible batch off the pending queues (blocks briefly
        for the first job).  Holding ``_avail`` (the admission lock's
        condition) for the whole drain means an in-flight submit_many
        burst is either fully visible or not at all — binning a prefix
        would split the batch and fork its cache key."""
        with self._avail:
            job = self._pop_next_locked()
            if job is None:
                self._avail.wait(timeout=0.1)
                job = self._pop_next_locked()
                if job is None:
                    return None
            key = _bin_key(job.spec)
            cap = self.batch_cap(job.spec)
            batch = [job]
            if cap > 1:
                self._fill_batch_locked(batch, key, cap)
        return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                if self._closing:
                    return
                continue
            try:
                self._serve_batch(batch)
            except BaseException as e:  # noqa: BLE001 - never kill the loop
                tlive.flight_recorder().dump(
                    "scheduler_exception", error=repr(e),
                    job_ids=[j.id for j in batch])
                for j in batch:
                    if not j._done.is_set():
                        j._finish(None, e)

    def _run_batched(self, plan: EnsemblePlan, cases: Sequence[Case],
                     niter: int) -> list[EnsembleResult]:
        faults.fire("serve.lane_dispatch", rail="scheduler",
                    batch=len(cases))
        return plan.run(cases, niter, cache=self.cache)

    def _serve_batch(self, batch: list[Job]) -> None:
        now = time.monotonic()
        live: list[Job] = []
        for j in batch:
            deadline = (None if j.spec.timeout_s is None
                        else j.submitted + j.spec.timeout_s)
            if deadline is not None and now > deadline:
                j._finish(None, JobTimeout(
                    f"job {j.id} expired in queue "
                    f"(waited {now - j.submitted:.2f}s)"))
                telemetry.counter("serve.jobs.timeout")
            else:
                live.append(j)
        if not live:
            return
        spec = live[0].spec
        key = _bin_key(spec)
        plan = self._plan_for(spec, key)
        cap = self.batch_cap(spec)
        waits = [round(now - j.submitted, 6) for j in live]
        for j in live:
            j.status = RUNNING
        job_ids = [j.id for j in live]
        telemetry.set_job(job_ids[0] if len(job_ids) == 1 else None)
        with telemetry.span("serve.batch", batch=len(live), capacity=cap,
                            model=spec.model.name, niter=int(spec.niter),
                            engine=plan.engine_tag(len(live)),
                            wait_s=waits, job_ids=job_ids,
                            tenants=[j.spec.tenant for j in live]) as sp:
            results: Optional[list[EnsembleResult]] = None
            err: Optional[BaseException] = None
            # the batch deadline is the earliest member's: a retry may
            # never start past the moment any co-batched caller times out
            bd = None
            for j in live:
                if j.spec.timeout_s is not None:
                    d = j.submitted + j.spec.timeout_s
                    bd = d if bd is None else min(bd, d)
            policy = self.retry_policy
            for attempt in range(policy.max_attempts):
                for j in live:
                    j.attempts += 1
                try:
                    results = self._batch_runner(
                        plan, [j.spec.case for j in live], spec.niter)
                    break
                except Exception as e:  # noqa: BLE001 - degrade below
                    err = e
                    delay = policy.next_delay(attempt, deadline=bd,
                                              key=f"batch:{job_ids[0]}")
                    if delay is None:
                        break
                    telemetry.counter("serve.batch.retry")
                    telemetry.event(
                        "serve.batch.retry", attempt=attempt + 1,
                        delay_s=round(delay, 6), job_ids=job_ids,
                        deadline_in_s=(None if bd is None else
                                       round(bd - time.monotonic(), 6)))
                    log.warning(f"serve: batched run failed "
                                f"(attempt {attempt + 1}): {e!r}; "
                                f"retrying in {delay:.3f}s")
                    time.sleep(delay)
            if results is not None:
                sp.add(outcome="ok", retries=attempt)
                telemetry.set_job(None)
                for j, r in zip(live, results):
                    j._finish(r, None)
                    self._stream(j)
                return
            # retry budget (or the deadline) exhausted: degrade to the
            # sequential path per job — one bad case (or a batched-
            # compile failure) must not take down its batch-mates
            sp.add(outcome="degraded", error=repr(err))
            telemetry.counter("serve.batch.degraded")
            log.warning(f"serve: batched run failed after "
                        f"{attempt + 1} attempt(s) ({err!r}); "
                        f"degrading {len(live)} job(s) to sequential")
        telemetry.set_job(None)
        for j in live:
            j.degraded = True
            telemetry.event("serve.job_degraded", job_id=j.id,
                            error=repr(err))
            with telemetry.job_context(j.id):
                try:
                    if plan.init_on_run:
                        r = self._seq_runner(plan, j.spec.case, spec.niter)
                    else:
                        # a continuation plan's state lives in base_state;
                        # run_sequential would re-init from scratch, so
                        # degrade to a singleton batch instead
                        r = plan.run([j.spec.case], spec.niter,
                                     cache=self.cache)[0]
                    j._finish(r, None)
                except Exception as e:  # noqa: BLE001 - per-job verdict
                    j._finish(None, e)
            self._stream(j)

    def _stream(self, job: Job) -> None:
        self._inflight.pop(job.id, None)
        telemetry.counter("serve.jobs.done" if job.status == DONE
                          else "serve.jobs.failed")
        telemetry.event(
            "serve.job_done", job_id=job.id, status=job.status,
            attempts=job.attempts, degraded=job.degraded,
            wall_s=(None if job.finished_at is None else
                    round(job.finished_at - job.submitted, 6)))
        if self._on_result is not None:
            try:
                self._on_result(job)
            except Exception as e:  # noqa: BLE001 - callback is advisory
                log.warning(f"serve: on_result callback failed: {e!r}")


def make_grad_evaluator(scheduler: Scheduler, spec: JobSpec) -> Callable:
    """Batched ``evaluate(thetas) -> [(objective, grad), ...]`` over a
    gradient-mode job class — the serving client
    :func:`tclb_tpu.adjoint.optimize.batched_descent` consumes.

    Each call submits one job per candidate theta; all of them share the
    template's bin key (same class, same :class:`GradSpec`), so a burst
    of N candidates runs as ONE batched adjoint dispatch whose compiled
    VJP executable is AOT-cached on content — a line search evaluating
    the same candidate width every iteration reuses a single executable
    for the whole optimization.  Submit-then-start keeps the binning
    deterministic (build the scheduler with ``autostart=False``)."""
    if spec.grad is None:
        raise ValueError("make_grad_evaluator needs a gradient-mode "
                         "JobSpec (spec.grad is None)")

    def evaluate(thetas: Sequence[Any]) -> list[tuple[float, Any]]:
        base_case = spec.case if spec.case is not None else Case()
        specs = [dataclasses.replace(
            spec,
            case=dataclasses.replace(base_case, theta=th),
            name=f"{spec.name or 'grad'}[{i}]")
            for i, th in enumerate(thetas)]
        jobs = scheduler.run(specs)
        out = []
        for j in jobs:
            r = j.result()   # re-raises a failed job's stored error
            out.append((r.objective, r.grad))
        return out

    return evaluate
