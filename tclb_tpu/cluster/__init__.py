"""Cross-host pod serving: cluster control plane + host-agent data plane.

The gateway process stays the single control plane and does zero device
work; each host in the pod runs one **host-agent**
(``python -m tclb_tpu.cluster.agent --gateway HOST:PORT``) that enrolls
over a TCP control channel, supervises its local
:class:`~tclb_tpu.serve.pool.WorkerPool` as the data plane, and streams
heartbeats, phase timings, and relayed telemetry back.

* :mod:`tclb_tpu.cluster.wire` — the shared length-prefixed JSON/npy
  frame protocol (moved out of ``serve/worker.py`` so the worker pipe
  and the control channel speak the same format);
* :mod:`tclb_tpu.cluster.registry` — gateway-side host bookkeeping:
  enrollment state, heartbeat ages, fair-share routing with
  host-affinity for resumable segments;
* :mod:`tclb_tpu.cluster.server` — the gateway-side
  :class:`ClusterServer`: speaks the pool protocol
  (``submit``/``live_workers``/``close``), so
  ``GatewayService(pool=ClusterServer(...))`` swaps the local worker
  pool for an enrolled pod without any service-layer changes;
* :mod:`tclb_tpu.cluster.agent` — the per-host agent process.
"""

from tclb_tpu.cluster.wire import (MAX_FRAME, Channel, IpcError, npy_bytes,
                                   npy_load, read_frame, write_frame)

__all__ = ["MAX_FRAME", "Channel", "IpcError", "npy_bytes", "npy_load",
           "read_frame", "write_frame"]
