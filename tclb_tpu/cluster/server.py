"""Gateway-side cluster control plane.

:class:`ClusterServer` listens for host-agent enrollments and speaks
the same pool protocol as :class:`~tclb_tpu.serve.pool.WorkerPool`
(``start`` / ``submit`` / ``live_workers`` / ``stats`` / ``close``), so
``GatewayService(pool=ClusterServer(...))`` swaps the local worker pool
for an enrolled pod with zero service-layer changes.  The gateway
process stays the single control plane and does **zero device work**:
jobs are framed over TCP to host-agents, results (including ``.npy``
field payloads) come back on the same channel.

Threads:

* **accept** — one enrollment handshake per connection, then hands the
  channel to a per-host reader;
* **per-host reader** — heartbeats, results, progress, and relayed
  telemetry frames; a read error of any kind marks the host lost;
* **dispatch** — pulls queued jobs and routes them through
  :class:`~tclb_tpu.cluster.registry.HostRegistry` (fair-share +
  resumable affinity); a send failure requeues via the host-death path;
* **watchdog** — heartbeat ages beyond ``heartbeat_timeout_s`` sever
  the channel so the reader notices a silently-hung host.

Requeue-on-host-death reuses the worker pool's attempt semantics: a job
is retried on surviving hosts up to ``job_attempts`` times; resumable
jobs resume from ``CheckpointManager.latest()`` on whichever host picks
them up, bit-identically (the checkpoint store is shared).
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Any, Callable, Optional

from tclb_tpu import faults
from tclb_tpu import telemetry
from tclb_tpu.cluster import wire
from tclb_tpu.cluster.registry import HostRecord, HostRegistry
from tclb_tpu.serve.pool import PoolJob, PoolJobError
from tclb_tpu.telemetry import live as tlive
from tclb_tpu.telemetry import locks
from tclb_tpu.utils import log


class ClusterServer:
    """Control plane for a serving pod (pool-protocol compatible)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 heartbeat_timeout_s: float = 15.0,
                 enroll_timeout_s: float = 10.0,
                 job_attempts: int = 2) -> None:
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.enroll_timeout_s = float(enroll_timeout_s)
        self.job_attempts = max(1, int(job_attempts))
        self.registry = HostRegistry()
        self._queue: "queue.Queue[PoolJob]" = queue.Queue()
        self._lock = locks.make_lock("cluster.server.ClusterServer._lock")
        self._stop_evt = threading.Event()
        self._threads: list[threading.Thread] = []
        self._status_fn: Optional[Callable[[], dict]] = None
        self._started = False
        self._closing = False
        self._seq = 0
        self._submitted = 0
        self._done = 0
        self._failed = 0
        self._requeued = 0
        # bind in the constructor so callers (CLI, tests) can read the
        # resolved port before start(); accepting begins in start()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()[:2]

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- pool protocol -------------------------------------------------------- #

    def start(self) -> "ClusterServer":
        with self._lock:
            if self._started or self._closing:
                return self
            self._started = True
        tlive.enable_live()
        tlive.flight_recorder().attach()
        # keep the exact callable: unregister_status matches by identity
        self._status_fn = self._status
        tlive.register_status("hosts", self._status_fn)
        for name, fn in (("tclb-cluster-accept", self._accept_loop),
                         ("tclb-cluster-dispatch", self._dispatch_loop),
                         ("tclb-cluster-watchdog", self._watchdog_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.notice(f"cluster: control plane listening on {self.address}")
        return self

    def submit(self, doc: dict,
               on_done: Optional[Callable[[PoolJob], None]] = None,
               on_progress: Optional[Callable[[dict], None]] = None
               ) -> PoolJob:
        """Queue one job doc for the pod.  Unlike the local pool there
        is no fail-fast on an empty pod — hosts enroll and re-enroll
        over time; jobs wait for capacity."""
        with self._lock:
            if self._closing:
                raise PoolJobError("cluster server is closed")
            self._seq += 1
            jid = f"cj-{self._seq}"
            self._submitted += 1
        job = PoolJob(jid, dict(doc), on_done=on_done,
                      on_progress=on_progress)
        self._queue.put(job)
        return job

    def live_workers(self) -> int:
        return self.registry.live_lanes()

    def live_hosts(self) -> int:
        return len(self.registry.live())

    def stats(self) -> dict:
        with self._lock:
            return {"submitted": self._submitted, "done": self._done,
                    "failed": self._failed, "requeued": self._requeued,
                    "hosts_live": len(self.registry.live()),
                    "workers_live": self.registry.live_lanes()}

    def close(self, wait: bool = True, timeout: float = 30.0) -> None:
        with self._lock:
            if self._closing:
                return
            self._closing = True
            started = self._started
        if wait and started:
            deadline = time.monotonic() + max(0.0, timeout)
            while time.monotonic() < deadline:
                with self._lock:
                    pending = self._submitted - self._done - self._failed
                if pending <= 0:
                    break
                time.sleep(0.05)
        self._stop_evt.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # whatever is left fails fast so a draining gateway can park
        # its records instead of hanging on result()
        self._fail_queued("cluster server is closed")
        for rec in self.registry.live():
            jobs = self.registry.mark_lost(rec, "server closed")
            for job in jobs or ():
                self._finish_failed(job, PoolJobError(
                    f"job {job.id} aborted: cluster server is closed"))
            try:
                rec.channel.send({"t": "shutdown"})
            except Exception:
                pass
            rec.channel.close()
        if started:
            for t in self._threads:
                t.join(timeout=2.0)
            tlive.unregister_status("hosts", self._status_fn)
            tlive.flight_recorder().detach()
            tlive.disable_live()

    # -- enrollment ----------------------------------------------------------- #

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # listening socket closed
            if self._closing:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._session, args=(conn, addr),
                             name="tclb-cluster-host", daemon=True).start()

    def _session(self, conn: socket.socket, addr: tuple) -> None:
        peer = "%s:%s" % addr[:2]
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self.enroll_timeout_s)
            ch = wire.Channel(conn, peer=peer)
            doc, _ = ch.recv()
            conn.settimeout(None)
        except Exception:
            try:
                conn.close()
            except OSError:
                pass
            return
        host = str(doc.get("host") or "")
        if doc.get("t") != "enroll" or not host:
            self._refuse(ch, "first frame must be an enroll")
            return
        try:
            faults.fire("cluster.enroll", host=host, peer=peer)
        except Exception as e:
            telemetry.counter("cluster.hosts.rejected")
            telemetry.event("gateway.host_rejected", host=host,
                            error=repr(e))
            self._refuse(ch, f"enrollment refused: {e!r}")
            return
        rec, rejoined, stale = self.registry.enroll(
            host, doc.get("pid"), int(doc.get("lanes") or 1), ch)
        if stale is not None:
            self._host_down(stale, "replaced by re-enrollment")
        try:
            ch.send({"t": "enrolled", "host": host,
                     "incarnation": rec.incarnation})
        except Exception:
            self._host_down(rec, "enroll ack failed")
            return
        telemetry.counter("cluster.hosts.enrolled")
        telemetry.event("gateway.host_enrolled", host=host,
                        pid=rec.pid, lanes=rec.lanes,
                        incarnation=rec.incarnation, rejoined=rejoined)
        if rejoined:
            telemetry.counter("cluster.hosts.rejoined")
            telemetry.event("gateway.host_rejoined", host=host,
                            pid=rec.pid, incarnation=rec.incarnation)
        log.notice(f"cluster: host {host} enrolled from {peer} "
                   f"(lanes={rec.lanes} incarnation={rec.incarnation}"
                   f"{' rejoin' if rejoined else ''})")
        self._host_loop(rec, ch)

    @staticmethod
    def _refuse(ch: wire.Channel, error: str) -> None:
        try:
            ch.send({"t": "enroll_err", "error": error})
        except Exception:
            pass
        ch.close()

    # -- per-host reader ------------------------------------------------------ #

    def _host_loop(self, rec: HostRecord, ch: wire.Channel) -> None:
        while True:
            try:
                doc, payload = ch.recv()
            except EOFError:
                self._host_down(rec, "channel closed")
                return
            except (wire.IpcError, OSError, ValueError) as e:
                self._host_down(rec, f"channel error: {e!r}")
                return
            self.registry.beat(rec)
            kind = doc.get("t")
            if kind == "hb":
                self.registry.update_status(rec, doc.get("status"))
            elif kind == "result":
                self._on_result(rec, doc, payload)
            elif kind == "progress":
                self._on_progress(rec, doc)
            elif kind == "telemetry":
                self._reemit(rec, doc)
            else:
                telemetry.counter("cluster.unknown_frames")

    def _on_result(self, rec: HostRecord, doc: dict,
                   payload: bytes) -> None:
        jid = str(doc.get("id"))
        try:
            verdict = faults.fire("cluster.channel", host=rec.host,
                                  job=jid, op="recv")
        except Exception as e:
            # an injected receive fault loses the frame with the
            # channel: the job requeues via the host-death path
            self._host_down(rec, f"injected channel fault: {e!r}")
            return
        if verdict == "torn":
            rec.channel.tear()
            self._host_down(rec, "torn control frame (recv)")
            return
        job = self.registry.take(rec, jid)
        if job is None:
            # result for a job already requeued elsewhere (the host
            # was presumed dead but delivered late) — drop it; the
            # retry owns the record now
            telemetry.counter("cluster.orphan_results")
            return
        ok = bool(doc.get("ok"))
        if ok:
            res = {k: v for k, v in doc.items()
                   if k not in ("t", "id", "ok")}
            if payload:
                res["fields"] = wire.npy_load(payload)
            res.setdefault("host", rec.host)
            job._finish(res, None)
            with self._lock:
                self._done += 1
        else:
            job._finish(None, PoolJobError(
                f"job {jid} failed on host {rec.host}: "
                f"{doc.get('error')}"))
            with self._lock:
                self._failed += 1
        telemetry.event("cluster.job_done", job=jid,
                        job_id=job.doc.get("job_id"), host=rec.host,
                        ok=ok, attempts=job.attempts)

    def _on_progress(self, rec: HostRecord, doc: dict) -> None:
        jid = str(doc.get("id"))
        with self.registry._lock:
            job = rec.inflight.get(jid)
        if job is None:
            return
        info = {k: v for k, v in doc.items() if k not in ("t", "id")}
        info.setdefault("host", rec.host)
        job.progress = info
        if job._on_progress is None:
            return
        try:
            job._on_progress(job)
        except Exception as e:  # advisory, never fatal
            log.warning(f"cluster: progress callback failed: {e!r}")

    def _reemit(self, rec: HostRecord, doc: dict) -> None:
        """Re-emit one relayed telemetry batch into the gateway's
        fan-out, stamped with the originating host (the agent already
        stamped ``worker_pid``/``lane``/``incarnation``)."""
        events = doc.get("events") or ()
        dropped = int(doc.get("dropped") or 0)
        if dropped:
            telemetry.counter("cluster.relay_dropped", dropped)
        for ev in events:
            if not isinstance(ev, dict):
                continue
            fields = dict(ev)
            kind = fields.pop("kind", None)
            if not kind:
                continue
            fields.setdefault("host", rec.host)
            telemetry.counter("cluster.relay_events")
            try:
                telemetry.event(str(kind), **fields)
            except Exception as e:  # advisory path, never fatal
                log.warning(f"cluster: relay re-emit failed: {e!r}")

    # -- dispatch ------------------------------------------------------------- #

    def _dispatch_loop(self) -> None:
        while True:
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                if self._closing:
                    return
                continue
            if job.done:
                continue
            self._dispatch_one(job)

    def _dispatch_one(self, job: PoolJob) -> None:
        while not job.done:
            if self._closing:
                self._finish_failed(job, PoolJobError(
                    f"job {job.id} aborted: cluster server is closed"))
                return
            rec = self.registry.pick(job.doc)
            if rec is None:
                # empty pod: hold the job until a host enrolls
                if self._stop_evt.wait(0.05):
                    self._finish_failed(job, PoolJobError(
                        f"job {job.id} aborted: cluster server is "
                        "closed"))
                    return
                continue
            if not self.registry.assign(rec, job):
                continue  # host died between routing and claim
            job.attempts += 1
            job.status = "running"
            try:
                verdict = faults.fire("cluster.channel", host=rec.host,
                                      job=job.id, op="send")
                if verdict == "torn":
                    rec.channel.tear()
                    raise wire.IpcError("torn control frame (send)")
                rec.channel.send(
                    {"t": "job", "id": job.id, "spec": job.doc})
            except Exception as e:
                # the channel is unusable; the host-death path claims
                # the just-assigned job and requeues or fails it
                self._host_down(rec, f"job send failed: {e!r}")
                return
            telemetry.event("cluster.job_dispatched", job=job.id,
                            job_id=job.doc.get("job_id"), host=rec.host,
                            attempt=job.attempts)
            return

    # -- death ---------------------------------------------------------------- #

    def _host_down(self, rec: HostRecord, reason: str) -> None:
        jobs = self.registry.mark_lost(rec, reason)
        rec.channel.close()
        if jobs is None:
            return  # another thread already handled this incarnation
        telemetry.counter("cluster.hosts.lost")
        telemetry.event("gateway.host_lost", host=rec.host, pid=rec.pid,
                        incarnation=rec.incarnation, reason=reason,
                        jobs_requeued=len(jobs))
        log.warning(f"cluster: host {rec.host} lost ({reason}); "
                 f"requeueing {len(jobs)} in-flight job(s)")
        for job in jobs:
            self._requeue(job, rec.host, reason)

    def _requeue(self, job: PoolJob, host: str, reason: str) -> None:
        if job.done:
            return
        if job.attempts >= self.job_attempts:
            self._finish_failed(job, PoolJobError(
                f"job {job.id} failed after {job.attempts} attempt(s); "
                f"last host {host} lost: {reason}"))
            return
        job.status = "queued"
        with self._lock:
            self._requeued += 1
        telemetry.counter("cluster.jobs.requeued")
        telemetry.event("cluster.job_requeued", job=job.id,
                        job_id=job.doc.get("job_id"), host=host,
                        reason=reason, attempts=job.attempts)
        self._queue.put(job)

    def _finish_failed(self, job: PoolJob, err: Exception) -> None:
        if job.done:
            return
        job._finish(None, err)
        with self._lock:
            self._failed += 1

    def _fail_queued(self, reason: str) -> None:
        while True:
            try:
                job = self._queue.get_nowait()
            except queue.Empty:
                return
            self._finish_failed(job, PoolJobError(
                f"job {job.id} aborted: {reason}"))

    # -- watchdog ------------------------------------------------------------- #

    def _watchdog_loop(self) -> None:
        tick = max(0.2, min(1.0, self.heartbeat_timeout_s / 4.0))
        while not self._stop_evt.wait(tick):
            now = time.monotonic()
            for rec in self.registry.live():
                age = now - rec.last_beat
                if age > self.heartbeat_timeout_s:
                    telemetry.event("cluster.host_hung", host=rec.host,
                                    beat_age_s=round(age, 3))
                    self._host_down(
                        rec, f"heartbeat timeout ({age:.1f}s)")

    # -- status provider ------------------------------------------------------ #

    def _status(self) -> dict:
        snap = self.registry.snapshot()
        with self._lock:
            snap["jobs"] = {
                "submitted": self._submitted, "done": self._done,
                "failed": self._failed, "requeued": self._requeued}
            snap["closing"] = self._closing
        snap["live"] = self.registry.live_lanes()
        snap["queue_depth"] = self._queue.qsize()
        snap["heartbeat_timeout_s"] = self.heartbeat_timeout_s
        snap["address"] = self.address
        return snap
