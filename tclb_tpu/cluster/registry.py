"""Gateway-side host bookkeeping for the serving pod.

:class:`HostRegistry` is the control plane's view of every enrolled
host-agent: enrollment state and incarnation, advertised lanes, the
latest heartbeat's pool status, in-flight job assignments, and recent
dead-host post-mortems.  The router lives here too:

* **fair-share spread** — a burst of scheduler bins lands one-batch-
  per-host: :meth:`pick` chooses the live host with the lowest
  load share (in-flight + queued, normalized by lanes), round-robin on
  ties, so 16 queued jobs spread across a 2-host pod instead of one
  host swallowing the sweep;
* **host affinity for resumable segments** — a resumable job
  (``ckpt_root`` in its spec) sticks to the host already holding its
  warm lattice and newest checkpoint; the affinity dissolves when the
  host dies (checkpoints live on the shared store, so any survivor can
  resume from ``CheckpointManager.latest()`` bit-identically);
* **requeue-on-host-death** — :meth:`mark_lost` atomically claims the
  dead host's in-flight jobs so the server requeues each exactly once,
  no matter whether the watchdog, the reader thread, or a re-enrollment
  noticed the death first.

The registry only mutates state; telemetry events
(``gateway.host_enrolled`` / ``host_lost`` / ``host_rejoined``) are
emitted by the :class:`~tclb_tpu.cluster.server.ClusterServer` outside
the registry lock.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from tclb_tpu.telemetry import locks


class HostRecord:
    """One enrolled host-agent incarnation (state owned by the
    registry; the channel is owned by the server's reader thread)."""

    __slots__ = ("host", "pid", "lanes", "incarnation", "state",
                 "enrolled_ts", "last_beat", "status", "channel",
                 "inflight", "jobs_done", "close_reason", "order")

    def __init__(self, host: str, pid: Optional[int], lanes: int,
                 incarnation: int, channel: Any, order: int):
        self.host = host
        self.pid = pid
        self.lanes = max(1, int(lanes))
        self.incarnation = incarnation
        self.state = "live"          # live / lost
        self.enrolled_ts = round(time.time(), 3)
        self.last_beat = time.monotonic()
        self.status: Optional[dict] = None   # latest heartbeat fragment
        self.channel = channel
        self.inflight: dict[str, Any] = {}   # job id -> PoolJob
        self.jobs_done = 0
        self.close_reason: Optional[str] = None
        self.order = order


class HostRegistry:
    """Thread-safe host table + cross-host router (see module doc)."""

    def __init__(self) -> None:
        self._lock = locks.make_lock("cluster.registry.HostRegistry._lock")
        self._hosts: dict[str, HostRecord] = {}
        self._affinity: dict[str, str] = {}  # ckpt_root -> host id
        self._dumps: list[dict] = []         # recent dead-host notes
        self._rr = 0                         # round-robin tiebreak

    # -- enrollment ---------------------------------------------------------- #

    def enroll(self, host: str, pid: Optional[int], lanes: int,
               channel: Any) -> tuple[HostRecord, bool,
                                      Optional[HostRecord]]:
        """Register one enrollment; returns ``(record, rejoined,
        stale)`` where ``stale`` is a still-live previous incarnation of
        the same host id the caller must tear down (its channel closed,
        its in-flight jobs requeued)."""
        with self._lock:
            prev = self._hosts.get(host)
            stale = prev if prev is not None and prev.state == "live" \
                else None
            incarnation = 0 if prev is None else prev.incarnation + 1
            self._rr += 1
            rec = HostRecord(host, pid, lanes, incarnation, channel,
                             order=self._rr)
            self._hosts[host] = rec
            return rec, prev is not None, stale

    def beat(self, rec: HostRecord) -> None:
        rec.last_beat = time.monotonic()

    def update_status(self, rec: HostRecord,
                      status: Optional[dict]) -> None:
        if isinstance(status, dict):
            with self._lock:
                rec.status = status

    # -- routing ------------------------------------------------------------- #

    def pick(self, doc: dict) -> Optional[HostRecord]:
        """Route one job doc to a live host (None when the pod is
        empty).  Resumable docs keep their affinity host while it
        lives; everything else fair-shares by load per lane."""
        key = doc.get("ckpt_root")
        with self._lock:
            live = [h for h in self._hosts.values() if h.state == "live"]
            if not live:
                return None
            if key:
                owner = self._affinity.get(key)
                if owner is not None:
                    rec = self._hosts.get(owner)
                    if rec is not None and rec.state == "live":
                        return rec
            self._rr += 1
            rr = self._rr

            def load(h: HostRecord) -> tuple:
                q = 0
                if h.status:
                    q = int(h.status.get("queue_depth") or 0)
                return (len(h.inflight) + q) / h.lanes, \
                    (h.order + rr) % max(1, len(live)), h.order

            rec = min(live, key=load)
            if key:
                self._affinity[key] = rec.host
            return rec

    def assign(self, rec: HostRecord, job: Any) -> bool:
        """Claim one in-flight slot on ``rec`` (False when the host died
        between routing and dispatch — the caller re-routes)."""
        with self._lock:
            if rec.state != "live":
                return False
            rec.inflight[job.id] = job
            return True

    def take(self, rec: HostRecord, jid: str) -> Optional[Any]:
        """Pop one in-flight job on result arrival (None for results of
        jobs already requeued to another host — orphans)."""
        with self._lock:
            job = rec.inflight.pop(jid, None)
            if job is not None:
                rec.jobs_done += 1
            return job

    # -- death --------------------------------------------------------------- #

    def mark_lost(self, rec: HostRecord, reason: str) -> Optional[list]:
        """Flip one incarnation to ``lost`` and claim its in-flight
        jobs for requeue.  Idempotent: exactly one caller (watchdog vs
        reader vs re-enroll) gets the job list — every other gets
        ``None`` and must not requeue or emit loss events."""
        with self._lock:
            if rec.state != "live":
                return None
            rec.state = "lost"
            rec.close_reason = reason
            jobs = list(rec.inflight.values())
            rec.inflight.clear()
            for key, owner in list(self._affinity.items()):
                if owner == rec.host:
                    del self._affinity[key]
            self._dumps.append({
                "host": rec.host, "pid": rec.pid,
                "incarnation": rec.incarnation, "reason": reason,
                "jobs_lost": len(jobs),
                "ts": round(time.time(), 3)})
            del self._dumps[:-8]
            return jobs

    # -- views --------------------------------------------------------------- #

    def live(self) -> list[HostRecord]:
        with self._lock:
            return [h for h in self._hosts.values() if h.state == "live"]

    def get(self, host: str) -> Optional[HostRecord]:
        with self._lock:
            return self._hosts.get(host)

    def live_lanes(self) -> int:
        """Serving capacity: live workers per the newest heartbeat when
        one arrived, the advertised lane count until then."""
        total = 0
        with self._lock:
            for h in self._hosts.values():
                if h.state != "live":
                    continue
                if h.status and h.status.get("live") is not None:
                    total += int(h.status.get("live") or 0)
                else:
                    total += h.lanes
        return total

    def snapshot(self) -> dict:
        """Plain-python ``/status`` fragment (monitor-thread safe)."""
        now = time.monotonic()
        with self._lock:
            hosts = []
            for h in sorted(self._hosts.values(), key=lambda x: x.host):
                st = h.status or {}
                hosts.append({
                    "host": h.host, "state": h.state, "pid": h.pid,
                    "lanes": h.lanes, "incarnation": h.incarnation,
                    "live_workers": st.get("live"),
                    "queue_depth": st.get("queue_depth"),
                    "inflight": len(h.inflight),
                    "jobs_done": h.jobs_done,
                    "last_heartbeat_age_s": round(now - h.last_beat, 3),
                    "enrolled_ts": h.enrolled_ts,
                    "close_reason": h.close_reason,
                })
            return {"hosts": hosts, "dead_host_dumps": list(self._dumps)}
