"""Length-prefixed JSON/npy frame protocol (the ``!II`` wire).

Grown as :mod:`tclb_tpu.serve.worker`'s pipe protocol and moved here so
the worker pipe (supervisor <-> lane subprocess, stdin/stdout) and the
cluster control channel (gateway <-> host-agent, TCP) speak one wire
format:

* every frame is an 8-byte ``!II`` header (JSON length, payload length)
  followed by a UTF-8 JSON document and an optional raw binary payload
  (``.npy`` bytes for array data) — **never** pickled objects, so a
  malicious or corrupt peer can at worst feed bad numbers, not code;
* a clean close at a frame boundary raises ``EOFError``; a torn or
  malformed frame raises :class:`IpcError` — the distinction the
  supervisors use to tell shutdown from failure;
* oversized length prefixes are refused (:data:`MAX_FRAME`) instead of
  allocating unbounded buffers.

:class:`Channel` wraps a connected socket in the same protocol with a
write lock, so an agent's heartbeat, result, and relay threads can
interleave whole frames — never bytes.
"""

from __future__ import annotations

import io
import json
import socket
import struct
from typing import BinaryIO, Optional

_HEADER = struct.Struct("!II")

#: refuse absurd frames instead of allocating unbounded buffers
MAX_FRAME = 1 << 30


class IpcError(RuntimeError):
    """A torn or malformed frame on the wire."""


def write_frame(fh: BinaryIO, doc: dict, payload: bytes = b"") -> None:
    """Write one length-prefixed frame: JSON doc + raw payload bytes."""
    from tclb_tpu.telemetry import events
    body = json.dumps(doc, default=events._json_default).encode()
    fh.write(_HEADER.pack(len(body), len(payload)))
    fh.write(body)
    if payload:
        fh.write(payload)
    fh.flush()


def _read_exact(fh: BinaryIO, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = fh.read(n)
        if not chunk:
            raise IpcError(f"pipe closed mid-frame ({n} bytes short)")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def read_frame(fh: BinaryIO) -> tuple[dict, bytes]:
    """Read one frame; EOFError on a clean close at a frame boundary,
    :class:`IpcError` on a torn or malformed one."""
    header = fh.read(_HEADER.size)
    if not header:
        raise EOFError("pipe closed")
    if len(header) < _HEADER.size:
        header += _read_exact(fh, _HEADER.size - len(header))
    body_len, payload_len = _HEADER.unpack(header)
    if body_len > MAX_FRAME or payload_len > MAX_FRAME:
        raise IpcError(f"oversized frame ({body_len}+{payload_len} bytes)")
    try:
        doc = json.loads(_read_exact(fh, body_len).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise IpcError(f"malformed frame body: {e}") from e
    payload = _read_exact(fh, payload_len) if payload_len else b""
    if not isinstance(doc, dict):
        raise IpcError("frame body must be a JSON object")
    return doc, payload


def npy_bytes(arr) -> bytes:
    """Serialize a host array as ``.npy`` bytes (the only array wire
    format — plain data, never pickles)."""
    import numpy as np
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(np.asarray(arr)),
            allow_pickle=False)
    return buf.getvalue()


def npy_load(payload: bytes):
    import numpy as np
    return np.load(io.BytesIO(payload), allow_pickle=False)


class Channel:
    """One framed duplex control channel over a connected socket.

    Reads are single-threaded by convention (one reader thread per
    channel); writes are serialized by a :func:`locks.make_lock` lock so
    concurrent senders (heartbeat thread, result callbacks, relay
    flush) interleave whole frames, never bytes.  Every send/recv error
    maps to the channel being unusable — callers tear the session down
    and re-enroll rather than resynchronize a desynced stream.
    """

    def __init__(self, sock: socket.socket,
                 peer: Optional[str] = None) -> None:
        from tclb_tpu.telemetry import locks
        self.sock = sock
        if peer is None:
            try:
                peer = "%s:%s" % sock.getpeername()[:2]
            except OSError:
                peer = "?"
        self.peer = peer
        self._r = sock.makefile("rb")
        self._w = sock.makefile("wb")
        self._wlock = locks.make_lock("cluster.wire.Channel._wlock")
        self.closed = False

    def send(self, doc: dict, payload: bytes = b"") -> None:
        """Write one frame atomically with respect to other senders."""
        with self._wlock:
            # concurrency-ok[blocking]: serializing whole-frame writes is
            # this lock's purpose — contenders are the channel's own
            # sender threads, and a frame is one bounded send
            write_frame(self._w, doc, payload)

    def recv(self) -> tuple[dict, bytes]:
        """Read one frame (reader-thread only)."""
        return read_frame(self._r)

    def tear(self) -> None:
        """Chaos helper: write a deliberately torn frame (a header
        promising more bytes than follow) and sever the socket — the
        peer's reader sees :class:`IpcError` mid-frame, the exact
        failure the ``cluster.channel`` ``torn`` schedule injects."""
        with self._wlock:
            # concurrency-ok[blocking]: one bounded write; see send()
            try:
                self._w.write(_HEADER.pack(64, 0))
                self._w.write(b"{\"t\": \"torn")
                self._w.flush()
            except (OSError, ValueError):
                pass
        self.close()

    def close(self) -> None:
        self.closed = True
        for closer in (lambda: self.sock.shutdown(socket.SHUT_RDWR),
                       self._w.close, self._r.close, self.sock.close):
            try:
                closer()
            except (OSError, ValueError):
                pass


def connect(host: str, port: int, timeout: Optional[float] = 10.0
            ) -> Channel:
    """Dial a control channel; the connect itself is bounded by
    ``timeout``, the established channel then blocks indefinitely
    (liveness is the heartbeat watchdog's job, not a socket timeout)."""
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return Channel(sock)
