"""Per-host agent: the data-plane half of the serving pod.

``python -m tclb_tpu.cluster.agent --gateway HOST:PORT`` runs on every
host in the pod.  It enrolls with the gateway's
:class:`~tclb_tpu.cluster.server.ClusterServer` over a TCP control
channel (the shared :mod:`~tclb_tpu.cluster.wire` ``!II`` framing),
supervises its local :class:`~tclb_tpu.serve.pool.WorkerPool` as the
data plane — all device work happens in this host's worker lanes — and
streams back:

* **heartbeats** carrying the pool's ``/status`` fragment (live lanes,
  queue depth, worker post-mortems) at ``--hb-interval`` cadence;
* **results** (globals, phase timings, digests, optional ``.npy`` field
  payloads) as each job finishes;
* **relayed telemetry**: the agent process's event fan-out — which
  already carries the worker events the pool re-emitted with
  ``worker_pid``/``lane``/``incarnation`` stamps — batched behind the
  heartbeat, so the gateway renders one cross-host timeline.

Preemption contract: the agent process is disposable.  A SIGKILLed
agent takes its workers with it; on restart it re-enrolls under the
same ``--host-id`` (next incarnation) and the gateway requeues the lost
host's in-flight jobs — resumable ones re-enter from
``CheckpointManager.latest()`` on whatever host picks them up, so the
run completes bit-identically.  The reconnect loop itself retries
forever with jittered backoff (the gateway may be restarting too).

Fault point fired here: ``cluster.host_exit`` (``error`` hard-exits the
agent in the heartbeat loop — the abrupt host death the gateway's
watchdog and requeue path must absorb).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading
from typing import Any, Optional

from tclb_tpu import faults
from tclb_tpu.cluster import wire
from tclb_tpu.serve.pool import PoolJob, WorkerPool
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.telemetry import locks
from tclb_tpu.utils import log

#: bounded relay queue (same discipline as the worker pipe relay):
#: events beyond this cap between two heartbeat flushes are dropped
#: and counted, never allowed to grow agent memory or block liveness
RELAY_QUEUE_CAP = 1024


class _AgentRelay:
    """Agent-side bridge from the in-process telemetry fan-out to the
    control channel.  ``sink`` is an ``events.subscribe`` subscriber:
    O(1) append, no I/O, safe under the events lock; the heartbeat loop
    drains it into one ``{"t": "telemetry"}`` frame right after each
    beat — relay can lag, liveness cannot."""

    def __init__(self, cap: int = RELAY_QUEUE_CAP) -> None:
        from collections import deque
        self.cap = max(1, int(cap))
        self._q: Any = deque()
        self._lock = locks.make_lock("cluster.agent._AgentRelay._lock")
        self.dropped_total = 0
        self._dropped_pending = 0

    def sink(self, doc: dict) -> None:
        # counters snapshots stay host-local (the gateway folds its own
        # sessions); docs already stamped with a host have been through
        # a gateway re-emit — skipping them makes the relay loop-proof
        # when agent and server share one process (tests)
        if doc.get("kind") == "counters" or "host" in doc:
            return
        if len(self._q) >= self.cap:
            with self._lock:
                self.dropped_total += 1
                self._dropped_pending += 1
            return
        self._q.append(doc)

    def drain(self) -> tuple[list, int]:
        q = self._q
        batch: list = []
        while q:
            try:
                batch.append(q.popleft())
            except IndexError:  # pragma: no cover — lone consumer
                break
        with self._lock:
            dropped = self._dropped_pending
            self._dropped_pending = 0
        return batch, dropped

    def requeue(self, batch: list, dropped: int) -> None:
        """Put an unsendable batch back as counted loss."""
        with self._lock:
            self.dropped_total += len(batch)
            self._dropped_pending += len(batch) + dropped


class ClusterAgent:
    """One host's enrollment in the serving pod (see module doc)."""

    def __init__(self, gateway: str, *, host_id: Optional[str] = None,
                 workers: int = 1, hb_interval_s: float = 2.0,
                 relay: bool = True,
                 reconnect: Optional[RetryPolicy] = None,
                 reconnect_forever: bool = True,
                 pool: Optional[WorkerPool] = None,
                 pool_kw: Optional[dict] = None) -> None:
        ghost, _, gport = gateway.rpartition(":")
        self.gateway = (ghost or "127.0.0.1", int(gport))
        self.host_id = host_id or \
            f"{socket.gethostname()}-{os.getpid()}"
        self.hb_interval_s = max(0.05, float(hb_interval_s))
        self.reconnect = reconnect if reconnect is not None else \
            RetryPolicy(max_attempts=8, base_delay_s=0.2,
                        max_delay_s=10.0)
        self.reconnect_forever = bool(reconnect_forever)
        self.pool = pool if pool is not None else WorkerPool(
            workers=max(1, int(workers)), autostart=False,
            **(pool_kw or {}))
        self.incarnation: Optional[int] = None
        self._relay: Optional[_AgentRelay] = None
        if relay:
            from tclb_tpu.telemetry import events
            self._relay = _AgentRelay()
            events.subscribe(self._relay.sink)
        self._lock = locks.make_lock("cluster.agent.ClusterAgent._lock")
        self._stop_evt = threading.Event()
        self._chan: Optional[wire.Channel] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------ #

    def start(self) -> "ClusterAgent":
        """Run the agent on a background thread (in-process tests; the
        CLI drives :meth:`run` on the main thread instead)."""
        self.pool.start()
        t = threading.Thread(target=self.run, name="tclb-cluster-agent",
                             daemon=True)
        t.start()
        self._thread = t
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_evt.set()
        with self._lock:
            ch = self._chan
        if ch is not None:
            ch.close()  # wakes the session reader
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        if self._relay is not None:
            from tclb_tpu.telemetry import events
            events.unsubscribe(self._relay.sink)
        self.pool.close(wait=False)

    def run(self) -> int:
        """Enroll-serve-reconnect until stopped.  Returns an exit code
        (0 = clean shutdown, 1 = gave up reconnecting)."""
        self.pool.start()
        attempt = 0
        while not self._stop_evt.is_set():
            try:
                ch = wire.connect(*self.gateway)
            except OSError as e:
                attempt += 1
                delay = self.reconnect.next_delay(
                    attempt, key=f"{self.host_id}:connect")
                if delay is None:
                    if not self.reconnect_forever:
                        log.warning(
                            f"agent: gateway {self.gateway[0]}:"
                            f"{self.gateway[1]} unreachable after "
                            f"{attempt} attempts — giving up ({e!r})")
                        return 1
                    # keep retrying at the backoff ceiling forever:
                    # a preempted gateway host comes back eventually
                    attempt = 0
                    delay = self.reconnect.max_delay_s
                if self._stop_evt.wait(delay or 1.0):
                    return 0
                continue
            attempt = 0
            verdict = self._session(ch)
            if verdict == "shutdown" or self._stop_evt.is_set():
                return 0
            # channel lost: loop around and re-enroll
        return 0

    # -- one enrolled session ------------------------------------------------- #

    def _session(self, ch: wire.Channel) -> str:
        try:
            ch.send({"t": "enroll", "host": self.host_id,
                     "pid": os.getpid(), "lanes": self.pool.n})
            ack, _ = ch.recv()
        except (OSError, ValueError, EOFError, wire.IpcError) as e:
            ch.close()
            log.warning(f"agent: enrollment failed: {e!r}")
            return "lost"
        if ack.get("t") != "enrolled":
            ch.close()
            log.warning(f"agent: enrollment refused: "
                        f"{ack.get('error') or ack}")
            return "lost"
        self.incarnation = int(ack.get("incarnation") or 0)
        with self._lock:
            self._chan = ch
        # the smoke harness greps this line for liveness
        print(f"agent: enrolled host={self.host_id} "
              f"incarnation={self.incarnation} lanes={self.pool.n}",
              flush=True)
        log.notice(f"agent: enrolled with gateway as {self.host_id} "
                   f"(incarnation {self.incarnation})")
        hb = threading.Thread(target=self._hb_loop, args=(ch,),
                              name="tclb-cluster-agent-hb", daemon=True)
        hb.start()
        verdict = "lost"
        while True:
            try:
                doc, _payload = ch.recv()
            except EOFError:
                break
            except (wire.IpcError, OSError, ValueError) as e:
                log.warning(f"agent: control channel lost: {e!r}")
                break
            t = doc.get("t")
            if t == "shutdown":
                verdict = "shutdown"
                break
            if t == "job":
                self._start_job(ch, doc)
        with self._lock:
            self._chan = None
        ch.close()  # stops the heartbeat thread's sends
        hb.join(timeout=self.hb_interval_s + 5.0)
        return verdict

    def _hb_loop(self, ch: wire.Channel) -> None:
        while not self._stop_evt.wait(self.hb_interval_s):
            try:
                faults.fire("cluster.host_exit", host=self.host_id,
                            at="hb")
            except faults.InjectedFault:
                # the abrupt host death the gateway must absorb: no
                # goodbye frame, no pool teardown — straight down
                os._exit(23)
            try:
                ch.send({"t": "hb", "host": self.host_id,
                         "status": self.pool._status()})
            except Exception:  # noqa: BLE001 — channel is gone
                ch.close()  # wake the session reader
                return
            self._flush_relay(ch)

    def _flush_relay(self, ch: wire.Channel) -> None:
        if self._relay is None:
            return
        batch, dropped = self._relay.drain()
        if not batch and not dropped:
            return
        try:
            ch.send({"t": "telemetry", "host": self.host_id,
                     "events": batch, "dropped": dropped})
        except Exception:  # noqa: BLE001 — relay loss counted, not fatal
            self._relay.requeue(batch, dropped)

    # -- job plumbing --------------------------------------------------------- #

    def _start_job(self, ch: wire.Channel, doc: dict) -> None:
        gid = str(doc.get("id"))
        spec = doc.get("spec") or {}

        def on_progress(pj: PoolJob) -> None:
            frame = {"t": "progress", "id": gid}
            frame.update(pj.progress or {})
            frame["host"] = self.host_id
            try:
                ch.send(frame)
            except Exception:  # noqa: BLE001 — advisory
                pass

        def on_done(pj: PoolJob) -> None:
            payload = b""
            if pj.error is not None:
                frame = {"t": "result", "id": gid, "ok": False,
                         "error": str(pj.error),
                         "error_kind": type(pj.error).__name__,
                         "host": self.host_id,
                         "attempts": pj.attempts}
            else:
                res = dict(pj._result or {})
                fields = res.pop("fields", None)
                if fields is not None:
                    payload = wire.npy_bytes(fields)
                res["host"] = self.host_id
                frame = dict({"t": "result", "id": gid, "ok": True},
                             **res)
            try:
                ch.send(frame, payload)
            except Exception:  # noqa: BLE001 — channel gone: the
                # gateway requeues via its host-death path
                pass

        try:
            self.pool.submit(spec, on_done=on_done,
                             on_progress=on_progress)
        except Exception as e:  # noqa: BLE001 — closed/lane-dead pool
            try:
                ch.send({"t": "result", "id": gid, "ok": False,
                         "error": repr(e),
                         "error_kind": type(e).__name__,
                         "host": self.host_id})
            except Exception:  # noqa: BLE001
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tclb-cluster-agent",
        description="pod host-agent: enrolls this host's worker pool "
                    "with a serving gateway's cluster control plane")
    ap.add_argument("--gateway", required=True, metavar="HOST:PORT",
                    help="cluster control-plane address (the gateway "
                         "CLI prints `cluster: HOST:PORT`)")
    ap.add_argument("--host-id", default=None,
                    help="stable pod identity for rejoin semantics "
                         "(default: <hostname>-<pid>, which never "
                         "rejoins — set it for preemptible hosts)")
    ap.add_argument("--workers", type=int, default=1,
                    help="local worker lanes (data-plane width)")
    ap.add_argument("--hb-interval", type=float, default=2.0,
                    metavar="SECONDS", help="heartbeat cadence")
    ap.add_argument("--heartbeat-timeout", type=float, default=60.0,
                    metavar="SECONDS",
                    help="local pool's per-worker heartbeat timeout")
    ap.add_argument("--no-relay", action="store_true",
                    help="do not relay telemetry events to the gateway")
    args = ap.parse_args(argv)

    agent = ClusterAgent(
        args.gateway, host_id=args.host_id, workers=args.workers,
        hb_interval_s=args.hb_interval, relay=not args.no_relay,
        pool_kw={"heartbeat_timeout_s": args.heartbeat_timeout})

    def _on_sigterm(signum, frame):  # signal-safe: Event.set only
        agent._stop_evt.set()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # pragma: no cover — exotic hosts
        pass

    print(f"agent: host={agent.host_id} workers={agent.pool.n} "
          f"gateway={args.gateway}", flush=True)
    try:
        return agent.run()
    finally:
        agent.pool.close(wait=False)


if __name__ == "__main__":
    sys.exit(main())
