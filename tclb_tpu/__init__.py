"""tclb_tpu — a TPU-native adjoint Lattice-Boltzmann CFD framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of TCLB
(reference: /root/reference, an MPI+CUDA adjoint LBM solver driven by an
R-template metaprogramming pipeline).  Where the reference generates
model-specialized CUDA programs from an R DSL (reference src/conf.R), this
framework registers models as Python model definitions traced by `jax.jit`;
where the reference exchanges halos over MPI (reference src/Lattice.cu.Rt:304-366),
this framework shards the lattice over a `jax.sharding.Mesh` and exchanges
halos with `lax.ppermute` over ICI; where the reference differentiates
kernels with Tapenade (reference tools/makeAD), this framework uses `jax.grad`
with checkpoint policies.
"""

__version__ = "0.2.0"

from tclb_tpu.core.registry import ModelDef, Model  # noqa: F401
from tclb_tpu.core.lattice import Lattice  # noqa: F401
from tclb_tpu.models import get_model, list_models  # noqa: F401
