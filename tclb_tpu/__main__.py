"""Command-line entry point: ``python -m tclb_tpu`` (or the ``tclb``
console script).

Parity target: the reference's per-model binaries
``CLB/<model>/main case.xml [devices]`` (reference src/main.cpp.Rt:220-252)
— one runtime here, the model selected by flag or by the config's
``<CLBConfig model=...>`` attribute, plus catalogue introspection commands
(the reference generates per-model wiki docs instead,
src/Model.md.Rt/src/Models.md.Rt).
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_run(args) -> int:
    import xml.etree.ElementTree as ET

    # honor the config's model attribute when --model is absent
    model_name = args.model
    if model_name is None:
        root = ET.parse(args.case).getroot()
        model_name = root.get("model")
    if model_name is None:
        print("error: no --model flag and no model= attribute on "
              "<CLBConfig>", file=sys.stderr)
        return 2

    if args.distributed:
        # multi-host: one process per host over DCN, same config
        # everywhere (the reference's mpirun surface,
        # src/main.cpp.Rt:178-183); jax.distributed wires the hosts into
        # one global device set, and the global-view arrays/mesh span it
        from tclb_tpu.parallel.multihost import initialize_distributed
        initialize_distributed(args.distributed)

    import jax
    import jax.numpy as jnp
    from tclb_tpu.control.solver import run_config
    from tclb_tpu.models import get_model

    model = get_model(model_name)
    mesh = None
    if args.mesh:
        import numpy as np
        from jax.sharding import Mesh
        axes = tuple(int(v) for v in args.mesh.split("x"))
        names = ("y", "x") if model.ndim == 2 else ("z", "y", "x")
        if len(axes) != len(names):
            print(f"error: --mesh needs {len(names)} factors for a "
                  f"{model.ndim}D model", file=sys.stderr)
            return 2
        n = int(np.prod(axes))
        mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(axes), names)
    dtype = {"f32": jnp.float32, "f64": jnp.float64}[args.precision]
    if dtype is jnp.float64:
        jax.config.update("jax_enable_x64", True)

    monitor = None
    if args.monitor:
        from tclb_tpu.telemetry.http import MonitorServer
        monitor = MonitorServer.from_spec(args.monitor).start()
        print(f"monitor: {monitor.url}/status")

    if args.profile:
        # XLA/TPU trace for TensorBoard (the reference's per-event CUDA
        # timing scaffolding + kernel stats, SURVEY §5 tracing)
        jax.profiler.start_trace(args.profile)
    try:
        solver = run_config(args.case, model, mesh=mesh, dtype=dtype,
                            output=args.output, resume=args.resume)
    finally:
        if args.profile:
            jax.profiler.stop_trace()
            print(f"profile trace written to {args.profile}")
        if monitor is not None:
            monitor.stop()
    print(f"done: {solver.iter} iterations")
    return 0


def _cmd_models(args) -> int:
    from tclb_tpu.models import get_model, list_models
    for name in list_models():
        if args.verbose:
            m = get_model(name)
            print(f"{name:32s} {m.ndim}D  {m.description}")
        else:
            print(name)
    return 0


def _cmd_describe(args) -> int:
    """Model introspection (the reference's generated per-model wiki page,
    src/Model.md.Rt)."""
    from tclb_tpu.models import get_model
    m = get_model(args.model)
    info = {
        "name": m.name,
        "ndim": m.ndim,
        "description": m.description,
        "densities": list(m.storage_names),
        "settings": [{"name": s.name, "default": s.default,
                      "zonal": s.zonal, "comment": s.comment}
                     for s in m.settings],
        "quantities": sorted(m.quantity_fns),
        "globals": [g.name for g in m.globals_],
        "node_types": sorted(m.node_types),
        "stages": sorted(m.stages),
        "actions": {k: list(v) for k, v in m.actions.items()},
    }
    print(json.dumps(info, indent=2, default=str))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tclb", description="TPU-native lattice-Boltzmann framework")
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="run an XML case file")
    r.add_argument("case", help="case.xml config")
    r.add_argument("--model", "-m", help="model name (or model= attr in "
                   "the config)")
    r.add_argument("--output", "-o", default=None, help="output prefix")
    r.add_argument("--mesh", default=None,
                   help="device mesh, e.g. 2x4 (z-y-x major)")
    r.add_argument("--precision", choices=("f32", "f64"), default="f32")
    r.add_argument("--resume", nargs="?", const="latest", default=None,
                   metavar="CKPT",
                   help="resume from a checkpoint before solving: bare "
                   "--resume picks the newest valid checkpoint under the "
                   "config's <SaveCheckpoint> root, or pass an explicit "
                   "checkpoint directory")
    r.add_argument("--profile", default=None, metavar="DIR",
                   help="write a TensorBoard trace of the run to DIR")
    r.add_argument("--monitor", default=None, metavar="[HOST]:PORT",
                   help="serve live /metrics, /status and /trace over "
                   "HTTP for the duration of the run (host defaults to "
                   "127.0.0.1; port 0 picks a free one)")
    r.add_argument("--distributed", default=None, metavar="SPEC",
                   help="multi-host init: 'auto' (TPU pod metadata) or "
                   "coordinator:port,num_processes,process_id")
    r.set_defaults(fn=_cmd_run)

    sw = sub.add_parser("sweep", help="batched parameter sweep over an "
                        "XML base case")
    from tclb_tpu.serve.__main__ import add_sweep_arguments, run_sweep
    add_sweep_arguments(sw)
    sw.set_defaults(fn=run_sweep)

    gw = sub.add_parser("gateway", help="multi-tenant HTTP serving "
                        "gateway (persistent job store + admission "
                        "control + checkpoint-backed resumability)")
    from tclb_tpu.gateway.__main__ import add_gateway_arguments, run_gateway
    add_gateway_arguments(gw)
    gw.set_defaults(fn=run_gateway)

    ls = sub.add_parser("models", help="list the model catalogue")
    ls.add_argument("--verbose", "-v", action="store_true")
    ls.set_defaults(fn=_cmd_models)

    d = sub.add_parser("describe", help="dump a model's registry as JSON")
    d.add_argument("model")
    d.set_defaults(fn=_cmd_describe)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
