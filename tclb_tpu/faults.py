"""Deterministic, seeded fault injection for chaos testing.

The serving stack's failure handling (retry ladders, device eviction,
checkpoint atomicity, journal replay) is only trustworthy if it is
*exercised* — so every failure seam registers a named injection point
and calls :func:`fire` on its hot path.  The call is a strict no-op
unless a fault plan is installed (same single-boolean discipline as
:mod:`tclb_tpu.telemetry`): no locks, no RNG, no clock reads on the
disabled path.

Injection points (the authoritative registry — :func:`fire` rejects
unknown names so a typo cannot silently disable a chaos schedule):

========================  ===================================================
``serve.lane_dispatch``   compiled-executable dispatch on a fleet lane /
                          scheduler batch (``dispatcher._run_batched``)
``serve.stage``           host staging ``device_put`` (``Lane._stage_loop``)
``serve.compile``         AOT compile on a cache miss (``CompiledCache.get``)
``checkpoint.write``      checkpoint shard IO (``writer.write_npy``):
                          ``enospc`` / ``torn`` / ``slow`` fsync — also
                          covers the revolve store's DISK spill tier
                          (``revolve.SnapshotStore._spill`` writes
                          through the same atomic helpers)
``adjoint.spill_d2d``     peer-device HBM spill ``device_put`` in the
                          revolve store (``SnapshotStore.put``, peer
                          tier): ``error`` fails the D2D park — the
                          store evacuates the peer tier to disk,
                          releases its lane lease and degrades; ``slow``
                          delays the park (overhead, not failure)
``store.journal``         JobStore journal append (``store.JobStore.put``)
``gateway.request``       gateway request handling (``GatewayService.submit``)
``pool.spawn``            worker subprocess spawn (``WorkerPool._spawn``):
                          ``error`` fails the attempt, retried under the
                          pool's crash-loop ``RetryPolicy``
``pool.heartbeat``        worker heartbeat emission (``worker._run_job``,
                          fired *inside* the worker): ``error`` wedges the
                          worker mid-solve — the missed heartbeat the
                          supervisor watchdog must catch; ``slow`` delays
                          the beat
``pool.ipc``              supervisor frame send / result receive
                          (``WorkerPool._serve`` / ``_await_result``):
                          ``error`` = torn pipe — worker killed and
                          restarted, job re-queued
``pool.worker_exit``      fired *inside* the worker at job start and each
                          checkpoint segment boundary: ``error`` hard-exits
                          the process (nonzero) — the crash the supervisor
                          must absorb without losing the job
``pool.telemetry_relay``  worker-side relay flush of batched telemetry
                          frames between solve chunks
                          (``worker._TelemetryRelay.flush``): ``error`` /
                          ``enospc`` / ``torn`` drop the batch (counted in
                          ``pool.relay_dropped``), ``slow`` delays the
                          flush — never the heartbeat, never the job
``cluster.enroll``        host-agent enrollment handshake on the gateway
                          (``ClusterServer._session``): ``error`` refuses
                          the enrollment (the agent backs off and
                          retries), ``slow`` delays the ack
``cluster.channel``       control-channel frame send / result receive on
                          the gateway (``ClusterServer._dispatch_one`` /
                          ``_on_result``): ``error`` fails the op,
                          ``torn`` tears the channel mid-frame — either
                          way the host is marked lost and its in-flight
                          jobs requeue on survivors
``cluster.host_exit``     fired *inside* the host-agent's heartbeat loop
                          (``ClusterAgent._hb_loop``): ``error``
                          hard-exits the agent process (takes its worker
                          lanes with it) — the abrupt host death the
                          gateway watchdog and requeue path must absorb
========================  ===================================================

Modes: ``error`` raises :class:`InjectedFault`; ``enospc`` raises
``OSError(ENOSPC)``; ``slow`` sleeps ``delay`` seconds then proceeds;
``torn`` returns the token ``"torn"`` — the seam truncates its write so
the torn-file tolerance machinery (CRC verify, journal replay) gets
exercised rather than faked.

Activation, exactly like telemetry: ``TCLB_FAULTS=<spec>`` in the
environment (parsed at import) or :func:`install` with a
:class:`FaultPlan`.  Spec grammar — ``;``-separated clauses, each either
``seed=N`` or ``point[:mode][:key=val]*``::

    TCLB_FAULTS="seed=7;serve.lane_dispatch:error:n=2;checkpoint.write:enospc:n=1:after=1"

Rule knobs: ``p`` (probability per hit, default 1), ``n`` (max
injections, default unlimited), ``after`` (skip the first N hits),
``delay`` (seconds, ``slow`` mode).  Determinism: each rule owns a
``random.Random`` seeded from ``(plan seed, point, rule index)``, and
hit counts are kept per point — a schedule replays identically as long
as each point's call sequence does, independent of cross-point thread
interleaving.

Every injection emits a ``fault.injected`` telemetry event + counter;
crash-mode injections (``error``/``enospc``/``torn``) are flight-recorder
dump triggers (telemetry/live.py) so each injected crash leaves a
post-mortem ring dump.
"""

from __future__ import annotations

import errno
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

from tclb_tpu import telemetry

POINTS = frozenset({
    "serve.lane_dispatch",
    "serve.stage",
    "serve.compile",
    "checkpoint.write",
    "adjoint.spill_d2d",
    "store.journal",
    "gateway.request",
    "pool.spawn",
    "pool.heartbeat",
    "pool.ipc",
    "pool.worker_exit",
    "pool.telemetry_relay",
    "cluster.enroll",
    "cluster.channel",
    "cluster.host_exit",
})

MODES = frozenset({"error", "enospc", "torn", "slow"})
CRASH_MODES = frozenset({"error", "enospc", "torn"})


class InjectedFault(RuntimeError):
    """A fault deliberately raised by an ``error``-mode injection rule."""


@dataclass(frozen=True)
class FaultRule:
    """One clause of a fault plan: when ``point`` fires, maybe inject."""

    point: str
    mode: str = "error"
    prob: float = 1.0
    times: Optional[int] = None     # max injections; None = unlimited
    after: int = 0                  # skip the first `after` hits
    delay_s: float = 0.05           # slow-mode stall

    def __post_init__(self) -> None:
        if self.point not in POINTS:
            raise ValueError(f"unknown injection point {self.point!r}; "
                             f"known: {sorted(POINTS)}")
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}; "
                             f"known: {sorted(MODES)}")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {self.prob}")


@dataclass(frozen=True)
class FaultPlan:
    """A seeded set of rules: what to break, where, and how often."""

    rules: tuple[FaultRule, ...] = ()
    seed: int = 0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``TCLB_FAULTS`` grammar (see module docstring)."""
        rules: list[FaultRule] = []
        seed = 0
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                seed = int(clause[5:])
                continue
            parts = clause.split(":")
            point = parts[0]
            mode = "error"
            kw: dict = {}
            for part in parts[1:]:
                if "=" not in part:
                    mode = part
                    continue
                k, v = part.split("=", 1)
                if k == "p":
                    kw["prob"] = float(v)
                elif k == "n":
                    kw["times"] = int(v)
                elif k == "after":
                    kw["after"] = int(v)
                elif k == "delay":
                    kw["delay_s"] = float(v)
                else:
                    raise ValueError(
                        f"unknown fault-rule knob {k!r} in {clause!r}")
            rules.append(FaultRule(point, mode, **kw))
        return cls(rules=tuple(rules), seed=seed)

    def to_spec(self) -> str:
        """Re-serialize to the ``TCLB_FAULTS`` grammar — the round-trip
        that carries an installed plan across a worker process boundary
        (``FaultPlan.parse(plan.to_spec())`` is equivalent)."""
        clauses = [f"seed={self.seed}"]
        for r in self.rules:
            c = f"{r.point}:{r.mode}"
            if r.prob < 1.0:
                c += f":p={r.prob}"
            if r.times is not None:
                c += f":n={r.times}"
            if r.after:
                c += f":after={r.after}"
            if r.mode == "slow" and r.delay_s != 0.05:
                c += f":delay={r.delay_s}"
            clauses.append(c)
        return ";".join(clauses)


class _RuleState:
    """Mutable per-rule bookkeeping behind one installed plan."""

    __slots__ = ("rule", "rng", "injected")

    def __init__(self, rule: FaultRule, seed: int, index: int):
        self.rule = rule
        self.rng = random.Random(f"{seed}:{rule.point}:{index}")
        self.injected = 0


_lock = threading.Lock()
_active = False                     # the single-boolean no-op gate
_plan: Optional[FaultPlan] = None
_states: list[_RuleState] = []
_hits: dict[str, int] = {}          # per-point call counts


def active() -> bool:
    return _active


def install(plan: FaultPlan) -> None:
    """Install (or replace) the process-wide fault plan."""
    global _active, _plan
    with _lock:
        _plan = plan
        _states[:] = [_RuleState(r, plan.seed, i)
                      for i, r in enumerate(plan.rules)]
        _hits.clear()
        _active = bool(plan.rules)


def uninstall() -> None:
    """Remove the fault plan; :func:`fire` returns to the no-op path."""
    global _active, _plan
    with _lock:
        _plan = None
        _states.clear()
        _hits.clear()
        _active = False


def current_spec() -> Optional[str]:
    """The installed plan as a ``TCLB_FAULTS`` spec string, or None when
    no plan is active — how the pool hands the schedule to workers."""
    with _lock:
        return _plan.to_spec() if _active and _plan is not None else None


def stats() -> dict:
    """Per-rule injection counts + per-point hit counts (for asserts)."""
    with _lock:
        return {
            "hits": dict(_hits),
            "injected": [{"point": s.rule.point, "mode": s.rule.mode,
                          "count": s.injected} for s in _states],
        }


def fire(point: str, **ctx) -> Optional[str]:
    """Evaluate the installed plan at a named injection point.

    No-op (returns None) when no plan is installed.  Otherwise the first
    matching rule whose predicate passes injects: ``error``/``enospc``
    raise, ``slow`` sleeps then returns None, ``torn`` returns the token
    ``"torn"`` for the seam to act on.  ``ctx`` fields are stamped onto
    the ``fault.injected`` telemetry event.
    """
    if not _active:
        return None
    if point not in POINTS:
        raise ValueError(f"unregistered injection point {point!r}")
    with _lock:
        if not _active:
            return None
        hit = _hits.get(point, 0) + 1
        _hits[point] = hit
        chosen: Optional[_RuleState] = None
        for st in _states:
            r = st.rule
            if r.point != point or hit <= r.after:
                continue
            if r.times is not None and st.injected >= r.times:
                continue
            if r.prob < 1.0 and st.rng.random() >= r.prob:
                continue
            st.injected += 1
            chosen = st
            break
    if chosen is None:
        return None
    rule = chosen.rule
    telemetry.event("fault.injected", point=point, mode=rule.mode,
                    hit=hit, injection=chosen.injected, **ctx)
    telemetry.counter("faults.injected")
    if rule.mode == "slow":
        time.sleep(rule.delay_s)
        return None
    if rule.mode == "torn":
        return "torn"
    if rule.mode == "enospc":
        raise OSError(errno.ENOSPC,
                      f"injected fault at {point}: no space left on device")
    raise InjectedFault(f"injected fault at {point} "
                        f"(hit {hit}, injection {chosen.injected})")


# env activation, mirroring TCLB_TELEMETRY: opt in at import time
_env_spec = os.environ.get("TCLB_FAULTS")
if _env_spec:
    install(FaultPlan.parse(_env_spec))
del _env_spec
