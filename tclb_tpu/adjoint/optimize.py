"""Optimizer drivers over (design, gradient) — the reference's NLopt layer.

Parity target: ``acOptimize``/``GenericOptimizer::Execute`` (reference
src/Handlers.cpp.Rt:1708-1943): rank-0 runs NLopt (MMA et al.) over the
concatenated parameter vector, evaluating (primal + adjoint) per step, with
optional material constraints; plus the built-in simultaneous descent
``Iteration_Opt`` (src/cuda.cu.Rt:224-234: steepest descent clamped to
[0, 1]).

NLopt is not in this environment; the method names map onto:

* ``MMA`` / ``LBFGS`` -> scipy L-BFGS-B (bound-constrained quasi-Newton —
  the same role MMA plays for topology optimization here),
* ``DESCENT`` -> clamped steepest descent (== the reference's built-in
  ``Iteration_Opt``),
* ``ADAM`` -> optax Adam (TPU-idiomatic extra).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


def _clamp(theta, lo, hi):
    if lo is None and hi is None:
        return theta
    return jax.tree_util.tree_map(
        lambda x: jnp.clip(x, lo if lo is not None else -np.inf,
                           hi if hi is not None else np.inf), theta)


def optimize(grad_fn: Callable, theta0: Any, method: str = "MMA",
             max_eval: int = 20, step: float = 1.0,
             bounds: tuple = (None, None),
             callback: Optional[Callable] = None) -> tuple[Any, float]:
    """Minimize ``objective`` over theta.  ``grad_fn(theta) ->
    (objective, grad_pytree)``; returns (theta_opt, best_objective).

    ``callback(k, obj, theta)`` fires per accepted evaluation (the
    reference's per-NLopt-iteration log/VTK hooks)."""
    method = method.upper()
    lo, hi = bounds if isinstance(bounds, tuple) and len(bounds) == 2 \
        else (None, None)
    if method in ("DESCENT", "STEEPEST"):
        theta = theta0
        obj = np.inf
        for k in range(max_eval):
            obj, g = grad_fn(theta)
            theta = _clamp(jax.tree_util.tree_map(
                lambda t, d: t - step * d, theta, g), lo, hi)
            if callback:
                callback(k, float(obj), theta)
        return theta, float(obj)
    if method == "ADAM":
        import optax
        opt = optax.adam(step)
        opt_state = opt.init(theta0)
        theta, obj = theta0, np.inf
        for k in range(max_eval):
            obj, g = grad_fn(theta)
            upd, opt_state = opt.update(g, opt_state)
            theta = _clamp(optax.apply_updates(theta, upd), lo, hi)
            if callback:
                callback(k, float(obj), theta)
        return theta, float(obj)
    if method in ("MMA", "LBFGS", "L-BFGS-B"):
        from scipy.optimize import minimize
        flat0, unravel = ravel_pytree(theta0)
        flat0 = np.asarray(flat0, dtype=np.float64)
        state = {"k": 0, "best": np.inf, "theta": theta0}

        def f_and_g(x):
            theta = unravel(jnp.asarray(x, dtype=flat0.dtype))
            obj, g = grad_fn(theta)
            gflat, _ = ravel_pytree(g)
            state["k"] += 1
            if float(obj) < state["best"]:
                state["best"], state["theta"] = float(obj), theta
            if callback:
                callback(state["k"], float(obj), theta)
            return float(obj), np.asarray(gflat, dtype=np.float64)

        b = None
        if lo is not None or hi is not None:
            b = [(lo, hi)] * flat0.size
        res = minimize(f_and_g, flat0, jac=True, method="L-BFGS-B",
                       bounds=b, options={"maxfun": max_eval})
        theta = unravel(jnp.asarray(res.x, dtype=flat0.dtype))
        return theta, float(res.fun)
    raise ValueError(f"unknown optimization method {method!r}")
