"""Optimizer drivers over (design, gradient) — the reference's NLopt layer.

Parity target: ``acOptimize``/``GenericOptimizer::Execute`` (reference
src/Handlers.cpp.Rt:1708-1943): rank-0 runs NLopt (MMA et al.) over the
concatenated parameter vector, evaluating (primal + adjoint) per step, with
optional material constraints; plus the built-in simultaneous descent
``Iteration_Opt`` (src/cuda.cu.Rt:224-234: steepest descent clamped to
[0, 1]).

Method map:

* ``MMA`` -> a native implementation of Svanberg's Method of Moving
  Asymptotes (the reference's NLopt default, LD_MMA,
  src/Handlers.cpp.Rt:1815): separable fractional approximations with
  moving asymptotes, the material constraint handled exactly by dual
  bisection on its single multiplier (:func:`_mma`),
* ``LBFGS`` -> scipy L-BFGS-B (bound-constrained quasi-Newton; SLSQP
  when a material constraint is present),
* ``DESCENT`` -> clamped steepest descent (== the reference's built-in
  ``Iteration_Opt``, src/cuda.cu.Rt:224-234),
* ``ADAM`` -> optax Adam (TPU-idiomatic extra).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


def _clamp(theta, lo, hi):
    if lo is None and hi is None:
        return theta
    return jax.tree_util.tree_map(
        lambda x: jnp.clip(x, lo if lo is not None else -np.inf,
                           hi if hi is not None else np.inf), theta)


def _project_material(theta, lo, hi, direction: str, m0: float,
                      mask=None):
    """Project theta onto the material half-space ``sum(theta[mask]) >=
    m0`` (direction 'more') or ``<= m0`` ('less') intersected with the
    [lo, hi] box: bisection on a uniform shift t of the masked entries
    with re-clipping — the Euclidean projection for this constraint pair.
    Plays the role of the reference's NLopt inequality constraints
    FMaterialMore/FMaterialLess (src/Handlers.cpp.Rt:1790-1812) for the
    projected-descent methods.  ``mask`` selects the entries that ARE
    material (the design nodes); without it every entry counts — the
    reference's parameter vector contains only design nodes, ours may
    carry masked-out background values that must not absorb the
    projection."""
    flat_j, unravel = ravel_pytree(theta)
    # bisection entirely in numpy: one device->host transfer instead of a
    # blocking float() sync per probe (~180 of them)
    flat = np.asarray(flat_j, dtype=np.float64)
    lo_ = -np.inf if lo is None else float(lo)
    hi_ = np.inf if hi is None else float(hi)
    msk = np.ones_like(flat) if mask is None else \
        np.asarray(mask, dtype=np.float64).ravel()
    total = float(flat @ msk)
    if (direction == "more" and total >= m0) or \
            (direction == "less" and total <= m0):
        return theta

    def s(t):
        return float(np.clip(flat + t * msk, lo_, hi_) @ msk)

    t_lo, t_hi = -1.0, 1.0
    for _ in range(60):
        if s(t_lo) <= m0:
            break
        t_lo *= 2.0
    for _ in range(60):
        if s(t_hi) >= m0:
            break
        t_hi *= 2.0
    for _ in range(60):
        tm = 0.5 * (t_lo + t_hi)
        if s(tm) < m0:
            t_lo = tm
        else:
            t_hi = tm
    t = t_hi if direction == "more" else t_lo
    shifted = np.clip(flat + t * msk, lo_, hi_)
    out = np.where(msk > 0, shifted, flat)
    return unravel(jnp.asarray(out, dtype=flat_j.dtype))


def _parse_material(material, n):
    """Normalize the ``('more'|'less', m0[, mask])`` material tuple into
    a single linear constraint ``a @ x <= b`` (None, None when absent)."""
    if material is None:
        return None, None
    direction, m0 = material[0], float(material[1])
    mvec = np.ones(n) if len(material) < 3 else \
        np.asarray(material[2], dtype=np.float64).ravel()
    # 'less': m.x <= m0;  'more': m.x >= m0  ->  (-m).x <= -m0
    return (mvec, m0) if direction == "less" else (-mvec, -m0)


def _mma(grad_fn, theta0, max_eval, lo, hi, material, callback):
    """Svanberg's Method of Moving Asymptotes (1987), the algorithm the
    reference actually runs as its NLopt default (LD_MMA,
    src/Handlers.cpp.Rt:1815-1868).

    Each outer iteration builds the separable convex approximation
    ``f(x) ~ r + sum_j p_j/(U_j - x_j) + q_j/(x_j - L_j)`` around the
    current point with moving asymptotes L < x < U (expanded on
    oscillation-free coordinates, contracted on oscillating ones), and
    minimizes it inside move limits.  The optional linear material
    constraint ``a @ x <= b`` is exact here (it IS linear): the
    subproblem Lagrangian stays separable, the per-coordinate minimizer
    is found by vectorized bisection on the strictly-increasing
    derivative, and the single multiplier by outer bisection on
    feasibility — the same dual approach NLopt's MMA inner solver uses,
    specialized to one constraint."""
    flat0, unravel = ravel_pytree(theta0)
    if material is not None:
        # start feasible: best-x tracking below compares objectives of
        # ITERATES, and every iterate after this projection is feasible
        theta0 = _project_material(theta0, lo, hi, *material)
        flat0, unravel = ravel_pytree(theta0)
    x = np.asarray(flat0, dtype=np.float64)
    n = x.size
    # unbounded coordinates get a pseudo-box scaled to the start point
    # (MMA needs finite asymptote spans); MMA is a box-constrained
    # topology-optimization algorithm — for genuinely unbounded smooth
    # problems prefer method="LBFGS", which converges much faster there
    wide = 2.0 * np.maximum(np.abs(x), 1.0)
    xmin = x - wide if lo is None else np.full(n, float(lo))
    xmax = x + wide if hi is None else np.full(n, float(hi))
    x = np.clip(x, xmin, xmax)
    span = np.maximum(xmax - xmin, 1e-12)

    a, b = _parse_material(material, n)

    low = x - 0.5 * span
    upp = x + 0.5 * span
    xold1 = xold2 = x
    best_obj, best_x = np.inf, x

    for k in range(max_eval):
        obj, g = grad_fn(unravel(jnp.asarray(x, dtype=flat0.dtype)))
        gflat = np.asarray(ravel_pytree(g)[0], dtype=np.float64)
        if float(obj) < best_obj:
            best_obj, best_x = float(obj), x
        if callback:
            callback(k, float(obj),
                     unravel(jnp.asarray(x, dtype=flat0.dtype)))

        # ---- asymptote update (Svanberg's gamma rule) ----------------- #
        if k < 2:
            low = x - 0.5 * span
            upp = x + 0.5 * span
        else:
            osc = (x - xold1) * (xold1 - xold2)
            gamma = np.where(osc > 0, 1.2, np.where(osc < 0, 0.7, 1.0))
            low = x - gamma * (xold1 - low)
            upp = x + gamma * (upp - xold1)
            low = np.clip(low, x - 10.0 * span, x - 0.01 * span)
            upp = np.clip(upp, x + 0.01 * span, x + 10.0 * span)

        # ---- separable approximation of the objective ----------------- #
        gp = np.maximum(gflat, 0.0)
        gm = np.maximum(-gflat, 0.0)
        reg = 1e-3 * np.abs(gflat) + 1e-6 / span
        p0 = (upp - x) ** 2 * (1.001 * gp + 0.001 * gm + reg)
        q0 = (x - low) ** 2 * (0.001 * gp + 1.001 * gm + reg)

        alpha = np.maximum(xmin, np.maximum(low + 0.1 * (x - low),
                                            x - 0.5 * span))
        beta = np.minimum(xmax, np.minimum(upp - 0.1 * (upp - x),
                                           x + 0.5 * span))

        def xa(lam):
            """argmin of the separable Lagrangian on [alpha, beta]: the
            derivative p/(U-x)^2 - q/(x-L)^2 + lam*a is strictly
            increasing in x -> vectorized bisection."""
            loj, hij = alpha.copy(), beta.copy()
            for _ in range(50):
                mid = 0.5 * (loj + hij)
                d = p0 / (upp - mid) ** 2 - q0 / (mid - low) ** 2
                if a is not None:
                    d = d + lam * a
                up = d < 0.0
                loj = np.where(up, mid, loj)
                hij = np.where(up, hij, mid)
            return 0.5 * (loj + hij)

        if a is None or float(a @ xa(0.0)) <= b:
            x_new = xa(0.0)
        else:
            lam_hi = 1.0
            for _ in range(60):
                if float(a @ xa(lam_hi)) <= b:
                    break
                lam_hi *= 2.0
            lam_lo = 0.0
            for _ in range(60):
                lam = 0.5 * (lam_lo + lam_hi)
                if float(a @ xa(lam)) <= b:
                    lam_hi = lam
                else:
                    lam_lo = lam
            x_new = xa(lam_hi)

        xold2, xold1, x = xold1, x, x_new

    return unravel(jnp.asarray(best_x, dtype=flat0.dtype)), best_obj


def batched_descent(evaluate: Callable, theta0: Any, max_iter: int = 10,
                    steps: tuple = (0.25, 0.5, 1.0, 2.0),
                    bounds: tuple = (None, None),
                    callback: Optional[Callable] = None
                    ) -> tuple[Any, float]:
    """Projected steepest descent whose line search is ONE batched
    gradient dispatch per iteration — the serving client of the
    gradient-mode scheduler (:func:`tclb_tpu.serve.make_grad_evaluator`).

    ``evaluate(thetas) -> [(objective, grad), ...]`` values a whole list
    of candidates at once; here every iteration submits the full
    candidate fan ``theta - s * g`` for each trial step ``s`` as a
    single batch, picks the best candidate, and reuses ITS gradient for
    the next fan — so each optimizer iteration costs exactly one batched
    adjoint dispatch of ``len(steps)`` whole (forward + reverse) sweeps.
    The warm-up evaluation replicates ``theta0`` to the candidate width:
    every dispatch then shares one batch size, so the whole optimization
    runs through ONE AOT-compiled VJP executable (the CI serving smoke
    asserts exactly that).

    When no candidate improves, the trial steps halve (classic
    backtracking) and the fan re-issues from the same point.  Returns
    ``(theta_best, objective_best)``."""
    lo, hi = bounds if isinstance(bounds, tuple) and len(bounds) == 2 \
        else (None, None)
    width = max(1, len(steps))
    out = evaluate([theta0] * width)
    obj, g = float(out[0][0]), out[0][1]
    theta, scale = theta0, 1.0
    best_obj, best_theta = obj, theta0
    if callback:
        callback(0, obj, theta0)
    for k in range(max_iter):
        cands = [_clamp(jax.tree_util.tree_map(
            lambda t, d, s=s: t - scale * s * d, theta, g), lo, hi)
            for s in steps]
        out = evaluate(cands)
        objs = [float(o) for o, _ in out]
        i = int(np.argmin(objs))
        if objs[i] < obj:
            theta, obj, g = cands[i], objs[i], out[i][1]
            scale = 1.0
        else:
            scale *= 0.5   # backtrack: same point, shorter fan
        if obj < best_obj:
            best_obj, best_theta = obj, theta
        if callback:
            callback(k + 1, obj, theta)
    return best_theta, best_obj


def optimize(grad_fn: Callable, theta0: Any, method: str = "MMA",
             max_eval: int = 20, step: float = 1.0,
             bounds: tuple = (None, None),
             callback: Optional[Callable] = None,
             material: Optional[tuple] = None
             ) -> tuple[Any, float]:
    """Minimize ``objective`` over theta.  ``grad_fn(theta) ->
    (objective, grad_pytree)``; returns (theta_opt, best_objective).

    ``callback(k, obj, theta)`` fires per accepted evaluation (the
    reference's per-NLopt-iteration log/VTK hooks).

    ``material=('more'|'less', m0)`` or ``('more'|'less', m0, mask)``
    constrains the total material ``sum(theta*mask)`` to stay above/below
    ``m0`` (reference <Optimize Material="more|less">,
    FMaterialMore/FMaterialLess inequality constraints,
    src/Handlers.cpp.Rt:1776-1812,1870-1886): projection for the descent
    methods, SLSQP inequality constraints for the quasi-Newton path.
    Pass the mask whenever theta carries masked-out background entries
    (e.g. InternalTopology's full design plane) — without it those
    entries count as material and absorb the projection."""
    method = method.upper()
    lo, hi = bounds if isinstance(bounds, tuple) and len(bounds) == 2 \
        else (None, None)

    def feasible(theta):
        if material is None:
            return theta
        return _project_material(theta, lo, hi, *material)

    if method in ("DESCENT", "STEEPEST"):
        theta = feasible(theta0)
        obj = np.inf
        for k in range(max_eval):
            obj, g = grad_fn(theta)
            theta = feasible(_clamp(jax.tree_util.tree_map(
                lambda t, d: t - step * d, theta, g), lo, hi))
            if callback:
                callback(k, float(obj), theta)
        return theta, float(obj)
    if method == "ADAM":
        import optax
        opt = optax.adam(step)
        opt_state = opt.init(theta0)
        theta, obj = feasible(theta0), np.inf
        for k in range(max_eval):
            obj, g = grad_fn(theta)
            upd, opt_state = opt.update(g, opt_state)
            theta = feasible(_clamp(optax.apply_updates(theta, upd),
                                    lo, hi))
            if callback:
                callback(k, float(obj), theta)
        return theta, float(obj)
    if method == "MMA":
        return _mma(grad_fn, theta0, max_eval, lo, hi, material, callback)
    if method in ("LBFGS", "L-BFGS-B"):
        from scipy.optimize import minimize
        flat0, unravel = ravel_pytree(theta0)
        flat0 = np.asarray(flat0, dtype=np.float64)
        state = {"k": 0, "best": np.inf, "theta": theta0}

        def f_and_g(x):
            theta = unravel(jnp.asarray(x, dtype=flat0.dtype))
            obj, g = grad_fn(theta)
            gflat, _ = ravel_pytree(g)
            state["k"] += 1
            if float(obj) < state["best"]:
                state["best"], state["theta"] = float(obj), theta
            if callback:
                callback(state["k"], float(obj), theta)
            return float(obj), np.asarray(gflat, dtype=np.float64)

        b = None
        if lo is not None or hi is not None:
            b = [(lo, hi)] * flat0.size
        if material is not None:
            # shared normal form a @ x <= b  ->  SLSQP ineq b - a@x >= 0
            a_c, b_c = _parse_material(material, flat0.size)
            cons = [{"type": "ineq",
                     "fun": lambda x: b_c - float(x @ a_c),
                     "jac": lambda x: -a_c}]
            res = minimize(f_and_g, flat0, jac=True, method="SLSQP",
                           bounds=b, constraints=cons,
                           options={"maxiter": max_eval})
        else:
            res = minimize(f_and_g, flat0, jac=True, method="L-BFGS-B",
                           bounds=b, options={"maxfun": max_eval})
        theta = unravel(jnp.asarray(res.x, dtype=flat0.dtype))
        return theta, float(res.fun)
    raise ValueError(f"unknown optimization method {method!r}")
