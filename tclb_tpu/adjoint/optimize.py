"""Optimizer drivers over (design, gradient) — the reference's NLopt layer.

Parity target: ``acOptimize``/``GenericOptimizer::Execute`` (reference
src/Handlers.cpp.Rt:1708-1943): rank-0 runs NLopt (MMA et al.) over the
concatenated parameter vector, evaluating (primal + adjoint) per step, with
optional material constraints; plus the built-in simultaneous descent
``Iteration_Opt`` (src/cuda.cu.Rt:224-234: steepest descent clamped to
[0, 1]).

NLopt is not in this environment; the method names map onto:

* ``MMA`` / ``LBFGS`` -> scipy L-BFGS-B (bound-constrained quasi-Newton —
  the same role MMA plays for topology optimization here),
* ``DESCENT`` -> clamped steepest descent (== the reference's built-in
  ``Iteration_Opt``),
* ``ADAM`` -> optax Adam (TPU-idiomatic extra).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


def _clamp(theta, lo, hi):
    if lo is None and hi is None:
        return theta
    return jax.tree_util.tree_map(
        lambda x: jnp.clip(x, lo if lo is not None else -np.inf,
                           hi if hi is not None else np.inf), theta)


def _project_material(theta, lo, hi, direction: str, m0: float,
                      mask=None):
    """Project theta onto the material half-space ``sum(theta[mask]) >=
    m0`` (direction 'more') or ``<= m0`` ('less') intersected with the
    [lo, hi] box: bisection on a uniform shift t of the masked entries
    with re-clipping — the Euclidean projection for this constraint pair.
    Plays the role of the reference's NLopt inequality constraints
    FMaterialMore/FMaterialLess (src/Handlers.cpp.Rt:1790-1812) for the
    projected-descent methods.  ``mask`` selects the entries that ARE
    material (the design nodes); without it every entry counts — the
    reference's parameter vector contains only design nodes, ours may
    carry masked-out background values that must not absorb the
    projection."""
    flat_j, unravel = ravel_pytree(theta)
    # bisection entirely in numpy: one device->host transfer instead of a
    # blocking float() sync per probe (~180 of them)
    flat = np.asarray(flat_j, dtype=np.float64)
    lo_ = -np.inf if lo is None else float(lo)
    hi_ = np.inf if hi is None else float(hi)
    msk = np.ones_like(flat) if mask is None else \
        np.asarray(mask, dtype=np.float64).ravel()
    total = float(flat @ msk)
    if (direction == "more" and total >= m0) or \
            (direction == "less" and total <= m0):
        return theta

    def s(t):
        return float(np.clip(flat + t * msk, lo_, hi_) @ msk)

    t_lo, t_hi = -1.0, 1.0
    for _ in range(60):
        if s(t_lo) <= m0:
            break
        t_lo *= 2.0
    for _ in range(60):
        if s(t_hi) >= m0:
            break
        t_hi *= 2.0
    for _ in range(60):
        tm = 0.5 * (t_lo + t_hi)
        if s(tm) < m0:
            t_lo = tm
        else:
            t_hi = tm
    t = t_hi if direction == "more" else t_lo
    shifted = np.clip(flat + t * msk, lo_, hi_)
    out = np.where(msk > 0, shifted, flat)
    return unravel(jnp.asarray(out, dtype=flat_j.dtype))


def optimize(grad_fn: Callable, theta0: Any, method: str = "MMA",
             max_eval: int = 20, step: float = 1.0,
             bounds: tuple = (None, None),
             callback: Optional[Callable] = None,
             material: Optional[tuple] = None
             ) -> tuple[Any, float]:
    """Minimize ``objective`` over theta.  ``grad_fn(theta) ->
    (objective, grad_pytree)``; returns (theta_opt, best_objective).

    ``callback(k, obj, theta)`` fires per accepted evaluation (the
    reference's per-NLopt-iteration log/VTK hooks).

    ``material=('more'|'less', m0)`` or ``('more'|'less', m0, mask)``
    constrains the total material ``sum(theta*mask)`` to stay above/below
    ``m0`` (reference <Optimize Material="more|less">,
    FMaterialMore/FMaterialLess inequality constraints,
    src/Handlers.cpp.Rt:1776-1812,1870-1886): projection for the descent
    methods, SLSQP inequality constraints for the quasi-Newton path.
    Pass the mask whenever theta carries masked-out background entries
    (e.g. InternalTopology's full design plane) — without it those
    entries count as material and absorb the projection."""
    method = method.upper()
    lo, hi = bounds if isinstance(bounds, tuple) and len(bounds) == 2 \
        else (None, None)

    def feasible(theta):
        if material is None:
            return theta
        return _project_material(theta, lo, hi, *material)

    if method in ("DESCENT", "STEEPEST"):
        theta = feasible(theta0)
        obj = np.inf
        for k in range(max_eval):
            obj, g = grad_fn(theta)
            theta = feasible(_clamp(jax.tree_util.tree_map(
                lambda t, d: t - step * d, theta, g), lo, hi))
            if callback:
                callback(k, float(obj), theta)
        return theta, float(obj)
    if method == "ADAM":
        import optax
        opt = optax.adam(step)
        opt_state = opt.init(theta0)
        theta, obj = feasible(theta0), np.inf
        for k in range(max_eval):
            obj, g = grad_fn(theta)
            upd, opt_state = opt.update(g, opt_state)
            theta = feasible(_clamp(optax.apply_updates(theta, upd),
                                    lo, hi))
            if callback:
                callback(k, float(obj), theta)
        return theta, float(obj)
    if method in ("MMA", "LBFGS", "L-BFGS-B"):
        from scipy.optimize import minimize
        flat0, unravel = ravel_pytree(theta0)
        flat0 = np.asarray(flat0, dtype=np.float64)
        state = {"k": 0, "best": np.inf, "theta": theta0}

        def f_and_g(x):
            theta = unravel(jnp.asarray(x, dtype=flat0.dtype))
            obj, g = grad_fn(theta)
            gflat, _ = ravel_pytree(g)
            state["k"] += 1
            if float(obj) < state["best"]:
                state["best"], state["theta"] = float(obj), theta
            if callback:
                callback(state["k"], float(obj), theta)
            return float(obj), np.asarray(gflat, dtype=np.float64)

        b = None
        if lo is not None or hi is not None:
            b = [(lo, hi)] * flat0.size
        if material is not None:
            direction, m0 = material[0], material[1]
            mvec = np.ones(flat0.size) if len(material) < 3 else \
                np.asarray(material[2], dtype=np.float64).ravel()
            sign = 1.0 if direction == "more" else -1.0
            cons = [{"type": "ineq",
                     "fun": lambda x: sign * (float(x @ mvec) - m0),
                     "jac": lambda x: sign * mvec}]
            res = minimize(f_and_g, flat0, jac=True, method="SLSQP",
                           bounds=b, constraints=cons,
                           options={"maxiter": max_eval})
        else:
            res = minimize(f_and_g, flat0, jac=True, method="L-BFGS-B",
                           bounds=b, options={"maxfun": max_eval})
        theta = unravel(jnp.asarray(res.x, dtype=flat0.dtype))
        return theta, float(res.fun)
    raise ValueError(f"unknown optimization method {method!r}")
