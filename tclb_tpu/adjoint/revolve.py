"""Binomial (revolve) checkpointing for the unsteady adjoint.

Griewank & Walther's *revolve* (ACM TOMS 26(1), 2000) is the provably
recompute-optimal schedule for reversing a length-``T`` evolution with a
fixed budget of ``S`` stored states — the algorithm behind the
reference's recorded-horizon adjoint snapshots (SnapLevel hierarchy,
src/Lattice.cu.Rt:34-49, disk spill at :735-765).  This module provides
the three layers the production sweep needs:

* :func:`revolve_schedule` — the OFFLINE planner: an explicit action
  sequence (``advance`` / ``snapshot`` / ``restore`` / ``free`` /
  ``reverse``) whose total advanced steps equal the Griewank binomial
  optimum :func:`binomial_bound` and whose peak simultaneously-held
  snapshots never exceed ``S`` (both asserted by the property test in
  tests/test_revolve.py);
* :class:`SnapshotStore` — the three-tier executor store: the first
  ``mem_slots`` snapshots stay in host memory, the next ``peer_slots``
  park on a fleet lane leased from the serving dispatcher (D2D
  ``device_put`` onto peer HBM), and the rest spill to disk through
  :class:`tclb_tpu.checkpoint.writer.AsyncWriter` (one write in
  flight, device→host copy on the writer thread) so spill overlaps the
  forward compute; the fence happens at reverse-sweep fetch, never per
  park.  Spill files are written atomically with a CRC32 sidecar — a
  SIGKILL mid-spill leaves only complete, CRC-verifiable ``.npy`` files
  (asserted by the kill-resume CI step);
* :func:`make_revolve_gradient` — the driver: executes the schedule
  over the engine's chunked diff step (Pallas where
  ``supports_diff`` covers the configuration, XLA otherwise), chaining
  per-unit ``jax.vjp`` cotangents across snapshot boundaries.  The
  accumulation structure mirrors ``make_unsteady_gradient``'s
  ``levels=1`` scan exactly (flat ``jnp.sum`` over forward-ordered
  increments, reverse-ordered cotangent additions, one ``design.put``
  VJP at the end) so the gradients are bit-identical to the in-memory
  reference on tier-1 cases.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import zlib
from functools import lru_cache
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tclb_tpu import faults, telemetry
from tclb_tpu.core.lattice import (LatticeState, SimParams, Streaming,
                                   make_action_step)
from tclb_tpu.core.registry import Model

# -- the planner ---------------------------------------------------------- #


def binomial_bound(T: int, S: int) -> int:
    """Minimal total advanced steps to reverse ``T`` steps with ``S``
    snapshot slots (Griewank & Walther 2000, Prop. 1):
    ``t = r*T - C(S+r, S+1)`` with ``r`` the least repetition number
    satisfying ``C(S+r, S) >= T``."""
    T, S = int(T), int(S)
    if T <= 1:
        return 0
    if S < 1:
        raise ValueError("revolve needs at least one snapshot slot")
    r = 0
    while math.comb(S + r, S) < T:
        r += 1
    return r * T - math.comb(S + r, S + 1)


@lru_cache(maxsize=None)
def _opt_cost(length: int, slots: int) -> int:
    """Dynamic-programming twin of :func:`binomial_bound` — also yields
    the optimal split point for the schedule recursion."""
    if length <= 1:
        return 0
    if slots == 1:
        return length * (length - 1) // 2
    return min(m + _opt_cost(m, slots) + _opt_cost(length - m, slots - 1)
               for m in range(1, length))


def _opt_split(length: int, slots: int) -> int:
    best_m, best = 1, None
    for m in range(1, length):
        c = m + _opt_cost(m, slots) + _opt_cost(length - m, slots - 1)
        if best is None or c < best:
            best, best_m = c, m
    return best_m


def revolve_schedule(T: int, S: int) -> list[tuple]:
    """The explicit action sequence reversing steps ``0..T-1`` with at
    most ``S`` live snapshots.  Actions:

    * ``("snapshot", i)`` — store the current state (at step ``i``);
    * ``("advance", i, j)`` — run steps ``i..j-1`` forward (``j > i``);
    * ``("restore", i)`` — load the stored state at step ``i``;
    * ``("free", i)`` — drop the stored state at step ``i``;
    * ``("reverse", i)`` — adjoint of step ``i`` (primal state must be
      at ``i``; the running cotangent moves from ``i+1`` to ``i``).

    The initial state occupies one of the ``S`` slots.  Total advanced
    steps equal :func:`binomial_bound`; reverses happen exactly once per
    step, in strictly decreasing order."""
    T, S = int(T), int(S)
    if T < 1:
        return []
    if S < 1:
        raise ValueError("revolve needs at least one snapshot slot")
    out: list[tuple] = [("snapshot", 0)]

    def rec(b: int, e: int, s: int) -> None:
        # precondition: the state at b is held in a slot; s slots total
        # are usable on [b, e) INCLUDING b's
        length = e - b
        if length == 1:
            out.append(("restore", b))
            out.append(("reverse", b))
            return
        if s == 1:
            for i in range(e - 1, b - 1, -1):
                out.append(("restore", b))
                if i > b:
                    out.append(("advance", b, i))
                out.append(("reverse", i))
            return
        m = _opt_split(length, s)
        out.append(("restore", b))
        out.append(("advance", b, b + m))
        out.append(("snapshot", b + m))
        rec(b + m, e, s - 1)
        out.append(("free", b + m))
        rec(b, b + m, s)

    rec(0, T, min(S, T))
    out.append(("free", 0))
    return out


@dataclasses.dataclass(frozen=True)
class RevolvePlan:
    """The planner's verdict for one adjoint run: snapshot budget and
    the memory / peer-HBM / disk split (``auto_plan``)."""

    horizon: int              # schedule units (niter // chunk)
    snapshots: int            # total slots S
    mem_slots: int            # slots kept in host memory
    bytes_per_snapshot: int
    advances: int             # binomial_bound(horizon, snapshots)
    peer_slots: int = 0       # slots parked on a leased fleet device

    @property
    def recompute_factor(self) -> float:
        return self.advances / max(self.horizon, 1)


def auto_plan(model: Model, shape, horizon: int,
              dtype=jnp.float32,
              host_budget_bytes: Optional[float] = None,
              spill: bool = False,
              dispatcher: Optional[Any] = None,
              peer_budget_bytes: Optional[float] = None) -> RevolvePlan:
    """Pick ``S`` and the three-tier split from measured capacities: the
    host budget modeled in
    :func:`tclb_tpu.ops.fusion.snapshot_mem_slots` (same working-set
    arithmetic as the serving batch cap), then — when a
    :class:`~tclb_tpu.serve.dispatcher.FleetDispatcher` with a sparable
    lane is given — a peer-HBM tier sized from ``peer_budget_bytes``
    (``TCLB_PEER_BUDGET_MB`` or 1 GiB: deliberately a fraction of any
    real device so the leased lane's HBM still fits a reinstated serving
    batch), and finally, with ``spill`` enabled, the disk tier grows S
    only while it still buys a meaningful recompute reduction (disk
    reads are not free), stopping once the recompute factor drops under
    ~1.5 extra sweeps."""
    from tclb_tpu.ops import fusion
    per = int(jnp.dtype(dtype).itemsize * model.n_storage
              * int(np.prod(shape)))
    mem = fusion.snapshot_mem_slots(model.n_storage, tuple(shape),
                                    jnp.dtype(dtype).itemsize,
                                    budget_bytes=host_budget_bytes)
    mem = max(1, min(mem, horizon))
    peer = 0
    if dispatcher is not None and mem < horizon:
        free = sum(1 for l in getattr(dispatcher, "lanes", [])
                   if not l.evicted and l.reserved is None)
        if free >= 2:   # reserve_lane keeps the last healthy lane serving
            if peer_budget_bytes is None:
                mb = os.environ.get("TCLB_PEER_BUDGET_MB")
                peer_budget_bytes = (int(mb) * 1024 * 1024 if mb
                                     else 1024 * 1024 * 1024)
            peer = min(int(peer_budget_bytes) // max(per, 1),
                       horizon - mem)
            peer = max(0, peer)
    S = mem + peer
    if spill:
        while S < horizon and binomial_bound(horizon, S) > 1.5 * horizon:
            S += 1
    return RevolvePlan(horizon=int(horizon), snapshots=S, mem_slots=mem,
                       bytes_per_snapshot=per,
                       advances=binomial_bound(horizon, S),
                       peer_slots=peer)


# -- the three-tier snapshot store ---------------------------------------- #


def _tree_nbytes(tree) -> int:
    return sum(int(getattr(x, "nbytes", 0)) for x in jax.tree.leaves(tree))


class SnapshotStore:
    """Three-tier store executing a revolve schedule's snapshot traffic:
    host memory → peer-device HBM → disk.

    The first ``mem_slots`` concurrently-live snapshots stay in host
    memory (numpy); the next ``peer_slots`` park on an idle fleet
    device's HBM — a lane leased from the ``dispatcher``
    (:meth:`~tclb_tpu.serve.dispatcher.FleetDispatcher.reserve_lane`),
    parked via a pinned ``device_put`` (D2D over ICI on a pod — the
    host never touches the bytes); further ones spill to ``spill_dir``
    through the async checkpoint writer — the device→host copy and the
    file write both happen on the writer thread, so parking overlaps
    the forward compute that follows it.  ``get`` fences (drains the
    writer) only when the requested snapshot was spilled to disk and
    not yet durable.

    The peer tier DEGRADES, never fails: an injected/real D2D fault
    (``adjoint.spill_d2d``), or the dispatcher revoking the lease for
    serving demand, evacuates every peer snapshot to the next tier,
    releases the lane, and the sweep continues — gradients stay
    bit-identical because every tier round-trips the exact array bytes.

    Spill files are crash-consistent: the payload is written through
    ``atomic_path`` (temp + fsync + rename — a SIGKILL never leaves a
    half-written ``.npy`` under the final name) and a ``.crc`` sidecar
    carrying the CRC32 of the payload bytes lands after it, so any
    surviving payload+sidecar pair is verifiable and a payload without a
    sidecar is identifiable as uncommitted."""

    def __init__(self, mem_slots: int, spill_dir: Optional[str] = None,
                 prefix: str = "snap", peer_slots: int = 0,
                 dispatcher: Optional[Any] = None):
        from tclb_tpu.checkpoint.writer import AsyncWriter
        self.mem_slots = max(0, int(mem_slots))
        self.spill_dir = spill_dir
        self.prefix = prefix
        self.peer_slots = max(0, int(peer_slots))
        self.dispatcher = dispatcher
        self._mem: dict[Any, Any] = {}
        self._peer: dict[Any, Any] = {}   # key -> device-resident pytree
        self._disk: dict[Any, str] = {}
        self._lease: Optional[Any] = None
        # tier transitions are cross-thread: a lease revocation arrives
        # on a dispatcher thread and migrates the peer tier while the
        # sweep thread is mid put/get.  RLock because _peer_down parks
        # through _park_low.  Ordering: store lock -> dispatcher lock
        # (reserve/release under this lock); the dispatcher never calls
        # back into the store while holding its own lock (on_revoke
        # fires outside it), so the ordering is acyclic.
        self._tlock = threading.RLock()
        self._writer = AsyncWriter()
        self._durable: set = set()
        self.peak_live = 0
        # cumulative bytes parked per tier (spill_bytes = peer + disk,
        # the pre-three-tier aggregate the CI compare gate keys on)
        self.tier_bytes = {"mem": 0, "peer": 0, "disk": 0}
        self.spill_bytes = 0
        self.parks = 0
        self.fetches = 0
        self.evacuations = 0

    def _path(self, key) -> str:
        return os.path.join(self.spill_dir, f"{self.prefix}_{key:05d}.npy")

    # -- peer tier (leased fleet lane) ------------------------------------ #

    def _ensure_lease(self):
        if self._lease is not None and not self._lease.released:
            return self._lease
        if self.dispatcher is None or self.peer_slots <= 0:
            return None
        lease = self.dispatcher.reserve_lane(
            tenant="adjoint.spill", on_revoke=self._on_revoke)
        if lease is None:
            # no lane to spare: don't re-ask on every park this sweep
            self.peer_slots = 0
            return None
        if lease.released or lease.revoked:
            # revoked during the handshake (a demand spike between the
            # grant and our adoption): stand down before parking
            # anything on a lane that is already serving again
            self.peer_slots = 0
            return None
        self._lease = lease
        return lease

    def _on_revoke(self, lease, reason: str) -> None:
        """Dispatcher reclaims the leased lane for serving: migrate
        every peer snapshot down the ladder before the lane resumes.
        The dispatcher releases the lease itself after this returns."""
        self._peer_down(f"revoked:{reason}", release=False)

    def _peer_down(self, reason: str, release: bool = True) -> None:
        with self._tlock:
            lease, self._lease = self._lease, None
            moved = list(self._peer.items())
            self._peer.clear()
            self.peer_slots = 0
            for k, parked in moved:
                host = jax.tree.map(np.asarray, parked)
                self._park_low(k, host)
                self.evacuations += 1
        telemetry.event("adjoint.spill_peer_down", reason=str(reason)[:200],
                        evacuated=len(moved))
        telemetry.counter("adjoint.spill_peer_down")
        if release and lease is not None and not lease.released:
            lease.release()

    def _park_low(self, key, tree) -> None:
        """Park below the peer tier: disk when configured, else host
        memory (overflowing ``mem_slots``, same as the two-tier store
        did without a spill dir — correctness over budget)."""
        if self.spill_dir is not None:
            path = self._path(key)
            self._disk[key] = path
            self._durable.discard(key)
            self._writer.submit(lambda: self._spill(key, path, tree))
        else:
            slot: dict = {}
            self._mem[key] = slot
            self.tier_bytes["mem"] += _tree_nbytes(tree)
            self._writer.submit(
                lambda: slot.update(v=jax.tree.map(np.asarray, tree)))

    def put(self, key, tree) -> None:
        """Park a snapshot down the tier ladder.  The pytree's leaves
        may be live device arrays: materialization happens on the writer
        thread for the disk tier (host copy for the memory tier is
        deferred the same way), so the caller returns immediately and
        keeps dispatching forward work; the peer tier's ``device_put``
        dispatches asynchronously for the same reason."""
        self.parks += 1
        with self._tlock:
            if len(self._mem) < self.mem_slots:
                slot: dict = {}
                self._mem[key] = slot
                self.tier_bytes["mem"] += _tree_nbytes(tree)
                self._writer.submit(
                    lambda: slot.update(
                        v=jax.tree.map(np.asarray, tree)))
            elif len(self._peer) < self.peer_slots \
                    and self._ensure_lease() is not None:
                lease = self._lease
                try:
                    faults.fire("adjoint.spill_d2d", key=int(key),
                                lane=lease.lane.index)
                    parked = jax.tree.map(
                        lambda x: jax.device_put(x, lease.device), tree)
                    self._peer[key] = parked
                    nb = _tree_nbytes(parked)
                    self.tier_bytes["peer"] += nb
                    self.spill_bytes += nb
                    telemetry.counter("adjoint.spill_d2d")
                except Exception as e:  # noqa: BLE001 - degrade to disk
                    self._peer_down(f"d2d_failed:{e!r}")
                    self._park_low(key, tree)
            else:
                self._park_low(key, tree)
            live = len(self._mem) + len(self._peer) + len(self._disk)
            self.peak_live = max(self.peak_live, live)

    def _spill(self, key, path: str, tree) -> None:
        from tclb_tpu.checkpoint import writer as ckw
        os.makedirs(self.spill_dir, exist_ok=True)
        flat, treedef = jax.tree.flatten(tree)
        host = [np.asarray(x) for x in flat]
        # one payload file: leaves stacked via savez-free raveled layout
        # is overkill here — revolve snapshots are (fields, globals_)
        # with fields dominating, so store fields as THE payload and the
        # small leaves in the sidecar-adjacent .meta file
        payload = host[0]
        rest = host[1:]
        data = ckw.npy_bytes(payload)
        # the disk tier shares checkpoint IO's chaos seam: `torn`
        # truncates the payload under an honest CRC sidecar, so the
        # verification machinery downstream is exercised, not faked
        mode = faults.fire("checkpoint.write",
                           file=os.path.basename(path))
        crc = zlib.crc32(data) & 0xFFFFFFFF
        if mode == "torn":
            ckw.atomic_write_bytes(path, data[:max(1, len(data) // 2)])
        else:
            ckw.atomic_write_bytes(path, data)
        ckw.atomic_write_bytes(path + ".crc", str(crc).encode())
        if rest:
            import io
            buf = io.BytesIO()
            np.savez(buf, *rest)
            ckw.atomic_write_bytes(path + ".meta", buf.getvalue())
        self._treedef = treedef
        self.tier_bytes["disk"] += len(data)
        self.spill_bytes += len(data)
        self._durable.add(key)

    def tier_of(self, key) -> Optional[str]:
        """Which tier currently holds ``key`` (None when not held)."""
        if key in self._mem:
            return "mem"
        if key in self._peer:
            return "peer"
        if key in self._disk:
            return "disk"
        return None

    def get(self, key):
        """Fetch a parked snapshot (host-side numpy pytree)."""
        self.fetches += 1
        with self._tlock:
            slot = self._mem.get(key)
            parked = self._peer.get(key)
        if slot is not None:
            if "v" not in slot:
                self._writer.wait()
            return slot["v"]
        if parked is not None:
            # D2H fetch of the exact parked bytes — no writer fence:
            # device_put ordering is the device stream's problem.  The
            # reference pinned under the lock stays valid even if a
            # concurrent revocation evacuates the peer tier right now.
            return jax.tree.map(np.asarray, parked)
        if key not in self._disk:
            raise KeyError(f"snapshot {key} not held")
        if key not in self._durable:
            self._writer.wait()   # the reverse-sweep fence
        from tclb_tpu.checkpoint import writer as ckw
        path = self._disk[key]
        payload = ckw.read_npy(path)
        leaves = [payload]
        if os.path.exists(path + ".meta"):
            with np.load(path + ".meta") as z:
                leaves += [z[k] for k in z.files]
        return jax.tree.unflatten(self._treedef, leaves)

    def free(self, key) -> None:
        with self._tlock:
            if self._mem.pop(key, None) is not None:
                return
            if self._peer.pop(key, None) is not None:
                return
            path = self._disk.pop(key, None)
        if path is not None:
            self._durable.discard(key)
            self._writer.wait()
            for p in (path, path + ".crc", path + ".meta"):
                if os.path.exists(p):
                    os.remove(p)

    def wait(self) -> None:
        """Drain the writer: every submitted park is durable after this
        returns (the reverse-sweep fence, exposed for tests/benches)."""
        self._writer.wait()

    def close(self) -> None:
        """Drain the writer, release the leased lane and delete every
        remaining spill file."""
        try:
            self._writer.wait()
        finally:
            self._peer.clear()
            if self._lease is not None and not self._lease.released:
                self._lease.release()
                self._lease = None
            for key in list(self._disk):
                try:
                    self.free(key)
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
            self._mem.clear()


# -- the gradient driver -------------------------------------------------- #

_last_gradient: dict = {}


def _status() -> dict:
    return dict(_last_gradient)


def _tree_add(a, b):
    """Pytree add that passes float0 (nondiff int leaves) through."""
    def add(x, y):
        if getattr(x, "dtype", None) == jax.dtypes.float0:
            return x
        return x + y
    return jax.tree.map(add, a, b)


def _zero_cot(x):
    """Zero cotangent for one leaf: float zeros for float leaves,
    ``float0`` for nondiff (integer) leaves — what ``jax.vjp`` expects
    as seed for outputs we do not differentiate."""
    if jnp.issubdtype(jnp.result_type(x), jnp.floating):
        return jnp.zeros_like(x)
    return np.zeros(np.shape(x), jax.dtypes.float0)


def make_revolve_gradient(model: Model, design, niter: int,
                          snapshots: Optional[int] = None,
                          action: str = "Iteration",
                          streaming: Optional[Streaming] = None,
                          engine: str = "auto",
                          shape: Optional[tuple] = None,
                          dtype=jnp.float32,
                          spill_dir: Optional[str] = None,
                          mem_slots: Optional[int] = None,
                          host_budget_bytes: Optional[float] = None,
                          dispatcher: Optional[Any] = None,
                          peer_slots: Optional[int] = None
                          ) -> Callable:
    """``grad_fn(theta, state, params) -> (objective, grads, final_state)``
    under a revolve schedule: peak live snapshots ≤ ``S``, total
    advanced units equal to the Griewank binomial optimum.

    ``snapshots=None`` lets :func:`auto_plan` pick S (and the tier
    split — memory, then peer-device HBM when a ``dispatcher`` with a
    sparable lane is given, then disk when ``spill_dir`` is given) from
    measured capacities.  Values are bit-identical to
    ``make_unsteady_gradient(levels=1)`` on the same engine AND
    invariant to the tier split: the unit step, the forward-ordered
    flat objective sum, the reverse-ordered cotangent accumulation and
    the final ``design.put`` VJP replicate that program's arithmetic
    order, and every tier round-trips exact array bytes."""
    from tclb_tpu.adjoint.run import _pick_engine, objective_weights

    step = _pick_engine(model, design, niter, engine, shape, action,
                        streaming, dtype)
    if step is None:
        step = make_action_step(model, action, streaming)
        chunk, returns_inc = 1, False
    else:
        chunk = int(getattr(step, "chunk", 1))
        returns_inc = bool(getattr(step, "returns_inc", False))
    if niter % chunk:
        raise ValueError(f"niter={niter} not divisible by chunk {chunk}")
    T = niter // chunk

    if snapshots is None:
        plan = auto_plan(model, shape or (), T, dtype=dtype,
                         host_budget_bytes=host_budget_bytes,
                         spill=spill_dir is not None,
                         dispatcher=dispatcher) if shape else \
            RevolvePlan(T, max(1, T), max(1, T), 0, binomial_bound(T, T))
        S = plan.snapshots
        mem = plan.mem_slots
        peer = plan.peer_slots
    else:
        S = max(1, int(snapshots))
        mem = S if mem_slots is None else int(mem_slots)
        peer = 0
    if peer_slots is not None:
        peer = max(0, int(peer_slots))
    if dispatcher is None:
        peer = 0
    schedule = revolve_schedule(T, S)

    def _units(state1, params1, w):
        step_fn = step.prepare(state1, params1) \
            if hasattr(step, "prepare") else step

        def body(fields, g0, it, params, wv):
            s = state1.replace(fields=fields, globals_=g0, iteration=it)
            if returns_inc:
                s2, ginc = step_fn(s, params)
                return s2.fields, s2.globals_, s2.iteration, \
                    jnp.sum(wv * ginc)
            s2 = step_fn(s, params)
            return s2.fields, s2.globals_, s2.iteration, \
                jnp.sum(wv * s2.globals_)

        @jax.jit
        def unit_fwd(fields, g0, it, params, wv):
            return body(fields, g0, it, params, wv)

        @jax.jit
        def unit_bwd(fields, g0, it, params, wv, cot_f, cot_g):
            def f(fs, gg, p, ww):
                f2, g2, _, inc = body(fs, gg, it, p, ww)
                return f2, g2, inc
            (f2, g2, inc), vjp = jax.vjp(f, fields, g0, params, wv)
            one = jnp.ones((), inc.dtype)
            cf, cg, cp, cw = vjp((cot_f, cot_g, one))
            return inc, cf, cg, cp, cw

        return unit_fwd, unit_bwd

    def grad_fn(theta, state: LatticeState, params: SimParams):
        (state1, params1), put_vjp = jax.vjp(
            lambda th: design.put(th, state, params), theta)
        w, w_vjp = jax.vjp(
            lambda p: objective_weights(model, p), params1)
        unit_fwd, unit_bwd = _units(state1, params1, w)

        store = SnapshotStore(mem, spill_dir=spill_dir,
                              peer_slots=peer, dispatcher=dispatcher)
        incs: list = [None] * T
        cur = (state1.fields, state1.globals_, state1.iteration)
        pos = 0
        advanced = 0
        final_state = state1
        cot_f = None
        cot_g = None
        cot_p = jax.tree.map(_zero_cot, params1)
        cot_w = jnp.zeros_like(w)
        g_theta = None
        with telemetry.span("adjoint.sweep", model=model.name,
                            mode="revolve",
                            horizon=T, chunk=chunk, snapshots=S,
                            mem_slots=mem, peer_slots=peer,
                            engine=getattr(step, "engine_name", "xla"),
                            bound=binomial_bound(T, S)) as sp:
            for act in schedule:
                if act[0] == "snapshot":
                    store.put(act[1], (cur[0], cur[1], cur[2]))
                elif act[0] == "restore":
                    if pos != act[1]:
                        f_, g_, it_ = store.get(act[1])
                        cur = (jnp.asarray(f_), jnp.asarray(g_),
                               jnp.asarray(it_))
                        pos = act[1]
                elif act[0] == "free":
                    store.free(act[1])
                elif act[0] == "advance":
                    _, j = act[1], act[2]
                    while pos < j:
                        f2, g2, it2, inc = unit_fwd(cur[0], cur[1],
                                                    cur[2], params1, w)
                        if incs[pos] is None:
                            incs[pos] = inc
                        cur = (f2, g2, it2)
                        advanced += 1
                        pos += 1
                        if pos == T:
                            final_state = state1.replace(
                                fields=f2, globals_=g2, iteration=it2)
                elif act[0] == "reverse":
                    t = act[1]
                    if cot_f is None:
                        # seed: the final unit still needs its primal
                        # run for the objective (the forward sweep stops
                        # at T-1); the vjp below provides both
                        cot_f = jnp.zeros_like(cur[0])
                        cot_g = jnp.zeros_like(cur[1])
                    inc, cot_f, cot_g, cp, cw = unit_bwd(
                        cur[0], cur[1], cur[2], params1, w, cot_f, cot_g)
                    if t == T - 1 and incs[t] is None:
                        incs[t] = inc
                        fin, gfin, itfin, _ = unit_fwd(
                            cur[0], cur[1], cur[2], params1, w)
                        final_state = state1.replace(
                            fields=fin, globals_=gfin, iteration=itfin)
                    cot_p = _tree_add(cot_p, cp)
                    cot_w = cot_w + cw
            obj = jnp.sum(jnp.stack(incs))
            cot_p = _tree_add(cot_p, w_vjp(cot_w)[0])
            cot_state1 = jax.tree.map(_zero_cot, state1)
            cot_state1 = cot_state1.replace(fields=cot_f, globals_=cot_g)
            (g_theta,) = put_vjp((cot_state1, cot_p))
            tiers = [t for t in ("mem", "peer", "disk")
                     if store.tier_bytes[t] > 0]
            sp.add(advances=advanced,
                   recompute_factor=round(advanced / max(T, 1), 4),
                   peak_snapshots=store.peak_live,
                   spill_bytes=store.spill_bytes,
                   spill_mem=store.tier_bytes["mem"],
                   spill_peer=store.tier_bytes["peer"],
                   spill_disk=store.tier_bytes["disk"],
                   evacuations=store.evacuations,
                   tiers=tiers)
        store.close()
        _last_gradient.update(
            model=model.name, horizon=T, snapshots=S,
            advances=advanced,
            recompute_factor=round(advanced / max(T, 1), 4),
            peak_snapshots=store.peak_live,
            spill_bytes=store.spill_bytes,
            spill_mem=store.tier_bytes["mem"],
            spill_peer=store.tier_bytes["peer"],
            spill_disk=store.tier_bytes["disk"],
            evacuations=store.evacuations,
            tiers=tiers,
            objective=float(obj),
            engine=getattr(step, "engine_name", "xla"))
        grad_fn.last = dict(_last_gradient)
        return obj, g_theta, final_state

    from tclb_tpu.telemetry import live as tlive
    tlive.register_status("adjoint", _status)
    grad_fn.engine_name = getattr(step, "engine_name", "xla")
    grad_fn.snapshots = S
    grad_fn.mem_slots = mem
    grad_fn.peer_slots = peer
    grad_fn.horizon = T
    grad_fn.bound = binomial_bound(T, S)
    return grad_fn
