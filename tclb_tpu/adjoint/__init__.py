"""Adjoint & optimization — the TPU-native replacement for the reference's
Tapenade machinery (reference tools/makeAD, src/ADTools.cu, the adjoint
branches of src/Lattice.cu.Rt and the optimization handlers of
src/Handlers.cpp.Rt:1571-2211).

Where the reference source-transforms the generated CUDA ``Run()`` into
``Run_b()`` and hand-manages a log-leveled snapshot tape (SnapLevel,
src/Lattice.cu.Rt:34-49), here the whole iteration is a differentiable JAX
program: ``jax.grad`` through a nested-checkpoint ``lax.scan`` reproduces the
reverse sweep with the same O(T^(1/levels)) memory/recompute trade, and the
"settings tape" (src/Lattice.cu.Rt:1048-1086) is free because parameters are
explicit function inputs.
"""

from tclb_tpu.adjoint.run import (nested_checkpoint_scan, objective_weights,
                                  make_objective_run, make_unsteady_gradient,
                                  make_spilled_gradient,
                                  make_steady_gradient, fd_test)
from tclb_tpu.adjoint.design import (ControlSecond, Design, InternalTopology, OptimalControl,
                                     Fourier, BSpline, RepeatControl,
                                     CompositeDesign, threshold_topology)
from tclb_tpu.adjoint.optimize import batched_descent, optimize
from tclb_tpu.adjoint.revolve import (RevolvePlan, SnapshotStore,
                                      auto_plan, binomial_bound,
                                      make_revolve_gradient,
                                      revolve_schedule)

__all__ = [
    "nested_checkpoint_scan", "objective_weights", "make_objective_run",
    "make_unsteady_gradient", "make_spilled_gradient",
    "make_steady_gradient", "fd_test",
    "Design", "InternalTopology", "OptimalControl", "Fourier", "BSpline",
    "RepeatControl", "CompositeDesign", "threshold_topology", "optimize",
    "batched_descent",
    "RevolvePlan", "SnapshotStore", "auto_plan", "binomial_bound",
    "make_revolve_gradient", "revolve_schedule",
]
