"""Design parameterizations: what the optimizer's vector theta means.

Parity target: the reference's "Design" handler family, which unifies very
different degrees of freedom behind one parameter-vector API
(``GetParameters``/``SetParameters``, reference src/Handlers.cpp.Rt:166-846):

* ``InternalTopology`` (:166) — per-node design densities masked by
  NODE_DesignSpace;
* ``OptimalControl``/``OptimalControlSecond`` (:201/:304) — a zonal
  setting's time series with bounds;
* ``Fourier`` (:431) — low-dimensional Fourier reparameterization of a
  control series;
* ``BSpline`` (:575) — B-spline control points (reference src/spline.h);
* ``RepeatControl`` (:727) — one period tiled over the horizon.

Every Design maps ``theta`` (a JAX pytree, usually one array) into the
(state, params) pair *inside* the differentiated function, so gradients
arrive already in theta-space — the reference needs explicit chain-rule
code per handler (e.g. Fourier's ``ToParameters``); here it is ``jax.grad``
through :meth:`Design.put`.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from tclb_tpu.core.lattice import FLAG_DTYPE, LatticeState, SimParams
from tclb_tpu.core.registry import Model


class Design:
    """theta <-> (state, params) mapping.  ``get`` extracts the current
    value (host side); ``put`` injects (traced, differentiable)."""

    def get(self, state: LatticeState, params: SimParams):
        raise NotImplementedError

    def put(self, theta, state: LatticeState, params: SimParams):
        raise NotImplementedError

    def bounds(self) -> tuple[Optional[float], Optional[float]]:
        return (None, None)


class InternalTopology(Design):
    """Per-node design densities (``parameter=True`` storage planes) masked
    by the DesignSpace node-type group (reference InternalTopology,
    src/Handlers.cpp.Rt:166-200: the parameter space is exactly the design
    field at NODE_DesignSpace nodes; bounds [0, 1])."""

    def __init__(self, model: Model, names: Optional[Sequence[str]] = None):
        self.model = model
        if names is None:
            names = [x.name for x in list(model.densities) + list(model.fields)
                     if x.parameter]
        if not names:
            raise ValueError(f"model {model.name} declares no parameter=True "
                             "densities/fields (no design space)")
        self.idx = tuple(model.storage_index[n] for n in names)
        self.names = tuple(names)

    def _mask(self, state: LatticeState) -> jnp.ndarray:
        m = self.model.group_masks["DESIGNSPACE"]
        return (state.flags & FLAG_DTYPE(m)) != FLAG_DTYPE(0)

    def get(self, state, params):
        return state.fields[jnp.asarray(self.idx)]

    def put(self, theta, state, params):
        mask = self._mask(state)[None]
        cur = state.fields[jnp.asarray(self.idx)]
        new = jnp.where(mask, theta, cur)
        return (state.replace(
            fields=state.fields.at[jnp.asarray(self.idx)].set(new)), params)

    def bounds(self):
        return (0.0, 1.0)


class OptimalControl(Design):
    """A zonal setting's time series as parameters (reference OptimalControl,
    src/Handlers.cpp.Rt:201-303).  The series must already exist in
    ``params`` (register via ``Lattice.set_setting_series`` or <Control>)."""

    def __init__(self, model: Model, setting: str, zone: int = 0,
                 lower: Optional[float] = None,
                 upper: Optional[float] = None):
        self.model = model
        self.sidx = model.setting_index[setting]
        self.zone = int(zone)
        self._bounds = (lower, upper)

    def _row(self, params: SimParams) -> int:
        for si, z, r in params.series_map:
            if si == self.sidx and z == self.zone:
                return r
        raise ValueError(
            f"no time series registered for setting index {self.sidx} "
            f"zone {self.zone}; call set_setting_series first")

    def get(self, state, params):
        return params.time_series[self._row(params)]

    def put(self, theta, state, params):
        r = self._row(params)
        return state, params.replace(
            time_series=params.time_series.at[r].set(theta))

    def bounds(self):
        return self._bounds


class Reparam(Design):
    """Base for low-dimensional reparameterizations of a control series:
    ``series = basis @ theta`` with a fixed (T, P) basis matrix."""

    def __init__(self, inner: OptimalControl, basis: np.ndarray):
        self.inner = inner
        self.basis = jnp.asarray(basis)

    def get(self, state, params):
        # least-squares pullback of the current series onto the basis
        series = np.asarray(self.inner.get(state, params))
        coef, *_ = np.linalg.lstsq(np.asarray(self.basis), series, rcond=None)
        return jnp.asarray(coef, dtype=series.dtype)

    def put(self, theta, state, params):
        series = self.basis.astype(theta.dtype) @ theta
        return self.inner.put(series, state, params)

    def bounds(self):
        return self.inner.bounds()


class Fourier(Reparam):
    """theta = (a0, a1, b1, ..., aK, bK) -> series via a truncated Fourier
    basis over the horizon (reference Fourier, src/Handlers.cpp.Rt:431-574)."""

    def __init__(self, inner: OptimalControl, horizon: int, modes: int):
        t = np.arange(horizon) * (2 * np.pi / horizon)
        cols = [np.ones(horizon)]
        for k in range(1, modes + 1):
            cols.append(np.cos(k * t))
            cols.append(np.sin(k * t))
        super().__init__(inner, np.stack(cols, axis=1))


class BSpline(Reparam):
    """theta = P control points -> series via uniform cubic B-spline basis
    (reference BSpline, src/Handlers.cpp.Rt:575-726, src/spline.h);
    ``periodic`` wraps the control polygon."""

    def __init__(self, inner: OptimalControl, horizon: int, points: int,
                 periodic: bool = False):
        B = np.zeros((horizon, points))
        def b3(u):  # cubic B-spline segments on [0,4)
            return np.where(
                u < 0, 0.0, np.where(
                    u < 1, u**3 / 6, np.where(
                        u < 2, (-3*(u-1)**3 + 3*(u-1)**2 + 3*(u-1) + 1) / 6,
                        np.where(
                            u < 3, (3*(u-2)**3 - 6*(u-2)**2 + 4) / 6,
                            np.where(u < 4, (1 - (u - 3))**3 / 6, 0.0)))))
        t = np.arange(horizon) / horizon
        if periodic:
            x = t * points
            for p in range(points):
                for wrap in (-points, 0, points):
                    B[:, p] += b3(x - (p + wrap) + 2)
        else:
            x = t * (points - 3)
            for p in range(points):
                B[:, p] = b3(x - p + 3)
            # normalize the open-end partition of unity
            B /= B.sum(axis=1, keepdims=True)
        super().__init__(inner, B)


class RepeatControl(Reparam):
    """theta = one period of length P tiled over the horizon (reference
    RepeatControl, src/Handlers.cpp.Rt:727-846)."""

    def __init__(self, inner: OptimalControl, horizon: int, period: int):
        B = np.zeros((horizon, period))
        B[np.arange(horizon), np.arange(horizon) % period] = 1.0
        super().__init__(inner, B)


class ControlSecond(Reparam):
    """Half-resolution control: theta holds every second sample; odd
    samples are the average of their neighbours (reference
    OptimalControlSecond, src/Handlers.cpp.Rt:304-430: PAR_SET places
    tab[i] at even indices and (tab[i]+tab[i+1])/2 between, PAR_GRAD is
    the transpose — which is exactly what differentiating this basis
    gives)."""

    def __init__(self, inner: OptimalControl, horizon: int):
        P = (horizon + 1) // 2
        B = np.zeros((horizon, P))
        for i in range(P):
            B[2 * i, i] = 1.0
            if 2 * i + 1 < horizon:
                if i + 1 < P:
                    B[2 * i + 1, i] = 0.5
                    B[2 * i + 1, i + 1] = 0.5
                else:
                    B[2 * i + 1, i] = 1.0
        super().__init__(inner, B)


class CompositeDesign(Design):
    """Concatenation of several designs into one theta tuple (the reference
    concatenates all design handlers' parameters into one NLopt vector,
    GenericOptimizer::Parameters, src/Handlers.cpp.Rt:1708-1775)."""

    def __init__(self, designs: Sequence[Design]):
        self.designs = tuple(designs)

    def get(self, state, params):
        return tuple(d.get(state, params) for d in self.designs)

    def put(self, theta, state, params):
        for d, th in zip(self.designs, theta):
            state, params = d.put(th, state, params)
        return state, params

    def bounds(self):
        return tuple(d.bounds() for d in self.designs)


def threshold_topology(model: Model, state: LatticeState,
                       level: float = 0.5) -> LatticeState:
    """Binarize topology design fields at ``level`` (reference
    acThreshold/acThresholdNow, src/Handlers.cpp.Rt:2100-2190)."""
    topo = InternalTopology(model)
    cur = topo.get(state, None)
    binary = jnp.where(cur > level, jnp.ones_like(cur), jnp.zeros_like(cur))
    state, _ = topo.put(binary, state, None)
    return state
