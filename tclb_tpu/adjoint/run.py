"""Differentiable objective runs: checkpointed scans + unsteady/steady
gradients + the finite-difference gradient check.

Parity targets:
* unsteady adjoint = reverse sweep over a recorded horizon with log-spaced
  state snapshots (reference acUSAdjoint, src/Handlers.cpp.Rt:1614-1662;
  SnapLevel tape, src/Lattice.cu.Rt:34-49, 723-770) — here
  :func:`nested_checkpoint_scan`: ``levels`` nested ``lax.scan``s with
  ``jax.checkpoint`` between them give O(levels * T^(1/levels)) stored states
  and the same recompute structure the reference's snapshot hierarchy has;
* steady adjoint = repeated adjoint iterations against the converged primal
  (reference acSAdjoint, src/Handlers.cpp.Rt:1664-1707, ITER_STEADY) — here
  :func:`make_steady_gradient`: a Neumann series of VJPs of one step at the
  fixed point;
* objective = the InObj-weighted sum of Globals (reference
  Lattice::calcGlobals, src/Lattice.cu.Rt:1113-1129), integrated over the
  horizon for unsteady runs;
* FDTest (reference acFDTest, src/Handlers.cpp.Rt:1944-2099) =
  :func:`fd_test`, central differences vs the adjoint gradient.
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree

from tclb_tpu.core.lattice import (LatticeState, SimParams, Streaming,
                                   make_action_step)
from tclb_tpu.core.registry import Model


def objective_weights(model: Model, params: SimParams) -> jnp.ndarray:
    """Per-Global weight vector from the ``<name>InObj`` settings
    (reference src/conf.R:212-216, Lattice::calcGlobals)."""
    idx = [model.setting_index[g.name + "InObj"] for g in model.globals_]
    return params.settings[jnp.asarray(idx, dtype=jnp.int32)]


def nested_checkpoint_scan(body: Callable, state: Any, niter: int,
                           levels: int = 2) -> tuple[Any, jnp.ndarray]:
    """Run ``state, inc = body(state)`` ``niter`` times, summing ``inc``,
    with ``levels`` nested remat scans.

    Memory for the backward pass is O(levels * niter^(1/levels)) carried
    states instead of O(niter) — the same trade the reference's log-leveled
    snapshot store makes (SnapLevel, src/Lattice.cu.Rt:34-49): inner segments
    are recomputed from their entry state during the reverse sweep.
    """
    if niter <= 0:
        return state, jnp.zeros(())
    if levels <= 1 or niter <= 4:
        def step(s, _):
            s2, inc = body(s)
            return s2, inc
        state, incs = lax.scan(step, state, None, length=niter)
        return state, jnp.sum(incs)
    chunk = max(2, int(round(niter ** (1.0 / levels))))
    n_outer, rem = divmod(niter, chunk)

    @jax.checkpoint
    def one_chunk(s):
        return nested_checkpoint_scan(body, s, chunk, levels - 1)

    def outer(s, _):
        s2, inc = one_chunk(s)
        return s2, inc

    total = jnp.zeros(())
    if n_outer:
        state, incs = lax.scan(outer, state, None, length=n_outer)
        total = total + jnp.sum(incs)
    if rem:
        state, inc = nested_checkpoint_scan(body, state, rem, levels - 1)
        total = total + inc
    return state, total


def make_objective_run(model: Model, niter: int, action: str = "Iteration",
                       streaming: Optional[Streaming] = None,
                       levels: int = 2,
                       step: Optional[Callable] = None) -> Callable:
    """``run(state, params) -> (objective, final_state)``: iterate ``niter``
    steps accumulating the InObj-weighted globals each step (time-integrated
    objective — what the reference's recorded-horizon adjoint measures).

    ``step`` overrides the engine: any differentiable
    ``(state, params) -> state`` with the per-step-globals contract (the
    Pallas diff step from :mod:`tclb_tpu.ops.pallas_adjoint` plugs in
    here).  A step advertising ``step.chunk = k`` advances ``k``
    iterations per call, so the scan runs ``niter // k`` bodies
    (``niter`` must divide); with ``step.returns_inc`` the step returns
    ``(state, chunk_globals)`` and the objective integrates the second
    value (state.globals_ keeps last-iteration semantics)."""
    if step is None:
        step = make_action_step(model, action, streaming)
    chunk = int(getattr(step, "chunk", 1))
    returns_inc = bool(getattr(step, "returns_inc", False))
    if niter % chunk:
        raise ValueError(f"niter={niter} not divisible by the engine "
                         f"chunk {chunk}")

    def run(state: LatticeState, params: SimParams):
        w = objective_weights(model, params)
        # engines with a prepare() hook bind their loop-invariant inputs
        # here, outside the scan (see pallas_adjoint.make_diff_step)
        step_fn = step.prepare(state, params) \
            if hasattr(step, "prepare") else step

        def body(s):
            if returns_inc:
                s2, ginc = step_fn(s, params)
                return s2, jnp.sum(w * ginc)
            s2 = step_fn(s, params)
            return s2, jnp.sum(w * s2.globals_)

        final, obj = nested_checkpoint_scan(body, state, niter // chunk,
                                            levels)
        return obj, final

    return run


def design_needs(design) -> Optional[set]:
    """What a design's ``put`` touches: a subset of
    ``{"state", "series"}``, or None for design types this classifier
    does not know (auto engine selection then falls back to XLA)."""
    from tclb_tpu.adjoint.design import (CompositeDesign, InternalTopology,
                                         OptimalControl, Reparam)
    if isinstance(design, InternalTopology):
        return {"state"}
    if isinstance(design, OptimalControl):
        return {"series"}
    if isinstance(design, Reparam):
        return design_needs(design.inner)
    if isinstance(design, CompositeDesign):
        out: set = set()
        for d in design.designs:
            n = design_needs(d)
            if n is None:
                return None
            out |= n
        return out
    return None


def _pick_engine(model: Model, design, niter: int, engine: str,
                 shape: Optional[tuple], action: str,
                 streaming, dtype=jnp.float32,
                 has_series: bool = False) -> Optional[object]:
    """Resolve ``engine`` ("auto"/"pallas"/"xla") to a diff step (or None
    for the XLA path).  The production auto-selection: the fused Pallas
    adjoint runs whenever it covers the configuration — the reference's
    adjoint is ALWAYS its tuned ``Run_b`` kernel (src/cuda.cu.Rt:240-256);
    XLA is the fallback, not the default."""
    import jax as _jax
    from tclb_tpu.ops import pallas_adjoint
    from tclb_tpu.utils import log
    if engine == "xla":
        return None
    if engine not in ("auto", "pallas"):
        raise ValueError(f"unknown adjoint engine {engine!r}")
    if shape is None:
        if engine == "pallas":
            raise ValueError("engine='pallas' needs the lattice shape")
        return None
    needs = design_needs(design)
    reasons = []
    if action != "Iteration":
        reasons.append(f"action {action!r}")
    if streaming is not None:
        reasons.append("custom streaming")
    if needs is None:
        reasons.append(f"unknown design type {type(design).__name__}")
    if _jax.default_backend() != "tpu" and engine != "pallas":
        # cheap check FIRST: skip the interpret-mode supports probe when
        # auto would fall back anyway
        reasons.append("not on TPU (interpret-mode kernels are slower "
                       "than XLA)")
    # series-mode kernels whenever the DESIGN differentiates the series
    # OR the params carry a fixed <Control> schedule (the per-step aux
    # must follow it either way); aux cotangents only for the former
    design_series = bool(needs and "series" in needs)
    series = design_series or has_series
    if not reasons and not pallas_adjoint.supports_diff(
            model, shape, dtype, series=series):
        # supports_diff rejects non-f32 dtypes, so double-precision
        # lattices fall back to the XLA engine here
        reasons.append(f"model/shape/dtype unsupported "
                       f"({model.name} {shape} {jnp.dtype(dtype).name})")
    k = 1 if series else pallas_adjoint.max_chunk(model)
    while k > 1 and niter % k:
        k -= 1
    if reasons:
        if engine == "pallas":
            raise ValueError("pallas adjoint unavailable: "
                             + "; ".join(reasons))
        log.debug("adjoint engine: XLA (" + "; ".join(reasons) + ")")
        return None
    step = pallas_adjoint.make_diff_step(model, shape, dtype, k=k,
                                         series=series,
                                         aux_grad=design_series)
    log.info(f"adjoint engine: {step.engine_name}")
    return step


def auto_levels(model: Model, shape, niter: int, chunk: int = 1,
                budget_bytes: float = 6e9, dtype=jnp.float32) -> int:
    """Pick the remat depth FOR THE CHUNKED PALLAS STEP: levels=1 (store
    every chunk input — NO recompute in the reverse sweep) whenever the
    stored states fit the budget, else nested remat.  The reference makes
    the same trade with its snapshot hierarchy (SnapLevel,
    src/Lattice.cu.Rt:34-49): disk is the fallback, full storage the
    fast path.  (The XLA step keeps levels=2: its un-remat'd reverse
    stores every stage temporary, far more than one state per body.)"""
    per = jnp.dtype(dtype).itemsize * model.n_storage * int(np.prod(shape))
    n_bodies = max(niter // max(chunk, 1), 1)
    if per * n_bodies <= budget_bytes:
        return 1
    return 2


def make_unsteady_gradient(model: Model, design, niter: int,
                           action: str = "Iteration",
                           streaming: Optional[Streaming] = None,
                           levels: Optional[int] = None,
                           engine: str = "auto",
                           shape: Optional[tuple] = None,
                           dtype=jnp.float32,
                           has_series: bool = False) -> Callable:
    """``grad_fn(theta, state, params) -> (objective, grads, final_state)``
    — reverse-mode sensitivity of the time-integrated objective with respect
    to the design vector (reference unsteady adjoint + parameter gather,
    acUSAdjoint / GetParameters, src/Handlers.cpp.Rt:1614-1713).

    ``design`` is a :class:`tclb_tpu.adjoint.design.Design`: ``theta`` is
    injected into (state, params) inside the differentiated function, so the
    gradient flows to exactly the declared degrees of freedom.

    ``engine`` selects the step implementation: ``"auto"`` (default) runs
    BOTH sweeps on the fused Pallas kernels whenever they cover the
    model/shape/design — forward = the generic engine's in-kernel-globals
    flavor fused ``k`` steps per band pass, backward = the in-band VJP of
    the same chain (the TPU analogue of the reference's Tapenade-generated
    ``Run_b`` device kernel, src/cuda.cu.Rt:240-256, including its
    settings tape ``DynamicsS_b`` for Control-series designs) — and falls
    back to the XLA step otherwise.  ``"pallas"`` insists (raising when
    unsupported, ``shape`` required); ``"xla"`` forces the fallback.

    ``levels=None`` picks the remat depth automatically: no-recompute
    (levels=1) when the stored chunk inputs fit in HBM."""
    step = _pick_engine(model, design, niter, engine, shape, action,
                        streaming, dtype, has_series)
    if levels is None:
        # no-recompute tape only for the custom_vjp chunk step (its
        # backward stores nothing beyond the chunk inputs); the XLA step
        # keeps the nested-remat default
        levels = auto_levels(model, shape, niter, step.chunk,
                             dtype=dtype) if step is not None else 2
    run = make_objective_run(model, niter, action, streaming, levels,
                             step=step)

    def loss(theta, state: LatticeState, params: SimParams):
        state, params = design.put(theta, state, params)
        obj, final = run(state, params)
        return obj, final

    vg = jax.value_and_grad(loss, has_aux=True)

    def grad_fn(theta, state, params):
        (obj, final), g = vg(theta, state, params)
        return obj, g, final

    jitted = jax.jit(grad_fn)

    def wrapped(theta, state, params):
        return jitted(theta, state, params)

    wrapped.engine_name = getattr(step, "engine_name", "xla")
    return wrapped


def make_spilled_gradient(model: Model, design, niter: int, segment: int,
                          action: str = "Iteration",
                          streaming: Optional[Streaming] = None,
                          levels: int = 1,
                          spill_dir: Optional[str] = None) -> Callable:
    """Unsteady gradient with HOST (or disk) snapshot spill for horizons
    whose in-HBM remat tree does not fit.

    The reference spills snapshot levels >= nSnaps to disk
    (``_Snap_PP_LL.dat``, src/Lattice.cu.Rt:735-765) so the reverse sweep
    of an arbitrarily long horizon needs only O(segment) device memory.
    Same structure here: the forward pass runs segment-by-segment, parking
    each segment's entry fields on the host (numpy) or on disk
    (``spill_dir``); the reverse sweep walks the segments backward,
    re-running each with ``jax.vjp`` (in-segment remat via ``levels``) and
    chaining the fields cotangent across the segment boundary.  Device
    memory is O(one segment's remat tree); host/disk holds
    ``ceil(niter/segment)`` field stacks.

    Exactly equals :func:`make_unsteady_gradient` (same time-integrated
    InObj objective; ``design.put`` re-applied per segment is identity on
    the carried design planes, so no contribution is double-counted —
    the put overwrite zeroes the state cotangent on the design region).

    Returns ``grad_fn(theta, state, params) -> (objective, grads,
    final_state)``.

    Snapshot parking is ASYNCHRONOUS: each segment's entry fields are
    handed to the one-in-flight checkpoint writer
    (:class:`tclb_tpu.checkpoint.writer.AsyncWriter` via
    :class:`tclb_tpu.adjoint.revolve.SnapshotStore`), whose thread does
    the device→host copy and the (atomic, CRC-sidecarred) file write
    while the main thread keeps dispatching the next segment — the
    forward wall only fences at reverse-sweep fetch.  The CI spill gate
    asserts the <5% forward-overhead budget via
    ``telemetry report --compare`` (same gate as async checkpoint
    saves), and the kill-resume step asserts a SIGKILL mid-run leaves
    only CRC-valid spill files.
    """
    if segment <= 0:
        raise ValueError("segment must be positive")
    lengths = [segment] * (niter // segment)
    if niter % segment:
        lengths.append(niter % segment)

    def _seg_run(theta, fields, state_t, params, length):
        state = state_t.replace(fields=fields)
        state, params2 = design.put(theta, state, params)
        run = make_objective_run(model, length, action, streaming, levels)
        obj, final = run(state, params2)
        return obj, final

    @partial(jax.jit, static_argnames=("length",))
    def seg_fwd(theta, fields, state_t, params, length):
        obj, final = _seg_run(theta, fields, state_t, params, length)
        return obj, final

    @partial(jax.jit, static_argnames=("length",))
    def seg_bwd(theta, fields, state_t, params, length, cot_fields):
        def loss(th, fs):
            obj, final = _seg_run(th, fs, state_t, params, length)
            return obj, final.fields
        (obj, _), vjp = jax.vjp(loss, theta, fields)
        g_th, g_fs = vjp((jnp.ones_like(obj), cot_fields))
        return obj, g_th, g_fs

    def grad_fn(theta, state: LatticeState, params: SimParams):
        from tclb_tpu import telemetry
        from tclb_tpu.adjoint.revolve import SnapshotStore
        # memory tier when spill_dir is None, pure disk tier otherwise;
        # parking never blocks the solve thread either way (the writer
        # thread materializes the device arrays)
        store = SnapshotStore(
            mem_slots=len(lengths) if spill_dir is None else 0,
            spill_dir=spill_dir)
        fields = state.fields
        it = state.iteration
        iters = []
        final = None
        with telemetry.span("adjoint.sweep", model=model.name,
                            mode="spill", segments=len(lengths),
                            niter=int(niter), snapshots=len(lengths),
                            spill_dir=spill_dir or "host") as sp:
            try:
                # forward: park each segment's entry fields off-device;
                # the async writer overlaps the park with this segment's
                # forward dispatch
                for k, length in enumerate(lengths):
                    store.put(k, fields)
                    iters.append(it)
                    _, final = seg_fwd(theta, fields,
                                       state.replace(iteration=it),
                                       params, length)
                    fields, it = final.fields, final.iteration
                # final carries the LAST step's globals_ — same contract
                # as make_unsteady_gradient's final_state
                final_state = final if final is not None else state

                # reverse: chain the fields cotangent across segment
                # boundaries (store.get fences the writer on first use)
                cot = jnp.zeros_like(fields)
                g_total = None
                obj_total = 0.0
                for k in reversed(range(len(lengths))):
                    fk = jnp.asarray(store.get(k))
                    obj_k, g_th, cot = seg_bwd(
                        theta, fk, state.replace(iteration=iters[k]),
                        params, lengths[k], cot)
                    obj_total += float(obj_k)
                    g_total = g_th if g_total is None else \
                        jax.tree_util.tree_map(jnp.add, g_total, g_th)
                sp.add(recompute_factor=1.0,
                       peak_snapshots=store.peak_live,
                       spill_bytes=store.spill_bytes)
            finally:
                # spilled snapshots can be GBs each — never leak them,
                # even when the reverse sweep dies (OOM/interrupt)
                store.close()
        return obj_total, g_total, final_state

    return grad_fn


def make_steady_gradient(model: Model, design, n_adjoint: int = 100,
                         action: str = "Iteration",
                         streaming: Optional[Streaming] = None,
                         tol: float = 1e-10, strict: bool = False,
                         engine: str = "auto",
                         shape: Optional[tuple] = None,
                         dtype=jnp.float32,
                         has_series: bool = False) -> Callable:
    """Fixed-point (steady) adjoint: with the primal converged, solve
    ``lambda = A^T lambda + dJ/ds`` by ``n_adjoint`` adjoint iterations
    (the Neumann series of VJPs of one step) and return
    ``dJ/dtheta = dJ_partial/dtheta + sum_k (A^T)^k dJ/ds . dF/dtheta``
    — exactly the reference's repeated ``Iteration_Adj`` with ITER_STEADY
    against a frozen primal state (acSAdjoint, src/Handlers.cpp.Rt:1664).

    ``grad_fn(theta, state, params) -> (objective, grads)`` where the
    objective is the InObj-weighted globals of ONE step at the fixed point.
    The Neumann series stops early once the adjoint increment norm drops
    below ``tol`` (relative to the accumulated lambda norm) and the final
    residual is checked on the host: a series still far from converged
    after ``n_adjoint`` passes warns (or raises with ``strict=True``)
    instead of returning a silently wrong gradient (the reference leaves
    the iteration count to the user's XML loop,
    src/Handlers.cpp.Rt:1664-1707 — here convergence is reported).

    ``engine="auto"`` (with ``shape``) runs each adjoint pass on the
    fused Pallas kernels at chunk 1 (the Neumann series applies ONE
    step's transpose per pass); XLA otherwise.  ``has_series=True``
    includes a fixed ``<Control>`` schedule in the engine decision (the
    per-step aux planes must follow it); a series showing up in
    ``params`` at call time without it falls back to the XLA step for
    that call instead of failing at trace time.
    """
    step = _pick_engine(model, design, 1, engine, shape, action, streaming,
                        dtype, has_series)
    step_is_series = ",series" in getattr(step, "engine_name", "")

    def _tree_norm(t) -> jnp.ndarray:
        return jnp.sqrt(sum(jnp.vdot(x, x).real
                            for x in jax.tree_util.tree_leaves(t)) + 1e-300)

    def _build(step_):
        returns_inc = bool(getattr(step_, "returns_inc", False))
        if step_ is None:
            step_ = make_action_step(model, action, streaming)

        def one_step(theta, fields, state, params):
            state, params = design.put(theta, state.replace(fields=fields),
                                       params)
            w = objective_weights(model, params)
            if returns_inc:
                s2, ginc = step_(state, params)
                return s2.fields, jnp.sum(w * ginc)
            s2 = step_(state, params)
            return s2.fields, jnp.sum(w * s2.globals_)

        @jax.jit
        def _run(theta, state: LatticeState, params: SimParams):
            fields = state.fields
            (new_fields, obj), vjp = jax.vjp(
                lambda th, fs: one_step(th, fs, state, params), theta,
                fields)
            # seed: dJ/d(output objective) = 1, dJ/d(output fields) = 0
            zero_f = jnp.zeros_like(new_fields)
            g_theta0, lam = vjp((zero_f, jnp.ones_like(obj)))

            # Neumann iterations: propagate lambda back through A^T,
            # accumulating the theta-cotangent each pass.  Convergence is
            # measured on what the caller consumes — the GRADIENT
            # increment ||dth|| relative to the accumulated gradient norm
            # — not on lambda (which can decay much more slowly than its
            # projection onto the design space).
            def cond(carry):
                _, acc, k, rel_inc = carry
                return (k < n_adjoint) & (rel_inc > tol)

            def body(carry):
                lam, acc, k, _ = carry
                dth, dlam = vjp((lam, jnp.zeros_like(obj)))
                acc = jax.tree_util.tree_map(jnp.add, acc, dth)
                rel_inc = _tree_norm(dth) / jnp.maximum(_tree_norm(acc),
                                                        1e-30)
                return (dlam, acc, k + 1, rel_inc)

            lam_f, g_theta, k, rel_inc = lax.while_loop(
                cond, body,
                (lam, g_theta0, jnp.zeros((), jnp.int32), jnp.ones(())))
            return obj, g_theta, k, rel_inc

        return _run

    _runs = {"main": _build(step)}

    def grad_fn(theta, state: LatticeState, params: SimParams):
        key = "main"
        if (params.time_series is not None and step is not None
                and not step_is_series):
            # the engine was picked without series knowledge (the
            # historical trace-time ValueError): run this call on the
            # XLA step instead of dropping the schedule
            if "xla" not in _runs:
                from tclb_tpu.utils import log
                log.info("steady adjoint: params carry a Control series "
                         "but the engine was built without one — XLA "
                         "fallback (pass has_series=True to keep the "
                         "Pallas engine)")
                _runs["xla"] = _build(None)
            key = "xla"
        obj, g_theta, k, rel_inc = _runs[key](theta, state, params)
        inc_v, k_v = float(rel_inc), int(k)
        if not np.isfinite(inc_v):
            raise FloatingPointError(
                "steady adjoint diverged: the primal state is not a stable "
                f"fixed point (gradient increment {inc_v} after {k_v} passes)")
        if k_v >= n_adjoint and inc_v > 1e-4:
            msg = (f"steady adjoint not fully converged: relative gradient "
                   f"increment {inc_v:.3e} after {k_v} passes — the "
                   "gradient is approximate (raise n_adjoint or converge "
                   "the primal further)")
            if strict:
                raise RuntimeError(msg)
            warnings.warn(msg, RuntimeWarning, stacklevel=2)
        return obj, g_theta

    return grad_fn


def fd_test(loss: Callable, grad: Any, theta: Any, n_checks: int = 5,
            eps: float = 1e-5, seed: int = 0) -> list[dict]:
    """Central-difference check of an adjoint gradient at ``n_checks``
    random components (reference acFDTest, src/Handlers.cpp.Rt:1944-2099).

    ``loss(theta) -> scalar``; ``grad`` is the analytic gradient pytree with
    ``theta``'s structure.  Returns one record per probed component with the
    analytic value, the FD value and the relative error.
    """
    flat, unravel = ravel_pytree(theta)
    gflat, _ = ravel_pytree(grad)
    rng = np.random.default_rng(seed)
    idx = rng.choice(flat.shape[0], size=min(n_checks, flat.shape[0]),
                     replace=False)
    out = []
    for i in idx:
        e = jnp.zeros_like(flat).at[i].set(eps)
        fp = float(loss(unravel(flat + e)))
        fm = float(loss(unravel(flat - e)))
        fd = (fp - fm) / (2 * eps)
        an = float(gflat[i])
        denom = max(abs(fd), abs(an), 1e-300)
        out.append({"index": int(i), "adjoint": an, "fd": fd,
                    "rel_err": abs(fd - an) / denom})
    return out
