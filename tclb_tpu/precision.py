"""Error-vs-reference harness for the mixed-precision storage ladder.

The bf16 storage knob (``Lattice(storage_dtype=jnp.bfloat16)``) trades
mantissa for HBM bytes, so its contract is NOT bit-parity — it is a
bounded drift from the f32 reference.  This module is that contract
made executable: run the same case twice (f32 storage vs narrowed
storage, identical flags/settings/engine dispatch rules), measure
relative L2/Linf error of the full distribution-field stack at fixed
iteration checkpoints, and compare against :data:`ERROR_BOUNDS`.

Reference TCLB treats precision as a compile-time build flavor
(``CALC_DOUBLE_PRECISION``); a per-run knob needs a per-run safety
net instead of a build matrix — this harness runs in CI on CPU
(``python -m tclb_tpu.precision``) and tests/test_precision.py asserts
the bounds, so a kernel change that silently degrades the bf16 path
(e.g. an accumulation slipping to storage dtype past the static
``precision.unsafe_accum`` check) fails the build.

Bounds are measured on the CPU XLA path at 500 steps (bf16 round trips
once per step there — the *worst* case: the fused Pallas engines
narrow once per K steps, so device error is at or below these bounds)
with ~2x headroom over observed error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Optional, Sequence

import numpy as np

# checkpoints: error growth is roughly sqrt(t) (random-walk rounding),
# so a mid-run sample catches a superlinear blowup the endpoint alone
# would misattribute
DEFAULT_CHECKPOINTS = (100, 250, 500)

# measured (CPU, XLA path, 64x64, 500 steps) 2026-08, keyed
# (case, storage dtype, storage repr).  raw: cavity peaks at l2 5.2e-3 /
# linf 1.6e-2 (iter 250, then plateaus); kuper_drop at l2 1.2e-2 /
# linf 5.0e-2 (the drop interface is a steep phi gradient — pointwise
# error concentrates there).  shifted (DDF shifting, stores f_i - w_i):
# the O(1) rest-equilibrium background no longer eats the bf16
# mantissa, so the low-Mach cavity collapses ~40x (measured l2 1.3e-4 /
# linf 4.0e-4; u_linf 1.5e-2 vs raw's 5.9e-1 — the Mach-independence
# headline).  kuper_drop is same-order on the bounded field norms: the
# drop's O(1) density deviation (rho ~3.26 in liquid) dwarfs the w_i
# background (measured l2 2.3e-2 / linf 1.2e-1; its informational
# spurious-current u_linf runs a transient ~12x raw at iter 100,
# settling to ~4x) — the field contract is what lets shifted be the
# blanket default narrow rung.  Bounds carry ~2x headroom.
ERROR_BOUNDS = {
    ("cavity", "bfloat16", "raw"): {"l2": 1.2e-2, "linf": 3.5e-2},
    ("kuper_drop", "bfloat16", "raw"): {"l2": 2.5e-2, "linf": 1.0e-1},
    ("cavity", "bfloat16", "shifted"): {"l2": 3.0e-4, "linf": 1.0e-3},
    ("kuper_drop", "bfloat16", "shifted"): {"l2": 5.0e-2,
                                            "linf": 2.5e-1},
}

CASE_NAMES = ("cavity", "kuper_drop")
REPR_NAMES = ("raw", "shifted")


def build_case(name: str, n: int = 64):
    """A ready-to-init :class:`Lattice` builder for one harness case.

    Returns ``(model, shape, settings, flags, zonal)`` — the caller
    constructs the Lattice so it can thread ``storage_dtype``.

    * ``cavity`` — the d2q9 driven cavity/channel family the bench's
      karman case uses: walls top/bottom, WVelocity inflow, EPressure
      outflow, a square obstacle (boundary dispatch + MRT bulk).
    * ``kuper_drop`` — the d2q9_kuper drop.xml physics: a liquid drop
      (zone-1 Density) equilibrating in vapor; exercises the
      CalcPhi gradient stencil double-stage the fused kuper kernel
      collapses.
    """
    from tclb_tpu.models import get_model
    if name == "cavity":
        m = get_model("d2q9")
        settings = {"nu": 0.05, "Velocity": 0.02}
        flags = np.full((n, n), m.flag_for("MRT"), dtype=np.uint16)
        flags[:, 0] = m.flag_for("WVelocity", "MRT")
        flags[:, -1] = m.flag_for("EPressure", "MRT")
        flags[0, :] = flags[-1, :] = m.flag_for("Wall")
        q = n // 4
        flags[q:q + q // 2, q:q + q // 2] = m.flag_for("Wall")
        return m, (n, n), settings, flags, {}
    if name == "kuper_drop":
        m = get_model("d2q9_kuper")
        settings = {"omega": 1.0, "Temperature": 0.56, "FAcc": 1.0,
                    "Magic": 0.01, "MagicA": -0.152,
                    "MagicF": -2.0 / 3.0,
                    "Density": 3.2600529440452366}
        zonal = {("Density", 1): 0.014500641645077492}
        flags = np.full((n, n), m.flag_for("MRT"), dtype=np.uint16)
        yy, xx = np.mgrid[0:n, 0:n]
        drop = (yy - n / 2) ** 2 + (xx - n / 2) ** 2 < (n / 5) ** 2
        flags[drop] = m.flag_for("MRT", zone=1)
        return m, (n, n), settings, flags, zonal
    raise ValueError(f"unknown precision case {name!r}; "
                     f"have {CASE_NAMES}")


def _run(name: str, n: int, niter: int, storage_dtype,
         checkpoints: Sequence[int], storage_repr: Optional[str] = None):
    """(field stack, velocity) as f64 numpy at each checkpoint.

    Field stacks come through :meth:`Lattice.fields_raw`, so a shifted
    run and its raw reference are compared in the same (raw)
    representation — the norms measure physics drift, not the at-rest
    encoding."""
    import jax.numpy as jnp
    from tclb_tpu.core.lattice import Lattice
    model, shape, settings, flags, zonal = build_case(name, n)
    lat = Lattice(model, shape, dtype=jnp.float32, settings=settings,
                  storage_dtype=storage_dtype, storage_repr=storage_repr)
    for (sname, zone), val in zonal.items():
        lat.set_setting(sname, val, zone=zone)
    lat.set_flags(flags)
    lat.init()
    out, prev = {}, 0
    for it in sorted(set(int(c) for c in checkpoints) | {int(niter)}):
        if it > niter:
            break
        if it > prev:
            lat.iterate(it - prev)
        prev = it
        out[it] = (lat.fields_raw(),
                   np.asarray(lat.get_quantity("U"), dtype=np.float64))
    return out


def _norm_rows(ref: dict, alt: dict) -> list:
    rows = []
    for it in sorted(ref):
        (r, ru), (a, au) = ref[it], alt[it]
        d = a - r
        du = au - ru
        rnorm = float(np.linalg.norm(r))
        rmax = float(np.max(np.abs(r)))
        rows.append({
            "iteration": it,
            "l2": float(np.linalg.norm(d)) / max(rnorm, 1e-30),
            "linf": float(np.max(np.abs(d))) / max(rmax, 1e-30),
            "u_l2": float(np.linalg.norm(du))
            / max(float(np.linalg.norm(ru)), 1e-30),
            "u_linf": float(np.max(np.abs(du)))
            / max(float(np.max(np.abs(ru))), 1e-30),
        })
    return rows


def error_norms(case: str = "cavity", niter: int = 500, n: int = 64,
                storage_dtype: Any = "bfloat16",
                storage_repr: str = "raw",
                checkpoints: Sequence[int] = DEFAULT_CHECKPOINTS) -> dict:
    """Relative L2/Linf error of narrowed-storage vs f32-storage runs.

    Both runs use the normal engine dispatch (on CPU that is the XLA
    step — the worst-case once-per-step narrowing).  Norms are over the
    whole distribution-field stack in the *raw* representation
    (shifted runs are un-shifted before differencing):
    ``l2 = ||a - r|| / ||r||``, ``linf = max|a - r| / max|r|``.

    Each row also reports the same norms over the velocity quantity
    (``u_l2``/``u_linf``) — these are informational, not bounded.
    Raw distributions carry an O(1) rest-equilibrium background, so
    with ``storage_repr="raw"`` bf16 quantization injects
    ~``2**-8 * max|f|`` of absolute noise per round trip; relative to a
    low-Mach velocity signal that amplifies by ``max|f|/max|u|``
    (~20-50x at Ma~0.02).  With ``storage_repr="shifted"`` the stored
    value is the deviation ``f_i - w_i``, the mantissa goes to the
    signal, and the u norms become Mach-independent — which is why
    shifted is the default narrow rung (see README "The storage
    ladder").
    """
    ref = _run(case, n, niter, None, checkpoints)
    alt = _run(case, n, niter, storage_dtype, checkpoints,
               storage_repr=storage_repr)
    return {"case": case, "storage_dtype": str(np.dtype(storage_dtype)),
            "storage_repr": storage_repr, "shape": [n, n],
            "niter": int(niter), "checkpoints": _norm_rows(ref, alt)}


def compare_reprs(case: str = "cavity", niter: int = 500, n: int = 64,
                  storage_dtype: Any = "bfloat16",
                  checkpoints: Sequence[int] = DEFAULT_CHECKPOINTS,
                  ) -> list[dict]:
    """Raw and shifted reports for one case off a *shared* f32
    reference run — the side-by-side ``--repr both`` column pair."""
    ref = _run(case, n, niter, None, checkpoints)
    out = []
    for repr_ in REPR_NAMES:
        alt = _run(case, n, niter, storage_dtype, checkpoints,
                   storage_repr=repr_)
        out.append({"case": case,
                    "storage_dtype": str(np.dtype(storage_dtype)),
                    "storage_repr": repr_, "shape": [n, n],
                    "niter": int(niter),
                    "checkpoints": _norm_rows(ref, alt)})
    return out


def check_bounds(report: dict,
                 bounds: Optional[dict] = None) -> list[str]:
    """Violation strings (empty = within contract).  Every checkpoint
    must satisfy the case's bound — error growing past the bound
    mid-run then drifting back would still be a broken ladder."""
    key = (report["case"], report["storage_dtype"],
           report.get("storage_repr", "raw"))
    bound = (bounds if bounds is not None else ERROR_BOUNDS).get(key)
    if bound is None:
        return [f"no documented error bound for {key}"]
    out = []
    for row in report["checkpoints"]:
        for norm in ("l2", "linf"):
            if row[norm] > bound[norm]:
                out.append(
                    f"{report['case']} @ iter {row['iteration']}: "
                    f"{norm}={row[norm]:.3e} exceeds bound "
                    f"{bound[norm]:.1e}")
    return out


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tclb_tpu.precision",
        description="bf16 storage-ladder error harness vs f32 reference")
    p.add_argument("--case", choices=CASE_NAMES + ("all",), default="all")
    p.add_argument("--niter", type=int, default=500)
    p.add_argument("--n", type=int, default=64,
                   help="lattice edge length (default 64)")
    p.add_argument("--storage-dtype", default="bfloat16")
    p.add_argument("--repr", dest="repr_", metavar="REPR",
                   choices=REPR_NAMES + ("both",), default="both",
                   help="storage representation to measure; 'both' "
                   "(default) prints the raw/shifted column pair off "
                   "one shared f32 reference")
    p.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)

    cases = CASE_NAMES if args.case == "all" else (args.case,)
    reports, violations = [], []
    for case in cases:
        if args.repr_ == "both":
            reps = compare_reprs(case, niter=args.niter, n=args.n,
                                 storage_dtype=args.storage_dtype)
        else:
            reps = [error_norms(case, niter=args.niter, n=args.n,
                                storage_dtype=args.storage_dtype,
                                storage_repr=args.repr_)]
        reports += reps
        for rep in reps:
            violations += check_bounds(rep)
    if args.format == "json":
        print(json.dumps({"reports": reports, "violations": violations},
                         indent=2))
    else:
        _print_text(reports)
        for v in violations:
            print("VIOLATION:", v)
        if not violations:
            print("all error bounds hold")
    return 1 if violations else 0


def _print_text(reports: list) -> None:
    """Per-case blocks; when both representations of a case are present
    they print as a side-by-side column pair (the low-Mach cavity u
    norms are the headline comparison)."""
    by_case: dict = {}
    for rep in reports:
        by_case.setdefault(rep["case"], []).append(rep)
    for case, reps in by_case.items():
        head = f"{case} ({reps[0]['storage_dtype']} storage, " \
               f"{reps[0]['shape'][0]}x{reps[0]['shape'][1]})"
        if len(reps) == 1:
            rep = reps[0]
            print(f"{head}, repr={rep['storage_repr']}:")
            for row in rep["checkpoints"]:
                print(f"  iter {row['iteration']:>5}  "
                      f"l2 {row['l2']:.3e}  linf {row['linf']:.3e}  "
                      f"(u: l2 {row['u_l2']:.3e}  "
                      f"linf {row['u_linf']:.3e})")
            continue
        cols = {rep["storage_repr"]: rep for rep in reps}
        print(f"{head}:")
        print(f"  {'':>10}  {'---- raw ----':^25}  "
              f"{'-- shifted --':^25}")
        print(f"  {'':>10}  {'linf':^11} {'u_linf':^12}  "
              f"{'linf':^11} {'u_linf':^12}")
        rows = zip(cols["raw"]["checkpoints"],
                   cols["shifted"]["checkpoints"])
        for rr, rs in rows:
            print(f"  iter {rr['iteration']:>5}  "
                  f"{rr['linf']:.3e}  {rr['u_linf']:.3e}   "
                  f"{rs['linf']:.3e}  {rs['u_linf']:.3e}")


if __name__ == "__main__":
    sys.exit(main())
