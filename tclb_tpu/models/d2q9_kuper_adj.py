"""d2q9_kuper_adj — Kupershtokh multiphase with adjoint design.

Behavioral parity target: reference model ``d2q9_kuper_adj``
(reference src/d2q9_kuper_adj/Dynamics.R, ADJOINT=1, with its eq.R
derivation data): d2q9_kuper plus a per-node design density ``wd`` scaling
the local interaction strength — the differentiable handle for
wetting/phase-distribution optimization.  The whole two-stage step is
differentiable here, so the Tapenade tape of the reference is unnecessary.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import d2q9_kuper


def _def():
    d = d2q9_kuper._def()
    d.name = "d2q9_kuper_adj"
    d.description = "Kupershtokh multiphase with design field"
    d.add_density("wd", group="wd", parameter=True)
    d.add_quantity("WD")
    d.add_quantity("WDB", adjoint=True)
    return d


def calc_phi(ctx: NodeCtx):
    out = d2q9_kuper.calc_phi(ctx)
    # design field scales the local pseudopotential (interaction strength)
    return {"phi": out["phi"] * ctx.density("wd")}


def init(ctx: NodeCtx):
    out = d2q9_kuper.init(ctx)   # write-set dict
    return {**out, "wd": jnp.ones(ctx.flags.shape, ctx._fields.dtype)}


def build():
    wq = lambda c: c.density("wd")        # noqa: E731
    return _def().finalize().bind(
        run=d2q9_kuper.run, init=init,
        stages={"CalcPhi": calc_phi},
        quantities={"Rho": lambda c: jnp.sum(c.group("f"), axis=0),
                    "U": d2q9_kuper.get_u, "P": d2q9_kuper.get_p,
                    "F": d2q9_kuper.get_f, "WD": wq, "WDB": wq})
