"""d2q9_kuper — Kupershtokh pseudopotential multiphase (phase change).

Behavioral parity target: reference model ``d2q9_kuper``
(reference src/d2q9_kuper/Dynamics.R, Dynamics.c.Rt): two-stage iteration —
``CalcPhi`` computes the pseudopotential
``phi = FAcc sqrt(rho/3 - Magic p_vdW(rho, T))`` from the streamed density
(src/d2q9_kuper/Dynamics.c.Rt:290-321), then ``Run`` assembles the
Kupershtokh exact-difference force from neighbor phi
(:57-127: ``R_i = A phi_i^2 + (1-2A) phi_i phi_0``, shell weights
(1, 1/4)), and collides with a settings-driven MRT.  The ``phi`` Field with
a +-1 stencil exercises the framework's non-streamed neighbor access
(reference AddField stencil2d=1, src/d2q9_kuper/Dynamics.R:12).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, M, OPP, _equilibrium
from tclb_tpu.ops import lbm

W = lbm.weights(E)
# shell force weights gs (reference src/d2q9_kuper/Dynamics.c.Rt:115)
GS = np.array([0.0, 1.0, 1.0, 1.0, 1.0, 0.25, 0.25, 0.25, 0.25])
# van der Waals EOS constants (reference src/d2q9_kuper/Dynamics.c.Rt:291-293)
A2 = 3.852462271644162
B2 = 0.1304438860971524 * 4.0
C2 = 2.785855170470555


def _def() -> ModelDef:
    d = ModelDef("d2q9_kuper", ndim=2,
                 description="Kupershtokh pseudopotential multiphase")
    d.add_densities("f", E)
    d.add_field("phi", dx=(-1, 1), dy=(-1, 1))
    d.add_stage("BaseIteration", "Run")
    d.add_stage("CalcPhi", "CalcPhi")
    d.add_stage("BaseInit", "Init", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "CalcPhi"))
    d.add_action("Init", ("BaseInit", "CalcPhi"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("P", unit="Pa")
    d.add_quantity("F", unit="N", vector=True)
    d.add_setting("omega", default=1.0)
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5),
                           "S7": lambda nu: 1.0 - 1.0 / (3 * nu + 0.5),
                           "S8": lambda nu: 1.0 - 1.0 / (3 * nu + 0.5)})
    d.add_setting("InletVelocity")
    d.add_setting("Temperature", default=0.9,
                  comment="temperature of the liquid/gas")
    d.add_setting("FAcc", default=1.0, comment="multiplier of potential")
    d.add_setting("Magic", default=0.01)
    d.add_setting("MagicA", default=-0.152, comment="A in force calc")
    d.add_setting("MagicF", default=-2.0 / 3.0, comment="force multiplier")
    d.add_setting("GravitationX")
    d.add_setting("GravitationY")
    d.add_setting("MovingWallVelocity")
    d.add_setting("Density", default=1.0, zonal=True)
    d.add_setting("Wetting", default=1.0)
    for i, dflt in enumerate([0, 0, 0, -1 / 3, 0, 0, 0, 0, 0]):
        d.add_setting(f"S{i}", default=dflt, comment="MRT keep factor")
    d.add_global("WallForceX")
    d.add_global("WallForceY")
    d.add_node_type("NSymmetry", "BOUNDARY")
    d.add_node_type("SSymmetry", "BOUNDARY")
    d.add_node_type("MovingWall", "BOUNDARY")
    return d


def _eos_pressure(rho, t):
    """Magic-scaled van der Waals pressure
    (reference src/d2q9_kuper/Dynamics.c.Rt:317-318)."""
    br = B2 * rho / 4.0
    p = ((rho * (-br ** 3 + br * br + br + 1.0) * t * C2)
         / (1.0 - br) ** 3 - A2 * rho * rho)
    return p


def calc_phi(ctx: NodeCtx):
    """CalcPhi stage: pseudopotential from the streamed density; boundary
    nodes use the zonal Density setting (reference
    src/d2q9_kuper/Dynamics.c.Rt:290-321)."""
    f = ctx.group("f")
    rho = jnp.sum(f, axis=0)
    bound = ctx.nt_in_group("BOUNDARY") \
        & ~(ctx.nt_is("NSymmetry") | ctx.nt_is("SSymmetry"))
    rho = jnp.where(bound, ctx.setting("Density"), rho)
    p = ctx.setting("Magic") * _eos_pressure(rho, ctx.setting("Temperature"))
    phi = ctx.setting("FAcc") * jnp.sqrt(jnp.maximum(rho / 3.0 - p, 0.0))
    return {"phi": phi}


def _force(ctx: NodeCtx, f: jnp.ndarray):
    """Kupershtokh exact-difference force from neighbor phi
    (reference src/d2q9_kuper/Dynamics.c.Rt:57-127)."""
    dt = f.dtype
    a = ctx.setting("MagicA")
    phi0 = ctx.load("phi")
    fx = jnp.zeros_like(phi0)
    fy = jnp.zeros_like(phi0)
    # the reference samples phi at the NEGATIVE directions (ph = phi(-e_i),
    # src/d2q9_kuper/Dynamics.c.Rt:19) while weighting with +e_i — with the
    # -2/3 multiplier this sets the force sign; sampling at +e_i (the
    # round-1 bug) inverted the interaction and blew up large domains
    for i in range(1, 9):
        phii = ctx.load("phi", -int(E[i, 0]), -int(E[i, 1]))
        r = a * phii * phii + (1.0 - 2.0 * a) * phii * phi0
        g = float(GS[i])
        fx = fx + g * r * float(E[i, 0])
        fy = fy + g * r * float(E[i, 1])
    scale = ctx.setting("MagicF")
    fx, fy = scale * fx, scale * fy
    # wall momentum term (reference :60-66) + wall force objectives
    ex = lbm.edot(E[:, 0], f)
    ey = lbm.edot(E[:, 1], f)
    wall = ctx.nt_is("Wall")
    fx = jnp.where(wall, fx + 2.0 * ex, fx)
    fy = jnp.where(wall, fy + 2.0 * ey, fy)
    ctx.add_global("WallForceX", ex, where=wall)
    ctx.add_global("WallForceY", ey, where=wall)
    return fx, fy


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    mwv = ctx.setting("MovingWallVelocity")

    def moving_wall(f):
        # bounce-back with tangential wall momentum (Ladd correction)
        fb = lbm.perm(f, OPP)
        corr = jnp.stack([6.0 * float(W[i]) * float(E[i, 0]) * mwv
                          * jnp.ones(f.shape[1:], dt) for i in range(9)])
        return fb + corr

    def mirror(perm):
        return lambda f: lbm.perm(f, perm)

    from tclb_tpu.models.family import mirror_perm
    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "MovingWall": moving_wall,
        "NSymmetry": mirror(mirror_perm(E, 1)),
        "SSymmetry": mirror(mirror_perm(E, 1)),
    })

    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    feq = _equilibrium(rho, ux, uy)
    mn = lbm.moments(M, f - feq)
    # per-plane scalar keep factors (a stacked-then-reshaped (9,)
    # settings vector is a shape cast Mosaic cannot lower)
    m_neq = jnp.stack([mn[i] * ctx.setting(f"S{i}")
                       for i in range(9)])
    fx, fy = _force(ctx, f)
    ux2 = ux + fx / rho + ctx.setting("GravitationX")
    uy2 = uy + fy / rho + ctx.setting("GravitationY")
    m_post = m_neq + lbm.moments(M, _equilibrium(rho, ux2, uy2))
    fc = lbm.from_moments(M, m_post)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(ctx.setting("Density"), shape).astype(dt)
    ux = jnp.broadcast_to(ctx.setting("InletVelocity"), shape).astype(dt)
    f = _equilibrium(rho, ux, jnp.zeros(shape, dt))
    return ctx.store({"f": f})


def get_u(ctx):
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_p(ctx):
    rho = jnp.sum(ctx.group("f"), axis=0)
    return ctx.setting("Magic") * _eos_pressure(rho,
                                                ctx.setting("Temperature"))


def get_f(ctx):
    fx, fy = _force(ctx, ctx.group("f"))
    return jnp.stack([fx, fy, jnp.zeros_like(fx)])


def build():
    return _def().finalize().bind(
        run=run, init=init,
        stages={"CalcPhi": calc_phi},
        quantities={"Rho": lambda c: jnp.sum(c.group("f"), axis=0),
                    "U": get_u, "P": get_p, "F": get_f})
