"""d3q27_cumulant — the flagship 3D cumulant model (forced-channel
benchmark family).

Behavioral parity target: reference model ``d3q27_cumulant``
(reference src/d3q27_cumulant/Dynamics.R, Dynamics.c.Rt): Geier-style
cumulant collision, zonal Velocity/Pressure/Turbulence, ForceX/Y/Z body
force, N/S symmetry + velocity/pressure faces, a turbulent-inlet node type
fed by the synthetic-turbulence coupling densities ``SynthT{X,Y,Z}``
(src/d3q27_cumulant/Dynamics.R:41-43), volume-flux global, and running
averages of velocity/pressure (``average=True`` densities,
src/d3q27_cumulant/Dynamics.R:54-60).
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.ops import cumulant, lbm

E = cumulant.velocity_set(3)
W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def():
    d = family.base_def("d3q27_cumulant", E, "3D cumulant collision",
                        faces="WENS", symmetries="NS", objectives=False)
    d.add_setting("nubuffer", default=0.01,
                  comment="viscosity in the buffer layer")
    d.add_setting("Turbulence", default=0.0, zonal=True,
                  comment="inlet turbulence intensity")
    d.add_setting("GalileanCorrection", default=1.0,
                  comment="Galilean correction term")
    d.add_setting("omega_bulk", default=1.0)
    for ax in ("X", "Y", "Z"):
        d.add_setting(f"Force{ax}")
    d.add_global("Flux", unit="m3/s", comment="volume flux")
    d.add_node_type("WVelocityTurbulent", "BOUNDARY")
    d.add_node_type("Buffer", "ADDITIONALS")
    # synthetic-turbulence coupling buffers (filled by the
    # SyntheticTurbulence handler each iteration)
    d.add_density("SynthTX", group="SynthT")
    d.add_density("SynthTY", group="SynthT")
    d.add_density("SynthTZ", group="SynthT")
    d.add_quantity("P", unit="Pa")
    # averaged fields (running averages via the <Average> machinery)
    d.add_density("avgP", group="avg", average=True)
    d.add_density("avgUX", group="avgU", average=True)
    d.add_density("avgUY", group="avgU", average=True)
    d.add_density("avgUZ", group="avgU", average=True)
    d.add_quantity("avgU", unit="m/s", vector=True)
    d.add_quantity("averageP", unit="Pa")
    return d


def _force(ctx: NodeCtx):
    return tuple(ctx.setting(f"Force{ax}") + g for ax, g in
                 zip(("X", "Y", "Z"), family.gravity_of(ctx)))


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    vel = ctx.setting("Velocity")
    # turbulent inlet: mean + synthetic fluctuation from the coupling
    # buffers (SynthT* carry the AR(1)-smoothed unit-variance field filled
    # by the <SyntheticTurbulence> handler) scaled by the zonal Turbulence
    # intensity; the full fluctuation VECTOR is imposed — normal component
    # on top of the mean, tangential via the ZouHe V3 mechanism (reference
    # WVelocityTurbulent, src/d3q27_cumulant/Dynamics.c.Rt:210-222)
    turb = ctx.setting("Turbulence")
    turb_u = vel + turb * ctx.density("SynthTX")
    extra = {
        "WVelocityTurbulent": lambda f: lbm.nebb_boundary(
            E, W, OPP, f, 0, +1, "velocity", turb_u,
            vt={1: turb * ctx.density("SynthTY"),
                2: turb * ctx.density("SynthTZ")}),
    }
    f = family.apply_boundaries(ctx, f, E, W, OPP, extra=extra)

    shape = f.shape[1:]
    # buffer layer runs at nubuffer viscosity (sponge), the bulk at nu
    om_bulk_visc = ctx.setting("omega")
    om_buffer = 1.0 / (3.0 * ctx.setting("nubuffer") + 0.5)
    om = jnp.where(ctx.nt_is("Buffer"), om_buffer, om_bulk_visc).astype(dt)
    F = f.reshape((3, 3, 3) + shape)
    Fp, rho, (ux, uy, uz) = cumulant.collide_d3q27(
        F, om, ctx.setting("omega_bulk"), force=_force(ctx),
        correlated=True, galilean=ctx.setting("GalileanCorrection"))
    coll = ctx.nt_in_group("COLLISION")
    f = jnp.where(coll[None], Fp.reshape((27,) + shape), f)
    ctx.add_global("Flux", ux, where=coll)

    # running averages accumulate per step; <Average> resets and rescales
    # (reference average=T densities, src/conf.R + Lattice::resetAverage)
    avg = jnp.stack([ux, uy, uz])
    return ctx.store({
        "f": f,
        "avg": ((rho - 1.0) / 3.0)[None] + ctx.group("avg"),
        "avgU": avg + ctx.group("avgU"),
    })


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    z = jnp.zeros((1,) + shape, dt)
    return family.standard_init(
        ctx, E, W, extra={"SynthT": jnp.zeros((3,) + shape, dt),
                          "avg": z, "avgU": jnp.zeros((3,) + shape, dt)})


def get_p(ctx: NodeCtx) -> jnp.ndarray:
    return (jnp.sum(ctx.group("f"), axis=0) - 1.0) / 3.0


def get_avg_u(ctx: NodeCtx) -> jnp.ndarray:
    # samples since the last <Average> reset (reference divides by
    # iter - reset_iter; ctx.avg_samples carries reset_iter)
    return ctx.group("avgU") / ctx.avg_samples()


def get_avg_p(ctx: NodeCtx) -> jnp.ndarray:
    return ctx.density("avgP") / ctx.avg_samples()


def build():
    q = family.make_getters(E, force_of=_force)
    q.update({"P": get_p, "avgU": get_avg_u, "averageP": get_avg_p})
    return _def().finalize().bind(run=run, init=init, quantities=q)
