"""d2q9_new — raw-moment MRT with Smagorinsky LES and an entropic (KBC)
stabilizer.

Behavioral parity target: reference model ``d2q9_new``
(reference src/d2q9_new/Dynamics.R, Dynamics.c.Rt, 217-line kernel — NOT
an alias of d2q9): monomial-moment MRT where moments of order <= 2 relax
at ``gamma = 1 - omega`` and higher moments at ``gamma2``; two optional
per-node modes:

* ``Smagorinsky`` (LES group): eddy viscosity from the second-order
  non-equilibrium moments, ``Q = 18 sqrt(sum m_neq,2^2) Smag``,
  ``tau = (tau0 + sqrt(tau0^2 + Q))/2`` (Dynamics.c.Rt:166-182);
* ``Stab`` (ENTROPIC group): KBC-style stabilizer replacing the
  higher-moment rate with ``gamma2 = -gamma a/b``,
  ``a = ds.P.dh``, ``b = dh.P.dh`` with ``P`` the H-norm metric
  ``Minv^T diag(1/w) Minv`` and ``ds``/``dh`` the order-2 / order>2
  non-equilibrium moments (:184-195); the ratio is exported as the ``A``
  quantity (:205-217).

Shear-layer initialization (SL_* settings) for the double-shear-layer
benchmark; plain Zou/He faces; no body force, no BC coupling planes
(both present in d2q9 but absent here, matching the reference).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, _zou_he_x
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)

# monomial moment basis m_pq = sum_i e_x^p e_y^q f_i with polynomial order
# p+q (the reference's EQ$mat from MRT_eq, lib/feq.R)
_POLYS = [(0, 0), (1, 0), (0, 1), (2, 0), (1, 1), (0, 2),
          (2, 1), (1, 2), (2, 2)]
_ORDER = np.array([p + q for p, q in _POLYS])
M = np.stack([E[:, 0].astype(np.float64) ** p
              * E[:, 1].astype(np.float64) ** q for p, q in _POLYS])
MINV = np.linalg.inv(M)
# H-norm metric on moment perturbations: dm.P.dm = sum_i (df_i)^2 / w_i
# (reference P = MI diag(1/wi) t(MI), Dynamics.c.Rt:146)
P_MAT = MINV.T @ np.diag(1.0 / W) @ MINV


def _def() -> ModelDef:
    d = ModelDef("d2q9_new", ndim=2,
                 description="raw-moment MRT with LES + entropic stabilizer")
    d.add_densities("f", E)
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("A", unit="1", vector=True)
    d.add_setting("omega", comment="one over relaxation time")
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity", default=0.0, zonal=True)
    d.add_setting("Pressure", default=0.0, zonal=True)
    d.add_setting("Smag", comment="Smagorinsky constant")
    d.add_setting("SL_U", comment="shear layer velocity")
    d.add_setting("SL_lambda", comment="shear layer steepness")
    d.add_setting("SL_delta", comment="shear layer disturbance")
    d.add_setting("SL_L", comment="shear layer length scale (0 = off)")
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    d.add_node_type("Smagorinsky", "LES")
    d.add_node_type("Stab", "ENTROPIC")
    return d


def _moments(f):
    return [sum(float(M[r, i]) * f[i] for i in range(9) if M[r, i])
            for r in range(9)]


def _neq_split(f):
    m = _moments(f)
    rho = m[0]
    feq = lbm.equilibrium(E, W, rho, (m[1] / rho, m[2] / rho))
    meq = _moments(feq)
    neq = [m[r] - meq[r] for r in range(9)]
    return rho, meq, neq


def _hquad(u, v, rho):
    """u.P.v over moment vectors with None entries treated as zero."""
    acc = None
    for r in range(9):
        if u[r] is None:
            continue
        for c in range(9):
            if v[c] is None or P_MAT[r, c] == 0.0:
                continue
            t = float(P_MAT[r, c]) * u[r] * v[c]
            acc = t if acc is None else acc + t
    return acc if acc is not None else jnp.zeros_like(rho)


def collision_core(f, omega, smag, smag_mask, stab_mask):
    """The raw-moment MRT + per-node Smagorinsky + entropic-stabilizer
    collision as a PURE function of planes and masks — one source of
    physics shared by the XLA path (:func:`_collision`) and the Pallas
    kernel branch (ops/pallas_d2q9.py); scalar-coefficient unrolled
    sums only, so it is Mosaic-safe as-is."""
    rho, meq, neq = _neq_split(f)
    gamma = 1.0 - omega

    # Smagorinsky mode (reference Dynamics.c.Rt:166-182)
    q2 = sum(neq[r] * neq[r] for r in range(9) if _ORDER[r] == 2)
    qs = 18.0 * jnp.sqrt(jnp.maximum(q2, 0.0)) * smag
    tau0 = 1.0 / (1.0 - gamma)
    tau = 0.5 * (jnp.sqrt(tau0 * tau0 + qs) + tau0)
    gamma_eff = jnp.where(smag_mask, 1.0 - 1.0 / tau, gamma)

    # entropic stabilizer (reference :184-195)
    ds = [neq[r] if _ORDER[r] == 2 else None for r in range(9)]
    dh = [neq[r] if _ORDER[r] > 2 else None for r in range(9)]
    a = _hquad(ds, dh, rho)
    b = _hquad(dh, dh, rho)
    safe_b = jnp.where(jnp.abs(b) > 1e-30, b, 1.0)
    gamma_ent = -gamma_eff * jnp.where(jnp.abs(b) > 1e-30,
                                       a / safe_b, -1.0)
    gamma2 = jnp.where(stab_mask, gamma_ent, gamma_eff)

    out_m = []
    for r in range(9):
        if _ORDER[r] <= 1:
            out_m.append(meq[r])
        elif _ORDER[r] == 2:
            out_m.append(meq[r] + gamma_eff * neq[r])
        else:
            out_m.append(meq[r] + gamma2 * neq[r])
    return jnp.stack([
        sum(float(MINV[i, r]) * out_m[r] for r in range(9) if MINV[i, r])
        for i in range(9)])


def _collision(ctx: NodeCtx, f):
    return collision_core(f, ctx.setting("omega"), ctx.setting("Smag"),
                          ctx.nt_is("Smagorinsky"), ctx.nt_is("Stab"))


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    vel = ctx.setting("Velocity")
    den = 1.0 + 3.0 * ctx.setting("Pressure")
    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, den, "pressure", "W"),
        "WVelocity": lambda f: _zou_he_x(f, vel, "velocity", "W"),
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
    })
    f = jnp.where(ctx.nt_is("MRT")[None], _collision(ctx, f), f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    """Uniform or double-shear-layer init (reference Init,
    src/d2q9_new/Dynamics.c.Rt:69-91)."""
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(1.0 + 3.0 * ctx.setting("Pressure"),
                           shape).astype(dt)
    sl_l = ctx.setting("SL_L")
    y = jnp.broadcast_to(jnp.arange(shape[0], dtype=dt)[:, None], shape)
    x = jnp.broadcast_to(jnp.arange(shape[1], dtype=dt)[None, :], shape)
    on = sl_l > 0
    safe_l = jnp.where(on, sl_l, 1.0)
    ux_sl = jnp.where(
        y < safe_l / 2,
        ctx.setting("SL_U") * jnp.tanh(
            ctx.setting("SL_lambda") * (y / safe_l - 0.25)),
        ctx.setting("SL_U") * jnp.tanh(
            ctx.setting("SL_lambda") * (0.75 - y / safe_l)))
    uy_sl = (ctx.setting("SL_delta") * ctx.setting("SL_U")
             * jnp.sin(2.0 * jnp.pi * (x / safe_l + 0.25)))
    ux = jnp.where(on, ux_sl, 0.0) + ctx.setting("Velocity")
    uy = jnp.where(on, uy_sl, 0.0)
    return ctx.store({"f": lbm.equilibrium(E, W, rho, (ux, uy))})


def get_a(ctx: NodeCtx) -> jnp.ndarray:
    """Entropic diagnostic (a/b, a, b) (reference getA,
    src/d2q9_new/Dynamics.c.Rt:205-217)."""
    rho, meq, neq = _neq_split(ctx.group("f"))
    ds = [neq[r] if _ORDER[r] == 2 else None for r in range(9)]
    dh = [neq[r] if _ORDER[r] > 2 else None for r in range(9)]
    a = _hquad(ds, dh, rho)
    b = _hquad(dh, dh, rho)
    safe = jnp.where(jnp.abs(b) > 1e-30, b, 1.0)
    return jnp.stack([a / safe, a, b])


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"Rho": lambda c: jnp.sum(c.group("f"), axis=0),
                    "U": get_u, "A": get_a})
