"""d2q9_new — the reference's newer d2q9 variant.

Behavioral parity target: reference model ``d2q9_new``
(reference src/d2q9_new/Dynamics.R, Dynamics.c.Rt): same physics family as
``d2q9`` (MRT, Zou/He faces, body force) with the modernized settings
surface; realized here as the d2q9 physics under its own registry name.
"""

from __future__ import annotations

from tclb_tpu.models import d2q9


def build():
    d = d2q9._def()
    d.name = "d2q9_new"
    d.description = "2D MRT (newer variant)"
    return d.finalize().bind(
        run=d2q9.run, init=d2q9.init,
        quantities={"Rho": d2q9.get_rho, "U": d2q9.get_u})
