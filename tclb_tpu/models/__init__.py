"""Model catalogue — the TPU-native counterpart of the reference's
``src/<model>/`` directories (inventory: SURVEY.md §2.3).  Models register a
builder here; ``get_model`` builds (and caches) the frozen Model with physics
bound."""

from __future__ import annotations

import importlib
from typing import Callable

from tclb_tpu.core.registry import Model

# model name -> module path (lazy import; building a model is cheap but
# importing all of them on package import is not needed)
_REGISTRY: dict[str, str] = {
    "d2q9": "tclb_tpu.models.d2q9",
    "d2q9_adj": "tclb_tpu.models.d2q9_adj",
}

_CACHE: dict[str, Model] = {}


def register(name: str, module: str) -> None:
    _REGISTRY[name] = module


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def get_model(name: str) -> Model:
    if name not in _CACHE:
        if name not in _REGISTRY:
            raise KeyError(f"unknown model {name!r}; known: {list_models()}")
        mod = importlib.import_module(_REGISTRY[name])
        _CACHE[name] = mod.build()
    return _CACHE[name]
