"""Model catalogue — the TPU-native counterpart of the reference's
``src/<model>/`` directories (inventory: SURVEY.md §2.3).  Models register a
builder here; ``get_model`` builds (and caches) the frozen Model with physics
bound."""

from __future__ import annotations

import importlib
from typing import Callable

from tclb_tpu.core.registry import Model

# model name -> module path (lazy import; building a model is cheap but
# importing all of them on package import is not needed)
# entries are "module.path" (uses its build()) or "module.path:builder"
_REGISTRY: dict[str, str] = {
    "d2q9": "tclb_tpu.models.d2q9",
    "d2q9_adj": "tclb_tpu.models.d2q9_adj",
    "d2q9_SRT": "tclb_tpu.models.d2q9_srt",
    "d2q9_cumulant": "tclb_tpu.models.d2q9_cumulant",
    "d2q9_inc": "tclb_tpu.models.d2q9_inc",
    "d2q9_les": "tclb_tpu.models.d2q9_les",
    "d3q19": "tclb_tpu.models.d3q19",
    "d3q19_les": "tclb_tpu.models.d3q19_les",
    "d3q27": "tclb_tpu.models.d3q27",
    "d3q27_BGK": "tclb_tpu.models.d3q27_bgk",
    "d3q27_BGK_galcor": "tclb_tpu.models.d3q27_bgk:build_galcor",
    "d3q27_cumulant": "tclb_tpu.models.d3q27_cumulant",
    "d3q27_cumulant_qibb_small": "tclb_tpu.models.d3q27_cumulant_qibb",
    "d3q27_viscoplastic": "tclb_tpu.models.d3q27_viscoplastic",
    "d2q9_new": "tclb_tpu.models.d2q9_new",
    "d2q9_heat": "tclb_tpu.models.d2q9_heat",
    "d2q9_hb": "tclb_tpu.models.d2q9_hb",
    "d2q9_diff": "tclb_tpu.models.d2q9_diff",
    "d2q9_kuper": "tclb_tpu.models.d2q9_kuper",
    "d2q9_lee": "tclb_tpu.models.d2q9_lee",
    "d2q9_npe_guo": "tclb_tpu.models.d2q9_npe_guo",
    "d2q9_poison_boltzmann": "tclb_tpu.models.d2q9_poison_boltzmann",
    "d2q9_pp_LBL": "tclb_tpu.models.d2q9_pp_lbl",
    "d2q9_pp_MCMP": "tclb_tpu.models.d2q9_pp_mcmp",
    "d2q9_pf": "tclb_tpu.models.d2q9_pf",
    "d2q9_pf_curvature": "tclb_tpu.models.d2q9_pf_curvature",
    "d2q9_pf_pressureEvolution":
        "tclb_tpu.models.d2q9_pf_pressure_evolution",
    "sw": "tclb_tpu.models.sw",
    "wave": "tclb_tpu.models.wave",
    "wave2d": "tclb_tpu.models.wave2d",
    "d2q9_heat_adj": "tclb_tpu.models.d2q9_heat_adj",
    "d2q9_kuper_adj": "tclb_tpu.models.d2q9_kuper_adj",
    "d2q9_plate": "tclb_tpu.models.d2q9_plate",
    "d2q9_optimalMixing": "tclb_tpu.models.d2q9_optimal_mixing",
    "d2q9_solid": "tclb_tpu.models.d2q9_solid",
    "d2q9_heat_conjugate": "tclb_tpu.models.d2q9_heat_conjugate",
    "d3q19_adj": "tclb_tpu.models.d3q19_adj",
    "d3q19_heat": "tclb_tpu.models.d3q19_heat",
    "d3q19_heat_adj": "tclb_tpu.models.d3q19_heat_adj",
    "d3q19_heat_adj_art": "tclb_tpu.models.d3q19_heat_adj:build_art",
    "d3q19_heat_adj_prop": "tclb_tpu.models.d3q19_heat_adj:build_prop",
    "d3q19_kuper": "tclb_tpu.models.d3q19_kuper",
}

_CACHE: dict[str, Model] = {}


def register(name: str, module: str) -> None:
    _REGISTRY[name] = module


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def get_model(name: str) -> Model:
    if name not in _CACHE:
        if name not in _REGISTRY:
            raise KeyError(f"unknown model {name!r}; known: {list_models()}")
        path = _REGISTRY[name]
        modpath, _, builder = path.partition(":")
        mod = importlib.import_module(modpath)
        _CACHE[name] = getattr(mod, builder or "build")()
    return _CACHE[name]
