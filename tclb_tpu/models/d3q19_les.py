"""d3q19_les — 3D BGK with Smagorinsky subgrid closure.

Behavioral parity target: reference model ``d3q19_les``
(reference src/d3q19_les/Dynamics.R, Dynamics.c.Rt).
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d3q19 import E, OPP, W
from tclb_tpu.ops import lbm


def _def():
    d = family.base_def("d3q19_les", E, "3D BGK + Smagorinsky LES",
                        faces="WE", symmetries="NS")
    d.add_setting("Smag", default=0.16, comment="Smagorinsky constant")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    u = tuple(lbm.edot(E[:, a], f) / rho
              for a in range(3))
    feq = lbm.equilibrium(E, W, rho, u)
    om_eff = lbm.smagorinsky_omega_unrolled(E, f, feq, rho, ctx.setting("omega"),
                                   ctx.setting("Smag"))
    fc = f + om_eff[None] * (feq - f)
    g = family.gravity_of(ctx)
    u2 = tuple(u[a] + g[a] for a in range(3))
    fc = fc + (lbm.equilibrium(E, W, rho, u2) - feq)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
