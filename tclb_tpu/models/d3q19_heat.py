"""d3q19_heat — 3D flow + temperature (d3q19 + d3q7 double distribution).

Behavioral parity target: reference model ``d3q19_heat``
(reference src/d3q19_heat/Dynamics.R, Dynamics.c.Rt): d3q19 flow coupled to
an advected temperature lattice with diffusivity ``FluidAlfa`` and Heater
nodes.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models import family
from tclb_tpu.models.d3q19 import E, OPP, W, collide
from tclb_tpu.ops import lbm

# d3q7 for the scalar: rest + 6 axis vectors
ET = np.array([(0, 0, 0), (1, 0, 0), (-1, 0, 0), (0, 1, 0), (0, -1, 0),
               (0, 0, 1), (0, 0, -1)], dtype=np.int32)
WT = lbm.weights(ET)
OPPT = lbm.opposite(ET)


def _def() -> ModelDef:
    d = family.base_def("d3q19_heat", E, "3D flow + temperature",
                        faces="WE", symmetries="NS")
    d.add_densities("T", ET, group="T")
    d.add_setting("S_high", default=1.0)
    d.add_setting("InletTemperature", default=1.0)
    d.add_setting("InitTemperature", default=1.0)
    d.add_setting("FluidAlfa", default=1.0)
    d.add_setting("HeaterTemperature", default=100.0)
    d.add_quantity("T", unit="K")
    d.add_global("OutFlux")
    d.add_node_type("Heater", "ADDITIONALS")
    return d


def _t_eq(T, u):
    dt = T.dtype
    out = []
    for i in range(7):
        eu = sum(float(ET[i, a]) * u[a] for a in range(3) if ET[i, a])
        if isinstance(eu, int):
            out.append(jnp.asarray(float(WT[i]), dt) * T)
        else:
            out.append(jnp.asarray(float(WT[i]), dt) * T * (1.0 + 4.0 * eu))
    return jnp.stack(out)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    fT = ctx.group("T")
    dt = f.dtype
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    t_in = ctx.setting("InletTemperature")
    shape = f.shape[1:]
    fT = ctx.boundary_case(fT, {
        ("Wall", "Solid"): lambda t: lbm.perm(t, OPPT),
        ("WVelocity", "EPressure"): lambda t: _t_eq(
            jnp.broadcast_to(t_in, shape).astype(dt),
            tuple(jnp.zeros(shape, dt) for _ in range(3))),
    })
    rho = jnp.sum(f, axis=0)
    u = tuple(lbm.edot(E[:, a], f) / rho
              for a in range(3))
    fc = collide(ctx, f)
    temp = jnp.sum(fT, axis=0)
    target = jnp.where(ctx.nt_is("Heater"),
                       ctx.setting("HeaterTemperature"), temp)
    # d3q7 diffusivity: alfa = (1/w_a)(tau - 1/2) with w_a = 1/4
    om_t = 1.0 / (4.0 * ctx.setting("FluidAlfa") + 0.5)
    tc = fT + om_t * (_t_eq(target, u) - fT)
    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    fT = jnp.where(coll, tc, fT)
    ctx.add_global("OutFlux", temp * u[0], where=ctx.nt_is("Outlet"))
    return ctx.store({"f": f, "T": fT})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    t0 = jnp.broadcast_to(ctx.setting("InitTemperature"), shape).astype(dt)
    fT = _t_eq(t0, tuple(jnp.zeros(shape, dt) for _ in range(3)))
    return family.standard_init(ctx, E, W, extra={"T": fT})


def build():
    q = family.make_getters(E, force_of=family.gravity_of)
    q["T"] = lambda c: jnp.sum(c.group("T"), axis=0)
    return _def().finalize().bind(run=run, init=init, quantities=q)
