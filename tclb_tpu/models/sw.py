"""sw — shallow-water equations on d2q9 with adjoint energy optimization.

Behavioral parity target: reference model ``sw``
(reference src/sw/Dynamics.R, Dynamics.c.Rt): MRT whose equilibrium energy
moments carry the shallow-water pressure ``g h^2`` terms
(src/sw/Dynamics.c.Rt:228-241), a ``w`` design field damping momentum
(energy extraction), and EnergyGain/TotalDiff/Material objectives on Obj1
nodes — the reference's wave-energy-harvesting optimization case.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, M, OPP, _zou_he_x
from tclb_tpu.ops import lbm

W = lbm.weights(E)


def _def() -> ModelDef:
    d = ModelDef("sw", ndim=2, description="Shallow water equation")
    d.add_densities("f", E)
    d.add_density("w", group="w", parameter=True)
    d.add_quantity("Rho", unit="m")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("RhoB", adjoint=True)
    d.add_quantity("UB", adjoint=True, vector=True)
    d.add_quantity("W")
    d.add_quantity("WB", adjoint=True)
    d.add_setting("omega", default=1.0,
                  comment="one over relaxation time")
    d.add_setting("nu", default=1 / 6, comment="viscosity",
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5),
                           "S8": lambda nu: 1.0 / (3 * nu + 0.5),
                           "S9": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("InletVelocity")
    d.add_setting("InletPressure", default=0.0,
                  derived={"InletDensity": lambda p: 1.0 + p / 3.0})
    d.add_setting("InletDensity", default=1.0)
    d.add_setting("Gravity", default=1.0)
    d.add_setting("SolidH", default=1.0)
    d.add_setting("EnergySink", default=0.0)
    d.add_setting("Height", default=0.0, zonal=True)
    # relaxation rates of the non-conserved moments (e, eps, qx, qy, pxx,
    # pxy) — reference S2..S9 (src/sw/Dynamics.c.Rt:206-248)
    for nm in ("S2", "S3", "S5", "S7"):
        d.add_setting(nm, default=1.0)
    d.add_setting("S8", default=1.0)
    d.add_setting("S9", default=1.0)
    d.add_global("PressDiff")
    d.add_global("TotalDiff", comment="total variation of velocity")
    d.add_global("Material", comment="total material")
    d.add_global("EnergyGain")
    d.add_node_type("Obj1", "OBJECTIVE")
    return d


def _eq_moments(dd, jx, jy, g):
    """Shallow-water equilibrium moments in the (rho, jx, jy, e, eps, qx,
    qy, pxx, pxy) basis (reference Req, src/sw/Dynamics.c.Rt:228-241)."""
    inv = 1.0 / dd
    usq = (jx * jx + jy * jy) * inv
    return [dd, jx, jy,
            -4.0 * dd + 3.0 * usq + 3.0 * dd * dd * g,
            4.0 * dd - 3.0 * usq - 4.5 * dd * dd * g,
            -jx, -jy,
            (jx * jx - jy * jy) * inv,
            jx * jy * inv]


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    w = ctx.density("w")
    dt = f.dtype
    vel = ctx.setting("InletVelocity")
    den = ctx.setting("InletDensity")
    f = ctx.boundary_case(f, {
        "Wall": lambda f: lbm.perm(f, OPP),
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, den, "pressure", "W"),
        "WVelocity": lambda f: _zou_he_x(f, vel, "velocity", "W"),
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
    })
    g = ctx.setting("Gravity")
    m = lbm.moments(M, f)
    dd, jx, jy = m[0], m[1], m[2]
    rates = jnp.stack([jnp.zeros((), dt), jnp.zeros((), dt),
                       jnp.zeros((), dt),
                       ctx.setting("S2"), ctx.setting("S3"),
                       ctx.setting("S5"), ctx.setting("S7"),
                       ctx.setting("S8"), ctx.setting("S9")]).astype(dt)
    req = _eq_moments(dd, jx, jy, g)
    # keep (1-S) of the non-equilibrium part
    m_rel = [m[i] if i < 3 else
             (1.0 - rates[i]) * (m[i] - req[i])
             for i in range(9)]
    obj = ctx.nt_is("Obj1")
    ctx.add_global("TotalDiff", jx * jx + jy * jy, where=obj)
    pre = jx * jx + jy * jy
    # momentum damping by the design field = energy extraction
    jx2, jy2 = jx * w, jy * w
    ctx.add_global("EnergyGain", pre - (jx2 * jx2 + jy2 * jy2), where=obj)
    ctx.add_global("Material", w)
    req2 = _eq_moments(dd, jx2, jy2, g)
    m_post = jnp.stack([(dd, jx2, jy2)[i] if i < 3
                        else m_rel[i] + req2[i]
                        for i in range(9)])
    fc = lbm.from_moments(M, m_post)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    h = ctx.setting("Height")
    dd = jnp.where(h > 0, h, jnp.ones(shape, dt)).astype(dt)
    dd = jnp.where(ctx.nt_is("Solid"),
                   jnp.broadcast_to(ctx.setting("SolidH"), shape), dd)
    ux = jnp.broadcast_to(ctx.setting("InletVelocity"), shape).astype(dt)
    g = ctx.setting("Gravity")
    req = _eq_moments(dd, dd * ux, jnp.zeros(shape, dt), g)
    f = lbm.from_moments(M, jnp.stack(req))
    w = jnp.where(ctx.nt_is("Solid") | ctx.nt_is("Wall"),
                  jnp.zeros(shape, dt),
                  jnp.full(shape, 1.0 - ctx.setting("EnergySink"), dt))
    return ctx.store({"f": f, "w": w[None]})


def get_u(ctx):
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def build():
    rhoq = lambda c: jnp.sum(c.group("f"), axis=0)   # noqa: E731
    wq = lambda c: c.density("w")                    # noqa: E731
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"Rho": rhoq, "U": get_u, "W": wq,
                    "RhoB": rhoq, "UB": get_u, "WB": wq})
