"""wave — scalar wave equation as a first-order system on Fields.

Behavioral parity target: reference model ``wave``
(reference src/wave/Dynamics.R — an R-only skeleton with no kernel file:
``u'' = c (u_xx + u_yy)`` via fields u, v with a +-1 stencil, Dirichlet
boundary pinning u to the zonal ``Value``).  The reference ships no
Dynamics.c for this model; this is the natural realization of its registry.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef


def _def() -> ModelDef:
    d = ModelDef("wave", ndim=2, description="wave equation on fields")
    d.add_field("u", dx=(-1, 1), dy=(-1, 1))
    d.add_field("v", dx=(-1, 1), dy=(-1, 1))
    d.add_quantity("U")
    d.add_setting("Speed", default=0.1)
    d.add_setting("Value", default=0.0, zonal=True)
    d.add_setting("Viscosity", default=0.0)
    d.add_node_type("Dirichlet", "BOUNDARY")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    u = ctx.load("u")
    v = ctx.load("v")
    lap = (ctx.load("u", 1, 0) + ctx.load("u", -1, 0)
           + ctx.load("u", 0, 1) + ctx.load("u", 0, -1) - 4.0 * u)
    v = v + ctx.setting("Speed") * lap - ctx.setting("Viscosity") * v
    u = u + v
    u = jnp.where(ctx.nt_is("Dirichlet"), ctx.setting("Value"), u)
    v = jnp.where(ctx.nt_is("Dirichlet"), jnp.zeros_like(v), v)
    return {"u": u, "v": v}


def init(ctx: NodeCtx):
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    u = jnp.broadcast_to(ctx.setting("Value"), shape).astype(dt)
    return {"u": u, "v": jnp.zeros(shape, dt)}


def build():
    return _def().finalize().bind(
        run=run, init=init, quantities={"U": lambda c: c.load("u")})
