"""d2q9 — 2D MRT lattice-Boltzmann with body force, Zou/He in/outlets,
symmetry walls and inlet/outlet flux + pressure-loss objectives.

Behavioral parity target: reference model ``d2q9``
(reference src/d2q9/Dynamics.R, src/d2q9/Dynamics.c.Rt).  The physics here is
written from the standard LBM formulation (Lallemand–Luo MRT moments, Zou/He
boundaries), vectorized over the whole lattice: per-node ``switch`` dispatch
becomes mask selects, the per-node 9x9 moment transform becomes one einsum
that XLA maps onto the MXU.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.registry import ModelDef
from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.ops import lbm


# D2Q9 velocity set (standard ordering: rest, axis, diagonal).
E = np.array([(0, 0), (1, 0), (0, 1), (-1, 0), (0, -1),
              (1, 1), (-1, 1), (-1, -1), (1, -1)], dtype=np.int32)
W = lbm.weights(E)
OPP = lbm.opposite(E)                      # bounce-back pairing
M = lbm.mrt_basis_d2q9(E)                  # (9, 9) orthogonal moment basis


def _def() -> ModelDef:
    d = ModelDef("d2q9", ndim=2,
                 description="2D MRT with Zou/He boundaries and objectives")
    d.add_densities("f", E)
    # coupling buffer for in-process (Python/NumPy) forcing — reference keeps
    # these for its CallPython example (src/d2q9/Dynamics.R:18-20)
    d.add_density("BC[0]", group="BC")
    d.add_density("BC[1]", group="BC")
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_setting("omega", comment="one over relaxation time",
                  derived={"S78": lambda om: 1.0 - om})
    d.add_setting("nu", default=1 / 6, comment="viscosity",
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity", default=0.0, zonal=True,
                  comment="inlet/outlet/init velocity")
    d.add_setting("Density", default=1.0, zonal=True,
                  comment="inlet/outlet/init density")
    d.add_setting("GravitationY")
    d.add_setting("GravitationX")
    d.add_setting("S3", default=-1 / 3, comment="MRT energy relaxation")
    d.add_setting("S4", default=0.0)
    d.add_setting("S56", default=0.0)
    d.add_setting("S78", default=0.0)
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    d.add_node_type("BottomSymmetry", "BOUNDARY")
    d.add_node_type("TopSymmetry", "BOUNDARY")
    return d


# ----------------------------------------------------------------------- #
# physics
# ----------------------------------------------------------------------- #


def _equilibrium(rho, ux, uy):
    return lbm.equilibrium(E, W, rho, (ux, uy))


def _zou_he_x(f, rho_or_u, kind: str, side: str):
    """Zou/He velocity/pressure boundaries on x-normal faces.

    ``side`` 'W' (flow enters +x) or 'E' (flow leaves +x); ``kind`` 'velocity'
    (given ux) or 'pressure' (given rho).  Unknown populations are
    reconstructed from the bounce-back of the non-equilibrium part plus a
    transverse correction — standard Zou/He closure.
    """
    # partial sums: populations tangent to the face and the known normals
    tang = f[0] + f[2] + f[4]
    if side == "W":
        known = f[3] + f[7] + f[6]
        if kind == "velocity":
            ux = rho_or_u
            rho = (tang + 2.0 * known) / (1.0 - ux)
        else:
            rho = rho_or_u
            ux = 1.0 - (tang + 2.0 * known) / rho
        ru = rho * ux
        f1 = f[3] + (2.0 / 3.0) * ru
        f5 = f[7] + (1.0 / 6.0) * ru + 0.5 * (f[4] - f[2])
        f8 = f[6] + (1.0 / 6.0) * ru + 0.5 * (f[2] - f[4])
        return jnp.stack([f[0], f1, f[2], f[3], f[4], f5, f[6], f[7], f8])
    else:
        known = f[1] + f[5] + f[8]
        if kind == "velocity":
            ux = rho_or_u
            rho = (tang + 2.0 * known) / (1.0 + ux)
        else:
            rho = rho_or_u
            ux = -1.0 + (tang + 2.0 * known) / rho
        ru = rho * ux
        f3 = f[1] - (2.0 / 3.0) * ru
        f7 = f[5] - (1.0 / 6.0) * ru + 0.5 * (f[2] - f[4])
        f6 = f[8] - (1.0 / 6.0) * ru + 0.5 * (f[4] - f[2])
        return jnp.stack([f[0], f[1], f[2], f3, f[4], f[5], f6, f7, f[8]])


def _symmetry(f, top: bool):
    """Mirror across an x-parallel wall: populations with the wall-normal
    velocity component are replaced by their mirror images."""
    if top:   # wall above: downward-moving come from upward-moving mirrors
        return jnp.stack([f[0], f[1], f[2], f[3], f[2], f[5], f[6], f[6], f[5]])
    else:
        return jnp.stack([f[0], f[1], f[4], f[3], f[4], f[8], f[7], f[7], f[8]])


def _collision_mrt(ctx: NodeCtx, f: jnp.ndarray):
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    jx = lbm.edot(E[:, 0], f)
    jy = lbm.edot(E[:, 1], f)
    ux, uy = jx / rho, jy / rho

    # objectives on Inlet/Outlet-tagged collision nodes
    # (reference src/d2q9/Dynamics.c.Rt:250-270)
    usq = ux * ux + uy * uy
    mrt = ctx.nt_is("MRT")
    ploss = ux / rho * ((rho - 1.0) / 3.0 + usq / rho * 0.5)
    ctx.add_global("OutletFlux", ux / rho, where=ctx.nt_is("Outlet") & mrt)
    ctx.add_global("InletFlux", ux / rho, where=ctx.nt_is("Inlet") & mrt)
    ctx.add_global("PressureLoss",
                   jnp.where(ctx.nt_is("Inlet"), ploss, 0.0)
                   - jnp.where(ctx.nt_is("Outlet"), ploss, 0.0),
                   where=(ctx.nt_is("Inlet") | ctx.nt_is("Outlet")) & mrt)

    # relax the non-equilibrium moments with pre-force velocity ...
    # per-plane scalar rates (a stacked-then-reshaped (9,) settings
    # vector is a shape cast Mosaic cannot lower); conserved moments
    # relax at rate 0 and drop out exactly
    rates = [None, None, None,
             ctx.setting("S3"), ctx.setting("S4"),
             ctx.setting("S56"), ctx.setting("S56"),
             ctx.setting("S78"), ctx.setting("S78")]
    feq = _equilibrium(rho, ux, uy)
    mn = lbm.moments(M, f - feq)
    m_neq = jnp.stack([jnp.zeros_like(mn[i]) if r is None else mn[i] * r
                       for i, r in enumerate(rates)])
    # ... then shift velocity by the body force (exact-difference style
    # forcing, reference src/d2q9/Dynamics.c.Rt:279-285) and add the
    # post-force equilibrium moments back
    ux2 = ux + ctx.setting("GravitationX") + ctx.density("BC[0]")
    uy2 = uy + ctx.setting("GravitationY") + ctx.density("BC[1]")
    # Minv @ (m_neq + M @ feq2) == Minv @ m_neq + feq2 — one transform
    # saved (exact algebra; the Pallas kernel uses the same identity)
    return lbm.from_moments(M, m_neq) + _equilibrium(rho, ux2, uy2)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    vel = ctx.setting("Velocity")
    den = ctx.setting("Density")
    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, den, "pressure", "W"),
        "WVelocity": lambda f: _zou_he_x(f, vel, "velocity", "W"),
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
        "TopSymmetry": lambda f: _symmetry(f, top=True),
        "BottomSymmetry": lambda f: _symmetry(f, top=False),
    })
    f = jnp.where(ctx.nt_is("MRT")[None], _collision_mrt(ctx, f), f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    den = ctx.setting("Density")
    vel = ctx.setting("Velocity")
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(jnp.asarray(den, dt), shape)
    ux = jnp.broadcast_to(jnp.asarray(vel, dt), shape)
    f = _equilibrium(rho, ux, jnp.zeros(shape, dt))
    return ctx.store({"f": f, "BC": jnp.zeros((2,) + shape, dt)})


def get_rho(ctx: NodeCtx) -> jnp.ndarray:
    return jnp.sum(ctx.group("f"), axis=0)


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    # measured velocity includes half the body force
    # (reference src/d2q9/Dynamics.c.Rt:43-49)
    ux = ux + ctx.density("BC[0]") * 0.5 + ctx.setting("GravitationX") * 0.5
    uy = uy + ctx.density("BC[1]") * 0.5 + ctx.setting("GravitationY") * 0.5
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def build():
    model = _def().finalize()
    return model.bind(run=run, init=init,
                      quantities={"Rho": get_rho, "U": get_u})
