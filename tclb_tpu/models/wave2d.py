"""wave2d — 2D scalar wave equation with adjoint support.

Behavioral parity target: reference model ``wave2d``
(reference src/wave2d/Dynamics.R, Dynamics.c.Rt): a finite-difference wave
equation carried on the lattice machinery — four streamed copies
``h1..h4`` of the height deliver the 5-point Laplacian, ``u`` is the time
derivative, ``w`` masks the domain (0 at walls), ``Loss`` damps.  Obj1
nodes accumulate the squared Laplacian (TotalDiff objective,
src/wave2d/Dynamics.c.Rt:59-66).
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef


def _def() -> ModelDef:
    d = ModelDef("wave2d", ndim=2, description="2D wave equation")
    d.add_density("h", group="state")
    d.add_density("u", group="state")
    d.add_density("h1", dx=1, dy=0, group="hn")
    d.add_density("h2", dx=0, dy=1, group="hn")
    d.add_density("h3", dx=-1, dy=0, group="hn")
    d.add_density("h4", dx=0, dy=-1, group="hn")
    d.add_density("w", group="w", parameter=True)
    d.add_quantity("H")
    d.add_quantity("W")
    d.add_quantity("WB", adjoint=True)
    d.add_quantity("HB", adjoint=True)
    d.add_setting("WaveK", default=0.1, comment="wave speed coefficient")
    d.add_setting("SolidH", default=0.0, comment="H of solid nodes")
    d.add_setting("Loss", default=1.0, comment="u multiplier")
    d.add_global("TotalDiff", comment="total diff")
    d.add_node_type("Obj1", "OBJECTIVE")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    h = ctx.density("h")
    u = ctx.density("u")
    w = ctx.density("w")
    h1, h2 = ctx.density("h1"), ctx.density("h2")
    h3, h4 = ctx.density("h3"), ctx.density("h4")
    du = h1 + h2 + h3 + h4 - 4.0 * h
    ctx.add_global("TotalDiff", du * du, where=ctx.nt_is("Obj1"))
    u = u + du * ctx.setting("WaveK")
    h = (h + u) * w
    u = u * ctx.setting("Loss")
    hn = jnp.stack([h, h, h, h])
    return ctx.store({"state": jnp.stack([h, u]), "hn": hn, "w": w[None]})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    w = jnp.where(ctx.nt_is("Wall"), 0.0, 1.0).astype(dt)
    h = jnp.where(ctx.nt_is("Solid"),
                  jnp.broadcast_to(ctx.setting("SolidH"), shape),
                  jnp.zeros(shape, dt)).astype(dt)
    z = jnp.zeros(shape, dt)
    return ctx.store({"state": jnp.stack([h, z]),
                      "hn": jnp.stack([h, h, h, h]), "w": w[None]})


def build():
    hq = lambda c: c.density("h")        # noqa: E731
    wq = lambda c: c.density("w")        # noqa: E731
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"H": hq, "W": wq, "HB": hq, "WB": wq})
