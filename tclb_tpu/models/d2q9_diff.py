"""d2q9_diff — 2D advection-diffusion with adjoint support.

Behavioral parity target: reference model ``d2q9_diff``
(reference src/d2q9_diff/Dynamics.R, Dynamics.c.Rt, ADJOINT=1): a scalar
concentration advected by a prescribed velocity field with BGK diffusion;
the total-concentration objective drives source optimization.  Adjoint is
native here (any model is differentiable); the design field ``w`` is a
distributed source strength.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, OPP
from tclb_tpu.ops import lbm

W = lbm.weights(E)


def _def() -> ModelDef:
    d = ModelDef("d2q9_diff", ndim=2, description="2D advection-diffusion")
    d.add_densities("f", E)
    d.add_density("w", group="w", parameter=True)
    d.add_quantity("C", comment="concentration")
    d.add_quantity("W")
    d.add_setting("omega", default=1.0)
    d.add_setting("Diffusivity", default=1 / 6,
                  derived={"omega": lambda a: 1.0 / (3 * a + 0.5)})
    d.add_setting("UX", comment="advection velocity x")
    d.add_setting("UY", comment="advection velocity y")
    d.add_setting("InitC", default=0.0, zonal=True)
    d.add_setting("Source", default=0.0, comment="source scale of w")
    d.add_global("TotalC", comment="total concentration")
    d.add_global("OutC", comment="outlet concentration flux")
    return d


def _eq(c, ux, uy):
    dt = c.dtype
    out = []
    for i in range(9):
        eu = float(E[i, 0]) * ux + float(E[i, 1]) * uy
        out.append(jnp.asarray(float(W[i]), dt) * c * (1.0 + 3.0 * eu))
    return jnp.stack(out)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    w = ctx.density("w")
    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
    })
    c = jnp.sum(f, axis=0)
    ux = ctx.setting("UX")
    uy = ctx.setting("UY")
    om = ctx.setting("omega")
    fc = f + om * (_eq(c, ux, uy) - f)
    # distributed source on DesignSpace nodes (adjoint design variable)
    src = ctx.setting("Source") * w
    src = jnp.where(ctx.nt_in_group("DESIGNSPACE"), src,
                    jnp.zeros_like(src))
    fc = fc + _eq(src, ux * 0.0, uy * 0.0)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    ctx.add_global("TotalC", c, where=ctx.nt_in_group("COLLISION"))
    ctx.add_global("OutC", c, where=ctx.nt_is("Outlet"))
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    c = jnp.broadcast_to(ctx.setting("InitC"), shape).astype(dt)
    z = jnp.zeros(shape, dt)
    return ctx.store({"f": _eq(c, z, z), "w": z[None] + 0.5})


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"C": lambda ctx: jnp.sum(ctx.group("f"), axis=0),
                    "W": lambda ctx: ctx.density("w")})
