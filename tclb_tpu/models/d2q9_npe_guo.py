"""d2q9_npe_guo — Nernst–Planck electrokinetics (Guo's coupled LBM).

Behavioral parity target: reference model ``d2q9_npe_guo``
(reference src/d2q9_npe_guo/Dynamics.R, Dynamics.c.Rt; validated there by
python/test_eof.py against the electro-osmotic channel flow).  Five d2q9
populations solve four coupled equations:

* ``g`` — internal potential psi by Guo's Poisson LBM: rest weight
  ``wp0 = 1/9``, equilibrium ``wp_i psi`` with ``wp = (1/9 - 1, 1/9 ...)``,
  charge source ``dt wps RD`` with ``RD = -(2/3)(1/2 - tau_psi) dt rho_e /
  epsilon`` (dt appears in both factors — a literal dt^2 scaling) and
  ``tau_psi = 1`` (Dynamics.c.Rt:92-99,266-270);
* ``phi`` — external potential by the same solver, source-free, driven by
  Dirichlet ``phi_bc`` at pressure boundaries;
* ``h_0``/``h_1`` — ion number densities ``n0``/``n1`` (valence +-ez):
  advection-diffusion with equilibrium ``wi n (1 - e.u/cs2)`` (the
  reference's literal form) and electro-migration source
  ``- wi z_k (e.gradPsi) n_k B el_kbT``, ``B = 3 D / tau_D``,
  ``tau_D = 3 D + 1/2`` (Dynamics.c.Rt:241-268);
* ``f`` — fluid BGK with exact-difference forcing by the electric body
  force ``F = -gradPhi rho_e / rho t_to_s^2`` (getF :390-405; the gradPsi
  term is commented out in the reference and omitted here too).

Charge density ``rho_e = el ez (n0 - n1)``; potential gradients are read
off the first moments of the solver populations:
``grad = -(3/2) sum_i (g_i - wp_i psi) e_i`` (:328-357).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E
# Guo Poisson-solver weights/update shared with d2q9_poison_boltzmann
from tclb_tpu.models.guo_poisson import WP, WP0, psi_of as _psi_of, \
    collide as _guo_collide
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
CS2 = 1.0 / 3.0
TAU_PSI = 1.0
TAU_PHI = 1.0
_GROUPS = ("phi", "g", "f", "h_0", "h_1")


def _def() -> ModelDef:
    d = ModelDef("d2q9_npe_guo", ndim=2,
                 description="Nernst-Planck electrokinetics (Guo)")
    for gname in _GROUPS:
        d.add_densities(gname, E)
    d.add_quantity("F", unit="kgm/s2", vector=True)
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("n0", unit="An/m3")
    d.add_quantity("n1", unit="An/m3")
    d.add_quantity("Psi", unit="V")
    d.add_quantity("Phi", unit="V")
    d.add_quantity("GradPsi", unit="V/m", vector=True)
    d.add_quantity("GradPhi", unit="V/m", vector=True)
    d.add_quantity("rho_e", unit="C/m3")
    d.add_setting("n_inf_0")
    d.add_setting("n_inf_1")
    d.add_setting("el", default=1.0)
    d.add_setting("el_kbT", default=1.0)
    d.add_setting("epsilon", default=1.0)
    d.add_setting("dt", default=1.0)
    d.add_setting("psi0", default=1.0)
    d.add_setting("phi0", default=1.0)
    d.add_setting("ez", default=1.0)
    d.add_setting("Ex", default=0.0)
    d.add_setting("D", default=1.0 / 6.0, comment="ion diffusivity")
    d.add_setting("nu", default=1 / 6, comment="viscosity")
    d.add_setting("rho_bc", default=1.0, zonal=True)
    d.add_setting("phi_bc", default=1.0, zonal=True)
    d.add_setting("psi_bc", default=1.0, zonal=True,
                  comment="zeta potential at walls")
    d.add_setting("t_to_s", default=1.0)
    # never accumulated — the reference's AddToTotalMomentum call is
    # commented out (src/d2q9_npe_guo/Dynamics.c.Rt:252); config parity
    d.add_global("TotalMomentum")
    d.add_node_type("BottomSymmetry", "BOUNDARY")
    d.add_node_type("TopSymmetry", "BOUNDARY")
    return d


def _stack(ctx, names):
    return jnp.concatenate([ctx.group(n) for n in names])


def _grad_of(g, pot):
    """grad = -(3/2) sum_i (g_i - wp_i pot) e_i (reference getGradPsi)."""
    gx = sum(float(E[i, 0]) * (g[i] - float(WP[i]) * pot)
             for i in range(9) if E[i, 0])
    gy = sum(float(E[i, 1]) * (g[i] - float(WP[i]) * pot)
             for i in range(9) if E[i, 1])
    return -1.5 * gx / TAU_PSI, -1.5 * gy / TAU_PSI


def _macro(ctx, f, g, phi, h0, h1):
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    n0 = jnp.sum(h0, axis=0)
    n1 = jnp.sum(h1, axis=0)
    psi = _psi_of(g)
    pot = _psi_of(phi)
    rho_e = ctx.setting("el") * ctx.setting("ez") * (n0 - n1)
    gpsi = _grad_of(g, psi)
    gphi = _grad_of(phi, pot)
    ts = ctx.setting("t_to_s")
    fx = -gphi[0] * rho_e / rho * ts * ts
    fy = -gphi[1] * rho_e / rho * ts * ts
    return rho, n0, n1, psi, pot, rho_e, gpsi, (fx, fy)


def run(ctx: NodeCtx) -> jnp.ndarray:
    s = _stack(ctx, _GROUPS)
    phi, g, f, h0, h1 = (s[9 * i:9 * i + 9] for i in range(5))
    dt = s.dtype

    # ---------------- boundaries (reference Run :181-219) --------------- #
    n_inf_0 = ctx.setting("n_inf_0")
    n_inf_1 = ctx.setting("n_inf_1")
    psi_bc = ctx.setting("psi_bc")
    phi_bc = ctx.setting("phi_bc")
    full = s.shape[1:]

    def _plane(x):
        return jnp.broadcast_to(x, full).astype(dt)

    def wall(stack):
        phi_, g_, f_, h0_, h1_ = (stack[9 * i:9 * i + 9] for i in range(5))
        f_ = lbm.perm(f_, OPP)
        phi_ = lbm.perm(phi_, OPP)
        g_ = lbm.wstack(WP, _plane(psi_bc))
        h0_ = lbm.wstack(W, _plane(n_inf_0 * jnp.exp(
            -ctx.setting("ez") * psi_bc * ctx.setting("el_kbT"))))
        h1_ = lbm.wstack(W, _plane(n_inf_1 * jnp.exp(
            ctx.setting("ez") * psi_bc * ctx.setting("el_kbT"))))
        return jnp.concatenate([phi_, g_, f_, h0_, h1_])

    def pressure(stack, side):
        from tclb_tpu.models.d2q9 import _zou_he_x
        phi_, g_, f_, h0_, h1_ = (stack[9 * i:9 * i + 9] for i in range(5))
        rho_b = ctx.setting("rho_bc") if side == "W" else 1.0
        f_ = _zou_he_x(f_, rho_b, "pressure", side)
        g_ = lbm.perm(g_, OPP)
        h0_ = lbm.wstack(W, _plane(n_inf_0))
        h1_ = lbm.wstack(W, _plane(n_inf_1))
        phi_ = lbm.wstack(WP, _plane(phi_bc))
        return jnp.concatenate([phi_, g_, f_, h0_, h1_])

    def symmetry(stack, top):
        # reflect_to (2,6,5) <- (4,7,8) for bottom; reverse for top
        if top:
            sel, src = (4, 7, 8), (2, 6, 5)
        else:
            sel, src = (2, 6, 5), (4, 7, 8)
        out = []
        for b in range(5):
            grp = stack[9 * b:9 * b + 9]
            planes = [grp[i] for i in range(9)]
            for t, sfrom in zip(sel, src):
                planes[t] = grp[sfrom]
            out.append(jnp.stack(planes))
        return jnp.concatenate(out)

    s = ctx.boundary_case(s, {
        ("Wall", "Solid"): wall,
        "WPressure": lambda st: pressure(st, "W"),
        "EPressure": lambda st: pressure(st, "E"),
        "BottomSymmetry": lambda st: symmetry(st, top=False),
        "TopSymmetry": lambda st: symmetry(st, top=True),
    })
    phi, g, f, h0, h1 = (s[9 * i:9 * i + 9] for i in range(5))

    # ---------------- collision (reference CollisionBGK :241-317) ------- #
    rho, n0, n1, psi, pot, rho_e, gpsi, force = _macro(
        ctx, f, g, phi, h0, h1)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    # measured velocity (with half-force) enters the ion equilibria
    umx = ux + force[0] * 0.5
    umy = uy + force[1] * 0.5

    d_ion = ctx.setting("D")
    tau_d = 3.0 * d_ion + 0.5
    bk = 3.0 * d_ion / tau_d * ctx.setting("el_kbT")
    ez = ctx.setting("ez")
    h0c, h1c = [], []
    for i in range(9):
        cu = float(E[i, 0]) * umx + float(E[i, 1]) * umy
        S = float(E[i, 0]) * gpsi[0] + float(E[i, 1]) * gpsi[1]
        heq0 = float(W[i]) * n0 * (1.0 - cu / CS2)
        heq1 = float(W[i]) * n1 * (1.0 - cu / CS2)
        h0c.append(h0[i] - (h0[i] - heq0) / tau_d
                   - float(W[i]) * ez * S * n0 * bk)
        h1c.append(h1[i] - (h1[i] - heq1) / tau_d
                   + float(W[i]) * ez * S * n1 * bk)
    h0c = jnp.stack(h0c)
    h1c = jnp.stack(h1c)

    gc = _guo_collide(g, psi, rho_e, TAU_PSI, ctx.setting("dt"),
                      ctx.setting("epsilon"))
    phic = phi - (phi - lbm.wstack(WP, pot)) / TAU_PHI

    omega = 1.0 / (3.0 * ctx.setting("nu") + 0.5)
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    feq2 = lbm.equilibrium(E, W, rho, (ux + force[0], uy + force[1]))
    fc = f - omega * (f - feq) + (feq2 - feq)

    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    g = jnp.where(coll, gc, g)
    phi = jnp.where(coll, phic, phi)
    h0 = jnp.where(coll, h0c, h0)
    h1 = jnp.where(coll, h1c, h1)
    return ctx.store({"f": f, "g": g, "phi": phi, "h_0": h0, "h_1": h1})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    ones = jnp.ones(shape, dt)
    # g_i = wp0 psi0 for ALL i (reference Init :221-239) so that
    # getPsi returns psi0; phi likewise
    g = jnp.stack([ctx.setting("psi0") * WP0 * ones for _ in range(9)])
    phi = jnp.stack([ctx.setting("phi0") * WP0 * ones for _ in range(9)])
    f = lbm.equilibrium(E, W, ones, (jnp.zeros(shape, dt),) * 2)
    h0 = jnp.stack([ctx.setting("n_inf_0") * float(W[i]) * ones
                    for i in range(9)])
    h1 = jnp.stack([ctx.setting("n_inf_1") * float(W[i]) * ones
                    for i in range(9)])
    return ctx.store({"f": f, "g": g, "phi": phi, "h_0": h0, "h_1": h1})


def _q(fn):
    def wrap(ctx):
        s = _stack(ctx, _GROUPS)
        phi, g, f, h0, h1 = (s[9 * i:9 * i + 9] for i in range(5))
        return fn(ctx, *_macro(ctx, f, g, phi, h0, h1), f)
    return wrap


def build():
    def u_of(ctx, rho, n0, n1, psi, pot, rho_e, gpsi, force, f):
        dt = f.dtype
        ux = lbm.edot(E[:, 0], f) / rho
        uy = lbm.edot(E[:, 1], f) / rho
        return jnp.stack([ux + 0.5 * force[0], uy + 0.5 * force[1],
                          jnp.zeros_like(ux)])

    def gpsi_q(ctx, rho, n0, n1, psi, pot, rho_e, gpsi, force, f):
        return jnp.stack([gpsi[0], gpsi[1], jnp.zeros_like(gpsi[0])])

    def gphi_q(ctx):
        s = _stack(ctx, _GROUPS)
        phi = s[0:9]
        pot = _psi_of(phi)
        gx, gy = _grad_of(phi, pot)
        return jnp.stack([gx, gy, jnp.zeros_like(gx)])

    return _def().finalize().bind(
        run=run, init=init,
        quantities={
            "F": _q(lambda ctx, rho, n0, n1, psi, pot, rho_e, gpsi, force,
                    f: jnp.stack([force[0], force[1],
                                  jnp.zeros_like(force[0])])),
            "U": _q(u_of),
            "Rho": _q(lambda ctx, rho, *a: rho),
            "n0": _q(lambda ctx, rho, n0, *a: n0),
            "n1": _q(lambda ctx, rho, n0, n1, *a: n1),
            "Psi": _q(lambda ctx, rho, n0, n1, psi, *a: psi),
            "Phi": _q(lambda ctx, rho, n0, n1, psi, pot, *a: pot),
            "GradPsi": _q(gpsi_q),
            "GradPhi": gphi_q,
            "rho_e": _q(lambda ctx, rho, n0, n1, psi, pot, rho_e, *a:
                        rho_e),
        })
