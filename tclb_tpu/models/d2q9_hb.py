"""d2q9_hb — thermal d2q9 with shear-driven material destruction
(Herschel-Bulkley-type erosion model).

Behavioral parity target: reference model ``d2q9_hb``
(reference src/d2q9_hb/Dynamics.R, hand-written Dynamics.c): the d2q9_heat
structure (flow f + advected scalar T) plus shear-stress quantities
(Q/Qxx/Qxy/Qyy/SS from the non-equilibrium stress) and ``Destroy`` nodes
where the scalar erodes at ``DestructionRate * SS^DestructionPower``;
DestroyedCellFlux tracks the eroded amount.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import d2q9_heat
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)


def _def():
    d = d2q9_heat._def()
    d.name = "d2q9_hb"
    d.description = "thermal d2q9 with shear-driven destruction"
    d.add_quantity("Q")
    d.add_quantity("Qxx")
    d.add_quantity("Qxy")
    d.add_quantity("Qyy")
    d.add_quantity("SS", unit="N/m2")
    d.add_setting("DestructionRate", default=0.0)
    d.add_setting("DestructionPower", default=1.0)
    d.add_global("DestroyedCellFlux")
    d.add_node_type("Destroy", "ADDITIONALS")
    d.add_node_type("Outlet2", "ADDITIONALS")
    return d


def _neq_stress(ctx: NodeCtx, f: jnp.ndarray):
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    fneq = f - feq
    qxx = lbm.edot(E[:, 0] * E[:, 0], fneq)
    qxy = lbm.edot(E[:, 0] * E[:, 1], fneq)
    qyy = lbm.edot(E[:, 1] * E[:, 1], fneq)
    ss = jnp.sqrt(qxx * qxx + 2.0 * qxy * qxy + qyy * qyy)
    return qxx, qxy, qyy, ss


def run(ctx: NodeCtx) -> jnp.ndarray:
    out = d2q9_heat.run(ctx)   # write-set dict {"f": ..., "T": ...}
    # erosion: Destroy nodes lose scalar at rate * SS^power
    f = out["f"]
    fT = out["T"]
    _, _, _, ss = _neq_stress(ctx, f)
    rate = ctx.setting("DestructionRate") \
        * jnp.power(jnp.maximum(ss, 1e-30), ctx.setting("DestructionPower"))
    destroy = ctx.nt_is("Destroy")
    scale = jnp.where(destroy, jnp.maximum(1.0 - rate, 0.0),
                      jnp.ones_like(rate))
    ctx.add_global("DestroyedCellFlux",
                   jnp.sum(fT, axis=0) * (1.0 - scale), where=destroy)
    return {**out, "T": fT * scale[None]}


def build():
    q = {"Rho": d2q9_heat.get_rho, "T": d2q9_heat.get_t,
         "U": d2q9_heat.get_u}

    def mk(i):
        return lambda ctx: _neq_stress(ctx, ctx.group("f"))[i]

    q.update({"Qxx": mk(0), "Qxy": mk(1), "Qyy": mk(2), "SS": mk(3),
              "Q": mk(3)})
    return _def().finalize().bind(run=run, init=d2q9_heat.init,
                                  quantities=q)
