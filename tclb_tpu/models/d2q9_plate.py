"""d2q9_plate — plate drag optimization (LES MRT with wall reaction forces).

Behavioral parity target: reference model ``d2q9_plate``
(reference src/d2q9_plate/Dynamics.R, ADJOINT=1): MRT with Smagorinsky
eddy viscosity (``tau0``/``Smag``), zonal Velocity/Density, and the plate
reaction-force objectives ForceX/ForceY/Moment/PowerX accumulated by
momentum exchange at Wall nodes — the drag-optimization case.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def():
    d = family.base_def("d2q9_plate", E, "plate drag optimization")
    d.add_setting("tau0", default=1.0,
                  comment="base relaxation time")
    d.add_setting("Smag", default=0.16)
    d.add_global("ForceX", comment="reaction force X")
    d.add_global("ForceY", comment="reaction force Y")
    d.add_global("Moment", comment="reaction moment")
    d.add_global("PowerX", comment="power extracted in X")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    # momentum exchange on walls = plate reaction force
    # (reference ForceX/ForceY globals)
    wall = ctx.nt_is("Wall")
    ex = lbm.edot(E[:, 0], f)
    ey = lbm.edot(E[:, 1], f)
    ctx.add_global("ForceX", 2.0 * ex, where=wall)
    ctx.add_global("ForceY", 2.0 * ey, where=wall)
    vel = ctx.setting("Velocity")
    ctx.add_global("PowerX", 2.0 * ex * vel, where=wall)
    ctx.add_global("Moment", 2.0 * ey, where=wall)

    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    om0 = 1.0 / (3.0 * ctx.setting("nu") + 0.5)
    om_eff = lbm.smagorinsky_omega_unrolled(E, f, feq, rho, om0, ctx.setting("Smag"))
    fc = f + om_eff[None] * (feq - f)
    gx, gy = family.gravity_of(ctx)
    fc = fc + (lbm.equilibrium(E, W, rho, (ux + gx, uy + gy)) - feq)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
