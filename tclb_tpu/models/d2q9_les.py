"""d2q9_les — 2D BGK with Smagorinsky subgrid closure.

Behavioral parity target: reference model ``d2q9_les``
(reference src/d2q9_les/Dynamics.R, Dynamics.c.Rt): the relaxation rate is
reduced locally by an eddy viscosity computed from the non-equilibrium
momentum flux (Hou et al. closed form).
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def():
    d = family.base_def("d2q9_les", E, "2D BGK + Smagorinsky LES")
    d.add_setting("Smag", default=0.16, comment="Smagorinsky constant")
    d.add_node_type("TopSymmetry", "BOUNDARY")
    d.add_node_type("BottomSymmetry", "BOUNDARY")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    om_eff = lbm.smagorinsky_omega_unrolled(E, f, feq, rho, ctx.setting("omega"),
                                   ctx.setting("Smag"))
    fc = f + om_eff[None] * (feq - f)
    gx, gy = family.gravity_of(ctx)
    fc = fc + (lbm.equilibrium(E, W, rho, (ux + gx, uy + gy)) - feq)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
