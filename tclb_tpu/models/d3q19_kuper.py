"""d3q19_kuper — 3D Kupershtokh pseudopotential multiphase.

Behavioral parity target: reference model ``d3q19_kuper``
(reference src/d3q19_kuper/Dynamics.R, Dynamics.c.Rt): the 3D version of
d2q9_kuper — same vdW pseudopotential ``phi`` stage, exact-difference force
over the 18 neighbor directions with shell weights, BGK+force collision.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models import family
from tclb_tpu.models.d3q19 import E, OPP, W
from tclb_tpu.models.d2q9_kuper import _eos_pressure
from tclb_tpu.ops import lbm

# gradient shell weights: 18 * w_i gives (1, 1/2) on (axis, diagonal)
GS = 18.0 * W


def _def() -> ModelDef:
    d = ModelDef("d3q19_kuper", ndim=3,
                 description="3D Kupershtokh pseudopotential multiphase")
    d.add_densities("f", E)
    d.add_field("phi", dx=(-1, 1), dy=(-1, 1), dz=(-1, 1))
    d.add_stage("BaseIteration", "Run")
    d.add_stage("CalcPhi", "CalcPhi")
    d.add_stage("BaseInit", "Init", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "CalcPhi"))
    d.add_action("Init", ("BaseInit", "CalcPhi"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("P", unit="Pa")
    d.add_setting("omega", default=1.0)
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Temperature", default=0.56)
    d.add_setting("FAcc", default=1.0)
    d.add_setting("Magic", default=0.01)
    d.add_setting("MagicA", default=-0.152)
    d.add_setting("MagicF", default=-2.0 / 3.0)
    for ax in ("X", "Y", "Z"):
        d.add_setting(f"Gravitation{ax}")
    d.add_setting("Density", default=3.26, zonal=True)
    d.add_setting("Wetting", default=1.0)
    return d


def calc_phi(ctx: NodeCtx):
    f = ctx.group("f")
    rho = jnp.sum(f, axis=0)
    rho = jnp.where(ctx.nt_in_group("BOUNDARY"), ctx.setting("Density"), rho)
    p = ctx.setting("Magic") * _eos_pressure(rho, ctx.setting("Temperature"))
    phi = ctx.setting("FAcc") * jnp.sqrt(jnp.maximum(rho / 3.0 - p, 0.0))
    return {"phi": phi}


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    a = ctx.setting("MagicA")
    phi0 = ctx.load("phi")
    fx = jnp.zeros_like(phi0)
    fy = jnp.zeros_like(phi0)
    fz = jnp.zeros_like(phi0)
    for i in range(1, 19):
        # phi sampled at -e_i like the reference (see d2q9_kuper._force)
        phii = ctx.load("phi", -int(E[i, 0]), -int(E[i, 1]), -int(E[i, 2]))
        r = a * phii * phii + (1.0 - 2.0 * a) * phii * phi0
        g = float(GS[i])
        fx = fx + g * r * float(E[i, 0])
        fy = fy + g * r * float(E[i, 1])
        fz = fz + g * r * float(E[i, 2])
    s = ctx.setting("MagicF")
    rho = jnp.sum(f, axis=0)
    u = tuple(lbm.edot(E[:, ax], f) / rho
              for ax in range(3))
    grav = family.gravity_of(ctx)
    frc = (s * fx / rho + grav[0], s * fy / rho + grav[1],
           s * fz / rho + grav[2])
    feq = lbm.equilibrium(E, W, rho, u)
    fc = f + ctx.setting("omega") * (feq - f)
    u2 = tuple(u[ax] + frc[ax] for ax in range(3))
    fc = fc + (lbm.equilibrium(E, W, rho, u2) - feq)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(ctx.setting("Density"), shape).astype(dt)
    f = lbm.equilibrium(E, W, rho,
                        tuple(jnp.zeros(shape, dt) for _ in range(3)))
    return ctx.store({"f": f})


def get_p(ctx):
    rho = jnp.sum(ctx.group("f"), axis=0)
    return ctx.setting("Magic") * _eos_pressure(rho,
                                                ctx.setting("Temperature"))


def build():
    q = family.make_getters(E, force_of=family.gravity_of)
    q["P"] = get_p
    return _def().finalize().bind(run=run, init=init,
                                  stages={"CalcPhi": calc_phi},
                                  quantities=q)
