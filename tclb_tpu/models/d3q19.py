"""d3q19 — 3D MRT.

Behavioral parity target: reference model ``d3q19``
(reference src/d3q19/Dynamics.R, Dynamics.c.Rt): 19-velocity MRT with
velocity/pressure faces and body force.  The moment basis is built
numerically by Gram-Schmidt over the monomials (the reference builds the
equivalent basis symbolically, src/lib/d3q19.R + lib/feq.R); conserved
moments are untouched, the stress moments relax with ``omega``, higher
moments with the free rates ``S_high`` (default 1 = project to equilibrium).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.ops import lbm

E = lbm.d3q19_velocities()
W = lbm.weights(E)
OPP = lbm.opposite(E)
M = lbm.gram_schmidt_basis(E)

def _def():
    d = family.base_def("d3q19", E, "3D MRT", faces="WE", symmetries="NS")
    d.add_setting("S_high", default=1.0,
                  comment="relaxation rate of the higher moments")
    return d


def collide(ctx: NodeCtx, f: jnp.ndarray) -> jnp.ndarray:
    """Two-rate MRT: rows 0-3 (rho, momentum) conserved, rows 4-9 (the
    six degree-2 stress moments) relax with ``omega``, the rest with
    ``S_high`` — evaluated via the exact stress-projection identity
    (lbm.two_rate_relax) instead of the full moment transform pair."""
    rho = jnp.sum(f, axis=0)
    u = tuple(lbm.edot(E[:, a], f) / rho
              for a in range(3))
    feq = lbm.equilibrium(E, W, rho, u)
    fneq = [f[k] - feq[k] for k in range(19)]
    relax = lbm.two_rate_relax(M, 4, 10, fneq,
                               1.0 - ctx.setting("omega"),
                               1.0 - ctx.setting("S_high"))
    g = family.gravity_of(ctx)
    u2 = tuple(u[a] + g[a] for a in range(3))
    return relax + lbm.equilibrium(E, W, rho, u2)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    # pin collide's input and output: without this XLA fuses the
    # boundary select chain and the collision select into the relaxation
    # arithmetic, and the FMA contraction it picks depends on the
    # surrounding graph — so the XLA step and the Pallas z-slab kernel
    # (which barriers the same two seams) would differ by 1 ULP instead
    # of being bit-identical
    f = lbm.pin(f)
    fc = lbm.pin(collide(ctx, f))
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
