"""d3q27_viscoplastic — Bingham viscoplastic rheology (regularized MRT).

Behavioral parity target: reference model ``d3q27_viscoplastic``
(reference src/d3q27_viscoplastic/Dynamics.c — hand-written, not
templated).  Single-step stress-projection collision (Vikhansky-style):

* He-forcing terms ``Phi_i = 3 w_i rho (e_i.F)`` and equilibria shifted by
  ``-Phi/2`` (Dynamics.c:440-478);
* the non-equilibrium momentum flux ``S_ab = sum_i (f_i - feq_i) e_a e_b``
  is made deviatoric and contracted; nodes with ``S:S < 2 Y^2`` are
  UNYIELDED: the stress is written back unscaled (no relaxation — rigid),
  ``yield_stat = 1``, ``nu_app = 0``; yielded nodes scale the stress by
  ``c = (6 nu - 1)/(6 nu + 1) + sqrt(2/S:S) Y omega`` — plain BGK recovery
  for ``Y = 0`` — and report ``nu_app = nu + Y sqrt(S:S / 2)``
  (Dynamics.c:481-520);
* write-back ``f_i = 4.5 w_i (e_i . S . e_i') + feq_i + Phi_i`` where the
  quadratic form carries the off-diagonal doubling of the reference's
  1/3-1/12-1/48 coefficient table (Dynamics.c:522-538);
* d3q27 Zou/He velocity & pressure faces on X and Y
  (``{E,W,S,N}{Velocity,Pressure}_ZouHe``): unknowns take
  ``f_bb + 6 w_i (e_i.J)`` with the normal momentum imposed/solved and the
  tangential J chosen to zero the face's tangential momentum
  (J_t = -3 x tangential momentum of the wall-parallel knowns)
  (Dynamics.c:175-327);
* Y/Z mirror symmetries, slice-monitor globals (XY/XZ/YZ slices).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.ops import cumulant, lbm

E = cumulant.velocity_set(3)
W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def() -> ModelDef:
    d = ModelDef("d3q27_viscoplastic", ndim=3,
                 description="Bingham viscoplastic (regularized MRT)")
    d.add_densities("f", E)
    d.add_density("nu_app")
    d.add_density("yield_stat")
    d.add_quantity("P", unit="Pa")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("nu_app", unit="m2/s")
    d.add_quantity("yield_stat")
    d.add_setting("nu", default=1 / 6, comment="plastic viscosity")
    d.add_setting("Velocity", default=0.0, zonal=True)
    d.add_setting("Pressure", default=0.0, zonal=True)
    d.add_setting("ForceX")
    d.add_setting("ForceY")
    d.add_setting("ForceZ")
    d.add_setting("YieldStress", default=0.0)
    # Flux/TotalRho are declared but never accumulated in the reference
    # either (no AddToFlux/AddToTotalRho in Dynamics.c) — config parity
    d.add_global("Flux", unit="m3/s")
    d.add_global("TotalRho", unit="kg")
    for pl in ("XY", "XZ", "YZ"):
        for gname in ("vx", "vy", "vz", "rho1", "rho2", "area"):
            d.add_global(pl + gname)
    for nt in ("SymmetryY", "SymmetryZ",
               "NVelocity_ZouHe", "SVelocity_ZouHe", "EVelocity_ZouHe",
               "WVelocity_ZouHe", "NPressure_ZouHe", "SPressure_ZouHe",
               "EPressure_ZouHe", "WPressure_ZouHe"):
        d.add_node_type(nt, "BOUNDARY")
    for nt in ("XYslice1", "XZslice1", "YZslice1",
               "XYslice2", "XZslice2", "YZslice2"):
        d.add_node_type(nt, "ADDITIONALS")
    return d


def _zou_he_3d(ctx, f, axis, side, kind):
    """d3q27 Zou/He on an axis-normal face (reference Dynamics.c:175-327).

    ``side=+1``: fluid lies in +axis (unknowns move +axis, a W/S-type
    face); ``side=-1``: the opposite.  Velocity kind imposes the zonal
    ``Velocity`` as the +axis velocity; pressure kind imposes
    ``rho = 1 + 3 Pressure``.
    """
    dt = f.dtype
    en = E[:, axis]
    tang_idx = np.where(en == 0)[0]
    into_idx = np.where(en == -side)[0]
    unk_idx = np.where(en == side)[0]
    s_t = sum(f[int(i)] for i in tang_idx)
    s_i = sum(f[int(i)] for i in into_idx)
    if kind == "velocity":
        v = ctx.setting("Velocity")
        rho = (s_t + 2.0 * s_i) / (1.0 - side * v)
        jn = v * rho          # reference: Jn = Velocity * rho (signed)
    else:
        rho = 1.0 + 3.0 * ctx.setting("Pressure")
        jn = (s_t + 2.0 * s_i - rho) / (-side)
    # tangential J zeroing the face's tangential momentum
    jt = {}
    for t_ax in range(3):
        if t_ax == axis:
            continue
        jt[t_ax] = -3.0 * sum(float(E[int(i), t_ax]) * f[int(i)]
                              for i in tang_idx if E[int(i), t_ax])
    out = [f[i] for i in range(27)]
    for i in unk_idx:
        i = int(i)
        ej = float(E[i, axis]) * jn
        for t_ax, val in jt.items():
            if E[i, t_ax]:
                ej = ej + float(E[i, t_ax]) * val
        out[i] = f[int(OPP[i])] + 6.0 * float(W[i]) * ej
    return jnp.stack(out)


def _mirror(f, axis):
    perm = np.zeros(27, dtype=np.int32)
    for i, e in enumerate(E):
        m = e.copy()
        m[axis] = -m[axis]
        (j,) = np.where((E == m).all(axis=1))
        perm[i] = j[0]
    return lbm.perm(f, perm)


def _collision(ctx: NodeCtx, f):
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    fx = ctx.setting("ForceX")
    fy = ctx.setting("ForceY")
    fz = ctx.setting("ForceZ")
    ux = lbm.edot(E[:, 0], f) / rho + fx * 0.5
    uy = lbm.edot(E[:, 1], f) / rho + fy * 0.5
    uz = lbm.edot(E[:, 2], f) / rho + fz * 0.5
    usq = ux * ux + uy * uy + uz * uz

    phi, feq = [], []
    for i in range(27):
        ex, ey, ez = (float(v) for v in E[i])
        ef = ex * fx + ey * fy + ez * fz
        p = 3.0 * float(W[i]) * rho * ef if (ex or ey or ez) \
            else jnp.zeros_like(rho)
        eu = ex * ux + ey * uy + ez * uz
        fe = float(W[i]) * rho * (1.0 + 3.0 * eu * (1.0 + 1.5 * eu)
                                  - 1.5 * usq) - 0.5 * p
        phi.append(p)
        feq.append(fe)

    # non-equilibrium momentum flux, deviatoric
    S = {}
    for a in range(3):
        for b in range(a, 3):
            s = None
            for i in range(27):
                c = float(E[i, a] * E[i, b])
                if c == 0.0:
                    continue
                t = c * (f[i] - feq[i])
                s = t if s is None else s + t
            S[(a, b)] = s
    tr3 = (S[(0, 0)] + S[(1, 1)] + S[(2, 2)]) / 3.0
    for a in range(3):
        S[(a, a)] = S[(a, a)] - tr3
    scontr = sum((1.0 if a == b else 2.0) * S[(a, b)] * S[(a, b)]
                 for a in range(3) for b in range(a, 3))

    y = ctx.setting("YieldStress")
    nu = ctx.setting("nu")
    omega = 1.0 / (3.0 * nu + 0.5)
    unyielded = scontr < 2.0 * y * y
    safe = jnp.where(scontr > 0, scontr, 1.0)
    sq2s = jnp.sqrt(2.0 / safe)
    c_bgk = (6.0 * nu - 1.0) / (6.0 * nu + 1.0)
    c = jnp.where(y < 1e-15, c_bgk, c_bgk + sq2s * y * omega)
    scale = jnp.where(unyielded, 1.0, c)
    nu_app = jnp.where(unyielded, 0.0, nu + y / sq2s)
    yield_stat = jnp.where(unyielded, 1.0, 0.0)

    out = []
    for i in range(27):
        ex, ey, ez = (float(v) for v in E[i])
        quad = None
        for (a, b), s_ab in S.items():
            cc = (E[i, a] * E[i, b]) * (1.0 if a == b else 2.0)
            if cc == 0:
                continue
            t = float(cc) * s_ab
            quad = t if quad is None else quad + t
        coef = 4.5 * float(W[i]) * quad * scale if quad is not None \
            else jnp.zeros_like(rho)
        out.append(coef + feq[i] + phi[i])
    fc = jnp.stack(out)

    # slice monitors (reference Dynamics.c:540-578)
    for pl in ("XY", "XZ", "YZ"):
        s1 = ctx.nt_is(pl + "slice1")
        ctx.add_global(pl + "vx", ux, where=s1)
        ctx.add_global(pl + "vy", uy, where=s1)
        ctx.add_global(pl + "vz", uz, where=s1)
        ctx.add_global(pl + "rho1", rho, where=s1)
        ctx.add_global(pl + "area", jnp.ones_like(rho), where=s1)
        ctx.add_global(pl + "rho2", rho, where=ctx.nt_is(pl + "slice2"))
    return fc, nu_app, yield_stat


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = ctx.boundary_case(f, {
        "EPressure_ZouHe": lambda f: _zou_he_3d(ctx, f, 0, -1, "pressure"),
        "WPressure_ZouHe": lambda f: _zou_he_3d(ctx, f, 0, +1, "pressure"),
        "SPressure_ZouHe": lambda f: _zou_he_3d(ctx, f, 1, +1, "pressure"),
        "NPressure_ZouHe": lambda f: _zou_he_3d(ctx, f, 1, -1, "pressure"),
        "WVelocity_ZouHe": lambda f: _zou_he_3d(ctx, f, 0, +1, "velocity"),
        "NVelocity_ZouHe": lambda f: _zou_he_3d(ctx, f, 1, -1, "velocity"),
        "SVelocity_ZouHe": lambda f: _zou_he_3d(ctx, f, 1, +1, "velocity"),
        "EVelocity_ZouHe": lambda f: _zou_he_3d(ctx, f, 0, -1, "velocity"),
        "SymmetryY": lambda f: _mirror(f, 1),
        "SymmetryZ": lambda f: _mirror(f, 2),
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
    })
    fc, nu_app, yield_stat = _collision(ctx, f)
    coll = ctx.nt_is("MRT")[None]
    f = jnp.where(coll, fc, f)
    return ctx.store({"f": f,
                      "nu_app": jnp.where(coll[0], nu_app,
                                          ctx.density("nu_app")),
                      "yield_stat": jnp.where(coll[0], yield_stat,
                                              ctx.density("yield_stat"))})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(1.0 + 3.0 * ctx.setting("Pressure"),
                           shape).astype(dt)
    zero = jnp.zeros(shape, dt)
    f = lbm.equilibrium(E, W, rho, (zero, zero, zero))
    return ctx.store({"f": f, "nu_app": zero, "yield_stat": zero})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    u = [(lbm.edot(E[:, a], f)
          + 0.5 * ctx.setting(n)) / rho
         for a, n in enumerate(("ForceX", "ForceY", "ForceZ"))]
    return jnp.stack(u)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities={
            "P": lambda c: (jnp.sum(c.group("f"), axis=0) - 1.0) / 3.0,
            "U": get_u,
            "nu_app": lambda c: c.density("nu_app"),
            "yield_stat": lambda c: c.density("yield_stat"),
        })
