"""d3q27_cumulant_qibb_small — cumulant collision with interpolated
(Q-cut) bounce-back for off-grid walls.

Behavioral parity target: reference model ``d3q27_cumulant_qibb_small``
(reference src/d3q27_cumulant_qibb_small/Dynamics.c.Rt; Q-cut storage
``cut_t``/``CUT_LEN`` in src/types.h:16-20, painted by
Lattice::CutsOverwrite, src/Lattice.cu.Rt:907-922).  Per streaming link a
wall-cut distance ``q in [0, 1]`` (fraction of the link inside the fluid)
drives Bouzidi-style interpolated bounce-back around the cumulant
collision:

* pre-collision (Dynamics.c.Rt:302-308): on a QIBB node, every cut link
  replaces its pulled-in population ``f[bounce(i)]`` (which came from the
  solid side) with the node's OWN pre-streaming ``f_i`` — plain on-node
  bounce-back — and the post-patch stack is saved as ``f_pre``;
* post-collision (:480-489): cut links blend
  ``f_i <- ((1-q) f_pre_i + q (f_i + f_bounce_i)) / (1 + q)``,
  which reduces to half-way bounce-back at q = 1/2 and anchors the
  zero-velocity plane at the true wall location.

Cut distances are stored as 26 per-direction Fields ``q[i]`` (sentinel
``-1`` = no cut = the reference's NO_CUT=255); the geometry helper
``utils.geometry.cuts_from_sdf`` paints them from a signed distance
function (the reference quantizes to 0.005 steps — we keep full floats).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.ops import cumulant, lbm

E = cumulant.velocity_set(3)
W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def():
    d = family.base_def("d3q27_cumulant_qibb_small", E,
                        "3D cumulant with interpolated (Q-cut) bounce-back",
                        faces="WENS", symmetries="NS", objectives=False)
    d.add_setting("nubuffer", default=0.01)
    d.add_setting("GalileanCorrection", default=1.0)
    d.add_setting("omega_bulk", default=1.0)
    for ax in ("X", "Y", "Z"):
        d.add_setting(f"Force{ax}")
    d.add_global("Flux", unit="m3/s")
    d.add_node_type("QIBB", "HO_BOUNDARY")
    d.add_node_type("Buffer", "ADDITIONALS")
    # per-direction wall-cut distances (reference cut_t Q planes)
    for i in range(1, 27):
        d.add_density(f"q[{i}]", group="q")
    d.add_quantity("P", unit="Pa")
    return d


def _force(ctx: NodeCtx):
    return tuple(ctx.setting(f"Force{ax}") + g for ax, g in
                 zip(("X", "Y", "Z"), family.gravity_of(ctx)))


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    f = family.apply_boundaries(ctx, f, E, W, OPP)

    qibb = ctx.nt_is("QIBB")
    cuts = ctx.group("q")          # (26, *shape), aligned with E[1:]
    # pre-collision: cut links take the node's own pre-streaming f_i in
    # place of the value pulled from the solid side
    planes = [f[i] for i in range(27)]
    for i in range(1, 27):
        has_cut = qibb & (cuts[i - 1] >= 0.0)
        own = ctx.load(f"f[{i}]")      # un-streamed (pre-pull) value
        b = int(OPP[i])
        planes[b] = jnp.where(has_cut, own, planes[b])
    f = jnp.stack(planes)
    fpre = f

    shape = f.shape[1:]
    om_visc = ctx.setting("omega")
    om_buffer = 1.0 / (3.0 * ctx.setting("nubuffer") + 0.5)
    om = jnp.where(ctx.nt_is("Buffer"), om_buffer, om_visc).astype(dt)
    F = f.reshape((3, 3, 3) + shape)
    Fp, rho, (ux, uy, uz) = cumulant.collide_d3q27(
        F, om, ctx.setting("omega_bulk"), force=_force(ctx),
        correlated=True, galilean=ctx.setting("GalileanCorrection"))
    coll = ctx.nt_in_group("COLLISION")
    f = jnp.where(coll[None], Fp.reshape((27,) + shape), f)
    ctx.add_global("Flux", ux, where=coll)

    # post-collision: interpolated bounce-back on cut links
    planes = [f[i] for i in range(27)]
    out = list(planes)
    for i in range(1, 27):
        has_cut = qibb & (cuts[i - 1] >= 0.0)
        q = jnp.maximum(cuts[i - 1], 0.0)
        b = int(OPP[i])
        blended = ((1.0 - q) * fpre[i] + q * (planes[i] + planes[b])) \
            / (1.0 + q)
        out[i] = jnp.where(has_cut, blended, out[i])
    f = jnp.stack(out)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    # preserve painted cuts across Init (they are static geometry data)
    return family.standard_init(ctx, E, W, extra={"q": ctx.group("q")})


def build():
    q = family.make_getters(E, force_of=_force)
    q["P"] = lambda c: (jnp.sum(c.group("f"), axis=0) - 1.0) / 3.0
    return _def().finalize().bind(run=run, init=init, quantities=q)
