"""d2q9_cumulant — 2D cumulant collision.

Behavioral parity target: reference model ``d2q9_cumulant``
(reference src/d2q9_cumulant/Dynamics.R, hand-written Dynamics.c).  The
collision is the tensor-product central-moment transform with Isserlis
closure (tclb_tpu/ops/cumulant.py) — the numerical equivalent of the
reference's symbolically generated cumulant kernel.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.ops import cumulant, lbm

E = cumulant.velocity_set(2)        # tensor order: (cx, cy), index -1,0,1
W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def():
    d = family.base_def("d2q9_cumulant", E, "2D cumulant collision")
    d.add_setting("omega_bulk", default=1.0,
                  comment="bulk (trace) relaxation rate")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    shape = f.shape[1:]
    F = f.reshape((3, 3) + shape)
    Fp, _, _ = cumulant.collide_d2q9(
        F, ctx.setting("omega"), ctx.setting("omega_bulk"),
        force=family.gravity_of(ctx))
    f = jnp.where(ctx.nt_in_group("COLLISION")[None],
                  Fp.reshape((9,) + shape), f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
