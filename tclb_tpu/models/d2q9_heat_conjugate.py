"""d2q9_heat_conjugate — conjugate solid/fluid heat transfer (EXTENSION).

NOT a reference model: this framework extra extends ``d2q9_heat`` so the
temperature lattice also collides inside Solid-tagged regions (pure
diffusion with ``SolidAlfa``) while flow bounces back there — conjugate
heat transfer through immersed solids.  (The reference model named
``d2q9_solid`` is the dendritic-solidification model, implemented
faithfully in :mod:`tclb_tpu.models.d2q9_solid`.)
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import d2q9_heat
from tclb_tpu.models.d2q9 import E
from tclb_tpu.models.d2q9_heat import _t_eq
from tclb_tpu.ops import lbm

W = lbm.weights(E)


def _def():
    d = d2q9_heat._def()
    d.name = "d2q9_heat_conjugate"
    d.description = "conjugate solid/fluid heat transfer"
    d.add_setting("SolidAlfa", default=0.05,
                  comment="thermal diffusivity of the solid")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    # solid_adiabatic=False: temperature conducts THROUGH Solid regions
    # (that is the whole point of the conjugate model)
    out = d2q9_heat.run(ctx, solid_adiabatic=False)
    # temperature additionally diffuses through Solid regions
    fT = out["T"]
    temp = jnp.sum(fT, axis=0)
    z = jnp.zeros_like(temp)
    om_s = 1.0 / (3.0 * ctx.setting("SolidAlfa") + 0.5)
    tc = fT + om_s * (_t_eq(temp, z, z) - fT)
    solid = ctx.nt_is("Solid")[None]
    return {**out, "T": jnp.where(solid, tc, fT)}


def build():
    return _def().finalize().bind(
        run=run, init=d2q9_heat.init,
        quantities={"Rho": d2q9_heat.get_rho, "T": d2q9_heat.get_t,
                    "U": d2q9_heat.get_u})
