"""Guo's Poisson-equation LBM solver — shared by d2q9_npe_guo and
d2q9_poison_boltzmann (reference src/d2q9_npe_guo/Dynamics.c.Rt:28-30 and
src/d2q9_poison_boltzmann/Dynamics.c.Rt:16-23 define the identical weights
and update).

The solver population ``g`` relaxes toward ``wp_i psi`` where
``wp = (1/9 - 1, 1/9 x8)`` (note the negative rest weight) with the source
``dt wps RD``, ``RD = -(2/3)(1/2 - tau_psi) dt rho_e / epsilon`` — the
reference multiplies by dt in BOTH places, giving a dt^2 scaling of the
source, and we reproduce that literally.  The potential is read back as
``psi = sum_{i>0} g_i / (1 - wp0)``.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.ops import lbm

WP0 = 1.0 / 9.0
WP = np.array([1.0 / 9.0 - 1.0] + [1.0 / 9.0] * 8)
WPS = np.array([0.0] + [1.0 / 8.0] * 8)


def psi_of(g):
    """Potential from the solver populations (reference getPsi)."""
    return sum(g[i] for i in range(1, 9)) / (1.0 - WP0)


def collide(g, psi, rho_e, tau_psi, dt, epsilon):
    """One Guo Poisson sweep: g' = g - (g - wp psi)/tau + dt wps RD."""
    rd = -2.0 / 3.0 * (0.5 - tau_psi) * dt * rho_e / epsilon
    # scalar-coefficient unroll (kernel-safe: no captured weight arrays)
    return jnp.stack([
        g[i] - (g[i] - float(WP[i]) * psi) / tau_psi
        + (dt * float(WPS[i])) * rd if WPS[i]
        else g[i] - (g[i] - float(WP[i]) * psi) / tau_psi
        for i in range(9)])
