"""d2q9_solid — dendritic solidification with flow, heat and solute.

Behavioral parity target: reference model ``d2q9_solid``
(reference src/d2q9_solid/Dynamics.R, Dynamics.c.Rt): THREE d2q9 MRT
lattices — ``f`` (flow), ``g`` (temperature ``rhoT``), ``h`` (solute
concentration ``C``) — coupled to a solid-fraction field ``fi_s`` and a
solid-side concentration ``Cs``:

* every non-conserved moment keeps ``1 - 1/(3 nu + 0.5)`` (all rates
  equal, reference OMEGA vector at Dynamics.c.Rt:303-307), so each MRT
  collision is algebraically a BGK relaxation with forcing applied by
  re-evaluating the equilibrium at the shifted velocity;
* the solute keep factor is blended per node with the solid fraction,
  ``kC_eff = kC (1 - fi_s) - fi_s`` (Dynamics.c.Rt:351-352): a fully
  solid node reflects the solute non-equilibrium;
* interface nodes (any fully-solid 9-neighborhood member,
  Dynamics.c.Rt:354-360) grow: ``dfi = (Cl_eq - C)/(Cl_eq (1 - k))``
  clamped to ``1 - fi_s``, rejecting ``dC = C (1-k) dfi`` into the
  liquid and banking ``Cs += C k dfi`` (:361-374);
* the local equilibrium interface concentration carries the
  Gibbs-Thomson curvature and 4-fold surface-energy anisotropy:
  ``Cl_eq = C0 + ((T - Teq) + GT K (1 - 15 SA cos(4 (theta - Theta0))))
  / m_L`` with K/theta from central differences of ``fi_s``
  (getCl_eq, Dynamics.c.Rt:70-91);
* flow feels the solid through ``a = (-2 ux fi_s, -2 uy fi_s +
  Buoyancy (rhoT/rho - T0))`` (:376-377), the temperature/solute
  equilibria ride the midpoint velocity ``u + a/2`` (:386-390);
* ``ForceTemperature`` / ``ForceConcentration`` nodes pin ``rhoT`` /
  ``C`` to the zonal settings; ``Seed`` nodes start fully solid
  (Init, :381-394); Obj nodes accumulate ``fi_s`` into the Material
  global (Run, :243).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, OPP, _zou_he_x
from tclb_tpu.ops import lbm

W = lbm.weights(E)
PI = 3.14159265358979311600


def _def() -> ModelDef:
    d = ModelDef("d2q9_solid", ndim=2,
                 description="dendritic solidification: flow + heat + "
                             "solute + solid fraction")
    d.add_densities("f", E)
    d.add_densities("g", E, group="g")
    d.add_densities("h", E, group="h")
    d.add_field("fi_s", dx=(-1, 1), dy=(-1, 1),
                comment="solid fraction (solidification)")
    d.add_density("Cs", comment="solid-side banked concentration")
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("T", unit="K")
    d.add_quantity("C", unit="1")
    d.add_quantity("Ct", unit="1")
    d.add_quantity("Cl_eq", unit="1")
    d.add_quantity("Solid", unit="1")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("K", unit="1/m")
    d.add_quantity("Theta", unit="1")
    d.add_setting("nu", default=1 / 6, comment="viscosity", unit="m2/s")
    d.add_setting("FluidAlfa", default=1.0, unit="m2/s",
                  comment="thermal diffusivity")
    d.add_setting("SoluteDiffusion", default=1.0, unit="m2/s",
                  comment="solute diffusion coefficient in liquid")
    d.add_setting("C0", comment="concentration 0")
    d.add_setting("T0", comment="temperature 0", unit="K")
    d.add_setting("Teq", comment="equilibrium interface temperature",
                  unit="K")
    d.add_setting("Velocity", default=0.0, zonal=True, unit="m/s")
    d.add_setting("Pressure", default=0.0, zonal=True, unit="Pa")
    d.add_setting("Temperature", default=0.0, zonal=True, unit="K")
    d.add_setting("Concentration", default=0.0, zonal=True)
    d.add_setting("Theta0", default=0.0, zonal=True, unit="d",
                  comment="angle of preferential growth")
    d.add_setting("PartitionCoef", default=0.1,
                  comment="partition coefficient k")
    d.add_setting("LiquidusSlope", default=1.0, comment="liquidus slope m")
    d.add_setting("GTCoef", default=0.0, unit="mK",
                  comment="Gibbs-Thomson coefficient")
    d.add_setting("SurfaceAnisotropy", default=0.0,
                  comment="degree of surface-energy anisotropy")
    d.add_setting("SoluteCapillar", default=0.0, unit="m",
                  comment="solutal capillary length d_0")
    d.add_setting("Buoyancy", default=0.0, unit="m/s2K",
                  comment="Boussinesq buoyancy coefficient")
    # OutFlux and Heater are DECLARED but unused, exactly like the
    # reference: Dynamics.R registers both, Dynamics.c.Rt's Run() never
    # accumulates OutFlux nor dispatches on Heater
    d.add_global("OutFlux")
    d.add_global("Material")
    d.add_node_type("Heater", "ADDITIONALS")
    d.add_node_type("ForceTemperature", "ADDITIONALS")
    d.add_node_type("ForceConcentration", "ADDITIONALS")
    d.add_node_type("Seed", "ADDITIONALS")
    d.add_node_type("Obj", "OBJECTIVE")
    return d


def _eq(rho, ux, uy):
    """Standard quadratic MRT equilibrium (reference lib/feq.R MRT_feq)."""
    return lbm.equilibrium(E, W, rho, (ux, uy))


def _fi_derivs(ctx: NodeCtx):
    """Central differences of the fi_s neighborhood (the reference's
    LBM_FD=FALSE branch, Dynamics.c.Rt:41-46)."""
    fi = {(dx, dy): ctx.load("fi_s", dx, dy)
          for dx in (-1, 0, 1) for dy in (-1, 0, 1)}
    dx_ = 0.5 * (fi[(1, 0)] - fi[(-1, 0)])
    dy_ = 0.5 * (fi[(0, 1)] - fi[(0, -1)])
    dxx = fi[(1, 0)] - 2.0 * fi[(0, 0)] + fi[(-1, 0)]
    dyy = fi[(0, 1)] - 2.0 * fi[(0, 0)] + fi[(0, -1)]
    dxy = 0.25 * (fi[(1, 1)] + fi[(-1, -1)]
                  - fi[(1, -1)] - fi[(-1, 1)])
    return fi, dx_, dy_, dxx, dyy, dxy


def _angle(dx_, dy_):
    """Gradient angle with quadrant corrections, 0 where the gradient
    vanishes (reference getCl_eq/getTheta acos + sign fixes)."""
    d2 = dx_ * dx_ + dy_ * dy_
    safe = jnp.where(d2 > 0.0, d2, 1.0)
    theta = jnp.arccos(jnp.sqrt(jnp.clip(dx_ * dx_ / safe, 0.0, 1.0)))
    theta = jnp.where(dx_ < 0, PI - theta, theta)
    theta = jnp.where(dy_ < 0, 2.0 * PI - theta, theta)
    return jnp.where(d2 > 0.0, theta, jnp.zeros_like(d2))


def _curvature(dx_, dy_, dxx, dyy, dxy):
    """Interface curvature from the fi_s derivatives, zero where the
    gradient vanishes (shared by the growth term and the K quantity)."""
    d2 = dx_ * dx_ + dy_ * dy_
    safe = jnp.where(d2 > 0.0, d2, 1.0)
    k = (2.0 * dx_ * dy_ * dxy - dx_ * dx_ * dyy
         - dy_ * dy_ * dxx) * safe ** -1.5
    return jnp.where(d2 > 0.0, k, jnp.zeros_like(d2)), d2, safe


def _curvature_theta(dx_, dy_, dxx, dyy, dxy):
    k, _, _ = _curvature(dx_, dy_, dxx, dyy, dxy)
    return k, _angle(dx_, dy_)


def _cl_eq(ctx: NodeCtx, T):
    """Equilibrium interface concentration with Gibbs-Thomson curvature
    undercooling + 4-fold anisotropy (reference getCl_eq).

    ``cos(4(theta - Theta0))`` is evaluated through the double-angle
    identities on the gradient components (``cos theta = dx/|grad|``)
    instead of the angle itself: exact same value, and no ``arccos`` —
    the one primitive Mosaic cannot lower, which kept this model off the
    fused engine."""
    _, dx_, dy_, dxx, dyy, dxy = _fi_derivs(ctx)
    k, d2, safe = _curvature(dx_, dy_, dxx, dyy, dxy)
    c2 = (dx_ * dx_ - dy_ * dy_) / safe
    s2 = 2.0 * dx_ * dy_ / safe
    c4 = c2 * c2 - s2 * s2
    s4 = 2.0 * s2 * c2
    # vanishing gradient: theta := 0 (the reference's convention), so
    # cos(4(theta - Theta0)) reduces to cos(4 Theta0)
    c4 = jnp.where(d2 > 0.0, c4, jnp.ones_like(d2))
    s4 = jnp.where(d2 > 0.0, s4, jnp.zeros_like(d2))
    th0 = 4.0 * ctx.setting("Theta0")
    cos4 = c4 * jnp.cos(th0) + s4 * jnp.sin(th0)
    aniso = 1.0 - 15.0 * ctx.setting("SurfaceAnisotropy") * cos4
    return ctx.setting("C0") + ((T - ctx.setting("Teq"))
                                + ctx.setting("GTCoef") * k * aniso
                                ) / ctx.setting("LiquidusSlope")


def _refill_w(q, target):
    """West-face equilibrium refill of an AD lattice: populations with
    e_x=+1 rebuilt from the target scalar (reference WVelocity/WPressure
    g/h blocks: rho = 6 (target - sum_{ex<=0}); g_i = w_i rho)."""
    keep = sum(q[i] for i in range(9) if E[i, 0] <= 0)
    s = 6.0 * (target - keep)
    return jnp.stack([jnp.asarray(float(W[i]), q.dtype) * s
                      if E[i, 0] == 1 else q[i] for i in range(9)])


def _refill_e(q):
    """East-face outflow refill: e_x=-1 populations from the e_x=+1 ones
    (reference EPressure/EVelocity g/h blocks)."""
    s = 6.0 * sum(q[i] for i in range(9) if E[i, 0] == 1)
    return jnp.stack([jnp.asarray(float(W[i]), q.dtype) * s
                      if E[i, 0] == -1 else q[i] for i in range(9)])


def run(ctx: NodeCtx) -> dict:
    f = ctx.group("f")
    g = ctx.group("g")
    h = ctx.group("h")
    fi_s = ctx.density("fi_s")
    cs = ctx.density("Cs")
    dt = f.dtype
    vel = ctx.setting("Velocity")
    den = 1.0 + ctx.setting("Pressure") / 3.0

    # ---- boundaries (reference Run switch, Dynamics.c.Rt:243-270) ----- #
    bb = ctx.nt_is("Wall") | ctx.nt_is("Solid")
    f = jnp.where(bb[None], lbm.perm(f, OPP), f)
    g = jnp.where(bb[None], lbm.perm(g, OPP), g)
    h = jnp.where(bb[None], lbm.perm(h, OPP), h)
    t_in = jnp.broadcast_to(ctx.setting("Temperature"),
                            f.shape[1:]).astype(dt)
    c_in = jnp.broadcast_to(ctx.setting("Concentration"),
                            f.shape[1:]).astype(dt)
    for name, ff, gg, hh in (
            ("WVelocity", _zou_he_x(f, vel, "velocity", "W"),
             _refill_w(g, t_in), _refill_w(h, c_in)),
            ("WPressure", _zou_he_x(f, den, "pressure", "W"),
             _refill_w(g, t_in), _refill_w(h, c_in)),
            # reference EVelocity touches only f (Dynamics.c.Rt:168-177);
            # g/h pass through unchanged.
            ("EVelocity", _zou_he_x(f, vel, "velocity", "E"), g, h),
            ("EPressure", _zou_he_x(f, 1.0, "pressure", "E"),
             _refill_e(g), _refill_e(h))):
        m = ctx.nt_is(name)
        f = jnp.where(m[None], ff, f)
        g = jnp.where(m[None], gg, g)
        h = jnp.where(m[None], hh, h)

    # ---- macroscopic fields ------------------------------------------- #
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    rhoT = jnp.sum(g, axis=0)
    c = jnp.sum(h, axis=0)

    ctx.add_global("Material", fi_s, where=ctx.nt_is("Obj"))

    # Dirichlet forcing (reference Q / dC, Dynamics.c.Rt:341-346)
    q_force = jnp.where(ctx.nt_is("ForceTemperature"),
                        ctx.setting("Temperature") - rhoT, 0.0)
    dc = jnp.where(ctx.nt_is("ForceConcentration"),
                   ctx.setting("Concentration") - c, 0.0)

    # keep factors (reference omega = 1 - 1/(3 nu + 0.5) etc.)
    kf = 1.0 - 1.0 / (3.0 * ctx.setting("nu") + 0.5)
    kt = 1.0 - 1.0 / (3.0 * ctx.setting("FluidAlfa") + 0.5)
    kc0 = 1.0 - 1.0 / (3.0 * ctx.setting("SoluteDiffusion") + 0.5)
    kc = (-kc0 - 1.0) * fi_s + kc0   # solid nodes reflect solute

    # ---- interface growth (Dynamics.c.Rt:354-374) --------------------- #
    fi_nb, *_ = _fi_derivs(ctx)
    all_liquid = None
    for off, plane in fi_nb.items():
        cond = plane < 1.0
        all_liquid = cond if all_liquid is None else (all_liquid & cond)
    interface = ~all_liquid
    cl_eq = _cl_eq(ctx, rhoT / rho)
    pk = ctx.setting("PartitionCoef")
    grow = interface & (cl_eq > c)
    dfi_raw = (cl_eq - c) / (cl_eq * (1.0 - pk))
    dfi = jnp.where(grow, jnp.minimum(dfi_raw, 1.0 - fi_s), 0.0)
    fi_new = fi_s + dfi
    # the reference OVERWRITES dC at growing nodes (:369) — mirror that
    dc = jnp.where(grow, c * (1.0 - pk) * dfi, dc)
    cs_new = cs + c * pk * dfi

    # ---- forcing accelerations (Dynamics.c.Rt:376-377) ---------------- #
    ax = -2.0 * ux * fi_new
    ay = -2.0 * uy * fi_new + ctx.setting("Buoyancy") * (
        rhoT / rho - ctx.setting("T0"))

    # ---- collisions: keep*(x - xeq(u)) + xeq(shifted) ----------------- #
    coll = ctx.nt_in_group("COLLISION")
    feq = _eq(rho, ux, uy)
    fc = kf * (f - feq) + _eq(rho, ux + ax, uy + ay)
    # g/h are emitted after `ux -= ax/2` in the reference
    # (Dynamics.c.Rt:371-388), so BOTH the relaxed non-equilibrium and the
    # re-added equilibrium ride the midpoint velocity u + a/2.
    uxm, uym = ux + 0.5 * ax, uy + 0.5 * ay
    geq = _eq(rhoT, uxm, uym)
    gc = kt * (g - geq) + _eq(rhoT + q_force, uxm, uym)
    heq = _eq(c, uxm, uym)
    hc = kc[None] * (h - heq) + _eq(c + dc, uxm, uym)

    f = jnp.where(coll[None], fc, f)
    g = jnp.where(coll[None], gc, g)
    h = jnp.where(coll[None], hc, h)
    fi_out = jnp.where(coll, fi_new, fi_s)
    cs_out = jnp.where(coll, cs_new, cs)
    return ctx.store({"f": f, "g": g, "h": h,
                      "fi_s": fi_out, "Cs": cs_out})


def init(ctx: NodeCtx) -> dict:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.ones(shape, dt)
    ux = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    uy = jnp.zeros(shape, dt)
    rhoT = jnp.broadcast_to(ctx.setting("Temperature"), shape).astype(dt)
    c = jnp.broadcast_to(ctx.setting("Concentration"), shape).astype(dt)
    seed = ctx.nt_is("Seed")
    fi = jnp.where(seed, 1.0, 0.0).astype(dt)
    cs = jnp.where(seed, c * ctx.setting("PartitionCoef"), 0.0).astype(dt)
    return ctx.store({"f": _eq(rho, ux, uy), "g": _eq(rhoT, ux, uy),
                      "h": _eq(c, ux, uy), "fi_s": fi, "Cs": cs})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_theta(ctx: NodeCtx) -> jnp.ndarray:
    """Growth angle from the ISOTROPIC (weighted) fi_s gradient — the
    reference getTheta uses the LBM_FD D1 form (Dynamics.c.Rt:117-131),
    unlike getCl_eq's central differences."""
    dt = ctx._fields.dtype
    over_c2 = 3.0
    dx_ = dy_ = None
    for i in range(9):
        ex, ey = int(E[i, 0]), int(E[i, 1])
        if ex == 0 and ey == 0:
            continue
        p = ctx.load("fi_s", ex, ey) * jnp.asarray(float(W[i]), dt)
        tx = p * ex if ex else None
        ty = p * ey if ey else None
        if tx is not None:
            dx_ = tx if dx_ is None else dx_ + tx
        if ty is not None:
            dy_ = ty if dy_ is None else dy_ + ty
    return _angle(dx_ * over_c2, dy_ * over_c2)


def build():
    def get_rho(ctx):
        return jnp.sum(ctx.group("f"), axis=0)

    def get_t(ctx):
        return jnp.sum(ctx.group("g"), axis=0)

    def get_c(ctx):
        return jnp.sum(ctx.group("h"), axis=0)

    def get_ct(ctx):
        return (jnp.sum(ctx.group("h"), axis=0)
                * (1.0 - ctx.density("fi_s")) + ctx.density("Cs"))

    def get_solid(ctx):
        return ctx.density("fi_s")

    def get_cl_eq(ctx):
        rho = jnp.sum(ctx.group("f"), axis=0)
        return _cl_eq(ctx, jnp.sum(ctx.group("g"), axis=0) / rho)

    def get_k(ctx):
        _, dx_, dy_, dxx, dyy, dxy = _fi_derivs(ctx)
        k, _ = _curvature_theta(dx_, dy_, dxx, dyy, dxy)
        return k

    return _def().finalize().bind(
        run=run, init=init,
        quantities={"Rho": get_rho, "T": get_t, "C": get_c, "Ct": get_ct,
                    "Cl_eq": get_cl_eq, "Solid": get_solid, "U": get_u,
                    "K": get_k, "Theta": get_theta})
