"""d2q9_pf_curvature — phase-field advection + CSF surface tension from
stencil curvature.

Behavioral parity target: reference model ``d2q9_pf_curvature``
(reference src/d2q9_pf_curvature/Dynamics.R, Dynamics.c.Rt, M. Dzikowski
2016; validated there by check.py fitting curvature of a circular drop).
On top of d2q9_pf it adds: a ``phi`` Field written by a ``CalcPhi`` stage
(walls store a -999 sentinel, Dynamics.c.Rt:329-369), a wall-repaired 9-point
stencil (``InitPhisStencil``, :185-245: sentinel links take the opposite
link's value, else the running mean of valid links), gradient/laplacian/
curvature from that stencil (:247-287), a surface-tension force
``SurfaceTensionRate * curv * n exp(-Decay phi^2)`` plus phase-interpolated
gravity (:157-181), and phase-interpolated viscosity (:492-550).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, _zou_he_x
from tclb_tpu.models.d2q9_pf import W, OPP, OPP18, _heq, init
from tclb_tpu.models.family import mirror_perm
from tclb_tpu.ops import lbm

MIRY = mirror_perm(E, 1)
MIRY18 = np.concatenate([MIRY, MIRY + 9])
SENTINEL = -999.0


def _def() -> ModelDef:
    d = ModelDef("d2q9_pf_curvature", ndim=2,
                 description="phase field with CSF curvature surface tension")
    d.add_densities("f", E)
    d.add_densities("h", E)
    d.add_field("phi", dx=(-1, 1), dy=(-1, 1))
    d.add_stage("BaseIteration", "Run")
    d.add_stage("CalcPhi", "CalcPhi")
    d.add_stage("BaseInit", "Init", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "CalcPhi"))
    d.add_action("Init", ("BaseInit", "CalcPhi"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("Normal", unit="1/m", vector=True)
    d.add_quantity("PhaseField", unit="1")
    d.add_quantity("Curvature", unit="1")
    d.add_quantity("InterfaceForce", unit="1", vector=True)
    d.add_setting("omega", comment="one over relaxation time (dense phase)")
    d.add_setting("omega_l", comment="one over relaxation time, light phase")
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity", default=0.0, zonal=True)
    d.add_setting("Pressure", default=0.0, zonal=True)
    d.add_setting("W", default=1.0, comment="anti-diffusivity coeff")
    d.add_setting("M", default=1.0, comment="mobility")
    d.add_setting("PhaseField", default=1.0, zonal=True)
    d.add_setting("GravitationX")
    d.add_setting("GravitationY")
    d.add_setting("GravitationX_l")
    d.add_setting("GravitationY_l")
    d.add_setting("SurfaceTensionDecay", default=100.0)
    d.add_setting("SurfaceTensionRate", default=0.1)
    d.add_setting("WettingAngle", default=0.0, zonal=True)
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    d.add_node_type("NSymmetry", "BOUNDARY")
    d.add_node_type("SSymmetry", "BOUNDARY")
    return d


def calc_phi(ctx: NodeCtx):
    """CalcPhi stage: phi = sum(h); walls write the -999 sentinel consumed
    by the stencil repair.  On a symmetry face the populations moving INTO
    the face are replaced by their y-mirrors before summing, so
    phi = sum_{ey==0} h + 2 sum_{ey<0} h on SSymmetry (ey>0 on NSymmetry) —
    reference src/d2q9_pf_curvature/Dynamics.c.Rt:329-360."""
    h = ctx.group("h")
    dt = h.dtype
    phi = jnp.sum(h, axis=0)
    ey = E[:, 1]
    tang = sum(h[i] for i in range(9) if ey[i] == 0)
    south = tang + 2.0 * sum(h[i] for i in range(9) if ey[i] < 0)
    north = tang + 2.0 * sum(h[i] for i in range(9) if ey[i] > 0)
    phi = jnp.where(ctx.nt_is("SSymmetry"), south, phi)
    phi = jnp.where(ctx.nt_is("NSymmetry"), north, phi)
    phi = jnp.where(ctx.nt_is("Wall"), jnp.asarray(SENTINEL, dt), phi)
    return {"phi": phi}


def _repaired_stencil(ctx: NodeCtx):
    """Wall-repaired phi stencil (reference InitPhisStencil,
    src/d2q9_pf_curvature/Dynamics.c.Rt:218-243): a -999 link takes the
    opposite link's value if valid, else the running mean of valid links
    (accumulated in reference order)."""
    phis = [ctx.load("phi", int(E[i, 0]), int(E[i, 1])) for i in range(9)]
    valid = [p > SENTINEL + 1.0 for p in phis]
    temp = jnp.zeros_like(phis[0])
    for j in range(9):
        temp = (j * temp + jnp.where(valid[j], phis[j], temp)) / (j + 1.0)
    rphis = []
    for j in range(9):
        opp = int(OPP[j])
        fallback = jnp.where(valid[opp], phis[opp], temp)
        rphis.append(jnp.where(valid[j], phis[j], fallback))
    return rphis


def _grad_phi(rphis):
    """Unweighted directional gradient sum_j rphis_j e_j (reference
    getGradientPhi, src/d2q9_pf_curvature/Dynamics.c.Rt:91-117)."""
    gx = sum(float(E[j, 0]) * rphis[j] for j in range(9) if E[j, 0])
    gy = sum(float(E[j, 1]) * rphis[j] for j in range(9) if E[j, 1])
    return gx, gy


def _normal(rphis):
    gx, gy = _grad_phi(rphis)
    ln = jnp.sqrt(gx * gx + gy * gy)
    safe = jnp.where(ln > 0, ln, 1.0)
    return (jnp.where(ln > 0, gx / safe, 0.0),
            jnp.where(ln > 0, gy / safe, 0.0))


def _curvature(ctx: NodeCtx, rphis):
    """curv = (lap(phi) - 2 phi (16 phi^2 - 4) W^2) / ((4 phi^2 - 1) W)
    (reference getCurvature, src/d2q9_pf_curvature/Dynamics.c.Rt:247-287);
    laplacian = 3 (mean_j phi_j - phi_0)."""
    w = ctx.setting("W")
    laplace = 3.0 * (sum(rphis) / 9.0 - rphis[0])
    phi0 = ctx.load("phi")
    ln = (4.0 * phi0 * phi0 - 1.0) * w
    # The reference guards only ln == 0 (Dynamics.c.Rt:280-284), which is
    # enough in f32 where 4 phi^2 - 1 underflows to exactly 0 in the +-1/2
    # bulk; in f64 roundoff leaves ln ~ 1e-15 there and the 0/0 amplifies
    # round-off noise beyond what the exp(-Decay phi^2) factor can absorb.
    # Thresholding is the f64-faithful version of the same guard: at a real
    # interface |ln| ~ W x O(1), orders of magnitude above it.
    dead = jnp.abs(ln) < 1e-6
    safe = jnp.where(dead, 1.0, ln)
    curv = (laplace - 2.0 * phi0 * (16.0 * phi0 * phi0 - 4.0) * w * w) / safe
    return jnp.where(dead, 0.0, curv)


def _force(ctx: NodeCtx, pf):
    """Surface tension + phase-interpolated gravity (reference getF,
    src/d2q9_pf_curvature/Dynamics.c.Rt:157-181).  ``pf`` is sum(h)."""
    rphis = _repaired_stencil(ctx)
    nx, ny = _normal(rphis)
    curv = _curvature(ctx, rphis)
    decay = jnp.exp(-ctx.setting("SurfaceTensionDecay") * pf * pf)
    rate = ctx.setting("SurfaceTensionRate")
    fx = rate * curv * nx * decay
    fy = rate * curv * ny * decay
    gx = ctx.setting("GravitationX")
    gy = ctx.setting("GravitationY")
    gxl = ctx.setting("GravitationX_l")
    gyl = ctx.setting("GravitationY_l")
    fx = fx + gxl - (pf - 0.5) * (gx - gxl)
    fy = fy + gyl - (pf - 0.5) * (gy - gyl)
    return fx, fy, (nx, ny)


def _boundaries(ctx: NodeCtx, fh: jnp.ndarray) -> jnp.ndarray:
    vel = ctx.setting("Velocity")
    den = 1.0 + 3.0 * ctx.setting("Pressure")
    pf_set = ctx.setting("PhaseField")

    def zou(kind, side, set_h):
        def apply(fh):
            f = _zou_he_x(fh[:9], vel if kind == "velocity" else den,
                          kind, side)
            h = fh[9:]
            if set_h:
                # pressure inlets/outlets also pin the phase field to its
                # zonal setting at the Zou/He velocity (reference
                # WPressure/EPressure, Dynamics.c.Rt:416-437)
                dt = f.dtype
                rho = jnp.sum(f, axis=0)
                ux = lbm.edot(E[:, 0], f) / rho
                uy = lbm.edot(E[:, 1], f) / rho
                pf = jnp.broadcast_to(pf_set, rho.shape).astype(dt)
                h = lbm.equilibrium(E, W, pf, (ux, uy))
            return jnp.concatenate([f, h])
        return apply

    return ctx.boundary_case(fh, {
        ("Wall", "Solid"): lambda s: lbm.perm(s, OPP18),
        "EVelocity": zou("velocity", "E", False),
        "WPressure": zou("pressure", "W", True),
        "WVelocity": zou("velocity", "W", False),
        "EPressure": zou("pressure", "E", True),
        ("NSymmetry", "SSymmetry"): lambda s: lbm.perm(s, MIRY18),
    })


# _heq and init are shared with d2q9_pf (imported above):
# the sharpening-flux equilibrium and the uniform-phase init are identical.


def run(ctx: NodeCtx) -> jnp.ndarray:
    fh = jnp.concatenate([ctx.group("f"), ctx.group("h")])
    fh = _boundaries(ctx, fh)
    f, h = fh[:9], fh[9:]
    dt = f.dtype

    pf = jnp.sum(h, axis=0)
    fx, fy, n = _force(ctx, pf)

    # phase-interpolated relaxation rate (reference CollisionMRT,
    # Dynamics.c.Rt:505: gamma = 1 - (omega_l - (pf-1/2)(omega - omega_l)))
    omega_eff = ctx.setting("omega_l") \
        - (pf - 0.5) * (ctx.setting("omega") - ctx.setting("omega_l"))
    rho = jnp.sum(f, axis=0)
    jx = lbm.edot(E[:, 0], f)
    jy = lbm.edot(E[:, 1], f)
    feq = lbm.equilibrium(E, W, rho, (jx / rho, jy / rho))
    # force enters the momentum directly (J += F, Dynamics.c.Rt:523-525)
    feq2 = lbm.equilibrium(E, W, rho, ((jx + fx) / rho, (jy + fy) / rho))
    fc = feq2 + (1.0 - omega_eff) * (f - feq)

    # h relaxes toward Heq at the momentum-like velocity J + 1.5 F — the
    # reference updates Jx += F.x then uses u = Jx + 0.5 F.x, un-normalized
    # by rho (Dynamics.c.Rt:537-549); rho ~ 1 in this model's regime
    uh = (jx + 1.5 * fx, jy + 1.5 * fy)
    omega_ph = 1.0 / (3.0 * ctx.setting("M") + 0.5)
    bh = 3.0 * ctx.setting("M") * (1.0 - 4.0 * pf * pf) * ctx.setting("W")
    hc = (1.0 - omega_ph) * h + omega_ph * _heq(pf, n, uh, bh)

    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    h = jnp.where(coll, hc, h)
    return ctx.store({"f": f, "h": h})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.where(ctx.nt_in_group("BOUNDARY"),
                    1.0 + 3.0 * ctx.setting("Pressure"),
                    jnp.sum(f, axis=0))
    pf = jnp.sum(ctx.group("h"), axis=0)
    fx, fy, _ = _force(ctx, pf)
    ux = (lbm.edot(E[:, 0], f) + 0.5 * fx) / rho
    uy = (lbm.edot(E[:, 1], f) + 0.5 * fy) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_curvature(ctx: NodeCtx) -> jnp.ndarray:
    return _curvature(ctx, _repaired_stencil(ctx))


def get_normal(ctx: NodeCtx) -> jnp.ndarray:
    nx, ny = _normal(_repaired_stencil(ctx))
    return jnp.stack([nx, ny, jnp.zeros_like(nx)])


def get_iforce(ctx: NodeCtx) -> jnp.ndarray:
    rphis = _repaired_stencil(ctx)
    nx, ny = _normal(rphis)
    curv = _curvature(ctx, rphis)
    pf = jnp.sum(ctx.group("h"), axis=0)
    decay = jnp.exp(-ctx.setting("SurfaceTensionDecay") * pf * pf)
    return jnp.stack([curv * nx * decay, curv * ny * decay,
                      jnp.zeros_like(curv)])


def build():
    return _def().finalize().bind(
        run=run, init=init,
        stages={"CalcPhi": calc_phi},
        quantities={
            "Rho": lambda c: jnp.sum(c.group("f"), axis=0),
            "U": get_u,
            "Normal": get_normal,
            "PhaseField": lambda c: jnp.sum(c.group("h"), axis=0),
            "Curvature": get_curvature,
            "InterfaceForce": get_iforce,
        })
