"""d3q19_heat_adj (+ _art / _prop variants) — 3D conjugate-heat topology
optimization.

Behavioral parity targets: reference models ``d3q19_heat_adj``,
``d3q19_heat_adj_art`` and ``d3q19_heat_adj_prop``
(reference src/d3q19_heat_adj*/Dynamics.R, ADJOINT=1): d3q19 flow +
advected temperature with a design field ``w`` — Brinkman velocity
penalization and w-interpolated diffusivity.  The variants differ in how
the design field penalizes momentum:

* base: post-collision momentum scaled by ``w`` (w=0 kills the flow);
* ``_art``: momentum scaled by ``omT = 2 w - 1`` — w=0 REVERSES the
  momentum, a bounce-back-like 'artificial' solid that penalizes leakage
  harder (reference src/d3q19_heat_adj_art/Dynamics.c:361-366);
* ``_prop``: the design weight PROPAGATES along +x through the streamed
  pair ``w0/w1``: on Propagate-flagged nodes
  ``w0 = w - PropagateX (1 - w1(x-1))`` — upstream solid material shades
  the nodes behind it (continuous-casting-style moving design,
  reference src/d3q19_heat_adj_prop/Dynamics.c.Rt:199-203); momentum and
  diffusivity use the propagated ``w0`` and a ``MaterialPenalty`` global
  ``w0 (1 - w0)`` penalizes intermediate material (:230-232).

The reference's Tapenade tape differences between the variants are an
implementation detail of source-transform AD with no analogue here —
``jax.grad`` differentiates each variant's own physics.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d3q19_heat import ET, OPPT, WT, _t_eq
from tclb_tpu.models.d3q19 import E, OPP, W
from tclb_tpu.ops import lbm


def _make(name: str, variant: str = "base"):
    def _def():
        d = family.base_def(name, E, "3D conjugate-heat topology opt",
                            faces="WE", symmetries="NS")
        d.add_densities("T", ET, group="T")
        d.add_density("w", group="w", parameter=True)
        d.add_setting("InletTemperature", default=1.0)
        d.add_setting("InitTemperature", default=1.0)
        d.add_setting("FluidAlfa", default=0.1)
        d.add_setting("SolidAlfa", default=0.01)
        d.add_setting("Porocity", default=0.0, zonal=True)
        d.add_quantity("T", unit="K")
        d.add_quantity("W")
        d.add_quantity("TB", adjoint=True)
        d.add_quantity("WB", adjoint=True)
        d.add_global("HeatFlux")
        d.add_global("Material")
        d.add_global("Drag")
        if variant == "prop":
            # streamed weight pair: w0 streams -x, w1 streams +x
            # (reference 'weight fluid-solid moving in X',
            # src/d3q19_heat_adj_prop/Dynamics.R:78-81)
            d.add_density("w0", dx=-1, group="wm")
            d.add_density("w1", dx=1, group="wm")
            d.add_setting("PropagateX", default=0.0,
                          comment="strength of +x design propagation")
            d.add_global("MaterialPenalty")
            d.add_node_type("Propagate", "ADDITIONALS")
        return d

    def run(ctx: NodeCtx) -> jnp.ndarray:
        f = ctx.group("f")
        fT = ctx.group("T")
        w = ctx.density("w")
        dt = f.dtype
        f = family.apply_boundaries(ctx, f, E, W, OPP)
        shape = f.shape[1:]
        t_in = ctx.setting("InletTemperature")
        fT = ctx.boundary_case(fT, {
            ("Wall", "Solid"): lambda t: lbm.perm(t, OPPT),
            "WVelocity": lambda t: _t_eq(
                jnp.broadcast_to(t_in, shape).astype(dt),
                tuple(jnp.zeros(shape, dt) for _ in range(3))),
        })
        extra_store = {}
        if variant == "prop":
            # propagated weight: pulled w1 carries the upstream (x-1)
            # value after streaming
            w1_up = ctx.density("w1")
            w_eff = jnp.where(ctx.nt_is("Propagate"),
                              w - ctx.setting("PropagateX") * (1.0 - w1_up),
                              w)
            w_eff = jnp.clip(w_eff, 0.0, 1.0)
            extra_store["wm"] = jnp.stack([w_eff, w_eff])
        else:
            w_eff = w
        rho = jnp.sum(f, axis=0)
        u = tuple(lbm.edot(E[:, a], f) / rho
                  for a in range(3))
        om = ctx.setting("omega")
        feq = lbm.equilibrium(E, W, rho, u)
        coll_mask = ctx.nt_in_group("COLLISION")
        ctx.add_global("Drag", (1.0 - w_eff) * jnp.abs(u[0]),
                       where=coll_mask)
        if variant == "art":
            # w=0 reverses the momentum (bounce-back-like artificial
            # solid, reference _art omT = w*2-1, Dynamics.c:361-366)
            scale = 2.0 * w_eff - 1.0
        else:
            scale = w_eff
        u2 = tuple(c * scale for c in u)
        fc = f + om * (feq - f) + (lbm.equilibrium(E, W, rho, u2) - feq)
        temp = jnp.sum(fT, axis=0)
        alfa = ctx.setting("FluidAlfa") * w_eff \
            + ctx.setting("SolidAlfa") * (1.0 - w_eff)
        om_t = 1.0 / (4.0 * alfa + 0.5)
        tc = fT + om_t[None] * (_t_eq(temp, u2) - fT)
        coll = coll_mask[None]
        f = jnp.where(coll, fc, f)
        fT = jnp.where(coll, tc, fT)
        ctx.add_global("HeatFlux", temp * u2[0], where=ctx.nt_is("Outlet"))
        ctx.add_global("Material", 1.0 - w_eff,
                       where=ctx.nt_in_group("DESIGNSPACE"))
        if variant == "prop":
            ctx.add_global("MaterialPenalty", w_eff * (1.0 - w_eff),
                           where=ctx.nt_in_group("DESIGNSPACE"))
        return ctx.store({"f": f, "T": fT, **extra_store})

    def init(ctx: NodeCtx) -> jnp.ndarray:
        shape = ctx.flags.shape
        dt = ctx._fields.dtype
        t0 = jnp.broadcast_to(ctx.setting("InitTemperature"),
                              shape).astype(dt)
        fT = _t_eq(t0, tuple(jnp.zeros(shape, dt) for _ in range(3)))
        w = 1.0 - jnp.broadcast_to(ctx.setting("Porocity"),
                                   shape).astype(dt)
        w = jnp.where(ctx.nt_is("Solid"), jnp.zeros_like(w), w)
        extra = {"T": fT, "w": w[None]}
        if variant == "prop":
            extra["wm"] = jnp.stack([w, w])
        return family.standard_init(ctx, E, W, extra=extra)

    def build():
        q = family.make_getters(E, force_of=family.gravity_of)
        tq = lambda c: jnp.sum(c.group("T"), axis=0)   # noqa: E731
        wq = lambda c: c.density("w")                  # noqa: E731
        q.update({"T": tq, "W": wq, "TB": tq, "WB": wq})
        return _def().finalize().bind(run=run, init=init, quantities=q)

    return build


build = _make("d3q19_heat_adj")
build_art = _make("d3q19_heat_adj_art", variant="art")
build_prop = _make("d3q19_heat_adj_prop", variant="prop")
