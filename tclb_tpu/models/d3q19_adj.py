"""d3q19_adj — 3D topology optimization (porous design field).

Behavioral parity target: reference model ``d3q19_adj``
(reference src/d3q19_adj/Dynamics.R, ADJOINT=1): the 3D analogue of
d2q9_adj — design density ``w`` with Brinkman penalization inside the MRT
collision, Drag/Lift/Material objectives.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d3q19 import E, OPP, W, M
from tclb_tpu.ops import lbm


def _def():
    d = family.base_def("d3q19_adj", E, "3D porous topology optimization",
                        faces="WE", symmetries="NS")
    d.add_density("w", group="w", parameter=True)
    d.add_setting("S_high", default=1.0)
    d.add_setting("Porocity", default=0.0, zonal=True)
    d.add_setting("PorocityGamma", default=0.0)
    d.add_quantity("W")
    d.add_quantity("WB", adjoint=True)
    d.add_global("Drag")
    d.add_global("Lift")
    d.add_global("Material")
    d.add_global("MaterialPenalty")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    w = ctx.density("w")
    dt = f.dtype
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    rho = jnp.sum(f, axis=0)
    u = tuple(lbm.edot(E[:, a], f) / rho
              for a in range(3))
    feq = lbm.equilibrium(E, W, rho, u)
    fneq = [f[k] - feq[k] for k in range(19)]
    relax = lbm.two_rate_relax(M, 4, 10, fneq,
                               1.0 - ctx.setting("omega"),
                               1.0 - ctx.setting("S_high"))
    g = family.gravity_of(ctx)
    nw = w / (1.0 - ctx.setting("PorocityGamma") * (1.0 - w))
    u2 = tuple((u[a] + g[a]) for a in range(3))
    coll = ctx.nt_in_group("COLLISION")
    ctx.add_global("Drag", (1.0 - nw) * u2[0], where=coll)
    ctx.add_global("Lift", (1.0 - nw) * u2[1], where=coll)
    u2 = tuple(c * nw for c in u2)
    fc = relax + lbm.equilibrium(E, W, rho, u2)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    in_design = ctx.nt_in_group("DESIGNSPACE")
    ctx.add_global("MaterialPenalty", w * (1.0 - w), where=in_design)
    ctx.add_global("Material", 1.0 - w, where=in_design)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    w = 1.0 - jnp.broadcast_to(ctx.setting("Porocity"), shape).astype(dt)
    w = jnp.where(ctx.nt_is("Solid"), jnp.zeros_like(w), w)
    return family.standard_init(ctx, E, W, extra={"w": w[None]})


def build():
    q = family.make_getters(E, force_of=family.gravity_of)
    wq = lambda c: c.density("w")          # noqa: E731
    q.update({"W": wq, "WB": wq})
    return _def().finalize().bind(run=run, init=init, quantities=q)
