"""Shared model-building blocks for the standard hydrodynamic families.

The reference repeats the same structure in every ``src/<model>/Dynamics.R``
+ ``Dynamics.c.Rt`` pair: f-densities over a velocity set, Rho/U getters,
Velocity/Pressure(Density) zonal settings, a boundary ``switch`` with
bounce-back / Zou-He faces / symmetry mirrors, then a collision.  Here that
skeleton is data: :func:`base_def` declares the common registry entries and
:func:`apply_boundaries` builds the mask-dispatch from whatever boundary
node types the model declares (reference boundary library,
src/lib/boundary.R).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.ops import lbm

# face name -> (E-column axis, side): side +1 = fluid lies toward +axis
FACES = {
    "W": (0, +1), "E": (0, -1),
    "S": (1, +1), "N": (1, -1),
    "B": (2, +1), "T": (2, -1),
}


def mirror_perm(E: np.ndarray, axis: int) -> np.ndarray:
    """Population permutation mirroring velocity component ``axis``."""
    Em = E.copy()
    Em[:, axis] = -Em[:, axis]
    perm = np.zeros(len(E), dtype=np.int32)
    for i, e in enumerate(Em):
        (j,) = np.where((E == e).all(axis=1))
        perm[i] = j[0]
    return perm


def base_def(name: str, E: np.ndarray, description: str = "",
             faces: str = "WE", symmetries: str = "",
             objectives: bool = True) -> ModelDef:
    """Common registry skeleton: f densities, Rho/U quantities,
    nu/Velocity/Density settings, gravity, in/outlet flux objectives.

    ``faces`` lists the boundary faces with Velocity/Pressure BCs
    ('W','E','N','S','T','B'); W/E (x faces) reuse the reference's default
    node types, others add <F>Velocity/<F>Pressure types (reference
    d3q27_cumulant adds NVelocity etc., src/d3q27_cumulant/Dynamics.R:34-37).
    ``symmetries`` adds <F>Symmetry mirror types.
    """
    ndim = E.shape[1]
    d = ModelDef(name, ndim=ndim, description=description or name)
    d.add_densities("f", E)
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_setting("nu", default=1 / 6, comment="viscosity",
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("omega", default=1.0, comment="one over relaxation time")
    d.add_setting("Velocity", default=0.0, zonal=True,
                  comment="inlet/outlet/init velocity")
    d.add_setting("Density", default=1.0, zonal=True,
                  comment="inlet/outlet/init density")
    for ax in ("X", "Y", "Z")[:ndim]:
        d.add_setting(f"Gravitation{ax}")
    if objectives:
        d.add_global("PressureLoss", unit="1mPa")
        d.add_global("OutletFlux", unit="1m2/s")
        d.add_global("InletFlux", unit="1m2/s")
    for face in faces:
        if face not in "WE":   # WVelocity/EPressure/... are defaults
            d.add_node_type(f"{face}Velocity", "BOUNDARY")
            d.add_node_type(f"{face}Pressure", "BOUNDARY")
    for face in symmetries:
        d.add_node_type(f"{face}Symmetry", "BOUNDARY")
    return d


def boundary_cases(model, E: np.ndarray, W: np.ndarray, OPP: np.ndarray,
                   vel, den, extra: Optional[dict] = None) -> dict:
    """The ordered case dict for every boundary node type the model
    declares: Wall/Solid bounce-back, <F>Velocity / <F>Pressure faces via
    non-equilibrium bounce-back, <F>Symmetry mirrors (the reference's
    per-node boundary switch, e.g. src/d2q9/Dynamics.c.Rt:121-150).

    ``vel``/``den`` are the (zonal) Velocity/Density values — planes or
    scalars.  Factored out of :func:`apply_boundaries` so the Pallas
    kernels (ops/pallas_d3q.py) dispatch the IDENTICAL boundary math."""
    # permutations as static stacks (not fancy indexing): identical XLA,
    # and the only form Mosaic accepts inside the Pallas kernels
    def _perm(f, p):
        return jnp.stack([f[int(p[k])] for k in range(len(p))])

    cases: dict = {("Wall", "Solid"): lambda f: _perm(f, OPP)}
    known = model.node_types
    for face, (axis, side) in FACES.items():
        if axis >= E.shape[1]:
            continue
        vname, pname = f"{face}Velocity", f"{face}Pressure"
        if vname in known:
            # vel is the signed +axis component on every face (reference
            # ZouHe: V3[direction] = Velocity, src/lib/boundary.R) —
            # nebb_boundary takes it as-is; negating by side would reverse
            # the flow on E/N/T faces vs the reference and our own d2q9
            cases[vname] = (lambda f, a=axis, s=side:
                            lbm.nebb_boundary(E, W, OPP, f, a, s,
                                              "velocity", vel))
        if pname in known:
            cases[pname] = (lambda f, a=axis, s=side:
                            lbm.nebb_boundary(E, W, OPP, f, a, s,
                                              "pressure", den))
        sname = f"{face}Symmetry"
        if sname in known:
            perm = mirror_perm(E, axis)
            cases[sname] = lambda f, p=perm: _perm(f, p)
    # legacy d2q9 names for y-mirrors
    for nm, axis in (("TopSymmetry", 1), ("BottomSymmetry", 1)):
        if nm in known and axis < E.shape[1]:
            perm = mirror_perm(E, axis)
            cases[nm] = lambda f, p=perm: _perm(f, p)
    if extra:
        cases.update(extra)
    return cases


def apply_boundaries(ctx: NodeCtx, f: jnp.ndarray, E: np.ndarray,
                     W: np.ndarray, OPP: np.ndarray,
                     extra: Optional[dict] = None) -> jnp.ndarray:
    """Mask-dispatch the :func:`boundary_cases` of the model."""
    si = ctx.model.setting_index
    vel = ctx.setting("Velocity") if "Velocity" in si else 0.0
    den = ctx.setting("Density") if "Density" in si else 1.0
    cases = boundary_cases(ctx.model, E, W, OPP, vel, den, extra)
    return ctx.boundary_case(f, cases)


def add_flux_objectives(ctx: NodeCtx, f: jnp.ndarray, E: np.ndarray) -> None:
    """Inlet/Outlet flux + pressure-loss globals on OBJECTIVE-tagged nodes
    (reference src/d2q9/Dynamics.c.Rt:250-270)."""
    if "OutletFlux" not in ctx.model.global_index:
        return
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    usq = ux * ux + uy * uy
    if E.shape[1] == 3:
        uz = lbm.edot(E[:, 2], f) / rho
        usq = usq + uz * uz
    coll = ctx.nt_in_group("COLLISION")
    ploss = ux / rho * ((rho - 1.0) / 3.0 + usq / rho * 0.5)
    ctx.add_global("OutletFlux", ux / rho, where=ctx.nt_is("Outlet") & coll)
    ctx.add_global("InletFlux", ux / rho, where=ctx.nt_is("Inlet") & coll)
    ctx.add_global("PressureLoss",
                   jnp.where(ctx.nt_is("Inlet"), ploss, -ploss),
                   where=(ctx.nt_is("Inlet") | ctx.nt_is("Outlet")) & coll)


def standard_init(ctx: NodeCtx, E: np.ndarray, W: np.ndarray,
                  extra: Optional[dict] = None) -> jnp.ndarray:
    """Equilibrium init from the zonal Density/Velocity settings (the common
    ``Init()`` of the reference models)."""
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    ndim = E.shape[1]
    rho = jnp.broadcast_to(ctx.setting("Density"), shape).astype(dt)
    ux = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    u = (ux,) + tuple(jnp.zeros(shape, dt) for _ in range(ndim - 1))
    f = lbm.equilibrium(E, W, rho, u)
    groups = {"f": f}
    if extra:
        groups.update(extra)
    return ctx.store(groups)


def make_getters(E: np.ndarray, force_of=None) -> dict[str, Callable]:
    """Rho and U quantity getters; ``force_of(ctx)`` (acceleration tuple)
    shifts measured U by half the force (reference convention,
    src/d2q9/Dynamics.c.Rt:43-49)."""

    def get_rho(ctx: NodeCtx) -> jnp.ndarray:
        return jnp.sum(ctx.group("f"), axis=0)

    def get_u(ctx: NodeCtx) -> jnp.ndarray:
        f = ctx.group("f")
        dt = f.dtype
        rho = jnp.sum(f, axis=0)
        comps = [lbm.edot(E[:, a], f) / rho
                 for a in range(E.shape[1])]
        if force_of is not None:
            frc = force_of(ctx)
            comps = [c + 0.5 * g for c, g in zip(comps, frc)]
        while len(comps) < 3:
            comps.append(jnp.zeros_like(comps[0]))
        return jnp.stack(comps)

    return {"Rho": get_rho, "U": get_u}


def dispatch_boundary_cases(cases: dict, f, mask_of,
                            present: Optional[set] = None):
    """Mask-dispatch a :func:`boundary_cases` dict — the shared inner
    loop of the Pallas kernels (2D and 3D): ``mask_of(name)`` yields the
    bool plane for a node type; cases whose types are all absent from
    ``present`` are skipped entirely (compile-time specialization on the
    painted boundary set, reference src/cuda.cu.Rt:81)."""
    out = f
    for names, fn in cases.items():
        names = [n for n in ((names,) if isinstance(names, str) else names)
                 if present is None or n in present]
        if not names:
            continue
        m = mask_of(names[0])
        for n in names[1:]:
            m = m | mask_of(n)
        out = jnp.where(m[None], fn(f), out)
    return out


def gravity_of(ctx: NodeCtx):
    """Acceleration tuple from the Gravitation* settings."""
    names = [f"Gravitation{a}" for a in ("X", "Y", "Z")]
    return tuple(ctx.setting(n) for n in names
                 if n in ctx.model.setting_index)
