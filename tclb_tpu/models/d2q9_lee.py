"""d2q9_lee — Lee–Lin-style multiphase with potential-form forcing.

Behavioral parity target: reference model ``d2q9_lee``
(reference src/d2q9_lee/Dynamics.R, Dynamics.c.Rt).  Single d2q9 population
plus two stencil-2 Fields: ``rho`` (recomputed per step with BC overrides,
CalcRho :199-221) and the chemical potential ``nu``
(``mu0 - Kappa lap(rho)`` with the double-well
``mu0 = 2 Beta (r - rho_l)(r - rho_v)(2r - rho_v - rho_l)``, CalcNu
:335-343).  The collision applies Lee's mixed-difference forcing: per
direction, a biased ("B", second-order one-sided using the distance-2
stencil) and a central ("C") projection
``fX_i = cs2 nabla^X_i rho - rho nabla^X_i nu + e_i.G - u.G``
(fillF :356-400), entering as ``feq``-weighted source terms — the central
form inside the pre-collision velocity/equilibrium shift, the biased form
after relaxation (CollisionBGK :430-480).

The reference's ``fillF`` reads the ``fC`` array in its velocity update
*before* assigning it (file-scope scratch, undefined on kernel entry); we
implement the self-consistent interpretation — velocity from bare momentum
for the projections' ``u.G`` work term, then the half-``fC`` shift — which
coincides with the reference whenever G = 0 (the gradient parts do not
depend on u at all).

MovingWall / ForcedMovingWall lid boundaries and the Wet/Dry contact-angle
density overrides are included; ``check.py``-style validation is the
Laplace/flat-interface test in tests/test_lee.py.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, _zou_he_x
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
CS2 = 1.0 / 3.0
# MRT rates S4..S9 of the reference's #define block (Dynamics.c.Rt:8-13);
# S8/S9 take omega at runtime
MRT_S_FIXED = {3: 4.0 / 3.0, 4: 1.0, 5: 1.0, 6: 1.0}


def _def() -> ModelDef:
    d = ModelDef("d2q9_lee", ndim=2,
                 description="Lee multiphase (potential-form forcing)")
    d.add_densities("f", E)
    d.add_field("rho", dx=(-2, 2), dy=(-2, 2))
    d.add_field("nu", dx=(-2, 2), dy=(-2, 2))
    d.add_stage("BaseIteration", "Run")
    d.add_stage("CalcRho", "CalcRho")
    d.add_stage("CalcNu", "CalcNu", load_densities=False)
    d.add_stage("InitF2", "InitF2", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "CalcRho", "CalcNu"))
    d.add_action("Init", ("InitF2", "CalcRho", "CalcNu"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("Nu", unit="kg/m3")
    d.add_quantity("P", unit="Pa")
    d.add_setting("omega", comment="one over relaxation time")
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("InletVelocity", default=0.0, zonal=True)
    d.add_setting("InletPressure", default=0.0, zonal=True,
                  derived={"InletDensity": lambda p: 1.0 + p / 3.0})
    d.add_setting("InletDensity", default=1.0, zonal=True)
    d.add_setting("OutletDensity", default=1.0, zonal=True)
    d.add_setting("InitDensity", zonal=True)
    d.add_setting("WallDensity", zonal=True)
    d.add_setting("GravitationY")
    d.add_setting("GravitationX")
    d.add_setting("MovingWallVelocity", zonal=True)
    d.add_setting("WetDensity", zonal=True)
    d.add_setting("DryDensity", zonal=True)
    d.add_setting("Wetting", zonal=True)
    d.add_setting("LiquidDensity")
    d.add_setting("VaporDensity")
    d.add_setting("Beta")
    d.add_setting("Kappa")
    d.add_global("MomentumX")
    d.add_global("MomentumY")
    d.add_global("Mass")
    d.add_node_type("MovingWall", "BOUNDARY")
    d.add_node_type("ForcedMovingWall", "BOUNDARY")
    d.add_node_type("Wet", "ADDITIONALS")
    d.add_node_type("Dry", "ADDITIONALS")
    return d


def _mu0(ctx: NodeCtx, r):
    """Double-well bulk chemical potential (reference getP/CalcNu)."""
    rl = ctx.setting("LiquidDensity")
    rv = ctx.setting("VaporDensity")
    return 2.0 * ctx.setting("Beta") * (r - rl) * (r - rv) \
        * (2.0 * r - rv - rl)


def calc_rho(ctx: NodeCtx):
    """rho = sum(f) with boundary overrides (reference CalcRho,
    src/d2q9_lee/Dynamics.c.Rt:199-221)."""
    rho = jnp.sum(ctx.group("f"), axis=0)
    wallish = ctx.nt_is("Wall") | ctx.nt_is("MovingWall")
    wall_rho = ctx.setting("WallDensity")
    wall_rho = jnp.where(ctx.nt_is("Wet") & wallish,
                         ctx.setting("WetDensity"), wall_rho)
    wall_rho = jnp.where(ctx.nt_is("Dry") & wallish,
                         ctx.setting("DryDensity"), wall_rho)
    rho = jnp.where(wallish, wall_rho, rho)
    rho = jnp.where(ctx.nt_is("EPressure"), ctx.setting("OutletDensity"),
                    rho)
    rho = jnp.where(ctx.nt_is("WPressure"), ctx.setting("InletDensity"),
                    rho)
    return {"rho": rho}


def calc_nu(ctx: NodeCtx):
    """nu = mu0(rho) - Kappa lap(rho); lap = sum_i (w_i/cs2)(rho(e) - 2
    rho(0) + rho(-e)) (reference CalcNu, src/d2q9_lee/Dynamics.c.Rt:335-343)."""
    r0 = ctx.load("rho")
    lap = sum(float(W[i] / CS2)
              * (ctx.load("rho", int(E[i, 0]), int(E[i, 1]))
                 - 2.0 * r0
                 + ctx.load("rho", -int(E[i, 0]), -int(E[i, 1])))
              for i in range(1, 9))
    return {"nu": _mu0(ctx, r0) - ctx.setting("Kappa") * lap}


def _projections(ctx: NodeCtx, u, d):
    """Per-direction biased/central force projections fB_i / fC_i
    (reference fillF, src/d2q9_lee/Dynamics.c.Rt:356-400)."""
    gx = ctx.setting("GravitationX")
    gy = ctx.setting("GravitationY")
    ug = u[0] * gx + u[1] * gy
    fB, fC = [], []
    for i in range(9):
        dx, dy = int(E[i, 0]), int(E[i, 1])
        if dx == 0 and dy == 0:
            grad_b = grad_c = 0.0
        else:
            r1 = ctx.load("rho", dx, dy)
            r2 = ctx.load("rho", 2 * dx, 2 * dy)
            r0 = ctx.load("rho")
            rm = ctx.load("rho", -dx, -dy)
            n1 = ctx.load("nu", dx, dy)
            n2 = ctx.load("nu", 2 * dx, 2 * dy)
            n0 = ctx.load("nu")
            nm = ctx.load("nu", -dx, -dy)
            grad_b = 0.5 * (-r2 + 4.0 * r1 - 3.0 * r0) * CS2 \
                - d * 0.5 * (-n2 + 4.0 * n1 - 3.0 * n0)
            grad_c = 0.5 * (r1 - rm) * CS2 - d * 0.5 * (n1 - nm)
        eg = float(E[i, 0]) * gx + float(E[i, 1]) * gy
        fB.append(grad_b + eg - ug)
        fC.append(grad_c + eg - ug)
    # ForcedMovingWall: additional momentum-matching force (fillF :380-398)
    fmw = ctx.nt_is("ForcedMovingWall")
    gx2 = (ctx.setting("MovingWallVelocity") - u[0]) * d
    gy2 = (0.0 - u[1]) * d
    ug2 = u[0] * gx2 + u[1] * gy2
    for i in range(9):
        extra = float(E[i, 0]) * gx2 + float(E[i, 1]) * gy2 - ug2
        fB[i] = jnp.where(fmw, fB[i] + extra, fB[i])
        fC[i] = jnp.where(fmw, fC[i] + extra, fC[i])
    return fB, fC


def _vec_of(proj):
    """make.vector: F = sum_i (w_i/cs2) proj_i e_i."""
    fx = sum(float(W[i] / CS2 * E[i, 0]) * proj[i]
             for i in range(9) if E[i, 0])
    fy = sum(float(W[i] / CS2 * E[i, 1]) * proj[i]
             for i in range(9) if E[i, 1])
    return fx, fy


def _fill(ctx: NodeCtx, f):
    """d, u (with the half-central-force shift) and the projections."""
    dt = f.dtype
    d = jnp.sum(f, axis=0)
    jx = lbm.edot(E[:, 0], f)
    jy = lbm.edot(E[:, 1], f)
    u_bare = (jx / d, jy / d)
    fB, fC = _projections(ctx, u_bare, d)
    fcx, fcy = _vec_of(fC)
    u = ((jx + 0.5 * fcx) / d, (jy + 0.5 * fcy) / d)
    return d, (jx, jy), u, fB, fC


def _force_term(feq, d, u, proj, uF):
    """force(): feq_i (proj_i - u.F) / (d cs2) (reference CollisionBGK)."""
    return [feq[i] * (proj[i] - uF) / (d * CS2) for i in range(9)]


def _collision_bgk(ctx: NodeCtx, f):
    dt = f.dtype
    d, (jx, jy), u, fB, fC = _fill(ctx, f)
    fcx, fcy = _vec_of(fC)
    fbx, fby = _vec_of(fB)
    coll = ctx.nt_in_group("COLLISION")
    ctx.add_global("Mass", d, where=coll)
    ctx.add_global("MomentumX", jx + 0.5 * fcx, where=coll)
    ctx.add_global("MomentumY", jy + 0.5 * fcy, where=coll)
    feq = lbm.equilibrium(E, W, d, u)
    omega = ctx.setting("omega")
    uFc = u[0] * fcx + u[1] * fcy
    uFb = u[0] * fbx + u[1] * fby
    fc_term = _force_term(feq, d, u, fC, uFc)
    fb_term = _force_term(feq, d, u, fB, uFb)
    out = []
    for i in range(9):
        fneq = f[i] - (feq[i] - 0.5 * fc_term[i])
        out.append((1.0 - omega) * fneq + feq[i] + 0.5 * fb_term[i])
    return jnp.stack(out)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    vel = ctx.setting("InletVelocity")

    def moving_wall(f):
        # lid at the BOTTOM of the fluid (reconstructs the upward-
        # moving populations f2/f5/f6 — reference MovingWall :62-71)
        rho = f[0] + f[1] + f[3] + 2.0 * (f[7] + f[4] + f[8])
        ru = rho * ctx.setting("MovingWallVelocity")
        f2 = f[4]
        f6 = f[8] - 0.5 * ru - 0.5 * (f[3] - f[1])
        f5 = f[7] + 0.5 * ru + 0.5 * (f[3] - f[1])
        return jnp.stack([f[0], f[1], f2, f[3], f[4], f5, f6, f[7], f[8]])

    def wvel_eq(f):
        # equilibrium inlet with Wet/Dry density override (:109-126)
        shape = f.shape[1:]
        rho2 = jnp.broadcast_to(ctx.setting("InletDensity"),
                                shape).astype(f.dtype)
        rho2 = jnp.where(ctx.nt_is("Wet"), ctx.setting("WetDensity"), rho2)
        rho2 = jnp.where(ctx.nt_is("Dry"), ctx.setting("DryDensity"), rho2)
        ux = jnp.broadcast_to(vel, shape).astype(f.dtype)
        return lbm.equilibrium(E, W, rho2, (ux, jnp.zeros(shape, f.dtype)))

    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "MovingWall": moving_wall,
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, ctx.setting("InletDensity"),
                                         "pressure", "W"),
        "WVelocity": wvel_eq,
        "EPressure": lambda f: _zou_he_x(f, ctx.setting("OutletDensity"),
                                         "pressure", "E"),
    })
    f = jnp.where(ctx.nt_is("BGK")[None], _collision_bgk(ctx, f), f)
    f = jnp.where(ctx.nt_is("MRT")[None], _collision_mrt(ctx, f), f)
    return ctx.store({"f": f})


def _collision_mrt(ctx: NodeCtx, f):
    """MRT variant (reference CollisionMRT, src/d2q9_lee/Dynamics.c.Rt:484-523):
    half the central force pre-added, non-conserved moments relaxed by
    (S - 1), half the biased force post-added.

    NOTE: the reference's MRT factor is literally ``(S - 1)``
    (Dynamics.c.Rt:516) — the SIGN-FLIPPED counterpart of its own BGK
    path's ``(1 - omega)``, so for S = omega != 1 the two collisions give
    different effective viscosities.  We reproduce the reference literally;
    use BGK nodes (as the reference's cases do) for physical runs."""
    from tclb_tpu.ops.lbm import moments, from_moments
    M = _MRT_BASIS
    d, _, u, fB, fC = _fill(ctx, f)
    fcx, fcy = _vec_of(fC)
    fbx, fby = _vec_of(fB)
    feq = lbm.equilibrium(E, W, d, u)
    uFc = u[0] * fcx + u[1] * fcy
    uFb = u[0] * fbx + u[1] * fby
    f2 = f + 0.5 * jnp.stack(_force_term(feq, d, u, fC, uFc))
    omega = ctx.setting("omega")
    m = moments(M, f2)
    meq = moments(M, feq)
    out_m = []
    for i in range(9):
        if i < 3:
            out_m.append(m[i])
        else:
            s = MRT_S_FIXED.get(i, None)
            rate = (s - 1.0) if s is not None else (omega - 1.0)
            out_m.append((m[i] - meq[i]) * rate + meq[i])
    f3 = from_moments(M, jnp.stack(out_m))
    return f3 + 0.5 * jnp.stack(_force_term(feq, d, u, fB, uFb))


# the reference's classical (non-orthonormalized) d2q9 MRT matrix
# (src/d2q9_lee/Dynamics.c.Rt:492-501)
_MRT_BASIS = np.array([
    [1, 1, 1, 1, 1, 1, 1, 1, 1],
    [0, 1, 0, -1, 0, 1, -1, -1, 1],
    [0, 0, 1, 0, -1, 1, 1, -1, -1],
    [-4, -1, -1, -1, -1, 2, 2, 2, 2],
    [4, -2, -2, -2, -2, 1, 1, 1, 1],
    [0, -2, 0, 2, 0, 1, -1, -1, 1],
    [0, 0, -2, 0, 2, 1, 1, -1, -1],
    [0, 1, -1, 1, -1, 0, 0, 0, 0],
    [0, 0, 0, 0, 0, 1, -1, 1, -1]], dtype=np.float64)


def init_f2(ctx: NodeCtx):
    """InitF2: f = feq(InitRho-rule density, (InletVelocity, 0)) (reference
    InitF2 + InitRho, src/d2q9_lee/Dynamics.c.Rt:174-197,415-424)."""
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(ctx.setting("InitDensity"), shape).astype(dt)
    rho = jnp.where(ctx.nt_is("Wall") | ctx.nt_is("MovingWall"),
                    ctx.setting("WallDensity"), rho)
    rho = jnp.where(ctx.nt_is("EPressure"), ctx.setting("OutletDensity"),
                    rho)
    rho = jnp.where(ctx.nt_is("WPressure"), ctx.setting("InletDensity"),
                    rho)
    ux = jnp.broadcast_to(ctx.setting("InletVelocity"), shape).astype(dt)
    f = lbm.equilibrium(E, W, rho, (ux, jnp.zeros(shape, dt)))
    return {"f": f}


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    _, _, u, _, _ = _fill(ctx, f)
    return jnp.stack([u[0], u[1], jnp.zeros_like(u[0])])


def build():
    d = _def()
    model = d.finalize()

    def _init_stage(ctx):
        upd = init_f2(ctx)
        return ctx.store(upd)

    return model.bind(
        run=run, init=_init_stage,
        stages={"CalcRho": calc_rho, "CalcNu": calc_nu,
                "InitF2": _init_stage},
        quantities={
            "Rho": lambda c: c.load("rho"),
            "U": get_u,
            "Nu": lambda c: c.load("nu"),
            "P": lambda c: _mu0(c, c.load("rho")),
        })
