"""d2q9_SRT — 2D single-relaxation-time BGK.

Behavioral parity target: reference model ``d2q9_SRT``
(reference src/d2q9_SRT/Dynamics.R, hand-written Dynamics.c): the simplest
hydrodynamic model — BGK collision, bounce-back walls, Zou/He-style
velocity/pressure faces, body force.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)


def _def():
    d = family.base_def("d2q9_SRT", E,
                        "2D single-relaxation-time BGK")
    d.add_node_type("TopSymmetry", "BOUNDARY")
    d.add_node_type("BottomSymmetry", "BOUNDARY")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    fc, _, _ = lbm.bgk_collide(E, W, f, ctx.setting("omega"),
                               force=family.gravity_of(ctx))
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
