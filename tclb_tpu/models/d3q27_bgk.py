"""d3q27_BGK and d3q27_BGK_galcor — 3D 27-velocity BGK, optionally with the
third-order (Galilean-invariance) equilibrium correction.

Behavioral parity targets: reference models ``d3q27_BGK`` and
``d3q27_BGK_galcor`` (reference src/d3q27_BGK/Dynamics.R,
src/d3q27_BGK_galcor — hand-written C).  The "galcor" variant extends the
equilibrium with the third-order Hermite term
``(e.u)^3/(6 cs^6) - (e.u) u^2/(2 cs^4)``, removing the cubic
Galilean-invariance defect of the standard second-order equilibrium.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.ops import cumulant, lbm

E = cumulant.velocity_set(3)
W = lbm.weights(E)
OPP = lbm.opposite(E)
CS2 = lbm.CS2


def _equilibrium(rho, u, galcor: bool):
    dt = rho.dtype
    usq = sum(c * c for c in u)
    out = []
    for i in range(27):
        eu = sum(float(E[i, a]) * u[a] for a in range(3) if E[i, a])
        if isinstance(eu, int):
            common = 1.0 - usq / (2 * CS2)
        else:
            common = (1.0 + eu / CS2 + eu * eu / (2 * CS2 * CS2)
                      - usq / (2 * CS2))
            if galcor:
                common = common + (eu * eu * eu / (6 * CS2 ** 3)
                                   - eu * usq / (2 * CS2 * CS2))
        out.append(jnp.asarray(float(W[i]), dt) * rho * common)
    return jnp.stack(out)


def _make(name: str, galcor: bool):
    def _def():
        return family.base_def(name, E,
                               "3D BGK" + (" + Galilean correction"
                                           if galcor else ""),
                               faces="WE", symmetries="NS")

    def run(ctx: NodeCtx) -> jnp.ndarray:
        f = ctx.group("f")
        f = family.apply_boundaries(ctx, f, E, W, OPP)
        family.add_flux_objectives(ctx, f, E)
        dt = f.dtype
        rho = jnp.sum(f, axis=0)
        u = tuple(lbm.edot(E[:, a], f) / rho
                  for a in range(3))
        om = ctx.setting("omega")
        feq = _equilibrium(rho, u, galcor)
        fc = f + om * (feq - f)
        g = family.gravity_of(ctx)
        u2 = tuple(u[a] + g[a] for a in range(3))
        fc = fc + (_equilibrium(rho, u2, galcor) - feq)
        f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
        return ctx.store({"f": f})

    def init(ctx: NodeCtx) -> jnp.ndarray:
        return family.standard_init(ctx, E, W)

    def build():
        return _def().finalize().bind(
            run=run, init=init,
            quantities=family.make_getters(E, force_of=family.gravity_of))

    return build


build = _make("d3q27_BGK", galcor=False)
build_galcor = _make("d3q27_BGK_galcor", galcor=True)
