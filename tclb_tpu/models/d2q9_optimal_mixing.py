"""d2q9_optimalMixing — mixing optimization (flow + d2q5 scalar, moving-wall
control).

Behavioral parity target: reference model ``d2q9_optimalMixing``
(reference src/d2q9_optimalMixing/Dynamics.R, ADJOINT=1): d2q9 flow with a
d2q5 advected scalar (temperature), a zonal ``MovingWallVelocity`` control
(the optimized stirring schedule), and the mixing objectives TotalTempSqr /
CountCells / NMovingWallForce.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, OPP
from tclb_tpu.ops import lbm

W = lbm.weights(E)
# d2q5 for the scalar
EG = np.array([(0, 0), (1, 0), (-1, 0), (0, 1), (0, -1)], dtype=np.int32)
WG = lbm.weights(EG)
OPPG = lbm.opposite(EG)


def _def() -> ModelDef:
    d = ModelDef("d2q9_optimalMixing", ndim=2,
                 description="mixing optimization with moving-wall control")
    d.add_densities("f", E)
    d.add_densities("g", EG, group="g")
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("T", unit="K")
    d.add_setting("omega", default=1.0)
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("omegaT", default=1.0)
    d.add_setting("K", default=1 / 6, comment="thermal diffusivity",
                  derived={"omegaT": lambda k: 1.0 / (3 * k + 0.5)})
    d.add_setting("MovingWallVelocity", default=0.0, zonal=True)
    d.add_setting("Velocity", default=0.0, zonal=True)
    d.add_setting("Pressure", default=0.0, zonal=True)
    d.add_setting("Temperature", default=0.0, zonal=True)
    d.add_global("TotalTempSqr")
    d.add_global("CountCells")
    d.add_global("NMovingWallForce")
    d.add_node_type("MovingWall", "BOUNDARY")
    return d


def _g_eq(T, ux, uy):
    dt = T.dtype
    out = []
    for i in range(5):
        eu = float(EG[i, 0]) * ux + float(EG[i, 1]) * uy
        out.append(jnp.asarray(float(WG[i]), dt) * T * (1.0 + 3.0 * eu))
    return jnp.stack(out)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    g = ctx.group("g")
    dt = f.dtype
    mwv = ctx.setting("MovingWallVelocity")

    def moving_wall(f):
        fb = lbm.perm(f, OPP)
        corr = jnp.stack([
            6.0 * float(W[i]) * float(E[i, 0]) * mwv
            * jnp.ones(f.shape[1:], dt) if E[i, 0] else
            jnp.zeros(f.shape[1:], dt) for i in range(9)])
        return fb + corr

    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "MovingWall": moving_wall,
    })
    g = ctx.boundary_case(g, {
        ("Wall", "Solid", "MovingWall"): lambda g: lbm.perm(g, OPPG),
    })

    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    fc = f + ctx.setting("omega") * (lbm.equilibrium(E, W, rho, (ux, uy)) - f)
    temp = jnp.sum(g, axis=0)
    gc = g + ctx.setting("omegaT") * (_g_eq(temp, ux, uy) - g)
    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    g = jnp.where(coll, gc, g)

    # mixing measure: mean-free squared temperature
    # (reference TotalTempSqr/CountCells)
    where = ctx.nt_in_group("COLLISION")
    ctx.add_global("TotalTempSqr", temp * temp, where=where)
    ctx.add_global("CountCells", jnp.ones_like(temp), where=where)
    ex = lbm.edot(E[:, 0], f)
    ctx.add_global("NMovingWallForce", 2.0 * ex * mwv,
                   where=ctx.nt_is("MovingWall"))
    return ctx.store({"f": f, "g": g})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = 1.0 + 3.0 * jnp.broadcast_to(ctx.setting("Pressure"),
                                       shape).astype(dt)
    ux = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    f = lbm.equilibrium(E, W, rho, (ux, jnp.zeros(shape, dt)))
    t0 = jnp.broadcast_to(ctx.setting("Temperature"), shape).astype(dt)
    g = _g_eq(t0, jnp.zeros(shape, dt), jnp.zeros(shape, dt))
    return ctx.store({"f": f, "g": g})


def build():
    from tclb_tpu.models.d2q9_heat import get_rho, get_u
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"Rho": get_rho, "U": get_u,
                    "T": lambda c: jnp.sum(c.group("g"), axis=0)})
