"""d2q9_pf — conservative phase-field interface tracking on two lattices.

Behavioral parity target: reference model ``d2q9_pf``
(reference src/d2q9_pf/Dynamics.R, Dynamics.c.Rt — "Conservative phase-field
lattice Boltzmann model for interface tracking equation", M. Dzikowski 2016).
Two d2q9 populations: ``f`` carries hydrodynamics (all non-conserved moments
relaxed at one rate — the reference's orthonormalized-basis MRT with equal
rates, Dynamics.c.Rt:189-248 — with exact-difference gravity forcing), ``h``
carries the phase field with an anti-diffusive interface-sharpening term
``Bh w_i e.n``, ``Bh = 3 M (1 - 4 pf^2) W`` (Dynamics.c.Rt:239-246).  The
interface normal comes from the first central moments of ``h``
(Dynamics.c.Rt:71-96).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, _zou_he_x
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
OPP18 = np.concatenate([OPP, OPP + 9])


def _def() -> ModelDef:
    d = ModelDef("d2q9_pf", ndim=2,
                 description="conservative phase-field interface tracking")
    d.add_densities("f", E)
    d.add_densities("h", E)
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("Normal", unit="1/m", vector=True)
    d.add_quantity("PhaseField", unit="1")
    d.add_setting("omega", comment="one over relaxation time")
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity", default=0.0, zonal=True)
    d.add_setting("Pressure", default=0.0, zonal=True)
    d.add_setting("W", default=1.0, comment="anti-diffusivity coeff")
    d.add_setting("M", default=1.0, comment="mobility")
    d.add_setting("PhaseField", default=1.0, zonal=True,
                  comment="phase-field marker scalar")
    d.add_setting("GravitationX")
    d.add_setting("GravitationY")
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    return d


def _heq(pf, n, u, bh):
    """h equilibrium: advected phase field + sharpening flux along the
    interface normal (reference Heq, src/d2q9_pf/Dynamics.c.Rt:44-46)."""
    base = lbm.equilibrium(E, W, pf, u)
    # unrolled with scalar coefficients (kernel-safe: no captured
    # constant arrays), skipping the zero e.n terms
    out = []
    for i in range(9):
        en = sum(float(E[i, a]) * n[a] for a in range(2) if E[i, a])
        out.append(base[i] if isinstance(en, int)
                   else base[i] + bh * float(W[i]) * en)
    return jnp.stack(out)


def _normal(h, u):
    """Interface normal from the first central moments of h (reference
    getNormal, src/d2q9_pf/Dynamics.c.Rt:71-96): k = sum_i h_i (e_i - u),
    n = -k/|k| (zero where |k| vanishes)."""
    dt = h.dtype
    pf = jnp.sum(h, axis=0)
    k10 = lbm.edot(E[:, 0], h) - pf * u[0]
    k01 = lbm.edot(E[:, 1], h) - pf * u[1]
    ln = jnp.sqrt(k10 * k10 + k01 * k01)
    safe = jnp.where(ln > 0, ln, 1.0)
    return (jnp.where(ln > 0, -k10 / safe, 0.0),
            jnp.where(ln > 0, -k01 / safe, 0.0))


def _boundaries(ctx: NodeCtx, fh: jnp.ndarray) -> jnp.ndarray:
    """Boundary dispatch over the stacked (f, h) populations: walls bounce
    both groups (reference FullBounceBack swaps every streamed pair,
    src/lib/boundary.R:31-33); Zou/He in/outlets act on f only
    (src/d2q9_pf/Dynamics.c.Rt:169-187)."""
    vel = ctx.setting("Velocity")
    den = 1.0 + 3.0 * ctx.setting("Pressure")

    def zou(kind, side):
        def apply(fh):
            f = _zou_he_x(fh[:9], vel if kind == "velocity" else den,
                          kind, side)
            return jnp.concatenate([f, fh[9:]])
        return apply

    return ctx.boundary_case(fh, {
        ("Wall", "Solid"): lambda s: lbm.perm(s, OPP18),
        "EVelocity": zou("velocity", "E"),
        "WPressure": zou("pressure", "W"),
        "WVelocity": zou("velocity", "W"),
        "EPressure": zou("pressure", "E"),
    })


def run(ctx: NodeCtx) -> jnp.ndarray:
    fh = jnp.concatenate([ctx.group("f"), ctx.group("h")])
    fh = _boundaries(ctx, fh)
    f, h = fh[:9], fh[9:]
    dt = f.dtype

    # hydrodynamic collision: all non-conserved moments at rate omega with
    # exact-difference gravity (reference CollisionMRT,
    # src/d2q9_pf/Dynamics.c.Rt:189-225: equal S on every order makes the
    # orthonormal basis immaterial)
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    gx = ctx.setting("GravitationX")
    gy = ctx.setting("GravitationY")
    omega = ctx.setting("omega")
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    feq2 = lbm.equilibrium(E, W, rho, (ux + gx, uy + gy))
    fc = feq2 + (1.0 - omega) * (f - feq)

    # phase-field collision sees the post-collision velocity (reference
    # calls getU() after the f update, Dynamics.c.Rt:229-246)
    u2 = (ux + gx, uy + gy)
    pf = jnp.sum(h, axis=0)
    n = _normal(h, u2)
    omega_ph = 1.0 / (3.0 * ctx.setting("M") + 0.5)
    bh = 3.0 * ctx.setting("M") * (1.0 - 4.0 * pf * pf) * ctx.setting("W")
    hc = h - omega_ph * (h - _heq(pf, n, u2, bh))

    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    h = jnp.where(coll, hc, h)
    return ctx.store({"f": f, "h": h})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(1.0 + 3.0 * ctx.setting("Pressure"),
                           shape).astype(dt)
    ux = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    uy = jnp.zeros(shape, dt)
    pf = jnp.broadcast_to(ctx.setting("PhaseField"), shape).astype(dt)
    f = lbm.equilibrium(E, W, rho, (ux, uy))
    h = lbm.equilibrium(E, W, pf, (ux, uy))
    return ctx.store({"f": f, "h": h})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_normal(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    h = ctx.group("h")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    u = (lbm.edot(E[:, 0], f) / rho,
         lbm.edot(E[:, 1], f) / rho)
    nx, ny = _normal(h, u)
    return jnp.stack([nx, ny, jnp.zeros_like(nx)])


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities={
            "Rho": lambda c: jnp.sum(c.group("f"), axis=0),
            "U": get_u,
            "Normal": get_normal,
            "PhaseField": lambda c: jnp.sum(c.group("h"), axis=0),
        })
