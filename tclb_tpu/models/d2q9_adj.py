"""d2q9_adj — 2D MRT with a per-node porosity design field for adjoint
topology optimization.

Behavioral parity target: reference model ``d2q9_adj``
(reference src/d2q9_adj/Dynamics.R, Dynamics.c.Rt): design density ``w``
(``parameter=T``), hyperbolic porosity transform
``nw = w / (1 - PorocityGamma*(1-w))``, Brinkman-style velocity penalization
``u *= nw`` inside the MRT collision, Drag/Lift accumulated as ``(1-nw)*u``,
Material/MaterialPenalty objectives on DesignSpace nodes.  Where the
reference differentiates the generated kernel with Tapenade
(tools/makeAD), here the whole step is differentiable by construction —
``tclb_tpu.adjoint`` provides the gradient machinery.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, OPP, M, W, _equilibrium, _zou_he_x
from tclb_tpu.ops import lbm


def _def() -> ModelDef:
    d = ModelDef("d2q9_adj", ndim=2,
                 description="2D MRT with porosity design field (adjoint "
                             "topology optimization)")
    d.add_densities("f", E)
    d.add_density("w", group="w", parameter=True)
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("W")
    d.add_quantity("RhoB", adjoint=True)
    d.add_quantity("UB", adjoint=True, vector=True)
    d.add_quantity("WB", adjoint=True)
    d.add_setting("omega", comment="one over relaxation time")
    d.add_setting("nu", default=1 / 6, comment="viscosity",
                  derived={"omega": lambda nu: 1.0 - 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity", default=0.0, zonal=True,
                  comment="inlet velocity")
    d.add_setting("Pressure", default=0.0, zonal=True,
                  comment="inlet pressure")
    d.add_setting("ForceX")
    d.add_setting("ForceY")
    d.add_setting("PorocityGamma",
                  comment="gamma of the hyperbolic porosity transform")
    d.add_setting("PorocityTheta",
                  derived={"PorocityGamma": lambda th: 1.0 - math.exp(th)},
                  comment="theta of the hyperbolic porosity transform")
    d.add_setting("Porocity", zonal=True,
                  comment="initial porosity of design nodes")
    d.add_global("Drag")
    d.add_global("Lift")
    d.add_global("MaterialPenalty")
    d.add_global("Material")
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    return d


def _collision_mrt(ctx: NodeCtx, f: jnp.ndarray, w: jnp.ndarray):
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho

    usq = ux * ux + uy * uy
    ploss = ux / rho * ((rho - 1.0) / 3.0 + usq / rho * 0.5)
    ctx.add_global("OutletFlux", ux / rho, where=ctx.nt_is("Outlet"))
    ctx.add_global("InletFlux", ux / rho, where=ctx.nt_is("Inlet"))
    ctx.add_global("PressureLoss",
                   jnp.where(ctx.nt_is("Inlet"), ploss, -ploss),
                   where=ctx.nt_is("Inlet") | ctx.nt_is("Outlet"))

    # keep-factors: energy -1/3, heat-flux/stress relax with omega
    # (reference OMEGA vector, src/d2q9_adj/Dynamics.c.Rt:137)
    om = ctx.setting("omega").astype(dt)
    feq = _equilibrium(rho, ux, uy)
    mn = lbm.moments(M, f - feq)
    # per-plane scalar keep factors (a stacked-then-reshaped (9,)
    # settings vector is a shape cast Mosaic cannot lower)
    keep = [None, None, None, -1.0 / 3.0, None, None, None, om, om]
    m_neq = jnp.stack([jnp.zeros_like(mn[i]) if r is None else mn[i] * r
                       for i, r in enumerate(keep)])

    ux2 = ux + ctx.setting("ForceX")
    uy2 = uy + ctx.setting("ForceY")
    # hyperbolic porosity transform + Brinkman penalization
    # (reference src/d2q9_adj/Dynamics.c.Rt:184-189)
    nw = w / (1.0 - ctx.setting("PorocityGamma") * (1.0 - w))
    ctx.add_global("Drag", (1.0 - nw) * ux2, where=ctx.nt_is("MRT"))
    ctx.add_global("Lift", (1.0 - nw) * uy2, where=ctx.nt_is("MRT"))
    ux2, uy2 = ux2 * nw, uy2 * nw
    m_post = m_neq + lbm.moments(M, _equilibrium(rho, ux2, uy2))
    return lbm.from_moments(M, m_post)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    w = ctx.density("w")
    vel = ctx.setting("Velocity")
    den = 1.0 + 3.0 * ctx.setting("Pressure")
    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, den, "pressure", "W"),
        "WVelocity": lambda f: _zou_he_x(f, vel, "velocity", "W"),
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
    })
    f = jnp.where(ctx.nt_is("MRT")[None], _collision_mrt(ctx, f, w), f)
    # material objectives live on DesignSpace nodes
    # (reference src/d2q9_adj/Dynamics.c.Rt:108-111)
    in_design = ctx.nt_in_group("DESIGNSPACE")
    ctx.add_global("MaterialPenalty", w * (1.0 - w), where=in_design)
    ctx.add_global("Material", 1.0 - w, where=in_design)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    den = jnp.broadcast_to(1.0 + 3.0 * ctx.setting("Pressure"),
                           shape).astype(dt)
    vel = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    f = _equilibrium(den, vel, jnp.zeros(shape, dt))
    w = 1.0 - jnp.broadcast_to(ctx.setting("Porocity"), shape).astype(dt)
    w = jnp.where(ctx.nt_is("Solid"), jnp.zeros_like(w), w)
    return ctx.store({"f": f, "w": w[None]})


def get_rho(ctx: NodeCtx) -> jnp.ndarray:
    return jnp.sum(ctx.group("f"), axis=0)


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_w(ctx: NodeCtx) -> jnp.ndarray:
    return ctx.density("w")


def build():
    model = _def().finalize()
    # adjoint quantities read the same expressions over the adjoint
    # (cotangent) planes — the solver passes adjoint storage as the ctx
    # fields when evaluating them (reference getRhoB/getUB/getWB)
    return model.bind(run=run, init=init,
                      quantities={"Rho": get_rho, "U": get_u, "W": get_w,
                                  "RhoB": get_rho, "UB": get_u,
                                  "WB": get_w})
