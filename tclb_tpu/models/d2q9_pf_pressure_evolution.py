"""d2q9_pf_pressureEvolution — Fakhari/Geier/Lee mass-conserving two-phase
LBM in pressure-evolution form.

Behavioral parity target: reference model ``d2q9_pf_pressureEvolution``
(reference src/d2q9_pf_pressureEvolution/Dynamics.R, Dynamics.c.Rt — "A
mass-conserving LBM with dynamic grid refinement for immiscible two-phase
flows", maintained by T. Mitchell).  The hydrodynamic population is the
pressure-shifted ``g-bar`` distribution: its equilibrium is
``Gamma_i rho/3 + w_i (p - rho/3)`` with the pressure recovered as
``p = sum(f) + (rho_h-rho_l)(grad phi . u)/6`` (Dynamics.c.Rt:105-110);
interface and body-force terms are the half-trapezoid corrections of the
reference (:283-335).  Relaxation is classical-matrix MRT with settings
S0..S6 and a phase-interpolated ``1/(tau+1/2)`` on the stress pair
(:296-322).  The phase field streams on ``h`` with mobility relaxation and a
``PhaseF`` Field provides the +-2 gradient stencil (:151-160).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
OPP18 = np.concatenate([OPP, OPP + 9])

# classical (integer Lallemand-Luo) d2q9 moment rows: rho, e, eps, jx, qx,
# jy, qy, pxx, pxy (reference CollisionMRT matrix, Dynamics.c.Rt:298-307)
M_CLASSIC = np.array([
    [1, 1, 1, 1, 1, 1, 1, 1, 1],
    [-4, -1, -1, -1, -1, 2, 2, 2, 2],
    [4, -2, -2, -2, -2, 1, 1, 1, 1],
    [0, 1, 0, -1, 0, 1, -1, -1, 1],
    [0, -2, 0, 2, 0, 1, -1, -1, 1],
    [0, 0, 1, 0, -1, 1, 1, -1, -1],
    [0, 0, -2, 0, 2, 1, 1, -1, -1],
    [0, 1, -1, 1, -1, 0, 0, 0, 0],
    [0, 0, 0, 0, 0, 1, -1, 1, -1],
], dtype=np.float64)


def _def() -> ModelDef:
    d = ModelDef("d2q9_pf_pressureEvolution", ndim=2,
                 description="pressure-evolution phase-field two-phase LBM")
    d.add_densities("f", E)
    d.add_densities("h", E)
    d.add_field("PhaseF", dx=(-2, 2), dy=(-2, 2), group="phi")
    d.add_stage("PhaseInit", "Init", load_densities=False)
    d.add_stage("BaseInit", "Init_distributions", load_densities=False)
    d.add_stage("calcPhase", "calcPhaseF")
    d.add_stage("BaseIter", "Run")
    d.add_action("Iteration", ("BaseIter", "calcPhase"))
    d.add_action("Init", ("PhaseInit", "BaseInit", "calcPhase"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("PhaseField", unit="1")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("P", unit="Pa")
    d.add_quantity("Mu", unit="1")
    d.add_quantity("InterfaceForce", unit="N", vector=True)
    d.add_setting("Density_h", default=1.0, comment="high density")
    d.add_setting("Density_l", default=1.0, comment="low density")
    d.add_setting("PhaseField_h", default=1.0)
    d.add_setting("PhaseField_l", default=0.0)
    d.add_setting("PhaseField", default=0.0, zonal=True)
    d.add_setting("W", default=4.0, comment="interface width")
    d.add_setting("M", default=0.05, comment="mobility")
    d.add_setting("sigma", default=1e-3, comment="surface tension")
    d.add_setting("omega_l", default=1.0)
    d.add_setting("omega_h", default=1.0)
    d.add_setting("nu_l", default=1 / 6,
                  derived={"omega_l": lambda nu: 1.0 / (3 * nu)})
    d.add_setting("nu_h", default=1 / 6,
                  derived={"omega_h": lambda nu: 1.0 / (3 * nu)})
    for i in range(7):
        d.add_setting(f"S{i}", default=1.0, comment="relaxation param")
    d.add_setting("VelocityX", default=0.0, zonal=True)
    d.add_setting("VelocityY", default=0.0, zonal=True)
    d.add_setting("Pressure", default=0.0, zonal=True)
    d.add_setting("GravitationX")
    d.add_setting("GravitationY")
    d.add_setting("BuoyancyX")
    d.add_setting("BuoyancyY")
    d.add_setting("GmatchedX")
    d.add_setting("GmatchedY")
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    d.add_global("TotalDensity", unit="1kg/m3",
                 comment="mass conservation check")
    return d


# --------------------------------------------------------------------- #
# helpers over the PhaseF stencil
# --------------------------------------------------------------------- #


def _phase(ctx, dx=0, dy=0):
    return ctx.load("PhaseF", dx, dy)


def _rho_of(ctx, pf):
    rl = ctx.setting("Density_l")
    rh = ctx.setting("Density_h")
    pl = ctx.setting("PhaseField_l")
    ph = ctx.setting("PhaseField_h")
    return rl + (rh - rl) * (pf - pl) / (ph - pl)


def _grad_phi(ctx):
    """Isotropic central gradient (reference calcGradPhi,
    Dynamics.c.Rt:151-157)."""
    gx = (_phase(ctx, 1, 0) - _phase(ctx, -1, 0)) / 3.0 \
        + (_phase(ctx, 1, 1) - _phase(ctx, -1, -1)
           + _phase(ctx, 1, -1) - _phase(ctx, -1, 1)) / 12.0
    gy = (_phase(ctx, 0, 1) - _phase(ctx, 0, -1)) / 3.0 \
        + (_phase(ctx, 1, 1) - _phase(ctx, -1, -1)
           + _phase(ctx, -1, 1) - _phase(ctx, 1, -1)) / 12.0
    return gx, gy


def _mu(ctx):
    """Chemical potential with the 9-point laplacian (reference getMu,
    Dynamics.c.Rt:111-120)."""
    pf = _phase(ctx)
    pl = ctx.setting("PhaseField_l")
    ph = ctx.setting("PhaseField_h")
    pavg = 0.5 * (pl + ph)
    w = ctx.setting("W")
    sig = ctx.setting("sigma")
    lp = (_phase(ctx, 1, 1) + _phase(ctx, -1, 1)
          + _phase(ctx, 1, -1) + _phase(ctx, -1, -1)
          + 4.0 * (_phase(ctx, 1, 0) + _phase(ctx, -1, 0)
                   + _phase(ctx, 0, 1) + _phase(ctx, 0, -1))
          - 20.0 * pf) / 6.0
    return (4.0 * (12.0 * sig / w) * (pf - pl) * (pf - ph) * (pf - pavg)
            - 1.5 * sig * w * lp)


def _body_force(ctx, rho, pf):
    """(rho-rho_h)*Buoyancy + rho*Gravitation + (1-pf)*rho_h*Gmatched
    (reference Dynamics.c.Rt:95-96)."""
    rh = ctx.setting("Density_h")
    fbx = (rho - rh) * ctx.setting("BuoyancyX") \
        + rho * ctx.setting("GravitationX") \
        + (1.0 - pf) * rh * ctx.setting("GmatchedX")
    fby = (rho - rh) * ctx.setting("BuoyancyY") \
        + rho * ctx.setting("GravitationY") \
        + (1.0 - pf) * rh * ctx.setting("GmatchedY")
    return fbx, fby


def _rc(ctx):
    """Directional central differences Rc_i = (phi(e_i)-phi(-e_i))/2
    (reference Dynamics.c.Rt:264-272)."""
    out = [jnp.zeros_like(_phase(ctx))]
    for i in range(1, 9):
        dx, dy = int(E[i, 0]), int(E[i, 1])
        out.append(0.5 * (_phase(ctx, dx, dy) - _phase(ctx, -dx, -dy)))
    return out


def _gamma(u):
    """Gamma_i = feq_i/rho (second-order equilibrium at unit density)."""
    one = jnp.ones_like(u[0])
    return lbm.equilibrium(E, W, one, u)


def _correction_terms(ctx, gamma, u, grad, fb, mu, rc):
    """Interface + body-force correction stacks (reference
    Dynamics.c.Rt:285-294): iface_i = ((Gamma_i - w_i)(rho_h-rho_l)/3 +
    mu Gamma_i)(Rc_i - u.grad); body_i = Gamma_i ((e_i-u).Fb)."""
    dt = gamma.dtype
    drho = ctx.setting("Density_h") - ctx.setting("Density_l")
    ugrad = u[0] * grad[0] + u[1] * grad[1]
    iface, body = [], []
    for i in range(9):
        gi = gamma[i]
        iface.append(((gi - float(W[i])) * drho / 3.0 + mu * gi)
                     * (rc[i] - ugrad))
        body.append(gi * ((float(E[i, 0]) - u[0]) * fb[0]
                          + (float(E[i, 1]) - u[1]) * fb[1]))
    return jnp.stack(iface).astype(dt), jnp.stack(body).astype(dt)


def _normal(grad):
    gn = jnp.sqrt(grad[0] * grad[0] + grad[1] * grad[1])
    safe = jnp.where(gn > 0, gn, 1.0)
    return (jnp.where(gn > 0, grad[0] / safe, 0.0),
            jnp.where(gn > 0, grad[1] / safe, 0.0))


def _heq(ctx, pf, gamma, n):
    """h equilibrium: Gamma_i pf + theta w_i e.n with
    theta = 3M(1-4(pf-pfavg)^2)/W (reference Dynamics.c.Rt:338-349)."""
    dt = gamma.dtype
    pavg = 0.5 * (ctx.setting("PhaseField_l") + ctx.setting("PhaseField_h"))
    theta = (3.0 * ctx.setting("M")) \
        * (1.0 - 4.0 * (pf - pavg) * (pf - pavg)) / ctx.setting("W")
    out = []
    for i in range(9):
        en = sum(float(E[i, a]) * n[a] for a in range(2) if E[i, a])
        out.append(gamma[i] * pf if isinstance(en, int)
                   else gamma[i] * pf + theta * float(W[i]) * en)
    return jnp.stack(out)


# --------------------------------------------------------------------- #
# stages
# --------------------------------------------------------------------- #


def phase_init(ctx: NodeCtx):
    """PhaseInit stage: seed PhaseF from the zonal setting so gradients are
    available to Init_distributions (reference Init, Dynamics.c.Rt:163-166)."""
    dt = ctx._fields.dtype
    pf = jnp.broadcast_to(ctx.setting("PhaseField"),
                          ctx.flags.shape).astype(dt)
    return {"PhaseF": pf}


def calc_phase(ctx: NodeCtx):
    """calcPhase stage: PhaseF = sum of the streamed h populations
    (reference calcPhaseF, Dynamics.c.Rt:158-160)."""
    return {"PhaseF": jnp.sum(ctx.group("h"), axis=0)}


def init_distributions(ctx: NodeCtx) -> jnp.ndarray:
    """BaseInit stage (reference Init_distributions, Dynamics.c.Rt:167-212):
    h at equilibrium, g-bar shifted to zero minus half corrections."""
    dt = ctx._fields.dtype
    pf = _phase(ctx)
    grad = _grad_phi(ctx)
    n = _normal(grad)
    mu = _mu(ctx)
    rho = _rho_of(ctx, pf)
    ctx.add_global("TotalDensity", rho)
    u = (jnp.broadcast_to(ctx.setting("VelocityX"), pf.shape).astype(dt),
         jnp.broadcast_to(ctx.setting("VelocityY"), pf.shape).astype(dt))
    fb = _body_force(ctx, rho, pf)
    gamma = _gamma(u)
    rc = _rc(ctx)
    iface, body = _correction_terms(ctx, gamma, u, grad, fb, mu, rc)
    h = _heq(ctx, pf, gamma, n)
    f = -0.5 * iface - 0.5 * body
    return ctx.store({"f": f, "h": h})


def run(ctx: NodeCtx) -> jnp.ndarray:
    fh = jnp.concatenate([ctx.group("f"), ctx.group("h")])
    # only bounce-back walls: the reference's velocity/pressure BC bodies
    # are empty (Dynamics.c.Rt:362-377)
    fh = ctx.boundary_case(fh, {
        ("Wall", "Solid"): lambda s: lbm.perm(s, OPP18),
    })
    f, h = fh[:9], fh[9:]
    dt = f.dtype

    pf = _phase(ctx)
    rho = _rho_of(ctx, pf)
    ctx.add_global("TotalDensity", rho, where=ctx.nt_is("MRT"))
    mu = _mu(ctx)
    fb = _body_force(ctx, rho, pf)
    grad = _grad_phi(ctx)
    jx = lbm.edot(E[:, 0], f)
    jy = lbm.edot(E[:, 1], f)
    u = ((3.0 / rho) * (jx + (0.5 / 3.0) * (mu * grad[0] + fb[0])),
         (3.0 / rho) * (jy + (0.5 / 3.0) * (mu * grad[1] + fb[1])))
    p = jnp.sum(f, axis=0) \
        + (ctx.setting("Density_h") - ctx.setting("Density_l")) \
        * (grad[0] * u[0] + grad[1] * u[1]) / 6.0

    gamma = _gamma(u)
    rc = _rc(ctx)
    iface, body = _correction_terms(ctx, gamma, u, grad, fb, mu, rc)
    g_bar_eq = gamma * rho / 3.0 + lbm.wstack(W, p - rho / 3.0)
    r = f - (g_bar_eq - 0.5 * iface - 0.5 * body)

    # classical-matrix MRT relaxation with phase-interpolated stress rate
    # (reference Dynamics.c.Rt:296-327)
    pl = ctx.setting("PhaseField_l")
    ph = ctx.setting("PhaseField_h")
    tau = 1.0 / (ctx.setting("omega_l")
                 + (ctx.setting("omega_h") - ctx.setting("omega_l"))
                 * (pf - pl) / (ph - pl))
    s_stress = 1.0 / (tau + 0.5)
    m = lbm.moments(M_CLASSIC, r)
    rates = [ctx.setting(f"S{i}") for i in range(7)]
    m = jnp.stack([m[i] * rates[i] for i in range(7)]
                  + [m[7] * s_stress, m[8] * s_stress])
    r = lbm.from_moments(M_CLASSIC, m)
    fc = f - r + iface + body

    # phase-field collision (reference Dynamics.c.Rt:338-349)
    n = _normal(grad)
    omega_ph = 1.0 / (3.0 * ctx.setting("M") + 0.5)
    hc = h - omega_ph * (h - _heq(ctx, pf, gamma, n))

    coll = ctx.nt_is("MRT")[None]
    f = jnp.where(coll, fc, f)
    h = jnp.where(coll, hc, h)
    return ctx.store({"f": f, "h": h})


# --------------------------------------------------------------------- #
# quantities
# --------------------------------------------------------------------- #


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    pf = _phase(ctx)
    rho = _rho_of(ctx, pf)
    mu = _mu(ctx)
    fb = _body_force(ctx, rho, pf)
    grad = _grad_phi(ctx)
    jx = lbm.edot(E[:, 0], f)
    jy = lbm.edot(E[:, 1], f)
    ux = (3.0 / rho) * (jx + (0.5 / 3.0) * (mu * grad[0] + fb[0]))
    uy = (3.0 / rho) * (jy + (0.5 / 3.0) * (mu * grad[1] + fb[1]))
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_p(ctx: NodeCtx) -> jnp.ndarray:
    u = get_u(ctx)
    grad = _grad_phi(ctx)
    return jnp.sum(ctx.group("f"), axis=0) \
        + (ctx.setting("Density_h") - ctx.setting("Density_l")) \
        * (grad[0] * u[0] + grad[1] * u[1]) / 6.0


def get_iforce(ctx: NodeCtx) -> jnp.ndarray:
    mu = _mu(ctx)
    grad = _grad_phi(ctx)
    return jnp.stack([mu * grad[0], mu * grad[1], jnp.zeros_like(mu)])


def build():
    return _def().finalize().bind(
        run=run, init=init_distributions,
        stages={"Init": phase_init,
                "Init_distributions": init_distributions,
                "calcPhaseF": calc_phase},
        quantities={
            "Rho": lambda c: _rho_of(c, _phase(c)),
            "PhaseField": lambda c: _phase(c),
            "U": get_u,
            "P": get_p,
            "Mu": lambda c: _mu(c),
            "InterfaceForce": get_iforce,
        })
