"""d2q9_heat — coupled flow + temperature (double-distribution d2q9+d2q9).

Behavioral parity target: reference model ``d2q9_heat``
(reference src/d2q9_heat/Dynamics.R, Dynamics.c.Rt): a d2q9 ``f`` lattice
for flow and a second d2q9 ``T`` lattice advecting temperature at the fluid
velocity with diffusivity ``FluidAlfa``; ``Heater`` nodes
(ADDITIONALS group) pin the relaxation target temperature (the reference
hard-codes 100, src/d2q9_heat/Dynamics.c.Rt:257 — here it is the
``HeaterTemperature`` setting with that default).
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, OPP, _zou_he_x
from tclb_tpu.ops import lbm

W = lbm.weights(E)


def _def() -> ModelDef:
    d = ModelDef("d2q9_heat", ndim=2,
                 description="2D flow + temperature (double distribution)")
    d.add_densities("f", E)
    d.add_densities("T", E, group="T")
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("T", unit="K")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_setting("omega", default=1.0, comment="one over relaxation time")
    d.add_setting("nu", default=1 / 6, comment="viscosity",
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("InletVelocity", comment="inlet velocity")
    d.add_setting("InletPressure", default=0.0, comment="inlet pressure",
                  derived={"InletDensity": lambda p: 1.0 + p / 3.0})
    d.add_setting("InletDensity", default=1.0)
    d.add_setting("InletTemperature", default=1.0)
    d.add_setting("InitTemperature", default=1.0)
    d.add_setting("FluidAlfa", default=1.0, comment="thermal diffusivity")
    d.add_setting("HeaterTemperature", default=100.0, zonal=True,
                  comment="pinned temperature of Heater nodes (zonal: "
                          "Heaters in different settings zones can pin "
                          "different temperatures; the reference hardcodes "
                          "d=100, src/d2q9_heat/Dynamics.c.Rt:257)")
    d.add_global("OutFlux")
    d.add_node_type("Heater", "ADDITIONALS")
    return d


def _t_eq(T, ux, uy):
    dt = T.dtype
    out = []
    for i in range(9):
        eu = float(E[i, 0]) * ux + float(E[i, 1]) * uy
        out.append(jnp.asarray(float(W[i]), dt) * T * (1.0 + 3.0 * eu))
    return jnp.stack(out)


def run(ctx: NodeCtx, solid_adiabatic: bool = True) -> jnp.ndarray:
    f = ctx.group("f")
    fT = ctx.group("T")
    dt = f.dtype
    vel = ctx.setting("InletVelocity")
    den = ctx.setting("InletDensity")
    t_in = ctx.setting("InletTemperature")

    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "WVelocity": lambda f: _zou_he_x(f, vel, "velocity", "W"),
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, den, "pressure", "W"),
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
    })
    # temperature boundaries: bounce-back at walls (adiabatic), fixed
    # inlet temperature at velocity inlets.  The conjugate model
    # (d2q9_heat_conjugate) passes solid_adiabatic=False: its Solid
    # nodes CONDUCT
    # (temperature streams through and collides with SolidAlfa there) —
    # bouncing fT back would insulate the interface and break conjugate
    # flux continuity.
    t_wall = ("Wall", "Solid") if solid_adiabatic else ("Wall",)
    fT = ctx.boundary_case(fT, {
        t_wall: lambda t: lbm.perm(t, OPP),
        ("WVelocity", "EPressure"): lambda t: _t_eq(
            jnp.broadcast_to(t_in, t.shape[1:]).astype(dt),
            jnp.zeros(t.shape[1:], dt), jnp.zeros(t.shape[1:], dt)),
    })

    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho

    om = ctx.setting("omega")
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    fc = f + om * (feq - f)

    temp = jnp.sum(fT, axis=0)
    # Heater nodes relax toward the pinned temperature
    # (reference src/d2q9_heat/Dynamics.c.Rt:257: d=100)
    target = jnp.where(ctx.nt_is("Heater"),
                       ctx.setting("HeaterTemperature"), temp)
    om_t = 1.0 / (3.0 * ctx.setting("FluidAlfa") + 0.5)
    tc = fT + om_t * (_t_eq(target, ux, uy) - fT)

    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    fT = jnp.where(coll, tc, fT)
    ctx.add_global("OutFlux", temp * ux, where=ctx.nt_is("Outlet"))
    return ctx.store({"f": f, "T": fT})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.ones(shape, dt)
    ux = jnp.broadcast_to(ctx.setting("InletVelocity"), shape).astype(dt)
    f = lbm.equilibrium(E, W, rho, (ux, jnp.zeros(shape, dt)))
    t0 = jnp.broadcast_to(ctx.setting("InitTemperature"), shape).astype(dt)
    fT = _t_eq(t0, jnp.zeros(shape, dt), jnp.zeros(shape, dt))
    return ctx.store({"f": f, "T": fT})


def get_rho(ctx):
    return jnp.sum(ctx.group("f"), axis=0)


def get_t(ctx):
    return jnp.sum(ctx.group("T"), axis=0)


def get_u(ctx):
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"Rho": get_rho, "T": get_t, "U": get_u})
