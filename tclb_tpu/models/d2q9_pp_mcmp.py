"""d2q9_pp_MCMP — Shan–Chen multi-component multi-phase (two populations).

Behavioral parity target: reference model ``d2q9_pp_MCMP``
(reference src/d2q9_pp_MCMP/Dynamics.R, Dynamics.c.Rt).  Two d2q9
populations ``f`` and ``g`` with pseudopotentials ``psi_f = rho_f``,
``psi_g = rho_g`` (walls carry the adhesion potentials ``Gad2/Gc`` and
``Gad1/Gc`` respectively — Dynamics.c.Rt:189-212), cross-component
Shan–Chen forces ``F_f = -Gc psi_f(0) sum w_i psi_g(x+e_i) e_i`` (+ the
mirror for g, :127-180), the viscosity-weighted common velocity
``u = (sum_k J_k/omega_k) / (sum_k rho_k/omega_k)`` (:93-115), and BGK
collision of each component toward the common velocity shifted by its own
force ``ueq_k = u + F_k/(omega_k rho_k)`` (:318-360).  Per-component Zou/He
velocity/pressure boundaries (lib ZouHe with ``rho = 3 P + 1``), full
bounce-back walls.  TotalDensity1/2 globals accumulate per collision node.

The optional shear-layer init (SL_* settings, :252-289) initializes a
double shear layer with a sinusoidal perturbation for the Kelvin–Helmholtz
demo; implemented via the same closed forms.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
OPP18 = np.concatenate([OPP, OPP + 9])


def _def() -> ModelDef:
    d = ModelDef("d2q9_pp_MCMP", ndim=2,
                 description="Shan-Chen multi-component multi-phase")
    d.add_densities("f", E)
    d.add_densities("g", E)
    d.add_field("psi_f", dx=(-1, 1), dy=(-1, 1))
    d.add_field("psi_g", dx=(-1, 1), dy=(-1, 1))
    d.add_stage("BaseIteration", "Run")
    d.add_stage("CalcPsi_f", "CalcPsi_f")
    d.add_stage("CalcPsi_g", "CalcPsi_g")
    d.add_stage("BaseInit", "Init", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "CalcPsi_f", "CalcPsi_g"))
    d.add_action("Init", ("BaseInit", "CalcPsi_f", "CalcPsi_g"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("Rhof", unit="kg/m3")
    d.add_quantity("Rhog", unit="kg/m3")
    d.add_quantity("P", unit="Pa")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("Ff", unit="N", vector=True)
    d.add_quantity("Fg", unit="N", vector=True)
    d.add_setting("omega", comment="one over relaxation time, f")
    d.add_setting("omega_g", comment="one over relaxation time, g")
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("nu_g", default=1 / 6,
                  derived={"omega_g": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity_f", default=0.0, zonal=True)
    d.add_setting("Pressure_f", default=0.0, zonal=True)
    d.add_setting("Velocity_g", default=0.0, zonal=True)
    d.add_setting("Pressure_g", default=0.0, zonal=True)
    d.add_setting("Density", default=1.0, zonal=True,
                  comment="init density of component f")
    d.add_setting("Density_dry", default=1.0, zonal=True,
                  comment="init density of component g")
    d.add_setting("Gc", comment="fluid-fluid interaction")
    d.add_setting("Gad1", comment="fluid1-wall adhesion")
    d.add_setting("Gad2", comment="fluid2-wall adhesion")
    d.add_setting("R", default=1.0, comment="EoS gas const (unused in the "
                  "live ideal-psi path, kept for config parity)")
    d.add_setting("T", default=1.0)
    d.add_setting("a", default=1.0)
    d.add_setting("b", default=4.0)
    d.add_setting("Smag", comment="Smagorinsky constant (MRT path only)")
    d.add_setting("SL_U", comment="shear layer velocity")
    d.add_setting("SL_lambda", comment="shear layer steepness")
    d.add_setting("SL_delta", comment="shear layer disturbance")
    d.add_setting("SL_L", comment="shear layer length scale (0 = off)")
    d.add_setting("GravitationX")
    d.add_setting("GravitationY")
    d.add_global("TotalDensity1", unit="kg/m3")
    d.add_global("TotalDensity2", unit="kg/m3")
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    d.add_node_type("Smagorinsky", "LES")
    d.add_node_type("Stab", "ENTROPIC")
    return d


def calc_psi_f(ctx: NodeCtx):
    """psi_f = rho_f; wall nodes carry Gad2/Gc for the adhesion force
    (reference CalcPsi_f, src/d2q9_pp_MCMP/Dynamics.c.Rt:189-200)."""
    rho = jnp.sum(ctx.group("f"), axis=0)
    return {"psi_f": jnp.where(ctx.nt_is("Wall"),
                               ctx.setting("Gad2") / ctx.setting("Gc"), rho)}


def calc_psi_g(ctx: NodeCtx):
    rho = jnp.sum(ctx.group("g"), axis=0)
    return {"psi_g": jnp.where(ctx.nt_is("Wall"),
                               ctx.setting("Gad1") / ctx.setting("Gc"), rho)}


def _sc_force(ctx: NodeCtx, own: str, other: str):
    """Cross-component Shan-Chen force (reference getFf/getFg,
    src/d2q9_pp_MCMP/Dynamics.c.Rt:127-180)."""
    psi0 = ctx.load(own)
    fx = sum(float(W[i] * E[i, 0])
             * ctx.load(other, int(E[i, 0]), int(E[i, 1]))
             for i in range(1, 9) if E[i, 0])
    fy = sum(float(W[i] * E[i, 1])
             * ctx.load(other, int(E[i, 0]), int(E[i, 1]))
             for i in range(1, 9) if E[i, 1])
    gc = ctx.setting("Gc")
    return (-gc * psi0 * fx + ctx.setting("GravitationX"),
            -gc * psi0 * fy + ctx.setting("GravitationY"))


def _common_u(ctx: NodeCtx, f, g):
    """Viscosity-weighted common velocity (reference getU,
    src/d2q9_pp_MCMP/Dynamics.c.Rt:93-115)."""
    dt = f.dtype
    om_f, om_g = ctx.setting("omega"), ctx.setting("omega_g")
    jfx = lbm.edot(E[:, 0], f)
    jfy = lbm.edot(E[:, 1], f)
    jgx = lbm.edot(E[:, 0], g)
    jgy = lbm.edot(E[:, 1], g)
    rf = jnp.sum(f, axis=0)
    rg = jnp.sum(g, axis=0)
    den = rf / om_f + rg / om_g
    den = jnp.where(jnp.abs(den) > 1e-12, den, 1.0)
    return (jfx / om_f + jgx / om_g) / den, (jfy / om_f + jgy / om_g) / den


def _zou_he(ctx: NodeCtx, stack, side, kind, vel_s, pres_s):
    """Per-component Zou/He on an x face: lib ZouHe with rho = 3 P + 1
    (reference src/lib/boundary.R:63-104)."""
    vel = ctx.setting(vel_s)
    den = 3.0 * ctx.setting(pres_s) + 1.0
    f = lbm.nebb_boundary(E, W, OPP, stack[:9], 0, side, kind,
                          vel if kind == "velocity" else den)
    g = lbm.nebb_boundary(E, W, OPP, stack[9:], 0, side, kind,
                          ctx.setting(vel_s.replace("_f", "_g"))
                          if kind == "velocity"
                          else 3.0 * ctx.setting(pres_s.replace("_f", "_g"))
                          + 1.0)
    return jnp.concatenate([f, g])


def run(ctx: NodeCtx) -> jnp.ndarray:
    fg = jnp.concatenate([ctx.group("f"), ctx.group("g")])
    fg = ctx.boundary_case(fg, {
        ("Wall", "Solid"): lambda s: lbm.perm(s, OPP18),
        "EVelocity": lambda s: _zou_he(ctx, s, -1, "velocity",
                                       "Velocity_f", "Pressure_f"),
        "WPressure": lambda s: _zou_he(ctx, s, +1, "pressure",
                                       "Velocity_f", "Pressure_f"),
        "WVelocity": lambda s: _zou_he(ctx, s, +1, "velocity",
                                       "Velocity_f", "Pressure_f"),
        "EPressure": lambda s: _zou_he(ctx, s, -1, "pressure",
                                       "Velocity_f", "Pressure_f"),
    })
    f, g = fg[:9], fg[9:]
    dt = f.dtype
    rf = jnp.sum(f, axis=0)
    rg = jnp.sum(g, axis=0)
    ux, uy = _common_u(ctx, f, g)
    ffx, ffy = _sc_force(ctx, "psi_f", "psi_g")
    fgx, fgy = _sc_force(ctx, "psi_g", "psi_f")
    om_f, om_g = ctx.setting("omega"), ctx.setting("omega_g")

    def shifted(u_c, force, om, rho):
        safe = jnp.where(rho > 1e-4, rho, 1.0)
        return jnp.where(rho > 1e-4, u_c + force / (om * safe), u_c)

    uf = (shifted(ux, ffx, om_f, rf), shifted(uy, ffy, om_f, rf))
    ug = (shifted(ux, fgx, om_g, rg), shifted(uy, fgy, om_g, rg))
    fc = f - om_f * (f - lbm.equilibrium(E, W, rf, uf))
    gc = g - om_g * (g - lbm.equilibrium(E, W, rg, ug))
    coll = ctx.nt_in_group("COLLISION")
    ctx.add_global("TotalDensity1", rf, where=coll)
    ctx.add_global("TotalDensity2", rg, where=coll)
    f = jnp.where(coll[None], fc, f)
    g = jnp.where(coll[None], gc, g)
    return ctx.store({"f": f, "g": g})


def init(ctx: NodeCtx) -> jnp.ndarray:
    """Component equilibria from Density/Density_dry; optional double
    shear layer (reference Init, src/d2q9_pp_MCMP/Dynamics.c.Rt:252-289);
    wall nodes start empty."""
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho_f = jnp.broadcast_to(ctx.setting("Density"), shape).astype(dt)
    rho_g = jnp.broadcast_to(ctx.setting("Density_dry"), shape).astype(dt)
    sl_l = ctx.setting("SL_L")
    y = jnp.broadcast_to(
        jnp.arange(shape[0], dtype=dt)[:, None], shape)
    x = jnp.broadcast_to(jnp.arange(shape[1], dtype=dt)[None, :], shape)
    sl_on = sl_l > 0
    safe_l = jnp.where(sl_on, sl_l, 1.0)
    ux_sl = jnp.where(
        y < safe_l / 2,
        ctx.setting("SL_U") * jnp.tanh(
            ctx.setting("SL_lambda") * (y / safe_l - 0.25)),
        ctx.setting("SL_U") * jnp.tanh(
            ctx.setting("SL_lambda") * (0.75 - y / safe_l)))
    uy_sl = (ctx.setting("SL_delta") * ctx.setting("SL_U")
             * jnp.sin(2.0 * jnp.pi * (x / safe_l + 0.25)))
    ux = jnp.where(sl_on, ux_sl, 0.0)
    uy = jnp.where(sl_on, uy_sl, 0.0)
    wall = ctx.nt_is("Wall")
    rho_f = jnp.where(wall, 0.0, rho_f)
    rho_g = jnp.where(wall, 0.0, rho_g)
    f = lbm.equilibrium(E, W, rho_f,
                        (ux + ctx.setting("Velocity_f"), uy))
    g = lbm.equilibrium(E, W, rho_g,
                        (ux + ctx.setting("Velocity_g"), uy))
    return ctx.store({"f": f, "g": g})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    ux, uy = _common_u(ctx, ctx.group("f"), ctx.group("g"))
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_p(ctx: NodeCtx) -> jnp.ndarray:
    """Mixture pressure rho/3 + Gc psi_f psi_g / 3 (reference getP,
    src/d2q9_pp_MCMP/Dynamics.c.Rt:181-188)."""
    rho = jnp.sum(ctx.group("f"), axis=0) + jnp.sum(ctx.group("g"), axis=0)
    return rho / 3.0 + ctx.setting("Gc") * ctx.load("psi_f") \
        * ctx.load("psi_g") / 3.0


def build():
    def _fvec(own, other):
        def q(ctx):
            fx, fy = _sc_force(ctx, own, other)
            return jnp.stack([fx, fy, jnp.zeros_like(fx)])
        return q
    return _def().finalize().bind(
        run=run, init=init,
        stages={"CalcPsi_f": calc_psi_f, "CalcPsi_g": calc_psi_g},
        quantities={
            "Rho": lambda c: jnp.sum(c.group("f"), axis=0)
            + jnp.sum(c.group("g"), axis=0),
            "Rhof": lambda c: jnp.sum(c.group("f"), axis=0),
            "Rhog": lambda c: jnp.sum(c.group("g"), axis=0),
            "P": get_p,
            "U": get_u,
            "Ff": _fvec("psi_f", "psi_g"),
            "Fg": _fvec("psi_g", "psi_f"),
        })
