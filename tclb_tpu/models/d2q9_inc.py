"""d2q9_inc — 2D incompressible-formulation LBM (He & Luo).

Behavioral parity target: reference model ``d2q9_inc``
(reference src/d2q9_inc/Dynamics.R, Dynamics.c.Rt): the equilibrium is
linear in the density deviation with a fixed reference density, removing
the O(Ma^2) compressibility error:
``f_eq = w (rho + rho0 (3 e.u + 4.5 (e.u)^2 - 1.5 u^2))`` with
``u = j / rho0``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
RHO0 = 1.0


def _inc_equilibrium(rho, ux, uy):
    dt = rho.dtype
    usq = ux * ux + uy * uy
    out = []
    for i in range(9):
        eu = float(E[i, 0]) * ux + float(E[i, 1]) * uy
        out.append(jnp.asarray(float(W[i]), dt)
                   * (rho + RHO0 * (3.0 * eu + 4.5 * eu * eu - 1.5 * usq)))
    return jnp.stack(out)


def _def():
    d = family.base_def("d2q9_inc", E, "2D incompressible formulation")
    d.add_node_type("TopSymmetry", "BOUNDARY")
    d.add_node_type("BottomSymmetry", "BOUNDARY")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / RHO0
    uy = lbm.edot(E[:, 1], f) / RHO0
    om = ctx.setting("omega")
    feq = _inc_equilibrium(rho, ux, uy)
    fc = f + om * (feq - f)
    gx, gy = family.gravity_of(ctx)
    fc = fc + (_inc_equilibrium(rho, ux + gx, uy + gy) - feq)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None], fc, f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(ctx.setting("Density"), shape).astype(dt)
    ux = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    return ctx.store({"f": _inc_equilibrium(rho, ux, jnp.zeros(shape, dt))})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    dt = f.dtype
    ux = lbm.edot(E[:, 0], f) / RHO0
    uy = lbm.edot(E[:, 1], f) / RHO0
    gx, gy = family.gravity_of(ctx)
    return jnp.stack([ux + 0.5 * gx, uy + 0.5 * gy, jnp.zeros_like(ux)])


def build():
    q = family.make_getters(E)
    q["U"] = get_u
    return _def().finalize().bind(run=run, init=init, quantities=q)
