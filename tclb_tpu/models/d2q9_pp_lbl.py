"""d2q9_pp_LBL — pseudopotential multiphase, Lycett-Brown & Luo forcing.

Behavioral parity target: reference model ``d2q9_pp_LBL``
(reference src/d2q9_pp_LBL/Dynamics.R, Dynamics.c.Rt — "Improved forcing
scheme in pseudopotential lattice Boltzmann methods for multiphase flow at
arbitrarily high density ratios", maintained by T. Mitchell).  Two-stage
iteration like the kuper family: ``calcPsi`` computes the pseudopotential
``psi = sqrt(2 (p0 - rho/3)/(G/3))`` from the Carnahan–Starling EoS
(Dynamics.c.Rt:217-224), then ``Run`` applies boundary conditions and a BGK
collision with the LBL third-order-corrected Guo-style forcing
(Dynamics.c.Rt:350-396: the ``gamma`` coefficient
``1 - omega/4 - rho omega/(4 G cs2 psi^2)`` restores mechanical stability at
high density ratio).  The Shan–Chen force is
``F = -G psi(0) sum_i w_i psi(x+e_i) e_i`` (Dynamics.c.Rt:203-212; the
templated symmetry adjustments of the R-section are dead code there — the
python section regenerates R[] before use — and are not reproduced).

Note the reference collides with ``tempomega`` (default 1), not ``omega``
(its own comment: "omega seems to get overwritten in preamble??"); we keep
both settings with the same semantics for config parity.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, _zou_he_x, _symmetry
from tclb_tpu.ops import lbm

W = lbm.weights(E)
OPP = lbm.opposite(E)
CS2 = 1.0 / 3.0


def _def() -> ModelDef:
    d = ModelDef("d2q9_pp_LBL", ndim=2,
                 description="pseudopotential multiphase (Lycett-Brown/Luo "
                             "forcing, Carnahan-Starling EoS)")
    d.add_densities("f", E)
    d.add_field("psi", dx=(-1, 1), dy=(-1, 1))
    d.add_stage("BaseIteration", "Run")
    d.add_stage("calcPsi", "calcPsi")
    d.add_stage("BaseInit", "Init", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "calcPsi"))
    d.add_action("Init", ("BaseInit", "calcPsi"))
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("F", unit="N", vector=True)
    d.add_quantity("P", unit="Pa")
    d.add_quantity("Psi", unit="1")
    d.add_setting("G", default=-1.0, comment="interaction strength")
    d.add_setting("T", default=0.0585, comment="effective temperature")
    d.add_setting("alpha", default=0.25, comment="CS EoS parameter")
    d.add_setting("R", default=0.25, comment="CS EoS parameter")
    d.add_setting("beta", default=1.0, comment="CS EoS parameter")
    d.add_setting("kappa", default=0.0, comment="surface tension parameter")
    d.add_setting("eps_0", default=2.0, comment="mechanical stability coef")
    d.add_setting("betaforcing", default=1.0, comment="beta forcing scheme")
    d.add_setting("omega", comment="one over relaxation time")
    d.add_setting("tempomega", default=1.0,
                  comment="relaxation rate the reference actually collides "
                          "with (src/d2q9_pp_LBL/Dynamics.c.Rt:352)")
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("Velocity", default=0.0, zonal=True)
    d.add_setting("VelocityY", default=0.0, zonal=True)
    d.add_setting("Density", default=1.0, zonal=True)
    d.add_setting("GravitationY")
    d.add_setting("GravitationX")
    for i, dflt in enumerate([0, 0, 0, -1 / 3, 0, 0, 0, 0, 0]):
        d.add_setting(f"S{i}", default=dflt, comment="MRT rate (unused in "
                      "the BGK path, kept for config parity)")
    d.add_global("PressureLoss", unit="1mPa")
    d.add_global("OutletFlux", unit="1m2/s")
    d.add_global("InletFlux", unit="1m2/s")
    d.add_node_type("BottomSymmetry", "BOUNDARY")
    d.add_node_type("TopSymmetry", "BOUNDARY")
    # declared for config parity; the reference's Run() switch never
    # dispatches SymmetryRight (its handler exists but is unreachable,
    # src/d2q9_pp_LBL/Dynamics.c.Rt:70-99,287-300) — same here
    d.add_node_type("RightSymmetry", "BOUNDARY")
    return d


def _cs_pressure(ctx: NodeCtx, rho):
    """Carnahan–Starling EoS (reference getP,
    src/d2q9_pp_LBL/Dynamics.c.Rt:146-153)."""
    bp = rho * ctx.setting("beta") / 4.0
    p0 = (rho * ctx.setting("R") * ctx.setting("T")
          * (1.0 + bp + bp * bp - bp ** 3) / (1.0 - bp) ** 3
          - ctx.setting("alpha") * rho * rho)
    return p0


def calc_psi(ctx: NodeCtx):
    """psi = sqrt(2 (p0 - rho/3)/(G/3)) (reference calcPsi,
    src/d2q9_pp_LBL/Dynamics.c.Rt:217-224).  For attractive G < 0 the
    argument is non-negative wherever the EoS is below ideal; clamped at 0
    against round-off (the reference lets sqrt produce NaN there)."""
    f = ctx.group("f")
    rho = jnp.sum(f, axis=0)
    p0 = _cs_pressure(ctx, rho)
    arg = 2.0 * (p0 - rho / 3.0) / (ctx.setting("G") / 3.0)
    return {"psi": jnp.sqrt(jnp.maximum(arg, 0.0))}


def _force(ctx: NodeCtx, rho):
    """Shan–Chen force + gravity (reference PPForce/getF,
    src/d2q9_pp_LBL/Dynamics.c.Rt:138-216)."""
    psi0 = ctx.load("psi")
    fx = sum(float(W[i] * E[i, 0])
             * ctx.load("psi", int(E[i, 0]), int(E[i, 1]))
             for i in range(1, 9) if E[i, 0])
    fy = sum(float(W[i] * E[i, 1])
             * ctx.load("psi", int(E[i, 0]), int(E[i, 1]))
             for i in range(1, 9) if E[i, 1])
    g = ctx.setting("G")
    return (-g * psi0 * fx + ctx.setting("GravitationX") * rho,
            -g * psi0 * fy + ctx.setting("GravitationY") * rho)


def _collision_bgk(ctx: NodeCtx, f):
    """BGK collision with the LBL forcing source term (reference
    CollisionBGK, src/d2q9_pp_LBL/Dynamics.c.Rt:350-396; the 'Excel
    generated' S block is the live one — it overwrites the sympy S)."""
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho
    fx, fy = _force(ctx, rho)
    om = ctx.setting("tempomega")
    g = ctx.setting("G")
    psi0 = ctx.load("psi")
    psi_safe = jnp.where(jnp.abs(psi0) > 1e-30, psi0, 1e-30)
    gamma = 1.0 - 0.25 * om - rho * om / (4.0 * g * CS2
                                          * psi_safe * psi_safe)
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    out = []
    ff = fx * fx + fy * fy
    for i in range(9):
        ex, ey = float(E[i, 0]), float(E[i, 1])
        eu = ex * ux + ey * uy
        ef = ex * fx + ey * fy
        s = float(W[i]) * ((ex - ux + ex * eu / CS2) * fx
                           + (ey - uy + ey * eu / CS2) * fy
                           + (gamma / (2.0 * rho)) * (ef * ef / CS2 - ff)
                           ) / CS2
        out.append(f[i] - om * (f[i] - feq[i]) + s)
    return jnp.stack(out)


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    vel = ctx.setting("Velocity")
    den = ctx.setting("Density")

    def _wvel_eq(f):
        # reference WVelocity is an equilibrium inlet: SetEquilibrium with
        # the zonal Density and Velocity (Dynamics.c.Rt:258-263)
        shape = f.shape[1:]
        rho = jnp.broadcast_to(den, shape).astype(f.dtype)
        ux = jnp.broadcast_to(vel, shape).astype(f.dtype)
        return lbm.equilibrium(E, W, rho, (ux, jnp.zeros(shape, f.dtype)))

    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "EVelocity": lambda f: _zou_he_x(f, vel, "velocity", "E"),
        "WPressure": lambda f: _zou_he_x(f, den, "pressure", "W"),
        "WVelocity": _wvel_eq,
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
        "TopSymmetry": lambda f: _symmetry(f, top=True),
        "BottomSymmetry": lambda f: _symmetry(f, top=False),
    })
    f = jnp.where(ctx.nt_in_group("COLLISION")[None],
                  _collision_bgk(ctx, f), f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.broadcast_to(ctx.setting("Density"), shape).astype(dt)
    ux = jnp.broadcast_to(ctx.setting("Velocity"), shape).astype(dt)
    uy = jnp.broadcast_to(ctx.setting("VelocityY"), shape).astype(dt)
    return ctx.store({"f": lbm.equilibrium(E, W, rho, (ux, uy))})


def get_u(ctx: NodeCtx) -> jnp.ndarray:
    """Velocity including the half-force shift (reference getU,
    src/d2q9_pp_LBL/Dynamics.c.Rt:124-137)."""
    f = ctx.group("f")
    dt = f.dtype
    rho = jnp.sum(f, axis=0)
    fx, fy = _force(ctx, rho)
    ux = (lbm.edot(E[:, 0], f) + 0.5 * fx) / rho
    uy = (lbm.edot(E[:, 1], f) + 0.5 * fy) / rho
    return jnp.stack([ux, uy, jnp.zeros_like(ux)])


def get_f(ctx: NodeCtx) -> jnp.ndarray:
    rho = jnp.sum(ctx.group("f"), axis=0)
    fx, fy = _force(ctx, rho)
    return jnp.stack([fx, fy, jnp.zeros_like(fx)])


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities={
            "Rho": lambda c: jnp.sum(c.group("f"), axis=0),
            "U": get_u,
            "F": get_f,
            "P": lambda c: _cs_pressure(c, jnp.sum(c.group("f"), axis=0)),
            "Psi": lambda c: c.load("psi"),
        },
        stages={"calcPsi": calc_psi})
