"""d3q27 — 3D 27-velocity central-moment (cascaded) MRT.

Behavioral parity target: reference model ``d3q27``
(reference src/d3q27/Dynamics.R, Dynamics.c.Rt): 27-velocity
multiple-relaxation collision.  Realized as the cascaded central-moment
operator (ops/cumulant.py with ``correlated=False``: higher moments project
onto the factorized Gaussian equilibrium), which is the modern form of a
d3q27 MRT.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.models import family
from tclb_tpu.ops import cumulant, lbm

E = cumulant.velocity_set(3)
W = lbm.weights(E)
OPP = lbm.opposite(E)
CORRELATED = False


def _def():
    d = family.base_def("d3q27", E, "3D central-moment (cascaded) MRT",
                        faces="WE", symmetries="NS")
    d.add_setting("omega_bulk", default=1.0,
                  comment="bulk (trace) relaxation rate")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    f = family.apply_boundaries(ctx, f, E, W, OPP)
    family.add_flux_objectives(ctx, f, E)
    shape = f.shape[1:]
    F = f.reshape((3, 3, 3) + shape)
    Fp, _, _ = cumulant.collide_d3q27(
        F, ctx.setting("omega"), ctx.setting("omega_bulk"),
        force=family.gravity_of(ctx), correlated=CORRELATED)
    f = jnp.where(ctx.nt_in_group("COLLISION")[None],
                  Fp.reshape((27,) + shape), f)
    return ctx.store({"f": f})


def init(ctx: NodeCtx) -> jnp.ndarray:
    return family.standard_init(ctx, E, W)


def build():
    return _def().finalize().bind(
        run=run, init=init,
        quantities=family.make_getters(E, force_of=family.gravity_of))
