"""d2q9_poison_boltzmann — nonlinear Poisson–Boltzmann potential solver.

Behavioral parity target: reference model ``d2q9_poison_boltzmann``
(reference src/d2q9_poison_boltzmann/Dynamics.R, Dynamics.c.Rt).  A single
``g`` population iterates Guo's Poisson LBM to a fixed point of
``epsilon lap(psi) = -rho_e(psi)`` with the full nonlinear charge density
``rho_e = -2 n_inf z el sinh(z el/(kb T) psi)`` (getrho_e :39-43).
Equilibrium ``wp_i psi`` with ``wp = (1/9 - 1, 1/9 ...)``, source
``dt wps RD``, ``RD = -(2/3)(1/2 - tau_psi) dt rho_e / epsilon``
(:16-23,96-108).  Walls impose a Dirichlet zeta potential
``g_i = wp_i psi_bc`` (:44-66).  The ``subiter`` plane counts fixed-point
sweeps (CalcSubiter :110-113) — the reference drives convergence through
repeated <Solve> iterations, and so do we.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.ops import lbm
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E
from tclb_tpu.models.guo_poisson import WP, \
    psi_of as _psi_of, collide as _guo_collide


def _def() -> ModelDef:
    d = ModelDef("d2q9_poison_boltzmann", ndim=2,
                 description="nonlinear Poisson-Boltzmann solver")
    d.add_densities("g", E)
    d.add_density("subiter")
    d.add_field("psi", dx=(-1, 1), dy=(-1, 1))
    d.add_quantity("Psi")
    d.add_quantity("Subiter")
    d.add_quantity("rho_e", unit="kg/m3")
    d.add_stage("BaseIteration", "Run")
    d.add_stage("CalcPsi", "CalcPsi")
    d.add_stage("CalcSubiter", "CalcSubiter", load_densities=False)
    d.add_stage("BaseInit", "Init", load_densities=False)
    d.add_action("Iteration", ("BaseIteration", "CalcPsi", "CalcSubiter"))
    d.add_action("Init", ("BaseInit", "CalcPsi"))
    d.add_setting("tau_psi", default=1.0)
    d.add_setting("n_inf", default=1.0)
    d.add_setting("z", default=1.0)
    d.add_setting("el", default=1.0)
    d.add_setting("kb", default=1.0)
    d.add_setting("T", default=1.0)
    d.add_setting("epsilon", default=1.0)
    d.add_setting("dt", default=1.0)
    d.add_setting("psi_bc", default=1.0, zonal=True,
                  comment="zeta potential at walls")
    d.add_setting("psi0", default=1.0, zonal=True)
    return d


def _rho_e(ctx: NodeCtx, psi):
    z = ctx.setting("z")
    return -2.0 * ctx.setting("n_inf") * z * ctx.setting("el") \
        * jnp.sinh(z * ctx.setting("el") / ctx.setting("kb")
                   / ctx.setting("T") * psi)


def run(ctx: NodeCtx) -> jnp.ndarray:
    g = ctx.group("g")
    dt_ = g.dtype
    g = ctx.boundary_case(g, {
        ("Wall", "Solid"): lambda g: lbm.wstack(
            WP, jnp.broadcast_to(ctx.setting("psi_bc"),
                                 g.shape[1:]).astype(dt_)),
    })
    psi = _psi_of(g)
    rho_e = _rho_e(ctx, psi)
    gc = _guo_collide(g, psi, rho_e, ctx.setting("tau_psi"),
                      ctx.setting("dt"), ctx.setting("epsilon"))
    g = jnp.where(ctx.nt_in_group("COLLISION")[None], gc, g)
    return ctx.store({"g": g})


def calc_psi(ctx: NodeCtx):
    return {"psi": _psi_of(ctx.group("g"))}


def calc_subiter(ctx: NodeCtx):
    return {"subiter": ctx.density("subiter") + 1.0}


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt_ = ctx._fields.dtype
    psi0 = jnp.broadcast_to(ctx.setting("psi0"), shape).astype(dt_)
    g = lbm.wstack(WP, psi0)
    return ctx.store({"g": g, "subiter": jnp.zeros(shape, dt_)})


def build():
    return _def().finalize().bind(
        run=run, init=init,
        stages={"CalcPsi": calc_psi, "CalcSubiter": calc_subiter},
        quantities={
            "Psi": lambda c: _psi_of(c.group("g")),
            "Subiter": lambda c: c.density("subiter"),
            "rho_e": lambda c: _rho_e(c, _psi_of(c.group("g"))),
        })
