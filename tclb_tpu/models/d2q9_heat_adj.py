"""d2q9_heat_adj — conjugate heat-transfer topology optimization.

Behavioral parity target: reference model ``d2q9_heat_adj``
(reference src/d2q9_heat_adj/Dynamics.R, Dynamics.c.Rt, ADJOINT=1 — the
example/heat_adj.xml benchmark): flow + temperature with a design field
``w``: Brinkman velocity penalization (fluid where w=1) and
w-interpolated thermal diffusivity between ``FluidAlfa`` and ``SolidAlfa``;
objectives HeatFlux / HeatSource / Material for heat-exchanger design.
"""

from __future__ import annotations

import jax.numpy as jnp

from tclb_tpu.core.lattice import NodeCtx
from tclb_tpu.core.registry import ModelDef
from tclb_tpu.models.d2q9 import E, OPP, _zou_he_x
from tclb_tpu.models.d2q9_heat import _t_eq
from tclb_tpu.ops import lbm

W = lbm.weights(E)


def _def() -> ModelDef:
    d = ModelDef("d2q9_heat_adj", ndim=2,
                 description="conjugate heat topology optimization")
    d.add_densities("f", E)
    d.add_densities("T", E, group="T")
    d.add_density("w", group="w", parameter=True)
    d.add_quantity("Rho", unit="kg/m3")
    d.add_quantity("T", unit="K")
    d.add_quantity("U", unit="m/s", vector=True)
    d.add_quantity("W")
    d.add_quantity("TB", adjoint=True)
    d.add_quantity("WB", adjoint=True)
    d.add_setting("omega", default=1.0)
    d.add_setting("nu", default=1 / 6,
                  derived={"omega": lambda nu: 1.0 / (3 * nu + 0.5)})
    d.add_setting("InletVelocity")
    d.add_setting("InletTemperature", default=1.0)
    d.add_setting("InitTemperature", default=1.0)
    d.add_setting("InletDensity", default=1.0)
    d.add_setting("FluidAlfa", default=0.1)
    d.add_setting("SolidAlfa", default=0.01)
    d.add_setting("HeatSource", default=0.0,
                  comment="volumetric heating of solid (1-w)")
    d.add_setting("Porocity", default=0.0, zonal=True)
    d.add_global("HeatFlux")
    d.add_global("HeatSourceTotal")
    d.add_global("Material")
    d.add_global("Drag")
    return d


def run(ctx: NodeCtx) -> jnp.ndarray:
    f = ctx.group("f")
    fT = ctx.group("T")
    w = ctx.density("w")
    dt = f.dtype
    vel = ctx.setting("InletVelocity")
    den = ctx.setting("InletDensity")
    t_in = ctx.setting("InletTemperature")

    f = ctx.boundary_case(f, {
        ("Wall", "Solid"): lambda f: lbm.perm(f, OPP),
        "WVelocity": lambda f: _zou_he_x(f, vel, "velocity", "W"),
        "EPressure": lambda f: _zou_he_x(f, den, "pressure", "E"),
    })
    fT = ctx.boundary_case(fT, {
        ("Wall", "Solid"): lambda t: lbm.perm(t, OPP),
        "WVelocity": lambda t: _t_eq(
            jnp.broadcast_to(t_in, t.shape[1:]).astype(dt),
            jnp.zeros(t.shape[1:], dt), jnp.zeros(t.shape[1:], dt)),
    })

    rho = jnp.sum(f, axis=0)
    ux = lbm.edot(E[:, 0], f) / rho
    uy = lbm.edot(E[:, 1], f) / rho

    om = ctx.setting("omega")
    feq = lbm.equilibrium(E, W, rho, (ux, uy))
    # Brinkman penalization: velocity scaled by w (solid where w -> 0)
    ctx.add_global("Drag", (1.0 - w) * jnp.abs(ux),
                   where=ctx.nt_in_group("COLLISION"))
    ux2, uy2 = ux * w, uy * w
    fc = f + om * (feq - f) \
        + (lbm.equilibrium(E, W, rho, (ux2, uy2)) - feq)

    temp = jnp.sum(fT, axis=0)
    alfa = ctx.setting("FluidAlfa") * w + ctx.setting("SolidAlfa") * (1.0 - w)
    om_t = 1.0 / (3.0 * alfa + 0.5)
    src = ctx.setting("HeatSource") * (1.0 - w)
    tc = fT + om_t[None] * (_t_eq(temp, ux2, uy2) - fT) \
        + _t_eq(src, jnp.zeros_like(ux), jnp.zeros_like(uy))
    coll = ctx.nt_in_group("COLLISION")[None]
    f = jnp.where(coll, fc, f)
    fT = jnp.where(coll, tc, fT)

    ctx.add_global("HeatFlux", temp * ux2, where=ctx.nt_is("Outlet"))
    ctx.add_global("HeatSourceTotal", src,
                   where=ctx.nt_in_group("COLLISION"))
    ctx.add_global("Material", 1.0 - w,
                   where=ctx.nt_in_group("DESIGNSPACE"))
    return ctx.store({"f": f, "T": fT})


def init(ctx: NodeCtx) -> jnp.ndarray:
    shape = ctx.flags.shape
    dt = ctx._fields.dtype
    rho = jnp.ones(shape, dt)
    ux = jnp.broadcast_to(ctx.setting("InletVelocity"), shape).astype(dt)
    f = lbm.equilibrium(E, W, rho, (ux, jnp.zeros(shape, dt)))
    t0 = jnp.broadcast_to(ctx.setting("InitTemperature"), shape).astype(dt)
    fT = _t_eq(t0, jnp.zeros(shape, dt), jnp.zeros(shape, dt))
    w = 1.0 - jnp.broadcast_to(ctx.setting("Porocity"), shape).astype(dt)
    w = jnp.where(ctx.nt_is("Solid"), jnp.zeros_like(w), w)
    return ctx.store({"f": f, "T": fT, "w": w[None]})


def build():
    tq = lambda c: jnp.sum(c.group("T"), axis=0)    # noqa: E731
    wq = lambda c: c.density("w")                   # noqa: E731
    from tclb_tpu.models.d2q9_heat import get_rho, get_u
    return _def().finalize().bind(
        run=run, init=init,
        quantities={"Rho": get_rho, "T": tq, "U": get_u, "W": wq,
                    "TB": tq, "WB": wq})
