"""Synthetic inflow turbulence: divergence-free random Fourier modes with a
von Kármán spectrum.

Parity target: reference ``SyntheticTurbulence`` (src/SyntheticTurbulence.h
:20-108, src/SyntheticTurbulence.cpp, 133 LoC) and its per-node evaluator
``calc()``: each mode carries a unit wavevector ``k``, an amplitude vector
``a`` orthogonal to ``k`` (so the field is divergence-free), and a
wavenumber ``w``; the fluctuation at ``x`` is
``sum_j sin(w k.x) a + cos(w k.x) (k x a)``.

The reference regenerates the random mode set on the host EVERY iteration
and smooths per node with an AR(1) factor ``k_aa = exp(-1/TimeWN)``
(src/d3q27_cumulant/Dynamics.c.Rt:210-222).  The TPU build regenerates per
handler segment (between callback events) instead — host work stays out of
the compiled scan — and applies the variance-exact n-step AR(1) update
``S' = k_aa^n S + sqrt(1 - k_aa^(2n)) u``, which has the same stationary
variance and correlation time; the fluctuation is piecewise-constant
within a segment (documented deviation).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

# von Karman spectrum constant (reference SyntheticTurbulence.cpp:104)
_VK_C = 0.9685081


class SyntheticTurbulence:
    """Host-side spectrum + mode generator (reference class of the same
    name).  Wavenumbers/amplitudes are set once by :meth:`set_von_karman`
    or :meth:`set_one_wave`; :meth:`generate` draws fresh random
    directions; :meth:`evaluate` renders the fluctuation field."""

    def __init__(self, seed: int = 0):
        self.wavenumbers = np.zeros(0)
        self.amplitudes = np.zeros(0)
        self.time_wn = 0.0
        self.energy_fraction = 0.0
        self.rng = np.random.default_rng(seed)

    @property
    def nmodes(self) -> int:
        return len(self.wavenumbers)

    def set_von_karman(self, main_wn: float, diff_wn: float,
                       min_wn: float, max_wn: float, nmodes: int = 100
                       ) -> float:
        """Even spread of ``nmodes`` wavenumbers over [min_wn, max_wn] with
        von Kármán amplitudes (reference setVonKarman,
        src/SyntheticTurbulence.cpp:96-118).  Returns the resolved energy
        fraction (the reference warns below 70/80%)."""
        dl = (max_wn - min_wn) / nmodes
        wn = min_wn + dl * (np.arange(nmodes) + 0.5)
        le, ld = main_wn, diff_wn
        e = (_VK_C / le * (wn / le) ** 4
             / (1.0 + (wn / le) ** 2) ** (17.0 / 6.0)
             * np.exp(-2.0 * (wn / ld) ** 2))
        self.wavenumbers = wn
        self.amplitudes = np.sqrt(e * dl)
        self.energy_fraction = float((self.amplitudes ** 2).sum())
        return self.energy_fraction

    def set_one_wave(self, wn: float) -> None:
        self.wavenumbers = np.array([wn])
        self.amplitudes = np.array([1.0])
        self.energy_fraction = 1.0

    def set_time_scale(self, time_wn: float) -> None:
        self.time_wn = float(time_wn)

    def ar1_factor(self, steps: int = 1) -> float:
        """AR(1) memory over ``steps`` iterations: k_aa^steps with
        ``k_aa = exp(-1/TimeWN)`` (reference WVelocityTurbulent)."""
        if self.time_wn <= 0:
            return 0.0
        return math.exp(-steps / self.time_wn)

    def generate(self) -> np.ndarray:
        """Fresh random mode set: rows (kx,ky,kz, ax,ay,az, wn) — the
        reference's Generate() (src/SyntheticTurbulence.cpp:47-68): k is a
        random unit vector, a is a random Gaussian vector orthogonalized
        against k and scaled to the mode amplitude."""
        n = self.nmodes
        k = self.rng.normal(size=(n, 3))
        k /= np.linalg.norm(k, axis=1, keepdims=True)
        a = self.rng.normal(size=(n, 3))
        a -= k * (a * k).sum(axis=1, keepdims=True)
        norm = np.linalg.norm(a, axis=1, keepdims=True)
        norm[norm == 0] = 1.0
        a *= self.amplitudes[:, None] / norm
        return np.concatenate([k, a, self.wavenumbers[:, None]], axis=1)

    def evaluate(self, shape, modes: Optional[np.ndarray] = None
                 ) -> np.ndarray:
        """Fluctuation velocity field over a lattice of ``shape`` (index
        order z,y,x / y,x): (3, *shape) with components (ux, uy, uz) —
        the reference device evaluator ``calc()``
        (src/SyntheticTurbulence.h:90-108)."""
        if modes is None:
            modes = self.generate()
        shape = tuple(int(s) for s in shape)
        grids = np.meshgrid(*[np.arange(s, dtype=np.float64)
                              for s in shape], indexing="ij")
        # physical coords (x, y, z) from index order (..., y, x)
        coords = [grids[-1], grids[-2] if len(shape) > 1 else 0.0,
                  grids[-3] if len(shape) > 2 else 0.0]
        out = np.zeros((3,) + shape)
        for k1, k2, k3, a1, a2, a3, wn in modes:
            w = (k1 * coords[0] + k2 * coords[1] + k3 * coords[2]) * wn
            sw, cw = np.sin(w), np.cos(w)
            out[0] += sw * a1 + cw * (k2 * a3 - k3 * a2)
            out[1] += sw * a2 + cw * (k3 * a1 - k1 * a3)
            out[2] += sw * a3 + cw * (k1 * a2 - k2 * a1)
        return out
