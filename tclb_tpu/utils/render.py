"""In-situ frame rendering — the TPU-native stand-in for the reference's
two visualization side stacks:

* ParaView Catalyst co-processing (reference src/Catalyst.cpp.Rt:33-80,
  cbCatalyst handler src/Handlers.cpp.Rt:898-1006): per-interval in-situ
  images of selected quantities without writing full VTI dumps;
* the GLUT GUI (reference src/gpu_anim.h + per-model ``Color()``,
  src/LatticeContainer.inc.cpp.Rt:414-461): live coloring of the field.

A TPU pod has no display and no ParaView server; the honest re-design is
an offline frame stream: each callback renders a quantity slice through a
colormap to a PNG (pure stdlib zlib encoder — no imaging dependency), so
a run directory accumulates ``<case>_<quantity>_<iter>.png`` frames that
play back as the reference's GUI animation would.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

# compact viridis-like colormap (8 anchor colors, interpolated)
_ANCHORS = np.array([
    (68, 1, 84), (70, 50, 127), (54, 92, 141), (39, 127, 142),
    (31, 161, 135), (74, 194, 109), (159, 218, 58), (253, 231, 37),
], dtype=np.float64)


def colormap(x: np.ndarray) -> np.ndarray:
    """Map [0,1] floats to (…, 3) uint8 RGB through the anchor table."""
    x = np.clip(np.nan_to_num(x, nan=0.0), 0.0, 1.0)
    pos = x * (len(_ANCHORS) - 1)
    i = np.clip(pos.astype(np.int64), 0, len(_ANCHORS) - 2)
    frac = (pos - i)[..., None]
    rgb = _ANCHORS[i] * (1.0 - frac) + _ANCHORS[i + 1] * frac
    return rgb.astype(np.uint8)


def write_png(path: str, rgb: np.ndarray) -> str:
    """Minimal PNG encoder (8-bit RGB, zlib stdlib only)."""
    h, w, _ = rgb.shape
    raw = b"".join(b"\x00" + rgb[row].tobytes() for row in range(h))

    def chunk(tag: bytes, data: bytes) -> bytes:
        return (struct.pack(">I", len(data)) + tag + data
                + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    png = (b"\x89PNG\r\n\x1a\n"
           + chunk(b"IHDR", ihdr)
           + chunk(b"IDAT", zlib.compress(raw, 6))
           + chunk(b"IEND", b""))
    with open(path, "wb") as f:
        f.write(png)
    return path


def downsample(plane: np.ndarray, max_dim: int = 32) -> np.ndarray:
    """Stride-sampled coarse view of a 2D plane, at most ``max_dim``
    points per axis — the kilobyte-sized in-situ extract a worker
    streams over its job progress channel instead of a full-field dump
    (the relay analogue of Catalyst's downsampled co-processing view)."""
    plane = np.asarray(plane)
    if plane.ndim != 2:
        raise ValueError(
            f"downsample expects a 2D plane, got shape {plane.shape}")
    max_dim = max(1, int(max_dim))
    sy = max(1, -(-plane.shape[0] // max_dim))
    sx = max(1, -(-plane.shape[1] // max_dim))
    return plane[::sy, ::sx]


def render_frame(path: str, plane: np.ndarray,
                 vmin=None, vmax=None) -> str:
    """Render a 2D scalar plane to a PNG (row 0 at the bottom, like the
    reference GUI's lattice orientation)."""
    plane = np.asarray(plane, dtype=np.float64)
    lo = float(np.nanmin(plane)) if vmin is None else float(vmin)
    hi = float(np.nanmax(plane)) if vmax is None else float(vmax)
    span = hi - lo if hi > lo else 1.0
    rgb = colormap((plane - lo) / span)
    return write_png(path, rgb[::-1])
