"""VTI (VTK ImageData) + CSV output writers.

Parity target: the reference's parallel VTI writer (reference
src/vtkLattice.cpp.Rt:17-75, src/vtkOutput.cpp) which emits a .pvti master +
per-rank .vti pieces with appended raw binary data, per-Quantity arrays and
node-type-group flag layers, and the CSV ``Log`` fan-out
(src/Solver.cpp.Rt:120-206).

Here quantities are computed on-device over the (sharded) lattice and
fetched once; files are written with the "appended" raw encoding the
reference uses (base64 would bloat; raw is what VTK tools read fastest).
A single .vti plus a .pvti master referencing it keeps tool compatibility
with the reference's output convention.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable

import numpy as np


def _vtk_type(a: np.ndarray) -> str:
    return {
        np.dtype(np.float32): "Float32", np.dtype(np.float64): "Float64",
        np.dtype(np.uint16): "UInt16", np.dtype(np.uint8): "UInt8",
        np.dtype(np.int32): "Int32", np.dtype(np.uint32): "UInt32",
    }[a.dtype]


def write_vti(path: str, arrays: dict[str, np.ndarray],
              spacing: float = 1.0, origin=(0.0, 0.0, 0.0),
              compress: bool = False) -> str:
    """Write point-data arrays on a uniform grid to ``path``.vti.

    Every array is (nz, ny, nx) scalar or (3, nz, ny, nx) vector — 2D inputs
    get a unit z axis.  Appended raw-binary encoding (reference vtkOutput's
    appended data blocks, src/vtkOutput.cpp); ``compress=True`` switches the
    blocks to vtkZLibDataCompressor layout (native C++ encoder in
    tclb_tpu/native when available) — every VTK reader understands it and
    large fields shrink ~3x.
    """
    norm: dict[str, np.ndarray] = {}
    shape = None
    for name, a in arrays.items():
        a = np.asarray(a)
        if a.ndim == 2:
            a = a[None]                      # (1, ny, nx)
        elif a.ndim == 3 and a.shape[0] == 3 and len(arrays) and any(
                np.asarray(v).ndim == 2 for v in arrays.values()):
            a = a[:, None]                   # vector on 2D grid
        norm[name] = a
        s = a.shape[-3:]
        if shape is None:
            shape = s
        elif s != shape:
            raise ValueError(f"array {name}: shape {s} != {shape}")
    nz, ny, nx = shape
    extent = f"0 {nx} 0 {ny} 0 {nz}"

    # cell data: VTK extent counts points; our lattice nodes are cells
    comp_attr = ' compressor="vtkZLibDataCompressor"' if compress else ""
    head = [
        '<?xml version="1.0"?>',
        '<VTKFile type="ImageData" version="0.1" '
        f'byte_order="LittleEndian" header_type="UInt32"{comp_attr}>',
        f'<ImageData WholeExtent="{extent}" Origin="{origin[0]} {origin[1]} '
        f'{origin[2]}" Spacing="{spacing} {spacing} {spacing}">',
        f'<Piece Extent="{extent}">',
        "<CellData>",
    ]
    offset = 0
    blocks: list[bytes] = []
    for name, a in norm.items():
        ncomp = a.shape[0] if a.ndim == 4 else 1
        if a.ndim == 4:
            flat = np.ascontiguousarray(np.moveaxis(a, 0, -1))
        else:
            flat = np.ascontiguousarray(a)
        raw = flat.tobytes()
        head.append(
            f'<DataArray type="{_vtk_type(a)}" Name="{name}" '
            f'NumberOfComponents="{ncomp}" format="appended" '
            f'offset="{offset}"/>')
        if compress:
            from tclb_tpu.native import zlib_blocks
            blocks.append(zlib_blocks(raw))
        else:
            blocks.append(struct.pack("<I", len(raw)) + raw)
        offset += len(blocks[-1])
    head += ["</CellData>", "</Piece>", "</ImageData>",
             '<AppendedData encoding="raw">']
    if not path.endswith(".vti"):
        path += ".vti"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write("\n".join(head).encode())
        f.write(b"\n_")
        for b in blocks:
            f.write(b)
        f.write(b"\n</AppendedData>\n</VTKFile>\n")
    return path


def write_pvti(path: str, piece: str, arrays: dict[str, np.ndarray],
               spacing: float = 1.0) -> str:
    """Master file referencing the piece (reference rank-0 .pvti,
    src/vtkOutput.cpp)."""
    sample = next(iter(arrays.values()))
    a = np.asarray(sample)
    if a.ndim == 2:
        nz, (ny, nx) = 1, a.shape
    else:
        nz, ny, nx = a.shape[-3:]
    extent = f"0 {nx} 0 {ny} 0 {nz}"
    lines = [
        '<?xml version="1.0"?>',
        '<VTKFile type="PImageData" version="0.1" '
        'byte_order="LittleEndian">',
        f'<PImageData WholeExtent="{extent}" GhostLevel="0" '
        f'Origin="0 0 0" Spacing="{spacing} {spacing} {spacing}">',
        "<PCellData>",
    ]
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        ncomp = 3 if (arr.ndim == 3 and arr.shape[0] == 3 and a.ndim == 2) \
            or arr.ndim == 4 else 1
        lines.append(f'<PDataArray type="{_vtk_type(arr)}" Name="{name}" '
                     f'NumberOfComponents="{ncomp}"/>')
    lines += ["</PCellData>",
              f'<Piece Extent="{extent}" Source="{os.path.basename(piece)}"/>',
              "</PImageData>", "</VTKFile>"]
    if not path.endswith(".pvti"):
        path += ".pvti"
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


class CSVLog:
    """The reference's CSV ``Log``: one row per callback with iteration,
    SI time, walltime, settings (lattice+SI), zonal settings per zone,
    globals (lattice+SI) and unit scales (reference initLog/writeLog,
    src/Solver.cpp.Rt:120-206)."""

    def __init__(self, path: str):
        self.path = path
        self._header: list[str] | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def write(self, row: dict[str, float]) -> None:
        if self._header is None:
            self._header = list(row.keys())
            with open(self.path, "w") as f:
                f.write(",".join(f'"{h}"' for h in self._header) + "\n")
        with open(self.path, "a") as f:
            f.write(",".join(repr(float(row.get(h, 0.0)))
                             for h in self._header) + "\n")


def csvdiff(a: str, b: str, tol: float = 1e-10,
            skip: Iterable[str] = ("Walltime",)) -> list[str]:
    """Compare two CSV logs with numeric tolerance, discarding volatile
    columns (the reference's golden-test comparator, tools/csvdiff:40-50).
    Returns a list of mismatch descriptions (empty = match)."""
    import csv

    def load(p):
        with open(p) as f:
            r = list(csv.reader(f))
        return r[0], r[1:]

    ha, ra = load(a)
    hb, rb = load(b)
    errs = []
    if ha != hb:
        errs.append(f"headers differ: {ha} vs {hb}")
        return errs
    if len(ra) != len(rb):
        errs.append(f"row counts differ: {len(ra)} vs {len(rb)}")
    for i, (x, y) in enumerate(zip(ra, rb)):
        for h, u, v in zip(ha, x, y):
            if h in skip:
                continue
            try:
                fu, fv = float(u), float(v)
            except ValueError:
                if u != v:
                    errs.append(f"row {i} col {h}: {u!r} != {v!r}")
                continue
            if abs(fu - fv) > tol * max(1.0, abs(fu), abs(fv)):
                errs.append(f"row {i} col {h}: {fu} != {fv} (tol {tol})")
    return errs
