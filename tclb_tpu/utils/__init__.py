"""Host-side utilities: units, geometry, output writers, samplers."""
