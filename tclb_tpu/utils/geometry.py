"""Geometry: XML-driven voxel painter for the node-type flag field.

Behavioral parity with the reference Geometry (reference
src/Geometry.{h,cpp.Rt}): regions with the dx/fx/nx attribute algebra and
negative-offset convention (src/Geometry.cpp.Rt:217-307), primitives
Box/Sphere/HalfSphere/OffgridSphere/Pipe/OffgridPipe/Wedge/Text/PythonInline
and named Zone references (Draw, :636-886), paint modes
overwrite/fill/change with a foreground mask (Dot, :310-322), the settings
zone registry (setZone, :196-214), and the built-in default zones
Inlet/Outlet/Channel/Tunnel (src/def.cpp.Rt:10-33).

Implementation is TPU-framework-idiomatic: primitives rasterize as numpy
boolean masks over coordinate grids (vectorized, not per-voxel ``Dot``
calls); the painted uint16 array is pushed to the device once via
``Lattice.set_flags`` — the reference's FlagOverwrite D2H/H2D dance
(src/Lattice.cu.Rt:892-905) has no equivalent cost here.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass

import numpy as np

from tclb_tpu.core.registry import Model
from tclb_tpu.utils.units import UnitEnv

MODE_OVERWRITE = 0
MODE_FILL = 1
MODE_CHANGE = 2
_MODES = {"overwrite": MODE_OVERWRITE, "fill": MODE_FILL,
          "change": MODE_CHANGE}

# default named zones (reference xml_definition, src/def.cpp.Rt:10-26):
# each zone is a list of Box-attribute dicts
DEFAULT_ZONES: dict[str, list[dict[str, str]]] = {
    "Inlet": [dict(dx="0", fx="0", dy="0", fy="-1", dz="0", fz="-1")],
    "Outlet": [dict(dx="-1", fx="-1", dy="0", fy="-1", dz="0", fz="-1")],
    "Channel": [
        dict(dx="0", dy="0", dz="0", fx="-1", fy="0", fz="-1"),
        dict(dx="0", dy="-1", dz="0", fx="-1", fy="-1", fz="-1"),
    ],
    "Tunnel": [
        dict(dx="0", dy="0", dz="0", fx="-1", fy="0", fz="-1"),
        dict(dx="0", dy="-1", dz="0", fx="-1", fy="-1", fz="-1"),
        dict(dx="0", dy="0", dz="0", fx="-1", fy="-1", fz="0"),
        dict(dx="0", dy="0", dz="-1", fx="-1", fy="-1", fz="-1"),
    ],
}


@dataclass
class Region:
    """An axis-aligned box: offset + extent per axis (reference lbRegion,
    src/Region.h)."""

    dx: int = 0
    dy: int = 0
    dz: int = 0
    nx: int = 1
    ny: int = 1
    nz: int = 1

    def intersect(self, o: "Region") -> "Region":
        dx, dy, dz = (max(self.dx, o.dx), max(self.dy, o.dy),
                      max(self.dz, o.dz))
        return Region(
            dx, dy, dz,
            max(0, min(self.dx + self.nx, o.dx + o.nx) - dx),
            max(0, min(self.dy + self.ny, o.dy + o.ny) - dy),
            max(0, min(self.dz + self.nz, o.dz + o.nz) - dz))

    @property
    def size(self) -> int:
        return self.nx * self.ny * self.nz


class Geometry:
    """Paints a ``(nz, ny, nx)``/``(ny, nx)`` uint16 flag array from an XML
    geometry tree."""

    def __init__(self, model: Model, shape: tuple[int, ...],
                 units: UnitEnv | None = None):
        self.model = model
        self.shape = tuple(shape)
        self.ndim = len(shape)
        if self.ndim == 2:
            ny, nx = shape
            nz = 1
        else:
            nz, ny, nx = shape
        self.region = Region(0, 0, 0, nx, ny, nz)
        self.units = units or UnitEnv()
        self.flags = np.zeros((nz, ny, nx), dtype=np.uint16)
        # settings-zone registry (reference SettingZones; zone 0 = default)
        self.setting_zones: dict[str, int] = {"DefaultZone": 0}
        # named zone shapes added by <Zone name=...> elements
        self.zones: dict[str, list[ET.Element]] = {}
        # foreground paint state
        self._fg = 0
        self._fg_mask = 0xFFFF
        self._fg_mode = MODE_OVERWRITE

    # -- attribute helpers -------------------------------------------------- #

    def _val(self, el: ET.Element, name: str, default=None) -> int:
        a = el.get(name)
        if a is None:
            if default is None:
                raise ValueError(f"<{el.tag}> missing attribute {name!r}")
            return default
        return int(round(self.units.alt(a)))

    def _val_p(self, el: ET.Element, name: str) -> tuple[int, str]:
        """Value with optional '<'/'>' prefix (reference val_p,
        src/Geometry.cpp.Rt:116-131)."""
        a = el.get(name)
        side = "+"
        if a and a[0] in "<>":
            side, a = a[0], a[1:]
        return int(round(self.units.alt(a))), side

    # -- region algebra ----------------------------------------------------- #

    def get_region(self, el: ET.Element | None,
                   parents: dict[ET.Element, ET.Element]) -> Region:
        """Region from dx/dy/dz ('<' measures from the far side; negative
        '+' values wrap), fx/fy/fz (far corner, negative wraps) and
        nx/ny/nz, resolved against the parent element's region (reference
        getRegion, src/Geometry.cpp.Rt:217-307)."""
        if el is None:
            return Region(0, 0, 0, self.region.nx, self.region.ny,
                          self.region.nz)
        ret = self.get_region(parents.get(el), parents)
        for ax in ("x", "y", "z"):
            if el.get("d" + ax) is not None:
                w, side = self._val_p(el, "d" + ax)
                n = getattr(ret, "n" + ax)
                if side == "<":
                    w = n + w
                elif side == "+" and w < 0:
                    w = n + w
                setattr(ret, "d" + ax, getattr(ret, "d" + ax) + w)
                setattr(ret, "n" + ax, n - w)
        for ax in ("x", "y", "z"):
            if el.get("f" + ax) is not None:
                w = self._val(el, "f" + ax)
                if w < 0:
                    w = getattr(ret, "n" + ax) + w + getattr(ret, "d" + ax)
                setattr(ret, "n" + ax, w - getattr(ret, "d" + ax) + 1)
        for ax in ("x", "y", "z"):
            if el.get("n" + ax) is not None:
                setattr(ret, "n" + ax, self._val(el, "n" + ax))
        return ret

    # -- paint state -------------------------------------------------------- #

    def set_flag(self, name: str) -> None:
        """Select foreground node type; its mask is the union of group masks
        covering it (reference setFlag + the generated Type table with the
        smallest covering mask, src/def.cpp.Rt:27-31)."""
        t = self.model.node_types[name]
        # smallest group mask that covers this type's value (reference picks
        # the min Node_Group >= value); our packing makes that the type's
        # own group mask
        self._fg = t.value
        self._fg_mask = t.mask
        self._fg_mode = MODE_OVERWRITE

    def set_mask(self, name: str) -> None:
        self._fg_mask = self.model.group_masks[name]

    def set_mode(self, mode: str) -> None:
        self._fg_mode = _MODES[mode]

    def set_zone(self, name: str) -> None:
        """Allocate/reuse a settings-zone id and fold it into the foreground
        flag's high bits (reference setZone, src/Geometry.cpp.Rt:196-214)."""
        if name not in self.setting_zones:
            self.setting_zones[name] = len(self.setting_zones)
        zid = self.setting_zones[name]
        if zid >= self.model.zone_max:
            raise ValueError(f"too many settings zones ({zid})")
        zmask = self.model.group_masks["SETTINGZONE"]
        self._fg = (self._fg & ~zmask) | (zid << self.model.zone_shift)
        self._fg_mask |= zmask

    # -- painting ----------------------------------------------------------- #

    def _paint(self, mask_xyz: np.ndarray, reg: Region) -> None:
        """Apply the foreground flag under ``mask_xyz`` (bool, region-shaped,
        indexed [z,y,x]) honoring mode+mask (reference Dot,
        src/Geometry.cpp.Rt:310-322)."""
        clip = self.region.intersect(reg)
        if clip.size == 0:
            return
        sl = (slice(clip.dz, clip.dz + clip.nz),
              slice(clip.dy, clip.dy + clip.ny),
              slice(clip.dx, clip.dx + clip.nx))
        sub = self.flags[sl]
        m = mask_xyz[clip.dz - reg.dz:clip.dz - reg.dz + clip.nz,
                     clip.dy - reg.dy:clip.dy - reg.dy + clip.ny,
                     clip.dx - reg.dx:clip.dx - reg.dx + clip.nx]
        if self._fg_mode == MODE_FILL:
            m = m & ((sub & self._fg_mask) == 0)
        elif self._fg_mode == MODE_CHANGE:
            m = m & ((sub & self._fg_mask) != 0)
        self.flags[sl] = np.where(
            m, (sub & ~np.uint16(self._fg_mask)) | np.uint16(self._fg), sub)

    def _grid(self, reg: Region):
        """Coordinate grids (z, y, x each region-shaped, indexed [z,y,x])."""
        z, y, x = np.meshgrid(
            np.arange(reg.dz, reg.dz + reg.nz),
            np.arange(reg.dy, reg.dy + reg.ny),
            np.arange(reg.dx, reg.dx + reg.nx), indexing="ij")
        return z, y, x

    def draw(self, node: ET.Element) -> None:
        """Rasterize every child primitive of ``node`` (reference Draw,
        src/Geometry.cpp.Rt:636-886)."""
        parents = {c: p for p in node.iter() for c in p}
        for n in node:
            reg = self.get_region(n, parents)
            tag = n.tag
            if tag == "Box":
                self._paint(np.ones((reg.nz, reg.ny, reg.nx), bool), reg)
            elif tag == "Sphere":
                z, y, x = self._grid(reg)
                xs = 2 * (0.5 + x - reg.dx) / reg.nx - 1
                ys = 2 * (0.5 + y - reg.dy) / reg.ny - 1
                zs = 2 * (0.5 + z - reg.dz) / reg.nz - 1
                self._paint(xs * xs + ys * ys + zs * zs < 1, reg)
            elif tag == "HalfSphere":
                z, y, x = self._grid(reg)
                xs = 2 * (0.5 + x - reg.dx) / reg.nx - 1
                ys = 2 * (0.5 - (y - 0.5 - reg.dy) / reg.ny / 2.0) - 1
                zs = 2 * (0.5 + z - reg.dz) / reg.nz - 1
                self._paint(xs * xs + ys * ys + zs * zs < 1, reg)
            elif tag == "OffgridSphere":
                x0 = self.units.alt(n.get("x"))
                y0 = self.units.alt(n.get("y"))
                z0 = self.units.alt(n.get("z", "0"))
                if n.get("R") is not None:
                    Rx = Ry = Rz = self.units.alt(n.get("R"))
                else:
                    Rx = self.units.alt(n.get("Rx"))
                    Ry = self.units.alt(n.get("Ry"))
                    Rz = self.units.alt(n.get("Rz", "1"))
                reg = Region(int(x0 - Rx - 5), int(y0 - Ry - 5),
                             int(z0 - Rz - 5), int(2 * Rx + 10),
                             int(2 * Ry + 10), int(2 * Rz + 10))
                z, y, x = self._grid(reg)
                xs = (0.5 + x - x0) / Rx
                ys = (0.5 + y - y0) / Ry
                zs = (0.5 + z - z0) / Rz
                self._paint(xs * xs + ys * ys + zs * zs < 1, reg)
            elif tag == "OffgridPipe":
                x0 = self.units.alt(n.get("x"))
                y0 = self.units.alt(n.get("y"))
                if n.get("R") is not None:
                    Rx = Ry = self.units.alt(n.get("R"))
                else:
                    Rx = self.units.alt(n.get("Rx"))
                    Ry = self.units.alt(n.get("Ry"))
                reg = Region(int(x0 - Rx - 5), int(y0 - Ry - 5), reg.dz,
                             int(2 * Rx + 10), int(2 * Ry + 10), reg.nz)
                z, y, x = self._grid(reg)
                xs = (0.5 + x - x0) / Rx
                ys = (0.5 + y - y0) / Ry
                self._paint(xs * xs + ys * ys < 1, reg)
            elif tag == "Pipe":
                # solid *outside* an inscribed y/z ellipse (reference :748-758)
                grown = Region(reg.dx, reg.dy - 1, reg.dz - 1,
                               reg.nx, reg.ny + 2, reg.nz + 2)
                z, y, x = self._grid(grown)
                ys = 2 * (0.5 + y - reg.dy) / reg.ny - 1
                zs = 2 * (0.5 + z - reg.dz) / reg.nz - 1
                self._paint(ys * ys + zs * zs >= 1, grown)
            elif tag == "Wedge":
                direction = n.get("direction", "UpperLeft") or "UpperLeft"
                z, y, x = self._grid(reg)
                xs = (x - reg.dx) / max(reg.nx - 1.0, 1.0)
                ys = (y - reg.dy) / max(reg.ny - 1.0, 1.0)
                if direction in ("UpperRight", "LowerRight"):
                    xs = 1.0 - xs
                if direction in ("LowerLeft", "LowerRight"):
                    ys = 1.0 - ys
                self._paint((xs - ys) < 1e-10, reg)
            elif tag == "Sweep":
                self._draw_sweep(n, reg)
            elif tag == "Text":
                self._draw_text(n, reg)
            elif tag == "PythonInline":
                self._draw_python(n, reg)
            elif tag == "STL":
                from tclb_tpu.utils.stl import draw_stl
                draw_stl(self, n, reg)
            elif tag == "Zone" or tag in self.zones or tag in DEFAULT_ZONES:
                self._draw_zone(n, reg)
            else:
                raise ValueError(f"unknown geometry primitive <{tag}>")

    def _draw_zone(self, n: ET.Element, reg: Region) -> None:
        """A named zone reference re-rasterizes the zone's stored shapes
        (reference keeps Zone shapes in a dictionary merged from xml_def,
        src/Geometry.cpp.Rt:905-917)."""
        name = n.get("name", n.tag) if n.tag == "Zone" else n.tag
        if n.tag == "Zone" and len(n):
            # definition: store children
            self.zones[name] = list(n)
            return
        shapes = self.zones.get(name)
        if shapes is None:
            boxes = DEFAULT_ZONES.get(name)
            if boxes is None:
                raise ValueError(f"unknown zone {name!r}")
            holder = ET.Element("Geometry")
            for attrs in boxes:
                ET.SubElement(holder, "Box", attrs)
            shapes = list(holder)
        holder = ET.Element("Geometry")
        holder.extend(shapes)
        self.draw(holder)

    def _draw_text(self, n: ET.Element, reg: Region) -> None:
        """Point list file: each line 'x y [z]' marks one voxel (reference
        Text, src/Geometry.cpp.Rt:851-884)."""
        fname = n.get("file")
        pts = np.loadtxt(fname, ndmin=2)
        m = np.zeros((reg.nz, reg.ny, reg.nx), bool)
        for p in pts:
            x, y = int(p[0]), int(p[1])
            z = int(p[2]) if len(p) > 2 else 0
            if (0 <= x - reg.dx < reg.nx and 0 <= y - reg.dy < reg.ny
                    and 0 <= z - reg.dz < reg.nz):
                m[z - reg.dz, y - reg.dy, x - reg.dx] = True
        self._paint(m, reg)

    def _draw_python(self, n: ET.Element, reg: Region) -> None:
        """Inline Python predicate over coordinate arrays — the reference
        embeds CPython for this (src/Geometry.cpp.Rt:771-828); here it's
        native.  The expression sees x, y, z, np and must return a boolean
        array (or scalar) over the region."""
        z, y, x = self._grid(reg)
        expr = (n.text or n.get("expr") or "").strip()
        mask = eval(expr, {"np": np, "x": x, "y": y, "z": z})  # noqa: S307
        self._paint(np.broadcast_to(np.asarray(mask, bool), x.shape), reg)

    # -- top-level load ----------------------------------------------------- #

    def load(self, root: ET.Element) -> None:
        """Process a <Geometry> tree: per child, set flag from tag name plus
        mask/mode/zone attributes, then rasterize grandchildren (reference
        Geometry::load, src/Geometry.cpp.Rt:905-950)."""
        for child in root:
            if child.tag == "Zone" and len(child):
                self.zones[child.get("name", "")] = list(child)
                continue
            self.set_flag(child.tag)
            for aname, aval in child.attrib.items():
                if aname == "mask":
                    self.set_mask(aval)
                elif aname == "mode":
                    self.set_mode(aval)
                elif aname == "name":
                    self.set_zone(aval)
            if len(child):
                self.draw(child)
            else:
                # no shape children: paint a Box over the element's OWN
                # region attributes (e.g. <Wall dx="0" fx="5"/> is the
                # first six columns, not the whole domain — reference
                # Geometry::load treats the element itself as the region,
                # src/Geometry.cpp.Rt:905-950)
                holder = ET.Element("g")
                ET.SubElement(holder, "Box", {
                    k: v for k, v in child.attrib.items()
                    if k not in ("mask", "mode", "name")})
                self.draw(holder)

    def _draw_sweep(self, n, reg) -> None:
        """<Sweep order= step=|steps= r=><Point x= y= z= r=/>...</Sweep>:
        paint a tube of (varying) radius swept along a clamped uniform
        B-spline through the Points (reference loadSweep,
        src/Geometry.cpp.Rt:579-634; spline of src/spline.h:9-43)."""
        order = int(n.get("order", "1"))
        dl = 1e-3
        if n.get("step") is not None:
            dl = float(n.get("step"))
        if n.get("steps") is not None:
            dl = 1.0 / self.units.alt(n.get("steps"))
        def_r = self.units.alt(n.get("r", "1"))
        pts = []
        for par in n:
            if par.tag == "Point":
                pts.append((self.units.alt(par.get("x", "0")),
                            self.units.alt(par.get("y", "0")),
                            self.units.alt(par.get("z", "0")),
                            self.units.alt(par.get("r"))
                            if par.get("r") is not None else def_r))
        if not pts:
            return
        if order > len(pts) - 1:
            order = len(pts) - 1
        ctrl = np.asarray(pts, dtype=np.float64)     # (n, 4): x,y,z,r
        # inclusive of l=1 so the tube always reaches the last Point
        ls = np.append(np.arange(0.0, 1.0, dl), 1.0)
        samples = np.stack([_bspline(l, ctrl, order) for l in ls])
        mask = np.zeros((reg.nz, reg.ny, reg.nx), dtype=bool)
        for x0, y0, z0, r in samples:
            # clip to the sample's bounding box: the work scales with the
            # tube volume, not samples x grid size
            lz = max(int(np.floor(z0 - r)) - reg.dz, 0)
            hz = min(int(np.ceil(z0 + r)) - reg.dz + 1, reg.nz)
            ly = max(int(np.floor(y0 - r)) - reg.dy, 0)
            hy = min(int(np.ceil(y0 + r)) - reg.dy + 1, reg.ny)
            lx = max(int(np.floor(x0 - r)) - reg.dx, 0)
            hx = min(int(np.ceil(x0 + r)) - reg.dx + 1, reg.nx)
            if lz >= hz or ly >= hy or lx >= hx:
                continue
            zz, yy, xx = np.meshgrid(
                np.arange(lz, hz) + reg.dz, np.arange(ly, hy) + reg.dy,
                np.arange(lx, hx) + reg.dx, indexing="ij")
            d2 = (xx - x0) ** 2 + (yy - y0) ** 2 + (zz - z0) ** 2
            mask[lz:hz, ly:hy, lx:hx] |= d2 < r * r
        self._paint(mask, reg)

    def result(self) -> np.ndarray:
        """Painted flags, shaped for the model's dimensionality."""
        if self.ndim == 2:
            return self.flags[0]
        return self.flags


# --------------------------------------------------------------------------- #
# Q-cut painting (interpolated bounce-back wall distances)
# --------------------------------------------------------------------------- #


def cuts_from_sdf(sdf, shape, E) -> np.ndarray:
    """Per-direction wall-cut distances from a signed distance function
    (the host-side analogue of the reference's Geometry cut generation
    consumed by Lattice::CutsOverwrite, src/Lattice.cu.Rt:907-922;
    storage semantics of src/types.h:16-20 with -1 as NO_CUT and the
    fraction kept as a float instead of the reference's 0.005 quanta).

    ``sdf(coords)`` maps an (ndim, *shape) array of node coordinates
    (index order matching ``shape``: z,y,x / y,x) to signed distances —
    positive in the fluid, negative in the solid.  For every fluid node
    whose ``E[i]`` neighbor is solid, the cut fraction along the link is
    the linear interpolation of the surface crossing:
    ``q = sdf(x) / (sdf(x) - sdf(x + e_i))``.

    Returns (len(E) - 1, *shape) float32, aligned with ``E[1:]`` (the
    rest vector carries no link).
    """
    shape = tuple(int(s) for s in shape)
    ndim = len(shape)
    grids = np.meshgrid(*[np.arange(s, dtype=np.float64) for s in shape],
                        indexing="ij")
    coords = np.stack(grids)
    d0 = np.asarray(sdf(coords), dtype=np.float64)
    out = np.full((len(E) - 1,) + shape, -1.0, dtype=np.float32)
    for i in range(1, len(E)):
        # E rows are (dx[, dy[, dz]]) = x first; index order is reversed
        off = np.array(list(E[i][::-1]) + [0] * (ndim - len(E[i])),
                       dtype=np.float64)[:ndim]
        dn = np.asarray(sdf(coords + off.reshape((ndim,) + (1,) * ndim)),
                        dtype=np.float64)
        crossing = (d0 > 0.0) & (dn <= 0.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            q = d0 / (d0 - dn)
        out[i - 1] = np.where(crossing, np.clip(q, 0.0, 1.0), -1.0)
    return out


def sphere_sdf(center, radius):
    """SDF of a solid sphere/cylinder: negative inside (coords in index
    order, matching :func:`cuts_from_sdf`); pass fewer center components
    than dimensions to get a cylinder extruded along the leading axes."""
    center = np.asarray(center, dtype=np.float64)

    def sdf(coords):
        nd = coords.shape[0]
        use = coords[nd - len(center):]
        r = np.sqrt(sum((use[k] - center[k]) ** 2
                        for k in range(len(center))))
        return r - radius
    return sdf


def _bspline_knot(i: int, n: int, k: int) -> float:
    """Clamped uniform knot vector (reference knot_bs, src/spline.h:9-14)."""
    if i < k + 1:
        return 0.0
    if i < n:
        return (i - k) / (n - k)
    return 1.0


def _bspline(x: float, ctrl: np.ndarray, k: int) -> np.ndarray:
    """De Boor evaluation on a clamped uniform B-spline, vectorized over
    the control-point columns (reference bspline_mod, src/spline.h:16-34)."""
    p = ctrl.copy()
    n = len(p)
    i = int(np.floor(x * (n - k))) + k
    k = min(k, n - 1)
    i = min(max(i, k), n - 1)
    for j in range(k, 0, -1):
        for l in range(j):
            lo = _bspline_knot(i - l, n, k)
            hi = _bspline_knot(i - l + j, n, k)
            a = (x - lo) / (hi - lo) if hi > lo else 0.0
            p[i - l] = a * p[i - l] + (1.0 - a) * p[i - l - 1]
    return p[i]
