"""Binary STL reader + voxelizer for the geometry painter.

Parity target: the reference's STL support (reference
src/Geometry.cpp.Rt:462-577 ``loadSTL``): binary STL, optional transform
attributes (Xrot/scale/x/y/z), voxelization with ``side`` = in / out /
surface, and the same half-voxel snap the reference applies
(transformSTL, :420-430: coordinates rounded to 1e-5 then shifted by a tiny
irrational-ish epsilon to dodge degenerate ray hits, minus 0.5).

The voxelizer is vectorized: for each (y, z) ray we collect x-crossings of
all triangles (watertight mesh -> even count) and mark voxels by crossing
parity — same ray-parity scheme the reference implements per-triangle-scanline.
"""

from __future__ import annotations

import struct
import xml.etree.ElementTree as ET

import numpy as np

_SM_DIFF = (0.123e-5, 0.231e-5, 0.312e-5)


def read_stl(path: str) -> np.ndarray:
    """Read a binary STL file -> (ntri, 3, 3) float64 vertex array."""
    with open(path, "rb") as f:
        header = f.read(80)
        if header[:5] == b"solid":
            # could still be binary; check length consistency
            pass
        (n,) = struct.unpack("<I", f.read(4))
        data = np.frombuffer(f.read(n * 50), dtype=np.uint8)
    if len(data) != n * 50:
        raise ValueError(f"truncated STL {path!r}")
    rec = data.reshape(n, 50)
    tri = rec[:, 12:48].copy().view("<f4").reshape(n, 3, 3).astype(np.float64)
    return tri


def transform_tri(tri: np.ndarray, n: ET.Element, units) -> np.ndarray:
    """Apply the reference's XML transform attributes (Xrot, scale, x, y, z)
    then its snap/epsilon-shift (src/Geometry.cpp.Rt:452-468)."""
    tri = tri.copy()
    if n.get("Xrot") is not None:
        v = units.alt(n.get("Xrot"))
        c, s = np.cos(v), np.sin(v)
        y, z = tri[..., 1].copy(), tri[..., 2].copy()
        tri[..., 1] = c * y - s * z
        tri[..., 2] = s * y + c * z
    if n.get("scale") is not None:
        tri *= units.alt(n.get("scale"))
    for ax, i in (("x", 0), ("y", 1), ("z", 2)):
        if n.get(ax) is not None:
            tri[..., i] += units.alt(n.get(ax))
    tri = np.round(tri * 1e5) * 1e-5
    tri += np.asarray(_SM_DIFF) - 0.5
    return tri


def voxelize(tri: np.ndarray, shape_xyz: tuple[int, int, int],
             side: str = "in") -> np.ndarray:
    """Ray-parity voxelization -> bool array indexed [z, y, x].

    ``side``: 'in' marks interior voxels, 'out' exterior, 'surface' marks
    voxels whose center lies within half a cell of the mesh surface along x.

    Dispatches to the native C++ voxelizer (tclb_tpu/native) when it is
    available — same algorithm, ~100x faster on large meshes — falling back
    to the pure-Python implementation below (the test oracle)."""
    from tclb_tpu import native
    out = native.voxelize(tri, shape_xyz, side)
    if out is not None:
        return out
    return voxelize_py(tri, shape_xyz, side)


def voxelize_py(tri: np.ndarray, shape_xyz: tuple[int, int, int],
                side: str = "in") -> np.ndarray:
    """Pure-Python/numpy reference implementation of :func:`voxelize`."""
    nx, ny, nz = shape_xyz
    inside = np.zeros((nz, ny, nx), dtype=bool)
    near = np.zeros((nz, ny, nx), dtype=bool) if side == "surface" else None

    p0, p1, p2 = tri[:, 0], tri[:, 1], tri[:, 2]
    # rays go along x at fixed (y, z): select triangles spanning each z plane
    zmin = tri[..., 2].min(axis=1)
    zmax = tri[..., 2].max(axis=1)
    for iz in range(nz):
        z = float(iz)
        sel = np.nonzero((zmin <= z) & (zmax >= z))[0]
        if len(sel) == 0:
            continue
        a, b, c = p0[sel], p1[sel], p2[sel]
        ymin = np.minimum(np.minimum(a[:, 1], b[:, 1]), c[:, 1])
        ymax = np.maximum(np.maximum(a[:, 1], b[:, 1]), c[:, 1])
        for iy in range(ny):
            y = float(iy)
            s2 = np.nonzero((ymin <= y) & (ymax >= y))[0]
            if len(s2) == 0:
                continue
            xs = _ray_hits(a[s2], b[s2], c[s2], y, z)
            if len(xs) == 0:
                continue
            xs.sort()
            # crossing parity marks interior runs
            for k in range(0, len(xs) - 1, 2):
                lo = max(0, int(np.ceil(xs[k])))
                hi = min(nx - 1, int(np.floor(xs[k + 1])))
                if hi >= lo:
                    inside[iz, iy, lo:hi + 1] = True
            if near is not None:
                for xhit in xs:
                    i = int(round(xhit))
                    if 0 <= i < nx and abs(i - xhit) <= 0.5:
                        near[iz, iy, i] = True
    if side == "in":
        return inside
    if side == "out":
        return ~inside
    return near


def _ray_hits(a: np.ndarray, b: np.ndarray, c: np.ndarray,
              y: float, z: float) -> list[float]:
    """x-coordinates where the ray (x, y, z), x in R, crosses triangles."""
    # solve barycentric in the (y, z) projection
    d = ((b[:, 1] - a[:, 1]) * (c[:, 2] - a[:, 2])
         - (c[:, 1] - a[:, 1]) * (b[:, 2] - a[:, 2]))
    ok = np.abs(d) > 1e-30
    if not ok.any():
        return []
    a, b, c, d = a[ok], b[ok], c[ok], d[ok]
    w1 = ((y - a[:, 1]) * (c[:, 2] - a[:, 2])
          - (c[:, 1] - a[:, 1]) * (z - a[:, 2])) / d
    w2 = ((b[:, 1] - a[:, 1]) * (z - a[:, 2])
          - (y - a[:, 1]) * (b[:, 2] - a[:, 2])) / d
    hit = (w1 >= 0) & (w2 >= 0) & (w1 + w2 <= 1)
    if not hit.any():
        return []
    w0 = 1.0 - w1 - w2
    x = (w0 * a[:, 0] + w1 * b[:, 0] + w2 * c[:, 0])[hit]
    return list(x)


def draw_stl(geom, n: ET.Element, reg) -> None:
    """<STL file=... side=in|out|surface> hook for Geometry.draw."""
    tri = transform_tri(read_stl(n.get("file")), n, geom.units)
    side = n.get("side", "in") or "in"
    r = geom.region
    mask = voxelize(tri, (r.nx, r.ny, r.nz), side)
    geom._paint(mask, r)
