"""Leveled console logging — the reference's ``myprint`` stack
(reference src/Global.cpp.Rt:181, macros debug2..error in
src/Global.h.Rt:100-150, rank filtering via InitPrint,
src/main.cpp.Rt:186).

Single-process by construction (JAX global-view arrays replace ranks), so
the rank prefix/filter degenerates to a level filter: set the threshold
with ``set_level()`` or the ``TCLB_LOG`` environment variable
(debug|info|notice|warning|error, default info).  ``error`` raises like
the reference's ERROR macro aborts.
"""

from __future__ import annotations

import os
import sys

LEVELS = {"debug": 0, "info": 1, "notice": 2, "warning": 3, "error": 4}


def _threshold_from_env() -> int:
    raw = os.environ.get("TCLB_LOG", "info")
    if raw not in LEVELS:
        # warn once at import, then fall back to info — a typo in TCLB_LOG
        # must not silently change verbosity
        print(f"[warning] TCLB_LOG={raw!r} is not a log level "
              f"(accepted: {', '.join(LEVELS)}); falling back to 'info'",
              file=sys.stderr, flush=True)
        return LEVELS["info"]
    return LEVELS[raw]


_threshold = _threshold_from_env()


def set_level(level: str) -> None:
    global _threshold
    if level not in LEVELS:
        raise ValueError(
            f"unknown log level {level!r} (accepted: {', '.join(LEVELS)})")
    _threshold = LEVELS[level]


def _emit(level: str, msg: str) -> None:
    if LEVELS[level] >= _threshold:
        stream = sys.stderr if LEVELS[level] >= 3 else sys.stdout
        print(f"[{level:7s}] {msg}", file=stream,
              flush=LEVELS[level] >= 2)   # reference per-level fflush


def debug(msg: str) -> None:
    _emit("debug", msg)


def info(msg: str) -> None:
    _emit("info", msg)


def notice(msg: str) -> None:
    _emit("notice", msg)


def warning(msg: str) -> None:
    _emit("warning", msg)


def error(msg: str) -> None:
    """Emit and raise — the reference's ERROR macro aborts the run."""
    _emit("error", msg)
    raise RuntimeError(msg)
