"""Units & gauging engine — behavioral parity with the reference's
``UnitVal``/``UnitEnv`` (reference src/unit.h:29-199, src/unit.cpp:60-275).

A value with unit is ``val * m^u0 s^u1 kg^u2 K^u3 x^u4 y^u5 z^u6 A^u7 t^u8``
(reference m_units, src/unit.h:18).  The user supplies *gauge* equations
(e.g. ``Viscosity="0.1m2/s"`` together with the model's lattice value) and
the scales of all nine base units are solved from the gauge set by Gauss
elimination over the unit-exponent matrix in log space (reference
UnitEnv::makeGauge, src/unit.cpp:223-262).  ``alt()`` converts an SI-tagged
value into lattice units — every attribute read in the control layer goes
through it, as in the reference.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

import numpy as np

M_UNITS = ("m", "s", "kg", "K", "x", "y", "z", "A", "t")
N_UNITS = len(M_UNITS)


@dataclass(frozen=True)
class UnitVal:
    """value * prod(base_i ^ uni_i)  (reference UnitVal, src/unit.h:29-135)."""

    val: float = 0.0
    uni: tuple[int, ...] = (0,) * N_UNITS

    def __mul__(self, o: "UnitVal | float") -> "UnitVal":
        o = _coerce(o)
        return UnitVal(self.val * o.val,
                       tuple(a + b for a, b in zip(self.uni, o.uni)))

    def __truediv__(self, o: "UnitVal | float") -> "UnitVal":
        o = _coerce(o)
        return UnitVal(self.val / o.val,
                       tuple(a - b for a, b in zip(self.uni, o.uni)))

    def __add__(self, o: "UnitVal") -> "UnitVal":
        o = _coerce(o)
        if o.uni != self.uni:
            raise ValueError(
                f"Different units in addition: {self} + {o}")
        return UnitVal(self.val + o.val, self.uni)

    def __pow__(self, n: int) -> "UnitVal":
        return UnitVal(self.val ** n, tuple(u * n for u in self.uni))

    def same_unit(self, o: "UnitVal") -> bool:
        return self.uni == o.uni

    def __str__(self) -> str:
        s = f"{self.val:g} [ "
        s += " ".join(f"{m}^{u}" for m, u in zip(M_UNITS, self.uni))
        return s + " ]"


def _coerce(v) -> UnitVal:
    return v if isinstance(v, UnitVal) else UnitVal(float(v))


def _base(k: int) -> UnitVal:
    uni = [0] * N_UNITS
    uni[k] = 1
    return UnitVal(1.0, tuple(uni))


_NUM_RE = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?")


class UnitEnv:
    """Unit environment: unit dictionary + gauge + scales
    (reference UnitEnv, src/unit.h:147-199)."""

    def __init__(self):
        self.units: dict[str, UnitVal] = {}
        self.gauge: dict[str, UnitVal] = {}
        self.scale = np.ones(N_UNITS)
        for i, name in enumerate(M_UNITS):
            self.units[name] = _base(i)
        # derived units & prefixes (reference src/unit.cpp:69-96)
        for name, txt in (("N", "1kgm/s2"), ("Pa", "1N/m2"), ("J", "1Nm"),
                          ("W", "1J/s"), ("V", "1kgm2/t3/A"), ("C", "1tA"),
                          ("nm", "1e-9m"), ("um", "1e-6m"), ("mm", "1e-3m"),
                          ("cm", "1e-2m"), ("km", "1e+3m"), ("h", "3600s"),
                          ("ns", "1e-9s"), ("us", "1e-6s"), ("ms", "1e-3s"),
                          ("g", "1e-3kg"), ("mg", "1e-6kg")):
            self.units[name] = self.read_text(txt)
        self.units["d"] = UnitVal(math.pi / 180.0)
        self.units["%"] = UnitVal(0.01)
        self.units["An"] = UnitVal(6.022e23)

    # -- parsing ----------------------------------------------------------- #

    def _read_alpha(self, s: str, p: int) -> UnitVal:
        """Longest-prefix factorization of an alpha unit run, preferring the
        2-char head when both parses exist (reference readUnitAlpha,
        src/unit.cpp:105-140): e.g. 'ms2' -> (1e-3 s)^2, 'kgm' -> kg*m."""
        if s in self.units:
            return self.units[s] ** p
        for head in (2, 1):
            if len(s) > head and s[:head] in self.units:
                try:
                    return (self.units[s[:head]]
                            * self._read_alpha(s[head:], 1)) ** p
                except ValueError:
                    continue
        raise ValueError(f"Unknown unit: {s!r}")

    def read_unit(self, s: str) -> UnitVal:
        """Parse a unit expression: alpha runs with integer powers joined by
        nothing (multiply) or '/' (divide) — reference readUnit,
        src/unit.cpp:142-183."""
        ret = UnitVal(1.0)
        i, w = 0, 1
        while i < len(s):
            j = i
            while i < len(s) and s[i].isalpha() or (i < len(s) and s[i] == "%"):
                i += 1
            k = i
            while i < len(s) and s[i].isdigit():
                i += 1
            p = int(s[k:i]) if i > k else 1
            last = self._read_alpha(s[j:k], p) if k > j else UnitVal(1.0)
            ret = ret * last if w > 0 else ret / last
            j = i
            while i < len(s) and not (s[i].isalnum() or s[i] == "%"):
                i += 1
            if i - j > 1:
                raise ValueError(f"Too many non-alphanumeric chars in {s!r}")
            if i - j == 1:
                if s[j] != "/":
                    raise ValueError(f"Only '/' allowed in units, got {s[j]!r}")
                w = -1
        return ret

    def read_text(self, s: str) -> UnitVal:
        """number + unit, e.g. '0.1m2/s' (reference readText,
        src/unit.cpp:184-216)."""
        s = s.strip()
        m = _NUM_RE.match(s)
        if m:
            num, unit = float(m.group(0)), s[m.end():]
        else:
            num, unit = 1.0, s
        ret = self.read_unit(unit) if unit else UnitVal(1.0)
        return ret * num

    def __call__(self, s: str) -> UnitVal:
        return self.read_text(s)

    # -- conversion -------------------------------------------------------- #

    def si(self, v) -> float:
        if isinstance(v, str):
            v = self.read_text(v)
        return v.val

    def alt(self, v, default: float | None = None) -> float:
        """SI-tagged value -> lattice units using the solved gauge scales;
        strings may be sums like '1m+10cm' (reference alt(), src/unit.h:159-191).
        """
        if isinstance(v, str):
            if not v:
                if default is None:
                    raise ValueError("empty value with no default")
                return default
            total = 0.0
            for term in _split_terms(v):
                total += self.alt(self.read_text(term))
            return total
        if v is None:
            if default is None:
                raise ValueError("missing value with no default")
            return default
        ret = v.val
        for i in range(N_UNITS):
            ret *= self.scale[i] ** v.uni[i]
        return ret

    # -- gauging ------------------------------------------------------------ #

    def set_unit(self, name: str, v: UnitVal, lattice_value: float = None
                 ) -> None:
        """Add a gauge equation: SI value ``v`` corresponds to
        ``lattice_value`` lattice units (reference setUnit,
        src/unit.cpp:217-222)."""
        if lattice_value is not None:
            v = v / UnitVal(float(lattice_value))
        self.gauge[name] = v

    def make_gauge(self) -> None:
        """Solve base-unit scales from the gauge equations: each equation
        ``val * prod(base^uni) == 1`` becomes a linear equation
        ``sum(uni_j * log(scale_j)) == -log(val)``; unconstrained base units
        get scale 1 (reference makeGauge, src/unit.cpp:223-262)."""
        rows, rhs = [], []
        for v in self.gauge.values():
            rows.append(list(v.uni))
            rhs.append(math.log(v.val))
        # pad: any base unit untouched by the gauge gets scale 1
        touched = np.any(np.array(rows, dtype=float).reshape(-1, N_UNITS) != 0,
                         axis=0) if rows else np.zeros(N_UNITS, bool)
        for j in range(N_UNITS):
            if not touched[j]:
                if len(rows) >= N_UNITS:
                    raise ValueError("Gauge variables over-constructed")
                r = [0] * N_UNITS
                r[j] = 1
                rows.append(r)
                rhs.append(0.0)
        if len(rows) < N_UNITS:
            raise ValueError("Gauge variables under-constructed")
        if len(rows) > N_UNITS:
            raise ValueError("Gauge variables over-constructed")
        x = np.linalg.solve(np.array(rows, dtype=float),
                            np.array(rhs, dtype=float))
        self.scale = np.exp(-x)

    def gauge_summary(self) -> str:
        lines = ["/---------------[ GAUGE ]-----------------"]
        for name, v in self.gauge.items():
            lines.append(f"|  {name}: {v}")
        lines.append("-" * 42)
        for j, m in enumerate(M_UNITS):
            lines.append(f"| 1 {m} = {self.scale[j]:f} units")
        lines.append("\\" + "-" * 41)
        return "\n".join(lines)


def _split_terms(s: str) -> list[str]:
    """Split '1m+10cm-2mm' into signed terms, keeping exponent signs
    (reference alt() scanner, src/unit.h:166-190)."""
    terms, cur = [], ""
    i = 0
    while i < len(s):
        c = s[i]
        if c in "+-" and cur and cur[-1].lower() != "e":
            terms.append(cur)
            cur = c if c == "-" else ""
        else:
            cur += c
        i += 1
    if cur:
        terms.append(cur)
    return terms
