"""Point sampler: high-frequency probes flushed to CSV.

Parity target: reference Sampler (src/Sampler.{h,cpp.Rt}, C16 in SURVEY.md):
points registered from the <Sample><Point .../></Sample> element, quantities
gathered every iteration into a device buffer (here: the scan-ys of
``make_sampled_iterate``), flushed to a CSV by the callback
(writeHistory, src/Sampler.cpp.Rt:35-58).
"""

from __future__ import annotations

import os

import numpy as np


class Sampler:
    def __init__(self, model, quantities: list[str],
                 points: np.ndarray, path: str, units=None):
        """``points`` is (npoints, ndim) in array index order."""
        self.model = model
        self.quantities = list(quantities)
        self.points = np.asarray(points, dtype=np.int32)
        self.path = path
        self.units = units
        self._rows: list[tuple[int, np.ndarray]] = []
        self._wrote_header = False
        # column names: per point, per quantity (vector -> 3 columns)
        self.columns: list[str] = []
        for i in range(len(self.points)):
            for q in self.quantities:
                spec = next(x for x in model.quantities if x.name == q)
                if spec.vector:
                    self.columns += [f"{q}_{i}_{c}" for c in "xyz"]
                else:
                    self.columns.append(f"{q}_{i}")

    def append(self, it0: int, samples: np.ndarray) -> None:
        """samples: (nsteps, npoints, ncols-per-point)."""
        flat = samples.reshape(samples.shape[0], -1)
        for k in range(flat.shape[0]):
            self._rows.append((it0 + k + 1, flat[k]))

    def flush(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        mode = "a" if self._wrote_header else "w"
        with open(self.path, mode) as f:
            if not self._wrote_header:
                f.write(",".join(["Iteration"] + self.columns) + "\n")
                self._wrote_header = True
            for it, row in self._rows:
                f.write(str(it) + "," + ",".join(f"{v:g}" for v in row)
                        + "\n")
        self._rows.clear()
