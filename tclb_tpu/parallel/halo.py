"""Sharded lattice stepping: halo exchange over ICI + shard_map.

TPU-native replacement for the reference's MPI halo pipeline (reference
src/Lattice.cu.Rt:304-366 and :383-461): where the reference stages 26 margin
buffers through pinned host memory around ``MPI_Isend/Irecv`` and manually
overlaps border/interior kernels, here each device holds one block of the
lattice, halos move with ``lax.ppermute`` over the mesh (ICI neighbors ARE
the lattice neighbors), and XLA's latency-hiding scheduler overlaps the
collective with interior compute.  No host staging exists at all.

Like the reference, which only sends non-empty margins (``NonEmptyMargin``,
src/conf.R:517-563), each exchange ships only the planes whose streaming
vector actually crosses that axis.

Globals go through ``lax.psum``/``pmax`` (reference MPI_Reduce,
src/Lattice.cu.Rt:1093-1106), hoisted outside the iteration loop.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional

import inspect

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.7 exposes shard_map at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# the replication-check kwarg was renamed check_rep -> check_vma across
# jax versions; translate (or drop) so one call site works on both
_SM_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def _shard_map(f, **kw):
    if "check_vma" in kw and "check_vma" not in _SM_PARAMS:
        v = kw.pop("check_vma")
        if "check_rep" in _SM_PARAMS:
            kw["check_rep"] = v
    return _shard_map_impl(f, **kw)

from tclb_tpu import telemetry
from tclb_tpu.core.lattice import (LatticeState, SimParams, Streaming,
                                   make_action_step)
from tclb_tpu.core.registry import Model
from tclb_tpu.parallel.mesh import field_spec, flag_spec

_COMP = {"x": 0, "y": 1, "z": 2}


def _validate_mesh(model: Model, mesh: Mesh) -> None:
    expected = ("y", "x") if model.ndim == 2 else ("z", "y", "x")
    if tuple(mesh.axis_names) != expected:
        raise ValueError(
            f"mesh axes {tuple(mesh.axis_names)} must be {expected} for a "
            f"{model.ndim}D model (one mesh axis per lattice dim, size 1 for "
            f"unsplit dims; use parallel.mesh.make_mesh)")


def _exchange_axis(block: jnp.ndarray, name: str, axis: int, width: int,
                   n: int, send: Optional[np.ndarray] = None) -> jnp.ndarray:
    """Extend ``block`` with ``width`` halo cells along ``axis`` from the
    torus neighbors on mesh axis ``name``.  ``send`` selects which storage
    planes participate (others get zero halos, which are never read).  On a
    size-1 mesh axis the permute is the identity — the periodic wrap of the
    global domain."""
    src = block if send is None else block[jnp.asarray(send)]
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    hi_edge = lax.slice_in_dim(src, src.shape[axis] - width, src.shape[axis],
                               axis=axis)
    lo_edge = lax.slice_in_dim(src, 0, width, axis=axis)
    lo_halo = lax.ppermute(hi_edge, name, fwd)   # from lower neighbor
    hi_halo = lax.ppermute(lo_edge, name, bwd)   # from upper neighbor
    if send is not None:
        shp = list(block.shape)
        shp[axis] = width
        z = jnp.zeros(shp, block.dtype)
        sel = jnp.asarray(send)
        lo_halo = z.at[sel].set(lo_halo)
        hi_halo = z.at[sel].set(hi_halo)
    return jnp.concatenate([lo_halo, block, hi_halo], axis=axis)


def halo_pad(block: jnp.ndarray, mesh: Mesh, width: int,
             start_axis: int = 1) -> jnp.ndarray:
    """Extend a local block with halos on every lattice axis (all planes).
    Axes are processed in order, so the second exchange carries corner data
    from the first — the reference's 26-direction margin system collapsed to
    2·ndim collectives."""
    out = block
    for k, name in enumerate(mesh.axis_names):
        out = _exchange_axis(out, name, start_axis + k, width,
                             mesh.shape[name])
    return out


class HaloStreaming(Streaming):
    """Streaming over a device mesh: pull via halo exchange + shifted static
    slices; Field neighbor loads via a halo-padded raw stack."""

    def __init__(self, model: Model, mesh: Mesh,
                 width: Optional[int] = None):
        super().__init__(model)
        _validate_mesh(model, mesh)
        self.mesh = mesh
        self.width = int(width or max(1, model.max_stencil))
        # which storage planes stream across each mesh axis
        self._send: dict[str, Optional[np.ndarray]] = {}
        for name in mesh.axis_names:
            sel = np.nonzero(model.ei[:, _COMP[name]])[0]
            self._send[name] = sel if len(sel) else None
        # does any Field declare a nonzero access stencil?
        self._needs_loader = any(
            lo or hi
            for f in model.fields
            for lo, hi in (f.dx_range, f.dy_range, f.dz_range))

    def pull(self, fields: jnp.ndarray) -> jnp.ndarray:
        w, names = self.width, self.mesh.axis_names
        local = fields.shape[1:]
        padded = fields
        for k, name in enumerate(names):
            send = self._send[name]
            if send is None:
                continue  # nothing streams across this axis
            padded = _exchange_axis(padded, name, 1 + k, w,
                                    self.mesh.shape[name], send)
        out = []
        # track how much each axis was actually padded
        pad = {name: (0 if self._send[name] is None else w) for name in names}
        for i in range(self.model.n_storage):
            e = self.model.ei[i]
            idx = []
            for k, name in enumerate(names):
                d = int(e[_COMP[name]])
                start = pad[name] - d
                idx.append(slice(start, start + local[k]))
            out.append(padded[(i, *idx)])
        return jnp.stack(out)

    def make_loader(self, raw: jnp.ndarray) -> Callable:
        if not self._needs_loader:
            # no Field declared a stencil: any ctx.load with a nonzero
            # offset would silently wrap at the local shard edge, so fail
            # loudly instead (the declared ranges size the halo)
            def no_load(index: int, dx: int, dy: int, dz: int):
                if dx or dy or dz:
                    raise ValueError(
                        "sharded ctx.load with nonzero offset requires the "
                        "Field to declare its access stencil (add_field "
                        "dx/dy/dz ranges)")
                return raw[index]
            return no_load
        w, names = self.width, self.mesh.axis_names
        local = raw.shape[1:]
        padded = halo_pad(raw, self.mesh, w)

        def load(index: int, dx: int, dy: int, dz: int) -> jnp.ndarray:
            if max(abs(dx), abs(dy), abs(dz)) > w:
                raise ValueError(
                    f"ctx.load offset ({dx},{dy},{dz}) exceeds halo width "
                    f"{w}; declare a wider stencil on the Field")
            d_by_name = {"x": dx, "y": dy, "z": dz}
            idx = []
            for k, name in enumerate(names):
                d = int(d_by_name[name])
                idx.append(slice(w + d, w + d + local[k]))
            return padded[(index, *idx)]

        return load


def _globals_allreduce(model: Model, g: jnp.ndarray, names) -> jnp.ndarray:
    """Cross-device reduction honoring each Global's op (SUM/MAX)."""
    if model.n_globals == 0:
        return g
    is_sum = np.array([gl.op == "SUM" for gl in model.globals_])
    g_sum = lax.psum(g, names)
    g_max = lax.pmax(g, names)
    return jnp.where(jnp.asarray(is_sum), g_sum, g_max)


def make_sharded_pallas_iterate(model: Model, mesh: Mesh, shape,
                                dtype=jnp.float32,
                                present: Optional[set] = None,
                                interpret: Optional[bool] = None
                                ) -> Optional[Callable]:
    """Fused Pallas fast path over the device mesh, or None if this
    configuration can't run it.

    The band axis of the kernels (y in 2D, z in 3D) is the sharded axis;
    x (and y in 3D) must be unsplit.  Each step exchanges an 8-row (2D,
    Mosaic tile granularity) or 1-slab (3D) halo via ``ppermute`` and
    runs the per-shard band kernel on the extended block — the TPU
    composition of the reference's RunBorder / MPIStream_A / RunInterior
    / MPIStream_B overlap pipeline (src/Lattice.cu.Rt:424-456), with
    XLA's latency-hiding scheduler providing the overlap.

    Like the single-device fast path this is the "NoGlobals"
    specialization: ``globals_`` is zeroed; the Lattice hybrid's trailing
    XLA step (which psums) supplies them."""
    from tclb_tpu.ops import pallas_d2q9, pallas_d3q
    try:
        _validate_mesh(model, mesh)
    except ValueError:
        return None
    if mesh.shape["x"] != 1 or (model.ndim == 3 and mesh.shape["y"] != 1):
        return None   # kernels keep the lane plane whole
    axis = "y" if model.ndim == 2 else "z"
    n = mesh.shape[axis]
    if shape[0] % n:
        return None
    local = (shape[0] // n,) + tuple(shape[1:])

    mode = None
    if model.ndim == 2:
        if local[0] % 8:
            return None
        if pallas_d2q9.supports(model, local, dtype):
            call1, call2, by, by2 = pallas_d2q9.make_pallas_iterate(
                model, local, dtype, interpret=interpret, fuse=2,
                present=present, ext_halo=True)
            mode = "tuned2d"
        else:
            # registry-driven generic kernel as the sharded building
            # block: same 8-row halo contract, per-step aux stack
            from tclb_tpu.ops import pallas_generic
            if not pallas_generic.supports(model, local, dtype):
                return None
            callg, byg, gz_names = pallas_generic.make_pallas_iterate(
                model, local, dtype, interpret=interpret, fuse=1,
                present=present, ext_halo=True)
            si = model.setting_index
            gz_si = [si[nm] for nm in gz_names]
            # iteration advances per action rep iff any stage streams —
            # the same rule the single-device generic engine applies
            g_adv = int(any(model.stages[st].load_densities
                            for st in model.actions["Iteration"]))
            mode = "generic2d"
        width = 8
    else:
        if not pallas_d3q.supports(model, local, dtype, ext_halo=True):
            return None
        call3, bz, zonal_names = pallas_d3q.make_pallas_iterate(
            model, local, dtype, interpret=interpret, present=present,
            ext_halo=True)
        si = model.setting_index
        zonal_si = [si[nm] for nm in zonal_names]
        width = 1
    zshift = model.zone_shift

    def exch(arr):
        """Prepend/append ``width`` halo rows/slabs from the torus
        neighbors along the sharded axis (identity wrap when n == 1) —
        the shared halo-exchange primitive, axis 1 = the band axis."""
        return _exchange_axis(arr, axis, 1, width, n)

    state_specs = LatticeState(
        fields=field_spec(mesh), flags=flag_spec(mesh),
        globals_=P(), iteration=P())

    @lru_cache(maxsize=None)
    def _for_niter(niter: int):
        def local_iterate(state: LatticeState, params: SimParams
                          ) -> LatticeState:
            flags_i32 = state.flags.astype(jnp.int32)
            zones = flags_i32 >> zshift
            sett = params.settings.astype(dtype)
            fields = state.fields
            if mode == "generic2d":
                aux_ext = exch(jnp.stack(
                    [flags_i32.astype(dtype)]
                    + [params.zone_table[j].astype(dtype)[zones]
                       for j in gz_si]))

                def bodyg(carry, _):
                    f, it = carry
                    out = callg(sett, it[None], exch(f), aux_ext)
                    return (out, it + g_adv), None

                (fields, _), _ = lax.scan(
                    bodyg, (fields, state.iteration), None, length=niter)
            elif model.ndim == 2:
                vel, den = pallas_d2q9.gather_zonal_planes(
                    model, params, zones, dtype)
                aux_ext = exch(jnp.stack(
                    [flags_i32.astype(dtype), vel, den]))

                def body2(f, _):
                    return call2(sett, exch(f), aux_ext), None

                fields, _ = lax.scan(body2, fields, None,
                                     length=niter // 2)
                if niter % 2:
                    fields = call1(sett, exch(fields), flags_i32, vel,
                                   den)
            else:
                zonal = jnp.stack([params.zone_table[j].astype(dtype)[zones]
                                   for j in zonal_si])

                def body3(f, _):
                    return call3(sett, exch(f), flags_i32, zonal), None

                fields, _ = lax.scan(body3, fields, None, length=niter)
            return LatticeState(
                fields=fields,
                flags=state.flags,
                globals_=jnp.zeros_like(state.globals_),
                iteration=state.iteration + niter,
            )

        f = _shard_map(local_iterate, mesh=mesh,
                       in_specs=(state_specs, P()),
                       out_specs=state_specs, check_vma=False)
        return jax.jit(f, donate_argnums=0)

    def iterate(state, params, niter):
        if params.time_series is not None:
            raise ValueError(
                "pallas iterate does not support Control time series")
        if not telemetry.enabled():
            return _for_niter(int(niter))(state, params)
        # one ppermute halo exchange per step along the band axis (plus
        # one aux-stack exchange per chunk) — counted host-side; the
        # per-step wall time is the enclosing iterate span's business
        with telemetry.span("halo.sharded_pallas_iterate",
                            iters=int(niter), mode=mode or "tuned3d",
                            mesh=dict(mesh.shape)) as sp:
            out = _for_niter(int(niter))(state, params)
            sp.sync(out.fields)
        telemetry.counter("halo.exchanges", int(niter))
        return out

    # the generic-kernel building block is capability-probed, not proven:
    # the Lattice dispatch probes its first call and falls back to the
    # sharded XLA engine on a Mosaic lowering failure
    iterate.uses_generic = (mode == "generic2d")
    return iterate


def make_sharded_iterate(model: Model, mesh: Mesh,
                         action: str = "Iteration",
                         unroll: int = 1,
                         present: Optional[set] = None) -> Callable:
    """``iterate(state, params, niter)`` over the device mesh.

    The whole scan lives inside one ``shard_map`` so per-step halo exchanges
    are collectives inside the compiled loop — the reference's
    per-iteration MPIStream_A/B dance (src/Lattice.cu.Rt:424-456) with the
    host entirely out of the loop.  Like the single-device engine, the
    first niter-1 steps run the NoGlobals specialization; the final step
    reduces and the allreduce happens once after the scan."""
    _validate_mesh(model, mesh)
    streaming = HaloStreaming(model, mesh)
    step_ng = make_action_step(model, action, streaming, present=present,
                               compute_globals=False)
    step = make_action_step(model, action, streaming, present=present,
                            compute_globals=True)
    names = tuple(mesh.axis_names)

    state_specs = LatticeState(
        fields=field_spec(mesh), flags=flag_spec(mesh),
        globals_=P(), iteration=P())
    # params are fully replicated; a single P() is a valid tree prefix for
    # whatever SimParams contains (incl. Control time series)
    param_specs = P()

    @lru_cache(maxsize=None)
    def _for_niter(niter: int):
        def local_iterate(state: LatticeState, params: SimParams
                          ) -> LatticeState:
            def body(s, _):
                return step_ng(s, params), None
            state, _ = lax.scan(body, state, None, length=max(niter - 1, 0),
                                unroll=unroll)
            if niter > 0:
                state = step(state, params)
            return state.replace(
                globals_=_globals_allreduce(model, state.globals_, names))

        f = _shard_map(local_iterate, mesh=mesh,
                       in_specs=(state_specs, param_specs),
                       out_specs=state_specs, check_vma=False)
        return jax.jit(f, donate_argnums=0)

    # how many per-step ppermute exchange rounds the streaming strategy
    # issues (mesh axes the velocity set actually crosses), for the
    # host-side exchange counter
    n_exch = sum(1 for v in streaming._send.values() if v is not None)

    def iterate(state, params, niter):
        if int(niter) <= 0:
            # match the single-device engine: no steps, no allreduce (a
            # psum of the already-reduced globals would scale them by the
            # device count)
            return state
        if not telemetry.enabled():
            return _for_niter(int(niter))(state, params)
        with telemetry.span("halo.sharded_iterate", iters=int(niter),
                            mesh=dict(mesh.shape)) as sp:
            out = _for_niter(int(niter))(state, params)
            sp.sync(out.fields)
        telemetry.counter("halo.exchanges", int(niter) * n_exch)
        return out

    return iterate
