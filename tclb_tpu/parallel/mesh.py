"""Device-mesh construction & domain decomposition.

The reference statically splits the global box over MPI ranks with a
divisor-pair search minimizing communication surface, keeping X whole because
X is its coalescing direction (reference Solver::MPIDivision,
src/Solver.cpp.Rt:284-360).  The TPU equivalent: choose a
``jax.sharding.Mesh`` whose named axes split lattice dims, keeping X (the
128-lane dimension) whole whenever possible, and minimizing halo perimeter —
LBM halo exchange is nearest-neighbor, which maps exactly onto the ICI torus.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# lattice axis names, innermost (lane dim) last
AXIS_NAMES_2D = ("y", "x")
AXIS_NAMES_3D = ("z", "y", "x")


def choose_decomposition(shape: Sequence[int], n_devices: int,
                         keep_x: bool = True) -> dict[str, int]:
    """Split ``n_devices`` over lattice dims minimizing halo surface.

    Mirrors the reference's divisor search (minimize ``divz*ny + divy*nz``,
    src/Solver.cpp.Rt:295-333) generalized to any rank: enumerate factor
    assignments of ``n_devices`` to dims, score = total halo area
    = sum over split dims of (points per cut plane) x (cuts), prefer leaving
    X whole (TPU lane dim / reference coalescing dim).

    The search is memoized on ``(shape, n_devices, keep_x)`` — the fleet
    dispatcher's routing cost model calls it per submitted job, and the
    exhaustive factorization walk must not be back on that hot path.
    Note the chosen score ranks identically to
    :func:`decomposition_overhead` (cost = total/2 x overhead for even
    splits), so the pick also minimizes the halo-to-volume ratio within
    its keep-x tier (tests/test_fleet.py proves this by enumeration).
    """
    return dict(_choose_decomposition_cached(
        tuple(int(s) for s in shape), int(n_devices), bool(keep_x)))


@functools.lru_cache(maxsize=4096)
def _choose_decomposition_cached(shape: tuple[int, ...], n_devices: int,
                                 keep_x: bool) -> tuple:
    names = AXIS_NAMES_2D if len(shape) == 2 else AXIS_NAMES_3D
    dims = dict(zip(names, shape))

    def factorizations(n: int, k: int):
        if k == 1:
            yield (n,)
            return
        for d in range(1, n + 1):
            if n % d == 0:
                for rest in factorizations(n // d, k - 1):
                    yield (d,) + rest

    # two-tier search: any valid non-x-splitting decomposition beats every
    # x-splitting one (x is the TPU lane dim, the reference's coalescing
    # direction — src/Solver.cpp.Rt:284 keeps X whole unconditionally)
    best, best_cost, best_tier = None, None, None
    for fac in factorizations(n_devices, len(names)):
        split = dict(zip(names, fac))
        if any(dims[a] % split[a] != 0 for a in names):
            continue
        tier = 1 if (keep_x and split["x"] > 1) else 0
        total = np.prod(list(dims.values()))
        cost = 0.0
        for a in names:
            if split[a] > 1:
                cost += (total / dims[a]) * split[a]  # halo area per axis
        if best_cost is None or (tier, cost) < (best_tier, best_cost):
            best, best_cost, best_tier = split, cost, tier
    if best is None:
        raise ValueError(
            f"cannot decompose shape {tuple(shape)} over {n_devices} devices")
    # cache a frozen snapshot; choose_decomposition hands out fresh dicts
    return tuple((a, best[a]) for a in names)


def make_mesh(shape: Sequence[int], devices: Optional[list] = None,
              decomposition: Optional[dict[str, int]] = None) -> Mesh:
    """Build a Mesh with axes named after the lattice dims they split."""
    devices = devices if devices is not None else jax.devices()
    names = AXIS_NAMES_2D if len(shape) == 2 else AXIS_NAMES_3D
    if decomposition is None:
        decomposition = choose_decomposition(shape, len(devices))
    mesh_shape = tuple(decomposition[a] for a in names)
    dev_array = np.asarray(devices).reshape(mesh_shape)
    return Mesh(dev_array, names)


def field_spec(mesh: Mesh) -> P:
    """PartitionSpec for the (n_storage, *shape) field stack."""
    return P(None, *mesh.axis_names)


def flag_spec(mesh: Mesh) -> P:
    return P(*mesh.axis_names)


def shard_state(state, params, mesh: Mesh):
    """Place a LatticeState/SimParams pair onto the mesh."""
    fs = NamedSharding(mesh, field_spec(mesh))
    gs = NamedSharding(mesh, flag_spec(mesh))
    rep = NamedSharding(mesh, P())
    state = state.replace(
        fields=jax.device_put(state.fields, fs),
        flags=jax.device_put(state.flags, gs),
        globals_=jax.device_put(state.globals_, rep),
        iteration=jax.device_put(state.iteration, rep),
    )
    params = params.replace(
        settings=jax.device_put(params.settings, rep),
        zone_table=jax.device_put(params.zone_table, rep),
        time_series=None if params.time_series is None
        else jax.device_put(params.time_series, rep),
    )
    return state, params


def decomposition_overhead(shape: Sequence[int], decomposition: dict[str, int]
                           ) -> float:
    """The reference prints ``max_subdomain*ranks/total - 1`` at startup
    (src/Solver.cpp.Rt:347-352); with our divisor constraint splits are even,
    so this reports the halo-to-volume ratio instead."""
    names = AXIS_NAMES_2D if len(shape) == 2 else AXIS_NAMES_3D
    dims = dict(zip(names, shape))
    local = {a: dims[a] // decomposition[a] for a in names}
    vol = float(np.prod(list(local.values())))
    halo = 0.0
    for a in names:
        if decomposition[a] > 1:
            halo += 2.0 * vol / local[a]
    return halo / vol
