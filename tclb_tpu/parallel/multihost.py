"""Multi-host initialization: the TPU-pod counterpart of the reference's
MPI startup (reference src/main.cpp.Rt:178-216: MPI_Init, rank/size, node
table, per-rank GPU binding).

On TPU pods each host runs one identical process; ``jax.distributed``
wires them into a single JAX runtime whose ``jax.devices()`` spans ALL
chips, global-view arrays shard transparently, and the halo ``ppermute``s
ride ICI within a slice / DCN across slices.  Nothing else in the
framework changes — the mesh in :mod:`tclb_tpu.parallel.mesh` simply gets
more devices, which is the whole point of designing against
``jax.sharding`` instead of translating the reference's per-rank MPI
bookkeeping.
"""

from __future__ import annotations

from typing import Optional


def initialize_distributed(spec: Optional[str] = "auto") -> None:
    """Initialize the multi-host runtime.

    ``spec``:
    * ``"auto"`` / ``None`` — rely on the environment (TPU pod metadata,
      or the ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
      ``JAX_PROCESS_ID`` variables a launcher sets);
    * ``"host:port,num_processes,process_id"`` — explicit wiring, the
      moral equivalent of an mpirun rank file.

    Must run before any other JAX API initializes the backend.
    """
    import jax

    if spec in (None, "", "auto"):
        jax.distributed.initialize()
        return
    parts = spec.split(",")
    if len(parts) != 3:
        raise ValueError(
            "distributed spec must be 'auto' or "
            "'coordinator:port,num_processes,process_id'")
    jax.distributed.initialize(
        coordinator_address=parts[0],
        num_processes=int(parts[1]),
        process_id=int(parts[2]))


def is_main_process() -> bool:
    """True on the process that should own rank-0 duties (file output,
    console logging) — the reference's ``InitPrint`` root filter."""
    import jax
    return jax.process_index() == 0
