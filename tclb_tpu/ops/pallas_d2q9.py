"""Pallas fused collide-stream kernel for the d2q9 model family.

This is the TPU equivalent of the reference's tuned CUDA hot loop
(reference src/LatticeContainer.inc.cpp.Rt:247-266 ``RunKernel`` and
src/cuda.cu.Rt:236-274 ``RunElement``): one kernel performs pull-streaming,
boundary handling and MRT collision in a single pass, reading each density
once from HBM and writing it once — the 1R+1W-per-density traffic model the
reference prints as GB/s (src/main.cpp.Rt:126).

Design (TPU-first, not a CUDA translation):

* the lattice is tiled into row bands of ``BY`` rows; each grid step DMAs its
  band plus one wrapped halo row above and below from HBM into VMEM scratch
  (the reference instead splits storage into 27 margin blocks — here the halo
  is re-read from the neighbouring band, a 2/BY traffic overhead);
* pull-streaming is static slicing in y (the halo rows make ``y ± 1`` local)
  and a lane-roll in x (``pltpu.roll`` — x is the lane dimension and stays
  whole, exactly like the reference keeps x unsplit for coalescing,
  src/Solver.cpp.Rt:274);
* per-node ``switch (NodeType)`` dispatch is mask/select algebra on an int32
  copy of the flag field (branchless, VPU-friendly);
* the 9x9 MRT moment transforms are unrolled sparse multiply-adds on the VPU
  (the matrices are ±small-integer constants; an MXU matmul would waste a
  128x128 systolic pass on a 9-vector);
* scalar Settings ride in SMEM; zonal Settings (Velocity/Density) are
  pre-gathered into per-node planes outside the kernel (they are constant
  across an ``Iterate`` call — the reference reads them per node from const
  memory through the zone bits, src/LatticeContainer.h.Rt:89-108).

This path is the reference's "NoGlobals" kernel specialization
(src/cuda.cu.Rt Globals-mode template parameter): per-iteration Globals are
not accumulated; ``state.globals_`` is zeroed.  Use the XLA path when
objectives/monitors are needed per step.

The physics here intentionally mirrors ``models/d2q9.py`` op for op;
``tests/test_pallas.py`` pins the two paths together.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tclb_tpu.core.lattice import LatticeState, SimParams
from tclb_tpu.core.registry import Model
from tclb_tpu.ops.lbm import equilibrium, present_types  # noqa: F401

_VMEM_SCRATCH_BUDGET = 4 * 1024 * 1024  # bytes for the band scratch


def _band_rows(model: Model, ny: int, nx: int) -> Optional[int]:
    """Largest band height BY that divides ny, is a multiple of 8 (f32
    sublane tile) and keeps the (n_storage, BY+2, nx) scratch in budget."""
    import os
    override = os.environ.get("TCLB_PALLAS_BY")
    if override:
        by = int(override)
        # the override must satisfy the same alignment/budget contract the
        # kernel's DMA offsets are built on, or Mosaic miscompiles
        if (by % 8 == 0 and ny % by == 0
                and model.n_storage * (by + 2) * nx * 4
                <= _VMEM_SCRATCH_BUDGET * 2):
            return by
    best = None
    for by in range(8, ny + 1, 8):
        if ny % by:
            continue
        if model.n_storage * (by + 2) * nx * 4 > _VMEM_SCRATCH_BUDGET:
            break
        best = by
    return best


def _fused_band(by: int, ny: int, nx: int) -> int:
    """Band height of the temporally-fused kernel (its VMEM working set
    holds two full intermediate stacks, so the band is capped lower than
    the single-step kernel's).  The cap scales inversely with the row
    width so the fused working set stays at the level measured safe on
    v5e: 48 rows at nx=1024 (beats the old 32 by ~14% on the karman
    1024x100 geometry — fewer bands, less 16-halo-row DMA amplification
    — and ~2% at 1024^2; 56+ shows no further gain and crowds the
    scoped-VMEM budget), halving for each doubling of nx."""
    cap = max(8, min(48, ((64 * 1024 // max(nx, 1) - 16) // 8) * 8))
    by2 = by
    while by2 > 8 and (ny % by2 or by2 > cap):
        by2 -= 8
    return by2


def _pad_rows(model: Model, ny: int, nx: int) -> Optional[int]:
    """Ghost-row padding lifting the ny % 8 (sublane tile) restriction.

    The kernel's DMA offsets need row counts that are multiples of 8; a
    lattice like the reference's karman.xml (1024x100) is padded with
    >= 4 ghost rows.  The first two ghost rows mirror physical rows 0,1
    and the last two mirror rows ny-2,ny-1 — refreshed before every
    kernel call — so the kernel's internal wrap over the padded height
    reproduces the EXACT periodic pull of the physical height (reach
    <= 2 for the fused two-step kernel).  Middle ghost rows (pad > 4)
    are never read by any physical row: they get static Wall flags and
    evolve freely (any garbage is confined — physical rows pull only
    from the refreshed mirror rows).

    The padded height is CHOSEN for band efficiency, not minimality: an
    8-row band pays 8+16 halo rows of DMA per 8 computed (3x read
    amplification), so padding further to reach a richer divisor (100 ->
    120 with 24-row fused bands) is a net traffic win.  Returns the pad
    (0 for already-aligned heights), or None if no candidate fits."""
    if ny % 8 == 0 and _band_rows(model, ny, nx) is not None:
        return 0
    lo = ny + 4 if ny % 8 else ny + 8   # aligned heights without a valid
    best, best_score = None, None       # band still pad (rare: tiny VMEM)
    for ny_pad in range(((lo + 7) // 8) * 8, 2 * ny + 64, 8):
        by = _band_rows(model, ny_pad, nx)
        if by is None:
            continue
        by2 = _fused_band(by, ny_pad, nx)
        score = ny_pad * (1.0 + (by2 + 16.0) / by2)
        if best_score is None or score < best_score:
            best, best_score = ny_pad - ny, score
        if ny_pad >= ny + 64 and best is not None:
            break   # diminishing returns; keep the search bounded
    return best


# family models whose collision the kernel implements via per-model
# branches (same pattern as ops/pallas_d3q.py); d2q9 itself keeps its
# hand-tuned MRT path with the BC coupling planes
_FAMILY_2D = ("d2q9_SRT", "d2q9_les", "d2q9_inc", "d2q9_cumulant",
              "d2q9_new")


def supports(model: Model, shape, dtype) -> bool:
    """Whether the fused kernel can run this configuration.

    ``d2q9`` plus the pure-f family models whose collisions the kernel
    implements as dedicated branches (``_FAMILY_2D`` — including
    d2q9_new's raw-moment/LES/entropic collision, which shares
    models.d2q9_new.collision_core with the XLA path)."""
    if model.name == "d2q9":
        pass
    elif model.name in _FAMILY_2D and model.n_storage == 9:
        pass
    else:
        return False
    if len(shape) != 2 or dtype != jnp.float32:
        return False
    ny, nx = shape
    if ny < 8:
        return False
    if jax.default_backend() == "tpu" and nx % 128:
        return False  # x is the lane dimension; keep it tile-aligned
    return _pad_rows(model, ny, nx) is not None


def _sparse_matvec(mat: np.ndarray, planes: list) -> list:
    """y = mat @ planes, unrolled over the (static, mostly-zero) matrix.
    ``planes`` entries may be None (= identically-zero plane, skipped)."""
    out = []
    for row in mat:
        acc = None
        for c, p in zip(row, planes):
            c = float(c)
            if c == 0.0 or p is None:
                continue
            t = p if c == 1.0 else (-p if c == -1.0 else c * p)
            acc = t if acc is None else acc + t
        out.append(acc if acc is not None else jnp.zeros_like(
            next(p for p in planes if p is not None)))
    return out


def gather_zonal_planes(model: Model, params, zones, dtype):
    """Per-node (velocity, density) planes from the zonal tables — the
    kernels' static per-call inputs.  Models without a Density setting
    (d2q9_new) parameterize the boundary density via zonal Pressure,
    rho = 1 + 3 p."""
    si = model.setting_index
    vel = params.zone_table[si["Velocity"]].astype(dtype)[zones]
    if "Density" in si:
        den = params.zone_table[si["Density"]].astype(dtype)[zones]
    else:
        den = 1.0 + 3.0 * \
            params.zone_table[si["Pressure"]].astype(dtype)[zones]
    return vel, den


def supports_resident(model: Model, shape, dtype) -> bool:
    """Whether the VMEM-resident multi-step kernel can run this
    configuration: the whole lattice (two ping-pong stacks + statics)
    must fit the on-chip budget.  Small-ny domains like the reference's
    karman.xml (1024x100) qualify — the band kernels there pay 16 halo
    rows of DMA per band, while the resident kernel streams the state
    from HBM once per FUSE_R steps."""
    if not supports(model, shape, dtype):
        return False
    ny, nx = (int(s) for s in shape)
    # input block + out block (doubles as the second ping-pong buffer) +
    # one scratch stack + 3 static planes; per-chunk temporaries live in
    # the scoped budget like the band kernels'
    if 3 * model.n_storage * ny * nx * 4 + 3 * ny * nx * 4 \
            > 15 * 1024 * 1024:
        return False
    return True


_RESIDENT_FUSE = 8   # lattice steps per kernel invocation (MUST be even:
#                      the in-kernel ping-pong ends in the out block)


def make_resident_iterate(model: Model, shape, dtype=jnp.float32,
                          interpret: Optional[bool] = None,
                          present: Optional[set] = None):
    """VMEM-resident engine for small domains: ONE kernel invocation runs
    ``_RESIDENT_FUSE`` lattice steps on the whole lattice held in VMEM
    (ping-pong stacks), so HBM traffic per step drops to (1R+1W)/FUSE_R
    and the periodic wrap is exact row arithmetic — no ghost padding, no
    halo DMA, any ny.  This is the deep temporal fusion the band kernels
    cannot do (their VMEM only holds a band); the reference has no
    analogue (its GPU has no software-managed on-chip tier).

    Same NoGlobals + no-Control contract as the band kernels."""
    if not supports_resident(model, shape, dtype):
        raise ValueError(f"resident kernel unsupported: {model.name} "
                         f"{shape}")
    ny, nx = (int(s) for s in shape)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # borrow the band builder's per-model physics closure (_lbm_step):
    # one source of in-kernel physics for both engines
    step_ctx = _make_step_ctx(model, present)
    _lbm_step, bc_idx, n_storage = (step_ctx["step"], step_ctx["bc_idx"],
                                    model.n_storage)
    # row chunks bound the per-chunk temporaries like the band kernels'
    # fused bands do
    chunk = ny
    while chunk > 56:
        chunk = (chunk + 1) // 2
    bounds = list(range(0, ny, chunk)) + [ny]

    def _circ_rows(ref_or_val, k, lo, hi):
        """Rows [lo, hi) of plane ``k`` with periodic wrap (static
        indices; at most one end wraps for multi-chunk layouts)."""
        src = ref_or_val
        if lo >= 0 and hi <= ny:
            return src[k, lo:hi, :]
        parts = []
        if lo < 0:
            parts.append(src[k, ny + lo:ny, :])
            lo = 0
        mid_hi = min(hi, ny)
        parts.append(src[k, lo:mid_hi, :])
        if hi > ny:
            parts.append(src[k, 0:hi - ny, :])
        return jnp.concatenate(parts, axis=0)

    def kernel(sett, f_ref, flags_ref, vel_ref, den_ref, out_ref,
               bufa):
        flags = flags_ref[:]
        vel = vel_ref[:]
        den = den_ref[:]

        def one_step(src, dst):
            """src -> dst (refs); BC planes copied through."""
            for c0, c1 in zip(bounds[:-1], bounds[1:]):
                pulled = []
                for k in range(9):
                    dx, dy = int(E_[k, 0]), int(E_[k, 1])
                    ext = _circ_rows(src, k, c0 - dy, c1 - dy)
                    pulled.append(pltpu.roll(ext, dx % nx, axis=1)
                                  if dx else ext)
                f = jnp.stack(pulled)
                bc0 = src[bc_idx[0], c0:c1, :] if bc_idx else 0.0
                bc1 = src[bc_idx[1], c0:c1, :] if bc_idx else 0.0
                fnew = _lbm_step(f, flags[c0:c1], vel[c0:c1], den[c0:c1],
                                 bc0, bc1, sett)
                for k in range(9):
                    dst[k, c0:c1, :] = fnew[k]
            for k in range(9, n_storage):
                dst[k] = src[k]

        # ping-pong between the scratch stack and the OUT block (saves a
        # whole-lattice buffer); _RESIDENT_FUSE is even, so the final
        # step lands in out_ref
        one_step(f_ref, bufa)
        src, dst = bufa, out_ref
        for _ in range(_RESIDENT_FUSE - 1):
            one_step(src, dst)
            src, dst = dst, src

    # velocity set for the pull slices (the registry's streaming vectors
    # ARE the model's E for the 9 f planes)
    E_ = model.ei[:9, :2]

    call = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_storage, ny, nx), dtype),
        scratch_shapes=[
            pltpu.VMEM((n_storage, ny, nx), dtype),
        ],
        interpret=interpret,
    )

    zshift = model.zone_shift

    @partial(jax.jit, static_argnames=("niter",), donate_argnums=0)
    def _iterate_jit(state: LatticeState, params: SimParams, niter: int
                     ) -> LatticeState:
        flags_i32 = state.flags.astype(jnp.int32)
        zones = flags_i32 >> zshift
        vel, den = gather_zonal_planes(model, params, zones, dtype)
        sett = params.settings.astype(dtype)

        def body(fields, _):
            return call(sett, fields, flags_i32, vel, den), None

        fields, _ = jax.lax.scan(body, state.fields, None,
                                 length=niter // _RESIDENT_FUSE)
        # remainder steps on the band path would need its ghost padding;
        # run them as additional resident calls is impossible (fuse is
        # baked in), so delegate the tail to the caller via the band
        # engine — the Lattice hybrid only ever calls with large niter,
        # and the fuse divides it after the -1 hybrid split rarely; keep
        # exactness by running the remainder through the single-step
        # band kernel of make_pallas_iterate when needed
        return LatticeState(
            fields=fields,
            flags=state.flags,
            globals_=jnp.zeros_like(state.globals_),
            iteration=state.iteration + (niter // _RESIDENT_FUSE)
            * _RESIDENT_FUSE,
        )

    band = make_pallas_iterate(model, shape, dtype, interpret=interpret,
                               fuse=1, present=present)

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        if params.time_series is not None:
            raise ValueError(
                "pallas iterate does not support Control time series; "
                "use the XLA path for time-dependent zonal settings")
        main = (niter // _RESIDENT_FUSE) * _RESIDENT_FUSE
        state = _iterate_jit(state, params, main)
        rest = niter - main
        if rest:
            state = band(state, params, rest)
        return state

    return iterate


def _make_step_ctx(model: Model, present=None):
    """Per-model physics closures (the band builder's _lbm_step + BC
    plane indices), extracted for the resident kernel to share — one
    source of in-kernel physics for both engines."""
    return make_pallas_iterate(model, (8, 256), jnp.float32,
                               interpret=True, fuse=1, present=present,
                               _want_step_ctx=True)


def make_pallas_iterate(model: Model, shape, dtype=jnp.float32,
                        interpret: Optional[bool] = None,
                        fuse: int = 1,
                        present: Optional[set] = None,
                        ext_halo: bool = False,
                        _want_step_ctx: bool = False):
    """Build ``iterate(state, params, niter) -> state`` running the fused
    Pallas collide-stream kernel.  Caller must check :func:`supports` first.

    ``fuse=2`` runs TWO lattice steps per kernel band pass (halving the
    HBM traffic per step); an odd trailing step falls back to the single-
    step kernel.

    ``present`` restricts which boundary node types are materialized
    (every case is full-band compute-then-select, so skipping absent
    types is pure win); parity holds whenever it is a superset of the
    types actually painted — :func:`present_types` computes that set.

    ``ext_halo=True`` builds the SHARDED building block instead: the
    domain is one device's block of a y-sharded lattice, the input field
    stack carries 8 exchanged halo rows at each end ((ns, ny+16, nx)),
    and the kernels read halos from those rows instead of wrapping
    periodically.  Returns ``(call1, call2, by, by2)`` raw band calls for
    :mod:`tclb_tpu.parallel.halo` to compose with ``ppermute`` (the
    reference's equivalent composition is RunBorder/MPIStream/RunInterior,
    src/Lattice.cu.Rt:424-456)."""
    from tclb_tpu.models import d2q9 as mod
    from tclb_tpu.models import d2q9_inc as inc_mod
    from tclb_tpu.models import d2q9_new as new_mod
    from tclb_tpu.models import family
    from tclb_tpu.ops import cumulant
    from tclb_tpu.ops import lbm as lbm_mod

    if not supports(model, shape, dtype):
        raise ValueError(f"pallas path unsupported for {model.name} {shape}")
    if fuse not in (1, 2):
        raise ValueError(f"fuse={fuse}: only 1 (single-step) and 2 "
                         "(temporally-fused pair) kernels exist")
    ny_phys, nx = (int(s) for s in shape)
    if ext_halo:
        if ny_phys % 8:
            raise ValueError("ext_halo blocks need ny % 8 == 0")
        pad = 0
    else:
        pad = _pad_rows(model, ny_phys, nx)
        if pad is None:
            raise ValueError(f"no valid band height for shape {shape}")
    ny = ny_phys + pad
    by = _band_rows(model, ny, nx)
    by2 = _fused_band(by, ny, nx)
    assert ny % by2 == 0   # _band_rows guarantees multiple-of-8 divisors
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    is_d2q9 = model.name == "d2q9"
    if is_d2q9:
        E, W, OPP, M = mod.E, mod.W, mod.OPP, mod.M
        norm = (M * M).sum(axis=1)
        Minv = (M / norm[:, None]).T
        bc_idx = list(model.groups["BC"])
    else:
        E = model.ei[:9, :2]
        W = lbm_mod.weights(E)
        OPP = lbm_mod.opposite(E)
        bc_idx = None
    n_storage = model.n_storage
    f_idx = list(model.groups["f"])
    assert f_idx == list(range(9)), "kernel assumes f planes lead the stack"

    si = model.setting_index
    i_gx = si.get("GravitationX")
    i_gy = si.get("GravitationY")
    coll_mask = int(model.group_masks["COLLISION"])
    nt = {n: (int(t.mask), int(t.value)) for n, t in model.node_types.items()}
    present = set(nt) if present is None else set(present)

    def _is(flags, name):
        mask, val = nt[name]
        return (flags & jnp.int32(mask)) == jnp.int32(val)

    def _apply_family_boundaries(f, flags, vel, den):
        """Mask-dispatch family.boundary_cases, skipping absent types —
        the identical closures the XLA path applies (same contract as
        ops/pallas_d3q.py)."""
        cases = family.boundary_cases(model, E, W, OPP, vel, den)
        return family.dispatch_boundary_cases(
            cases, f, lambda n: _is(flags, n), present)

    def _zouhe_boundaries(f, flags, vel, den):
        """d2q9-style explicit boundary list (models/d2q9.run order),
        shared by the d2q9 and d2q9_new branches; absent node types
        (``present``) are skipped entirely — each case is a full-band
        compute, so this mirrors the reference's compile-time
        specialization on the model's boundary set."""
        def apply(mask, new, cur):
            return jnp.where(mask[None], new, cur)

        def mask_of(*names):
            names = [n for n in names if n in present and n in nt]
            if not names:
                return None
            m = _is(flags, names[0])
            for n in names[1:]:
                m = m | _is(flags, n)
            return m

        ws = mask_of("Wall", "Solid")
        if ws is not None:
            f = apply(ws, jnp.stack([f[int(OPP[k])] for k in range(9)]), f)
        for name, plane, kind, side in (
                ("EVelocity", vel, "velocity", "E"),
                ("WPressure", den, "pressure", "W"),
                ("WVelocity", vel, "velocity", "W"),
                ("EPressure", den, "pressure", "E")):
            if name in present and name in nt:
                f = apply(_is(flags, name),
                          mod._zou_he_x(f, plane, kind, side), f)
        if "TopSymmetry" in present and "TopSymmetry" in nt:
            f = apply(_is(flags, "TopSymmetry"),
                      mod._symmetry(f, top=True), f)
        if "BottomSymmetry" in present and "BottomSymmetry" in nt:
            f = apply(_is(flags, "BottomSymmetry"),
                      mod._symmetry(f, top=False), f)
        return f

    def _lbm_step_d2q9(f, flags, vel, den, bc0, bc1, sett):
        """One collide step on an arbitrary row band: d2q9-style boundary
        dispatch, then the MRT collision (mirrors
        models.d2q9._collision_mrt, sans globals)."""
        i_s3, i_s4 = si["S3"], si["S4"]
        i_s56, i_s78 = si["S56"], si["S78"]
        f = _zouhe_boundaries(f, flags, vel, den)

        rho = sum(f[k] for k in range(9))
        ux = sum(float(E[k, 0]) * f[k] for k in range(9) if E[k, 0]) / rho
        uy = sum(float(E[k, 1]) * f[k] for k in range(9) if E[k, 1]) / rho
        s3, s4 = sett[i_s3], sett[i_s4]
        s56, s78 = sett[i_s56], sett[i_s78]
        feq = equilibrium(E, W, rho, (ux, uy))
        fneq = [f[k] - feq[k] for k in range(9)]
        # moment rates: rows 0-2 (density/momentum) relax at rate 0, so
        # their moments need not be computed and their Minv columns drop
        # out — exact, the conserved moments never enter the update
        rates = [s3, s4, s56, s56, s78, s78]
        mn = _sparse_matvec(M[3:], fneq)
        m_neq = [None, None, None] + [m * o for m, o in zip(mn, rates)]
        ux2 = ux + sett[i_gx] + bc0
        uy2 = uy + sett[i_gy] + bc1
        feq2 = equilibrium(E, W, rho, (ux2, uy2))
        # Minv @ (m_neq + M @ feq2) == Minv @ m_neq + feq2 — one matvec
        # saved vs the naive moment-space form (exact algebra, not an
        # approximation)
        relax = _sparse_matvec(Minv, m_neq)
        coll = [r + q for r, q in zip(relax, feq2)]
        mrt = _is(flags, "MRT")
        return jnp.stack([jnp.where(mrt, coll[k], f[k]) for k in range(9)])

    def _lbm_step_family(f, flags, vel, den, bc0, bc1, sett):
        """Family-model collide step: shared boundary dispatch + the
        model's own collision, op-for-op the XLA model code (minus
        globals) — BGK (d2q9_SRT), Smagorinsky (d2q9_les, in-kernel
        unrolled |Pi|), He-Luo incompressible (d2q9_inc), central-moment
        cumulant (d2q9_cumulant via ops/cumulant.py)."""
        if model.name == "d2q9_new":
            # d2q9-style explicit Zou-He list (the model's own run(),
            # models/d2q9_new.py), then the shared raw-moment collision
            # core — one source of physics for both engines
            f = _zouhe_boundaries(f, flags, vel, den)
            fc = new_mod.collision_core(
                f, sett[si["omega"]], sett[si["Smag"]],
                _is(flags, "Smagorinsky"), _is(flags, "Stab"))
            mrt = _is(flags, "MRT")
            return jnp.where(mrt[None], fc, f)
        f = _apply_family_boundaries(f, flags, vel, den)
        coll = (flags & jnp.int32(coll_mask)) != jnp.int32(0)
        gx, gy = sett[i_gx], sett[i_gy]
        if model.name == "d2q9_cumulant":
            F = f.reshape((3, 3) + f.shape[1:])
            Fp, _, _ = cumulant.collide_d2q9(
                F, sett[si["omega"]], sett[si["omega_bulk"]],
                force=(gx, gy))
            fc = Fp.reshape(f.shape)
        elif model.name == "d2q9_inc":
            rho = jnp.sum(f, axis=0)
            ux = sum(float(E[k, 0]) * f[k] for k in range(9)
                     if E[k, 0]) / inc_mod.RHO0
            uy = sum(float(E[k, 1]) * f[k] for k in range(9)
                     if E[k, 1]) / inc_mod.RHO0
            feq = inc_mod._inc_equilibrium(rho, ux, uy)
            fc = f + sett[si["omega"]] * (feq - f)
            fc = fc + (inc_mod._inc_equilibrium(rho, ux + gx, uy + gy)
                       - feq)
        else:   # d2q9_SRT / d2q9_les
            rho = jnp.sum(f, axis=0)
            ux = sum(float(E[k, 0]) * f[k] for k in range(9)
                     if E[k, 0]) / rho
            uy = sum(float(E[k, 1]) * f[k] for k in range(9)
                     if E[k, 1]) / rho
            feq = equilibrium(E, W, rho, (ux, uy))
            if model.name == "d2q9_les":
                om = lbm_mod.smagorinsky_omega_unrolled(
                    E, f, feq, rho, sett[si["omega"]], sett[si["Smag"]])
            else:
                om = sett[si["omega"]]
            fc = f + om * (feq - f)
            fc = fc + (equilibrium(E, W, rho, (ux + gx, uy + gy)) - feq)
        return jnp.where(coll[None], fc, f)

    _lbm_step = _lbm_step_d2q9 if is_d2q9 else _lbm_step_family
    if _want_step_ctx:
        # the resident kernel borrows the per-model physics closure
        return {"step": _lbm_step, "bc_idx": bc_idx}

    def kernel(sett, f_hbm, flags_ref, vel_ref, den_ref, out_ref,
               buf2, sems):
        # One CONTIGUOUS scratch buffer of by+16 rows per slot: the band
        # lands at rows [8, 8+by), its 8-row halo blocks at [0, 8) and
        # [8+by, 16+by) — all three DMA destinations are (8, 128)-tile
        # aligned, and every pull below is a single SLICE of the buffer
        # (rows 7..7+by for y-1, 9..9+by for y+1) instead of the former
        # per-plane concatenate of halo and band pieces (pure VPU copies,
        # round-2 VERDICT Weak #2's named suspect).  Double-slotted: band
        # i+1's DMA is issued before band i's compute, overlapping HBM
        # fetch with VPU work across grid steps (the reference gets the
        # same overlap from its border/interior kernel split + async
        # memcpy streams, src/Lattice.cu.Rt:424-456).
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            base = pl.multiple_of(band * jnp.int32(by), 8)
            if ext_halo:
                # input rows are [halo(8) | local ny | halo(8)]: the band
                # lives at base+8, its halos at base and base+8+by —
                # no wrap, the exchanged rows ARE the neighbors
                mid8 = pl.multiple_of(base + jnp.int32(8), 8)
                top8 = base
                bot8 = pl.multiple_of(base + jnp.int32(8 + by), 8)
            else:
                mid8 = base
                top8 = pl.multiple_of(
                    jax.lax.rem(base - jnp.int32(8) + jnp.int32(ny),
                                jnp.int32(ny)), 8)
                bot8 = pl.multiple_of(
                    jax.lax.rem(base + jnp.int32(by), jnp.int32(ny)), 8)
            return (
                pltpu.make_async_copy(f_hbm.at[:, pl.ds(mid8, by), :],
                                      buf2.at[slot, :, pl.ds(8, by), :],
                                      sems.at[slot, 0]),
                pltpu.make_async_copy(f_hbm.at[:, pl.ds(top8, 8), :],
                                      buf2.at[slot, :, pl.ds(0, 8), :],
                                      sems.at[slot, 1]),
                pltpu.make_async_copy(f_hbm.at[:, pl.ds(bot8, 8), :],
                                      buf2.at[slot, :, pl.ds(8 + by, 8), :],
                                      sems.at[slot, 2]),
            )

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for d in band_dmas(jnp.int32(0), i):
                d.start()

        @pl.when(i + 1 < n)
        def _():
            for d in band_dmas(nxt, i + jnp.int32(1)):
                d.start()

        for d in band_dmas(slot, i):
            d.wait()

        def mid(k):
            return buf2[slot, k, 8:8 + by, :]

        # pull-streaming: f_i(x) <- f_i(x - e_i); halo rows make y +- 1 a
        # plain row-shifted slice, lane-roll covers the periodic x wrap
        # (matches core.lattice.pull_stream)
        pulled = []
        for k in range(9):
            dx, dy = int(E[k, 0]), int(E[k, 1])
            sl = buf2[slot, k, 8 - dy:8 - dy + by, :]
            pulled.append(pltpu.roll(sl, dx % nx, axis=1) if dx else sl)
        f = jnp.stack(pulled)
        bc0 = mid(bc_idx[0]) if bc_idx else 0.0
        bc1 = mid(bc_idx[1]) if bc_idx else 0.0
        fnew = _lbm_step(f, flags_ref[:], vel_ref[:], den_ref[:],
                         bc0, bc1, sett)
        for k in range(9):
            out_ref[k] = fnew[k]
        if bc_idx:
            out_ref[bc_idx[0]] = bc0
            out_ref[bc_idx[1]] = bc1

    def kernel2(sett, f_hbm, aux_hbm, out_ref, buff, bufa, sems):
        """Temporally-fused kernel: TWO collide-stream steps per band pass
        (the esoteric-twist-style traffic saving flagged in SURVEY §7's
        hard parts — each density is read/written once per TWO steps).
        Step 1 runs on an extended band of by+2 rows so step 2's pull has
        valid neighbours; the 8-row aligned halo blocks already cover the
        2-row reach.  ``aux_hbm`` stacks (flags-as-f32, Velocity, Density)
        so the statics ride the same contiguous-buffer DMA scheme (flag
        values < 2^16 are exact in f32).  Like kernel, the band+halos land
        in ONE contiguous (by2+16)-row buffer so extended-row access is a
        single slice, not a concatenate."""
        i = pl.program_id(0)
        base = pl.multiple_of(i * jnp.int32(by2), 8)
        if ext_halo:
            mid8 = pl.multiple_of(base + jnp.int32(8), 8)
            top8 = base
            bot8 = pl.multiple_of(base + jnp.int32(8 + by2), 8)
        else:
            mid8 = base
            top8 = pl.multiple_of(
                jax.lax.rem(base - jnp.int32(8) + jnp.int32(ny),
                            jnp.int32(ny)), 8)
            bot8 = pl.multiple_of(
                jax.lax.rem(base + jnp.int32(by2), jnp.int32(ny)), 8)
        dmas = (
            pltpu.make_async_copy(f_hbm.at[:, pl.ds(mid8, by2), :],
                                  buff.at[:, pl.ds(8, by2), :], sems.at[0]),
            pltpu.make_async_copy(f_hbm.at[:, pl.ds(top8, 8), :],
                                  buff.at[:, pl.ds(0, 8), :], sems.at[1]),
            pltpu.make_async_copy(f_hbm.at[:, pl.ds(bot8, 8), :],
                                  buff.at[:, pl.ds(8 + by2, 8), :],
                                  sems.at[2]),
            pltpu.make_async_copy(aux_hbm.at[:, pl.ds(mid8, by2), :],
                                  bufa.at[:, pl.ds(8, by2), :], sems.at[3]),
            pltpu.make_async_copy(aux_hbm.at[:, pl.ds(top8, 8), :],
                                  bufa.at[:, pl.ds(0, 8), :], sems.at[4]),
            pltpu.make_async_copy(aux_hbm.at[:, pl.ds(bot8, 8), :],
                                  bufa.at[:, pl.ds(8 + by2, 8), :],
                                  sems.at[5]),
        )
        for d in dmas:
            d.start()
        for d in dmas:
            d.wait()

        def ext(buf, k, lo, hi):
            """Rows [lo, hi) of the band-extended plane k (band row 0 is
            buffer row 8) — a single slice of the contiguous buffer."""
            return buf[k, 8 + lo:8 + hi, :]

        # ---- step 1 on rows [-1, by+1) ---------------------------------- #
        pulled = []
        for k in range(9):
            dx, dy = int(E[k, 0]), int(E[k, 1])
            sl = ext(buff, k, -1 - dy, by2 + 1 - dy)
            pulled.append(pltpu.roll(sl, dx % nx, axis=1) if dx else sl)
        f = jnp.stack(pulled)
        flags_e = ext(bufa, 0, -1, by2 + 1).astype(jnp.int32)
        vel_e = ext(bufa, 1, -1, by2 + 1)
        den_e = ext(bufa, 2, -1, by2 + 1)
        bc0_e = ext(buff, bc_idx[0], -1, by2 + 1) if bc_idx else 0.0
        bc1_e = ext(buff, bc_idx[1], -1, by2 + 1) if bc_idx else 0.0
        f1 = _lbm_step(f, flags_e, vel_e, den_e, bc0_e, bc1_e, sett)

        # ---- step 2 on rows [0, by) ------------------------------------- #
        pulled = []
        for k in range(9):
            dx, dy = int(E[k, 0]), int(E[k, 1])
            sl = f1[k, 1 - dy:1 - dy + by2, :]
            pulled.append(pltpu.roll(sl, dx % nx, axis=1) if dx else sl)
        f = jnp.stack(pulled)
        f2 = _lbm_step(f, flags_e[1:by2 + 1], vel_e[1:by2 + 1],
                       den_e[1:by2 + 1],
                       bc0_e[1:by2 + 1] if bc_idx else 0.0,
                       bc1_e[1:by2 + 1] if bc_idx else 0.0,
                       sett)
        for k in range(9):
            out_ref[k] = f2[k]
        if bc_idx:
            out_ref[bc_idx[0]] = ext(buff, bc_idx[0], 0, by2)
            out_ref[bc_idx[1]] = ext(buff, bc_idx[1], 0, by2)

    grid2 = (ny // by2,)
    call2 = pl.pallas_call(
        kernel2,
        grid=grid2,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((n_storage, by2, nx), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_storage, ny, nx), dtype),
        scratch_shapes=[
            pltpu.VMEM((n_storage, by2 + 16, nx), dtype),
            pltpu.VMEM((3, by2 + 16, nx), dtype),
            pltpu.SemaphoreType.DMA((6,)),
        ],
        interpret=interpret,
    )

    call = pl.pallas_call(
        kernel,
        grid=(ny // by,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((by, nx), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((by, nx), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((by, nx), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n_storage, by, nx), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_storage, ny, nx), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, n_storage, by + 16, nx), dtype),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )

    if ext_halo:
        return call, call2, by, by2

    zshift = model.zone_shift

    @partial(jax.jit, static_argnames=("niter", "fuse"), donate_argnums=0)
    def _iterate_jit(state: LatticeState, params: SimParams, niter: int,
                     fuse: int = 1) -> LatticeState:
        flags_i32 = state.flags.astype(jnp.int32)
        fields = state.fields
        if pad:
            # ghost layout: [mirror 0, mirror 1, walls..., mirror ny-2,
            # mirror ny-1]; middle ghosts are Wall nodes (bounce-back in
            # place — unconditionally stable, and never read by physical
            # rows)
            init_src = jnp.asarray(np.array(
                [0, 1] + [0] * (pad - 4) + [ny_phys - 2, ny_phys - 1]))
            gflags = flags_i32[init_src]
            if pad > 4:
                wall = jnp.int32(model.flag_for("Wall"))
                gflags = gflags.at[2:pad - 2].set(wall)
            flags_i32 = jnp.concatenate([flags_i32, gflags], axis=0)
            fields = jnp.concatenate([fields, fields[:, init_src, :]],
                                     axis=1)
        zones = flags_i32 >> zshift
        vel, den = gather_zonal_planes(model, params, zones, dtype)
        sett = params.settings.astype(dtype)

        def refresh(fields):
            if not pad:
                return fields
            f = fields.at[:, ny_phys:ny_phys + 2, :].set(fields[:, 0:2, :])
            return f.at[:, ny - 2:, :].set(
                fields[:, ny_phys - 2:ny_phys, :])

        if fuse == 2:
            aux = jnp.stack([flags_i32.astype(dtype), vel, den])

            def body2(fields, _):
                return call2(sett, refresh(fields), aux), None

            fields, _ = jax.lax.scan(body2, fields, None,
                                     length=niter // 2)
        rest = niter % 2 if fuse == 2 else niter

        def body(fields, _):
            return call(sett, refresh(fields), flags_i32, vel, den), None

        fields, _ = jax.lax.scan(body, fields, None, length=rest)
        if pad:
            fields = fields[:, :ny_phys, :]
        return LatticeState(
            fields=fields,
            flags=state.flags,
            globals_=jnp.zeros_like(state.globals_),
            iteration=state.iteration + niter,
        )

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        # the kernel freezes zonal Velocity/Density planes for the whole
        # call; a <Control> time series changes them per iteration, which
        # only the XLA path implements (NodeCtx.setting) — reject rather
        # than silently diverge
        if params.time_series is not None:
            raise ValueError(
                "pallas iterate does not support Control time series; "
                "use the XLA path for time-dependent zonal settings")
        return _iterate_jit(state, params, niter, fuse=fuse)

    return iterate
