"""Registry-driven Pallas engine: ANY 2D model's own physics in the fused
collide-stream kernel.

This is the round-4 answer to the reference's defining property: its code
generator emits a tuned device kernel for EVERY model
(reference src/cuda.cu.Rt:81-283 ``RunKernel`` templated over the model's
``Node_Run``, src/LatticeContainer.inc.cpp.Rt:247-266), so no model pays an
interpreted-path tax.  Here the same guarantee comes from tracing instead of
generation: the model's registered stage functions (the SAME ``run(ctx)``
callables the XLA engine traces — one source of physics, automatic parity)
are traced INSIDE a Pallas band kernel against a band-local
:class:`KernelCtx`, and the registry metadata drives everything the
generator would have emitted:

* per-plane streaming vectors (``model.ei``) become static row-slices of the
  band buffer + lane rolls (pull scheme);
* declared Field stencils (``Field.dy_range``) bound the in-band halo reach,
  exactly like the reference's ``stencil2d`` bounds its margins
  (src/conf.R:134);
* multi-stage actions (e.g. d2q9_kuper's Run + CalcPhi) run back-to-back in
  one band pass on progressively-shrinking row extensions, so multi-stage
  models stream their state from HBM ONCE per iteration;
* zonal settings are pre-gathered into per-node planes that ride the aux
  DMA (the reference reads them per node through the flag's zone bits,
  src/LatticeContainer.h.Rt:89-108);
* the ``present`` node-type set specializes the trace on the painted
  boundary types (reference compile-time kernel zoo specialization).

Eligibility is capability-probed, not allowlisted: :func:`supports` traces
one band-kernel call abstractly (which rejects models whose code captures
constant arrays or uses untraceable ops) and the Lattice engine compile-
probes the result on TPU, falling back to the XLA path when Mosaic cannot
lower an op (e.g. ``arccos``).  The hand-tuned d2q9-family kernels
(ops/pallas_d2q9.py) keep priority for the 9-plane models they cover.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tclb_tpu.core import shift as ddf
from tclb_tpu.core.lattice import (LatticeState, NodeCtx, SimParams,
                                   series_dt_overrides, series_overrides)
from tclb_tpu.core.registry import Model
from tclb_tpu.ops import fusion
from tclb_tpu.ops.lbm import present_types  # noqa: F401  (re-export)

# jax < 0.5 names the Pallas TPU params dataclass TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

_VMEM_SCRATCH_BUDGET = 4 * 1024 * 1024
_HALO = 8   # DMA halo block height: one (8, 128) f32 tile per side
HALO = _HALO  # public: max per-action reach a caller can plan against

# storage dtypes the generic engines can keep in HBM.  Compute is ALWAYS
# f32: field planes are widened right after the VMEM read and narrowed
# on the output write, and the aux stack (flags + zonal planes) stays
# f32 outright — bf16's 8 mantissa bits cannot represent uint16 flag
# values exactly.  At f32 storage the casts are traced no-ops, so the
# bit-parity contract with the XLA path is untouched; bf16 runs are
# validated by the error-vs-f32 harness (tclb_tpu/precision.py), not by
# bit-parity.  analysis/precision.py keys its unsafe-accumulation scan
# on this marker.
STORAGE_DTYPES = (jnp.float32, jnp.bfloat16)
_COMPUTE_DTYPE = jnp.float32


def _storage_ok(dtype) -> bool:
    return jnp.dtype(dtype) in {jnp.dtype(d) for d in STORAGE_DTYPES}


# --------------------------------------------------------------------------- #
# Registry-derived stage plan
# --------------------------------------------------------------------------- #


def _stage_reach(model: Model, stage_name: str) -> int:
    """Band-axis reach of one stage's reads: pull distance of streamed
    densities (when the stage streams) and the declared Field stencil
    extents, along the banded axis (y rows in 2D, z slabs in 3D).
    x-reach is free (lane rolls wrap the whole row), and in 3D the whole
    (ny, nx) plane rides the band so y is free too."""
    stage = model.stages[stage_name]
    r = 0
    if model.ndim == 2:
        if stage.load_densities:
            r = max((abs(int(d.dy)) for d in model.densities), default=0)
        for f in model.fields:
            r = max(r, abs(f.dy_range[0]), abs(f.dy_range[1]))
    else:
        if stage.load_densities:
            r = max((abs(int(d.dz)) for d in model.densities), default=0)
        for f in model.fields:
            r = max(r, abs(f.dz_range[0]), abs(f.dz_range[1]))
    return r


def action_plan(model: Model, action: str = "Iteration", fuse: int = 1
                ) -> tuple[list[tuple[str, int]], int]:
    """Execution plan for ``fuse`` repetitions of an action: a list of
    (stage_name, out_ext) in execution order, plus the input halo width R.

    ``out_ext`` is how many EXTRA rows beyond the output band the stage
    must compute so that every later stage's reads stay within valid
    rows; R is the extension the very first stage's reads need of the
    input.  (The reference never needs this arithmetic: each CUDA stage
    is a separate global kernel launch.  Fusing the whole action into one
    band pass is the TPU-side traffic win — state is read once per
    iteration, not once per stage.)"""
    names = list(model.actions[action]) * fuse
    plan: list[tuple[str, int]] = [("", 0)] * len(names)
    ext = 0
    for i in range(len(names) - 1, -1, -1):
        plan[i] = (names[i], ext)
        ext += _stage_reach(model, names[i])
    return plan, ext


def choose_fuse(model: Model, fmax: int = fusion.FUSE_MAX) -> int:
    """Fusion depth for the 2D band engine: the deepest fuse whose
    fused-plan reach still fits the fixed 8-row DMA halo.  The halo
    (and so the per-call HBM traffic) is constant in fuse, so the win
    is linear — K steps amortize one band round trip."""
    return fusion.choose_fuse_band(
        lambda f: action_plan(model, "Iteration", fuse=f)[1], _HALO, fmax)


# --------------------------------------------------------------------------- #
# Band sizing / ghost-row padding (generalized from ops/pallas_d2q9.py)
# --------------------------------------------------------------------------- #


_DEFAULT_BY_CAP = 32


def _band_rows(model: Model, ny: int, nx: int,
               by_cap: Optional[int] = None,
               itemsize: int = 4) -> Optional[int]:
    """Largest multiple-of-8 band height dividing ny whose scratch
    (state + aux stacks, band + two 8-row halo blocks) fits the budget.

    ``by_cap`` bounds the band height: the model's traced physics holds
    its live temporaries in scoped VMEM, which the band sizing cannot see
    — the default cap keeps typical models inside the budget and the
    Lattice's first-call probe retries with a halved cap when a complex
    model still overflows (Mosaic's scoped-vmem limit error)."""
    # Budget against the LARGEST kernel flavor (the Control-series
    # variant carries value + _DT planes per zonal setting): all flavors
    # of one engine share `by` (the padded height and grid must agree),
    # and a series run attaching mid-process reuses the cached build cfg
    # WITHOUT a compile probe — an overflow there would escape the
    # fallback ladder.  Costs at most one `by` notch on zonal-heavy
    # models vs budgeting the plain flavor only.
    n_aux = 1 + 2 * len(model.zonal_settings)
    # field planes scale with the storage itemsize; the aux stack is
    # always f32 (flags must survive the float round trip exactly)
    per_row = (model.n_storage * itemsize + n_aux * 4) * nx
    cap = _DEFAULT_BY_CAP if by_cap is None else by_cap
    best = None
    for by in range(8, min(ny, cap) + 1, 8):
        if ny % by:
            continue
        if 2 * (by + 2 * _HALO) * per_row > _VMEM_SCRATCH_BUDGET * 2:
            break
        best = by
    return best


def _pad_rows(model: Model, ny: int, nx: int, mirror: int,
              by_cap: Optional[int] = None,
              itemsize: int = 4) -> Optional[int]:
    """Ghost-row padding lifting ny % 8, generalized to mirror width
    ``mirror`` (= the plan's total reach): the first/last ``mirror`` ghost
    rows replicate the physical edge rows so the kernel's periodic wrap
    over the padded height reproduces the exact periodic pull of the
    physical height (same scheme as ops/pallas_d2q9._pad_rows, reach
    parameterized).  Returns pad rows (0 if aligned), None if impossible."""
    if ny % 8 == 0 and _band_rows(model, ny, nx, by_cap,
                                  itemsize) is not None:
        return 0
    lo = ny + 2 * mirror
    best, best_score = None, None
    for ny_pad in range(((lo + 7) // 8) * 8, 2 * ny + 64, 8):
        by = _band_rows(model, ny_pad, nx, by_cap, itemsize)
        if by is None:
            continue
        score = ny_pad * (1.0 + 2.0 * _HALO / by)
        if best_score is None or score < best_score:
            best, best_score = ny_pad - ny, score
        if ny_pad >= ny + 64 and best is not None:
            break
    return best


# --------------------------------------------------------------------------- #
# Band-local NodeCtx
# --------------------------------------------------------------------------- #


class _DtypeShim:
    """Stands in for the full field stack in ``ctx._fields.dtype`` uses."""

    def __init__(self, dtype):
        self.dtype = dtype


def assemble_aux(params, zones, flags_f, base_planes, zonal_si, it, dtype,
                 with_dt: bool):
    """The aux plane stack: flags + per-node zonal-setting planes, with
    any registered <Control> series overrides applied at iteration ``it``
    (+ the per-iteration ``_DT`` planes when ``with_dt``).  ONE
    implementation shared by the 2D/3D generic engines and the
    differentiable step — the override scalars come from the same
    series_overrides/series_dt_overrides the XLA NodeCtx uses, so the
    engines cannot drift."""
    has = params.time_series is not None
    planes = [flags_f]
    for j, k in enumerate(zonal_si):
        p = base_planes[j]
        if has:
            for z, v in series_overrides(params, k, it):
                p = jnp.where(zones == z, v.astype(dtype), p)
        planes.append(p)
    if with_dt:
        for k in zonal_si:
            p = jnp.zeros_like(flags_f)
            if has:
                for z, v in series_dt_overrides(params, k, it):
                    p = jnp.where(zones == z, v.astype(dtype), p)
            planes.append(p)
    return jnp.stack(planes)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _roll_prim(x, s, nx):
    return pltpu.roll(x, s, axis=1)


def _roll_fwd(x, s, nx):
    return _roll_prim(x, s, nx), None


def _roll_bwd(s, nx, _res, ct):
    # roll is linear: out[i] = x[i - s], so the transpose is the
    # opposite roll (the adjoint band kernel differentiates through the
    # streaming slices; pltpu.roll itself has no AD rule)
    return (_roll_prim(ct, (nx - s) % nx, nx),)


_roll_prim.defvjp(_roll_fwd, _roll_bwd)


def _lane_roll(sl, shift, nx):
    s = shift % nx
    return _roll_prim(sl, s, nx) if s else sl


def run_action_plan(model: Model, plan, work: list, flags_full, zonal_full,
                    dt_full, sett, it0, nt_present, halo: int, nx: int,
                    dtype, n_per_rep: int, collect_globals: bool = False,
                    extra: int = 0, full_band: bool = False):
    """Execute ``plan``'s stages over band-buffer VALUE arrays (2D).

    ``work`` is one ``(H, nx)`` array per storage plane with the output
    band at rows ``[halo, H - halo)``; the list is updated in place so
    later stages read earlier stages' writes.  ``extra`` widens every
    stage's output window by that many rows: the adjoint band kernel
    (ops/pallas_adjoint) computes the action on a band extended by the
    plan's total reach so the VJP dependency cone of the band rows is
    fully covered; the forward kernel uses ``extra=0``.

    Returns ``(work, g_planes, g_last_planes)`` where ``g_planes`` maps
    each Global's name to its ``(by + 2*extra, nx)`` contribution plane
    over the extended output window (stages with larger extents are
    trimmed to the window — rows beyond it lie outside the band's
    dependency cone) summed over ALL fused repetitions, and
    ``g_last_planes`` holds the LAST repetition's contributions only
    (the last-iteration globals the per-step engines report).

    ``full_band=True`` computes EVERY stage over the whole (tile-aligned)
    buffer height instead of progressively-shrinking windows: the pull
    becomes a sublane roll (whose wrap lands garbage only in the outermost
    rows, which stay within the ``halo`` margin callers discard), stage
    updates replace whole planes (no row-concats), and every op keeps the
    aligned ``(H, nx)`` shape — much friendlier Mosaic tiling.  Globals
    planes then come back full-height and the CALLER must mask rows
    outside its valid window.

    This is THE collide semantics of the 2D generic engine — the forward
    band kernel and the adjoint's in-band chain both trace it, so the
    two can never drift apart.
    """
    ns = model.n_storage
    ei = model.ei
    by = work[0].shape[0] - 2 * halo
    n_reps = max(len(plan) // max(n_per_rep, 1), 1)
    g_acc: dict = {}
    g_last: dict = {}
    for st_i, (stage_name, out_ext) in enumerate(plan):
        stage = model.stages[stage_name]
        fn = model.stage_fns[stage.main]
        eff = halo if full_band else out_ext + extra
        n_i = by + 2 * eff
        lo = halo - eff                # first row of this stage's window
        rep = st_i // n_per_rep        # fused action repetition index

        if stage.load_densities:
            planes = []
            for k in range(ns):
                dxk, dyk = int(ei[k, 0]), int(ei[k, 1])
                if full_band:
                    sl = jnp.roll(work[k], dyk, axis=0) if dyk else work[k]
                else:
                    sl = work[k][lo - dyk:lo - dyk + n_i, :]
                planes.append(_lane_roll(sl, dxk, nx))
        else:
            planes = [w[lo:lo + n_i, :] for w in work]

        if full_band:
            def loader(index, dx, dy, dz=0):
                assert dz == 0, "2D band kernel: no z loads"
                sl = work[index]
                if dy:
                    sl = jnp.roll(sl, -dy, axis=0)
                return _lane_roll(sl, -dx, nx)
        else:
            def loader(index, dx, dy, dz=0, _lo=lo, _n=n_i):
                assert dz == 0, "2D band kernel: no z loads"
                sl = work[index][_lo + dy:_lo + dy + _n, :]
                return _lane_roll(sl, -dx, nx)

        ctx = KernelCtx(
            model, planes, loader,
            flags_full[lo:lo + n_i, :],
            {nm: p[lo:lo + n_i, :] for nm, p in zonal_full.items()},
            sett, dtype, it0 + rep, nt_present,
            dt_planes={nm: p[lo:lo + n_i, :] for nm, p in dt_full.items()},
            compute_globals=collect_globals)
        res = fn(ctx)
        if collect_globals:
            # SUM Globals accumulate across the action's stages, trimmed
            # to the output window (rows beyond it belong to other bands
            # or lie outside the band's dependency cone); in full_band
            # mode the caller masks invalid rows instead
            for nm, plane in ctx._globals.items():
                if not full_band:
                    plane = plane[out_ext:out_ext + by + 2 * extra, :]
                g_acc[nm] = plane if nm not in g_acc else g_acc[nm] + plane
                if rep == n_reps - 1:
                    # last-repetition-only accumulation: the chunked diff
                    # step reports these as state.globals_ so the final
                    # state matches the per-step engines' last-iteration
                    # semantics (the chunk SUM would be ~k-fold inflated)
                    g_last[nm] = plane if nm not in g_last \
                        else g_last[nm] + plane

        if isinstance(res, dict):
            updates: dict[int, jnp.ndarray] = {}
            for name, stack in res.items():
                if name in model.groups:
                    idx = model.groups[name]
                    if len(idx) == 1 and stack.ndim == 2:
                        updates[idx[0]] = stack
                    else:
                        for j, k in enumerate(idx):
                            updates[k] = stack[j]
                else:
                    updates[model.storage_index[name]] = stack
        else:
            updates = {k: res[k] for k in range(ns)}
        for k, new in updates.items():
            if full_band:
                work[k] = new
            else:
                w = work[k]
                work[k] = jnp.concatenate([w[:lo], new, w[lo + n_i:]],
                                          axis=0)
    return work, g_acc, g_last


class KernelCtx(NodeCtx):
    """A :class:`NodeCtx` whose world is one VMEM row band.

    The model's stage function cannot tell the difference: ``group`` /
    ``density`` return the streamed band planes, ``load`` reaches into the
    band's halo rows, zonal ``setting``s are pre-gathered planes, node-type
    tests run on the band's flag rows.  (The reference's ``Node_Run`` object
    plays this role per thread; here it's per band.)"""

    def __init__(self, model: Model, planes: list, loader: Callable,
                 flags_i32, zonal: dict, sett, dtype,
                 iteration, present: Optional[set],
                 dt_planes: Optional[dict] = None,
                 compute_globals: bool = False):
        # deliberately NOT calling NodeCtx.__init__: the band context has
        # list-of-planes storage and SMEM-backed settings
        self.model = model
        self._planes = planes          # streamed view, one 2D array per plane
        self._loader_fn = loader       # load(index, dx, dy) on the RAW band
        self.flags = flags_i32
        self._zonal = zonal            # zonal setting name -> band plane
        self._dt = dt_planes or {}     # zonal setting name -> d/dt band plane
        self._sett = sett              # SMEM settings ref/array
        self._fields = _DtypeShim(dtype)
        self.iteration = iteration
        self.avg_start = 0
        self._globals: dict = {}
        self.present = present
        self.compute_globals = compute_globals

    # -- field access -------------------------------------------------- #

    def group(self, name: str) -> jnp.ndarray:
        idx = self.model.groups[name]
        return jnp.stack([self._planes[i] for i in idx])

    def density(self, name: str) -> jnp.ndarray:
        return self._planes[self.model.storage_index[name]]

    def load(self, name: str, dx: int = 0, dy: int = 0, dz: int = 0
             ) -> jnp.ndarray:
        return self._loader_fn(self.model.storage_index[name], dx, dy, dz)

    # -- settings ------------------------------------------------------ #

    def setting(self, name: str) -> jnp.ndarray:
        m = self.model
        i = m.setting_index[name]
        if m.settings[i].zonal:
            return self._zonal[name]
        return self._sett[i]

    def setting_dt(self, name: str) -> jnp.ndarray:
        # the series-aware kernel flavor carries per-iteration _DT planes
        # in its aux stack; without a Control series every derivative is
        # identically zero
        if name in self._dt:
            return self._dt[name]
        return jnp.zeros_like(self._planes[0])

    # -- node types ---------------------------------------------------- #

    def nt_is(self, name: str) -> jnp.ndarray:
        t = self.model.node_types[name]
        return (self.flags & jnp.int32(t.mask)) == jnp.int32(t.value)

    def nt_in_group(self, group: str) -> jnp.ndarray:
        m = self.model.group_masks[group]
        return (self.flags & jnp.int32(m)) != jnp.int32(0)


# --------------------------------------------------------------------------- #
# Eligibility
# --------------------------------------------------------------------------- #

_probe_cache: dict = {}
_mosaic_verdict: dict = {}
_cfg_cache: dict = {}


def mosaic_ok(model: Model, shape) -> bool:
    """Process-wide memo of whether this model/shape's kernel survived
    Mosaic lowering on TPU (unknown counts as OK — the Lattice's
    first-call probe settles it).  Keyed per shape: a VMEM overflow at a
    huge nx must not disable the engine for small lattices."""
    return _mosaic_verdict.get((model.name, tuple(shape)), True)


def set_mosaic_ok(model: Model, shape, ok: bool) -> None:
    _mosaic_verdict[(model.name, tuple(shape))] = ok


def get_build_cfg(model: Model, shape) -> Optional[tuple]:
    """(fuse, by_cap) that survived this model/shape's scoped-VMEM
    pressure on a previous build (None = untested; default config)."""
    return _cfg_cache.get((model.name, tuple(shape)))


def set_build_cfg(model: Model, shape, fuse: int,
                  by_cap: Optional[int]) -> None:
    _cfg_cache[(model.name, tuple(shape))] = (fuse, by_cap)


def supports(model: Model, shape, dtype, probe: bool = True) -> bool:
    """Whether the generic band kernel can run this model/shape.

    Structural checks from the registry, then (``probe=True``) an abstract
    trace of one band-kernel call — the capability test that replaces the
    old per-model name allowlist.  Mosaic lowering failures (TPU compile)
    are caught later by the Lattice's compile probe."""
    if model.ndim == 3:
        return supports_3d(model, shape, dtype, probe=probe)
    if model.ndim != 2 or len(shape) != 2 or not _storage_ok(dtype):
        return False
    if "Iteration" not in model.actions:
        return False
    for s in model.actions["Iteration"]:
        st = model.stages.get(s)
        if st is None or st.fixed_point or model.stage_fns.get(st.main) is None:
            return False
    plan, reach = action_plan(model, "Iteration", fuse=1)
    if reach > _HALO:
        return False
    ny, nx = (int(v) for v in shape)
    itemsize = jnp.dtype(dtype).itemsize
    if ny < 8:
        return False
    if jax.default_backend() == "tpu" and nx % 128:
        return False
    if _pad_rows(model, ny, nx, max(reach, 1), itemsize=itemsize) is None:
        return False
    if not probe:
        return True
    key = (model.name, nx, itemsize)
    if key not in _probe_cache:
        try:
            iterate = make_pallas_iterate(model, (8 if ny % 8 else min(ny, 64),
                                                  nx), dtype, interpret=True)
            state = LatticeState(
                fields=jax.ShapeDtypeStruct(
                    (model.n_storage, 8 if ny % 8 else min(ny, 64), nx), dtype),
                flags=jax.ShapeDtypeStruct(
                    (8 if ny % 8 else min(ny, 64), nx), jnp.uint16),
                globals_=jax.ShapeDtypeStruct((model.n_globals,), dtype),
                iteration=jax.ShapeDtypeStruct((), jnp.int32))
            params = SimParams(
                settings=jax.ShapeDtypeStruct((len(model.settings),), dtype),
                zone_table=jax.ShapeDtypeStruct(
                    (len(model.settings), model.zone_max), dtype))
            jax.eval_shape(partial(iterate, niter=2), state, params)
            _probe_cache[key] = True
        except Exception as e:  # noqa: BLE001 — any trace failure = ineligible
            from tclb_tpu.utils import log
            log.debug(f"pallas_generic: {model.name} trace probe failed: "
                      f"{type(e).__name__}: {str(e)[:200]}")
            _probe_cache[key] = False
    return _probe_cache[key]


# --------------------------------------------------------------------------- #
# Kernel builder
# --------------------------------------------------------------------------- #


def make_pallas_iterate(model: Model, shape, dtype=jnp.float32,
                        interpret: Optional[bool] = None,
                        fuse: int = 1,
                        present: Optional[set] = None,
                        ext_halo: bool = False,
                        by_cap: Optional[int] = None,
                        full_band: Optional[bool] = None,
                        shift: Optional[np.ndarray] = None):
    """Build ``iterate(state, params, niter) -> state`` running the model's
    full Iteration action as one fused Pallas band kernel per step.

    ``ext_halo=True`` builds the sharded building block instead (the
    domain is one device's y-block carrying 8 exchanged halo rows at each
    end); returns ``(call, by, zonal_names)`` for
    :mod:`tclb_tpu.parallel.halo` to compose with ``ppermute``."""
    if model.ndim == 3:
        if ext_halo:
            raise ValueError("3d generic engine has no ext_halo mode")
        return make_pallas_iterate_3d(model, shape, dtype,
                                      interpret=interpret, present=present,
                                      fuse=fuse, by_cap=by_cap,
                                      shift=shift)
    if not supports(model, shape, dtype, probe=False):
        raise ValueError(f"pallas_generic unsupported: {model.name} {shape}")
    cdtype = _COMPUTE_DTYPE
    itemsize = jnp.dtype(dtype).itemsize
    if ext_halo and jnp.dtype(dtype) != jnp.dtype(cdtype):
        raise ValueError("ext_halo (sharded) blocks are f32-only")
    plan, reach = action_plan(model, "Iteration", fuse=fuse)
    if reach > _HALO:
        raise ValueError(f"fuse={fuse} needs reach {reach} > halo {_HALO}")
    mirror = max(reach, 1)
    ny_phys, nx = (int(s) for s in shape)
    if ext_halo:
        if ny_phys % 8:
            raise ValueError("ext_halo blocks need ny % 8 == 0")
        pad = 0
    else:
        pad = _pad_rows(model, ny_phys, nx, mirror, by_cap, itemsize)
        if pad is None:
            raise ValueError(f"no valid band height for {shape}")
    ny = ny_phys + pad
    by = _band_rows(model, ny, nx, by_cap, itemsize)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    n_storage = model.n_storage
    # per-plane DDF shift at the DMA seams (None = raw: pure astype, so
    # the f32/raw path traces bit-identically to the pre-shift kernel)
    _shifts = ([None] * n_storage if shift is None
               else [float(w) or None for w in shift])
    zonal_names = list(model.zonal_settings)
    zshift = model.zone_shift
    zone_max = model.zone_max
    si = model.setting_index
    zonal_si = [si[nm] for nm in zonal_names]
    # aux diet: the non-series flavors DMA ONLY the flag plane — zonal
    # settings are iteration-invariant there, a pure function of the
    # flag zone bits, so they are reconstructed in-kernel from the SMEM
    # zone table (fusion.zone_plane) instead of riding every HBM round
    # trip as full planes.  Series flavors keep the full aux stack (the
    # per-iteration _DT overrides genuinely change per step).
    lean_aux = len(zonal_names) > 0
    nt_present = set(model.node_types) if present is None else set(present)
    if pad > 2 * mirror:
        nt_present = nt_present | {"Wall"}   # middle ghost rows are walls
    if full_band is None:
        import os
        full_band = os.environ.get("TCLB_FULLBAND", "0") == "1"

    def _mk_kernel(plan, with_dt=False, with_globals=False, lean=False):
        """Kernel flavor factory: ``with_dt`` adds per-iteration _DT
        planes to the aux stack (the Control-series flavor), and
        ``with_globals`` accumulates the model's SUM Globals in-kernel
        into an extra (8, 128) partial-sums output (the reference's
        in-kernel Globals accumulation, src/cuda.cu.Rt:176-202);
        ``with_globals="split"`` emits a (2, 8, 128) block instead —
        [0] the whole fused chunk's sums (the objective increment), [1]
        the LAST repetition's only (last-iteration globals semantics,
        used by the chunked diff step).  ``lean`` is the aux-diet
        flavor: an extra SMEM zone-table input, flags-only aux stack,
        zonal planes rebuilt in-kernel."""
        def kern(sett, it_ref, *rest):
            if lean:
                ztab, f_hbm, aux_hbm, *refs = rest
            else:
                ztab = None
                f_hbm, aux_hbm, *refs = rest
            if with_globals:
                out_ref, g_ref, buff, bufa, sems = refs
            else:
                (out_ref, buff, bufa, sems), g_ref = refs, None
            kernel(plan, with_dt, with_globals, ztab, sett, it_ref, f_hbm,
                   aux_hbm, out_ref, g_ref, buff, bufa, sems)
        return kern

    def kernel(plan, with_dt, with_globals, ztab, sett, it_ref, f_hbm,
               aux_hbm, out_ref, g_ref, buff, bufa, sems):
        """One band pass = the whole Iteration action (x fuse).  The band
        plus 8-row halo blocks land in ONE contiguous (by+16)-row buffer
        per stack, so every extended-row access below is a single slice;
        double-slotted: band i+1's DMA is issued before band i's compute,
        overlapping HBM fetch with VPU work across grid steps (same scheme
        as ops/pallas_d2q9.kernel — the reference gets the overlap from
        its border/interior split + async memcpy streams,
        src/Lattice.cu.Rt:424-456)."""
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            base = pl.multiple_of(band * jnp.int32(by), 8)
            if ext_halo:
                mid8 = pl.multiple_of(base + jnp.int32(_HALO), 8)
                top8 = base
                bot8 = pl.multiple_of(base + jnp.int32(_HALO + by), 8)
            else:
                mid8 = base
                top8 = pl.multiple_of(
                    jax.lax.rem(base - jnp.int32(_HALO) + jnp.int32(ny),
                                jnp.int32(ny)), 8)
                bot8 = pl.multiple_of(
                    jax.lax.rem(base + jnp.int32(by), jnp.int32(ny)), 8)
            return (
                pltpu.make_async_copy(f_hbm.at[:, pl.ds(mid8, by), :],
                                      buff.at[slot, :, pl.ds(_HALO, by), :],
                                      sems.at[slot, 0]),
                pltpu.make_async_copy(f_hbm.at[:, pl.ds(top8, _HALO), :],
                                      buff.at[slot, :, pl.ds(0, _HALO), :],
                                      sems.at[slot, 1]),
                pltpu.make_async_copy(
                    f_hbm.at[:, pl.ds(bot8, _HALO), :],
                    buff.at[slot, :, pl.ds(_HALO + by, _HALO), :],
                    sems.at[slot, 2]),
                pltpu.make_async_copy(aux_hbm.at[:, pl.ds(mid8, by), :],
                                      bufa.at[slot, :, pl.ds(_HALO, by), :],
                                      sems.at[slot, 3]),
                pltpu.make_async_copy(aux_hbm.at[:, pl.ds(top8, _HALO), :],
                                      bufa.at[slot, :, pl.ds(0, _HALO), :],
                                      sems.at[slot, 4]),
                pltpu.make_async_copy(
                    aux_hbm.at[:, pl.ds(bot8, _HALO), :],
                    bufa.at[slot, :, pl.ds(_HALO + by, _HALO), :],
                    sems.at[slot, 5]),
            )

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for d in band_dmas(jnp.int32(0), i):
                d.start()

        @pl.when(i + 1 < n)
        def _():
            for d in band_dmas(nxt, i + jnp.int32(1)):
                d.start()

        for d in band_dmas(slot, i):
            d.wait()

        # working stack: one (by+16, nx) array per plane; band row 0 is
        # buffer row _HALO.  Stages update their stored planes in place
        # (functionally — row-concat), later stages read the updates.
        # Planes are widened to the compute dtype at the read (a traced
        # no-op at f32 storage) and narrowed on the output write — the
        # whole fused action accumulates in f32.
        work = [ddf.widen_plane(buff[slot, k], cdtype, _shifts[k])
                for k in range(n_storage)]
        flags_full = bufa[slot, 0].astype(jnp.int32)
        if ztab is not None:
            zones_full = flags_full >> zshift
            zonal_full = {nm: fusion.zone_plane(ztab, j, zone_max,
                                                zones_full)
                          for j, nm in enumerate(zonal_names)}
            dt_full = {}
        else:
            zonal_full = {nm: bufa[slot, 1 + j]
                          for j, nm in enumerate(zonal_names)}
            dt_full = {nm: bufa[slot, 1 + len(zonal_names) + j]
                       for j, nm in enumerate(zonal_names)} \
                if with_dt else {}

        work, g_acc, g_last = run_action_plan(
            model, plan, work, flags_full, zonal_full, dt_full, sett,
            it_ref[0], nt_present, _HALO, nx, cdtype,
            n_per_rep=len(model.actions["Iteration"]),
            collect_globals=g_ref is not None, full_band=full_band)

        for k in range(n_storage):
            out_ref[k] = ddf.narrow_plane(work[k][_HALO:_HALO + by, :],
                                          dtype, _shifts[k])

        if g_ref is not None:
            split = with_globals == "split"

            @pl.when(i == 0)
            def _():
                g_ref[...] = jnp.zeros((2, 8, 128) if split else (8, 128),
                                       cdtype)
            if pad:
                # ghost rows must not contribute (mirror rows would
                # double-count, wall rows are unphysical)
                rows = jax.lax.broadcasted_iota(jnp.int32, (by, nx), 0) \
                    + i * jnp.int32(by)
                gmask = (rows < jnp.int32(ny_phys)).astype(cdtype)
            for blk, acc in enumerate((g_acc, g_last) if split
                                      else (g_acc,)):
                for gi, g in enumerate(model.globals_):
                    if g.name not in acc:
                        continue
                    plane = acc[g.name]
                    if full_band:
                        plane = plane[_HALO:_HALO + by, :]
                    if pad:
                        plane = plane * gmask
                    part = plane.reshape((by * (nx // 128),
                                          128)).sum(axis=0)
                    if split:
                        g_ref[blk, gi] = g_ref[blk, gi] + part
                    else:
                        g_ref[gi] = g_ref[gi] + part

    grid = (ny // by,)

    def _mk_call(plan_n, with_dt=False, with_globals=False, lean=False):
        n_aux_k = 1 if lean \
            else 1 + (2 if with_dt else 1) * len(zonal_names)
        out_specs = pl.BlockSpec((n_storage, by, nx), lambda i: (0, i, 0),
                                 memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((n_storage, ny, nx), dtype)
        if with_globals:
            gshape = (2, 8, 128) if with_globals == "split" else (8, 128)
            out_specs = [out_specs,
                         pl.BlockSpec(gshape,
                                      (lambda i: (0, 0, 0))
                                      if with_globals == "split"
                                      else (lambda i: (0, 0)),
                                      memory_space=pltpu.VMEM)]
            out_shape = [out_shape, jax.ShapeDtypeStruct(gshape, cdtype)]
        import os
        vmem_mb = int(os.environ.get("TCLB_VMEM_LIMIT_MB", "0"))
        return pl.pallas_call(
            _mk_kernel(plan_n, with_dt, with_globals, lean),
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ] + ([pl.BlockSpec(memory_space=pltpu.SMEM)] if lean else [])
            + [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((2, n_storage, by + 2 * _HALO, nx), dtype),
                pltpu.VMEM((2, n_aux_k, by + 2 * _HALO, nx), cdtype),
                pltpu.SemaphoreType.DMA((2, 6)),
            ],
            compiler_params=_CompilerParams(
                vmem_limit_bytes=vmem_mb * 1024 * 1024)
            if vmem_mb else None,
            interpret=interpret,
        )

    if ext_halo:
        # the sharded building block keeps the full-aux convention: the
        # halo composer assembles + exchanges aux planes host-side
        return _mk_call(plan), by, zonal_names

    call = _mk_call(plan, lean=lean_aux)
    plan1 = plan if fuse == 1 \
        else action_plan(model, "Iteration", fuse=1)[0]
    call1 = call if fuse == 1 else _mk_call(plan1, lean=lean_aux)
    # in-kernel globals flavor (final step of an iterate call): SUM only —
    # MAX would need max-combining across bands/stages (no model uses MAX)
    can_globals = (nx % 128 == 0
                   and model.n_globals <= 8   # the (8, 128) partials block
                   and all(g.op == "SUM" for g in model.globals_))
    call_g = _mk_call(plan1, with_globals=True, lean=lean_aux) \
        if can_globals and model.n_globals else None
    # Control-series flavors: per-iteration zonal + _DT planes, fuse=1
    # (fused steps would reuse iteration t's settings at t+1)
    call_s = _mk_call(plan1, with_dt=True)
    call_sg = _mk_call(plan1, with_dt=True, with_globals=True) \
        if can_globals and model.n_globals else None
    # one action rep advances the iteration counter iff any stage streams
    adv = int(any(model.stages[s].load_densities
                  for s in model.actions["Iteration"]))

    @partial(jax.jit, static_argnames=("niter",), donate_argnums=0)
    def _iterate_jit(state: LatticeState, params: SimParams, niter: int
                     ) -> LatticeState:
        flags_i32 = state.flags.astype(jnp.int32)
        fields = state.fields.astype(dtype)
        if pad:
            # ghost layout: [mirror rows 0..m-1, walls, mirror ny-m..ny-1]
            mid = pad - 2 * mirror
            init_src = jnp.asarray(np.array(
                list(range(mirror)) + [0] * mid
                + list(range(ny_phys - mirror, ny_phys))))
            gflags = flags_i32[init_src]
            if mid:
                wall = jnp.int32(model.flag_for("Wall"))
                gflags = gflags.at[mirror:mirror + mid].set(wall)
            flags_i32 = jnp.concatenate([flags_i32, gflags], axis=0)
            fields = jnp.concatenate([fields, fields[:, init_src, :]],
                                     axis=1)
        zones = flags_i32 >> zshift
        sett = params.settings.astype(cdtype)
        has_series = params.time_series is not None

        # loop-invariant pieces (XLA hoists them out of the step scan):
        # the base zonal planes and the affected-zone masks.  Per step
        # only scalar masked selects remain — a zone-table re-gather
        # inside the scan is ~25 ms/step at 1024^2 (unhoistable gather)
        flags_f = flags_i32.astype(cdtype)
        base_planes = [params.zone_table[k].astype(cdtype)[zones]
                       for k in zonal_si]

        def aux_of(it):
            return assemble_aux(params, zones, flags_f, base_planes,
                                zonal_si, it, cdtype, with_dt=has_series)

        def refresh(fields):
            if not pad:
                return fields
            f = fields.at[:, ny_phys:ny_phys + mirror, :].set(
                fields[:, 0:mirror, :])
            return f.at[:, ny - mirror:, :].set(
                fields[:, ny_phys - mirror:ny_phys, :])

        final_g = call_sg if has_series else call_g
        if niter <= 0:
            return state
        main = niter - (1 if final_g is not None else 0)

        if has_series:
            def body_s(carry, _):
                fields, it = carry
                out = call_s(sett, it[None], refresh(fields), aux_of(it))
                return (out, it + adv), None

            (fields, it), _ = jax.lax.scan(
                body_s, (fields, state.iteration), None, length=main)
        else:
            if lean_aux:
                # aux diet: the DMA'd aux stack is the flag plane alone;
                # the zone table rides in SMEM and the kernel rebuilds
                # the (iteration-invariant) zonal planes itself
                ztab = jnp.concatenate(
                    [params.zone_table[k].astype(cdtype) for k in zonal_si])
                aux = flags_f[None]

                def invoke(c, it, fields):
                    return c(sett, it[None], ztab, refresh(fields), aux)
            else:
                aux = aux_of(state.iteration)

                def invoke(c, it, fields):
                    return c(sett, it[None], refresh(fields), aux)

            def body(carry, _):
                fields, it = carry
                return (invoke(call, it, fields), it + adv * fuse), None

            (fields, it), _ = jax.lax.scan(
                body, (fields, state.iteration), None, length=main // fuse)

            def body1(carry, _):
                fields, it = carry
                return (invoke(call1, it, fields), it + adv), None

            (fields, it), _ = jax.lax.scan(
                body1, (fields, it), None, length=main % fuse)

        globals_ = jnp.zeros_like(state.globals_)
        if final_g is not None:
            if has_series:
                fields, gpart = final_g(sett, it[None], refresh(fields),
                                        aux_of(it))
            else:
                fields, gpart = invoke(final_g, it, fields)
            it = it + adv
            globals_ = gpart[:model.n_globals].sum(axis=1).astype(
                state.globals_.dtype)

        if pad:
            fields = fields[:, :ny_phys, :]
        return LatticeState(
            fields=fields,
            flags=state.flags,
            globals_=globals_,
            iteration=it,
        )

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        return _iterate_jit(state, params, niter)

    # contract flags the Lattice dispatch keys on: the engine handles
    # Control time series itself, and (when the globals flavor exists)
    # returns the LAST step's Globals — no trailing XLA step needed
    iterate.supports_series = True
    iterate.full_globals = bool(model.n_globals == 0 or call_g is not None)
    # internals for make_diff_step (the differentiable single-step path
    # reuses the forward globals kernel verbatim)
    iterate._impl = dict(call1=call1, call_g=call_g, by=by, pad=pad,
                         zonal_si=zonal_si, zshift=zshift,
                         nt_present=nt_present, mk_call=_mk_call)
    return iterate


# --------------------------------------------------------------------------- #
# Generic VMEM-resident engine (2D): whole lattice on-chip, FUSE_R steps
# per kernel launch
# --------------------------------------------------------------------------- #

_RESIDENT_FUSE = 8       # steps per kernel call (EVEN: ping-pong parity)
_RESIDENT_BUDGET = 72 * 1024 * 1024   # state+aux residency budget (v5e
#                          VMEM is 128 MiB; the rest holds the chunk
#                          temporaries Mosaic scopes)


def supports_resident(model: Model, shape, dtype) -> bool:
    """Whether the generic VMEM-resident engine covers this model/shape:
    any fused-engine-eligible 2D model whose two ping-pong stacks + aux
    planes fit the residency budget.  This generalizes the d2q9-family
    resident kernel (ops/pallas_d2q9.make_resident_iterate) to EVERY
    registry model — the deep temporal fusion the band kernels cannot do
    (their VMEM holds only a band; the reference has no analogue, its GPU
    has no software-managed on-chip tier)."""
    if model.ndim != 2 or len(shape) != 2 or not _storage_ok(dtype):
        return False
    if not supports(model, shape, dtype, probe=False):
        return False
    ny, nx = (int(v) for v in shape)
    if ny % 8 or nx % 128:
        return False   # residency keeps the exact periodic wrap: no
        #                ghost-row machinery, so the shape must be aligned
    n_aux = 1 + len(model.zonal_settings)
    itemsize = jnp.dtype(dtype).itemsize
    # ping-pong field stacks narrow with the storage dtype; the aux
    # planes stay f32 (flags + zonal settings)
    if (2 * model.n_storage * itemsize + n_aux * 4) * ny * nx \
            > _RESIDENT_BUDGET:
        return False
    plan, reach = action_plan(model, "Iteration", fuse=1)
    if reach > _HALO:
        return False
    return supports(model, shape, dtype, probe=True)


def make_resident_iterate(model: Model, shape, dtype=jnp.float32,
                          interpret: Optional[bool] = None,
                          present: Optional[set] = None,
                          chunk_cap: int = 64,
                          shift: Optional[np.ndarray] = None):
    """Generic VMEM-resident engine: ``_RESIDENT_FUSE`` full lattice
    steps per kernel launch with the state ping-ponging between two
    on-chip stacks — HBM traffic (1R+1W)/FUSE per step and ONE kernel
    launch per FUSE steps (the band engines pay a launch per 1-2 steps,
    measured ~40 us of gap each on v5e).

    Physics is the SAME ``run_action_plan`` trace as the band kernels,
    applied to row chunks of the resident stack; chunk halos are sliced
    from the resident neighbors with exact periodic wrap (``_circ``), so
    full-band's roll-wrap garbage stays in the discarded margin."""
    if not supports_resident(model, shape, dtype):
        raise ValueError(f"generic resident unsupported: {model.name} "
                         f"{shape}")
    cdtype = _COMPUTE_DTYPE
    ny, nx = (int(s) for s in shape)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    ns = model.n_storage
    _shifts = ([None] * ns if shift is None
               else [float(w) or None for w in shift])
    zonal_names = list(model.zonal_settings)
    n_aux = 1 + len(zonal_names)
    nt_present = set(model.node_types) if present is None else set(present)
    plan1, reach = action_plan(model, "Iteration", fuse=1)
    n_per_rep = len(model.actions["Iteration"])
    adv = int(any(model.stages[s].load_densities
                  for s in model.actions["Iteration"]))

    # largest multiple-of-8 chunk dividing ny under the cap (bounds the
    # per-chunk temporaries exactly like the band kernels' bands do)
    chunk = 8
    for c in range(8, min(ny, chunk_cap) + 1, 8):
        if ny % c == 0:
            chunk = c

    def _circ(src, k, lo, hi):
        """Rows [lo, hi) of resident plane ``k`` with periodic wrap
        (static indices; at most one end wraps)."""
        if lo >= 0 and hi <= ny:
            return src[k, lo:hi, :]
        parts = []
        if lo < 0:
            parts.append(src[k, ny + lo:ny, :])
            lo = 0
        parts.append(src[k, lo:min(hi, ny), :])
        if hi > ny:
            parts.append(src[k, 0:hi - ny, :])
        return jnp.concatenate(parts, axis=0)

    def kernel(sett, it_ref, f_ref, aux_ref, out_ref, buf):
        """Time rides the GRID: step t's src/dst are picked by parity
        (f_ref only feeds step 0), so the whole horizon runs in ONE
        kernel launch with the state resident on-chip — the in/out
        blocks and scratch have constant index maps, so pallas keeps
        them in VMEM across grid steps and writes HBM once at the end."""
        t = pl.program_id(0)

        def one_step(src, dst):
            for c0 in range(0, ny, chunk):
                c1 = c0 + chunk
                work = [ddf.widen_plane(
                    _circ(src, k, c0 - _HALO, c1 + _HALO), cdtype,
                    _shifts[k]) for k in range(ns)]
                fl = _circ(aux_ref, 0, c0 - _HALO, c1 + _HALO).astype(
                    jnp.int32)
                zon = {nm: _circ(aux_ref, 1 + j, c0 - _HALO, c1 + _HALO)
                       for j, nm in enumerate(zonal_names)}
                work, _, _ = run_action_plan(
                    model, plan1, work, fl, zon, {}, sett,
                    it_ref[0] + t * adv, nt_present, _HALO, nx, cdtype,
                    n_per_rep=n_per_rep, full_band=True)
                for k in range(ns):
                    dst[k, c0:c1, :] = ddf.narrow_plane(
                        work[k][_HALO:_HALO + chunk, :], dtype,
                        _shifts[k])

        # ping-pong scratch <-> out (saves a third whole-lattice stack);
        # an EVEN grid length lands the final step in out_ref
        @pl.when(t == 0)
        def _():
            one_step(f_ref, buf)

        @pl.when(jnp.logical_and(t > 0, jax.lax.rem(t, jnp.int32(2)) == 1))
        def _():
            one_step(buf, out_ref)

        @pl.when(jnp.logical_and(t > 0, jax.lax.rem(t, jnp.int32(2)) == 0))
        def _():
            one_step(out_ref, buf)

    @lru_cache(maxsize=None)
    def _call_for(nsteps: int):
        return pl.pallas_call(
            kernel,
            grid=(nsteps,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((ns, ny, nx), dtype),
            scratch_shapes=[pltpu.VMEM((ns, ny, nx), dtype)],
            compiler_params=_CompilerParams(
                vmem_limit_bytes=120 * 1024 * 1024),
            interpret=interpret,
        )

    zshift = model.zone_shift
    zonal_si = [model.setting_index[nm] for nm in zonal_names]
    # the band engine supplies the trailing in-kernel-globals step (and
    # any remainder), making the composition full_globals
    band = make_pallas_iterate(model, shape, dtype, interpret=interpret,
                               fuse=1, present=present, full_band=True,
                               shift=shift)

    @partial(jax.jit, static_argnames=("niter",), donate_argnums=0)
    def _resident_jit(state: LatticeState, params: SimParams, niter: int
                      ) -> LatticeState:
        flags_i32 = state.flags.astype(jnp.int32)
        zones = flags_i32 >> zshift
        sett = params.settings.astype(cdtype)
        aux = jnp.stack(
            [flags_i32.astype(cdtype)]
            + [params.zone_table[j].astype(cdtype)[zones]
               for j in zonal_si])
        fields = _call_for(niter)(sett, state.iteration[None],
                                  state.fields.astype(dtype), aux)
        return LatticeState(fields=fields, flags=state.flags,
                            globals_=jnp.zeros_like(state.globals_),
                            iteration=state.iteration + adv * niter)

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        if params.time_series is not None:
            raise ValueError("generic resident engine does not support "
                             "Control time series")
        # EVEN resident length (ping-pong parity) leaving >=1 step for
        # the band engine's globals flavor when the model declares
        # Globals (full_globals contract)
        tail_min = 1 if getattr(band, "full_globals", False) \
            and model.n_globals else 0
        main = max(niter - tail_min, 0) // 2 * 2
        if main:
            state = _resident_jit(state, params, main)
        rest = niter - main
        if rest:
            state = band(state, params, rest)
        return state

    iterate.supports_series = False
    iterate.full_globals = getattr(band, "full_globals", False)
    return iterate


# --------------------------------------------------------------------------- #
# 3D: z-slab bands (the generic counterpart of ops/pallas_d3q's block kernel)
# --------------------------------------------------------------------------- #


# fused (fuse>=2) 3D calls budget a larger scratch against the raised
# 100 MB scoped-vmem ceiling they always compile with — the wider K*R
# halo is what buys the K-fold traffic amortization
_FUSED3D_BUDGET = 28 * 1024 * 1024


def _slab_depth_gen(model: Model, nz: int, ny: int, nx: int,
                    reach: int, cap: Optional[int] = None,
                    n_aux: Optional[int] = None,
                    budget: Optional[int] = None,
                    itemsize: int = 4) -> Optional[int]:
    """Largest slab depth BZ dividing nz whose double-slotted scratch
    (state + aux, band + ``reach`` halo slabs each side) fits the budget.
    Unlike the 2D rows, z is NOT a tiled axis, so halos are exactly
    ``reach`` slabs — no 8-alignment games."""
    if n_aux is None:
        n_aux = 1 + 2 * len(model.zonal_settings)   # series flavor's aux
    # field slabs scale with the storage itemsize; aux stays f32
    per_slab = (model.n_storage * itemsize + n_aux * 4) * ny * nx
    if budget is None:
        budget = 12 * 1024 * 1024
    best = None
    for bz in range(1, (nz if cap is None else min(nz, cap)) + 1):
        if nz % bz:
            continue
        # double-slotted scratch; compute temporaries live in the rest of
        # VMEM (the same ~15 MB working budget the tuned 3D kernel uses)
        if 2 * (bz + 2 * reach) * per_slab > budget:
            break
        best = bz
    return best


def choose_fuse_3d(model: Model, shape,
                   fmax: int = fusion.FUSE_MAX,
                   itemsize: int = 4) -> int:
    """Fusion depth for the 3D generic z-slab engine: deepest K whose
    fused plan both fits the (raised-ceiling) VMEM budget at some slab
    depth AND beats the single-step engine's modeled traffic.  3D halos
    are real slabs (not fixed-height row blocks), so unlike 2D the halo
    cost grows with K and the planner must weigh it."""
    nz, ny, nx = (int(s) for s in shape)
    _, r1 = action_plan(model, "Iteration", fuse=1)
    R1 = max(r1, 1)
    ns = model.n_storage
    bz1 = _slab_depth_gen(model, nz, ny, nx, R1, itemsize=itemsize)
    if bz1 is None:
        return 1
    # lean aux: the non-series kernels move ns + 1 planes per slab
    best, best_c = 1, ((ns + 1) * (bz1 + 2 * R1) + ns * bz1) / bz1
    for K in range(2, fmax + 1):
        _, rK = action_plan(model, "Iteration", fuse=K)
        RK = max(rK, 1)
        if nz < 2 * RK:
            break
        bzK = _slab_depth_gen(model, nz, ny, nx, RK, n_aux=1,
                              budget=_FUSED3D_BUDGET, itemsize=itemsize)
        if bzK is None:
            continue
        c = ((ns + 1) * (bzK + 2 * RK) + ns * bzK) / (K * bzK)
        if c < best_c:
            best, best_c = K, c
    return best


def supports_3d(model: Model, shape, dtype, probe: bool = True) -> bool:
    """3D eligibility: same registry checks as 2D, z-banded."""
    if model.ndim != 3 or len(shape) != 3 or not _storage_ok(dtype):
        return False
    if "Iteration" not in model.actions:
        return False
    for s in model.actions["Iteration"]:
        st = model.stages.get(s)
        if st is None or st.fixed_point \
                or model.stage_fns.get(st.main) is None:
            return False
    plan, reach = action_plan(model, "Iteration", fuse=1)
    nz, ny, nx = (int(v) for v in shape)
    itemsize = jnp.dtype(dtype).itemsize
    if nz < 2 * max(reach, 1):
        return False
    if jax.default_backend() == "tpu" and (nx % 128 or ny % 8):
        return False  # (ny, nx) is the (sublane, lane) tile
    if _slab_depth_gen(model, nz, ny, nx, max(reach, 1),
                       itemsize=itemsize) is None:
        return False
    if not probe:
        return True
    key = (model.name, "3d", ny, nx, itemsize)
    if key not in _probe_cache:
        try:
            it = make_pallas_iterate_3d(model, (4 * max(reach, 1), ny, nx),
                                        dtype, interpret=True)
            shp = (4 * max(reach, 1), ny, nx)
            state = LatticeState(
                fields=jax.ShapeDtypeStruct((model.n_storage,) + shp, dtype),
                flags=jax.ShapeDtypeStruct(shp, jnp.uint16),
                globals_=jax.ShapeDtypeStruct((model.n_globals,), dtype),
                iteration=jax.ShapeDtypeStruct((), jnp.int32))
            params = SimParams(
                settings=jax.ShapeDtypeStruct((len(model.settings),), dtype),
                zone_table=jax.ShapeDtypeStruct(
                    (len(model.settings), model.zone_max), dtype))
            jax.eval_shape(partial(it, niter=2), state, params)
            _probe_cache[key] = True
        except Exception as e:  # noqa: BLE001
            from tclb_tpu.utils import log
            log.debug(f"pallas_generic 3d: {model.name} probe failed: "
                      f"{type(e).__name__}: {str(e)[:200]}")
            _probe_cache[key] = False
    return _probe_cache[key]


def make_pallas_iterate_3d(model: Model, shape, dtype=jnp.float32,
                           interpret: Optional[bool] = None,
                           present: Optional[set] = None,
                           fuse: int = 1,
                           by_cap: Optional[int] = None,
                           shift: Optional[np.ndarray] = None):
    """3D generic engine: the model's full Iteration action per z-slab
    band pass, with the same registry-driven machinery as the 2D builder
    (multi-stage extension plan, zonal aux planes, in-kernel SUM globals
    flavor, Control-series flavor).  ``fuse=K`` runs K action reps per
    HBM round trip: the fused action plan's progressive windows already
    encode the shrinking interiors, so the kernel machinery is identical
    — only the halo widens to the fused plan's reach and the non-series
    scan advances K iterations per call (remainder steps use a fuse=1
    flavor)."""
    if not supports_3d(model, shape, dtype, probe=False):
        raise ValueError(f"pallas_generic 3d unsupported: {model.name} "
                         f"{shape}")
    cdtype = _COMPUTE_DTYPE
    itemsize = jnp.dtype(dtype).itemsize
    plan, reach = action_plan(model, "Iteration", fuse=fuse)
    R = max(reach, 1)
    plan1, r1 = (plan, reach) if fuse == 1 \
        else action_plan(model, "Iteration", fuse=1)
    R1 = max(r1, 1)
    nz, ny, nx = (int(s) for s in shape)
    if nz < 2 * R:
        raise ValueError(f"fuse={fuse} needs nz >= {2 * R}")
    # the Lattice probe ladder passes row-oriented caps (16, 8); for
    # z-slabs interpret them as a slab-depth cap (8 rows ~ 1 slab) so the
    # retry actually shrinks the scoped-VMEM working set.  NEGATIVE caps
    # are the last-resort rungs: |cap| plus a raised scoped-vmem ceiling
    # (the big ceiling costs ~2x in Mosaic codegen quality, so it is
    # never the default — only what rescues temporaries-heavy models
    # like d3q19_kuper that OOM even at bz=1).  Fused (K>=2) builds
    # always compile with the raised ceiling: their K*R halo scratch is
    # budgeted against it (_FUSED3D_BUDGET).
    vmem_ceiling = (by_cap is not None and by_cap < 0) or fuse >= 2
    cap = None if by_cap is None else max(1, abs(by_cap) // 8)
    bz = _slab_depth_gen(model, nz, ny, nx, R, cap, n_aux=1,
                         budget=_FUSED3D_BUDGET, itemsize=itemsize) \
        if fuse >= 2 \
        else _slab_depth_gen(model, nz, ny, nx, R, cap, itemsize=itemsize)
    if bz is None:
        raise ValueError(f"no slab depth fits fuse={fuse} for "
                         f"{model.name} {shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    ns = model.n_storage
    _shifts = ([None] * ns if shift is None
               else [float(w) or None for w in shift])
    zonal_names = list(model.zonal_settings)
    zshift = model.zone_shift
    zone_max = model.zone_max
    si = model.setting_index
    zonal_si = [si[nm] for nm in zonal_names]
    # same aux diet as 2D: non-series flavors DMA flags only and rebuild
    # zonal planes in-kernel from the SMEM zone table
    lean_aux = len(zonal_names) > 0
    ei = model.ei
    stage_fns = {nm: model.stage_fns[model.stages[nm].main]
                 for nm in model.actions["Iteration"]}
    loads_density = {nm: model.stages[nm].load_densities
                     for nm in model.actions["Iteration"]}
    nt_present = set(model.node_types) if present is None else set(present)

    def _mk_kernel(plan, R, with_dt=False, with_globals=False, lean=False):
        n_aux_k = 1 if lean \
            else 1 + (2 if with_dt else 1) * len(zonal_names)

        def kern(sett, it_ref, *rest):
            if lean:
                ztab, f_hbm, aux_hbm, *refs = rest
            else:
                ztab = None
                f_hbm, aux_hbm, *refs = rest
            if with_globals:
                out_ref, g_ref, buff, bufa, sems = refs
            else:
                (out_ref, buff, bufa, sems), g_ref = refs, None
            i = pl.program_id(0)
            n = pl.num_programs(0)

            def band_dmas(slot, band):
                # halo slabs are copied ONE AT A TIME with individual
                # modular indices: a block copy of R slabs starting at
                # (base - R) mod nz would straddle the periodic boundary
                # whenever that start lands within R of the top (e.g.
                # bz=1, R=2, band 1), reading out of bounds
                base = band * jnp.int32(bz)
                out = []
                n_sem = 1 + 2 * R
                for si_, (hbm, buf, nplanes) in enumerate((
                        (f_hbm, buff, ns), (aux_hbm, bufa, n_aux_k))):
                    out.append(pltpu.make_async_copy(
                        hbm.at[pl.ds(0, nplanes), pl.ds(base, bz)],
                        buf.at[slot, :, pl.ds(R, bz)],
                        sems.at[slot, n_sem * si_]))
                    for r in range(R):
                        zm_r = jax.lax.rem(
                            base - jnp.int32(R - r) + jnp.int32(nz),
                            jnp.int32(nz))
                        zp_r = jax.lax.rem(base + jnp.int32(bz + r),
                                           jnp.int32(nz))
                        out.append(pltpu.make_async_copy(
                            hbm.at[pl.ds(0, nplanes), pl.ds(zm_r, 1)],
                            buf.at[slot, :, pl.ds(r, 1)],
                            sems.at[slot, n_sem * si_ + 1 + r]))
                        out.append(pltpu.make_async_copy(
                            hbm.at[pl.ds(0, nplanes), pl.ds(zp_r, 1)],
                            buf.at[slot, :, pl.ds(R + bz + r, 1)],
                            sems.at[slot, n_sem * si_ + 1 + R + r]))
                return out

            slot = jax.lax.rem(i, jnp.int32(2))
            nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

            @pl.when(i == 0)
            def _():
                for d in band_dmas(jnp.int32(0), i):
                    d.start()

            @pl.when(i + 1 < n)
            def _():
                for d in band_dmas(nxt, i + jnp.int32(1)):
                    d.start()

            for d in band_dmas(slot, i):
                d.wait()

            def _rollyx(sl, dy, dx):
                if dy:
                    sl = jnp.roll(sl, dy, axis=1)
                if dx % nx:
                    sl = pltpu.roll(sl, dx % nx, axis=2)
                return sl

            # widen to the compute dtype at the read (traced no-op at f32
            # storage); the whole fused action accumulates in f32 and the
            # output write narrows back to the storage dtype
            work = [ddf.widen_plane(buff[slot, k], cdtype, _shifts[k])
                    for k in range(ns)]
            flags_full = bufa[slot, 0].astype(jnp.int32)
            if ztab is not None:
                zones_full = flags_full >> zshift
                zonal_full = {nm: fusion.zone_plane(ztab, j, zone_max,
                                                    zones_full)
                              for j, nm in enumerate(zonal_names)}
                dt_full = {}
            else:
                zonal_full = {nm: bufa[slot, 1 + j]
                              for j, nm in enumerate(zonal_names)}
                dt_full = {nm: bufa[slot, 1 + len(zonal_names) + j]
                           for j, nm in enumerate(zonal_names)} \
                    if with_dt else {}
            g_acc: dict = {}

            n_per_rep = len(model.actions["Iteration"])
            for st_i, (stage_name, out_ext) in enumerate(plan):
                n_i = bz + 2 * out_ext
                lo = R - out_ext
                rep = st_i // n_per_rep

                if loads_density[stage_name]:
                    planes = []
                    for k in range(ns):
                        dxk, dyk, dzk = (int(v) for v in ei[k])
                        sl = work[k][lo - dzk:lo - dzk + n_i]
                        planes.append(_rollyx(sl, dyk, dxk))
                else:
                    planes = [w[lo:lo + n_i] for w in work]

                def loader(index, dx, dy, dz=0, _lo=lo, _n=n_i):
                    sl = work[index][_lo + dz:_lo + dz + _n]
                    return _rollyx(sl, -dy, -dx)

                ctx = KernelCtx(
                    model, planes, loader,
                    flags_full[lo:lo + n_i],
                    {nm: p[lo:lo + n_i] for nm, p in zonal_full.items()},
                    sett, cdtype, it_ref[0] + rep, nt_present,
                    dt_planes={nm: p[lo:lo + n_i]
                               for nm, p in dt_full.items()},
                    compute_globals=g_ref is not None)
                res = stage_fns[stage_name](ctx)
                if g_ref is not None:
                    for nm, plane in ctx._globals.items():
                        part = plane[out_ext:out_ext + bz]
                        g_acc[nm] = part if nm not in g_acc \
                            else g_acc[nm] + part

                if isinstance(res, dict):
                    updates: dict[int, jnp.ndarray] = {}
                    for name, stack in res.items():
                        if name in model.groups:
                            idx = model.groups[name]
                            if len(idx) == 1 and stack.ndim == 3:
                                updates[idx[0]] = stack
                            else:
                                for j, k in enumerate(idx):
                                    updates[k] = stack[j]
                        else:
                            updates[model.storage_index[name]] = stack
                else:
                    updates = {k: res[k] for k in range(ns)}
                for k, new in updates.items():
                    w = work[k]
                    work[k] = jnp.concatenate(
                        [w[:lo], new, w[lo + n_i:]], axis=0)

            for k in range(ns):
                out_ref[k] = ddf.narrow_plane(work[k][R:R + bz], dtype,
                                              _shifts[k])

            if g_ref is not None:
                @pl.when(i == 0)
                def _():
                    g_ref[...] = jnp.zeros((8, 128), cdtype)
                for gi, g in enumerate(model.globals_):
                    if g.name not in g_acc:
                        continue
                    part = g_acc[g.name].reshape(
                        (bz * ny * (nx // 128), 128)).sum(axis=0)
                    g_ref[gi] = g_ref[gi] + part

        return kern, n_aux_k

    def _mk_call(plan_k, R_k, with_dt=False, with_globals=False,
                 lean=False):
        kern, n_aux_k = _mk_kernel(plan_k, R_k, with_dt, with_globals,
                                   lean)
        out_specs = pl.BlockSpec((ns, bz, ny, nx), lambda i: (0, i, 0, 0),
                                 memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((ns, nz, ny, nx), dtype)
        if with_globals:
            out_specs = [out_specs,
                         pl.BlockSpec((8, 128), lambda i: (0, 0),
                                      memory_space=pltpu.VMEM)]
            out_shape = [out_shape,
                         jax.ShapeDtypeStruct((8, 128), cdtype)]
        return pl.pallas_call(
            kern,
            grid=(nz // bz,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ] + ([pl.BlockSpec(memory_space=pltpu.SMEM)] if lean else [])
            + [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((2, ns, bz + 2 * R_k, ny, nx), dtype),
                pltpu.VMEM((2, n_aux_k, bz + 2 * R_k, ny, nx), cdtype),
                pltpu.SemaphoreType.DMA((2, 2 * (1 + 2 * R_k))),
            ],
            compiler_params=_CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024)
            if vmem_ceiling else None,
            interpret=interpret,
        )

    call = _mk_call(plan, R, lean=lean_aux)
    call1 = call if fuse == 1 else _mk_call(plan1, R1, lean=lean_aux)
    can_globals = (nx % 128 == 0 and model.n_globals <= 8
                   and all(g.op == "SUM" for g in model.globals_))
    call_g = _mk_call(plan1, R1, with_globals=True, lean=lean_aux) \
        if can_globals and model.n_globals else None
    call_s = _mk_call(plan1, R1, with_dt=True)
    call_sg = _mk_call(plan1, R1, with_dt=True, with_globals=True) \
        if can_globals and model.n_globals else None
    adv = int(any(model.stages[s].load_densities
                  for s in model.actions["Iteration"]))

    @partial(jax.jit, static_argnames=("niter",), donate_argnums=0)
    def _iterate_jit(state: LatticeState, params: SimParams, niter: int
                     ) -> LatticeState:
        flags_i32 = state.flags.astype(jnp.int32)
        fields = state.fields.astype(dtype)
        zones = flags_i32 >> zshift
        sett = params.settings.astype(cdtype)
        has_series = params.time_series is not None
        flags_f = flags_i32.astype(cdtype)
        base_planes = [params.zone_table[k].astype(cdtype)[zones]
                       for k in zonal_si]

        def aux_of(it):
            return assemble_aux(params, zones, flags_f, base_planes,
                                zonal_si, it, cdtype, with_dt=has_series)

        final_g = call_sg if has_series else call_g
        if niter <= 0:
            return state
        main = niter - (1 if final_g is not None else 0)

        if has_series:
            # series flavors keep the full host-assembled aux stack: the
            # dt planes depend on the Control series, not just zone bits
            def body_s(carry, _):
                fields, it = carry
                out = call_s(sett, it[None], fields, aux_of(it))
                return (out, it + adv), None

            (fields, it), _ = jax.lax.scan(
                body_s, (fields, state.iteration), None, length=main)
        else:
            # lean aux: iteration-invariant zonal planes are rebuilt
            # in-kernel from the SMEM zone table — the aux DMA leg
            # carries exactly one flags plane, every step, regardless of
            # how many zonal settings the model declares
            if lean_aux:
                ztab = jnp.concatenate(
                    [params.zone_table[k].astype(cdtype)
                     for k in zonal_si])
                aux = flags_f[None]

                def invoke(c, it, fields):
                    return c(sett, it[None], ztab, fields, aux)
            else:
                aux = aux_of(state.iteration)

                def invoke(c, it, fields):
                    return c(sett, it[None], fields, aux)

            def body(carry, _):
                fields, it = carry
                out = invoke(call, it, fields)
                return (out, it + adv * fuse), None

            def body1(carry, _):
                fields, it = carry
                out = invoke(call1, it, fields)
                return (out, it + adv), None

            (fields, it), _ = jax.lax.scan(
                body, (fields, state.iteration), None,
                length=main // fuse)
            if fuse > 1:
                (fields, it), _ = jax.lax.scan(
                    body1, (fields, it), None, length=main % fuse)

        globals_ = jnp.zeros_like(state.globals_)
        if final_g is not None:
            if has_series:
                fields, gpart = final_g(sett, it[None], fields,
                                        aux_of(it))
            else:
                fields, gpart = invoke(final_g, it, fields)
            it = it + adv
            globals_ = gpart[:model.n_globals].sum(axis=1).astype(
                state.globals_.dtype)
        return LatticeState(fields=fields, flags=state.flags,
                            globals_=globals_, iteration=it)

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        return _iterate_jit(state, params, niter)

    iterate.supports_series = True
    iterate.full_globals = bool(model.n_globals == 0 or call_g is not None)
    # internals for the differentiable wrapper (ops/pallas_adjoint's 3D
    # diff step drives call_g directly, outside the scanning iterate)
    iterate._impl = dict(call_g=call_g, call_sg=call_sg, lean_aux=lean_aux,
                         zonal_si=zonal_si, zshift=zshift, adv=adv,
                         cdtype=cdtype, bz=bz, R=R)
    return iterate
