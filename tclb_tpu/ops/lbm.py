"""Shared symbolic-free LBM math — the TPU-side equivalent of the reference's
R algebra library (reference src/lib/feq.R, src/lib/cumulant.R,
src/lib/lattice.R).  Where the reference emits closed-form C expressions from
symbolic algebra at build time, we compute the same quantities numerically
with numpy (constants) + jnp (traced), and let XLA do the fusing.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

CS2 = 1.0 / 3.0  # lattice speed of sound squared


def opposite(E: np.ndarray) -> np.ndarray:
    """Index i -> index of -e_i (bounce-back pairing)."""
    opp = np.zeros(len(E), dtype=np.int32)
    for i, e in enumerate(E):
        (j,) = np.where((E == -e).all(axis=1))
        opp[i] = j[0]
    return opp


def weights(E: np.ndarray) -> np.ndarray:
    """Standard lattice weights by speed shell (works for d2q9/d3q19/d3q27)."""
    q, d = E.shape
    table = {
        (9, 2): {0: 4 / 9, 1: 1 / 9, 2: 1 / 36},
        (19, 3): {0: 1 / 3, 1: 1 / 18, 2: 1 / 36},
        (27, 3): {0: 8 / 27, 1: 2 / 27, 2: 1 / 54, 3: 1 / 216},
        (5, 2): {0: 1 / 3, 1: 1 / 6},
        (7, 3): {0: 1 / 4, 1: 1 / 8},
    }[(q, d)]
    return np.array([table[int((e * e).sum())] for e in E])


def equilibrium(E: np.ndarray, W: np.ndarray, rho, u):
    """Second-order Maxwell equilibrium
    f_i = w_i rho (1 + e.u/cs2 + (e.u)^2/(2 cs4) - u^2/(2 cs2)).

    ``u`` is a tuple of velocity planes; returns a (Q, *shape) stack.
    """
    dt = rho.dtype
    usq = sum(c * c for c in u)
    out = []
    for i in range(len(E)):
        # skip exact-zero velocity components so XLA sees fewer ops
        eu = sum(float(E[i, a]) * u[a] for a in range(len(u)) if E[i, a])
        if isinstance(eu, int):  # rest population: e.u == 0
            common = 1.0 - usq / (2 * CS2)
        else:
            common = 1.0 + eu / CS2 + eu * eu / (2 * CS2 * CS2) \
                - usq / (2 * CS2)
        out.append(jnp.asarray(float(W[i]), dt) * rho * common)
    return jnp.stack(out)


def mrt_basis_d2q9(E: np.ndarray) -> np.ndarray:
    """Orthogonal (Gram-Schmidt) d2q9 moment basis of Lallemand & Luo:
    rows = (rho, jx, jy, e, eps, qx, qy, pxx, pxy) as integer polynomials of
    the velocity set.  Matches the basis the reference builds symbolically in
    src/lib/feq.R (used at src/d2q9/Dynamics.c.Rt:234-243)."""
    ex, ey = E[:, 0].astype(np.float64), E[:, 1].astype(np.float64)
    e2 = ex * ex + ey * ey
    M = np.stack([
        np.ones_like(ex),               # rho
        ex,                             # jx
        ey,                             # jy
        3.0 * e2 - 4.0,                 # e (energy)
        4.5 * e2 * e2 - 10.5 * e2 + 4.0,  # eps (energy squared)
        (3.0 * e2 - 5.0) * ex,          # qx (energy flux)
        (3.0 * e2 - 5.0) * ey,          # qy
        ex * ex - ey * ey,              # pxx
        ex * ey,                        # pxy
    ])
    # sanity: rows orthogonal
    g = M @ M.T
    assert np.allclose(g - np.diag(np.diag(g)), 0.0), "basis not orthogonal"
    return M


def moments(M: np.ndarray, f: jnp.ndarray) -> jnp.ndarray:
    """m = M f over the leading (population) axis — an MXU matmul batched
    over lattice points."""
    return jnp.einsum("qi,i...->q...", jnp.asarray(M, f.dtype), f)


def from_moments(M: np.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`moments` for an orthogonal (row) basis."""
    norm = (M * M).sum(axis=1)
    Minv = (M / norm[:, None]).T
    return jnp.einsum("iq,q...->i...", jnp.asarray(Minv, m.dtype), m)
