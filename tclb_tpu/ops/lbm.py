"""Shared symbolic-free LBM math — the TPU-side equivalent of the reference's
R algebra library (reference src/lib/feq.R, src/lib/cumulant.R,
src/lib/lattice.R).  Where the reference emits closed-form C expressions from
symbolic algebra at build time, we compute the same quantities numerically
with numpy (constants) + jnp (traced), and let XLA do the fusing.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

CS2 = 1.0 / 3.0  # lattice speed of sound squared


@jax.custom_vjp
def pin(x):
    """Identity that pins ``x`` to one canonical evaluation: the
    compiler may not fuse ``x``'s producers into its consumers, so the
    multiply-add contraction of the producing graph no longer depends on
    where the value is used.  The engines' bit-parity contract (same
    model arithmetic on the XLA path and inside a Pallas kernel) needs
    this at fusion-sensitive seams.  Differentiable in reverse mode (the
    cotangent is pinned the same way), which the raw
    ``lax.optimization_barrier`` primitive is not."""
    return jax.lax.optimization_barrier(x)


def _pin_fwd(x):
    return pin(x), None


def _pin_bwd(_, g):
    return (jax.lax.optimization_barrier(g),)


pin.defvjp(_pin_fwd, _pin_bwd)


def _register_pin_batching() -> None:
    # optimization_barrier ships without a vmap rule in the pinned jax
    # version, which would make every pinned model un-batchable by the
    # ensemble engine (serve/ensemble.py).  A barrier is rank-polymorphic:
    # batching it is binding it on the batched operands with the batch
    # dims passed through unchanged.
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:      # pragma: no cover - future jax relocations
        return
    if optimization_barrier_p in batching.primitive_batchers:
        return               # pragma: no cover - newer jax grew a rule

    def _barrier_batcher(args, dims):
        return optimization_barrier_p.bind(*args), dims

    batching.primitive_batchers[optimization_barrier_p] = _barrier_batcher


_register_pin_batching()


def present_types(model, flags: np.ndarray) -> set:
    """Node-type names actually present in a host flag field — used by the
    Pallas kernels to skip absent boundary cases (the reference gets the
    same effect from compile-time specialization of the generated kernel
    on the model's boundary set)."""
    flags = np.asarray(flags)
    out = set()
    for name, t in model.node_types.items():
        if ((flags & np.uint16(t.mask)) == np.uint16(t.value)).any():
            out.add(name)
    return out


def opposite(E: np.ndarray) -> np.ndarray:
    """Index i -> index of -e_i (bounce-back pairing)."""
    opp = np.zeros(len(E), dtype=np.int32)
    for i, e in enumerate(E):
        (j,) = np.where((E == -e).all(axis=1))
        opp[i] = j[0]
    return opp


def weights(E: np.ndarray) -> np.ndarray:
    """Standard lattice weights by speed shell (works for d2q9/d3q19/d3q27)."""
    q, d = E.shape
    table = {
        (9, 2): {0: 4 / 9, 1: 1 / 9, 2: 1 / 36},
        (19, 3): {0: 1 / 3, 1: 1 / 18, 2: 1 / 36},
        (27, 3): {0: 8 / 27, 1: 2 / 27, 2: 1 / 54, 3: 1 / 216},
        (5, 2): {0: 1 / 3, 1: 1 / 6},
        (7, 3): {0: 1 / 4, 1: 1 / 8},
    }[(q, d)]
    return np.array([table[int((e * e).sum())] for e in E])


def edot(vec, stack) -> jnp.ndarray:
    """``sum_i vec[i] * stack[i]`` over the leading (population) axis,
    unrolled with SCALAR coefficients and exact-zero terms skipped.

    The kernel-safe replacement for
    ``jnp.tensordot(jnp.asarray(vec, dt), stack, axes=1)``: Pallas
    rejects kernels that capture constant ARRAYS (the materialized
    ``vec``), and the tiny q-length contraction would otherwise become a
    padded MXU pass.  Works identically under XLA (constant-folded
    adds), so model code uses this one form for both engines."""
    acc = None
    for i, v in enumerate(np.asarray(vec)):
        v = float(v)
        if v == 0.0:
            continue
        t = stack[i] if v == 1.0 else (-stack[i] if v == -1.0
                                       else v * stack[i])
        acc = t if acc is None else acc + t
    return acc if acc is not None else jnp.zeros_like(stack[0])


def perm(stack, idx) -> jnp.ndarray:
    """Reorder the leading (population) axis by a CONSTANT permutation:
    ``stack[idx]`` as a static unstack/restack — the only form Mosaic
    accepts inside a Pallas kernel (no gather, no captured index
    array); XLA folds it to the same free layout change."""
    return jnp.stack([stack[int(k)] for k in np.asarray(idx)])


def wstack(w, value) -> jnp.ndarray:
    """``(q, *shape)`` stack of ``w[i] * value`` with SCALAR weight
    coefficients — the kernel-safe replacement for broadcasting a
    materialized ``(q,1,1)`` weight-vector constant (which Pallas rejects
    as a captured array).  ``value`` may be a plane or a traced scalar."""
    return jnp.stack([float(wi) * value for wi in np.asarray(w)])


def equilibrium(E: np.ndarray, W: np.ndarray, rho, u):
    """Second-order Maxwell equilibrium
    f_i = w_i rho (1 + e.u/cs2 + (e.u)^2/(2 cs4) - u^2/(2 cs2)).

    ``u`` is a tuple of velocity planes; returns a (Q, *shape) stack.
    """
    dt = rho.dtype
    usq = sum(c * c for c in u)
    out = []
    for i in range(len(E)):
        # skip exact-zero velocity components so XLA sees fewer ops
        eu = sum(float(E[i, a]) * u[a] for a in range(len(u)) if E[i, a])
        if isinstance(eu, int):  # rest population: e.u == 0
            common = 1.0 - usq / (2 * CS2)
        else:
            common = 1.0 + eu / CS2 + eu * eu / (2 * CS2 * CS2) \
                - usq / (2 * CS2)
        out.append(jnp.asarray(float(W[i]), dt) * rho * common)
    # pinned so f_eq gets ONE canonical evaluation: fused into its
    # consumers (f - feq, relax + feq2, ...) the compiler contracts the
    # multiply-add chains differently depending on the surrounding
    # graph, so the same source gives 1-ULP-different values in the XLA
    # step vs a Pallas kernel — which breaks the engines' bit-parity
    # contract.  Costs one materialized (q, *shape) temp.
    return pin(jnp.stack(out))


def mrt_basis_d2q9(E: np.ndarray) -> np.ndarray:
    """Orthogonal (Gram-Schmidt) d2q9 moment basis of Lallemand & Luo:
    rows = (rho, jx, jy, e, eps, qx, qy, pxx, pxy) as integer polynomials of
    the velocity set.  Matches the basis the reference builds symbolically in
    src/lib/feq.R (used at src/d2q9/Dynamics.c.Rt:234-243)."""
    ex, ey = E[:, 0].astype(np.float64), E[:, 1].astype(np.float64)
    e2 = ex * ex + ey * ey
    M = np.stack([
        np.ones_like(ex),               # rho
        ex,                             # jx
        ey,                             # jy
        3.0 * e2 - 4.0,                 # e (energy)
        4.5 * e2 * e2 - 10.5 * e2 + 4.0,  # eps (energy squared)
        (3.0 * e2 - 5.0) * ex,          # qx (energy flux)
        (3.0 * e2 - 5.0) * ey,          # qy
        ex * ex - ey * ey,              # pxx
        ex * ey,                        # pxy
    ])
    # sanity: rows orthogonal
    g = M @ M.T
    assert np.allclose(g - np.diag(np.diag(g)), 0.0), "basis not orthogonal"
    return M


def d3q19_velocities() -> np.ndarray:
    """Standard 19-velocity set: rest, 6 axis, 12 edge vectors (reference
    src/lib/d3q19.R ordering is its own; ours is shell-ordered)."""
    E = [(0, 0, 0)]
    for a in range(3):
        for s in (1, -1):
            v = [0, 0, 0]
            v[a] = s
            E.append(tuple(v))
    for a in range(3):
        for b in range(a + 1, 3):
            for sa in (1, -1):
                for sb in (1, -1):
                    v = [0, 0, 0]
                    v[a], v[b] = sa, sb
                    E.append(tuple(v))
    return np.array(E, dtype=np.int32)


def d3q27_velocities() -> np.ndarray:
    """Tensor-product 27-velocity set (cumulant reshape order)."""
    from tclb_tpu.ops.cumulant import velocity_set
    return velocity_set(3)


def gram_schmidt_basis(E: np.ndarray) -> np.ndarray:
    """Orthogonal moment basis over a velocity set by Gram-Schmidt on the
    monomials 1, ex, ey[, ez], exey, ... in graded order — the numerical
    equivalent of the reference's symbolically-built MRT bases
    (src/lib/feq.R MRT_polyMatrix).  Rows ordered by total degree; the
    first 1+d rows are the conserved (rho, j) moments."""
    q, d = E.shape
    polys = []
    degs = []
    for total in range(0, 3 * d + 1):
        for px in range(total + 1):
            for py in range(total - px + 1):
                pz = total - px - py
                if d == 2 and pz:
                    continue
                p = (px, py) if d == 2 else (px, py, pz)
                if max(p) > 2:   # velocities in {-1,0,1}: e^3 == e
                    continue
                polys.append(p)
                degs.append(total)
    cols = []
    M = []
    for p in polys:
        row = np.ones(q)
        for a, pw in enumerate(p):
            row = row * E[:, a].astype(np.float64) ** pw
        # orthogonalize against accepted rows
        for r in M:
            row = row - r * (row @ r) / (r @ r)
        if (np.abs(row) > 1e-9).any():
            M.append(row)
            cols.append(p)
        if len(M) == q:
            break
    assert len(M) == q, f"basis incomplete: {len(M)}/{q}"
    return np.stack(M)


def bgk_collide(E: np.ndarray, W: np.ndarray, f: jnp.ndarray, omega,
                force=None, rho_u=None):
    """Plain BGK with optional velocity-shift (exact-difference) forcing.
    Returns (f', rho, u-tuple)."""
    rho = jnp.sum(f, axis=0)
    d = E.shape[1]
    u = tuple(edot(E[:, a], f) / rho for a in range(d))
    feq = equilibrium(E, W, rho, u)
    out = f + omega * (feq - f)
    if force is not None:
        u2 = tuple(u[a] + force[a] for a in range(d))
        out = out + (equilibrium(E, W, rho, u2) - feq)
    return out, rho, u


def nebb_boundary(E: np.ndarray, W: np.ndarray, OPP: np.ndarray,
                  f: jnp.ndarray, axis: int, side: int, kind: str, value,
                  vt: Optional[dict] = None):
    """Generic straight-wall velocity/pressure boundary by non-equilibrium
    bounce-back (Zou & He's closure generalized to any face/velocity set —
    the role of the reference's per-model ZouHe() template,
    src/lib/boundary.R).

    ``axis``: face normal axis (0=x, 1=y, 2=z); ``side``: +1 if the fluid
    lies in +axis direction (a "low" face), -1 for a "high" face;
    ``kind``: 'velocity' (``value`` = signed +axis velocity component) or
    'pressure' (``value`` = density).  Unknown populations (e.axis == side)
    get ``f_opp + 2 w rho (e.u)/cs2`` for the normal velocity, minus the
    tangential-momentum correction ``(e.t)(Q_t/2 - cs2 rho u_t)`` with
    ``Q_t`` the tangential momentum carried by the wall-parallel knowns
    (Zou & He's d2q9 ``0.5 (f[2]-f[4])`` terms, generalized to 3D a la
    Hecht & Harting) — the closure the reference ZouHe applies
    (src/lib/boundary.R); the imposed tangential velocity defaults to zero.

    ``vt`` optionally imposes NONZERO tangential velocities:
    ``{axis: value}`` planes/scalars — the reference lib ZouHe's ``V3``
    argument (used by the turbulent inlet,
    src/d3q27_cumulant/Dynamics.c.Rt:210-222): each adds ``rho v_t`` to
    the corresponding tangential momentum target.
    """
    # Unrolled over populations with float-scalar coefficients (no
    # constant coefficient VECTORS are materialized): identical algebra,
    # and the form Mosaic accepts when this runs inside a Pallas kernel
    # (ops/pallas_d3q.py) — Pallas rejects captured non-scalar constants.
    q = len(E)
    en = E[:, axis].astype(np.int64)
    tang_k = [k for k in range(q) if en[k] == 0]
    out_k = [k for k in range(q) if en[k] == -side]  # known, entering wall
    s_t = sum(f[k] for k in tang_k)
    s_o = sum(f[k] for k in out_k)
    if kind == "velocity":
        # value is the signed +axis velocity component at the wall
        un = value
        rho = (s_t + 2.0 * s_o) / (1.0 - side * un)
    else:
        rho = value
        un = side * (1.0 - (s_t + 2.0 * s_o) / rho)
    # non-equilibrium bounce-back: f_i = f_opp(i) + 6 w_i rho e_i.u
    corr = [6.0 * float(W[k]) * float(en[k]) * rho * un
            if en[k] else None for k in range(q)]
    # tangential closure: redistribute the excess tangential momentum of
    # the wall-parallel populations onto the unknowns, weight-proportional:
    # corr_i += 6 w_i e_t J_t with J_t = -3 q_t + rho v_t — exactly the
    # reference lib ZouHe's solved tangential moment + V3 shift
    # (src/lib/boundary.R:83-101; the hand-written d3q27 BCs' Jy/Jz =
    # tangential sums / (-1/3) are the same solve).  In d2q9 this reduces
    # to the classic 0.5 (f[2]-f[4]) terms (6 w_diag 3 = 1/2); a flat
    # 0.5 q_t per unknown would over-correct 3x on d3q19/d3q27 faces and
    # blow up under sheared/turbulent inflow.
    for t_ax in range(E.shape[1]):
        if t_ax == axis:
            continue
        et = E[:, t_ax].astype(np.int64)
        if not et.any():
            continue
        q_t = sum(float(et[k]) * f[k] for k in tang_k if et[k])
        j_t = -3.0 * q_t
        if vt and t_ax in vt:
            # full imposition: the j_t -> total-momentum slope of the 6 w
            # distribution is 1/3, so the target needs 3 rho v_t.  (The
            # reference lib ZouHe adds only rho V3 here — lib/boundary.R:
            # 83-101 — which imposes a third of the requested tangential
            # velocity; deliberate deviation, documented.)
            j_t = j_t + 3.0 * rho * vt[t_ax]
        for k in range(q):
            if en[k] == side and et[k]:
                add = 6.0 * float(W[k]) * float(et[k]) * j_t
                corr[k] = add if corr[k] is None else corr[k] + add
    return jnp.stack([
        f[int(OPP[k])] + (corr[k] if corr[k] is not None
                          else jnp.zeros_like(rho))
        if en[k] == side else f[k]
        for k in range(q)])


def _unrolled_matvec(mat: np.ndarray, f) -> jnp.ndarray:
    """mat @ f over the leading axis, unrolled with SCALAR coefficients.

    The moment matrices are tiny (q x q) with many +-1/0 entries; an
    einsum would become an MXU matmul with contraction dim q (padded to
    the 128 tile, then multiplied into several passes by the "highest"
    precision the engine demands) — measured ~2.5x slower than the
    equivalent unrolled VPU elementwise form on the d2q9 step.  Exact
    f32 arithmetic, and XLA constant-folds the 0/±1 entries."""
    rows = []
    for row in np.asarray(mat):
        acc = None
        for c, p in zip(row, f):
            c = float(c)
            if c == 0.0:
                continue
            t = p if c == 1.0 else (-p if c == -1.0 else c * p)
            acc = t if acc is None else acc + t
        rows.append(acc if acc is not None else jnp.zeros_like(f[0]))
    return jnp.stack(rows)


def smagorinsky_omega_unrolled(E: np.ndarray, f, feq, rho, omega0, smag):
    """Smagorinsky eddy-viscosity relaxation rate (Hou et al.):
    ``tau_eff = (tau0 + sqrt(tau0^2 + 18 sqrt(2) Cs^2 |Pi|/rho)) / 2``
    with ``|Pi|`` the Frobenius norm of the non-equilibrium momentum
    flux — the closed form the reference's LES models compute in-kernel
    (src/d2q9_les/Dynamics.c.Rt, src/d3q19_les).  The contraction is
    unrolled with SCALAR coefficients (Pallas rejects materialized
    constant coefficient vectors) — the one implementation every LES
    user (XLA models and Pallas kernels, 2D and 3D) shares."""
    d = E.shape[1]
    pi2 = None
    for a in range(d):
        for b in range(a, d):
            ks = [k for k in range(len(E)) if E[k, a] * E[k, b]]
            if not ks:
                continue
            pab = sum(float(E[k, a] * E[k, b]) * (f[k] - feq[k])
                      for k in ks)
            term = pab * pab * (1.0 if a == b else 2.0)
            pi2 = term if pi2 is None else pi2 + term
    tau0 = 1.0 / omega0
    tau_eff = 0.5 * (tau0 + jnp.sqrt(
        tau0 * tau0 + 18.0 * math.sqrt(2.0) * smag * smag
        * jnp.sqrt(pi2) / rho))
    return 1.0 / tau_eff


def two_rate_relax(M: np.ndarray, lo: int, hi: int, fneq,
                   keep_stress, keep_high) -> jnp.ndarray:
    """Relaxed non-equilibrium for a two-rate MRT: rows ``lo:hi`` of the
    orthogonal basis ``M`` (the stress group) keep ``keep_stress``, every
    higher row keeps ``keep_high``, conserved rows (0:lo) drop out.

    Uses the exact projection identity
    ``Minv @ (keep * M @ fneq) == keep_high * fneq
    + (keep_stress - keep_high) * P_s @ fneq``
    (valid because the conserved moments of ``fneq = f - feq`` vanish for
    a mass/momentum-conserving equilibrium), so only the |stress| = hi-lo
    rank-one projections are computed instead of a full q x (q - lo)
    moment transform pair — ~3x fewer multiply-adds on d3q19, identical
    algebra (the reference generator gets the same effect by emitting the
    symbolically simplified closed form, src/lib/feq.R MRT)."""
    norms = (M * M).sum(axis=1)
    mn = _unrolled_matvec(M[lo:hi], fneq)
    back = _unrolled_matvec((M[lo:hi] / norms[lo:hi, None]).T, mn)
    d = keep_stress - keep_high
    return jnp.stack([keep_high * fneq[k] + d * back[k]
                      for k in range(len(M))])


def moments(M: np.ndarray, f: jnp.ndarray) -> jnp.ndarray:
    """m = M f over the leading (population) axis."""
    return _unrolled_matvec(M, f)


def from_moments(M: np.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`moments` for an orthogonal (row) basis."""
    norm = (M * M).sum(axis=1)
    Minv = (M / norm[:, None]).T
    return _unrolled_matvec(Minv, m)
