"""Differentiable Pallas fast path: ``custom_vjp`` around the fused
action chunk with a Pallas BACKWARD band kernel.

The reference's adjoint is itself a tuned device kernel: Tapenade emits
``Run_b`` and the generated adjoint streaming scatters through the margins
(reference src/cuda.cu.Rt:240-256 ``RunKernel<..., adjoint>``, transpose
access in src/LatticeAccess.inc.cpp.Rt:227-261), with a dedicated settings
tape for control gradients (src/cuda.cu.Rt:216 ``DynamicsS_b``,
tools/makeAD:24).  Here BOTH sweeps run the registry-driven band machinery
of the generic engine (ops/pallas_generic):

* the FORWARD is the generic kernel's in-kernel-globals flavor, fused
  ``k`` iterations per band pass (one HBM round trip per ``k`` steps);
* the BACKWARD band kernel re-traces the SAME action chain
  (``run_action_plan`` — the exact collide semantics of the forward
  kernel) on a band extended by the chain's total reach ``R`` and takes
  ``jax.vjp`` of it in-band.  A band of ``lambda_in`` rows ``[a, b)``
  receives cotangent only from output rows within ``R``; computing the
  chain on ``[a-R, b+R)`` from inputs on ``[a-2R, b+2R)`` (all inside the
  8-row DMA halo blocks) covers that cone exactly, so no cross-band
  scatter is needed — the transposed streaming falls out of the VJP of
  the in-band pull slices.

Because the VJP differentiates the full traced chain, the scope is the
generic engine's own: multi-stage actions, Field stencils, zonal
settings, and — unlike round 4 — cotangents for SETTINGS (accumulated
in-kernel across bands, the ``DynamicsS_b`` analogue) and for the aux
stack (zonal planes + Control ``_DT`` planes), which chain to
``params.time_series`` so OptimalControl/Fourier/BSpline control
gradients run fused too (``series=True`` flavor, one step per chunk).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tclb_tpu.core.lattice import LatticeState, SimParams
from tclb_tpu.core.registry import Model
from tclb_tpu.ops import fusion, pallas_generic
from tclb_tpu.ops.pallas_generic import (_CompilerParams, _HALO, KernelCtx,
                                         action_plan, run_action_plan)

_probe_cache: dict = {}


def max_chunk(model: Model, cap: int = 4) -> int:
    """Largest per-chunk iteration count ``k`` whose fused chain reach
    fits the backward kernel's halo budget (``2*R <= 8``: the in-band
    chain needs inputs ``2R`` beyond the band)."""
    best = 0
    for k in range(1, cap + 1):
        _, reach = action_plan(model, "Iteration", fuse=k)
        if 2 * max(reach, 1) <= _HALO:
            best = k
    return best


def supports_diff(model: Model, shape, dtype, series: bool = False) -> bool:
    """Whether the differentiable Pallas chunk covers this configuration:
    everything the forward generic kernel needs, plus aligned unpadded
    shapes (the backward band kernel has no ghost-row machinery), chain
    reach within the halo budget, and SUM Globals (the objective).

    3D models (d3q19_adj and friends) route to the z-slab flavor: the
    forward sweep runs the fused 3D Pallas engine, the backward the XLA
    whole-array chain (see :func:`_make_diff_step_3d`)."""
    if model.ndim == 3 and len(shape) == 3:
        return _supports_diff_3d(model, shape, dtype, series)
    if model.ndim != 2 or len(shape) != 2:
        return False
    if not pallas_generic.supports(model, shape, dtype, probe=False):
        return False
    ny, nx = (int(s) for s in shape)
    if ny % 8 or nx % 128:
        return False
    if pallas_generic._pad_rows(model, ny, nx, 1) != 0:
        return False
    if max_chunk(model) < 1:
        return False
    if not (1 <= model.n_globals <= 8) \
            or any(g.op != "SUM" for g in model.globals_):
        return False
    if len(model.settings) > 1024:
        return False   # the (8, 128) in-kernel settings-tape accumulator
    if series and not model.zonal_settings:
        return False
    # static gates from the analyzer: the backward kernel's scratch at
    # this width (ineligibility decided before any compile), and the
    # stencil-footprint safety verdict (a stage reading beyond its
    # declaration would make the band chain silently wrong)
    from tclb_tpu import analysis
    from tclb_tpu.analysis import resources
    if not resources.adjoint_static_ok(model, nx, series):
        return False
    if not analysis.kernel_safety_ok(model):
        return False
    # cache on the structural fingerprint, not id(model): rebuilt-but-
    # identical models share the verdict, and a recycled address can
    # never inherit a stale one.  Probe at the PRODUCTION chunk
    # k=max_chunk — the fused-chain trace the engine actually builds
    # (the historical k=1 probe validated a chain nobody runs).
    key = (model.fingerprint, nx, series)
    if key not in _probe_cache:
        try:
            step = make_diff_step(model, (16, nx), dtype, interpret=True,
                                  series=series,
                                  k=1 if series else max_chunk(model))
            n_aux = 1 + (2 if series else 1) * len(model.zonal_settings)
            fields = jax.ShapeDtypeStruct((model.n_storage, 16, nx), dtype)
            sett = jax.ShapeDtypeStruct((len(model.settings),), dtype)
            aux = jax.ShapeDtypeStruct((n_aux, 16, nx), dtype)

            def loss(f, s, a):
                out, g, g_last = step.arrays(f, s, a,
                                             jnp.zeros((1,), jnp.int32))
                return jnp.sum(out) + jnp.sum(g) + jnp.sum(g_last)

            jax.eval_shape(jax.grad(loss, argnums=(0, 1, 2)),
                           fields, sett, aux)
            _probe_cache[key] = True
        except Exception as e:  # noqa: BLE001 — untraceable = ineligible
            from tclb_tpu.utils import log
            log.debug(f"pallas_adjoint: {model.name} diff probe failed: "
                      f"{type(e).__name__}: {str(e)[:200]}")
            _probe_cache[key] = False
    return _probe_cache[key]


def _supports_diff_3d(model: Model, shape, dtype,
                      series: bool = False) -> bool:
    """3D eligibility: the generic z-slab engine must cover the
    configuration (its in-kernel-globals flavor is the forward sweep),
    the objective must be SUM Globals, and the traced grad probe at the
    production chunk size must go through.  When
    :func:`adjoint_slab_plan` finds a feasible ``(k, bz)`` the backward
    runs the fused z-slab ``Run_b`` kernel; otherwise the step degrades
    to the XLA-chain backward (still eligible — the forward sweep
    dominates a revolve adjoint).  The Control-series flavor is 2D-only
    for now."""
    if series:
        return False
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32):
        return False
    if not pallas_generic.supports(model, shape, dtype, probe=False):
        return False
    nz, ny, nx = (int(s) for s in shape)
    if ny % 8 or nx % 128:
        return False
    if not (1 <= model.n_globals <= 8) \
            or any(g.op != "SUM" for g in model.globals_):
        return False
    if len(model.settings) > 1024:
        return False   # the (8, 128) in-kernel settings-tape accumulator
    from tclb_tpu import analysis
    if not analysis.kernel_safety_ok(model):
        return False
    key = (model.fingerprint, tuple(shape), "3d")
    if key not in _probe_cache:
        try:
            step = make_diff_step(model, shape, dtype, interpret=True,
                                  k=max_chunk(model))
            fields = jax.ShapeDtypeStruct((model.n_storage,) + tuple(shape),
                                          dtype)
            flags = jax.ShapeDtypeStruct(tuple(shape), jnp.uint16)

            def loss(f):
                from tclb_tpu.core.lattice import LatticeState
                st = LatticeState(
                    fields=f,
                    flags=jnp.zeros(tuple(shape), jnp.uint16),
                    globals_=jnp.zeros((model.n_globals,), dtype),
                    iteration=jnp.zeros((), jnp.int32))
                st2, ginc = step.prepare(st, _probe_params(model, dtype))(
                    st, _probe_params(model, dtype))
                return jnp.sum(st2.fields) + jnp.sum(ginc)

            jax.eval_shape(jax.grad(loss), fields)
            del flags
            _probe_cache[key] = True
        except Exception as e:  # noqa: BLE001 — untraceable = ineligible
            from tclb_tpu.utils import log
            log.debug(f"pallas_adjoint: {model.name} 3d diff probe "
                      f"failed: {type(e).__name__}: {str(e)[:200]}")
            _probe_cache[key] = False
    return _probe_cache[key]


def _probe_params(model: Model, dtype):
    from tclb_tpu.core.lattice import SimParams
    n_sett = len(model.settings)
    return SimParams(settings=jnp.zeros((n_sett,), dtype),
                     zone_table=jnp.zeros((n_sett, model.zone_max), dtype))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _roll3_prim(x, s, nx):
    return pltpu.roll(x, s, axis=2)


def _roll3_fwd(x, s, nx):
    return _roll3_prim(x, s, nx), None


def _roll3_bwd(s, nx, _res, ct):
    # same linearity argument as the 2D _roll_prim, lane axis 2: the
    # transpose of out[..., i] = x[..., i - s] is the opposite roll
    return (_roll3_prim(ct, (nx - s) % nx, nx),)


_roll3_prim.defvjp(_roll3_fwd, _roll3_bwd)


def _lane_roll3(sl, shift, nx):
    s = shift % nx
    return _roll3_prim(sl, s, nx) if s else sl


def adjoint_slab_plan(model: Model, shape, k: Optional[int] = None,
                      budget: Optional[int] = None):
    """The fused 3D backward's ``(k, bz)`` — None when no chunk/slab
    config fits the VMEM budget (the builder then degrades to the XLA
    backward).  Thin model-aware wrapper over
    :func:`tclb_tpu.ops.fusion.adjoint_slab_plan` so the builder, the
    eligibility gate and the static analyzers all plan identically."""
    nz, ny, nx = (int(s) for s in shape)
    if k is None:
        k = max_chunk(model)
    if k < 1:
        return None
    # the backward aux stack is one flags plane either way: zonal models
    # run the lean flavor (planes rebuilt in-kernel from the SMEM zone
    # table), zonal-free models have nothing beyond flags
    return fusion.adjoint_slab_plan(
        nz, model.n_storage, ny * nx * 4,
        lambda f: action_plan(model, "Iteration", fuse=f)[1], k,
        n_aux=1, budget=budget)


def _make_diff_step_3d(model: Model, shape, dtype=jnp.float32,
                       interpret: Optional[bool] = None,
                       present: Optional[set] = None,
                       k: Optional[int] = None,
                       bwd: str = "auto"):
    """The 3D differentiable chunk: ``custom_vjp`` pairing the z-slab
    Pallas engine's in-kernel-globals flavor (forward) with a z-slab
    Pallas BACKWARD band kernel — the 3D ``Run_b``.

    The backward mirrors the forward's DMA pipeline on slabs haloed by
    ``2*R`` (the adjoint-band rule: the in-band chain recomputes the
    forward cone AND transposes it, each costing reach ``R``), pulls the
    chunk-input primal + the output cotangent + the flags plane on three
    double-buffered stacks, re-traces the fused action chain FULL-SLAB
    (every per-row op identical to the windowed forward on the rows the
    window mask keeps) and takes ``jax.vjp`` of it in-band; the settings
    tape accumulates per-slab so band overlaps never double-count.
    ``bwd="xla"`` keeps the PR 9 hybrid (Pallas forward / XLA-chain
    backward) — the measured baseline ``bench.py``'s
    ``adjoint3d_speedup`` compares against; ``"auto"`` takes the fused
    kernel whenever :func:`adjoint_slab_plan` finds a feasible config."""
    nz, ny, nx = (int(s) for s in shape)
    if k is None:
        k = max_chunk(model)
    plan3 = adjoint_slab_plan(model, shape, k) if bwd != "xla" else None
    if bwd == "pallas" and plan3 is None:
        raise ValueError(f"{model.name} {shape}: no (k, bz) fits the "
                         "fused 3D backward's VMEM budget")
    fused = plan3 is not None
    if fused:
        # the chunk the WHOLE diff step (forward loop included) runs at:
        # a divisor of the requested k, so the caller's niter % k == 0
        # guarantee carries over
        k = plan3[0]
    base = pallas_generic.make_pallas_iterate_3d(
        model, shape, dtype, interpret=interpret, fuse=1, present=present)
    impl = base._impl
    call_g = impl["call_g"]
    if call_g is None:
        raise ValueError(f"{model.name}: 3D diff step needs the "
                         "in-kernel-globals flavor (SUM globals, "
                         "nx % 128 == 0)")
    lean = impl["lean_aux"]
    zonal_si, zshift = impl["zonal_si"], impl["zshift"]
    adv, cdtype = impl["adv"], impl["cdtype"]
    n_globals = model.n_globals
    from tclb_tpu.core.lattice import make_action_step
    xla_step = make_action_step(model, "Iteration", present=present)

    call_bwd = _mk_call_bwd_3d(model, shape, cdtype, interpret, present,
                               k, plan3[1], lean) if fused else None
    n_sett = len(model.settings)

    def _mk_step(params: SimParams, flags):
        if params.time_series is not None:
            raise ValueError(
                "the 3D diff step has no Control-series flavor; use "
                "engine='xla' for series designs")
        @jax.custom_vjp
        def chunk(fields, p, fl, itv):
            flags_i32 = fl.astype(jnp.int32)
            sett = p.settings.astype(cdtype)
            if lean:
                ztab = jnp.concatenate(
                    [p.zone_table[j].astype(cdtype) for j in zonal_si])
                aux = flags_i32.astype(cdtype)[None]

                def call(f, it):
                    return call_g(sett, it[None], ztab, f, aux)
            else:
                zones = flags_i32 >> zshift
                aux = jnp.stack(
                    [flags_i32.astype(cdtype)]
                    + [p.zone_table[j].astype(cdtype)[zones]
                       for j in zonal_si])

                def call(f, it):
                    return call_g(sett, it[None], f, aux)
            f, gs, gl = fields, None, None
            for j in range(k):
                f, gpart = call(f, itv + adv * j)
                g_now = gpart[:n_globals].sum(axis=1)
                gs = g_now if gs is None else gs + g_now
                gl = g_now
            return f, gs, gl

        def chunk_fwd(fields, p, fl, itv):
            return chunk(fields, p, fl, itv), (fields, p, fl, itv)

        def chunk_bwd_xla(res, cot):
            fields, p, fl, itv = res
            cot_f, cot_g, cot_gl = cot

            def ref(fs, pp):
                st = LatticeState(
                    fields=fs, flags=fl,
                    globals_=jnp.zeros((n_globals,), cdtype),
                    iteration=itv)
                gs = None
                for _ in range(k):
                    st = xla_step(st, pp)
                    gs = st.globals_ if gs is None else gs + st.globals_
                return st.fields, gs, st.globals_

            (_, gs_ref, gl_ref), vjp = jax.vjp(ref, fields, p)
            cf, cp = vjp((cot_f.astype(fields.dtype),
                          cot_g.astype(gs_ref.dtype),
                          cot_gl.astype(gl_ref.dtype)))
            return (cf, cp,
                    np.zeros(np.shape(fl), jax.dtypes.float0),
                    np.zeros(np.shape(itv), jax.dtypes.float0))

        def chunk_bwd_pallas(res, cot):
            fields, p, fl, itv = res
            cot_f, cot_g, cot_gl = cot
            lg = jnp.stack([cot_g.astype(cdtype), cot_gl.astype(cdtype)])
            sett = p.settings.astype(cdtype)
            flags_i32 = fl.astype(jnp.int32)
            it_arr = jnp.asarray(itv, jnp.int32).reshape((1,))
            lam_f_ct = cot_f.astype(cdtype)
            if lean:
                ztab = jnp.concatenate(
                    [p.zone_table[j].astype(cdtype) for j in zonal_si])
                aux = flags_i32.astype(cdtype)[None]
                lam_f, sett_acc = call_bwd(sett, lg, it_arr, ztab,
                                           fields.astype(cdtype),
                                           lam_f_ct, aux)
            else:
                zones = flags_i32 >> zshift
                aux = jnp.stack(
                    [flags_i32.astype(cdtype)]
                    + [p.zone_table[j].astype(cdtype)[zones]
                       for j in zonal_si])
                lam_f, sett_acc = call_bwd(sett, lg, it_arr,
                                           fields.astype(cdtype),
                                           lam_f_ct, aux)
            lam_sett = sett_acc.reshape(-1)[:n_sett]
            # non-series 3D: cotangents flow to the scalar settings (the
            # in-kernel tape); the zone-table/aux cotangent is zero —
            # the same aux_grad=False contract as the 2D default
            cp = jax.tree.map(jnp.zeros_like, p)
            cp = cp.replace(settings=lam_sett.astype(p.settings.dtype))
            return (lam_f.astype(fields.dtype), cp,
                    np.zeros(np.shape(fl), jax.dtypes.float0),
                    np.zeros(np.shape(itv), jax.dtypes.float0))

        chunk.defvjp(chunk_fwd,
                     chunk_bwd_pallas if fused else chunk_bwd_xla)

        def step(state: LatticeState, p2: SimParams):
            new_fields, g, g_last = chunk(state.fields, p2, state.flags,
                                          state.iteration)
            return LatticeState(
                fields=new_fields, flags=state.flags,
                globals_=g_last.astype(state.globals_.dtype),
                iteration=state.iteration + adv * k), g
        return step

    def step(state: LatticeState, params: SimParams):
        return _mk_step(params, state.flags)(state, params)

    def prepare(state: LatticeState, params: SimParams):
        return _mk_step(params, state.flags)

    step.prepare = prepare
    step.chunk = k
    step.returns_inc = True
    if fused:
        step.engine_name = (f"pallas_adjoint[{model.name},k={k},"
                            f"bz={plan3[1]},3d]")
    else:
        step.engine_name = (f"pallas_adjoint3d[{model.name},k={k},"
                            f"bz={impl['bz']},bwd=xla]")
    return step


def _mk_call_bwd_3d(model: Model, shape, cdtype, interpret, present,
                    k: int, bz: int, lean: bool):
    """Build the z-slab backward band kernel (``Run_b``): one grid step
    per slab band, halo = ``2 * R(k)`` slabs per side (adjoint-band
    rule), three double-buffered DMA stacks (chunk-input primal, output
    cotangent, flags/aux), in-band ``jax.vjp`` of the full-slab fused
    action chain.  Returns ``call(sett, lg, it, [ztab,] primal, lam_out,
    aux) -> (lam_in, settings_tape)``."""
    nz, ny, nx = (int(s) for s in shape)
    plan_k, reach_k = action_plan(model, "Iteration", fuse=k)
    Rk = max(reach_k, 1)
    Hb = bz + 4 * Rk
    ns = model.n_storage
    n_globals = model.n_globals
    n_sett = len(model.settings)
    zonal_names = list(model.zonal_settings)
    zone_max = model.zone_max
    zshift = model.zone_shift
    n_aux = 1 if lean else 1 + len(zonal_names)
    n_sem = 1 + 4 * Rk
    ei = model.ei
    stage_fns = {nm: model.stage_fns[model.stages[nm].main]
                 for nm in model.actions["Iteration"]}
    loads_density = {nm: model.stages[nm].load_densities
                     for nm in model.actions["Iteration"]}
    nt_present = set(model.node_types) if present is None else set(present)
    n_per_rep = len(model.actions["Iteration"])
    adv = int(any(model.stages[s].load_densities
                  for s in model.actions["Iteration"]))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def bwd_kernel(sett, lg_ref, it_ref, *rest):
        if lean:
            ztab, p_hbm, l_hbm, a_hbm, *refs = rest
        else:
            ztab = None
            p_hbm, l_hbm, a_hbm, *refs = rest
        out_lam, out_sett, bufp, bufl, bufa, sems = refs
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            # halo slabs one at a time with modular indices (a block
            # copy straddling the periodic z boundary would read out of
            # bounds — same scheme as the forward slab kernel, halo 2R)
            base = band * jnp.int32(bz)
            out = []
            for si_, (hbm, buf, nplanes) in enumerate((
                    (p_hbm, bufp, ns), (l_hbm, bufl, ns),
                    (a_hbm, bufa, n_aux))):
                out.append(pltpu.make_async_copy(
                    hbm.at[pl.ds(0, nplanes), pl.ds(base, bz)],
                    buf.at[slot, :, pl.ds(2 * Rk, bz)],
                    sems.at[slot, n_sem * si_]))
                for r in range(2 * Rk):
                    zm_r = jax.lax.rem(
                        base - jnp.int32(2 * Rk - r) + jnp.int32(nz),
                        jnp.int32(nz))
                    zp_r = jax.lax.rem(base + jnp.int32(bz + r),
                                       jnp.int32(nz))
                    out.append(pltpu.make_async_copy(
                        hbm.at[pl.ds(0, nplanes), pl.ds(zm_r, 1)],
                        buf.at[slot, :, pl.ds(r, 1)],
                        sems.at[slot, n_sem * si_ + 1 + r]))
                    out.append(pltpu.make_async_copy(
                        hbm.at[pl.ds(0, nplanes), pl.ds(zp_r, 1)],
                        buf.at[slot, :, pl.ds(2 * Rk + bz + r, 1)],
                        sems.at[slot, n_sem * si_ + 1 + 2 * Rk + r]))
            return out

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for d in band_dmas(jnp.int32(0), i):
                d.start()

        @pl.when(i + 1 < n)
        def _():
            for d in band_dmas(nxt, i + jnp.int32(1)):
                d.start()

        for d in band_dmas(slot, i):
            d.wait()

        sv = jnp.stack([sett[j] for j in range(n_sett)])
        it0 = it_ref[0]
        # settings enter the trace PER SLAB: the cotangent seeds span the
        # R-extended window overlapping the neighbor bands' windows, so a
        # scalar settings cotangent would double-count the margin slabs;
        # slab-resolved cotangents can be band-trimmed before the
        # cross-band accumulation (the 2D tape's argument, z-banded)
        sv_rows = jnp.broadcast_to(sv[None, :], (Hb, n_sett))

        class _RowSett3:
            def __init__(self, rows):
                self._rows = rows

            def __getitem__(self, j):
                return self._rows[:, j][:, None, None]

        flags_full = bufa[slot, 0].astype(jnp.int32)
        if ztab is not None:
            zones_full = flags_full >> zshift
            zonal_full = {nm: fusion.zone_plane(ztab, j, zone_max,
                                                zones_full)
                          for j, nm in enumerate(zonal_names)}
        else:
            zonal_full = {nm: bufa[slot, 1 + j]
                          for j, nm in enumerate(zonal_names)}

        def _rollyx(sl, dy, dx):
            if dy:
                sl = jnp.roll(sl, dy, axis=1)
            if dx % nx:
                sl = _lane_roll3(sl, dx, nx)
            return sl

        def C(work, sv_rows_):
            """The forward chunk traced FULL-SLAB from this band's
            buffers: per-row ops identical to the windowed forward
            kernel (z pulls become axis-0 rolls whose wrap garbage stays
            in the outermost ``Rk`` slabs), so rows inside the window
            mask below linearize exactly the physics that ran."""
            work = list(work)
            g_acc: dict = {}
            g_lst: dict = {}
            for st_i, (stage_name, _ext) in enumerate(plan_k):
                rep = st_i // n_per_rep
                if loads_density[stage_name]:
                    planes = []
                    for k_ in range(ns):
                        dxk, dyk, dzk = (int(v) for v in ei[k_])
                        sl = jnp.roll(work[k_], dzk, axis=0) if dzk \
                            else work[k_]
                        planes.append(_rollyx(sl, dyk, dxk))
                else:
                    planes = list(work)

                def loader(index, dx, dy, dz=0):
                    sl = work[index]
                    if dz:
                        sl = jnp.roll(sl, -dz, axis=0)
                    return _rollyx(sl, -dy, -dx)

                ctx = KernelCtx(
                    model, planes, loader, flags_full, dict(zonal_full),
                    _RowSett3(sv_rows_), cdtype, it0 + adv * rep,
                    nt_present, compute_globals=True)
                res = stage_fns[stage_name](ctx)
                for nm, plane in ctx._globals.items():
                    g_acc[nm] = plane if nm not in g_acc \
                        else g_acc[nm] + plane
                    if rep == k - 1:
                        g_lst[nm] = plane if nm not in g_lst \
                            else g_lst[nm] + plane

                if isinstance(res, dict):
                    updates: dict[int, jnp.ndarray] = {}
                    for name, stack in res.items():
                        if name in model.groups:
                            idx = model.groups[name]
                            if len(idx) == 1 and stack.ndim == 3:
                                updates[idx[0]] = stack
                            else:
                                for j, k_ in enumerate(idx):
                                    updates[k_] = stack[j]
                        else:
                            updates[model.storage_index[name]] = stack
                else:
                    updates = {k_: res[k_] for k_ in range(ns)}
                for k_, new in updates.items():
                    work[k_] = new
            zero_pl = jnp.zeros((Hb, ny, nx), cdtype)
            gpl = [g_acc.get(g.name, zero_pl) for g in model.globals_]
            gll = [g_lst.get(g.name, zero_pl) for g in model.globals_]
            return jnp.stack(work), jnp.stack(gpl), jnp.stack(gll)

        pst = [bufp[slot, j] for j in range(ns)]
        _, vjp_fn = jax.vjp(C, pst, sv_rows)
        # cotangent seeds live on the R-extended output window
        # [band - R, band + bz + R): slabs beyond it either belong to the
        # neighbor bands' lambda_in (they own those output slabs) or hold
        # full-slab roll garbage — both masked to zero
        rows = jax.lax.broadcasted_iota(jnp.int32, (Hb, ny, nx), 0)
        win = (rows >= Rk) & (rows < bz + 3 * Rk)
        zero_pl = jnp.zeros((Hb, ny, nx), cdtype)
        lam_win = jnp.stack(
            [jnp.where(win, bufl[slot, j], zero_pl) for j in range(ns)])
        lgpl = jnp.stack(
            [jnp.where(win, jnp.full((Hb, ny, nx), lg_ref[0, gi], cdtype),
                       zero_pl) for gi in range(n_globals)])
        lgll = jnp.stack(
            [jnp.where(win, jnp.full((Hb, ny, nx), lg_ref[1, gi], cdtype),
                       zero_pl) for gi in range(n_globals)])
        lam_p, lam_sv_rows = vjp_fn((lam_win, lgpl, lgll))

        for j in range(ns):
            out_lam[j] = lam_p[j][2 * Rk:2 * Rk + bz]

        @pl.when(i == 0)
        def _():
            out_sett[...] = jnp.zeros((8, 128), cdtype)
        # band slabs only: margin slabs belong to the neighbor bands
        lam_sv = lam_sv_rows[2 * Rk:2 * Rk + bz, :].sum(axis=0)
        pad_s = jnp.concatenate(
            [lam_sv, jnp.zeros((1024 - n_sett,), cdtype)]).reshape((8, 128))
        out_sett[...] = out_sett[...] + pad_s

    return pl.pallas_call(
        bwd_kernel,
        grid=(nz // bz,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ] + ([pl.BlockSpec(memory_space=pltpu.SMEM)] if lean else [])
        + [
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((ns, bz, ny, nx), lambda i: (0, i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((ns, nz, ny, nx), cdtype),
            jax.ShapeDtypeStruct((8, 128), cdtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, ns, Hb, ny, nx), cdtype),
            pltpu.VMEM((2, ns, Hb, ny, nx), cdtype),
            pltpu.VMEM((2, n_aux, Hb, ny, nx), cdtype),
            pltpu.SemaphoreType.DMA((2, 3 * n_sem)),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )


def make_diff_step(model: Model, shape, dtype=jnp.float32,
                   interpret: Optional[bool] = None,
                   present: Optional[set] = None,
                   k: Optional[int] = None,
                   series: bool = False,
                   aux_grad: Optional[bool] = None,
                   by_bwd: Optional[int] = None,
                   bwd: str = "auto"):
    """Build ``step(state, params) -> (state, chunk_globals)`` advancing
    ``step.chunk`` iterations on the fused Pallas kernels,
    differentiable end-to-end: forward = the generic engine's
    in-kernel-globals flavor at ``fuse=k``, backward = the in-band VJP
    of the same chain (module docstring).  Plugs into
    :func:`tclb_tpu.adjoint.run.make_objective_run` via the
    ``returns_inc`` protocol: ``state.globals_`` keeps LAST-iteration
    semantics (matching the per-step engines) while ``chunk_globals``
    is the k-step sum the time-integrated objective accumulates.

    ``series=True`` builds the Control-series flavor: one step per
    chunk, per-iteration zonal + ``_DT`` aux planes rebuilt (and
    differentiated) each step, cotangents flowing to
    ``params.time_series`` — the reference's control-gradient tape.
    ``aux_grad`` (default = ``series``) controls whether the backward
    kernel emits the aux-stack cotangent at all (an extra HBM write).

    3D shapes dispatch to :func:`_make_diff_step_3d` (z-slab Pallas
    forward AND backward; ``bwd="xla"`` keeps the PR 9 hybrid as the
    measured baseline; no series flavor)."""
    if len(shape) == 3:
        if series:
            raise ValueError("3D diff step: no Control-series flavor")
        return _make_diff_step_3d(model, shape, dtype,
                                  interpret=interpret, present=present,
                                  k=k, bwd=bwd)
    ny, nx = (int(s) for s in shape)
    if series:
        k = 1
    if k is None:
        k = max_chunk(model)
    if aux_grad is None:
        aux_grad = series
    plan_k, reach = action_plan(model, "Iteration", fuse=k)
    R = max(reach, 1)
    if 2 * R > _HALO:
        raise ValueError(f"chunk k={k} reach {reach} exceeds halo budget")
    if ny % 8 or nx % 128:
        raise ValueError(f"diff step needs aligned shape, got {shape}")

    # full_band: all-aligned stage windows — measurably faster at fuse=k
    # and REQUIRED for the backward chain (the VJP cone arithmetic below
    # assumes full-height stages)
    base = pallas_generic.make_pallas_iterate(
        model, shape, dtype, interpret=interpret, fuse=1, present=present,
        full_band=True)
    impl = base._impl
    if impl["pad"] != 0:
        raise ValueError("diff step requires an unpadded band layout")
    mk_call = impl["mk_call"]
    call_f = mk_call(plan_k, with_dt=series, with_globals="split")
    zonal_si, zshift = impl["zonal_si"], impl["zshift"]
    nt_present = impl["nt_present"]
    # backward bands default WIDER than the forward's (64 vs 32): the
    # halo margin is pure compute waste for the in-band chain, and the
    # k=4/by=64 point measured fastest on v5e (raised vmem ceiling
    # below).  The default scales down with nx so the three
    # double-buffered scratch stacks stay within ~1/4 of the raised
    # ceiling, leaving room for the VJP chain's live temporaries.
    if by_bwd is None:
        n_aux_b = 1 + (2 if series else 1) * len(model.zonal_settings)
        per_row = (2 * model.n_storage + n_aux_b) * nx * 4
        by_bwd = 64
        while by_bwd > 8 and 2 * (by_bwd + 2 * _HALO) * per_row \
                > 24 * 1024 * 1024:
            by_bwd -= 8
    by = max(8, (by_bwd // 8) * 8)
    while by > 8 and ny % by:
        by -= 8
    if ny % by:
        raise ValueError(f"no 8-aligned backward band divides ny={ny}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    ns = model.n_storage
    n_globals = model.n_globals
    n_sett = len(model.settings)
    zonal_names = list(model.zonal_settings)
    n_aux = 1 + (2 if series else 1) * len(zonal_names)
    n_per_rep = len(model.actions["Iteration"])
    adv = int(any(model.stages[s].load_densities
                  for s in model.actions["Iteration"]))

    def bwd_kernel(sett, lg_ref, it_ref, p_hbm, l_hbm, aux_hbm, *refs):
        """One band pass of the reverse sweep: pulled primal chunk-input
        + lambda_out + aux on 8-row-haloed bands, in-band VJP of the
        traced action chain, emitting the band's lambda_in rows plus the
        accumulated settings tape (and optionally the aux cotangent)."""
        if aux_grad:
            out_lam, out_sett, out_laux, bufp, bufl, bufa, sems = refs
        else:
            (out_lam, out_sett, bufp, bufl, bufa, sems), out_laux = \
                refs, None
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            base_r = pl.multiple_of(band * jnp.int32(by), 8)
            top8 = pl.multiple_of(
                jax.lax.rem(base_r - jnp.int32(_HALO) + jnp.int32(ny),
                            jnp.int32(ny)), 8)
            bot8 = pl.multiple_of(
                jax.lax.rem(base_r + jnp.int32(by), jnp.int32(ny)), 8)
            out = []
            for si_, (hbm, buf) in enumerate(
                    ((p_hbm, bufp), (l_hbm, bufl), (aux_hbm, bufa))):
                out += [
                    pltpu.make_async_copy(
                        hbm.at[:, pl.ds(base_r, by), :],
                        buf.at[slot, :, pl.ds(_HALO, by), :],
                        sems.at[slot, 3 * si_]),
                    pltpu.make_async_copy(
                        hbm.at[:, pl.ds(top8, _HALO), :],
                        buf.at[slot, :, pl.ds(0, _HALO), :],
                        sems.at[slot, 3 * si_ + 1]),
                    pltpu.make_async_copy(
                        hbm.at[:, pl.ds(bot8, _HALO), :],
                        buf.at[slot, :, pl.ds(_HALO + by, _HALO), :],
                        sems.at[slot, 3 * si_ + 2]),
                ]
            return out

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for d in band_dmas(jnp.int32(0), i):
                d.start()

        @pl.when(i + 1 < n)
        def _():
            for d in band_dmas(nxt, i + jnp.int32(1)):
                d.start()

        for d in band_dmas(slot, i):
            d.wait()

        sv = jnp.stack([sett[j] for j in range(n_sett)])
        it0 = it_ref[0]
        H = by + 2 * _HALO
        # settings enter the trace PER ROW: the cotangent seeds below span
        # the R-extended window, which overlaps the neighboring bands'
        # windows — a scalar settings cotangent would double-count the
        # margin rows across bands.  Row-resolved cotangents can be
        # band-masked before the cross-band accumulation.
        sv_rows = jnp.broadcast_to(sv[None, :], (H, n_sett))

        class _RowSett:
            def __init__(self, rows):
                self._rows = rows

            def __getitem__(self, i):
                return self._rows[:, i][:, None]

        def C(work, aux_pl, sv_rows_):
            """The forward chunk traced full-band from this band's
            buffers — run_action_plan is the SAME function the forward
            kernel executes, so the VJP transposes exactly the physics
            that ran.  full_band keeps every op tile-aligned; the edge
            rows beyond the chain's reach hold garbage, which the
            WINDOW-MASKED seeds below exclude from the cotangent."""
            flags_full = aux_pl[0].astype(jnp.int32)
            zonal_full = {nm: aux_pl[1 + j]
                          for j, nm in enumerate(zonal_names)}
            dt_full = {nm: aux_pl[1 + len(zonal_names) + j]
                       for j, nm in enumerate(zonal_names)} if series else {}
            work, g_acc, g_lst = run_action_plan(
                model, plan_k, list(work), flags_full, zonal_full,
                dt_full, _RowSett(sv_rows_), it0, nt_present, _HALO, nx,
                dtype, n_per_rep=n_per_rep, collect_globals=True,
                full_band=True)
            gpl = [g_acc.get(g.name, jnp.zeros((H, nx), dtype))
                   for g in model.globals_]
            gll = [g_lst.get(g.name, jnp.zeros((H, nx), dtype))
                   for g in model.globals_]
            return jnp.stack(work), jnp.stack(gpl), jnp.stack(gll)

        pst = [bufp[slot, j] for j in range(ns)]
        apl = [bufa[slot, j] for j in range(n_aux)]
        _, vjp_fn = jax.vjp(C, pst, apl, sv_rows)
        # cotangent seeds live on the R-extended output window
        # [band - R, band + by + R): rows beyond it either belong to the
        # neighboring bands' lambda_in (they own those output rows) or
        # hold full-band garbage — both masked to zero
        rows = jax.lax.broadcasted_iota(jnp.int32, (H, nx), 0)
        win = (rows >= _HALO - R) & (rows < _HALO + by + R)
        lam_win = jnp.stack(
            [jnp.where(win, bufl[slot, j], jnp.zeros((H, nx), dtype))
             for j in range(ns)])
        zero_pl = jnp.zeros((H, nx), dtype)
        lgpl = jnp.stack(
            [jnp.where(win, jnp.full((H, nx), lg_ref[0, gi], dtype),
                       zero_pl) for gi in range(n_globals)])
        lgll = jnp.stack(
            [jnp.where(win, jnp.full((H, nx), lg_ref[1, gi], dtype),
                       zero_pl) for gi in range(n_globals)])
        lam_p, lam_aux, lam_sv_rows = vjp_fn((lam_win, lgpl, lgll))

        for j in range(ns):
            out_lam[j] = lam_p[j][_HALO:_HALO + by, :]
        if out_laux is not None:
            for j in range(n_aux):
                out_laux[j] = lam_aux[j][_HALO:_HALO + by, :]

        @pl.when(i == 0)
        def _():
            out_sett[...] = jnp.zeros((8, 128), dtype)
        # band rows only: margin rows belong to the neighboring bands
        lam_sv = lam_sv_rows[_HALO:_HALO + by, :].sum(axis=0)
        pad_s = jnp.concatenate(
            [lam_sv, jnp.zeros((1024 - n_sett,), dtype)]).reshape((8, 128))
        out_sett[...] = out_sett[...] + pad_s

    out_specs = [
        pl.BlockSpec((ns, by, nx), lambda i: (0, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((8, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((ns, ny, nx), dtype),
        jax.ShapeDtypeStruct((8, 128), dtype),
    ]
    if aux_grad:
        out_specs.append(pl.BlockSpec((n_aux, by, nx), lambda i: (0, i, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((n_aux, ny, nx), dtype))

    call_bwd = pl.pallas_call(
        bwd_kernel,
        grid=(ny // by,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((2, ns, by + 2 * _HALO, nx), dtype),
            pltpu.VMEM((2, ns, by + 2 * _HALO, nx), dtype),
            pltpu.VMEM((2, n_aux, by + 2 * _HALO, nx), dtype),
            pltpu.SemaphoreType.DMA((2, 9)),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )

    @jax.custom_vjp
    def step_arrays(fields, sett, aux, itv):
        out, gpart = call_f(sett, itv, fields, aux)
        # [0] chunk-summed globals (the objective increment over the k
        # fused steps), [1] last-iteration globals (state.globals_ —
        # same semantics as the per-step engines)
        return (out, gpart[0, :n_globals].sum(axis=1),
                gpart[1, :n_globals].sum(axis=1))

    def step_f(fields, sett, aux, itv):
        out = step_arrays(fields, sett, aux, itv)
        return out, (fields, sett, aux, itv)

    def step_b(res, cot):
        fields, sett, aux, itv = res
        lam_f, lam_g, lam_gl = cot
        lg = jnp.stack([lam_g.astype(dtype), lam_gl.astype(dtype)])
        outs = call_bwd(sett, lg, itv, fields, lam_f, aux)
        if aux_grad:
            lam_fields, sett_acc, lam_aux = outs
        else:
            lam_fields, sett_acc = outs
            lam_aux = jnp.zeros_like(aux)
        lam_sett = sett_acc.reshape(-1)[:n_sett]
        return (lam_fields, lam_sett, lam_aux,
                np.zeros((1,), jax.dtypes.float0))

    step_arrays.defvjp(step_f, step_b)

    def _aux_base(params: SimParams, flags):
        flags_i32 = flags.astype(jnp.int32)
        zones = flags_i32 >> zshift
        base = [params.zone_table[j].astype(dtype)[zones] for j in zonal_si]
        return flags_i32.astype(dtype), zones, base

    def _aux_series(params: SimParams, flags_f, zones, base, it):
        return pallas_generic.assemble_aux(params, zones, flags_f, base,
                                           zonal_si, it, dtype,
                                           with_dt=True)

    def _mk_step(params: SimParams, flags):
        sett = params.settings.astype(dtype)
        flags_f, zones, base = _aux_base(params, flags)
        if series:
            def step(state: LatticeState, p2: SimParams):
                it = state.iteration
                aux = _aux_series(p2, flags_f, zones, base, it)
                new_fields, g, g_last = step_arrays(
                    state.fields, sett, aux,
                    it[None].astype(jnp.int32) if it.ndim == 0 else it)
                return LatticeState(
                    fields=new_fields, flags=state.flags,
                    globals_=g_last.astype(state.globals_.dtype),
                    iteration=state.iteration + adv * k), g
            return step
        if params.time_series is not None:
            raise ValueError(
                "this diff step was built without Control-series support "
                "(series=False) but params carry a time series — the "
                "schedule would be silently dropped; build with "
                "series=True (auto engine: pass has_series=True to "
                "make_unsteady_gradient) or use engine='xla'")
        aux = jnp.stack([flags_f] + base)

        def step(state: LatticeState, p2: SimParams):
            it = state.iteration
            new_fields, g, g_last = step_arrays(
                state.fields, sett, aux,
                it[None].astype(jnp.int32) if it.ndim == 0 else it)
            return LatticeState(
                fields=new_fields, flags=state.flags,
                globals_=g_last.astype(state.globals_.dtype),
                iteration=state.iteration + adv * k), g
        return step

    def step(state: LatticeState, params: SimParams):
        # slow path (loop invariants re-derived per call) — drivers bind
        # them once via prepare().  Returns (state, chunk_globals): the
        # state carries LAST-iteration globals (per-step engine
        # semantics); the second value is the k-step objective increment.
        return _mk_step(params, state.flags)(state, params)

    def prepare(state: LatticeState, params: SimParams):
        """Bind the loop-invariant inputs ONCE per (jitted) gradient
        call: the zonal gather, settings cast and aux assembly must
        happen OUTSIDE the step scan — as scan-carry derived values they
        would re-run every step (flags ride the carry, so XLA cannot
        hoist them).  Called INSIDE the differentiated trace, so
        cotangents still flow to ``params`` through the bindings."""
        return _mk_step(params, state.flags)

    step.prepare = prepare
    step.chunk = k
    step.returns_inc = True
    step.arrays = step_arrays
    step.engine_name = (f"pallas_adjoint[{model.name},k={k}"
                        + (",series" if series else "") + f",by={by}]")
    return step
