"""Differentiable Pallas fast path: ``custom_vjp`` around the fused step
with a Pallas BACKWARD kernel.

The reference's adjoint is itself a tuned device kernel: Tapenade emits
``Run_b`` and the generated adjoint streaming scatters through the margins
(reference src/cuda.cu.Rt:240-256 ``RunKernel<..., adjoint>``, transpose
access in src/LatticeAccess.inc.cpp.Rt:227-261).  Round 3 only
differentiated the XLA step, so every ``<Adjoint>``/``<Optimize>`` run paid
~10x the engine rate in both sweeps.  Here the same structure as the
reference's falls out of two observations:

* the transpose of pull-streaming is pull-streaming with NEGATED vectors:
  ``out_i(x) = in_i(x - e_i)`` transposes to
  ``lambda_in_i(x) = lambda_pre_i(x + e_i)`` — no scatter needed, the
  backward kernel re-uses the band/halo machinery of the forward one;
* the collide (boundaries + collision + Globals contributions) is
  POINTWISE in the streamed state for the pure-streaming models, so its
  VJP is obtained by ``jax.vjp`` of the model's own stage function traced
  INSIDE the backward kernel — the transposed operations (adds, selects,
  broadcast-of-reductions) lower through Mosaic exactly like the primal.

One backward band pass computes
``lambda_in_i(x) = G_i(x + e_i)`` with
``G_i(y) = sum_j dC_j/dp_i (p(y)) . lambda_out_j(y)
          + sum_g dg/dp_i (p(y)) . lambda_globals_g``
on a 1-row-extended band (G of a boundary row is recomputed by the
neighboring band — recompute instead of cross-band accumulation, the same
trade the forward halo bands make).

Scope (checked by :func:`supports_diff`): single-stage Iteration, pull
reach 1, no Field stencils, SUM Globals, f32, aligned shapes.  The
cotangents for settings/zone tables are ZERO by contract — the design must
live in storage planes (InternalTopology — the reference's adjoint
optimizes exactly those) — and :func:`make_diff_step` is opt-in via
``make_unsteady_gradient(engine="pallas")``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tclb_tpu.core.lattice import LatticeState, SimParams
from tclb_tpu.core.registry import Model
from tclb_tpu.ops import pallas_generic
from tclb_tpu.ops.pallas_generic import _HALO, KernelCtx, action_plan


def _stored_planes(model: Model, shape, dtype) -> Optional[set]:
    """Indices of storage planes the Run stage writes, discovered by an
    abstract trace of the stage function (the write set is the dict the
    stage returns — registry metadata doesn't carry it)."""
    stage = model.stages[model.actions["Iteration"][0]]
    fn = model.stage_fns[stage.main]
    ns = model.n_storage
    ny, nx = 8, int(shape[1])

    def wrapper(planes, sett, zone_table):
        zonal = {nm: planes[0] * 0.0 for nm in model.zonal_settings}
        ctx = KernelCtx(model, list(planes), lambda *a: None,
                        jnp.zeros((ny, nx), jnp.int32), zonal, sett,
                        dtype, 0, set(model.node_types))
        return fn(ctx)

    try:
        res = jax.eval_shape(
            wrapper,
            [jax.ShapeDtypeStruct((ny, nx), dtype)] * ns,
            jax.ShapeDtypeStruct((len(model.settings),), dtype),
            jax.ShapeDtypeStruct((len(model.settings), model.zone_max),
                                 dtype))
    except Exception:  # noqa: BLE001 — untraceable stage: not eligible
        return None
    if not isinstance(res, dict):
        return set(range(ns))
    out = set()
    for name in res:
        if name in model.groups:
            out.update(model.groups[name])
        else:
            out.add(model.storage_index[name])
    return out


def supports_diff(model: Model, shape, dtype) -> bool:
    """Whether the differentiable Pallas step covers this configuration:
    everything the forward generic kernel needs, PLUS single-stage /
    reach-1 / no-Fields (the backward kernel's pointwise-collide
    factorization) and a write set covering every moving plane (an
    unmentioned streamed plane would pass through RAW in the forward
    kernel but PULLED in the backward factorization)."""
    if model.ndim != 2 or len(shape) != 2:
        return False   # the backward factorization is 2D-only for now
    if not pallas_generic.supports(model, shape, dtype, probe=False):
        return False
    ny, nx = (int(s) for s in shape)
    if ny % 8 or nx % 128:
        return False
    if model.fields:
        return False
    plan, reach = action_plan(model, "Iteration", fuse=1)
    if len(plan) != 1 or reach > 1:
        return False
    # the forward flavor with in-kernel globals is the diff step's primal
    # (objectives come from Globals); a model without Globals has no
    # differentiable objective here
    if not (1 <= model.n_globals <= 8) \
            or any(g.op != "SUM" for g in model.globals_):
        return False
    stored = _stored_planes(model, shape, dtype)
    if stored is None:
        return False
    for k in range(model.n_storage):
        dxk, dyk = int(model.ei[k, 0]), int(model.ei[k, 1])
        if (dxk or dyk) and k not in stored:
            return False
    return True


def make_diff_step(model: Model, shape, dtype=jnp.float32,
                   interpret: Optional[bool] = None,
                   present: Optional[set] = None,
                   by_bwd: Optional[int] = None):
    """Build ``step(state, params) -> state`` running ONE iteration on the
    fused Pallas kernel, differentiable end-to-end: the forward is the
    generic engine's globals flavor, the backward a dedicated Pallas band
    kernel (module docstring).  Drop-in for ``make_action_step`` inside
    the adjoint drivers (same state contract: globals_ = this step's)."""
    if not supports_diff(model, shape, dtype):
        raise ValueError(f"pallas diff step unsupported: {model.name} "
                         f"{shape}")
    ny, nx = (int(s) for s in shape)
    base = pallas_generic.make_pallas_iterate(
        model, shape, dtype, interpret=interpret, fuse=1, present=present)
    impl = base._impl
    call_g, by_f = impl["call_g"], impl["by"]
    zonal_si, zshift = impl["zonal_si"], impl["zshift"]
    nt_present = impl["nt_present"]
    assert impl["pad"] == 0 and call_g is not None
    # the backward band holds TWO input stacks plus the VJP's doubled
    # temporaries — size its band separately (~1/2 the forward band),
    # kept a multiple of 8 (sublane tile) that divides ny
    by = by_bwd if by_bwd is not None else max(8, (by_f // 16) * 8)
    by = max(8, (by // 8) * 8)
    while by > 8 and ny % by:
        by -= 8
    if ny % by:
        raise ValueError(f"no 8-aligned backward band divides ny={ny}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    ns = model.n_storage
    n_globals = model.n_globals
    ei = model.ei
    zonal_names = list(model.zonal_settings)
    n_aux = 1 + len(zonal_names)
    stage = model.stages[model.actions["Iteration"][0]]
    stage_fn = model.stage_fns[stage.main]

    def _roll(sl, shift):
        return pltpu.roll(sl, shift % nx, axis=1) if shift % nx else sl

    def bwd_kernel(sett, lg_ref, p_hbm, l_hbm, aux_hbm, out_ref,
                   bufp, bufl, bufa, sems):
        """lambda_in band pass: pulled primal + lambda_out on a 1-row
        extended band, pointwise collide-VJP via jax.vjp of the model's
        stage function, then the negated-pull shift."""
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            base_r = pl.multiple_of(band * jnp.int32(by), 8)
            top8 = pl.multiple_of(
                jax.lax.rem(base_r - jnp.int32(_HALO) + jnp.int32(ny),
                            jnp.int32(ny)), 8)
            bot8 = pl.multiple_of(
                jax.lax.rem(base_r + jnp.int32(by), jnp.int32(ny)), 8)
            out = []
            for si_, (hbm, buf) in enumerate(
                    ((p_hbm, bufp), (l_hbm, bufl), (aux_hbm, bufa))):
                out += [
                    pltpu.make_async_copy(
                        hbm.at[:, pl.ds(base_r, by), :],
                        buf.at[slot, :, pl.ds(_HALO, by), :],
                        sems.at[slot, 3 * si_]),
                    pltpu.make_async_copy(
                        hbm.at[:, pl.ds(top8, _HALO), :],
                        buf.at[slot, :, pl.ds(0, _HALO), :],
                        sems.at[slot, 3 * si_ + 1]),
                    pltpu.make_async_copy(
                        hbm.at[:, pl.ds(bot8, _HALO), :],
                        buf.at[slot, :, pl.ds(_HALO + by, _HALO), :],
                        sems.at[slot, 3 * si_ + 2]),
                ]
            return out

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for d in band_dmas(jnp.int32(0), i):
                d.start()

        @pl.when(i + 1 < n)
        def _():
            for d in band_dmas(nxt, i + jnp.int32(1)):
                d.start()

        for d in band_dmas(slot, i):
            d.wait()

        n_e = by + 2
        lo = _HALO - 1
        # pulled primal on the extended rows (reach 2 into the 8-row halo)
        p = []
        for k in range(ns):
            dxk, dyk = int(ei[k, 0]), int(ei[k, 1])
            sl = bufp[slot, k][lo - dyk:lo - dyk + n_e, :]
            p.append(_roll(sl, dxk))
        pst = jnp.stack(p)
        lam_out = jnp.stack([bufl[slot, k][lo:lo + n_e, :]
                             for k in range(ns)])
        flags_e = bufa[slot, 0][lo:lo + n_e, :].astype(jnp.int32)
        zonal_e = {nm: bufa[slot, 1 + j][lo:lo + n_e, :]
                   for j, nm in enumerate(zonal_names)}

        def C(pstack):
            ctx = KernelCtx(model, [pstack[k] for k in range(ns)],
                            lambda *a: None, flags_e, zonal_e, sett,
                            dtype, 0, nt_present, compute_globals=True)
            res = stage_fn(ctx)
            outs = list(pstack)
            if isinstance(res, dict):
                for name, stack in res.items():
                    if name in model.groups:
                        idx = model.groups[name]
                        if len(idx) == 1 and stack.ndim == 2:
                            outs[idx[0]] = stack
                        else:
                            for j, k in enumerate(idx):
                                outs[k] = stack[j]
                    else:
                        outs[model.storage_index[name]] = stack
            else:
                outs = [res[k] for k in range(ns)]
            gpl = [ctx._globals.get(g.name, jnp.zeros_like(pstack[0]))
                   for g in model.globals_]
            return jnp.stack(outs), (jnp.stack(gpl) if gpl
                                     else jnp.zeros((1,) + pstack[0].shape,
                                                    dtype))

        _, vjp_fn = jax.vjp(C, pst)
        if n_globals:
            lgpl = jnp.stack([
                jnp.full((n_e, nx), lg_ref[gi], dtype)
                for gi in range(n_globals)])
        else:
            lgpl = jnp.zeros((1, n_e, nx), dtype)
        (lam_p,) = vjp_fn((lam_out, lgpl))

        # negated-pull shift: lambda_in_i(x) = G_i(x + e_i)
        for k in range(ns):
            dxk, dyk = int(ei[k, 0]), int(ei[k, 1])
            sl = lam_p[k][1 + dyk:1 + dyk + by, :]
            out_ref[k] = _roll(sl, -dxk)

    call_bwd = pl.pallas_call(
        bwd_kernel,
        grid=(ny // by,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((ns, by, nx), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ns, ny, nx), dtype),
        scratch_shapes=[
            pltpu.VMEM((2, ns, by + 2 * _HALO, nx), dtype),
            pltpu.VMEM((2, ns, by + 2 * _HALO, nx), dtype),
            pltpu.VMEM((2, n_aux, by + 2 * _HALO, nx), dtype),
            pltpu.SemaphoreType.DMA((2, 9)),
        ],
        interpret=interpret,
    )

    def _aux_of(zone_table, flags16):
        flags_i32 = flags16.astype(jnp.int32)
        zones = flags_i32 >> zshift
        return jnp.stack(
            [flags_i32.astype(dtype)]
            + [zone_table[k].astype(dtype)[zones] for k in zonal_si])

    @jax.custom_vjp
    def step_arrays(fields, sett, aux):
        # aux (flags + gathered zonal planes) is an ARGUMENT, not
        # recomputed here: custom_vjp is opaque to XLA's loop-invariant
        # code motion, so a zone-table gather inside it would run every
        # scan step (~7 ms/step at 512x1024) instead of hoisting
        out, gpart = call_g(sett, jnp.zeros((1,), jnp.int32), fields, aux)
        return out, gpart[:n_globals].sum(axis=1)

    def step_f(fields, sett, aux):
        out = step_arrays(fields, sett, aux)
        return out, (fields, sett, aux)

    def step_b(res, cot):
        fields, sett, aux = res
        lam_f, lam_g = cot
        lam_in = call_bwd(sett, lam_g.astype(dtype), fields, lam_f, aux)
        # design lives in storage planes (supports_diff's contract):
        # settings/zonal cotangents are zero by construction here —
        # differentiate via the XLA engine for Control-series gradients
        return (lam_in, jnp.zeros_like(sett), jnp.zeros_like(aux))

    step_arrays.defvjp(step_f, step_b)

    def _mk_step(sett, aux):
        def step(state: LatticeState, params: SimParams) -> LatticeState:
            new_fields, g = step_arrays(state.fields, sett, aux)
            return LatticeState(fields=new_fields, flags=state.flags,
                                globals_=g.astype(state.globals_.dtype),
                                iteration=state.iteration + 1)
        return step

    def step(state: LatticeState, params: SimParams) -> LatticeState:
        # slow path (aux re-gathered per call) — drivers use prepare()
        return _mk_step(params.settings.astype(dtype),
                        _aux_of(params.zone_table, state.flags))(
            state, params)

    def prepare(state: LatticeState, params: SimParams):
        """Bind the loop-invariant inputs ONCE per (jitted) gradient
        call: the zonal gather and settings cast must happen OUTSIDE the
        step scan — as scan-carry derived values they would re-run every
        step (flags ride the carry, so XLA cannot hoist them), costing
        more than the kernels themselves."""
        return _mk_step(params.settings.astype(dtype),
                        _aux_of(params.zone_table, state.flags))

    step.prepare = prepare
    return step
